"""Scaling-efficiency harness (north-star metric #2, BASELINE.md).

Measures data-parallel ResNet train-step throughput at 1..N chips and the
raw gradient-allreduce bandwidth, reporting scaling efficiency
(throughput_n / (n × throughput_1)).  On a real pod the mesh covers
physical chips and the collective rides ICI; on this 1-chip dev box run
with ``--simulate-devices 8 --platform cpu`` for the methodology curve
(framework-overhead scaling only — SURVEY §7 step 7 notes v4-32 numbers
are for the real-pod stage).

Output: one JSON line per device count + a summary line.
"""

import argparse
import json
import os
import time

import numpy as np


def measure_step_throughput(n_devices, per_chip_bs, image_size, steps,
                            model_kind="resnet18"):
    import jax
    try:  # persistent compile cache (shared with bench.py)
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/chainermn_tpu_jax_cache")
    except Exception:
        pass
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.models import Classifier, ResNet18, ResNet50

    devices = jax.devices()[:n_devices]
    comm = ct.create_communicator("jax_ici", devices=devices,
                                  axis_name=f"bench{n_devices}",
                                  allreduce_grad_dtype="bfloat16")
    model_cls = ResNet50 if model_kind == "resnet50" else ResNet18
    model = Classifier(model_cls(n_classes=1000,
                                 compute_dtype=jnp.bfloat16, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1, momentum=0.9), comm).setup(model)

    gbs = per_chip_bs * n_devices
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(0, 1, (gbs, 3, image_size, image_size))
                    .astype(np.float32))
    t = jnp.asarray(rng.randint(0, 1000, gbs).astype(np.int32))
    for _ in range(2):
        loss = opt.update(model, x, t)
    jax.block_until_ready(loss)
    start = time.perf_counter()
    for _ in range(steps):
        loss = opt.update(model, x, t)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - start
    return steps * gbs / dt


def measure_allreduce_bandwidth(n_devices, n_floats, iters=20):
    import jax
    import jax.numpy as jnp
    from jax import lax, shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devices = jax.devices()[:n_devices]
    mesh = Mesh(np.asarray(devices), ("ar",))
    x = jnp.ones((n_devices, n_floats), jnp.float32)

    fn = jax.jit(shard_map(lambda x: lax.psum(x, "ar"), mesh=mesh,
                           in_specs=P("ar"), out_specs=P("ar"),
                           check_vma=False))
    jax.block_until_ready(fn(x))
    start = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    jax.block_until_ready(out)
    dt = time.perf_counter() - start
    # ring allreduce moves 2(n-1)/n × payload per chip
    bytes_moved = 4 * n_floats * 2 * (n_devices - 1) / max(n_devices, 1)
    return iters * bytes_moved / dt / 1e9  # GB/s per chip


def project_efficiency(step_ms, n_chips, grad_mb=51.1, ici_gbps=100.0,
                       overlap_fraction=0.8, host_overhead_ms=0.5):
    """Analytic DP scaling-efficiency projection for an n-chip pod
    (BENCH_NOTES.md "Scaling-efficiency projection" — the defensible
    basis for the v4-32 north-star claim while only one chip exists).

    Model: per-step time on n chips =
        step_ms + exposed_allreduce
    where ``step_ms`` is the measured single-chip wall-clock step (host
    bookkeeping included — bench.py times ``opt.update`` end to end, so
    host overhead is already inside it), exposed_allreduce =
    (1 - overlap_fraction) × t_ring_allreduce, and
    t_ring_allreduce = 2(n-1)/n × grad_bytes / ici_bandwidth.

    * ``grad_mb`` — ResNet-50 has 25.557M params; bf16-compressed gradient
      payload = 51.1 MB (the flagship ``allreduce_grad_dtype="bfloat16"``
      configuration).
    * ``ici_gbps`` — per-chip algorithmic ring bandwidth along one torus
      axis.  v4's ICI is ~100 GB/s bidirectional per axis; this is the
      conservative single-axis figure (XLA can also use multiple axes).
    * ``overlap_fraction`` — XLA overlaps the gradient all-reduce with the
      remaining backward pass inside the single compiled step; 0.8 is
      conservative (the last layer's gradients cannot overlap).
    * ``host_overhead_ms`` — extra per-step host cost that appears ONLY
      in the multi-chip regime (e.g. multi-controller bookkeeping); the
      single-chip host cost is already inside the measured ``step_ms``,
      so it must not be double-counted here.  Default 0.5 ms is the
      round-1 measured bookkeeping figure used as a conservative adder.
    """
    t_ar_ms = 2 * (n_chips - 1) / n_chips * grad_mb * 1e6 / (ici_gbps * 1e9) * 1e3
    exposed = (1.0 - overlap_fraction) * t_ar_ms
    t_n = step_ms + host_overhead_ms + exposed
    t_1 = step_ms
    return t_1 / t_n


def _gloo_worker(pid, nprocs, port, per_rank_bs, hidden, steps,
                 zero=False, exchange="flat"):
    """One process of the REAL cross-process compiled DP step (the same
    path as ``tests/multiprocess_tests/_worker.py · run_dp_step``): gloo
    CPU backend, 1 device per process, the whole DP step one shard_mapped
    jit whose gradient pmean crosses actual process boundaries.  With
    ``zero`` the optimizer state is ZeRO-1 sharded: the gradient
    traffic becomes psum_scatter + all_gather instead of one pmean —
    the curve then measures the reduce-scatter refactoring's transport
    cost across real process boundaries.  Times the steady-state step;
    rank 0 prints the row."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from chainermn_tpu.communicators._communication_utility import (
        initialize_distributed)
    assert initialize_distributed(f"localhost:{port}",
                                  num_processes=nprocs, process_id=pid)
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.models import MLP, Classifier

    # exchange selects the gradient-exchange structure under test (the
    # ISSUE 5 exposed-comm A/B: bucketed vs flat across REAL process
    # boundaries; ISSUE 6 adds the hierarchical two-level legs — with
    # one device per process the split infers to dcn=nprocs × ici=1, so
    # the DCN hop is the one crossing the real process boundary);
    # reduce_scatter routes through the optimizer-level step variant,
    # zero keeps the ZeRO-1 contract
    comm_name, bc, opt_exchange = ct.communicators.exchange_knobs(exchange)
    # the striped legs (ISSUE 11) must run a NONZERO ratio or the curve
    # would silently measure the strict hierarchical schedule under the
    # striped name; the launcher exports CHAINERMN_TPU_STRIPE_RATIO for
    # the ratio sweep
    stripe = None
    if exchange in ("striped", "striped_rs"):
        from chainermn_tpu.communicators._memory_utility import (
            DEFAULT_STRIPE_RATIO)
        stripe = float(os.environ.get("CHAINERMN_TPU_STRIPE_RATIO", "")
                       or DEFAULT_STRIPE_RATIO)
    comm = ct.create_communicator(comm_name, batch_collectives=bc,
                                  stripe_ratio=stripe)
    assert comm.size == nprocs == jax.device_count()
    model = Classifier(MLP(n_units=hidden, n_out=10, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.01, momentum=0.9), comm, zero_sharding=zero,
        exchange=opt_exchange).setup(model)

    gbs = per_rank_bs * nprocs
    rng = np.random.RandomState(0)
    x = np.asarray(rng.normal(0, 1, (gbs, 64)).astype(np.float32))
    t = np.asarray(rng.randint(0, 10, gbs).astype(np.int32))

    for _ in range(3):  # trace+compile, then steady-state warmup
        loss = opt.update(model, x, t)
    float(loss)

    n_buckets = None
    if exchange == "bucketed":
        # post-warmup: params materialize lazily on the first update
        n_buckets = len(comm.grad_buckets_for(model))
    if nprocs > 1:
        comm._host_channel().barrier()
    start = time.perf_counter()
    for _ in range(steps):
        loss = opt.update(model, x, t)
    float(loss)  # the collective step is lock-step across processes
    dt = time.perf_counter() - start
    if pid == 0:
        n_params = sum(int(np.prod(p.array.shape))
                       for p in model.params())
        row = {
            "processes": nprocs, "per_rank_bs": per_rank_bs,
            "zero_sharding": bool(zero),
            "exchange": exchange,
            "topology": comm.topology,
            "ici_size": comm.ici_size, "dcn_size": comm.dcn_size,
            "grad_payload_mb": round(n_params * 4 / 1e6, 2),
            "step_ms": round(dt / steps * 1e3, 3),
            "examples_per_sec": round(steps * gbs / dt, 1)}
        if exchange == "bucketed":
            # the degenerate single-bucket datum (payload fits the
            # bound) must be tellable apart downstream
            row["bucket_mb"] = comm.bucket_mb
            row["n_buckets"] = n_buckets
        if comm.striped:
            # the ratio sweep's independent variable travels with the
            # row — three curves at {0.25, 0.5, 0.75} are only
            # comparable if each datum names its split
            row["stripe_ratio"] = comm.stripe_ratio
        print(json.dumps(row), flush=True)


def _run_gloo_curve(proc_counts, per_rank_bs, hidden, steps, zero=False,
                    reps=1, exchange="flat", stripe_ratio=None):
    """Launch each P-process measurement and report per-hop overhead:
    step_ms(P) - step_ms(1) is the cost the framework adds per step when
    the SAME compiled program's gradient mean must cross P real process
    boundaries (gloo over localhost — an upper bound on framework
    overhead; ICI on a pod is faster than loopback gloo).

    ``reps`` > 1 repeats each P-process measurement and reports
    mean/min/max step_ms per row (VERDICT r4 Weak #2: on a 1-core box
    the multi-process rows carry scheduler time-slicing noise — the
    spread quantifies it instead of a single draw hiding it)."""
    import re
    import socket
    import subprocess
    import sys
    # 1 device per process is the measurement's contract: a leaked
    # simulated-mesh flag (tests/conftest.py exports
    # --xla_force_host_platform_device_count into the environment) would
    # give every worker N devices and break the topology assert
    env = dict(os.environ)
    if "XLA_FLAGS" in env:
        env["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+\s*", "",
            env["XLA_FLAGS"])
    if stripe_ratio is not None:
        # the ratio sweep's per-invocation knob: workers read it at
        # communicator construction (ISSUE 11)
        env["CHAINERMN_TPU_STRIPE_RATIO"] = str(stripe_ratio)
    if 1 not in proc_counts:
        # the per-hop summary is defined relative to the 1-process step;
        # computing it against rows[0] at some other count would publish
        # silently mislabeled overhead numbers
        raise SystemExit("--gloo-procs must include 1 (the baseline for "
                         "the per-hop overhead summary)")
    rows = []
    for nprocs in proc_counts:
      rep_rows = []
      for _rep in range(max(1, reps)):
        # bind-then-close port choice has a TOCTOU window (another
        # process can grab it before the coordinator re-binds): retry
        # the whole P-process measurement on rendezvous failure
        for attempt in range(3):
            with socket.socket() as s:
                s.bind(("localhost", 0))
                port = s.getsockname()[1]
            procs = [subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--gloo-worker", str(pid), str(nprocs), str(port),
                 str(per_rank_bs), str(hidden), str(steps),
                 str(int(zero)), exchange],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
                for pid in range(nprocs)]
            timed_out = False
            outs = [None] * nprocs
            deadline = time.monotonic() + 600
            for i, p in enumerate(procs):
                try:
                    outs[i] = p.communicate(timeout=max(
                        1.0, deadline - time.monotonic()))[0]
                except subprocess.TimeoutExpired:
                    # rendezvous hang manifestation: a stolen port that
                    # accepts connections but never speaks the
                    # coordinator protocol blocks workers inside
                    # initialize_distributed
                    timed_out = True
            # a wedged worker (dead peer in the gloo barrier) must not
            # outlive the measurement: kill stragglers, but KEEP their
            # output — the final-attempt assertion needs diagnostics
            for i, p in enumerate(procs):
                if p.poll() is None:
                    p.kill()
                try:
                    rem = p.communicate()[0]
                except Exception:
                    rem = None
                if outs[i] is None:
                    outs[i] = rem
            outs = [o or "" for o in outs]
            if not timed_out and all(p.returncode == 0 for p in procs):
                break
            # retry ONLY rendezvous-class failures (the port was taken in
            # the TOCTOU window, or the coordinator wasn't reachable);
            # any other worker crash is a real defect and must surface
            # immediately, not be averaged away by a silent re-run
            rendezvous_err = timed_out or any(
                p.returncode != 0 and re.search(
                    r"[Aa]ddress already in use|UNAVAILABLE|"
                    r"DEADLINE_EXCEEDED|[Ff]ailed to connect|"
                    r"errno 98", o or "")
                for p, o in zip(procs, outs))
            if attempt == 2 or not rendezvous_err:
                raise AssertionError(
                    [(p.returncode, o) for p, o in zip(procs, outs)])
        rep_rows.append(json.loads([ln for ln in outs[0].splitlines()
                                    if ln.startswith("{")][-1]))
      row = dict(rep_rows[0])
      if len(rep_rows) > 1:
          samples = sorted(r["step_ms"] for r in rep_rows)
          row["step_ms"] = round(float(np.mean(samples)), 3)
          row["step_ms_min"] = samples[0]
          row["step_ms_max"] = samples[-1]
          row["reps"] = len(samples)
          # derived from the mean step time (harmonic aggregation), so
          # the row's two fields stay mutually consistent
          row["examples_per_sec"] = round(
              nprocs * per_rank_bs / (row["step_ms"] / 1e3), 1)
      if row.get("exchange") == "bucketed" and row.get("n_buckets", 0) <= 1:
          # worker output is captured, so the launcher owns the warning
          print(f"bench_scaling: bucketed plan degenerated to ONE bucket "
                f"at bucket_mb={row.get('bucket_mb')} (gradient payload "
                f"fits the bound) — structurally identical to flat; set "
                f"CHAINERMN_TPU_BUCKET_MB below the payload for a real "
                f"bucketed-vs-flat A/B", file=sys.stderr, flush=True)
      rows.append(row)
      print(json.dumps(row), flush=True)
    base = next(r["step_ms"] for r in rows if r["processes"] == 1)
    n_cores = os.cpu_count() or 1
    for row in rows:
        if row["processes"] == 1:
            continue
        p = row["processes"]
        # With fewer cores than processes the P workers' compute
        # time-slices one core, so the raw delta over the 1-proc step is
        # mostly contention; the serialized-compute baseline
        # (ceil(P/cores) × 1-proc step) isolates the transport/dispatch
        # overhead the framework actually adds per process boundary.
        serial_ms = -(-p // n_cores) * base
        print(json.dumps({
            "processes": p, "n_cores": n_cores,
            "per_hop_overhead_raw_ms": round(row["step_ms"] - base, 3),
            "overhead_vs_serialized_compute_ms": round(
                row["step_ms"] - serial_ms, 3),
            "scaling_efficiency_vs_1proc": round(
                base / row["step_ms"], 4)}), flush=True)
    return rows


def _gloo_elastic_worker(pid, nprocs, port, per_rank_bs, hidden, steps,
                         preempt_rank):
    """One process of the elastic preempt-and-rejoin measurement
    (ISSUE 10): a Trainer-supervised run over real gloo transport in
    which rank ``preempt_rank`` is hard-preempted a third of the way
    in, the survivors shrink and keep training, and the rank re-joins
    (world grows back).  ``preempt_rank < 0`` is the uninterrupted
    baseline leg of the A/B.  Rank 0 prints the row; ``step_ms`` is
    wall-clock over ALL iterations, so the resize + state-sync tax is
    IN the number — that tax vs the baseline row is the measurement."""
    import time as _time

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from chainermn_tpu.communicators._communication_utility import (
        initialize_distributed)
    assert initialize_distributed(f"localhost:{port}",
                                  num_processes=nprocs, process_id=pid)
    import tempfile

    import chainermn_tpu as ct
    from chainermn_tpu.communicators import (FaultInjectionCommunicator,
                                             FaultSchedule)
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.dataset import SerialIterator, TupleDataset
    from chainermn_tpu.extensions import ElasticRecovery
    from chainermn_tpu.models import MLP, Classifier
    from chainermn_tpu.training import StandardUpdater, Trainer
    from chainermn_tpu.training.trainer import Extension

    out = tempfile.mkdtemp(prefix=f"elastic_bench_{pid}_")
    rng = np.random.RandomState(0)
    gbs = per_rank_bs * nprocs
    x = np.asarray(rng.normal(0, 1, (gbs, 64)).astype(np.float32))
    t = np.asarray(rng.randint(0, 10, gbs).astype(np.int32))

    comm = ct.create_communicator("jax_ici")
    comm._host_channel()._timeout_ms = 6000  # typed detection in seconds
    if preempt_rank >= 0:
        # beacon + join-poll = two bcast_obj calls per iteration; fire
        # at the target iteration's beacon
        k = max(2, steps // 3)
        comm = FaultInjectionCommunicator(comm, FaultSchedule(
            [dict(op="bcast_obj", nth=2 * (k - 1) + 1, action="preempt",
                  rank=preempt_rank)], seed=0))
    model = Classifier(MLP(n_units=hidden, n_out=10, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.01, momentum=0.9), comm).setup(model)
    it = SerialIterator(TupleDataset(x, t), gbs, shuffle=False)
    trainer = Trainer(StandardUpdater(it, opt), (steps, "iteration"),
                      out=out)
    cp = ct.create_multi_node_checkpointer(comm, name="eb", path=out)
    recovery = ElasticRecovery(checkpointer=cp, comm=comm,
                               rejoin_after_s=1.0,
                               resolve_timeout_ms=120_000, verbose=False)

    class _Beacon(Extension):
        trigger = (1, "iteration")
        priority = 400

        def __call__(self, trainer):
            recovery.comm.bcast_obj(
                {"it": trainer.updater.iteration}, root=0)

    class _Pacer(Extension):
        # keeps the survivor in the loop long enough for the rejoin to
        # land mid-run (the elastic leg only; the baseline pays the
        # SAME dwell so the A/B delta isolates the elasticity tax)
        trigger = (1, "iteration")
        priority = 350

        def __call__(self, trainer):
            _time.sleep(0.1)

    trainer.extend(_Beacon())
    trainer.extend(_Pacer())
    trainer.extend(cp, trigger=(max(2, steps // 6), "iteration"))
    trainer.extend(recovery)
    start = _time.perf_counter()
    trainer.run()
    wall = _time.perf_counter() - start
    if pid == 0:
        stats = recovery.stats
        print(json.dumps({
            "processes": nprocs, "per_rank_bs": per_rank_bs,
            "elastic": preempt_rank >= 0,
            "preempt_rank": preempt_rank if preempt_rank >= 0 else None,
            "world_size": recovery.comm.inter_size,
            "resizes": stats["resizes"],
            "ranks_lost": stats["ranks_lost"],
            "ranks_joined": stats["ranks_joined"],
            "iterations": trainer.updater.iteration,
            "wall_s": round(wall, 3),
            "step_ms": round(wall / max(1, trainer.updater.iteration)
                             * 1e3, 3),
            "examples_per_sec": round(
                trainer.updater.iteration * gbs / wall, 1)}), flush=True)


def _gloo_fleet_worker(pid, nprocs, port, n_requests, kill_step):
    """One process of the serving-fleet kill-under-load A/B (ISSUE 15):
    process 0 runs the router + replica 0, every other process one
    :class:`FleetWorker` replica over the REAL host channel.  On the
    kill leg the worker replica preempts at decode step ``kill_step``
    (announced leave + silence — the router detects through the typed
    channel timeout), its in-flight requests replay on the survivor
    with ZERO drops, and the preempted replica re-joins via the
    multicast-tree weight sync.  ``kill_step < 0`` is the uninterrupted
    baseline leg; the p99 completion-latency delta between the legs is
    the detection-bounded spike the FIRST-CHIP-CONTACT checklist item 9
    stamps."""
    import time as _time

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from chainermn_tpu.communicators._communication_utility import (
        initialize_distributed)
    assert initialize_distributed(f"localhost:{port}",
                                  num_processes=nprocs, process_id=pid)

    import chainermn_tpu as ct
    from chainermn_tpu.communicators import ElasticMembership
    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.serving import (FleetWorker, RemoteReplica,
                                       ReplicaFleet, Request,
                                       ServingEngine)

    comm = ct.create_communicator("jax_ici")
    ch = comm._host_channel()
    ch._timeout_ms = 6000   # typed detection in seconds, not minutes
    membership = ElasticMembership(ch._client, rank=pid, world=nprocs,
                                   role="fleet", settle_s=0.5,
                                   poll_s=0.02, timeout_ms=90_000)
    model = TransformerLM(n_vocab=257, d_model=64, n_heads=2,
                          n_layers=2, max_len=64, seed=0)
    engine = ServingEngine(model, num_pages=64, page_size=16,
                           max_batch=4, max_context=64,
                           prefix_cache=False)

    if pid != 0:
        worker = FleetWorker(engine, ch, membership=membership,
                             router_process=0)
        outcome = worker.serve(kill_at=kill_step if kill_step >= 0
                               else None)
        if outcome == "preempted":
            # park until the survivors' shrink decision lands (a join
            # announced mid-shrink would collapse shrink+grow into one
            # no-op resolve — the elastic _preempted discipline)
            epoch_at_leave = membership.current_epoch()
            deadline = _time.monotonic() + 60
            while membership.current_epoch() == epoch_at_leave \
                    and _time.monotonic() < deadline:
                _time.sleep(0.05)
            _time.sleep(0.5)
            membership.announce_join(note="rejoin after preemption")
            view = membership.resolve(
                expect={0, pid}, require={0})
            worker.sync_weights(view, joiners=(pid,))
            worker.serve()   # back in rotation until the router stops us
        return

    # -- process 0: router + local replica 0 --------------------------------
    remotes = {p: RemoteReplica(p, ch, p) for p in range(1, nprocs)}
    fleet = ReplicaFleet(engines={0: engine, **remotes},
                         membership=membership)
    rng = np.random.RandomState(0)
    reqs = [Request(rng.randint(0, 257, 8).astype(np.int32), 4,
                    tenant=f"t{i % 2}", arrival_time=0.0)
            for i in range(n_requests)]
    submit_wall = {}
    t0 = _time.monotonic()
    for r in reqs:
        fleet.submit(r)
        submit_wall[r.request_id] = _time.monotonic()
    rejoined = kill_step < 0
    deadline = _time.monotonic() + 120
    while (fleet.pending() or not rejoined) \
            and _time.monotonic() < deadline:
        if fleet.pending():
            fleet.step()
        if not rejoined:
            if fleet.sheds:
                joins = membership.pending_joins(fleet.view)
                if joins:
                    fleet.join(engines={joins[0]: RemoteReplica(
                        joins[0], ch, joins[0])})
                    rejoined = True
                else:
                    _time.sleep(0.05)
            elif not fleet.pending():
                break   # kill never fired: report the row honestly
    wall = _time.monotonic() - t0
    for rep in fleet.replicas.values():
        if rep.remote and rep.live:
            rep.stop()
    done_ms = [(r.finish_time - submit_wall[r.request_id]) * 1e3
               for r in fleet.completed if r.finish_time is not None
               and r.request_id in submit_wall]
    print(json.dumps({
        "fleet": True, "processes": nprocs, "kill_step": kill_step
        if kill_step >= 0 else None, "requests": n_requests,
        "completed": len(fleet.completed),
        "dropped": n_requests - len(fleet.completed),
        "reroutes": fleet.reroutes, "sheds": fleet.sheds,
        "rejoined": rejoined and kill_step >= 0,
        "detection_s": round(fleet.last_detection_s, 3)
        if fleet.last_detection_s is not None else None,
        "weight_sync_s": round(fleet.weight_sync_s, 3),
        "p99_completion_ms": round(float(
            np.percentile(done_ms, 99)), 2) if done_ms else None,
        "wall_s": round(wall, 3)}), flush=True)


def _run_fleet_ab(nprocs, n_requests, kill_step):
    """The 2-replica gloo fleet kill-under-load A/B (ISSUE 15): one
    uninterrupted run, one kill-and-rejoin run; the summary line is the
    detection-bounded p99 completion spike + the tree weight-sync cost
    (FIRST-CHIP-CONTACT checklist item 9)."""
    import re
    import socket
    import subprocess
    import sys
    if kill_step < 0:
        raise SystemExit(f"--fleet-kill {kill_step} must be a decode "
                         f"step index >= 0")
    env = dict(os.environ)
    if "XLA_FLAGS" in env:
        env["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+\s*", "",
            env["XLA_FLAGS"])
    rows = []
    for leg_kill in (-1, kill_step):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--gloo-fleet-worker", str(pid), str(nprocs), str(port),
             str(n_requests), str(leg_kill)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for pid in range(nprocs)]
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=600)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
        assert all(p.returncode == 0 for p in procs), \
            [(p.returncode, o[-2000:]) for p, o in zip(procs, outs)]
        row = json.loads([ln for ln in outs[0].splitlines()
                          if ln.startswith("{")][-1])
        rows.append(row)
        print(json.dumps(row), flush=True)
    base, killed = rows
    print(json.dumps({
        "fleet_ab": True, "processes": nprocs,
        "kill_step": kill_step,
        "dropped": killed["dropped"],
        "reroutes": killed["reroutes"],
        "detection_s": killed["detection_s"],
        "weight_sync_s": killed["weight_sync_s"],
        "p99_spike_ms_vs_baseline": round(
            (killed["p99_completion_ms"] or 0)
            - (base["p99_completion_ms"] or 0), 2)}), flush=True)
    return rows


def _gloo_capacity_worker(pid, nprocs, port, n_requests, convert):
    """One process of the capacity-transfer A/B (ISSUE 16).  BOTH legs
    train the same data-parallel MLP over real gloo transport and serve
    the same open-loop burst from process 0 — they differ only in what
    the cluster does with rank 1 during the burst.  Baseline
    (``convert=0``): rank 1 keeps training (full world) and ONE replica
    serves.  Capacity leg (``convert=1``): queue pressure trips the
    hysteresis policy's +1 and the :class:`CapacityBroker` converts
    rank 1 into a second replica over the REAL KV membership +
    multicast tree (training continues at world 1 on rank 0's data
    shard), the drained queues trip the -1 and rank 1 retires back
    into training.  Both legs run the SAME total optimizer-step count
    and end with a root-0 param resync (the rejoin's state sync), so
    the runner can gate final-loss parity."""
    import time as _time

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from chainermn_tpu.communicators._communication_utility import (
        initialize_distributed)
    assert initialize_distributed(f"localhost:{port}",
                                  num_processes=nprocs, process_id=pid)

    import chainermn_tpu as ct
    from chainermn_tpu.communicators import ElasticMembership
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.elastic import CapacityBroker
    from chainermn_tpu.models import MLP, Classifier, TransformerLM
    from chainermn_tpu.serving import (FleetWorker, RemoteReplica,
                                       ReplicaFleet, Request,
                                       ServingEngine)
    from chainermn_tpu.serving.fleet import QueueDepthScalePolicy

    CAP_TAG = 7003
    T_JOINT_IN, T_STINT, T_JOINT_OUT = 4, 6, 6
    comm = ct.create_communicator("jax_ici")
    ch = comm._host_channel()
    ch._timeout_ms = 30_000   # solo-step compiles pause the pump loop
    kv = ch._client
    train_mem = ElasticMembership(kv, rank=pid, world=nprocs,
                                  role="elastic",
                                  settle_s=2.0 if pid == 0 else 0.5,
                                  poll_s=0.02, timeout_ms=90_000)
    fleet_mem = ElasticMembership(kv, rank=pid, world=nprocs,
                                  role="fleet",
                                  settle_s=2.0 if pid == 0 else 0.5,
                                  poll_s=0.02, timeout_ms=90_000)

    rng = np.random.RandomState(0)
    # a SMOOTH training problem (large batch, learnable labels): the
    # parity gate compares the two legs' final loss, so the landscape
    # must not be a memorization cliff where any trajectory split
    # explodes the relative delta
    gbs = 128 * nprocs
    x = rng.normal(0, 1, (gbs, 64)).astype(np.float32)
    w_true = rng.normal(0, 1, (64, 10)).astype(np.float32)
    t = np.argmax(x @ w_true, axis=1).astype(np.int32)
    model = Classifier(MLP(n_units=64, n_out=10, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.05, momentum=0.9), comm).setup(model)

    # the convertible rank's engine seeds DIFFERENT weights (seed=pid):
    # the tree sync must overwrite them from replica 0
    serve_model = TransformerLM(n_vocab=257, d_model=32, n_heads=1,
                                n_layers=1, max_len=32, seed=pid)
    engine = ServingEngine(serve_model, num_pages=64, page_size=8,
                           max_batch=4, max_context=32,
                           prefix_cache=False)

    for _ in range(T_JOINT_IN):
        opt.update(model, x, t)

    if pid != 0:
        msg = ch.recv_obj(0, tag=CAP_TAG)
        if msg == ("stint",):   # baseline: keep training at full world
            for _ in range(T_STINT):
                opt.update(model, x, t)
        else:                   # capacity leg: become a serving replica
            assert msg == ("convert",), msg
            fleet_mem.announce_join(note="capacity transfer")
            fview = fleet_mem.resolve(expect=set(range(nprocs)),
                                      require={0})
            worker = FleetWorker(engine, ch, membership=fleet_mem,
                                 router_process=0)
            worker.sync_weights(fview, joiners=(pid,))
            outcome = worker.serve()   # until the retire stops us
            assert outcome == "stopped", outcome
            train_mem.announce_join(note="capacity transfer: rejoin")
            train_mem.resolve(expect=set(range(nprocs)), require={0})
        comm.bcast_data(model)  # root-0 resync (the rejoin's state
        #                         sync; an idempotent no-op baseline)
        for _ in range(T_JOINT_OUT):
            opt.update(model, x, t)
        return

    # -- process 0: router + replica 0 + the broker --------------------------
    policy = QueueDepthScalePolicy(scale_up_depth=2, scale_down_depth=0,
                                   min_replicas=1, max_replicas=2)
    fleet = ReplicaFleet(engines={0: engine}, membership=fleet_mem,
                         min_replicas=1,
                         scale_policy=policy if convert else None)
    broker = CapacityBroker(
        train_mem, fleet,
        engine_factory=lambda r: RemoteReplica(r, ch, r),
        min_world=1) if convert else None

    srng = np.random.RandomState(3)
    reqs = [Request(srng.randint(1, 257, 8).astype(np.int32), 4,
                    tenant=f"t{i % 2}", arrival_time=0.0, request_id=i)
            for i in range(n_requests)]
    submit_wall = {}
    t0 = _time.monotonic()
    for r in reqs:
        fleet.submit(r)
        submit_wall[r.request_id] = _time.monotonic()

    if convert:
        st = fleet.step()
        assert st["scale_decision"] == 1, st
        ch.send_obj(("convert",), 1, tag=CAP_TAG)
        # wait for the worker's fleet join intent so the admission
        # resolve can never settle without it
        deadline = _time.monotonic() + 60
        while fleet_mem._try_get(f"{fleet_mem._base}/join/1") is None \
                and _time.monotonic() < deadline:
            _time.sleep(0.02)
        res = broker.apply(st["scale_decision"])
        assert res == ("convert", 1), res
    else:
        ch.send_obj(("stint",), 1, tag=CAP_TAG)

    # the stint: training continues WHILE the burst is served —
    # baseline at full world (rank 1 in lockstep), capacity leg at
    # world 1 on rank 0's own data shard (rank 1 is busy serving)
    decision = 0
    shard = slice(0, gbs // nprocs)
    for _ in range(T_STINT):
        if convert:
            opt.actual_optimizer.update(model, x[shard], t[shard])
        else:
            opt.update(model, x, t)
        for _ in range(4):
            if not fleet.pending():
                break
            st = fleet.step()
            if st.get("scale_decision"):
                decision = st["scale_decision"]
    steps = 0
    while fleet.pending() and steps < 10_000:
        st = fleet.step()
        if st.get("scale_decision"):
            decision = st["scale_decision"]
        steps += 1
    if convert:
        assert decision == -1, decision  # the drain tripped the -1
        res = broker.apply(decision)
        assert res == ("retire", 1), res
        deadline = _time.monotonic() + 60
        while not train_mem.pending_joins() \
                and _time.monotonic() < deadline:
            _time.sleep(0.05)
        train_mem.resolve(expect=set(range(nprocs)))
    comm.bcast_data(model)
    final_loss = None
    for _ in range(T_JOINT_OUT):
        final_loss = float(opt.update(model, x, t))
    wall = _time.monotonic() - t0

    done_ms = [(r.finish_time - submit_wall[r.request_id]) * 1e3
               for r in fleet.completed if r.finish_time is not None
               and r.request_id in submit_wall]
    print(json.dumps({
        "capacity": True, "processes": nprocs,
        "convert": bool(convert), "requests": n_requests,
        "completed": len(fleet.completed),
        "dropped": n_requests - len(fleet.completed),
        "p99_completion_ms": round(float(
            np.percentile(done_ms, 99)), 2) if done_ms else None,
        "final_loss": round(final_loss, 6),
        "conversions": broker.stats["conversions"]
        if broker is not None else 0,
        "role_transfers": broker.stats["role_transfers"]
        if broker is not None else 0,
        "convert_s": round(broker.stats["convert_s"], 3)
        if broker is not None else 0.0,
        "weight_sync_s": round(fleet.weight_sync_s, 3),
        "wall_s": round(wall, 3)}), flush=True)


def _run_capacity_ab(nprocs, n_requests):
    """The 2-process gloo capacity-transfer A/B (ISSUE 16): one leg
    where rank 1 keeps training through the serving burst (one
    replica), one where the CapacityBroker converts it into a second
    replica for the burst and retires it after the drain.  Gates: ZERO
    drops on both legs, exactly one conversion + retire on the
    capacity leg, and final training loss parity within ±5% — lending
    a rank to serving must not cost the training run.  The summary
    line is the p99 completion delta the borrowed replica bought
    (FIRST-CHIP-CONTACT checklist item 10)."""
    import re
    import socket
    import subprocess
    import sys
    env = dict(os.environ)
    if "XLA_FLAGS" in env:
        env["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+\s*", "",
            env["XLA_FLAGS"])
    rows = []
    for leg_convert in (0, 1):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--gloo-capacity-worker", str(pid), str(nprocs), str(port),
             str(n_requests), str(leg_convert)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for pid in range(nprocs)]
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=600)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
        assert all(p.returncode == 0 for p in procs), \
            [(p.returncode, o[-2000:]) for p, o in zip(procs, outs)]
        row = json.loads([ln for ln in outs[0].splitlines()
                          if ln.startswith("{")][-1])
        rows.append(row)
        print(json.dumps(row), flush=True)
    base, cap = rows
    assert base["dropped"] == 0 and cap["dropped"] == 0, (base, cap)
    assert cap["conversions"] == 1 and cap["role_transfers"] == 2, cap
    parity = abs(cap["final_loss"] - base["final_loss"]) \
        / max(abs(base["final_loss"]), 1e-9)
    assert parity <= 0.05, \
        f"capacity stint cost training: final loss {cap['final_loss']}" \
        f" vs baseline {base['final_loss']} ({parity:.1%} > 5%)"
    print(json.dumps({
        "capacity_ab": True, "processes": nprocs,
        "loss_parity_frac": round(parity, 4),
        "conversions": cap["conversions"],
        "role_transfers": cap["role_transfers"],
        "convert_s": cap["convert_s"],
        "weight_sync_s": cap["weight_sync_s"],
        "p99_ms_saved_vs_training_priority": round(
            (base["p99_completion_ms"] or 0)
            - (cap["p99_completion_ms"] or 0), 2)}), flush=True)
    return rows


def _run_elastic_ab(nprocs, per_rank_bs, hidden, steps, preempt_rank):
    """The ≥2-host elastic A/B (ISSUE 10): one uninterrupted P-process
    run, one preempt-and-rejoin run, and the delta — the end-to-end
    cost of losing and re-admitting a rank (typed detection + two
    membership resolves + two rebuilds + snapshot sync) under real
    process boundaries."""
    import re
    import socket
    import subprocess
    import sys
    if not 0 <= preempt_rank < nprocs:
        raise SystemExit(f"--preempt-rank {preempt_rank} is not a rank "
                         f"of a {nprocs}-process run")
    env = dict(os.environ)
    if "XLA_FLAGS" in env:
        env["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+\s*", "",
            env["XLA_FLAGS"])
    rows = []
    for leg_preempt in (-1, preempt_rank):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--gloo-elastic-worker", str(pid), str(nprocs), str(port),
             str(per_rank_bs), str(hidden), str(steps),
             str(leg_preempt)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for pid in range(nprocs)]
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=600)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
        assert all(p.returncode == 0 for p in procs), \
            [(p.returncode, o[-2000:]) for p, o in zip(procs, outs)]
        row = json.loads([ln for ln in outs[0].splitlines()
                          if ln.startswith("{")][-1])
        rows.append(row)
        print(json.dumps(row), flush=True)
    base, elastic = rows
    print(json.dumps({
        "processes": nprocs, "preempt_rank": preempt_rank,
        "elastic_overhead_s": round(
            elastic["wall_s"] - base["wall_s"], 3),
        "elastic_step_ms_vs_baseline": round(
            elastic["step_ms"] - base["step_ms"], 3),
        "resizes": elastic["resizes"]}), flush=True)
    return rows


def _gloo_autotune_worker(pid, nprocs, port, per_rank_bs, hidden, steps,
                          mode, ratio):
    """One process of the ISSUE 19 autotune A/B: the same hierarchical
    compiled DP step as ``_gloo_worker``'s striped legs, but leg
    ``auto`` builds its communicator with ``autotune=True`` (the
    startup micro-bench runs over the real gloo fabric and the agreed
    plan fills the knobs the caller left free) while leg ``hand`` pins
    ``stripe_ratio`` to the value the auto leg derived.  Every per-step
    loss travels in the row as ``float.hex()`` — the parent gates
    BITWISE equality between the two legs (the golden-trajectory
    contract: a derived plan matching the hand knobs must compile the
    identical program)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from chainermn_tpu.communicators._communication_utility import (
        initialize_distributed)
    assert initialize_distributed(f"localhost:{port}",
                                  num_processes=nprocs, process_id=pid)
    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.models import MLP, Classifier

    if mode == "auto":
        # stripe_ratio deliberately NOT passed: the knob must stay free
        # for the agreed plan to fill (hand knobs always win — a pinned
        # ratio here would make the A/B compare hand vs hand)
        comm = ct.create_communicator("hierarchical",
                                      batch_collectives=True,
                                      autotune=True)
        assert comm.autotune_plan is not None
        assert comm.striped, \
            "autotune must have applied the derived stripe plan"
    else:
        comm = ct.create_communicator("hierarchical",
                                      batch_collectives=True,
                                      stripe_ratio=float(ratio))
    assert comm.size == nprocs == jax.device_count()
    model = Classifier(MLP(n_units=hidden, n_out=10, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.01, momentum=0.9), comm).setup(model)

    gbs = per_rank_bs * nprocs
    rng = np.random.RandomState(0)
    x = np.asarray(rng.normal(0, 1, (gbs, 64)).astype(np.float32))
    t = np.asarray(rng.randint(0, 10, gbs).astype(np.int32))

    losses = []
    for _ in range(3):  # trace+compile, then steady-state warmup
        losses.append(float(opt.update(model, x, t)))
    if nprocs > 1:
        comm._host_channel().barrier()
    start = time.perf_counter()
    for _ in range(steps):
        # the per-step float() sync is part of BOTH legs' measured
        # loop, so the step_ms rows stay comparable — and the full
        # loss trajectory is what the bitwise gate compares
        losses.append(float(opt.update(model, x, t)))
    dt = time.perf_counter() - start
    if pid == 0:
        row = {"mode": mode, "processes": nprocs,
               "per_rank_bs": per_rank_bs,
               "stripe_ratio": comm.stripe_ratio,
               "step_ms": round(dt / steps * 1e3, 3),
               "examples_per_sec": round(steps * gbs / dt, 1),
               "losses_hex": [float(v).hex() for v in losses]}
        if mode == "auto":
            plan = comm.autotune_plan
            dcn = plan["measurements"]["hops"].get("dcn") or {}
            row["plan"] = {
                "fingerprint": plan["fingerprint"],
                "stripe_ratio": plan["stripe_ratio"],
                "bucket_mb": plan["bucket_mb"],
                "grad_dtype": plan["grad_dtype"],
                "dcn_gbps": dcn.get("gbps"),
                "dcn_lat_us": dcn.get("lat_us"),
                "notes": plan["derivation"]["notes"]}
        print(json.dumps(row), flush=True)


#: sweep legs of the --autotune optimum-band gate, and how far (mean
#: step_ms, relative) a ratio may sit above the sweep winner and still
#: count as inside the band.  Generous on purpose: loopback gloo on a
#: time-sliced host is noisy, and at one device per process the ICI hop
#: is wireless, which flattens the ratio curve toward a tie
AUTOTUNE_SWEEP_RATIOS = (0.25, 0.5, 0.75)
AUTOTUNE_BAND_TOL = 0.35


def _run_autotune_ab(nprocs, per_rank_bs, hidden, steps):
    """The 2-process gloo autotune A/B (ISSUE 19) — the promotion of
    the queued three-invocation striped ratio sweep into ONE
    self-gating invocation.  Leg 1 builds its communicator with
    ``autotune=True`` (startup micro-bench over the real gloo fabric,
    agreed plan applied); leg 2 hand-pins ``stripe_ratio`` to the
    derived value.  Gates: (a) BITWISE golden-trajectory equality
    between the two legs — the derived plan must compile exactly the
    program the equivalent hand knobs would; (b) the derived ratio
    lands inside the measured optimum band of the
    ``AUTOTUNE_SWEEP_RATIOS`` sweep (mean step_ms within
    ``AUTOTUNE_BAND_TOL`` of the sweep winner).  In the gloo world the
    ICI axis is size 1 (unmeasurable), so the derived ratio is the
    documented DEFAULT_STRIPE_RATIO fallback — the band gate then
    checks the fallback itself is not a measured pessimization."""
    import re
    import socket
    import subprocess
    import sys
    env = dict(os.environ)
    if "XLA_FLAGS" in env:
        env["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+\s*", "",
            env["XLA_FLAGS"])
    # a leaked ratio env var would hand-pin the auto leg's knob and turn
    # the golden gate into hand-vs-hand
    env.pop("CHAINERMN_TPU_STRIPE_RATIO", None)

    def leg(mode, ratio):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--gloo-autotune-worker", str(pid), str(nprocs), str(port),
             str(per_rank_bs), str(hidden), str(steps), mode, str(ratio)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for pid in range(nprocs)]
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=600)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
        assert all(p.returncode == 0 for p in procs), \
            [(p.returncode, o[-2000:]) for p, o in zip(procs, outs)]
        row = json.loads([ln for ln in outs[0].splitlines()
                          if ln.startswith("{")][-1])
        print(json.dumps({k: v for k, v in row.items()
                          if k != "losses_hex"}), flush=True)
        return row

    auto = leg("auto", "-")
    derived = auto["plan"]["stripe_ratio"]
    hand = leg("hand", derived)
    assert auto["losses_hex"] == hand["losses_hex"], \
        f"golden-trajectory gate FAILED: autotune (plan " \
        f"{auto['plan']['fingerprint']}) diverged from hand knobs at " \
        f"stripe_ratio={derived}"

    sweep = {}
    for r in AUTOTUNE_SWEEP_RATIOS:
        # the hand leg already measured the derived ratio — reuse its
        # datum rather than burning a fourth spawn on the same point
        sweep[r] = hand["step_ms"] if abs(r - derived) < 1e-9 \
            else leg("hand", r)["step_ms"]
    winner_ms = min(sweep.values())
    band = [r for r in AUTOTUNE_SWEEP_RATIOS
            if sweep[r] <= winner_ms * (1.0 + AUTOTUNE_BAND_TOL)]
    assert any(abs(derived - r) < 1e-9 for r in band), \
        f"derived stripe_ratio {derived} is outside the measured " \
        f"optimum band {band} (sweep step_ms {sweep}, winner " \
        f"{winner_ms} ms, tol {AUTOTUNE_BAND_TOL:.0%})"

    print(json.dumps({
        "autotune_ab": True, "processes": nprocs,
        "derived_stripe_ratio": derived,
        "plan_fingerprint": auto["plan"]["fingerprint"],
        "golden_trajectory_equal": True,
        "sweep_step_ms": {str(r): sweep[r] for r in sorted(sweep)},
        "optimum_band": band,
        "derived_in_band": True,
        "measured_dcn_gbps": auto["plan"]["dcn_gbps"],
        "measured_dcn_lat_us": auto["plan"]["dcn_lat_us"]}), flush=True)
    return auto, hand, sweep


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--per-chip-bs", type=int, default=8)
    parser.add_argument("--size", type=int, default=96)
    parser.add_argument("--steps", type=int, default=5)
    parser.add_argument("--model", default="resnet18",
                        choices=["resnet18", "resnet50"])
    parser.add_argument("--allreduce-floats", type=int, default=1 << 22)
    parser.add_argument("--platform", default=None)
    parser.add_argument("--simulate-devices", type=int, default=0)
    parser.add_argument("--project", action="store_true",
                        help="print analytic pod projections from a "
                             "measured single-chip step time (--step-ms)")
    parser.add_argument("--step-ms", type=float, default=None,
                        help="measured single-chip step time for --project")
    parser.add_argument("--gloo-procs", default=None,
                        help="comma list, e.g. 1,2,4: measure the REAL "
                             "cross-process compiled DP step at each "
                             "process count (gloo CPU backend)")
    parser.add_argument("--gloo-worker", nargs=8, default=None,
                        help=argparse.SUPPRESS)  # internal
    parser.add_argument("--gloo-elastic-worker", nargs=7, default=None,
                        help=argparse.SUPPRESS)  # internal
    parser.add_argument("--gloo-fleet-worker", nargs=5, default=None,
                        help=argparse.SUPPRESS)  # internal
    parser.add_argument("--gloo-capacity-worker", nargs=5, default=None,
                        help=argparse.SUPPRESS)  # internal
    parser.add_argument("--gloo-autotune-worker", nargs=8, default=None,
                        help=argparse.SUPPRESS)  # internal
    parser.add_argument("--autotune", action="store_true",
                        help="run the self-tuning A/B (ISSUE 19): one "
                             "gloo leg builds its communicator with "
                             "autotune=True (startup micro-bench over "
                             "the real fabric, agreed plan applied), "
                             "one hand-pins the derived knobs; gates "
                             "BITWISE golden-trajectory equality plus "
                             "'derived ratio inside the measured "
                             "optimum band' of the {0.25, 0.5, 0.75} "
                             "sweep — replaces the queue's three "
                             "striped ratio-sweep invocations; P = max "
                             "of --gloo-procs (default 2)")
    parser.add_argument("--capacity", action="store_true",
                        help="run the capacity-transfer A/B (ISSUE 16):"
                             " one gloo leg where rank 1 keeps training"
                             " through a serving burst (one replica), "
                             "one where the CapacityBroker converts it "
                             "into a second replica and retires it "
                             "after the drain; gates zero drops + "
                             "training loss parity (±5%); the summary "
                             "line is the p99 completion delta the "
                             "borrowed replica bought.  Request count "
                             "from --fleet-requests; P = max of "
                             "--gloo-procs (default 2)")
    parser.add_argument("--fleet-kill", type=int, default=None,
                        help="run the serving-fleet kill-under-load A/B"
                             " (ISSUE 15): an uninterrupted 2-replica "
                             "gloo fleet run vs one where the worker "
                             "replica preempts at this decode step, its"
                             " in-flight requests replay on the "
                             "survivor (zero drops) and the replica "
                             "re-joins via the multicast-tree weight "
                             "sync; P = max of --gloo-procs (default "
                             "2).  The summary line is the detection-"
                             "bounded p99 spike + the sync cost")
    parser.add_argument("--fleet-requests", type=int, default=16,
                        help="open-loop request count for --fleet-kill")
    parser.add_argument("--preempt-rank", type=int, default=None,
                        help="run the elastic preempt-and-rejoin A/B "
                             "(ISSUE 10): an uninterrupted P-process "
                             "gloo run vs one where this rank is "
                             "hard-preempted mid-run, shrinks out, "
                             "re-joins and the world grows back; P = "
                             "max of --gloo-procs (default 2).  The "
                             "summary line is the end-to-end "
                             "elasticity tax")
    parser.add_argument("--gloo-hidden", type=int, default=512,
                        help="MLP hidden width for --gloo-procs")
    parser.add_argument("--gloo-zero", action="store_true",
                        help="use the ZeRO-1 sharded step (psum_scatter"
                             " + all_gather) instead of plain DP pmean")
    parser.add_argument("--gloo-reps", type=int, default=1,
                        help="repeat each P-process measurement and "
                             "report mean/min/max (noise quantification"
                             " on time-sliced hosts)")
    parser.add_argument("--gloo-exchange", default="flat",
                        help="gradient-exchange structure under test: "
                             "per_leaf|flat|bucketed|reduce_scatter|"
                             "hierarchical|hierarchical_rs|striped|"
                             "striped_rs (validated against "
                             "communicators.EXCHANGES — the "
                             "ISSUE 5 exposed-comm A/B: run the curve "
                             "once with flat, once with bucketed — the "
                             "delta across real process boundaries is "
                             "the overlap payoff.  The ISSUE 6 "
                             "hierarchical legs run the two-level "
                             "exchange with the DCN hop on the real "
                             "process boundary: dcn=P × ici=1 at one "
                             "device per process; the ISSUE 11 striped "
                             "legs run the multi-path exchange — sweep "
                             "--stripe-ratio over {0.25, 0.5, 0.75} to "
                             "measure the per-topology split a pod "
                             "should commit)")
    parser.add_argument("--stripe-ratio", type=float, default=None,
                        help="DCN share of the striped exchange for "
                             "this invocation (striped legs only; "
                             "default: the committed "
                             "DEFAULT_STRIPE_RATIO).  The first-chip-"
                             "contact queue runs the {0.25, 0.5, 0.75} "
                             "sweep as three invocations")
    args = parser.parse_args()

    if args.gloo_worker:
        pid, nprocs, port, bs, hidden, steps, zero = \
            map(int, args.gloo_worker[:7])
        _gloo_worker(pid, nprocs, port, bs, hidden, steps, bool(zero),
                     exchange=args.gloo_worker[7])
        return
    if args.gloo_elastic_worker:
        _gloo_elastic_worker(*map(int, args.gloo_elastic_worker))
        return
    if args.gloo_fleet_worker:
        _gloo_fleet_worker(*map(int, args.gloo_fleet_worker))
        return
    if args.gloo_capacity_worker:
        _gloo_capacity_worker(*map(int, args.gloo_capacity_worker))
        return
    if args.gloo_autotune_worker:
        pid, nprocs, port, bs, hidden, steps = \
            map(int, args.gloo_autotune_worker[:6])
        _gloo_autotune_worker(pid, nprocs, port, bs, hidden, steps,
                              args.gloo_autotune_worker[6],
                              args.gloo_autotune_worker[7])
        return
    if args.autotune:
        nprocs = max(int(c) for c in args.gloo_procs.split(",")) \
            if args.gloo_procs else 2
        _run_autotune_ab(nprocs, args.per_chip_bs, args.gloo_hidden,
                         args.steps)
        return
    if args.capacity:
        nprocs = max(int(c) for c in args.gloo_procs.split(",")) \
            if args.gloo_procs else 2
        _run_capacity_ab(nprocs, args.fleet_requests)
        return
    if args.fleet_kill is not None:
        nprocs = max(int(c) for c in args.gloo_procs.split(",")) \
            if args.gloo_procs else 2
        _run_fleet_ab(nprocs, args.fleet_requests, args.fleet_kill)
        return
    if args.preempt_rank is not None:
        nprocs = max(int(c) for c in args.gloo_procs.split(",")) \
            if args.gloo_procs else 2
        _run_elastic_ab(nprocs, args.per_chip_bs, args.gloo_hidden,
                        args.steps, args.preempt_rank)
        return
    if args.gloo_procs:
        # lazy: the vocabulary lives with the communicator mapping (the
        # parent never touches devices, so the import is safe here; the
        # --gloo-worker branch above stays import-free until its own
        # platform pinning has run)
        from chainermn_tpu.communicators import EXCHANGES
        if args.gloo_exchange not in EXCHANGES:
            parser.error(f"unknown --gloo-exchange "
                         f"{args.gloo_exchange!r} "
                         f"({'|'.join(EXCHANGES)})")
        if args.gloo_zero and args.gloo_exchange in ("reduce_scatter",
                                                     "hierarchical_rs",
                                                     "striped_rs"):
            # fail before any worker spawns: every worker would raise
            # create_multi_node_optimizer's zero×reduce_scatter
            # ValueError after ports are bound and gloo is up — in the
            # unattended queue that burns the slot with no datum
            parser.error("--gloo-zero already exchanges gradients via "
                         "reduce-scatter; drop --gloo-exchange "
                         f"{args.gloo_exchange}")
        if args.stripe_ratio is not None \
                and args.gloo_exchange not in ("striped", "striped_rs"):
            parser.error("--stripe-ratio only applies to the striped "
                         "legs; drop it or use --gloo-exchange striped")
        counts = [int(c) for c in args.gloo_procs.split(",")]
        _run_gloo_curve(counts, args.per_chip_bs, args.gloo_hidden,
                        args.steps, zero=args.gloo_zero,
                        reps=args.gloo_reps, exchange=args.gloo_exchange,
                        stripe_ratio=args.stripe_ratio)
        return

    if args.project:
        if args.step_ms is None:
            parser.error("--project requires --step-ms (from bench.py)")
        for n in (2, 4, 8, 16, 32, 64):
            eff = project_efficiency(args.step_ms, n)
            print(json.dumps({"devices": n, "step_ms_1chip": args.step_ms,
                              "projected_scaling_efficiency": round(eff, 4)}))
        return

    if args.simulate_devices:
        from chainermn_tpu.utils import simulate_devices
        simulate_devices(args.simulate_devices)
    if args.platform:
        from chainermn_tpu.utils import use_platform
        use_platform(args.platform)

    import jax
    max_devices = len(jax.devices())
    counts = [n for n in (1, 2, 4, 8, 16, 32) if n <= max_devices]

    base = None
    results = []
    for n in counts:
        thr = measure_step_throughput(n, args.per_chip_bs, args.size,
                                      args.steps, args.model)
        if base is None:
            base = thr
        eff = thr / (n * base)
        bw = measure_allreduce_bandwidth(n, args.allreduce_floats) \
            if n > 1 else 0.0
        row = {"devices": n, "images_per_sec": round(thr, 2),
               "scaling_efficiency": round(eff, 4),
               "allreduce_gbps_per_chip": round(bw, 2)}
        results.append(row)
        print(json.dumps(row), flush=True)

    print(json.dumps({
        "metric": f"{args.model}_dp_scaling_efficiency_1_to_{counts[-1]}",
        "value": results[-1]["scaling_efficiency"],
        "unit": "fraction",
        "vs_baseline": round(results[-1]["scaling_efficiency"] / 0.9, 3),
    }))


if __name__ == "__main__":
    main()
