"""Benchmark harness: ResNet-50/ImageNet training throughput per chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "mfu": ..., "compile_s": ..., "platform": ..., ...}

Never dies with a bare traceback: on backend failure it retries on CPU
(explicitly marked ``platform: "cpu_fallback"``) and, failing even that,
emits a JSON line with an ``error`` field so the driver always records a
machine-readable result (VERDICT r1 Weak #1).

Baseline derivation (BASELINE.md: reference published numbers): the
ChainerMN scaling study (arXiv:1710.11351) trains ResNet-50/ImageNet 100
epochs in ~4.4 h on 128 P100s → 1.28M images × 100 / (4.4·3600 s) / 128
≈ 225 images/sec/GPU.  ``vs_baseline`` is measured throughput per chip
against that per-device figure.

MFU: analytic ResNet-50 flops model.  Forward ≈ 4.1 GFLOP/image at 224²
(standard count, multiply-add = 2 flops); training step ≈ 3× forward
(bwd ≈ 2× fwd).  MFU = achieved flops/sec ÷ peak bf16 flops of the chip
(TPU v5 lite: 197 TFLOP/s bf16; override with BENCH_PEAK_TFLOPS).

The training step is the framework's real data-parallel path:
``create_multi_node_optimizer`` over a ``jax_ici`` communicator spanning
all available chips (one on this box), bf16 conv compute, bf16 gradient
compression — the TPU translation of the reference's flagship
``pure_nccl`` fp16 configuration (SURVEY §2.1 pure_nccl).
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_PER_SEC = 225.0  # ChainerMN-era images/sec/P100 (docstring)

# Peak bf16 flops by TPU generation (per chip).  v5 lite = v5e.
_PEAK_TFLOPS = {
    "v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0,
    "v4": 275.0, "v6e": 918.0, "cpu": None,
}


def _resnet50_train_flops_per_image(image_size):
    """Analytic flops model: fwd ~4.1 GFLOP at 224² (scales with area),
    train = fwd + bwd ≈ 3× fwd."""
    fwd = 4.1e9 * (image_size / 224.0) ** 2
    return 3.0 * fwd


def _peak_tflops(devices):
    override = os.environ.get("BENCH_PEAK_TFLOPS")
    if override:
        return float(override)
    kind = getattr(devices[0], "device_kind", "") or ""
    kl = kind.lower()
    for name, peak in _PEAK_TFLOPS.items():
        if name in kl and peak:
            return peak
    return None


def _transformer_flops_per_token(d_model, n_layers, n_vocab, seq_len):
    """Analytic train-step flops per token for the causal LM: matmul
    fwd = 2·(12·L·d² + d·V), attention fwd = 4·T·d·L (scores + values,
    causal halving ignored ≈ upper bound), train ≈ 3× fwd."""
    matmul = 2.0 * (12.0 * n_layers * d_model ** 2 + d_model * n_vocab)
    attn = 4.0 * seq_len * d_model * n_layers
    return 3.0 * (matmul + attn)


def _enable_compile_cache(jax):
    try:  # persistent compile cache: repeat runs skip the ~30s XLA compile
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/chainermn_tpu_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def _timed_steps(do_steps, calls, trials=3):
    """Shared timing discipline for every bench mode: one trace+compile
    call, 2 warmup calls, then best-of-``trials`` over ``calls``
    dispatches per trial — each trial synced by a real device->host
    value fetch (float(loss)); through the remote-tunnel backend on this
    box jax.block_until_ready returns before execution completes, which
    once inflated numbers past physical peak flops.  A value fetch
    cannot be faked.  Returns (best_elapsed_seconds, compile_seconds)."""
    t0 = time.perf_counter()
    loss = do_steps()  # first call: trace + XLA compile
    float(loss)
    compile_s = time.perf_counter() - t0
    for _ in range(2):
        loss = do_steps()
    float(loss)
    best = None
    for _ in range(trials):
        start = time.perf_counter()
        for _ in range(calls):
            loss = do_steps()
        float(loss)
        elapsed = time.perf_counter() - start
        best = elapsed if best is None else min(best, elapsed)
    return best, compile_s


def _run_bench_transformer():
    """Auxiliary bench mode (BENCH_MODEL=transformer): GPT-2-small-class
    causal LM, tokens/sec/chip + MFU.  No reference-era baseline exists
    for this vertical (vs_baseline=null); recorded for the long-context
    story alongside the headline ResNet number."""
    import jax
    _enable_compile_cache(jax)
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import Adam
    from chainermn_tpu.models import TransformerLM

    per_chip_bs = int(os.environ.get("BENCH_BS", "8"))
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    n_steps = int(os.environ.get("BENCH_STEPS", "20"))
    d_model = int(os.environ.get("BENCH_D_MODEL", "768"))
    n_layers = int(os.environ.get("BENCH_LAYERS", "12"))
    n_vocab = int(os.environ.get("BENCH_VOCAB", "32768"))
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    n_heads = int(os.environ.get("BENCH_HEADS", "0")) or max(1, d_model // 64)
    if d_model % n_heads:
        raise ValueError(f"BENCH_D_MODEL={d_model} is not divisible by "
                         f"n_heads={n_heads}; set BENCH_HEADS explicitly")

    devices = jax.devices()
    n_devices = len(devices)
    platform = devices[0].platform

    def run(per_chip_bs):
        comm = ct.create_communicator("jax_ici",
                                      allreduce_grad_dtype="bfloat16")
        model = TransformerLM(n_vocab=n_vocab, d_model=d_model,
                              n_heads=n_heads, n_layers=n_layers,
                              max_len=seq_len, seed=0, remat=remat,
                              compute_dtype=jnp.bfloat16)
        comm.bcast_data(model)
        inner = Adam(alpha=3e-4)
        inner.donate_params = True
        opt = ct.create_multi_node_optimizer(inner, comm).setup(model)

        global_bs = per_chip_bs * n_devices
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randint(0, n_vocab, (global_bs, seq_len))
                        .astype(np.int32))
        t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))
        best, compile_s = _timed_steps(lambda: opt.update(model, x, t),
                                       n_steps)
        return n_steps * global_bs * seq_len / best, compile_s

    tokens_per_sec = None
    last_err = None
    used_bs = None
    for bs in (per_chip_bs, per_chip_bs // 2, per_chip_bs // 4):
        if bs < 1:
            break
        try:
            tokens_per_sec, compile_s = run(bs)
            used_bs = bs
            break
        except Exception as e:  # e.g. HBM OOM at the largest batch
            last_err = e
    if tokens_per_sec is None:
        raise last_err
    per_chip = tokens_per_sec / n_devices
    result = {
        "metric": "transformer_lm_train_throughput",
        "value": round(per_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,
        "platform": platform,
        "device_kind": getattr(devices[0], "device_kind", platform),
        "n_devices": n_devices,
        "per_chip_batch": used_bs,
        "seq_len": seq_len,
        "d_model": d_model,
        "n_layers": n_layers,
        "compile_s": round(compile_s, 1),
    }
    peak = _peak_tflops(devices)
    if peak:
        fpt = _transformer_flops_per_token(d_model, n_layers, n_vocab,
                                           seq_len)
        result["mfu"] = round(per_chip * fpt / (peak * 1e12), 4)
        result["peak_tflops_bf16"] = peak
    return result


def _run_bench():
    import jax
    _enable_compile_cache(jax)
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.models import Classifier, ResNet50

    # smoke-test knobs (defaults are the real benchmark configuration)
    per_chip_bs = int(os.environ.get("BENCH_BS", "64"))
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    image_size = int(os.environ.get("BENCH_SIZE", "224"))
    n_steps = int(os.environ.get("BENCH_STEPS", "40"))
    # BENCH_SCAN=K fuses K steps per dispatch via update_scan (one jit
    # containing a lax.scan) — isolates device throughput from host/relay
    # dispatch latency; 0 = plain per-step update() dispatch
    scan_k = int(os.environ.get("BENCH_SCAN", "0"))

    devices = jax.devices()  # raises if the backend is unavailable
    n_devices = len(devices)
    platform = devices[0].platform
    device_kind = getattr(devices[0], "device_kind", platform)

    def run(per_chip_bs):
        global_bs = per_chip_bs * n_devices
        comm = ct.create_communicator("jax_ici",
                                      allreduce_grad_dtype="bfloat16")
        model = Classifier(ResNet50(n_classes=1000, remat=remat,
                                    compute_dtype=jnp.bfloat16, seed=0))
        comm.bcast_data(model)
        inner = MomentumSGD(lr=0.1, momentum=0.9)
        inner.donate_params = True  # in-place param update (bench owns the model)
        opt = ct.create_multi_node_optimizer(inner, comm).setup(model)

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.normal(
            0, 1, (global_bs, 3, image_size, image_size)).astype(np.float32))
        t = jnp.asarray(rng.randint(0, 1000, global_bs).astype(np.int32))

        if scan_k:
            xs = jnp.broadcast_to(x, (scan_k,) + x.shape)
            ts = jnp.broadcast_to(t, (scan_k,) + t.shape)
            do_steps = lambda: opt.update_scan(model, xs, ts)[-1]
            steps_per_call, calls = scan_k, max(1, n_steps // scan_k)
        else:
            do_steps = lambda: opt.update(model, x, t)
            steps_per_call, calls = 1, n_steps
        best, compile_s = _timed_steps(do_steps, calls)
        return calls * steps_per_call * global_bs / best, compile_s

    images_per_sec = None
    last_err = None
    used_bs = None
    for bs in (per_chip_bs, per_chip_bs // 2, per_chip_bs // 4):
        if bs < 1:
            break
        try:
            images_per_sec, compile_s = run(bs)
            used_bs = bs
            break
        except Exception as e:  # e.g. HBM OOM at the largest batch
            last_err = e
    if images_per_sec is None:
        raise last_err

    per_chip = images_per_sec / n_devices
    result = {
        "metric": "resnet50_imagenet_train_throughput",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC, 3),
        "platform": platform,
        "device_kind": device_kind,
        "n_devices": n_devices,
        "per_chip_batch": used_bs,
        "image_size": image_size,
        "compile_s": round(compile_s, 1),
        "fused_steps_per_dispatch": scan_k or 1,
    }
    peak = _peak_tflops(devices)
    if peak:
        flops = _resnet50_train_flops_per_image(image_size)
        result["mfu"] = round(per_chip * flops / (peak * 1e12), 4)
        result["peak_tflops_bf16"] = peak
    return result


def main():
    transformer_mode = \
        os.environ.get("BENCH_MODEL", "resnet50") == "transformer"
    if transformer_mode:
        err_metric = ("transformer_lm_train_throughput", "tokens/sec/chip")
    else:
        err_metric = ("resnet50_imagenet_train_throughput",
                      "images/sec/chip")
    try:
        result = _run_bench_transformer() if transformer_mode \
            else _run_bench()
    except Exception as e:
        err = f"{type(e).__name__}: {e}"
        if (os.environ.get("JAX_PLATFORMS", "") != "cpu"
                and os.environ.get("BENCH_NO_FALLBACK") != "1"):
            # Backend wedged → rerun ourselves on CPU so the round still
            # yields a datum, explicitly marked as a fallback.
            import subprocess
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       BENCH_BS=os.environ.get("BENCH_BS_CPU", "8"),
                       BENCH_STEPS="3")
            result = None
            try:
                proc = subprocess.run([sys.executable, __file__],
                                      env=env, capture_output=True,
                                      text=True, timeout=1200)
                line = (proc.stdout.strip().splitlines() or [""])[-1]
                child = json.loads(line)
                child_err = child.get("error")
                result = child
                result["error"] = err
                if child.get("value") is not None:
                    result["platform"] = "cpu_fallback"
                else:  # child failed too — keep its own diagnostic
                    result["fallback_error"] = child_err
            except Exception as fb:
                result = {
                    "metric": err_metric[0],
                    "value": None, "unit": err_metric[1],
                    "vs_baseline": None, "error": err,
                    "fallback_error": f"{type(fb).__name__}: {fb}"[:500],
                }
        else:
            result = {
                "metric": err_metric[0],
                "value": None, "unit": err_metric[1],
                "vs_baseline": None, "error": err,
            }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
