"""Benchmark harness: ResNet-50/ImageNet training throughput per chip.

Prints ONE final JSON line (preliminary lines may precede it; the last
line is authoritative):
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "mfu": ..., "compile_s": ..., "platform": ..., ...}

Robustness contract (VERDICT r2 Missing #1): the harness must ALWAYS
emit a parseable result line well inside the driver's timeout window,
no matter what wedges.  Three layers of defense:

1. **Supervisor/child split.**  ``main()`` re-execs itself as a child
   process and enforces ``BENCH_DEADLINE_S`` (default 270 s once the
   prewarm sentinel marks the XLA cache warm, 480 s on first contact —
   cold compile through the relay measured 75–109 s in r2) from the
   parent, which never imports jax.  This is the only mechanism that
   survives the known failure mode on this box — ``jax.devices()``
   blocking forever inside ``make_c_api_client`` when the remote relay
   is wedged — because a SIGALRM handler cannot run while the main
   thread is stuck in a C call.  At the deadline the supervisor emits
   and DETACHES the child rather than killing it: killing (or
   alarm-interrupting) a process with an in-flight remote-compile RPC
   is what wedges the relay in the first place (r5 postmortems); the
   detached child drains its RPC, finishes, and persists its result
   for the next run.  A registry caps lingering detached children.
2. **Early emission.**  The child emits a full result line immediately
   after the FIRST successful timing trial (and persists it to
   ``/tmp/chainermn_tpu_last_bench.json``); later trials only improve
   it.  Default trials = 1 for driver runs (``BENCH_TRIALS`` raises it).
3. **Last-good-result cache, two slots.**  If the deadline passes
   before any trial completes, the supervisor re-emits the most recent
   persisted result marked ``"stale": true`` (with the failure reason
   attached), so a wedged relay still yields the last real measurement
   instead of nothing.  Flagship entries are mirrored into the
   committed ``bench_last_good.json`` because machine restarts wipe
   /tmp (and are also what heals the relay, so the two failure modes
   co-occur); both slots share the same fingerprint/payload gates.

Baseline derivation (BASELINE.md: reference published numbers): the
ChainerMN scaling study (arXiv:1710.11351) trains ResNet-50/ImageNet 100
epochs in ~4.4 h on 128 P100s → 1.28M images × 100 / (4.4·3600 s) / 128
≈ 225 images/sec/GPU.  ``vs_baseline`` is measured throughput per chip
against that per-device figure.

MFU: analytic ResNet-50 flops model.  Forward ≈ 4.1 GFLOP/image at 224²
(standard count, multiply-add = 2 flops); training step ≈ 3× forward
(bwd ≈ 2× fwd).  MFU = achieved flops/sec ÷ peak bf16 flops of the chip
(TPU v5 lite: 197 TFLOP/s bf16; override with BENCH_PEAK_TFLOPS).

The training step is the framework's real data-parallel path:
``create_multi_node_optimizer`` over a ``jax_ici`` communicator spanning
all available chips (one on this box), bf16 conv compute, bf16 gradient
compression — the TPU translation of the reference's flagship
``pure_nccl`` fp16 configuration (SURVEY §2.1 pure_nccl).

Env knobs (defaults = the flagship config; any deviation makes the run
a variant that is excluded from the last-good cache):

  measurement   BENCH_MODEL (resnet50|transformer|longcontext|serving|
                moe),
                BENCH_BS, BENCH_SIZE, BENCH_LAYOUT (NHWC|NCHW),
                BENCH_SCAN, BENCH_REMAT, BENCH_INPUT_PIPELINE — resnet;
                BENCH_SEQ, BENCH_D_MODEL, BENCH_LAYERS, BENCH_VOCAB,
                BENCH_HEADS, BENCH_REMAT_POLICY — transformer;
                BENCH_LC_SEQS (default 16384,32768), BENCH_LC_XLA_T
                (default 8192: the stock-XLA contrast leg),
                BENCH_LC_BS/BENCH_LC_HEAD_DIM/BENCH_LC_REPS —
                longcontext (T=16k/32k flash fwd+bwd rows + the
                "XLA fails to compile, flash runs" contrast; never
                cached as flagship data);
                BENCH_SERVE_QPS (default 16), BENCH_SERVE_TENANTS (4),
                BENCH_SERVE_REQUESTS (64), BENCH_SERVE_MAX_NEW (32),
                BENCH_SERVE_PROMPT (64), BENCH_SERVE_MAX_BATCH (8),
                BENCH_SERVE_PAGE (16), BENCH_SERVE_PAGES (256),
                BENCH_SERVE_PREFIX (16: per-tenant shared system-prompt
                tokens in the chat-shaped load; 0 disables the prefix
                cache — the A/B off leg), BENCH_SERVE_DISAGG (0|1:
                disaggregated prefill/decode slices),
                BENCH_SERVE_TP (1: tensor-parallel decode ways),
                BENCH_SERVE_SPEC_K (0: speculative decoding — K n-gram
                proposals verified per dispatch, bit-identical tokens;
                rows grow spec_steps/accepted_tokens_per_dispatch/
                spec_acceptance_rate/draft_overhead),
                BENCH_SERVE_CHUNK (0: chunked prefill — C-token chunks
                AND a mixed short/long load, every fourth prompt up to
                4x BENCH_SERVE_PROMPT; rows grow chunked_admissions/
                chunk_prefills),
                BENCH_SERVE_REPLICAS (1: >1 serves through a
                ReplicaFleet behind the router — rows grow replicas/
                reroutes/weight_sync_s), BENCH_FLEET_KILL_AT (-1:
                decode step at which the highest replica preempts;
                its in-flight sequences reroute with zero drops and a
                cold replica joins via the multicast-tree weight
                sync), BENCH_DIURNAL (0|1: sinusoidal arrival rate
                plus a CapacityBroker auto-applying the hysteresis
                policy's +1/-1 as REAL training<->serving role
                transfers — rows grow conversions/role_transfers/
                convert_s and are payload-fenced from the flagship
                cache), BENCH_DIURNAL_PERIOD (8.0 s),
                BENCH_DIURNAL_AMP (0.8), BENCH_DIURNAL_WORLD (2:
                synthetic training ranks eligible to convert),
                BENCH_DIURNAL_UP (8) / BENCH_DIURNAL_DOWN (0:
                queue-depth water marks) — serving (continuous-batching
                engine under a
                seeded open-loop Poisson load: tokens/sec + p50/p99
                per-token latency + page-pool occupancy +
                prefix_hit_rate / effective_capacity_x /
                transferred_page_bytes / tp;
                CPU runs clamp to a labeled cpu_smoke row; never
                cached as flagship data);
                BENCH_MOE_EXPERTS (chip count), BENCH_MOE_TOPK (1),
                BENCH_MOE_CAPACITY (1.25), BENCH_MOE_TWO_STAGE
                (''=auto|0|1) — moe (Switch-FFN expert-parallel
                vertical: tokens/sec/chip + exchanged dispatch bytes
                per fabric + moe_dropped_frac; the hierarchical
                BENCH_EXCHANGE legs run the two-stage ici×dcn dispatch
                and BENCH_GRAD_DTYPE=int8 quantizes its DCN crossing;
                CPU runs clamp to a labeled cpu_smoke row; never
                cached as flagship data);
                BENCH_STEPS (steps/trial), BENCH_TRIALS,
                BENCH_PEAK_TFLOPS (MFU denominator override)
                BENCH_DONATE=0 (A/B leg: disable params/opt-state
                buffer donation — never cached as flagship data),
                BENCH_MEMSTATS=0 (skip the memory_analysis row fields),
                BENCH_EXCHANGE (per_leaf|flat|bucketed|reduce_scatter|
                hierarchical|hierarchical_rs — gradient-exchange
                structure of the DP step; default flat, the historical
                flagship config; any other value is a variant excluded
                from the last-good cache; the hierarchical legs run
                the two-level ici × dcn exchange and carry
                topology/ici_size/dcn_size + per-hop exchanged-byte
                columns),
                BENCH_BUCKET_MB (bucket bound for bucketed, default 4;
                the recovery queue sweeps 1/4/16),
                BENCH_INTER_SIZE (hierarchical legs: force a dcn × ici
                split of the local chips — the on-host structural A/B;
                default: one dcn group per controller process),
                BENCH_SHORT_STEPS (first-contact fallback steps/trial,
                default 4 — see the staleness note below)
  staleness     a FIRST-CONTACT run (no warm-cache sentinel for the
                model) with a deadline below the first-contact default
                clamps to BENCH_SHORT_STEPS and emits a FRESH row
                (n_steps-gated out of the flagship cache) instead of
                measuring into the deadline; and the stale re-serve
                path REFUSES to serve the cached flagship on first
                contact — three straight rounds (VERDICT r3–r5) the
                driver's first contact returned the same stale datum
                with rc=0 and the round recorded no fresh data.  A
                first-contact invocation now returns fresh data or an
                honest ``value: null`` error, never ``"stale": true``.
  deadline      BENCH_DEADLINE_S (else 270 s warm / 480 s first
                contact per model, via BENCH_PREWARM_SENTINEL);
                compile time is EXCLUDED from it via the compile
                heartbeat (BENCH_COMPILE_STAMP path, credit capped at
                BENCH_COMPILE_GRACE_S, default 900)
  compile cache BENCH_XLA_CACHE_DIR (persistent XLA cache location;
                cpu+scan runs skip persistence — replay segfault,
                BENCH_NOTES r5 tail)
  cache slots   BENCH_CACHE_PATH (/tmp), BENCH_REPO_CACHE_PATH
                (committed bench_last_good.json; "" disables)
  detach        BENCH_DETACH_REGISTRY (lingering-children registry),
                BENCH_START_STAMP (cross-run contention detection)
  internal      BENCH_SUPERVISED / BENCH_RUN_ID / BENCH_STALE_FP /
                BENCH_CONTENDED (set by the supervisor),
                BENCH_NO_SUPERVISE (child only — deadline becomes
                cooperative-only), BENCH_NO_FALLBACK (disable the CPU
                fallback re-exec), BENCH_BS_CPU (fallback batch),
                BENCH_TEST_WEDGE (fault injection for tests)
"""

import fcntl
import json
import os
import selectors
import signal
import subprocess
import sys
import time

import numpy as np

BASELINE_IMG_PER_SEC = 225.0  # ChainerMN-era images/sec/P100 (docstring)

# Flagship-config defaults, shared by the env lookups AND the cache
# fingerprint (`_cacheable`) so a config bump cannot silently disable
# last-good persistence.  OOM backoff halves the batch at most twice,
# hence the //4 floor on an acceptable per-chip batch.
DEFAULT_BS = 64
DEFAULT_SIZE = 224
DEFAULT_SEQ = 1024
# steps per timing trial: part of the fingerprint/payload gates — a
# short-step warmup (the recovery queue's BENCH_STEPS=4 prewarm) has
# different amortization and must never be re-served as flagship data
DEFAULT_STEPS = 40
DEFAULT_TF_STEPS = 20
# transformer-mode flagship config (GPT-2-small-class): shared by the
# env parsing, the fingerprint, and the payload checks — one definition
# so a bump cannot silently desync the cache gates
DEFAULT_TF_BS = 8
DEFAULT_TF_D_MODEL = 768
DEFAULT_TF_LAYERS = 12
DEFAULT_TF_VOCAB = 32768

_CACHE_PATH = os.environ.get("BENCH_CACHE_PATH",
                             "/tmp/chainermn_tpu_last_bench.json")
# Repo-committed fallback slot for the same cache: /tmp is wiped by
# machine restarts (round 5 saw the restart that HEALED the relay also
# destroy the freshly recorded flagship datum), so every successful
# flagship run mirrors its entry here too.  The builder commits the
# file; a wedged driver run on a fresh /tmp can then still re-serve a
# fingerprint-matched real-TPU datum — marked stale, with its original
# run_id/saved_at — instead of failing empty.  Read goes through the
# same `_cacheable`/fingerprint gates as the primary slot.  Empty
# string disables.
_REPO_CACHE_PATH = os.environ.get(
    "BENCH_REPO_CACHE_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "bench_last_good.json"))
# Touched after a successful real-accelerator trial: signals the
# persistent XLA compile cache is warm.  Per MODEL family (resnet50 /
# transformer compile different programs — a warm transformer cache says
# nothing about the flagship resnet program): a first-contact run for a
# model with no sentinel (cold cache + relay round-trips; r2 measured
# 75–109 s cold compile) gets a longer default deadline so it cannot
# stale-out on compile time alone (VERDICT r4 Weak #4).  Explicit
# BENCH_DEADLINE_S always wins.
_PREWARM_SENTINEL_BASE = os.environ.get(
    "BENCH_PREWARM_SENTINEL", "/tmp/chainermn_tpu_bench_prewarmed")


def _prewarm_sentinel(model):
    return f"{_PREWARM_SENTINEL_BASE}.{model}"


def _first_contact(model=None):
    """No successful on-chip trial of this model family has stamped the
    warm-cache sentinel yet — cold XLA cache, cold relay."""
    return not os.path.exists(_prewarm_sentinel(
        model or os.environ.get("BENCH_MODEL", "resnet50")))


# first-contact default deadline (cold compile through the relay
# measured 75-109 s in r2); doubles as the "tight deadline" threshold
# for the first-contact short-steps fallback
_FIRST_CONTACT_DEADLINE_S = 480.0

_START = time.monotonic()
_DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S") or
                    (270 if not _first_contact()
                     else _FIRST_CONTACT_DEADLINE_S))


def _effective_steps(default):
    """(steps per timing trial, short_steps flag).

    First contact with a deadline below the first-contact default is a
    tight window the full measurement has repeatedly failed to fit
    (VERDICT r5 Weak #1: three straight rounds the driver's first
    contact stale-outed): clamp to BENCH_SHORT_STEPS so a FRESH row is
    emitted — it can never be re-served as flagship data (n_steps is
    part of the payload gates) but it is real data, and its success
    stamps the prewarm sentinel so the NEXT run measures at full steps
    under the warm 270 s window.  Explicit BENCH_STEPS always wins."""
    if os.environ.get("BENCH_STEPS"):
        return int(os.environ["BENCH_STEPS"]), False
    if _first_contact() and _DEADLINE_S < _FIRST_CONTACT_DEADLINE_S:
        return _env_int("BENCH_SHORT_STEPS", 4), True
    return default, False

# Peak bf16 flops by TPU generation (per chip).  v5 lite = v5e.
_PEAK_TFLOPS = {
    "v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0,
    "v4": 275.0, "v6e": 918.0, "cpu": None,
}


class BenchDeadline(Exception):
    """Cooperative child-side deadline: raised only from Python code
    BETWEEN device operations (never from a signal handler — an
    interrupt inside an in-flight relay RPC abandons it and wedges the
    relay; see `_child_main`)."""


# Every process gets a unique run id (the supervisor overrides it for its
# child) so staleness detection compares measurement provenance, not ''.
os.environ.setdefault("BENCH_RUN_ID", f"{os.getpid()}-{int(time.time())}")

# -- compile-phase heartbeat -------------------------------------------------
#
# VERDICT r5 Weak #1: three straight rounds the driver's first-contact
# run stale-outed on COMPILE time, not measurement time.  The child now
# stamps a heartbeat file around every trace+compile; the supervisor
# reads it and EXCLUDES compile time from the measurement deadline — the
# clock pauses while a compile is in flight (bounded by
# BENCH_COMPILE_GRACE_S) and the recorded compile seconds stay credited
# afterwards.  The child's cooperative `_remaining()` gets the same
# credit, so both sides agree on the budget.

_COMPILE_STAMP = os.environ.get("BENCH_COMPILE_STAMP") or (
    "/tmp/chainermn_tpu_bench_compile." + os.environ["BENCH_RUN_ID"])
_COMPILE_GRACE_S = float(os.environ.get("BENCH_COMPILE_GRACE_S", "900"))
_COMPILE_CREDIT = [0.0]  # child-side cumulative compile seconds


_STAMP_WRITTEN = [False]


def _stamp_compile(phase, credit_s):
    """Write the compile-phase heartbeat (atomic replace; never raises).
    ``phase``: "compile" (in flight — the supervisor's clock pauses) or
    "done" (credit_s holds the cumulative compile seconds).  The first
    write registers an atexit removal, so unsupervised and DETACHED
    children clean their own stamp (the supervisor only removes its
    still-supervised child's) — /tmp must not accumulate one uniquely
    named file per bench run."""
    try:
        tmp = _COMPILE_STAMP + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"run_id": os.environ["BENCH_RUN_ID"],
                       "phase": phase, "t": time.monotonic(),
                       "credit_s": credit_s}, f)
        os.replace(tmp, _COMPILE_STAMP)
        if not _STAMP_WRITTEN[0]:
            _STAMP_WRITTEN[0] = True
            import atexit

            def _cleanup():
                try:
                    os.remove(_COMPILE_STAMP)
                except OSError:
                    pass
            atexit.register(_cleanup)
    except Exception:
        pass


def _compile_credit_from_stamp(stamp_path, run_id, now, grace_s):
    """Supervisor side: deadline extension earned by the child's compile
    phases — the recorded cumulative compile seconds, plus the elapsed
    time of an in-flight compile (CLOCK_MONOTONIC is process-shared on
    this platform), capped at ``grace_s``.  A stamp from another run_id
    earns nothing.  Never raises."""
    try:
        with open(stamp_path) as f:
            st = json.load(f)
        if st.get("run_id") != run_id:
            return 0.0
        credit = float(st.get("credit_s", 0.0))
        if st.get("phase") == "compile":
            credit += max(0.0, now - float(st.get("t", now)))
        return min(credit, grace_s)
    except Exception:
        return 0.0


def _remaining():
    credit = min(_COMPILE_CREDIT[0], _COMPILE_GRACE_S)
    return _DEADLINE_S + credit - (time.monotonic() - _START)


def _check_compile_budget():
    """Cooperative pre-compile deadline, shared by both model modes:
    never START a compile without budget for it — a mid-compile
    interrupt (signal or kill) abandons the RPC and wedges the relay."""
    if _remaining() <= 0:
        raise BenchDeadline(
            f"cooperative deadline ({_DEADLINE_S:.0f}s) exceeded "
            "before compile")


# Touched by every supervisor immediately before it spawns its child.
# A bench that observes a LATER start stamp before persisting its own
# result ran concurrently with that newer bench on the one chip (the
# detached-overrun scenario) — its measurement is contention-degraded
# and must be marked, or a detached child's slow datum would overwrite
# the last-good cache as a clean flagship number.
_START_STAMP = os.environ.get("BENCH_START_STAMP",
                              "/tmp/chainermn_tpu_bench_started")
_WALL_START = time.time()


def _newer_bench_started():
    """True when another bench invocation stamped its start AFTER this
    process began — i.e. this (detached, overrunning) run shared the
    chip with it."""
    try:
        return os.path.getmtime(_START_STAMP) > _WALL_START
    except OSError:
        return False


_EMITTED = [None]  # last result dict this process printed


_METRIC_TO_MODEL = {
    "resnet50_imagenet_train_throughput": "resnet50",
    "transformer_lm_train_throughput": "transformer",
}

# The flagship configurations.  A run may be persisted to (or re-served
# from) the last-good cache ONLY when its REQUESTED config — read from
# the same env knobs the bench itself reads — equals one of these.  The
# recovery queue's variant runs (BENCH_BS=256, BENCH_LAYOUT=NCHW,
# BENCH_SCAN=8, BENCH_SEQ=8192 ...) are measurements, not flagship
# data: they must never be re-served under the default-config metric.
_DEFAULT_FINGERPRINTS = {
    "resnet50": {"model": "resnet50", "bs": DEFAULT_BS,
                 "image_size": DEFAULT_SIZE, "layout": "NHWC",
                 "scan": 0, "remat": False, "n_steps": DEFAULT_STEPS,
                 "input_pipeline": False, "donate": True,
                 "exchange": "flat", "bucket_mb": 0, "inter_size": 0,
                 "stripe_ratio": 0,
                 "grad_dtype": "bfloat16", "error_feedback": True,
                 "preempt_rank": -1, "trace": "off",
                 "serve_replicas": 1, "fleet_kill_at": -1,
                 "diurnal": False, "diurnal_period": 0.0,
                 "autotune": False,
                 "serve_spec_k": 0, "serve_chunk": 0},
    "transformer": {"model": "transformer", "bs": DEFAULT_TF_BS,
                    "seq_len": DEFAULT_SEQ, "d_model": DEFAULT_TF_D_MODEL,
                    "n_layers": DEFAULT_TF_LAYERS,
                    "n_vocab": DEFAULT_TF_VOCAB, "heads": 0,
                    "remat": False, "remat_policy": "",
                    "n_steps": DEFAULT_TF_STEPS,
                    "flash_blocks": ":", "donate": True,
                    "exchange": "flat", "bucket_mb": 0, "inter_size": 0,
                    "stripe_ratio": 0,
                    "grad_dtype": "bfloat16", "error_feedback": True,
                    "preempt_rank": -1, "trace": "off",
                    "serve_replicas": 1, "fleet_kill_at": -1,
                    "diurnal": False, "diurnal_period": 0.0,
                    "autotune": False,
                    "serve_spec_k": 0, "serve_chunk": 0},
}

def _env_float(name, default):
    """float env knob with the same never-raises contract as
    ``_env_int`` (used inside the fingerprint)."""
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name, default):
    """int env knob that NEVER raises: `_config_fingerprint` runs inside
    `_emit_stale_or_error` (documented 'never raises') — a typo'd knob
    (BENCH_SCAN=8x) must not turn the always-emit fallback into a
    traceback.  The measurement itself still crashes loudly on the bad
    value (it parses the env with plain int()); only the fingerprint
    falls back to the default."""
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _config_fingerprint(model=None):
    """The current process's REQUESTED benchmark configuration, from the
    same env knobs `_run_bench`/`_run_bench_transformer` read.
    BENCH_STALE_FP (set for the CPU-fallback re-exec) overrides: the
    fallback child changes BENCH_BS for its own cpu measurement, but its
    stale re-serve decisions must be made with the ORIGINAL requested
    config, or a default-config flagship run would refuse its own cached
    datum."""
    override = os.environ.get("BENCH_STALE_FP")
    if override:
        try:
            fp = json.loads(override)
            if model is None or fp.get("model") == model:
                return fp
        except Exception:
            pass
    model = model or os.environ.get("BENCH_MODEL", "resnet50")
    if model == "transformer":
        return {
            "model": "transformer",
            "bs": _env_int("BENCH_BS", DEFAULT_TF_BS),
            "seq_len": _env_int("BENCH_SEQ", DEFAULT_SEQ),
            "d_model": _env_int("BENCH_D_MODEL", DEFAULT_TF_D_MODEL),
            "n_layers": _env_int("BENCH_LAYERS", DEFAULT_TF_LAYERS),
            "n_vocab": _env_int("BENCH_VOCAB", DEFAULT_TF_VOCAB),
            "heads": _env_int("BENCH_HEADS", 0),
            "remat": os.environ.get("BENCH_REMAT", "0") == "1",
            "remat_policy": os.environ.get("BENCH_REMAT_POLICY", ""),
            "n_steps": _env_int("BENCH_STEPS", DEFAULT_TF_STEPS),
            # the Pallas attention tile knobs change the compiled
            # program: a block-sweep run must not be cacheable as the
            # flagship datum ("" = kernel default)
            "flash_blocks":
                os.environ.get("CHAINERMN_TPU_FLASH_BLOCK_Q", "")
                + ":"
                + os.environ.get("CHAINERMN_TPU_FLASH_BLOCK_K", ""),
            # BENCH_DONATE=0 is the buffer-donation A/B leg: different
            # compiled program + different HBM headroom, never flagship
            "donate": os.environ.get("BENCH_DONATE", "1") == "1",
            # exchange variants (bucketed sweep, reduce-scatter A/B)
            # compile different collective structures — measurements,
            # not flagship data
            "exchange": os.environ.get("BENCH_EXCHANGE", "flat"),
            "bucket_mb": _env_float("BENCH_BUCKET_MB", 0),
            "inter_size": _env_int("BENCH_INTER_SIZE", 0),
            # the striped ratio sweep (ISSUE 11) measures a different
            # collective structure per ratio — never flagship data
            "stripe_ratio": _env_float("BENCH_STRIPE_RATIO", 0),
            # the wire-dtype A/B (int8/fp8/lossless DCN) and the
            # error-feedback ablation compile different exchanges —
            # measurements, never flagship data
            "grad_dtype": os.environ.get("BENCH_GRAD_DTYPE", "bfloat16"),
            "error_feedback":
                os.environ.get("BENCH_ERROR_FEEDBACK", "1") == "1",
            # the elastic A/B (preempt-and-rejoin, ISSUE 10) measures a
            # resizing world — never flagship data (-1 = no preemption)
            "preempt_rank": _env_int("BENCH_PREEMPT_RANK", -1),
            # span tracing (ISSUE 14): a traced run pays the recording
            # overhead — its numbers stamp the overhead DELTA (recovery
            # queue), never the flagship datum
            "trace": os.environ.get("CHAINERMN_TPU_TRACE", "off"),
            # the serving-fleet knobs (ISSUE 15): a multi-replica or
            # kill-under-load run is a fleet measurement — fenced from
            # the flagship fingerprints like every A/B knob (serving
            # rows are metric-fenced anyway; this closes the env half)
            "serve_replicas": _env_int("BENCH_SERVE_REPLICAS", 1),
            "fleet_kill_at": _env_int("BENCH_FLEET_KILL_AT", -1),
            # the diurnal capacity-transfer scenario (ISSUE 16): a
            # sinusoidal-QPS run with the broker moving ranks between
            # training and serving measures a TWO-ROLE world — a
            # measurement, never flagship data
            "diurnal": os.environ.get("BENCH_DIURNAL", "0") == "1",
            "diurnal_period": _env_float("BENCH_DIURNAL_PERIOD", 0),
            # the self-tuning A/B (ISSUE 19): an autotuned exchange
            # executes whatever plan the micro-bench derived — a
            # measurement of that plan, never flagship data
            "autotune": os.environ.get("BENCH_AUTOTUNE", "0") == "1",
            # the round-20 serving A/Bs (ISSUE 20): speculative decode
            # (BENCH_SERVE_SPEC_K) and chunked prefill
            # (BENCH_SERVE_CHUNK) reshape the dispatch schedule — A/B
            # measurements, never flagship data
            "serve_spec_k": _env_int("BENCH_SERVE_SPEC_K", 0),
            "serve_chunk": _env_int("BENCH_SERVE_CHUNK", 0),
        }
    return {
        "model": "resnet50",
        "bs": _env_int("BENCH_BS", DEFAULT_BS),
        "image_size": _env_int("BENCH_SIZE", DEFAULT_SIZE),
        "layout": os.environ.get("BENCH_LAYOUT", "NHWC"),
        "scan": _env_int("BENCH_SCAN", 0),
        "remat": os.environ.get("BENCH_REMAT", "0") == "1",
        "n_steps": _env_int("BENCH_STEPS", DEFAULT_STEPS),
        "input_pipeline":
            os.environ.get("BENCH_INPUT_PIPELINE", "0") == "1",
        "donate": os.environ.get("BENCH_DONATE", "1") == "1",
        "exchange": os.environ.get("BENCH_EXCHANGE", "flat"),
        "bucket_mb": _env_float("BENCH_BUCKET_MB", 0),
        "inter_size": _env_int("BENCH_INTER_SIZE", 0),
        "stripe_ratio": _env_float("BENCH_STRIPE_RATIO", 0),
        "grad_dtype": os.environ.get("BENCH_GRAD_DTYPE", "bfloat16"),
        "error_feedback":
            os.environ.get("BENCH_ERROR_FEEDBACK", "1") == "1",
        "preempt_rank": _env_int("BENCH_PREEMPT_RANK", -1),
        "trace": os.environ.get("CHAINERMN_TPU_TRACE", "off"),
        "serve_replicas": _env_int("BENCH_SERVE_REPLICAS", 1),
        "fleet_kill_at": _env_int("BENCH_FLEET_KILL_AT", -1),
        "diurnal": os.environ.get("BENCH_DIURNAL", "0") == "1",
        "diurnal_period": _env_float("BENCH_DIURNAL_PERIOD", 0),
        "autotune": os.environ.get("BENCH_AUTOTUNE", "0") == "1",
        "serve_spec_k": _env_int("BENCH_SERVE_SPEC_K", 0),
        "serve_chunk": _env_int("BENCH_SERVE_CHUNK", 0),
    }


def _cacheable(result):
    """Gate for the last-good-result cache: ONLY a fresh real-accelerator
    run whose REQUESTED config (env fingerprint) is the flagship default
    may be persisted or re-served stale.  Two layers: (a) the env
    fingerprint of the current process must equal the flagship default
    for the result's metric — this covers every BENCH_* knob, including
    ones the payload doesn't carry; (b) payload sanity checks on the
    result itself, which also defend against planted/legacy cache files
    that predate fingerprint storage.  Round-3 postmortem: a 32×32/bs-2
    CPU smoke persisted by a harness test was re-emitted under the
    headline TPU metric when the relay wedged."""
    metric = result.get("metric")
    model = _METRIC_TO_MODEL.get(metric)
    if model is None:
        return False
    if _config_fingerprint(model) != _DEFAULT_FINGERPRINTS[model]:
        return False  # this process requested a non-flagship config
    # value/stale/error/platform sanity lives in the shared payload
    # helper (one copy — it doubles as the cross-slot write screen)
    return _payload_flagship_ok(model, result)


def _payload_flagship_ok(model, result):
    """The payload half of `_cacheable`'s gates — result-field sanity
    checks that need no environment, shared with the cross-slot write
    screen (`_entry_shape_ok`) so a fingerprint-less planted entry
    cannot bypass them."""
    if result.get("value") is None or result.get("stale") \
            or result.get("error") or result.get("contended") \
            or result.get("platform") in (None, "cpu", "cpu_fallback"):
        return False
    if not result.get("donated", True):
        # the BENCH_DONATE=0 A/B leg is a measurement, not flagship data
        return False
    if result.get("resizes"):
        # a mid-run communicator resize (elastic shrink/grow, ISSUE 10)
        # changes the measured world mid-row — never flagship data
        # (legacy rows lack the key and were fixed-size by construction)
        return False
    if result.get("conversions") or result.get("role_transfers"):
        # a capacity transfer (ISSUE 16): ranks changed ROLE mid-row —
        # the measured world was part training, part serving; never
        # flagship data (legacy rows lack the keys: no broker existed)
        return False
    if result.get("exchange", "flat") != "flat":
        # bucketed/reduce_scatter/per_leaf legs compile a different
        # collective structure — measurements, not flagship data
        # (legacy entries lack the key and were flat by construction)
        return False
    if model == "resnet50":
        # batch bounds: OOM backoff halves the requested batch at most
        # twice (lower bound); anything ABOVE the default batch is a
        # different measurement regime (bs-256 throughput overstates the
        # bs-64 flagship by ~45% — round-2 notes), only reachable via a
        # planted/legacy cache file
        return (result.get("image_size") == DEFAULT_SIZE
                and result.get("layout", "NHWC") == "NHWC"
                and result.get("fused_steps_per_dispatch", 1) == 1
                and not result.get("remat", False)
                # payload-level n_steps check: a short-step prewarm datum
                # (queue step 1, BENCH_STEPS=4) measures amortization, not
                # throughput — tolerate only legacy entries lacking the key
                and result.get("n_steps", DEFAULT_STEPS) == DEFAULT_STEPS
                and not result.get("input_pipeline", False)
                and DEFAULT_BS // 4 <= result.get("per_chip_batch", 0)
                <= DEFAULT_BS)
    return (result.get("seq_len", 0) == DEFAULT_SEQ
            and result.get("d_model", DEFAULT_TF_D_MODEL)
            == DEFAULT_TF_D_MODEL
            and result.get("n_layers", DEFAULT_TF_LAYERS)
            == DEFAULT_TF_LAYERS
            and result.get("n_vocab", DEFAULT_TF_VOCAB)
            == DEFAULT_TF_VOCAB
            and not result.get("remat", False)
            and result.get("remat_policy", "") == ""
            and result.get("n_steps", DEFAULT_TF_STEPS) == DEFAULT_TF_STEPS
            and DEFAULT_TF_BS // 4 <= result.get("per_chip_batch", 0)
            <= DEFAULT_TF_BS)


def _emit(result, persist=True):
    """Print a result line AND (for fresh default-config accelerator
    measurements — see ``_cacheable``) persist it so a later wedged run
    can re-emit it marked stale.  The last printed line is authoritative.
    ``persist=False`` keeps stale/error re-emissions from polluting the
    last-good-result cache."""
    result = dict(result)
    if result.get("value") is not None and not result.get("stale") \
            and not result.get("error") and (
            os.environ.get("BENCH_CONTENDED") == "1"
            or _newer_bench_started()):
        # FRESH measurements only: a re-served historical datum (stale
        # or error-annotated) was measured cleanly in its own run and
        # must not inherit this run's contention
        # Either a detached child from an earlier run was still draining
        # on the chip when this run started (BENCH_CONTENDED, set by the
        # supervisor), or a NEWER bench started while this run was still
        # measuring (this run is the detached overrunner).  Both mean
        # the device was time-shared: the result must say so, and the
        # payload gates refuse it for the last-good cache.
        result["contended"] = True
    line = json.dumps(result)  # serialization errors stay LOUD
    try:
        print(line, flush=True)
    except Exception:
        # stdout is gone when the supervisor detached this process at
        # its deadline; finishing the persistence below is the whole
        # point of letting the run complete
        pass
    _EMITTED[0] = result
    if result.get("value") is not None and not result.get("stale") \
            and not result.get("error") \
            and result.get("platform") not in (None, "cpu", "cpu_fallback") \
            and result.get("metric") in _METRIC_TO_MODEL:
        # any successful on-chip trial of this MODEL family (flagship or
        # variant, including the recovery queue's prewarm) marks its XLA
        # cache warm: later default-deadline runs of the same model drop
        # back to the tight 270 s window
        try:
            with open(_prewarm_sentinel(
                    _METRIC_TO_MODEL[result["metric"]]), "w") as f:
                f.write(f"{os.environ['BENCH_RUN_ID']} {time.time()}\n")
        except Exception:
            pass
    if not persist or not _cacheable(result):
        return
    try:
        # merge both slots (newest saved_at wins per metric: a stale
        # local /tmp entry must not overwrite a newer repo-committed
        # one, nor vice versa) so a restart-wiped /tmp does not drop
        # the OTHER metric's entry on the next write; screen every
        # carried entry so transient /tmp poison (the round-3 plant
        # vector) cannot be promoted into the committed repo file
        # where it would outlive restarts
        # screen FIRST: a poison entry must be dropped before the
        # newest-wins arbitration, or its (arbitrary) saved_at could
        # displace a valid older entry from the other slot.  Repo
        # entries this version cannot judge (a newer branch's metric or
        # fingerprint schema) are preserved verbatim — the screens
        # protect the slots we understand, they must not DELETE durable
        # committed data we don't.  /tmp entries we cannot judge are
        # NEVER promoted into the committed slot: transient state earns
        # durability only by passing the screens.
        entries = {m: e for m, e
                   in _read_cache_entries(_REPO_CACHE_PATH).items()
                   if not _judgeable(m, e) or _entry_shape_ok(m, e)}
        for m, e in _read_cache_entries(_CACHE_PATH).items():
            if not _judgeable(m, e) or not _entry_shape_ok(m, e):
                continue
            if m not in entries or _saved_at(e) >= _saved_at(entries[m]):
                entries[m] = e
        # one slot per metric: a transformer run must not destroy the
        # last-good resnet datum (the recovery queue interleaves both)
        entries[result["metric"]] = {
            "run_id": os.environ["BENCH_RUN_ID"], "saved_at": time.time(),
            "fingerprint": _config_fingerprint(
                _METRIC_TO_MODEL[result["metric"]]),
            "result": result}
        # atomic replace: the multi-entry file must not be left truncated
        # by a supervisor SIGKILL mid-write (that would destroy BOTH
        # metrics' last-good data)
        for path in (_CACHE_PATH, _REPO_CACHE_PATH):
            if not path:
                continue
            try:
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"entries": entries}, f)
                os.replace(tmp, path)
            except Exception:
                pass  # a read-only repo must not break the /tmp slot
    except Exception:
        pass


def _saved_at(entry):
    """Numeric saved_at for merge arbitration; malformed → 0."""
    ts = entry.get("saved_at", 0)
    return ts if isinstance(ts, (int, float)) else 0


def _backfill_fp(model, fp):
    """Stored fingerprint completed with the flagship defaults for keys
    a pre-schema-bump writer didn't know.  ONE copy — used by both the
    write screen and the read gate, so they cannot desync."""
    default = _DEFAULT_FINGERPRINTS[model]
    return {**{k: v for k, v in default.items() if k not in fp}, **fp}


def _judgeable(metric, entry):
    """Can THIS version meaningfully validate the entry?  False for a
    metric we don't know, or a fingerprint carrying keys a NEWER
    branch's schema added (backfill only works forward).  Screening
    what we can't judge would delete durable committed data, so the
    repo-slot merge preserves such entries verbatim — while `_load_cache`
    still refuses to SERVE them (its gates require a judgeable match)
    and the /tmp→repo promotion path drops them entirely."""
    if metric not in _METRIC_TO_MODEL:
        return False
    if not isinstance(entry, dict):
        return True  # malformed shapes ARE judgeable (and rejected)
    fp = entry.get("fingerprint")
    if isinstance(fp, dict) and set(fp) - set(
            _DEFAULT_FINGERPRINTS[_METRIC_TO_MODEL[metric]]):
        return False
    return True


def _read_cache_entries(path):
    """entries dict from one cache file, {} on any problem; tolerates the
    legacy single-slot format."""
    try:
        with open(path) as f:
            data = json.load(f)
        entries = data.get("entries", {})
        if not entries and isinstance(data.get("result"), dict):
            legacy_metric = data["result"].get("metric")  # single-slot
            if legacy_metric:
                entries = {legacy_metric: data}
        return entries if isinstance(entries, dict) else {}
    except Exception:
        return {}


def _entry_shape_ok(metric, entry):
    """Defensive screen for a cache entry carried across slots or read
    back for re-serve: a hand-edited/truncated/planted file must never
    crash the harness (the stale path is documented 'never raises') nor
    have a non-flagship payload promoted into the committed repo slot.
    Checks shape plus the STORED fingerprint against the flagship
    default (env-fingerprint and payload gates are the reader's job)."""
    if not isinstance(entry, dict):
        return False
    result = entry.get("result")
    if not isinstance(result, dict) or result.get("metric") != metric:
        return False
    model = _METRIC_TO_MODEL.get(metric)
    if model is None:
        return False
    fp = entry.get("fingerprint")
    if fp is not None:
        if not isinstance(fp, dict):
            return False
        if _backfill_fp(model, fp) != _DEFAULT_FINGERPRINTS[model]:
            return False
    # payload gates apply to fingerprint-less (legacy/planted) entries
    # too: without this, a non-flagship /tmp payload passes the screen
    # and gets promoted into the committed repo slot
    return _payload_flagship_ok(model, result)


def _load_cache(metric):
    """Return (run_id, result, fingerprint) for the metric's cache slot.
    fingerprint is None for entries written by the legacy single-slot
    format (pre-fingerprint); such entries rely on `_cacheable`'s
    payload checks alone.  A stored fingerprint that predates a newly
    ADDED fingerprint key (e.g. n_steps) is backfilled with that key's
    default — mirroring the payload checks' legacy tolerance, so a
    fingerprint-schema bump cannot orphan a valid flagship datum
    mid-outage.  Falls back to the repo-committed slot when /tmp has no
    SERVABLE entry for the metric: an entry the downstream gates would
    refuse (malformed shape, wrong fingerprint, non-flagship payload)
    must not mask a valid datum one slot further down — serving nothing
    because /tmp held poison is the exact outcome the repo slot was
    added to prevent.  Never raises (the stale path's contract)."""
    best = None  # (entry, backfilled_fp) — newest saved_at wins, the
    # same arbitration `_emit` applies on write: a valid-but-older /tmp
    # entry must not shadow a newer committed repo datum
    for path in (_CACHE_PATH, _REPO_CACHE_PATH):
        if not path:
            continue
        try:
            entry = _read_cache_entries(path).get(metric)
            if not _entry_shape_ok(metric, entry):
                continue
            model = _METRIC_TO_MODEL[metric]  # non-None per shape check
            fp = entry.get("fingerprint")
            if fp is not None:
                # backfill from the METRIC's model (matching the shape
                # check), not fp's own "model" key: a schema-bump entry
                # lacking that key must still resolve to its defaults
                fp = _backfill_fp(model, fp)
                if fp != _config_fingerprint(model):
                    continue  # current process requests another config
            if not _cacheable(entry["result"]):
                continue
            if best is None or _saved_at(entry) > _saved_at(best[0]):
                best = (entry, fp)
        except Exception:
            continue
    if best is not None:
        entry, fp = best
        return entry.get("run_id"), entry["result"], fp
    return None, None, None


def _resnet50_train_flops_per_image(image_size):
    """Analytic flops model: fwd ~4.1 GFLOP at 224² (scales with area),
    train = fwd + bwd ≈ 3× fwd."""
    fwd = 4.1e9 * (image_size / 224.0) ** 2
    return 3.0 * fwd


def _peak_tflops(devices):
    override = os.environ.get("BENCH_PEAK_TFLOPS")
    if override:
        return float(override)
    kind = getattr(devices[0], "device_kind", "") or ""
    kl = kind.lower()
    for name, peak in _PEAK_TFLOPS.items():
        if name in kl and peak:
            return peak
    return None


def _transformer_flops_per_token(d_model, n_layers, n_vocab, seq_len):
    """Analytic train-step flops per token for the causal LM: matmul
    fwd = 2·(12·L·d² + d·V), attention fwd = 4·T·d·L (scores + values,
    causal halving ignored ≈ upper bound), train ≈ 3× fwd."""
    matmul = 2.0 * (12.0 * n_layers * d_model ** 2 + d_model * n_vocab)
    attn = 4.0 * seq_len * d_model * n_layers
    return 3.0 * (matmul + attn)


def _exchange_config():
    """(exchange, bucket_mb_or_None) from the env, validated against
    the ONE exchange vocabulary (communicators.EXCHANGES; flat is the
    historical flagship — other flavors are measured variants, never
    flagship-cacheable).  Lazy import: this runs inside the measured
    child, after platform config."""
    from chainermn_tpu.communicators import EXCHANGES
    exchange = os.environ.get("BENCH_EXCHANGE", "flat")
    if exchange not in EXCHANGES:
        raise ValueError(
            f"unknown BENCH_EXCHANGE={exchange!r} ({'|'.join(EXCHANGES)})")
    bucket_mb = os.environ.get("BENCH_BUCKET_MB")
    return exchange, (float(bucket_mb) if bucket_mb else None)


def _make_bench_communicator(exchange, bucket_mb):
    """Communicator for the requested gradient exchange, from the same
    env knobs every bench mode reads (BENCH_GRAD_DTYPE /
    BENCH_INTER_SIZE / BENCH_STRIPE_RATIO / BENCH_ERROR_FEEDBACK).
    Split out of `_make_dp_optimizer` because the MoE vertical needs
    the communicator BEFORE the model exists (the expert bank shards
    over it).  Returns ``(comm, opt_exchange)``."""
    import chainermn_tpu as ct
    comm_name, bc, opt_exchange = ct.communicators.exchange_knobs(exchange)
    autotune = os.environ.get("BENCH_AUTOTUNE", "0") == "1"
    inter_size = _env_int("BENCH_INTER_SIZE", 0) or None
    grad_dtype = os.environ.get("BENCH_GRAD_DTYPE", "bfloat16")
    grad_dtype = None if grad_dtype.lower() in ("none", "") else grad_dtype
    if autotune and "BENCH_GRAD_DTYPE" not in os.environ:
        # the autotune leg (ISSUE 19, queue item 11) leaves every knob
        # the operator did not explicitly set free for the agreed plan
        # to fill — applying the flagship bf16 default here would read
        # as a hand knob and pin the dtype ladder shut
        grad_dtype = None
    # the striped legs (ISSUE 11) need a NONZERO ratio or they would
    # silently measure the strict hierarchical schedule under the
    # striped name: BENCH_STRIPE_RATIO, else the committed default —
    # except under autotune, where an unset ratio stays FREE for the
    # derived plan (that is the measurement)
    stripe_ratio = None
    if exchange in ("striped", "striped_rs"):
        from chainermn_tpu.communicators._memory_utility import \
            DEFAULT_STRIPE_RATIO
        stripe_ratio = _env_float("BENCH_STRIPE_RATIO", 0) or None
        if stripe_ratio is None and not autotune:
            stripe_ratio = DEFAULT_STRIPE_RATIO
    comm = ct.create_communicator(comm_name,
                                  allreduce_grad_dtype=grad_dtype,
                                  batch_collectives=bc,
                                  bucket_mb=bucket_mb,
                                  inter_size=inter_size
                                  if comm_name == "hierarchical" else None,
                                  stripe_ratio=stripe_ratio,
                                  error_feedback=os.environ.get(
                                      "BENCH_ERROR_FEEDBACK", "1") == "1",
                                  autotune=True if autotune else None)
    return comm, opt_exchange


def _make_dp_optimizer(inner, model, exchange, bucket_mb, comm=None,
                       opt_exchange=None):
    """Communicator + multi-node wrapper for the requested gradient
    exchange (flagship bf16 gradient compression on every flavor;
    BENCH_GRAD_DTYPE overrides — ``none`` for lossless, ``int8`` /
    ``float8_e4m3`` / ``float8_e5m2`` for the quantized-wire A/B, where
    a scalar quantized dtype compresses the DCN hop only, per the
    communicator's own rule; BENCH_ERROR_FEEDBACK=0 is the ablation
    leg).  The hierarchical legs honor BENCH_INTER_SIZE (force a
    dcn × ici split of the local chips — the on-host structural A/B the
    queue runs as 2×4; default: infer from the controller topology,
    i.e. a real multi-host run gets one dcn group per host).  Pass a
    prebuilt ``comm`` (+ its ``opt_exchange``) when the model already
    holds it — the MoE vertical's expert-parallel axis IS the
    data-parallel communicator."""
    import chainermn_tpu as ct
    if comm is None:
        comm, opt_exchange = _make_bench_communicator(exchange, bucket_mb)
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(inner, comm,
                                         exchange=opt_exchange)
    return comm, opt.setup(model)


def _exchange_row_fields(model, comm, exchange):
    """Row fields documenting the exchange: structure knobs, the
    TOPOLOGY columns (ici/dcn split — 1×N on flat communicators), and
    the per-replica wire-byte accounting (ring decomposition — the
    same formulas tools/comm_budgets.json commits; 0 on a single chip;
    hierarchical legs additionally split the bill by hop).

    Every crossing is priced at its WIRE dtype — the itemsize of the
    packed buffer that actually crosses (ISSUE 8 satellite: the old
    gradient-dtype accounting happened to be right for bf16 casts and
    wrong for everything else).  Quantized wires change the collective
    SHAPE too (all_gather of codewords / all_to_all of segments), so
    they route through ``quantized_hop_bytes``, never the psum ring
    formula."""
    from chainermn_tpu.communicators._memory_utility import (
        exchanged_bytes, hierarchical_exchanged_bytes, is_quantized_dtype,
        quantized_hop_bytes)
    arrays = [p.array for p in model.params() if p.array is not None]
    n_params = sum(int(np.prod(a.shape)) for a in arrays)
    param_bytes = sum(int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
                      for a in arrays)
    gdtype = comm.allreduce_grad_dtype
    q_wire = comm.quantized_wire_dtype
    grad_bytes = (n_params * gdtype.itemsize if gdtype is not None
                  else param_bytes)  # uncompressed grads ride param dtype
    size = comm.size
    fields = {"exchange": exchange,
              "bucket_mb": comm.bucket_mb if exchange == "bucketed"
              else None,
              "topology": comm.topology,
              "ici_size": comm.ici_size,
              "dcn_size": comm.dcn_size,
              # elastic columns (ISSUE 10): the controller world the row
              # was measured at, and how many membership epochs the
              # COMMUNICATOR has been through at construction (bench.py
              # itself never resizes mid-measurement — the elastic
              # measurement is bench_scaling --preempt-rank, whose rows
              # carry recovery-stats resize counts; here >0 means the
              # row was measured on a resize-scarred world, and
              # `_payload_flagship_ok` fences any resizes>0 row out of
              # the flagship last-good cache)
              "world_size": getattr(comm, "inter_size", 1),
              "resizes": int(getattr(comm, "epoch", 0)),
              "grad_dtype": str(gdtype) if gdtype is not None else None,
              "dcn_wire_dtype": str(comm.dcn_grad_dtype)
              if comm.dcn_grad_dtype is not None else None,
              "error_feedback": comm.error_feedback
              if q_wire is not None else None}
    if comm.striped:
        # striped multi-path split (ISSUE 11): each path priced as its
        # own two-level exchange — the ICI path fast-hop-major, the
        # DCN path transposed — with the hop labels mapped back to
        # FABRICS, padding element counts exactly like the wire does
        # (each slice to its own ring multiple).  Rows carry the ratio
        # plus the same per-fabric byte columns the hierarchical legs
        # carry, so the A/B deltas line up column-for-column.
        from chainermn_tpu.communicators._memory_utility import \
            stripe_plan
        fields["stripe_ratio"] = comm.stripe_ratio
        intra, inter = comm.ici_size, comm.dcn_size
        wire_itemsize = gdtype.itemsize if gdtype is not None else 4
        dcn_itemsize = (comm.dcn_grad_dtype.itemsize
                        if comm.dcn_grad_dtype is not None
                        else wire_itemsize)
        n_i, n_d = stripe_plan(n_params, comm.stripe_ratio)
        if exchange == "striped_rs":
            size = comm.size
            n_pa = -(-n_i // size) * size
            n_pb = -(-n_d // size) * size
            ga = hierarchical_exchanged_bytes(
                n_pa * wire_itemsize, intra, inter, "reduce_scatter",
                dcn_n_bytes=n_pa // intra * dcn_itemsize)
            gb = hierarchical_exchanged_bytes(
                n_pb * dcn_itemsize, inter, intra, "reduce_scatter",
                dcn_n_bytes=n_pb // inter * 4)
            hops = {"ici": ga["ici"] + gb["dcn"],
                    "dcn": ga["dcn"] + gb["ici"]}
            pa = hierarchical_exchanged_bytes(n_pa * 4, intra, inter,
                                              "all_gather")
            pb = hierarchical_exchanged_bytes(n_pb * 4, inter, intra,
                                              "all_gather")
            p_hops = {"ici": pa["ici"] + pb["dcn"],
                      "dcn": pa["dcn"] + pb["ici"]}
        elif q_wire is not None:
            # quantized DCN crossings on BOTH paths: the ICI path's
            # chunk rides the gather-of-codewords hop, the DCN path
            # quantizes its whole pre-reduction slice (gather over dcn
            # + lossless full-slice psum over ici)
            n_pa = -(-n_i // intra) * intra
            hops = {
                "ici": exchanged_bytes(n_pa * wire_itemsize, intra,
                                       "psum")
                + exchanged_bytes(n_d * 4, intra, "psum"),
                "dcn": quantized_hop_bytes(n_pa // intra, inter,
                                           "psum", q_wire)
                + quantized_hop_bytes(n_d, inter, "psum", q_wire)}
            p_hops = None
        else:
            # the ONE per-path pricing surface (also what the census
            # identities are pinned against) — it pads each slice to
            # its ring multiple exactly like the wire does
            from chainermn_tpu.communicators._memory_utility import \
                striped_exchanged_bytes
            paths = striped_exchanged_bytes(
                n_params * wire_itemsize, intra, inter,
                comm.stripe_ratio, itemsize=wire_itemsize,
                dcn_itemsize=dcn_itemsize
                if comm.dcn_grad_dtype is not None else None)
            hops = {"ici": paths["ici_path"]["ici"]
                    + paths["dcn_path"]["ici"],
                    "dcn": paths["ici_path"]["dcn"]
                    + paths["dcn_path"]["dcn"]}
            p_hops = None
        fields["exchanged_grad_bytes"] = hops["ici"] + hops["dcn"]
        fields["exchanged_dcn_bytes"] = hops["dcn"]
        fields["exchanged_ici_bytes"] = hops["ici"]
        fields["exchanged_bytes"] = fields["exchanged_grad_bytes"]
        if exchange == "striped_rs":
            fields["exchanged_bytes"] += p_hops["ici"] + p_hops["dcn"]
            fields["exchanged_dcn_bytes"] += p_hops["dcn"]
            fields["exchanged_ici_bytes"] += p_hops["ici"]
        return fields
    if comm.hierarchy is not None:
        # per-hop split.  The accounting pads ELEMENTS exactly like the
        # wire does (pad_to_multiple on the packed vector: to intra for
        # the per-bucket exchange, to the full size for the sharded
        # update), then prices each hop in its own wire dtype — the dcn
        # dtype may differ from the ici wire dtype.
        intra, inter = comm.ici_size, comm.dcn_size
        coll = ("reduce_scatter"
                if exchange in ("reduce_scatter", "hierarchical_rs")
                else "psum")
        multiple = intra * inter if coll == "reduce_scatter" else intra
        n_pad = -(-n_params // multiple) * multiple
        wire_itemsize = gdtype.itemsize if gdtype is not None else 4
        if q_wire is not None:
            # quantized DCN: the slow hop is a different collective
            # shape with its own pricing; ICI keeps the lossless ring
            hops = hierarchical_exchanged_bytes(
                n_pad * wire_itemsize, intra, inter, coll)
            hops["dcn"] = quantized_hop_bytes(
                n_pad // intra, inter, coll, q_wire)
        else:
            dcn_itemsize = (comm.dcn_grad_dtype.itemsize
                            if comm.dcn_grad_dtype is not None
                            else wire_itemsize)
            hops = hierarchical_exchanged_bytes(
                n_pad * wire_itemsize, intra, inter, coll,
                dcn_n_bytes=n_pad // intra * dcn_itemsize)
        fields["exchanged_grad_bytes"] = hops["ici"] + hops["dcn"]
        fields["exchanged_dcn_bytes"] = hops["dcn"]
        fields["exchanged_ici_bytes"] = hops["ici"]
        fields["exchanged_bytes"] = fields["exchanged_grad_bytes"]
        if coll == "reduce_scatter":
            # params rebuild: the sharded update all-gathers the PACKED
            # flat params vector (tree_pack's concatenate promotes to
            # one dtype — f32 on the bench models)
            p_hops = hierarchical_exchanged_bytes(n_pad * 4, intra,
                                                  inter, "all_gather")
            fields["exchanged_bytes"] += p_hops["ici"] + p_hops["dcn"]
            fields["exchanged_dcn_bytes"] += p_hops["dcn"]
            fields["exchanged_ici_bytes"] += p_hops["ici"]
        return fields
    if is_quantized_dtype(gdtype):
        # flat quantized exchange: all_gather of codewords (allreduce)
        # or all_to_all of segments (reduce-scatter update), priced at
        # the 1-byte wire
        coll = "reduce_scatter" if exchange == "reduce_scatter" else "psum"
        grad = quantized_hop_bytes(n_params, size, coll, gdtype)
        fields["exchanged_grad_bytes"] = grad
        fields["exchanged_bytes"] = grad + (
            exchanged_bytes(param_bytes, size, "all_gather")
            if exchange == "reduce_scatter" else 0)
    elif exchange == "reduce_scatter":
        grad = exchanged_bytes(grad_bytes, size, "reduce_scatter")
        fields["exchanged_bytes"] = grad + exchanged_bytes(
            param_bytes, size, "all_gather")
        fields["exchanged_grad_bytes"] = grad
    else:
        fields["exchanged_bytes"] = exchanged_bytes(grad_bytes, size,
                                                    "psum")
        fields["exchanged_grad_bytes"] = fields["exchanged_bytes"]
    return fields


def _scan_mode_requested():
    """Will this run compile a scan-over-steps program?  Mirrors the
    BENCH_SCAN / BENCH_INPUT_PIPELINE default logic in `_run_bench`."""
    scan_env = os.environ.get("BENCH_SCAN", "")
    if scan_env:
        return _env_int("BENCH_SCAN", 0) > 0
    return os.environ.get("BENCH_INPUT_PIPELINE", "0") == "1"


def _enable_compile_cache(jax):
    # On this box the JAX_PLATFORMS env var is NOT honored (the axon
    # sitecustomize registers its PJRT plugin at interpreter startup and
    # the plugin initializes regardless); jax.config.update before first
    # backend use is the reliable lever.  Without this, JAX_PLATFORMS=cpu
    # still dials the TPU relay — and blocks forever when it's wedged.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    # Persistent compile cache: repeat runs skip the ~30s XLA compile.
    # Gated through the shared guard — the CPU backend CRASHES replaying
    # persisted scan-over-steps programs (BENCH_NOTES r5 tail) AND
    # params-donated step programs (round 6; donation is the default),
    # so such cpu runs forgo persistence entirely and scan programs
    # elsewhere get a `.scan`-keyed sibling cache dir.
    from chainermn_tpu.utils.compat import configure_persistent_cache
    configure_persistent_cache(
        jax, cache_dir=os.environ.get("BENCH_XLA_CACHE_DIR"),
        platform=plat, scan_program=_scan_mode_requested(),
        donated_program=os.environ.get("BENCH_DONATE", "1") == "1")


def _timed_steps(do_steps, calls, trials=None, on_first=None):
    """Shared timing discipline for every bench mode: one trace+compile
    call, 1 warmup call, then best-of-``trials`` over ``calls``
    dispatches per trial — each trial synced by a real device->host
    value fetch (float(loss)); through the remote-tunnel backend on this
    box jax.block_until_ready returns before execution completes, which
    once inflated numbers past physical peak flops.  A value fetch
    cannot be faked.  ``on_first(elapsed, compile_s)`` fires right after
    the first trial so the caller can emit a preliminary result before
    later trials risk the deadline.  Returns (best_seconds, compile_s)."""
    if trials is None:
        trials = int(os.environ.get("BENCH_TRIALS", "1"))
    _stamp_compile("compile", _COMPILE_CREDIT[0])
    t0 = time.perf_counter()
    loss = do_steps()  # first call: trace + XLA compile
    float(loss)
    compile_s = time.perf_counter() - t0
    # compile time is excluded from the deadline (both sides: the child's
    # cooperative checks here, the supervisor via the heartbeat file)
    _COMPILE_CREDIT[0] += compile_s
    _stamp_compile("done", _COMPILE_CREDIT[0])
    loss = do_steps()  # warmup dispatch
    float(loss)
    best = None
    for i in range(trials):
        start = time.perf_counter()
        for _ in range(calls):
            loss = do_steps()
        float(loss)
        elapsed = time.perf_counter() - start
        best = elapsed if best is None else min(best, elapsed)
        if i == 0 and on_first is not None:
            on_first(elapsed, compile_s)
        if _remaining() < 30:  # no budget for another trial — NEVER
            # raise here: a completed trial is a real measurement and
            # must be returned, not replaced by a stale/error line
            break
    return best, compile_s


def _step_hbm_stats(opt):
    """``memory_analysis`` of the step program just benchmarked: the
    donation proof (params + opt-state alias bytes) and the
    peak-resident figure for the result row.  AOT re-lower + compile
    from shape specs, run UNDER the compile heartbeat: where the
    persistent cache absorbs it, the credit is ~0; where the cache is
    disabled (cpu + donated programs — the replay-crash guard) the
    recompile's seconds are excluded from the deadline like any other
    compile, so this query can never stale-out the run it decorates.
    Skipped when the remaining budget is thin, the knob is off, or the
    backend implements no analysis."""
    if os.environ.get("BENCH_MEMSTATS", "1") != "1" or _remaining() < 45:
        return None
    from chainermn_tpu.core.optimizer import memory_stats_dict
    _stamp_compile("compile", _COMPILE_CREDIT[0])
    t0 = time.perf_counter()
    try:
        ma = opt.compiled_step_memory_analysis()
    except Exception:
        ma = None
    finally:
        _COMPILE_CREDIT[0] += time.perf_counter() - t0
        _stamp_compile("done", _COMPILE_CREDIT[0])
    return memory_stats_dict(ma)


def _run_bench_transformer():
    """Auxiliary bench mode (BENCH_MODEL=transformer): GPT-2-small-class
    causal LM, tokens/sec/chip + MFU.  No reference-era baseline exists
    for this vertical (vs_baseline=null); recorded for the long-context
    story alongside the headline ResNet number."""
    import jax
    _enable_compile_cache(jax)
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import Adam
    from chainermn_tpu.models import TransformerLM

    per_chip_bs = int(os.environ.get("BENCH_BS", str(DEFAULT_TF_BS)))
    seq_len = int(os.environ.get("BENCH_SEQ", str(DEFAULT_SEQ)))
    n_steps, short_steps = _effective_steps(DEFAULT_TF_STEPS)
    exchange, bucket_mb = _exchange_config()
    exchange_info = {"exchange": exchange, "bucket_mb": bucket_mb}
    d_model = int(os.environ.get("BENCH_D_MODEL",
                                 str(DEFAULT_TF_D_MODEL)))
    n_layers = int(os.environ.get("BENCH_LAYERS",
                                  str(DEFAULT_TF_LAYERS)))
    n_vocab = int(os.environ.get("BENCH_VOCAB", str(DEFAULT_TF_VOCAB)))
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    # BENCH_REMAT_POLICY ("dots", "full", or a jax.checkpoint_policies
    # name): what the per-block remat recomputes — meaningless without
    # BENCH_REMAT=1, and silently ignoring it would mislabel a no-remat
    # measurement as a policy run (models/transformer.py · _remat_policy)
    remat_policy = os.environ.get("BENCH_REMAT_POLICY", "")
    if remat_policy and not remat:
        raise ValueError("BENCH_REMAT_POLICY is set but BENCH_REMAT is "
                         "not 1 — the policy would not be applied")
    remat_arg = (remat_policy or True) if remat else False
    n_heads = int(os.environ.get("BENCH_HEADS", "0")) or max(1, d_model // 64)
    if d_model % n_heads:
        raise ValueError(f"BENCH_D_MODEL={d_model} is not divisible by "
                         f"n_heads={n_heads}; set BENCH_HEADS explicitly")
    donate = os.environ.get("BENCH_DONATE", "1") == "1"

    devices = jax.devices()
    n_devices = len(devices)
    platform = devices[0].platform

    def mk_result(tokens_per_sec, compile_s, used_bs, hbm=None):
        per_chip = tokens_per_sec / n_devices
        result = {
            "metric": "transformer_lm_train_throughput",
            "value": round(per_chip, 1),
            "unit": "tokens/sec/chip",
            "vs_baseline": None,
            "platform": platform,
            "device_kind": getattr(devices[0], "device_kind", platform),
            "n_devices": n_devices,
            "per_chip_batch": used_bs,
            "seq_len": seq_len,
            "d_model": d_model,
            "n_layers": n_layers,
            "n_vocab": n_vocab,
            "remat": remat,
            "remat_policy": remat_policy,
            "n_steps": n_steps,
            "donated": donate,
            "compile_s": round(compile_s, 1),
        }
        result.update(exchange_info)
        if short_steps:
            # first-contact tight-deadline fallback: real data, but a
            # different amortization regime — labeled, and n_steps-gated
            # out of the flagship cache
            result["short_steps"] = True
        if hbm is not None:
            result["peak_hbm_bytes"] = hbm["peak_hbm_bytes"]
            result["hbm"] = hbm
        peak = _peak_tflops(devices)
        if peak:
            fpt = _transformer_flops_per_token(d_model, n_layers, n_vocab,
                                               seq_len)
            result["mfu"] = round(per_chip * fpt / (peak * 1e12), 4)
            result["peak_tflops_bf16"] = peak
        return result

    def run(per_chip_bs):
        model = TransformerLM(n_vocab=n_vocab, d_model=d_model,
                              n_heads=n_heads, n_layers=n_layers,
                              max_len=seq_len, seed=0, remat=remat_arg,
                              compute_dtype=jnp.bfloat16)
        inner = Adam(alpha=3e-4)
        inner.donate_params = donate  # BENCH_DONATE=0 = the A/B leg
        comm, opt = _make_dp_optimizer(inner, model, exchange, bucket_mb)
        exchange_info.update(_exchange_row_fields(model, comm, exchange))

        global_bs = per_chip_bs * n_devices
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randint(0, n_vocab, (global_bs, seq_len))
                        .astype(np.int32))
        t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))

        def on_first(elapsed, compile_s):
            tps = n_steps * global_bs * seq_len / elapsed
            _emit(mk_result(tps, compile_s, per_chip_bs))

        best, compile_s = _timed_steps(lambda: opt.update(model, x, t),
                                       n_steps, on_first=on_first)
        return (n_steps * global_bs * seq_len / best, compile_s,
                _step_hbm_stats(opt))

    tokens_per_sec = None
    last_err = None
    used_bs = None
    for bs in (per_chip_bs, per_chip_bs // 2, per_chip_bs // 4):
        if bs < 1:
            break
        _check_compile_budget()
        try:
            tokens_per_sec, compile_s, hbm = run(bs)
            used_bs = bs
            break
        except BenchDeadline:
            raise
        except Exception as e:  # e.g. HBM OOM at the largest batch
            last_err = e
    if tokens_per_sec is None:
        raise last_err
    return mk_result(tokens_per_sec, compile_s, used_bs, hbm)


def _run_bench_moe():
    """BENCH_MODEL=moe: the Switch-FFN MoE transformer vertical (ISSUE
    12) — expert-parallel feed-forward blocks over the SAME communicator
    the data-parallel gradient exchange rides, so a hierarchical
    BENCH_EXCHANGE gives BOTH the two-level gradient sync and the
    two-stage (ici → dcn) token dispatch, and BENCH_GRAD_DTYPE's dcn
    entry compresses both slow-hop crossings.  Reports tokens/sec/chip
    plus the exchanged DISPATCH bytes per fabric per step (the
    activation-scaled wire bill the gradient rows cannot see), the
    committed off_host_dispatch_ratio, and the routing-honesty column
    moe_dropped_frac (capacity-cut fraction, from the model's own
    reported observation).

    Knobs: BENCH_MOE_EXPERTS (default = chip count; experts are
    rank-sharded one per device, so any other value on this mesh is a
    loud error — the knob exists for pods), BENCH_MOE_TOPK (1 = Switch
    top-1 routing, >1 = the GShard top-k mixture),
    BENCH_MOE_CAPACITY (capacity factor, default 1.25),
    BENCH_MOE_TWO_STAGE (''=topology-aware auto, 0 = the explicit
    flat-dispatch escape on a hierarchical comm — the structural A/B).
    MoE rows are metric-fenced out of the flagship last-good cache by
    construction (the metric is not in _METRIC_TO_MODEL — the serving/
    longcontext discipline); a successful on-chip run stamps its own
    prewarm sentinel.  CPU runs clamp to a labeled cpu_smoke row."""
    import jax
    _enable_compile_cache(jax)
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.core import reporter
    from chainermn_tpu.core.optimizer import Adam
    from chainermn_tpu.models import MoETransformerLM

    devices = jax.devices()
    n_devices = len(devices)
    platform = devices[0].platform
    cpu_smoke = jax.default_backend() == "cpu"

    per_chip_bs = _env_int("BENCH_BS", 8)
    seq_len = _env_int("BENCH_SEQ", 512)
    d_model = _env_int("BENCH_D_MODEL", 512)
    n_layers = _env_int("BENCH_LAYERS", 6)
    n_vocab = _env_int("BENCH_VOCAB", DEFAULT_TF_VOCAB)
    n_steps, short_steps = _effective_steps(DEFAULT_TF_STEPS)
    topk = _env_int("BENCH_MOE_TOPK", 1)
    capacity_factor = _env_float("BENCH_MOE_CAPACITY", 1.25)
    experts = _env_int("BENCH_MOE_EXPERTS", n_devices)
    ts_env = os.environ.get("BENCH_MOE_TWO_STAGE", "")
    two_stage = None if ts_env == "" else ts_env == "1"
    donate = os.environ.get("BENCH_DONATE", "1") == "1"
    if cpu_smoke:
        # clamp: the CPU smoke must finish in seconds — labeled, and
        # never readable as an MoE measurement
        per_chip_bs = min(per_chip_bs, 2)
        seq_len = min(seq_len, 32)
        d_model = min(d_model, 64)
        n_layers = min(n_layers, 2)
        n_vocab = min(n_vocab, 512)
        n_steps = min(n_steps, 3)
    if experts != n_devices:
        raise ValueError(
            f"BENCH_MOE_EXPERTS={experts}: experts are rank-sharded one "
            f"per device and this mesh has {n_devices} — the knob exists "
            f"for larger pods, it cannot invent experts here")
    n_heads = _env_int("BENCH_HEADS", 0) or max(1, d_model // 64)
    exchange, bucket_mb = _exchange_config()

    comm, opt_exchange = _make_bench_communicator(exchange, bucket_mb)
    model = MoETransformerLM(
        n_vocab=n_vocab, ep_comm=comm, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, max_len=seq_len, seed=0,
        capacity_factor=capacity_factor, topk=topk, two_stage=two_stage,
        compute_dtype=jnp.bfloat16)
    inner = Adam(alpha=3e-4)
    inner.donate_params = donate
    comm, opt = _make_dp_optimizer(inner, model, exchange, bucket_mb,
                                   comm=comm, opt_exchange=opt_exchange)
    exchange_info = {"exchange": exchange, "bucket_mb": bucket_mb}
    exchange_info.update(_exchange_row_fields(model, comm, exchange))

    # dispatch wire bill (the activation-scaled bytes this vertical
    # exists to measure): tokens route per rank per layer through an
    # [E, C, D] capacity buffer at the bf16 compute dtype; priced by
    # the ONE surface the census identities are pinned against
    from chainermn_tpu.communicators._memory_utility import \
        moe_dispatch_exchanged_bytes
    from chainermn_tpu.parallel.moe import _resolve_two_stage, moe_capacity
    # the resolution rule and capacity formula the dispatch itself
    # applies — so the priced byte columns can never describe a
    # different exchange than the model runs (and an impossible
    # request fails here, before any compile, with the dispatch's own
    # error)
    resolved_two_stage = _resolve_two_stage(comm, two_stage)
    tokens_local = per_chip_bs * seq_len
    capacity = moe_capacity(tokens_local, experts, capacity_factor,
                            k=max(topk, 1))
    disp_elems = experts * capacity * d_model
    wire_itemsize = 2  # bf16 compute dtype
    dcn_wire = comm.dcn_grad_dtype
    hops = moe_dispatch_exchanged_bytes(
        disp_elems * wire_itemsize, comm.ici_size, comm.dcn_size,
        two_stage=resolved_two_stage,
        dcn_n_bytes=disp_elems * dcn_wire.itemsize
        if (resolved_two_stage and dcn_wire is not None) else None)
    moe_info = {
        "moe_experts": experts, "moe_topk": topk,
        "capacity_factor": capacity_factor,
        "moe_capacity": capacity,
        "two_stage": resolved_two_stage,
        "off_host_dispatch_ratio":
            (comm.dcn_size - 1) / comm.dcn_size
            if comm.hierarchy is not None else None,
        # per step = per layer bill × layers (dispatch + combine round
        # trip each); flat single-axis rows carry the joint figure
        "dispatch_bytes_ici": hops.get("ici", 0) * n_layers,
        "dispatch_bytes_dcn": hops.get("dcn", 0) * n_layers,
        "dispatch_bytes_world": hops.get("world", 0) * n_layers,
    }

    global_bs = per_chip_bs * n_devices
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, n_vocab, (global_bs, seq_len))
                    .astype(np.int32))
    t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))

    def mk_result(tokens_per_sec, compile_s, dropped, hbm=None):
        per_chip = tokens_per_sec / n_devices
        result = {
            "metric": "moe_lm_train_throughput",
            "value": round(per_chip, 1),
            "unit": "tokens/sec/chip",
            "vs_baseline": None,  # greenfield: the reference had no MoE
            "platform": platform,
            "device_kind": getattr(devices[0], "device_kind", platform),
            "n_devices": n_devices,
            "per_chip_batch": per_chip_bs,
            "seq_len": seq_len, "d_model": d_model,
            "n_layers": n_layers, "n_vocab": n_vocab,
            "n_steps": n_steps, "donated": donate,
            "moe_dropped_frac": dropped,
            "compile_s": round(compile_s, 1),
        }
        result.update(exchange_info)
        result.update(moe_info)
        if short_steps:
            result["short_steps"] = True
        if cpu_smoke:
            result["cpu_smoke"] = True
        if hbm is not None:
            result["peak_hbm_bytes"] = hbm["peak_hbm_bytes"]
            result["hbm"] = hbm
        return result

    # capture the model's own routing-honesty observation (reported
    # through the reporter on every update) alongside the timings —
    # observers must be registered on the scoped reporter or the
    # in-step report raises at trace time.  The value is READ (a
    # device->host sync) only outside the timed loop: a per-step
    # float() inside do_steps would serialize dispatches and deflate
    # tokens/sec relative to every other bench vertical.
    rep = reporter.Reporter()
    rep.add_observer("main", model)
    rep.add_observers("main", model.namedlinks(skipself=True))
    obs = {}

    def do_steps():
        with rep.scope(obs):
            return opt.update(model, x, t)

    def dropped():
        for key, value in obs.items():
            if key.endswith("moe_dropped"):
                return round(float(value), 4)
        return None

    def on_first(elapsed, compile_s):
        tps = n_steps * global_bs * seq_len / elapsed
        _emit(mk_result(tps, compile_s, dropped()))

    best, compile_s = _timed_steps(do_steps, n_steps, on_first=on_first)
    result = mk_result(n_steps * global_bs * seq_len / best, compile_s,
                       dropped(), _step_hbm_stats(opt))
    if not cpu_smoke and result["value"] is not None:
        # a real on-chip MoE run warms this model family's sentinel
        # (the metric is not in _METRIC_TO_MODEL — MoE rows are never
        # flagship-cacheable — so _emit won't stamp it)
        try:
            with open(_prewarm_sentinel("moe"), "w") as f:
                f.write(f"{os.environ['BENCH_RUN_ID']} {time.time()}\n")
        except Exception:
            pass
    return result


def _run_bench_longcontext():
    """BENCH_MODEL=longcontext: the long-context feasibility claim as a
    committed artifact (VERDICT r5 Next-round #8) instead of a
    BENCH_NOTES paragraph.  Emits one row per T of the causal flash
    attention fwd+bwd (GPT-2-small head geometry, T = BENCH_LC_SEQS,
    default 16k and 32k) through the default FUSED backward, plus the
    contrast row: XLA attention at BENCH_LC_XLA_T (default 8192), which
    on a real chip fails to compile/fit its [B, H, T, T] score tensors
    while the flash rows run — that recorded failure IS the datum.  The
    summary line's value is the largest T the flash kernels completed.

    CPU fallback (smoke only): interpret mode with T clamped to ≤512 —
    mechanics validation, labeled ``interpreted`` so nobody reads the
    timings as the feasibility claim."""
    import importlib

    import jax
    _enable_compile_cache(jax)
    import jax.numpy as jnp
    fa = importlib.import_module("chainermn_tpu.ops.flash_attention")

    # default geometry matches the sweep/probe tools and the r5 baseline
    # row (B4 H12 D64 causal bf16) so the rows compare directly — and so
    # the XLA contrast leg's score tensors are genuinely unfittable
    B = _env_int("BENCH_LC_BS", 4)
    H = _env_int("BENCH_HEADS", 12)
    D = _env_int("BENCH_LC_HEAD_DIM", 64)
    seqs = tuple(int(t) for t in os.environ.get(
        "BENCH_LC_SEQS", "16384,32768").split(","))
    xla_t = _env_int("BENCH_LC_XLA_T", 8192)
    reps = _env_int("BENCH_LC_REPS", 10)

    devices = jax.devices()
    platform = devices[0].platform
    interp = jax.default_backend() == "cpu"
    if interp:
        # interpret-mode grad at long T is effectively unbounded (see
        # probe_perf.probe_flashcmp) — clamp hard, label loudly
        seqs = tuple(t for t in seqs if t <= 512) or (256,)
        xla_t = min(xla_t, 128)
        reps = 1

    scale = 1.0 / (D ** 0.5)
    bwd_mode = fa._flash_bwd_mode()
    peak = _peak_tflops(devices)

    def _qkvg(T, dtype=jnp.bfloat16):
        mk = lambda i: jnp.asarray(
            np.random.RandomState(i).normal(0, 1, (B, H, T, D))
            .astype(np.float32)).astype(dtype)
        return mk(0), mk(1), mk(2), jnp.ones((B, H, T, D), dtype)

    def common(row):
        row.update({"platform": platform,
                    "device_kind": getattr(devices[0], "device_kind",
                                           platform),
                    "B": B, "H": H, "head_dim": D,
                    "bwd_mode": bwd_mode})
        if interp:
            row["interpreted"] = True  # mechanics smoke, not perf
        return row

    rows = []
    max_ok_t = None
    compile_total = 0.0
    for T in seqs:
        if _remaining() < 45:
            rows.append(common({"T": T, "skipped": "deadline"}))
            break
        # ragged-T guard: _adaptive_block falls back to 128 when no
        # candidate divides T, and grid = T // block would then silently
        # drop the tail rows — refuse the row instead of mismeasuring
        bq, bk = fa._flash_blocks(tq=T, tk=T)
        if T % min(bq, T) or T % min(bk, T):
            rows.append(common({
                "T": T,
                "error": f"tiles ({bq},{bk}) do not divide T={T}: pick "
                         "BENCH_LC_SEQS multiples of 128 (or set "
                         "CHAINERMN_TPU_FLASH_BLOCK_Q/K)"}))
            continue
        q, k, v, g = _qkvg(T)

        def step(q, k, v, g):
            out, lse = fa.flash_attention_fwd(
                q, k, v, causal=True, scale=scale, interpret=interp)
            dq, dk, dv = fa.flash_attention_bwd(
                q, k, v, out, lse, g, causal=True, scale=scale,
                interpret=interp)
            # scalar sync handle: a real device->host value fetch (the
            # relay lies through block_until_ready — bench docstring)
            return (dq[0, 0, 0, 0].astype(jnp.float32)
                    + dk[0, 0, 0, 0] + dv[0, 0, 0, 0])

        fn = jax.jit(step)
        try:
            best, compile_s = _timed_steps(
                lambda: fn(q, k, v, g), reps, trials=1)
            dt = best / reps
        except BenchDeadline:
            raise
        except Exception as e:
            rows.append(common({"T": T,
                                "error": f"{type(e).__name__}: {e}"[:300]}))
            continue
        compile_total += compile_s
        flops = 4 * B * H * T * T * D * 3.5 / 2  # causal fwd+bwd model
        row = common({"T": T, "fwd_bwd_ms": round(dt * 1e3, 2),
                      "tflops": round(flops / dt / 1e12, 1),
                      "compile_s": round(compile_s, 1)})
        if peak:
            row["mfu"] = round(flops / dt / (peak * 1e12), 3)
        rows.append(row)
        max_ok_t = T
    for row in rows:
        _emit(dict(row, metric="longcontext_flash_row"), persist=False)

    # the contrast leg: stock XLA attention at the T where the flash
    # path demonstrably runs — on chip this fails (scores tensor alone
    # at T=8192 is B·H·T²·4 bytes ≈ 12.9 GB fp32) and the recorded
    # failure is the artifact
    xla_row = {"T": xla_t}
    if _remaining() < 30:
        xla_row["skipped"] = "deadline"
    else:
        q, k, v, g = _qkvg(xla_t)

        def xla_step(q, k, v, g):
            def loss(q, k, v):
                return jnp.sum(fa.xla_attention(q, k, v, causal=True,
                                                scale=scale)
                               .astype(jnp.float32))
            dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            return dq[0, 0, 0, 0] + dk[0, 0, 0, 0] + dv[0, 0, 0, 0]

        xfn = jax.jit(xla_step)
        try:
            best, compile_s = _timed_steps(
                lambda: xfn(q, k, v, g), max(1, reps // 2), trials=1)
            xla_row["fwd_bwd_ms"] = round(best / max(1, reps // 2) * 1e3,
                                          2)
            xla_row["compile_s"] = round(compile_s, 1)
        except BenchDeadline:
            raise
        except Exception as e:
            xla_row["failed"] = f"{type(e).__name__}: {e}"[:300]
    _emit(common(dict(xla_row, metric="longcontext_xla_contrast")),
          persist=False)

    result = common({
        "metric": "longcontext_flash_feasibility",
        "value": max_ok_t,
        "unit": "tokens_context",
        "vs_baseline": None,
        "n_devices": len(devices),
        "seqs": list(seqs),
        "rows": [{k: v for k, v in r.items()} for r in rows],
        "xla_contrast": xla_row,
        "compile_s": round(compile_total, 1),
    })
    if peak:
        result["peak_tflops_bf16"] = peak
    return result


def _run_bench_serving():
    """BENCH_MODEL=serving: the continuous-batching engine under a
    seeded synthetic OPEN-LOOP load (ISSUE 9).  Arrivals are a Poisson
    process at BENCH_SERVE_QPS spread over BENCH_SERVE_TENANTS tenants
    — generated up front from a fixed seed, independent of the service
    rate (open loop: a slow engine builds queue, it does not slow the
    offered load).  Reports tokens/sec (generated tokens over the
    measured window), p50/p99 PER-TOKEN latency (first token: arrival →
    production, includes queueing + prefill; later tokens: gap since
    the previous token of the same request, includes preemption
    stalls), p50/p99 QUEUE WAIT (the sum of the request's
    per-admission waits — arrival → first admission plus each
    eviction-requeue → re-admission dwell; the pure scheduling share
    of its latency, ISSUE 14), and page-pool occupancy (mean/max over
    decode steps).

    Round 14: the load is CHAT-SHAPED — every tenant re-sends a fixed
    ``BENCH_SERVE_PREFIX``-token system prompt ahead of a random tail —
    and the row carries the measured prefix economics
    (``prefix_hit_rate``, ``effective_capacity_x``, ``forks``), the
    disaggregation ship's ``transferred_page_bytes``
    (``BENCH_SERVE_DISAGG=1``) and the ``tp`` decode ways
    (``BENCH_SERVE_TP``).  ``BENCH_SERVE_PREFIX=0`` is the sharing-off
    A/B leg (engine prefix cache disabled).

    Two phases on ONE engine: a warmup pass first drives every prefill/
    decode bucket the load will touch (all jit compiles land here,
    under the compile heartbeat so the supervisor's clock pauses), then
    the engine is drained and the measured load runs against warm
    programs — the trace counters are asserted flat across the
    measured phase.

    CPU fallback (smoke only): the model and load CLAMP to a
    seconds-scale configuration and the row is labeled
    ``cpu_smoke: true`` — mechanics validation, never a serving
    number.  Serving rows are excluded from the last-good cache by
    construction (the metric is not flagship-cacheable, same
    discipline as the longcontext rows)."""
    import jax
    _enable_compile_cache(jax)
    import jax.numpy as jnp

    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.serving import Request, ServingEngine

    devices = jax.devices()
    platform = devices[0].platform
    cpu_smoke = jax.default_backend() == "cpu"

    qps = _env_float("BENCH_SERVE_QPS", 16.0)
    tenants = _env_int("BENCH_SERVE_TENANTS", 4)
    n_requests = _env_int("BENCH_SERVE_REQUESTS", 64)
    max_new = _env_int("BENCH_SERVE_MAX_NEW", 32)
    prompt_max = _env_int("BENCH_SERVE_PROMPT", 64)
    max_batch = _env_int("BENCH_SERVE_MAX_BATCH", 8)
    page_size = _env_int("BENCH_SERVE_PAGE", 16)
    num_pages = _env_int("BENCH_SERVE_PAGES", 256)
    # round-14 scale-out knobs: the chat-shaped load (per-tenant shared
    # system prompt — what prefix sharing exists for), the
    # disaggregated prefill/decode split, and tensor-parallel decode
    prefix_len = _env_int("BENCH_SERVE_PREFIX", 16)
    disagg = os.environ.get("BENCH_SERVE_DISAGG", "0") == "1"
    tp = _env_int("BENCH_SERVE_TP", 1)
    # round-20 knobs (ISSUE 20): BENCH_SERVE_SPEC_K=K turns on
    # speculative decoding (n-gram self-draft, K proposals verified in
    # one dispatch — bit-identical tokens, fewer dispatches);
    # BENCH_SERVE_CHUNK=C turns on chunked prefill AND switches the
    # load to mixed short/long — every fourth request carries a LONG
    # prompt (up to 4x BENCH_SERVE_PROMPT) that admits in C-token
    # chunks between decode steps, which is exactly the head-of-line
    # blocking the p99 column measures
    spec_k = max(0, _env_int("BENCH_SERVE_SPEC_K", 0))
    chunk_env = max(0, _env_int("BENCH_SERVE_CHUNK", 0))
    long_factor = 4 if chunk_env else 1
    # round-16 fleet knobs (ISSUE 15): BENCH_SERVE_REPLICAS > 1 serves
    # through a ReplicaFleet behind the router; BENCH_FLEET_KILL_AT=K
    # preempts the highest replica at decode step K (its in-flight
    # sequences reroute — zero drops) and a cold replica then joins via
    # the multicast-tree weight sync (weight_sync_s measures it)
    from chainermn_tpu.serving.fleet import fleet_mode as _fleet_mode
    replicas = max(1, _env_int("BENCH_SERVE_REPLICAS", 1))
    if not _fleet_mode():
        replicas = 1   # CHAINERMN_TPU_FLEET=off: single-engine hatch
    fleet_kill_at = _env_int("BENCH_FLEET_KILL_AT", -1)
    # round-17 diurnal scenario (ISSUE 16): BENCH_DIURNAL=1 modulates
    # the arrival rate sinusoidally — λ(t) = qps·(1 + amp·sin(2πt/T))
    # — and runs a CapacityBroker over a synthetic training group next
    # to the fleet: the peak trips the hysteresis policy's +1 and a
    # training rank CONVERTS into a serving replica; the trough trips
    # the -1 and it retires back.  The row's conversions /
    # role_transfers / convert_s columns measure the transfers.
    diurnal = os.environ.get("BENCH_DIURNAL", "0") == "1"
    if not _fleet_mode():
        diurnal = False   # no fleet to grow: nothing to convert into
    diurnal_period = _env_float("BENCH_DIURNAL_PERIOD", 8.0)
    diurnal_amp = _env_float("BENCH_DIURNAL_AMP", 0.8)
    diurnal_world = max(2, _env_int("BENCH_DIURNAL_WORLD", 2))
    d_model = _env_int("BENCH_D_MODEL", 256)
    n_layers = _env_int("BENCH_LAYERS", 4)
    n_vocab = _env_int("BENCH_VOCAB", 8192)
    n_heads = _env_int("BENCH_HEADS", 0) or max(1, d_model // 64)
    if cpu_smoke:
        # clamp: the CPU interpret smoke must finish in seconds — it is
        # labeled, and could never stale-out first contact on size
        n_requests = min(n_requests, 12)
        max_new = min(max_new, 8)
        prompt_max = min(prompt_max, 24)
        d_model = min(d_model, 64)
        n_layers = min(n_layers, 2)
        n_vocab = min(n_vocab, 512)
        n_heads = max(1, d_model // 32)
        num_pages = min(num_pages, 64)
        # keep the chunk threshold below the clamped long prompts so
        # the smoke actually exercises chunked admission
        if chunk_env:
            chunk_env = min(chunk_env, 16)
    if cpu_smoke:
        long_factor = min(long_factor, 2)
    # the shared prefix must leave room for a per-request tail
    prefix_len = max(0, min(prefix_len, prompt_max - 8))
    long_max = prompt_max * long_factor
    max_context = 1
    while max_context < long_max + max_new:
        max_context *= 2
    # chunk size: page-multiple (the engine's admission contract),
    # bounded by the context
    chunk_tokens = None
    if chunk_env:
        chunk_tokens = min(max(page_size,
                               (chunk_env // page_size) * page_size),
                           max_context)

    model = TransformerLM(n_vocab=n_vocab, d_model=d_model,
                          n_heads=n_heads, n_layers=n_layers,
                          max_len=max_context, seed=0,
                          compute_dtype=jnp.bfloat16)

    def _build_engine(rid=0):
        return ServingEngine(model, num_pages=num_pages,
                             page_size=page_size, max_batch=max_batch,
                             max_context=max_context,
                             max_queue=n_requests + max_batch,
                             prefix_cache=prefix_len > 0, disagg=disagg,
                             tp=tp, spec_k=spec_k,
                             chunk_tokens=chunk_tokens)

    broker = None
    if replicas > 1 or diurnal:
        from chainermn_tpu.serving import ReplicaFleet
        scale_policy = None
        if diurnal:
            from chainermn_tpu.serving.fleet import QueueDepthScalePolicy
            scale_policy = QueueDepthScalePolicy(
                scale_up_depth=_env_float("BENCH_DIURNAL_UP", 8),
                scale_down_depth=_env_float("BENCH_DIURNAL_DOWN", 0),
                min_replicas=1,
                max_replicas=replicas + diurnal_world - 1)
        fleet = ReplicaFleet(engine_factory=_build_engine,
                             replicas=replicas,
                             scale_policy=scale_policy)
        if fleet_kill_at >= 0:
            # seeded kill-under-load: the HIGHEST replica preempts at
            # that decode step (deterministic — the same discipline as
            # the elastic BENCH_PREEMPT_RANK leg)
            fleet.replicas[max(fleet.replicas)].kill_at = fleet_kill_at
        if diurnal:
            # the diurnal scenario's training side is synthetic (this
            # is a single-host bench): diurnal_world ranks sit in a
            # LocalTrainGroup and the broker EXECUTES the policy's
            # decisions as real role transfers — the converted rank's
            # engine joins through the same tree-sync path a gloo
            # fleet uses, its compiles landing as conversion cost
            from chainermn_tpu.elastic import (CapacityBroker,
                                               LocalTrainGroup)
            broker = CapacityBroker(LocalTrainGroup(world=diurnal_world),
                                    fleet, engine_factory=_build_engine,
                                    min_world=1)
        target = fleet
        engines = [r.engine for r in fleet.live_replicas()]
    else:
        fleet = None
        engine = _build_engine()
        target = engine
        engines = [engine]

    rng = np.random.RandomState(0)
    # chat-shaped load: every tenant re-sends its own fixed system
    # prompt (prefix_len tokens) ahead of a random tail — the traffic
    # shape prefix sharing multiplies effective pool capacity on
    sys_prompts = [rng.randint(0, n_vocab, prefix_len).astype(np.int32)
                   for _ in range(tenants)]

    def synth_requests(n, t0):
        reqs, t = [], t0
        for _ in range(n):
            lam = qps
            if diurnal:
                # sinusoidal day: λ(t) = qps·(1 + amp·sin(2πt/T)),
                # floored so the trough still trickles arrivals — the
                # peak builds the queue that trips the +1, the trough
                # drains it for the -1
                lam = max(qps * 0.05,
                          qps * (1.0 + diurnal_amp * np.sin(
                              2.0 * np.pi * t / diurnal_period)))
            t += rng.exponential(1.0 / lam)
            ten = rng.randint(tenants)
            hi = prompt_max - prefix_len + 1
            if chunk_tokens is not None and len(reqs) % 4 == 3:
                # the mixed-load long leg: a prompt past the chunk
                # threshold, admitted in chunks between decode steps
                hi = long_max - prefix_len + 1
            tail = rng.randint(
                0, n_vocab, rng.randint(4, hi)).astype(np.int32)
            reqs.append(Request(
                np.concatenate([sys_prompts[ten], tail]),
                max_new_tokens=max_new,
                tenant=f"tenant{ten}",
                arrival_time=t))
        return reqs

    # -- warmup: compile every bucketed program BEFORE the window (the
    # engine's never-retrace contract needs all buckets pre-traced; the
    # compile heartbeat keeps the supervisor's clock paused meanwhile)
    _check_compile_budget()
    _stamp_compile("compile", _COMPILE_CREDIT[0])
    t0 = time.perf_counter()
    for e in engines:
        e.warmup()
    compile_s = time.perf_counter() - t0
    _COMPILE_CREDIT[0] += compile_s
    _stamp_compile("done", _COMPILE_CREDIT[0])
    traces_before = sum(e.prefill_traces + e.decode_traces
                        + e.spec_traces + e.chunk_traces
                        for e in engines)

    # -- measured open-loop window
    for req in synth_requests(n_requests, 0.0):
        target.submit(req)
    occ, cap_x, steps = [], [], 0
    joined = False
    base = time.monotonic()
    while (fleet.pending() if fleet is not None
           else engine.running or engine.prefilling
           or engine.scheduler.pending()):
        if _remaining() < 20:
            break  # cooperative: report the partial window honestly
        st = target.step(now=time.monotonic() - base)
        if broker is not None and st.get("scale_decision"):
            # auto-apply INSIDE the loop: the -1 fires mid-drain (the
            # hysteresis policy disarms after answering, and a
            # post-drain read returns 0) so the decision must be
            # executed the step it surfaces
            broker.apply(st["scale_decision"],
                         now=time.monotonic() - base)
        if fleet is not None and fleet.sheds and not joined:
            # scale back after the kill: a COLD replica joins mid-load
            # and syncs weights over the multicast tree — weight_sync_s
            # is the row's cold-start cost column (its compiles are
            # cold-start cost too, outside the initial engines'
            # never-retrace window)
            fleet.join()
            joined = True
        if st["decoded"] == 0 and st["admitted"] == 0:
            # open-loop idle tick: nothing arrived yet — wait for the
            # load, don't spin (idle ticks are not decode steps and
            # must not dilute the occupancy series)
            time.sleep(0.002)
            continue
        occ.append(st["occupancy"])
        cap_x.append(st["capacity_x"])
        steps += 1
    elapsed = time.monotonic() - base

    completed = (fleet.completed if fleet is not None
                 else engine.completed)
    all_engines = engines if fleet is None else \
        [r.engine for r in fleet.replicas.values() if not r.remote]

    lat = []
    for req in completed:
        if not req.token_times:
            continue
        lat.append(req.token_times[0] - req.arrival_time)
        lat.extend(np.diff(req.token_times))
    lat = np.asarray(lat) if lat else np.asarray([0.0])
    # scheduler health (ISSUE 14 satellite): queue wait = the SUM of
    # the request's per-admission waits (arrival -> first admission,
    # plus eviction-requeue -> re-admission) — the pure scheduling
    # share of its life, decode time excluded.  The same per-admission
    # values the observability histogram buckets when tracing is on;
    # the bench reports them exactly (per-request sums, not bucket
    # bounds), trace on or off.
    qwait = np.asarray([r.queue_wait_s for r in completed
                        if r.admit_time is not None
                        or r.queue_wait_s > 0] or [0.0])
    # token_times, not tokens: an evicted request's generated tokens
    # fold into its prompt (recompute on re-admit) but each kept its
    # one production timestamp — len(tokens) would deflate tokens/sec
    # exactly on the saturation rows where eviction happens
    n_tokens = sum(len(r.token_times) for r in completed)

    result = {
        "metric": "serving_engine_throughput",
        "value": round(n_tokens / elapsed, 1) if elapsed > 0 else None,
        "unit": "tokens/sec",
        "vs_baseline": None,   # greenfield: the reference had no serving
        "platform": platform,
        "device_kind": getattr(devices[0], "device_kind", platform),
        "n_devices": len(devices),
        "p50_token_latency_ms": round(float(np.percentile(lat, 50)) * 1e3,
                                      2),
        "p99_token_latency_ms": round(float(np.percentile(lat, 99)) * 1e3,
                                      2),
        "p50_queue_wait_ms": round(float(np.percentile(qwait, 50)) * 1e3,
                                   2),
        "p99_queue_wait_ms": round(float(np.percentile(qwait, 99)) * 1e3,
                                   2),
        "page_occupancy_mean": round(float(np.mean(occ)), 3) if occ
        else 0.0,
        "page_occupancy_max": round(float(np.max(occ)), 3) if occ
        else 0.0,
        "qps": qps, "tenants": tenants, "requests": n_requests,
        "completed": len(completed),
        "generated_tokens": int(n_tokens),
        "evictions": sum(e.evictions for e in all_engines),
        "decode_steps": steps,
        "max_batch": max_batch, "page_size": page_size,
        "num_pages": num_pages, "max_context": max_context,
        "d_model": d_model, "n_layers": n_layers, "n_vocab": n_vocab,
        "attn_mode": engines[0].mode,
        "page_dtype": str(engines[0].kv.dtype),
        # round-14 scale-out surface: the chat-shaped load's measured
        # prefix economics, the disagg ship's wire bytes, and tp
        "prefix_tokens": prefix_len,
        "prefix_hit_rate": round(
            sum(e.prefix_hits for e in all_engines)
            / max(1, sum(e.admissions for e in all_engines)), 3),
        "prefix_matched_tokens": int(sum(e.prefix_tokens_matched
                                         for e in all_engines)),
        "forks": sum(e.forks for e in all_engines),
        "effective_capacity_x": round(float(np.mean(cap_x)), 3)
        if cap_x else 1.0,
        "effective_capacity_x_max": round(float(np.max(cap_x)), 3)
        if cap_x else 1.0,
        "disagg": engines[0].disagg,
        "transferred_page_bytes": int(sum(e.transferred_page_bytes
                                          for e in all_engines)),
        "tp": engines[0].tp,
        # round-20 surface (ISSUE 20): the speculative economics — the
        # dispatch-count reduction IS accepted_tokens_per_dispatch; a
        # draft model's extra dispatches show up as draft_overhead —
        # and the chunked-prefill admission counters (present on EVERY
        # serving row; zeros when the knobs are off)
        "spec_k": spec_k,
        "chunk_tokens": chunk_tokens or 0,
        "spec_steps": sum(e.spec_steps for e in all_engines),
        "accepted_tokens_per_dispatch": round(
            sum(e.spec_emitted for e in all_engines)
            / max(1, sum(e.spec_lane_steps for e in all_engines)), 3),
        "spec_acceptance_rate": round(
            sum(e.spec_accepted for e in all_engines)
            / max(1, sum(e.spec_proposed for e in all_engines)), 3),
        "draft_overhead": round(
            sum(e.draft_dispatches for e in all_engines)
            / max(1, sum(e.spec_steps for e in all_engines)), 3),
        "chunked_admissions": sum(e.chunked_admissions
                                  for e in all_engines),
        "chunk_prefills": sum(e.chunk_prefills for e in all_engines),
        "compile_s": round(compile_s, 1),
        # the never-retrace contract, measured: bucket programs compiled
        # in warmup, zero traces during the window — counted over the
        # INITIAL replicas (a mid-window joiner compiles cold by
        # design; that cost is the join's, not the window's)
        "window_retraces": (sum(e.prefill_traces + e.decode_traces
                                + e.spec_traces + e.chunk_traces
                                for e in engines) - traces_before),
        # round-16 fleet surface (ISSUE 15): present on EVERY serving
        # row (single-engine rows backfill the fleet-less defaults, so
        # row consumers never key-miss)
        "replicas": replicas,
        "reroutes": fleet.reroutes if fleet is not None else 0,
        "weight_sync_s": round(fleet.weight_sync_s, 3)
        if fleet is not None else 0.0,
        "fleet_kill_at": fleet_kill_at if fleet is not None else -1,
        # round-17 capacity surface (ISSUE 16): present on EVERY
        # serving row (broker-less rows backfill zeros); any non-zero
        # conversions/role_transfers payload-fences the row from the
        # flagship cache — the measured world changed ROLE mid-window
        "conversions": broker.stats["conversions"]
        if broker is not None else 0,
        "role_transfers": broker.stats["role_transfers"]
        if broker is not None else 0,
        "convert_s": round(broker.stats["convert_s"], 3)
        if broker is not None else 0.0,
        "diurnal": diurnal,
        "diurnal_period": diurnal_period if diurnal else 0.0,
    }
    if cpu_smoke:
        # labeled loudly: mechanics smoke, not a serving measurement
        result["cpu_smoke"] = True
    elif result["value"] is not None:
        # a real on-chip serving run warms this model family's sentinel
        # (the metric is not in _METRIC_TO_MODEL — serving rows are
        # never flagship-cacheable — so _emit won't stamp it)
        try:
            with open(_prewarm_sentinel("serving"), "w") as f:
                f.write(f"{os.environ['BENCH_RUN_ID']} {time.time()}\n")
        except Exception:
            pass
    return result


def _run_bench():
    import jax
    _enable_compile_cache(jax)
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.models import Classifier, ResNet50

    # smoke-test knobs (defaults are the real benchmark configuration)
    per_chip_bs = int(os.environ.get("BENCH_BS", str(DEFAULT_BS)))
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    image_size = int(os.environ.get("BENCH_SIZE", str(DEFAULT_SIZE)))
    n_steps, short_steps = _effective_steps(DEFAULT_STEPS)
    exchange, bucket_mb = _exchange_config()
    exchange_info = {"exchange": exchange, "bucket_mb": bucket_mb}
    # BENCH_SCAN=K fuses K steps per dispatch via update_scan (one jit
    # containing a lax.scan) — isolates device throughput from host/relay
    # dispatch latency; 0 = plain per-step update() dispatch.  The
    # input-pipeline mode defaults to K=4 (set BENCH_SCAN=0 to disable):
    # overlapped host feed + multi-step fused dispatch is the composed
    # configuration that mode exists to measure.
    _scan_env = os.environ.get("BENCH_SCAN", "")
    # activation layout: NHWC is the TPU-native convolution layout
    layout = os.environ.get("BENCH_LAYOUT", "NHWC")
    # BENCH_INPUT_PIPELINE=1: feed each step from the REAL host pipeline
    # (uint8 synthetic rows → batch assembly in BENCH_ITERATOR workers →
    # DevicePrefetchIterator overlapped placement → in-graph input_norm
    # cast) instead of one pre-staged device batch — measures on chip how
    # much of the host feed the overlapped dispatch actually hides (the
    # delta vs the pre-staged flagship row is the exposed input cost,
    # also reported directly as input_stall_ms).  Composes with
    # BENCH_SCAN: K fed batches are stacked ON DEVICE per fused dispatch.
    input_pipeline = os.environ.get("BENCH_INPUT_PIPELINE", "0") == "1"
    scan_k = int(_scan_env) if _scan_env else (4 if input_pipeline else 0)
    # BENCH_ITERATOR: which host iterator assembles batches —
    # multiprocess (process pool + shared-memory slots, default),
    # native (C++ gather engine), thread (GIL-bound prefetch thread)
    iterator_kind = os.environ.get("BENCH_ITERATOR", "multiprocess")
    if input_pipeline and iterator_kind not in ("multiprocess", "native",
                                                "thread"):
        raise ValueError(f"unknown BENCH_ITERATOR={iterator_kind!r} "
                         "(multiprocess|native|thread)")
    if input_pipeline and iterator_kind == "native":
        # fail fast: a missing native loader must not burn deadline
        # budget on the OOM-backoff loop's model rebuilds
        from chainermn_tpu.utils.native import load_library
        if load_library() is None:
            raise RuntimeError(
                "BENCH_ITERATOR=native requires the native loader "
                "(g++ toolchain) — unavailable on this host")

    donate = os.environ.get("BENCH_DONATE", "1") == "1"

    devices = jax.devices()  # raises if the backend is unavailable
    n_devices = len(devices)
    platform = devices[0].platform
    device_kind = getattr(devices[0], "device_kind", platform)

    def mk_result(images_per_sec, compile_s, used_bs, feed_stats=None,
                  hbm=None):
        per_chip = images_per_sec / n_devices
        result = {
            "metric": "resnet50_imagenet_train_throughput",
            "value": round(per_chip, 2),
            "unit": "images/sec/chip",
            "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC, 3),
            "platform": platform,
            "device_kind": device_kind,
            "n_devices": n_devices,
            "per_chip_batch": used_bs,
            "image_size": image_size,
            "layout": layout,
            "remat": remat,
            "n_steps": n_steps,
            "input_pipeline": input_pipeline,
            "donated": donate,
            "compile_s": round(compile_s, 1),
            "fused_steps_per_dispatch": scan_k or 1,
        }
        result.update(exchange_info)
        if short_steps:
            # first-contact tight-deadline fallback: real data, but a
            # different amortization regime — labeled, and n_steps-gated
            # out of the flagship cache
            result["short_steps"] = True
        if hbm is not None:
            result["peak_hbm_bytes"] = hbm["peak_hbm_bytes"]
            result["hbm"] = hbm
        if input_pipeline:
            result["iterator_kind"] = iterator_kind
            if feed_stats is not None:
                # consumer time blocked on the host feed, normalized to
                # one trial's worth of dispatches — 0 means the
                # overlapped feed fully hid batch assembly + H2D behind
                # device compute
                result["input_stall_ms"] = round(feed_stats(), 1)
        peak = _peak_tflops(devices)
        if peak:
            flops = _resnet50_train_flops_per_image(image_size)
            result["mfu"] = round(per_chip * flops / (peak * 1e12), 4)
            result["peak_tflops_bf16"] = peak
        return result

    def _make_input_feed(global_bs, shape, rng):
        """The real host pipeline: uint8 rows → BENCH_ITERATOR batch
        assembly → DevicePrefetchIterator overlapped H2D.  Returns the
        device-feed iterator (finalize() it after timing)."""
        from chainermn_tpu.dataset import (DevicePrefetchIterator,
                                           MultiprocessIterator,
                                           MultithreadIterator,
                                           TupleDataset, concat_examples)
        n_img = max(2 * global_bs * max(1, scan_k), 256)
        xs = rng.randint(0, 256, (n_img,) + shape[1:], dtype=np.uint8)
        ys = rng.randint(0, 1000, n_img).astype(np.int32)
        converter = None
        if iterator_kind == "native":
            from chainermn_tpu.dataset import NativeBatchIterator
            base = NativeBatchIterator((xs, ys), global_bs, seed=0)
        elif iterator_kind == "thread":
            base = MultithreadIterator(TupleDataset(xs, ys), global_bs,
                                       seed=0)
            converter = concat_examples
        else:
            base = MultiprocessIterator(
                TupleDataset(xs, ys), global_bs, seed=0, as_arrays=True,
                n_processes=_env_int("BENCH_LOADER_PROCS", 4),
                n_prefetch=2)
        return DevicePrefetchIterator(base, size=2, converter=converter)

    def run(per_chip_bs):
        global_bs = per_chip_bs * n_devices
        model = Classifier(ResNet50(
            n_classes=1000, remat=remat, compute_dtype=jnp.bfloat16,
            seed=0, layout=layout,
            input_norm="imagenet" if input_pipeline else None))
        inner = MomentumSGD(lr=0.1, momentum=0.9)
        inner.donate_params = donate  # BENCH_DONATE=0 = the A/B leg
        comm, opt = _make_dp_optimizer(inner, model, exchange, bucket_mb)
        exchange_info.update(_exchange_row_fields(model, comm, exchange))

        rng = np.random.RandomState(0)
        shape = ((global_bs, image_size, image_size, 3) if layout == "NHWC"
                 else (global_bs, 3, image_size, image_size))

        it = None
        feed_stats = None
        if input_pipeline:
            it = _make_input_feed(global_bs, shape, rng)
            stall_base = [0.0]
            dispatch_no = [0]
            feed_calls = [1]  # timed dispatches per trial (set below)

            def feed_stats():
                # stall accumulates across ALL timed trials while the
                # throughput is best-of-trials: normalize to one trial's
                # worth of dispatches (timed dispatches = total - the 2
                # compile/warmup calls) so BENCH_TRIALS>1 does not
                # inflate the reported exposed input cost
                timed = max(1, dispatch_no[0] - 2)
                return (it.input_stall_ms - stall_base[0]) \
                    * feed_calls[0] / timed

            def _count_dispatch():
                # rebase the stall baseline at the START of call 3 —
                # after trace+compile (call 1) and warmup (call 2) have
                # fully drained their cold-pipeline fill — so the
                # emitted input_stall_ms covers only the timed trials'
                # steady-state exposed input cost
                dispatch_no[0] += 1
                if dispatch_no[0] == 3:
                    stall_base[0] = it.input_stall_ms
            if scan_k:
                # fused multi-step dispatch over the REAL feed: pull K
                # batches (device-resident), stack on device, one
                # update_scan dispatch — host feed and collective fusion
                # compose instead of excluding each other
                def do_steps():
                    _count_dispatch()
                    batches = [it.next() for _ in range(scan_k)]
                    xs_ = jnp.stack([b[0] for b in batches])
                    ts_ = jnp.stack([b[1] for b in batches])
                    return opt.update_scan(model, xs_, ts_)[-1]
                steps_per_call, calls = scan_k, max(1, n_steps // scan_k)
            else:
                def do_steps():
                    _count_dispatch()
                    return opt.update(model, *it.next())
                steps_per_call, calls = 1, n_steps
            feed_calls[0] = calls
        else:
            x = jnp.asarray(rng.normal(0, 1, shape).astype(np.float32))
            t = jnp.asarray(rng.randint(0, 1000, global_bs)
                            .astype(np.int32))
            if scan_k:
                xs = jnp.broadcast_to(x, (scan_k,) + x.shape)
                ts = jnp.broadcast_to(t, (scan_k,) + t.shape)
                do_steps = lambda: opt.update_scan(model, xs, ts)[-1]
                steps_per_call, calls = scan_k, max(1, n_steps // scan_k)
            else:
                do_steps = lambda: opt.update(model, x, t)
                steps_per_call, calls = 1, n_steps

        def on_first(elapsed, compile_s):
            ips = calls * steps_per_call * global_bs / elapsed
            _emit(mk_result(ips, compile_s, per_chip_bs, feed_stats))

        try:
            if feed_stats is not None:
                # construction-time baseline; _count_dispatch refines it
                # once compile+warmup have drained their cold fill
                stall_base[0] = it.input_stall_ms
            best, compile_s = _timed_steps(do_steps, calls,
                                           on_first=on_first)
            return (calls * steps_per_call * global_bs / best, compile_s,
                    feed_stats, _step_hbm_stats(opt))
        finally:
            if it is not None:
                it.finalize()  # stop pool/threads before any OOM rebuild

    images_per_sec = None
    last_err = None
    used_bs = None
    for bs in (per_chip_bs, per_chip_bs // 2, per_chip_bs // 4):
        if bs < 1:
            break
        _check_compile_budget()
        try:
            images_per_sec, compile_s, feed_stats, hbm = run(bs)
            used_bs = bs
            break
        except BenchDeadline:
            raise
        except Exception as e:  # e.g. HBM OOM at the largest batch
            last_err = e
    if images_per_sec is None:
        raise last_err
    return mk_result(images_per_sec, compile_s, used_bs, feed_stats, hbm)


def _err_metric():
    model = os.environ.get("BENCH_MODEL", "resnet50")
    if model == "transformer":
        return ("transformer_lm_train_throughput", "tokens/sec/chip")
    if model == "longcontext":
        return ("longcontext_flash_feasibility", "tokens_context")
    if model == "serving":
        return ("serving_engine_throughput", "tokens/sec")
    if model == "moe":
        return ("moe_lm_train_throughput", "tokens/sec/chip")
    return ("resnet50_imagenet_train_throughput", "images/sec/chip")


def _emit_stale_or_error(err):
    """Terminal fallback: re-emit the last persisted good result marked
    stale, or a machine-readable error line.  Never raises.  A cached
    result is re-served ONLY if it passes the same config fingerprint
    that gated its persistence (``_cacheable``): a non-default or
    non-accelerator payload under the flagship metric is worse than
    ``value: null`` — it reads as a (terrible) datum.

    FIRST CONTACT refuses the stale re-serve entirely (VERDICT r5 Weak
    #1, third straight stale round): with no warm-cache sentinel this
    invocation was supposed to produce fresh data (the short-steps
    fallback exists precisely for its tight window) — re-serving the
    cached flagship here is how three rounds in a row looked "fine"
    while recording zero new measurements.  The honest ``value: null``
    error line is the signal the driver needs to act on."""
    metric, unit = _err_metric()
    if _first_contact():
        _emit({"metric": metric, "value": None, "unit": unit,
               "vs_baseline": None, "error": err, "first_contact": True,
               "stale_refused": "no warm-cache sentinel for this model: "
               "first contact must yield fresh data (short-steps "
               "fallback) or fail honestly, never a stale re-serve"},
              persist=False)
        return
    # _load_cache is the single authoritative gate: it returns ONLY an
    # entry that passed the shape screen, the stored-vs-requested
    # fingerprint match, and `_cacheable`'s env+payload checks — or
    # (None, None, None)
    run_id, cached, fp = _load_cache(metric)
    if cached:
        out = dict(cached)
        if run_id != os.environ["BENCH_RUN_ID"]:
            out["stale"] = True  # measured by an earlier bench invocation
        if fp is not None:
            out["config"] = fp  # stale lines self-document provenance
        out["error"] = err
        _emit(out, persist=False)
    else:
        _emit({"metric": metric, "value": None, "unit": unit,
               "vs_baseline": None, "error": err}, persist=False)


def _child_main():
    """The actual bench, run under the supervisor's deadline.  No
    internal SIGALRM: an alarm that fires inside an in-flight
    remote-compile/step RPC abandons it, and an abandoned RPC wedges
    the relay for hours (r5 postmortems: the 04:55 and 07:20 wedges
    were both child-side deadline exits mid-compile).  Child-side
    deadline policy is the cooperative `_remaining()` check between
    trials; everything harder is the supervisor's detach-at-deadline."""
    if os.environ.get("BENCH_TEST_WEDGE") == "1":
        # fault injection (tests/test_bench_harness.py): simulate the
        # known failure mode — a child stuck in an uninterruptible call
        # before any output.  SIGTERM is IGNORED (a thread wedged in a C
        # call never runs the handler); the supervisor must emit its own
        # line at the deadline and leave this process running.
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        while True:
            time.sleep(3600)
    if os.environ.get("BENCH_TEST_WEDGE") == "slow-compile":
        # fault injection: a compile phase LONGER than the whole
        # deadline, then a fresh result — the supervisor must pause its
        # clock on the heartbeat and serve the fresh line, not stale
        # (VERDICT r5 Weak #1: first contact stale-outing on compile)
        dur = float(os.environ.get("BENCH_TEST_COMPILE_S", "12"))
        _stamp_compile("compile", 0.0)
        time.sleep(dur)
        _COMPILE_CREDIT[0] += dur
        _stamp_compile("done", _COMPILE_CREDIT[0])
        print(json.dumps({"metric": "resnet50_imagenet_train_throughput",
                          "value": 77.0, "unit": "images/sec/chip",
                          "vs_baseline": None, "platform": "test",
                          "compile_s": dur, "fresh_after_compile": True}),
              flush=True)
        return 0
    if os.environ.get("BENCH_TEST_WEDGE") == "emit-then-wedge":
        # fault injection: an early-emit line, then the wedge — the
        # supervisor's incremental read must serve the early line as
        # this run's authoritative result.
        print(json.dumps({"metric": "resnet50_imagenet_train_throughput",
                          "value": 123.0, "unit": "images/sec/chip",
                          "vs_baseline": None, "platform": "test",
                          "early": True}), flush=True)
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        while True:
            time.sleep(3600)

    def on_term(signum, frame):
        # only reachable via the supervisor's detach-cap fallback, the
        # supervisor's TERM/INT forwarding, or an external TERM: emit
        # before dying if nothing was emitted yet
        if _EMITTED[0] is None:
            _emit_stale_or_error("terminated by supervisor at deadline")
        os._exit(3)

    try:
        signal.signal(signal.SIGTERM, on_term)
    except Exception:
        pass  # non-main-thread / exotic platforms: supervisor still covers us

    if os.environ.get("BENCH_TEST_WEDGE") == "sleep-obedient":
        # fault injection: a child parked BEFORE any output but with the
        # NORMAL TERM handler installed — exercises the supervisor's
        # TERM/INT forwarding (the child must emit its terminated line
        # and die when the supervisor receives a group-directed signal)
        while True:
            time.sleep(3600)

    bench_model = os.environ.get("BENCH_MODEL", "resnet50")
    try:
        if bench_model == "transformer":
            result = _run_bench_transformer()
        elif bench_model == "longcontext":
            result = _run_bench_longcontext()
        elif bench_model == "serving":
            result = _run_bench_serving()
        elif bench_model == "moe":
            result = _run_bench_moe()
        else:
            result = _run_bench()
        _emit(result)  # final (possibly improved over the early emit)
        return 0
    except BenchDeadline as e:
        _emit_stale_or_error(f"BenchDeadline: {e}")
        return 0
    except Exception as e:
        err = f"{type(e).__name__}: {e}"
        if (os.environ.get("JAX_PLATFORMS", "") != "cpu"
                and os.environ.get("BENCH_NO_FALLBACK") != "1"
                and _remaining() > 60):
            # Backend failed fast → rerun ourselves on CPU so the round
            # still yields a datum, explicitly marked as a fallback.
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       BENCH_BS=os.environ.get("BENCH_BS_CPU", "8"),
                       BENCH_STEPS="3", BENCH_NO_SUPERVISE="1",
                       BENCH_DEADLINE_S=str(max(30, _remaining() - 30)),
                       # the child's stale re-serve decisions must use
                       # THIS process's requested config, not the
                       # shrunken cpu knobs (else a default-config run's
                       # fallback refuses its own cached flagship datum)
                       BENCH_STALE_FP=json.dumps(_config_fingerprint()))
            try:
                try:
                    proc = subprocess.run(
                        [sys.executable, os.path.abspath(__file__)],
                        env=env, capture_output=True, text=True,
                        timeout=max(30, _remaining() - 20))
                    fb_out = proc.stdout
                except subprocess.TimeoutExpired as te:
                    # the killed CPU child (no relay RPC — safe to kill)
                    # may still have early-emitted a real datum: salvage
                    # the partial stdout the exception carries
                    fb_out = te.stdout or ""
                    if isinstance(fb_out, bytes):
                        fb_out = fb_out.decode("utf-8", "replace")
                child = _parse_last_json_line(fb_out)
                if child is None:
                    raise RuntimeError("fallback produced no output")
                child_err = child.get("error")
                result = child
                result["error"] = err
                if child.get("value") is not None \
                        and not child.get("stale"):
                    result["platform"] = "cpu_fallback"
                else:  # child failed or re-emitted an old cached result —
                    # keep its own platform/stale labels and diagnostic
                    result["fallback_error"] = child_err
                _emit(result, persist=False)
            except Exception as fb:
                metric, unit = _err_metric()
                _emit({"metric": metric, "value": None, "unit": unit,
                       "vs_baseline": None, "error": err,
                       "fallback_error": f"{type(fb).__name__}: {fb}"[:500]})
        else:
            _emit_stale_or_error(err)
        return 0


def _parse_last_json_line(text):
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            return json.loads(line)
        except Exception:
            continue
    return None


_DETACH_REGISTRY = os.environ.get(
    "BENCH_DETACH_REGISTRY", "/tmp/chainermn_tpu_bench_detached.pids")
_DETACH_CAP = 2


def _proc_starttime(pid):
    """Kernel starttime of the process (field 22 of /proc/pid/stat), or
    None if it does not exist.  Identifying registry entries by
    (pid, starttime) makes the liveness check pid-reuse-proof: a bare
    /proc/<pid> check could count an unrelated process that recycled
    the pid as a live detached child forever, permanently tripping the
    cap into the kill fallback."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[19]
    except Exception:
        return None


def _read_detached_alive():
    """[(pid, starttime)] of registry entries whose process still exists
    with the SAME starttime.  Malformed or dead entries are dropped."""
    alive = []
    try:
        with open(_DETACH_REGISTRY) as f:
            for ln in f.read().splitlines():
                parts = ln.split()
                if len(parts) != 2:
                    continue
                pid, start = int(parts[0]), parts[1]
                if _proc_starttime(pid) == start:
                    alive.append((pid, start))
    except Exception:
        pass
    return alive


def _registry_locked():
    """fcntl.flock guard for the registry's read-append-replace: two
    concurrent supervisors must not each pass the cap check and then
    have one os.replace drop the other's just-written entry (ADVICE r5).
    Returns the open lock-file handle (unlocks on close), or None when
    even the lock file can't be had — callers proceed unlocked rather
    than fail (driver runs are mostly serialized anyway)."""
    try:
        f = open(_DETACH_REGISTRY + ".lock", "a+")
        fcntl.flock(f.fileno(), fcntl.LOCK_EX)
        return f
    except Exception:
        return None


def _register_detached(pid):
    """Record a child left running past its deadline (relay discipline:
    never kill a process that may hold an in-flight TPU RPC — every
    relay wedge in rounds 3-5 traced to an abandoned one).  Returns
    False when _DETACH_CAP still-alive lingering children already
    exist: at that point the relay is already in the restart-needed
    state, and bounding host memory wins over the discipline.  The
    read-append-replace runs under an fcntl.flock; a failed write still
    detaches (never force a kill) but says so on stderr — an unrecorded
    child is invisible to the next run's contention wait."""
    lock = _registry_locked()
    try:
        alive = _read_detached_alive()
        if len(alive) >= _DETACH_CAP:
            return False
        start = _proc_starttime(pid)
        if start is not None:
            alive.append((pid, start))
        tmp = _DETACH_REGISTRY + ".tmp"
        with open(tmp, "w") as f:
            f.write("".join(f"{p} {s}\n" for p, s in alive))
        os.replace(tmp, _DETACH_REGISTRY)
        return True
    except Exception as e:
        try:  # diagnostic, not silence: the child runs on unrecorded
            print(f"bench: detached child pid={pid} could NOT be "
                  f"recorded in {_DETACH_REGISTRY} "
                  f"({type(e).__name__}: {e}); next run's contention "
                  "wait will not see it", file=sys.stderr, flush=True)
        except Exception:
            pass
        return True  # registry trouble must not force a kill
    finally:
        if lock is not None:
            try:
                lock.close()
            except Exception:
                pass


def _supervise():
    """Parent process: never imports jax, so it cannot wedge.  Runs the
    bench as a child, reads its stdout incrementally, and guarantees
    exactly one authoritative (last) JSON line on stdout within the
    deadline.

    At the deadline the child is DETACHED, not killed: every relay
    wedge this round traced to a deadline exit abandoning an in-flight
    remote-compile/step RPC (BENCH_NOTES r5 postmortems), so the child
    is left alone to drain its RPC and finish; on completion it
    persists its result to the last-good cache and prewarm sentinel
    even though its stdout is gone (`_emit` tolerates that), seeding
    the NEXT run.  The incremental read means an early-emit line the
    child printed before wedging is still served as this run's
    authoritative result.  A cap on still-alive detached children
    (`_register_detached`) falls back to the old terminate→kill
    escalation so repeated outage runs cannot exhaust host memory.

    The child runs in its OWN session (start_new_session): a
    group-directed signal — GNU ``timeout`` around the driver, Ctrl-C
    on an interactive run, a CI group-kill — reaches only the
    supervisor, so a detach stays a real detach (ADVICE r5).  To keep
    interactive kill semantics, the supervisor forwards TERM/INT to the
    still-supervised child as SIGTERM (whose handler emits before
    dying); once detached, nothing is forwarded."""
    run_id = f"{os.getpid()}-{int(time.time())}"
    compile_stamp = os.environ.get("BENCH_COMPILE_STAMP") or (
        "/tmp/chainermn_tpu_bench_compile." + run_id)
    env = dict(os.environ, BENCH_SUPERVISED="1", BENCH_RUN_ID=run_id,
               BENCH_COMPILE_STAMP=compile_stamp)
    sig_state = {"proc": None, "detached": False}

    def _forward_signal(signum, frame):
        # non-timeout path only: after detach the child must survive
        # exactly the signals this handler would forward
        p = sig_state["proc"]
        if p is not None and not sig_state["detached"] \
                and p.poll() is None:
            try:
                os.kill(p.pid, signal.SIGTERM)
            except Exception:
                pass
            # fall through: the read loop continues to EOF so the
            # child's emit-before-death line is still served as the
            # final result
            return
        # no supervised child to forward to (pre-spawn contention wait,
        # or already detached): swallowing the signal would make the
        # supervisor uninterruptible — restore the default disposition
        # and re-deliver
        try:
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
        except Exception:
            pass

    for _sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(_sig, _forward_signal)
        except Exception:
            pass
    # A detached child from an EARLIER run may still be draining on the
    # one chip: wait briefly for it to finish, and if it is still there,
    # mark this run contended — a time-shared measurement must not look
    # like a clean datum (nor enter the last-good cache; the payload
    # gates refuse contended results).
    if _read_detached_alive():
        wait_until = time.monotonic() + min(60.0, _DEADLINE_S / 3)
        while time.monotonic() < wait_until and _read_detached_alive():
            time.sleep(2)
        if _read_detached_alive():
            env["BENCH_CONTENDED"] = "1"
    try:
        # stamp BEFORE spawning: a still-running detached child from an
        # earlier run sees this newer stamp at its persist time and
        # marks its own (time-shared) result contended
        with open(_START_STAMP, "w") as f:
            f.write(run_id + "\n")
        # our own stamp must not trip _newer_bench_started() in THIS
        # process (the supervisor's stale re-serve is not contended)
        global _WALL_START
        _WALL_START = time.time()
    except Exception:
        pass
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            env=env, stdout=subprocess.PIPE,
                            start_new_session=True)
    sig_state["proc"] = proc
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    deadline = time.monotonic() + _DEADLINE_S
    buf = bytearray()
    timed_out = False
    while True:
        now = time.monotonic()
        # compile time is excluded from the measurement deadline: the
        # child's heartbeat pauses the clock while a compile is in
        # flight and credits recorded compile seconds afterwards
        # (VERDICT r5 Weak #1 — first contact must not stale-out on
        # compile time alone), bounded by BENCH_COMPILE_GRACE_S
        left = deadline + _compile_credit_from_stamp(
            compile_stamp, run_id, now, _COMPILE_GRACE_S) - now
        if left <= 0:
            timed_out = True
            break
        if sel.select(timeout=min(1.0, left)):
            chunk = proc.stdout.read1(65536)
            if not chunk:
                break  # EOF: child closed stdout (exited or exiting)
            buf += chunk
    sel.close()
    if timed_out:
        sig_state["detached"] = True  # TERM/INT no longer forwarded
        if not _register_detached(proc.pid):
            proc.terminate()  # cap reached; SIGTERM → handler emits
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=5)
                except Exception:
                    pass
            try:  # BOUNDED drain of whatever the TERM handler wrote: a
                # surviving fd-inheritor of the killed child would make
                # a bare read() block forever, wedging the one process
                # whose contract is "never wedges"
                os.set_blocking(proc.stdout.fileno(), False)
                t_end = time.monotonic() + 5
                while time.monotonic() < t_end:
                    chunk = proc.stdout.read1(65536)
                    if chunk:
                        buf += chunk
                    elif chunk == b"":
                        break  # EOF: every writer closed
                    else:
                        time.sleep(0.1)  # None: no data yet
            except Exception:
                pass
        # else: no signal, no wait — the child drains its RPC and exits
        # on its own (stdout writes fail silently; persistence works)
    else:
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            # stdout closed but process lingering: leave it alone, but
            # record it so the next run's contention wait can see it
            # (ADVICE r5 low: the EOF-but-lingering child was the one
            # detach path that stayed unregistered).  Cap-reached means
            # it stays unrecorded — it closed stdout (exit imminent),
            # so unlike the timeout path we don't escalate to kill,
            # but the invisibility is at least said out loud.
            sig_state["detached"] = True
            if not _register_detached(proc.pid):
                try:
                    print(f"bench: EOF-lingering child pid={proc.pid} "
                          "NOT recorded (detach cap reached); next "
                          "run's contention wait cannot see it",
                          file=sys.stderr, flush=True)
                except Exception:
                    pass
    if not sig_state["detached"]:
        try:  # heartbeat hygiene; a detached child may still be writing
            os.remove(compile_stamp)
        except OSError:
            pass
    out = buf.decode("utf-8", "replace")
    result = _parse_last_json_line(out)
    if result is None:
        # Child produced nothing (wedged before any emit): fall back to
        # the persisted cache from an earlier run, else a pure error.
        err = ("deadline exceeded before first result"
               if timed_out else "bench child produced no output")
        os.environ["BENCH_RUN_ID"] = run_id
        _emit_stale_or_error(err)
    else:
        print(json.dumps(result), flush=True)
    return 0


def main():
    if (os.environ.get("BENCH_SUPERVISED") == "1"
            or os.environ.get("BENCH_NO_SUPERVISE") == "1"):
        sys.exit(_child_main())
    sys.exit(_supervise())


if __name__ == "__main__":
    main()
