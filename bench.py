"""Benchmark harness: ResNet-50/ImageNet training throughput per chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Baseline derivation (BASELINE.md: reference published numbers): the
ChainerMN scaling study (arXiv:1710.11351) trains ResNet-50/ImageNet 100
epochs in ~4.4 h on 128 P100s → 1.28M images × 100 / (4.4·3600 s) / 128
≈ 225 images/sec/GPU.  ``vs_baseline`` is measured throughput per chip
against that per-device figure.

The training step is the framework's real data-parallel path:
``create_multi_node_optimizer`` over a ``jax_ici`` communicator spanning
all available chips (one on this box), bf16 conv compute, bf16 gradient
compression — the TPU translation of the reference's flagship
``pure_nccl`` fp16 configuration.
"""

import json
import os
import time

import numpy as np


def main():
    import jax
    try:  # persistent compile cache: repeat runs skip the ~30s XLA compile
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/chainermn_tpu_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.models import Classifier, ResNet50

    # smoke-test knobs (defaults are the real benchmark configuration)
    per_chip_bs = int(os.environ.get("BENCH_BS", "64"))
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    image_size = int(os.environ.get("BENCH_SIZE", "224"))
    n_steps = int(os.environ.get("BENCH_STEPS", "10"))

    n_devices = len(jax.devices())

    def run(per_chip_bs):
        global_bs = per_chip_bs * n_devices
        comm = ct.create_communicator("jax_ici",
                                      allreduce_grad_dtype="bfloat16")
        model = Classifier(ResNet50(n_classes=1000, remat=remat,
                                    compute_dtype=jnp.bfloat16, seed=0))
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            MomentumSGD(lr=0.1, momentum=0.9), comm).setup(model)

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.normal(
            0, 1, (global_bs, 3, image_size, image_size)).astype(np.float32))
        t = jnp.asarray(rng.randint(0, 1000, global_bs).astype(np.int32))

        for _ in range(3):  # warmup: compile + 2 steady steps
            loss = opt.update(model, x, t)
        jax.block_until_ready(loss)

        start = time.perf_counter()
        for _ in range(n_steps):
            loss = opt.update(model, x, t)
        jax.block_until_ready(loss)
        elapsed = time.perf_counter() - start
        return n_steps * global_bs / elapsed

    images_per_sec = None
    last_err = None
    for bs in (per_chip_bs, per_chip_bs // 2, per_chip_bs // 4):
        if bs < 1:
            break
        try:
            images_per_sec = run(bs)
            break
        except Exception as e:  # e.g. HBM OOM at the largest batch
            last_err = e
    if images_per_sec is None:
        raise last_err
    per_chip = images_per_sec / n_devices
    baseline = 225.0  # ChainerMN-era images/sec/GPU (see module docstring)
    print(json.dumps({
        "metric": "resnet50_imagenet_train_throughput",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / baseline, 3),
    }))


if __name__ == "__main__":
    main()
