"""Span tracer + trace schema (ISSUE 14): the committed Chrome-trace
contract, the ring-buffer bounds, the off-path zero-cost pin, and the
rank-shard merge tool.

Host-only — no jit, no devices; tiny per the tier-1 budget."""

import json
import os
import sys

import pytest

from chainermn_tpu import observability as obs
from chainermn_tpu.observability import tracing


@pytest.fixture
def events_mode():
    prev = obs.set_mode("events")
    obs.reset_tracer()
    yield
    obs.set_mode(prev)
    obs.reset_tracer()


def _tools():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "..", "..", "tools"))
    import trace_merge
    return trace_merge


# -- schema validator ---------------------------------------------------------

def _ev(name="x", ph="B", ts=0, pid=0, tid=1, **kw):
    return dict({"name": name, "ph": ph, "ts": ts, "pid": pid,
                 "tid": tid}, **kw)


def test_validator_accepts_wellformed():
    events = [_ev("process_name", "M"),
              _ev("a", "B", 0), _ev("b", "B", 1), _ev("mark", "i", 2),
              _ev("b", "E", 3), _ev("a", "E", 4)]
    assert obs.validate_events(events) == 6


def test_validator_rejects_missing_key():
    bad = _ev()
    del bad["ts"]
    with pytest.raises(ValueError, match="missing key"):
        obs.validate_events([bad])


def test_validator_rejects_backwards_ts():
    with pytest.raises(ValueError, match="backwards"):
        obs.validate_events([_ev("a", "B", 5), _ev("a", "E", 3)])


def test_validator_rejects_unbalanced():
    with pytest.raises(ValueError, match="no open B"):
        obs.validate_events([_ev("a", "E", 0)])
    with pytest.raises(ValueError, match="unclosed"):
        obs.validate_events([_ev("a", "B", 0)])


def test_validator_rejects_bad_nesting():
    with pytest.raises(ValueError, match="innermost"):
        obs.validate_events([_ev("a", "B", 0), _ev("b", "B", 1),
                             _ev("a", "E", 2), _ev("b", "E", 3)])


def test_validator_separate_tracks_independent():
    events = [_ev("a", "B", 0, tid=1), _ev("b", "B", 1, tid=2),
              _ev("a", "E", 2, tid=1), _ev("b", "E", 3, tid=2)]
    assert obs.validate_events(events) == 4


# -- recording + export -------------------------------------------------------

def test_span_records_balanced_pair(events_mode):
    with obs.span("train/input_stall", tags={"k": 1}):
        pass
    evs = obs.tracer().events()
    assert [e["ph"] for e in evs] == ["B", "E"]
    assert evs[0]["name"] == evs[1]["name"] == "train/input_stall"
    assert evs[0]["args"] == {"k": 1}
    obs.validate_events(evs)


def test_instant_and_rank_epoch_tags(events_mode):
    obs.tracer().configure(rank=3, epoch=7)
    obs.instant("elastic/preempt_detect", tags={"exc": "X"})
    (ev,) = obs.tracer().events()
    assert ev["ph"] == "i" and ev["pid"] == 3
    assert ev["args"]["epoch"] == 7 and ev["args"]["exc"] == "X"


def test_complete_retroactive_span_is_valid(events_mode):
    obs.tracer().complete("serve/queue_wait", 0.001, tid=42)
    evs = obs.tracer().events()
    assert [e["ph"] for e in evs] == ["B", "E"]
    assert evs[0]["ts"] <= evs[1]["ts"]
    assert evs[0]["args"]["duration_ms"] == 1.0   # exact, un-clamped
    obs.validate_events(evs)


def test_complete_clamps_to_track_floor(events_mode):
    """A foreign-clock duration larger than the real elapsed tracer
    time (simulated engine clocks) must not reach back past earlier
    spans on the same lane — that would cross-pair B/E under LIFO
    pairing (the code-review finding).  The drawn interval clamps to
    the track's last event; the exact duration survives in args."""
    tr = obs.tracer()
    with tr.span("first", tid=7):
        pass
    tr.complete("second", duration_s=1e6, tid=7)   # "11 days waited"
    evs = tr.events()
    first_end = evs[1]["ts"]
    b2, e2 = evs[2], evs[3]
    assert b2["ts"] >= first_end                   # no overlap
    assert b2["args"]["duration_ms"] == 1e9        # truth preserved
    # ts-sorted export of the lane stays properly nested
    obs.validate_events(sorted(evs, key=lambda e: e["ts"]))


def test_ring_buffer_bounds_and_export_repair(events_mode, tmp_path):
    tr = tracing.SpanTracer(rank=0, capacity=8)
    # 6 nested B... then enough child spans to evict the outer Bs
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    assert len(tr.events()) == 8  # bounded
    path = tmp_path / "t.jsonl"
    n = tr.export(str(path))
    events = obs.read_jsonl(str(path))
    obs.validate_events(events)  # eviction damage repaired
    assert n == sum(1 for e in events if e["ph"] != "M")


def test_export_closes_unclosed_spans(events_mode, tmp_path):
    tr = obs.tracer()
    span = tr.span("left/open")
    tr.instant("mark")
    del span  # never closed
    path = tmp_path / "t.jsonl"
    tr.export(str(path))
    events = obs.read_jsonl(str(path))
    obs.validate_events(events)
    assert any(e["ph"] == "E" and e["name"] == "left/open"
               for e in events)


def test_export_writes_rank_metadata(events_mode, tmp_path):
    obs.tracer().configure(rank=2)
    obs.instant("x")
    path = tmp_path / "t.jsonl"
    obs.tracer().export(str(path))
    meta = [e for e in obs.read_jsonl(str(path)) if e["ph"] == "M"]
    assert meta and meta[0]["args"]["name"] == "rank2"
    assert meta[0]["pid"] == 2


# -- the off-path cost contract ----------------------------------------------

def test_off_is_default_and_emits_nothing():
    assert obs.mode() == "off"          # the conftest env default
    assert not obs.enabled()
    obs.reset_tracer()
    with obs.span("anything", tags={"a": 1}):
        obs.instant("nothing")
    assert obs.tracer().events() == []


def test_off_span_returns_singleton_no_alloc():
    """The committed near-zero-cost contract: every disabled span call
    returns THE module singleton, and a hot loop of span call sites
    leaves no net allocations behind."""
    assert obs.mode() == "off"
    first = obs.span("a")
    assert obs.span("b") is first is tracing._NOOP
    # warm up any lazy caches, then measure net allocated blocks: a
    # per-call-site allocation would add >= 10_000 blocks; anything in
    # the noise floor (interpreter-internal caches) stays constant
    import gc
    for _ in range(64):
        with obs.span("warm"):
            pass
    gc.collect()
    before = sys.getallocatedblocks()
    for _ in range(10_000):
        with obs.span("hot"):
            pass
    gc.collect()
    after = sys.getallocatedblocks()
    assert after - before < 100, (before, after)


def test_set_mode_rejects_unknown():
    with pytest.raises(ValueError, match="expected one of"):
        obs.set_mode("loud")


def test_full_mode_opens_named_scope(events_mode, monkeypatch):
    opened = []
    import jax

    class _Scope:
        def __init__(self, name):
            opened.append(name)

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    monkeypatch.setattr(jax, "named_scope", _Scope)
    obs.set_mode("full")
    with obs.span("train/optimizer_update"):
        pass
    assert opened == ["train.optimizer_update"]
    evs = obs.tracer().events()
    assert [e["ph"] for e in evs] == ["B", "E"]


# -- trace_merge --------------------------------------------------------------

def test_trace_merge_lossless_and_sorted(events_mode, tmp_path):
    trace_merge = _tools()
    shards = []
    for rank in (0, 1):
        tr = tracing.SpanTracer(rank=rank)
        with tr.span("train/optimizer_update"):
            tr.instant("mark", tags={"rank": rank})
        p = tmp_path / f"trace-rank{rank}.jsonl"
        tr.export(str(p))
        shards.append(str(p))
    out = tmp_path / "merged.json"
    merged = trace_merge.merge_files(shards, str(out))
    obs.validate_events(merged)
    # lossless: every shard event survives the merge
    shard_events = [e for p in shards for e in obs.read_jsonl(p)]
    key = trace_merge._dedupe_key
    assert {key(e) for e in shard_events} == {key(e) for e in merged}
    assert {e["pid"] for e in merged} == {0, 1}
    # the written file is a Perfetto-loadable JSON array
    loaded = json.loads(out.read_text())
    assert loaded == merged
    # non-meta events are ts-sorted
    ts = [e["ts"] for e in merged if e["ph"] != "M"]
    assert ts == sorted(ts)


def test_trace_merge_preserves_same_key_events_within_shard(tmp_path):
    """Two DISTINCT back-to-back sub-microsecond spans can share the
    full (pid, tid, ts, ph, name) key inside one shard — dedupe is
    cross-shard only (review finding: intra-shard dedupe orphaned an E
    and refused a valid shard)."""
    trace_merge = _tools()
    shard = [_ev("s", "B", 100), _ev("s", "E", 100),
             _ev("s", "B", 100), _ev("s", "E", 101)]
    obs.validate_events(shard)                       # valid as written
    merged = trace_merge.merge_events([shard])
    assert len(merged) == 4                          # lossless
    obs.validate_events(merged)
    # and the cross-shard dedupe still collapses a double-read shard
    assert len(trace_merge.merge_events([shard, list(shard)])) == 4


def test_trace_merge_dedupes_reexported_shard(events_mode, tmp_path):
    trace_merge = _tools()
    tr = tracing.SpanTracer(rank=0)
    with tr.span("s"):
        pass
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    tr.export(str(a))
    tr.export(str(b))   # the same ring exported twice
    merged = trace_merge.merge_events([obs.read_jsonl(str(a)),
                                       obs.read_jsonl(str(b))])
    assert len(merged) == len(obs.read_jsonl(str(a)))


def test_trace_merge_cli_refuses_invalid(tmp_path):
    trace_merge = _tools()
    bad = tmp_path / "bad.jsonl"
    ev = _ev("a", "B", 0)
    del ev["ts"]   # genuinely malformed — repair cannot fix this
    bad.write_text(json.dumps(ev) + "\n")
    rc = trace_merge.main([str(bad), "-o", str(tmp_path / "out.json")])
    assert rc == 1
    assert not (tmp_path / "out.json").exists()


def test_trace_merge_checkpoint_plus_exit_export(events_mode, tmp_path):
    """A mid-run export (open span closed with a synthetic E) merged
    with the exit export (the real E, later ts) must succeed — the
    orphaned synthetic-vs-real E pair is repaired, not refused (the
    code-review repro)."""
    trace_merge = _tools()
    tr = obs.tracer()
    span = tr.span("train/run")
    p1 = tmp_path / "ckpt.jsonl"
    tr.export(str(p1))           # closes train/run synthetically
    span.close()                 # the real E, later ts
    p2 = tmp_path / "exit.jsonl"
    tr.export(str(p2))
    merged = trace_merge.merge_files([str(p1), str(p2)],
                                     str(tmp_path / "m.json"))
    obs.validate_events(merged)
    pairs = [e for e in merged if e["name"] == "train/run"]
    assert [e["ph"] for e in pairs] == ["B", "E"]
