"""End-to-end observability acceptance (ISSUE 14): seeded runs of the
three subsystems each produce a schema-valid Chrome-trace shard whose
span names cover the committed taxonomy, the rank shards merge
losslessly, the metrics registry carries the committed scheduler/
trainer/supervisor metrics — and with the DEFAULT off mode the same
runs emit nothing.

Kept tiny (tier-1 budget): one MLP trainer compile shared across the
iterator-contract grid, one 1-layer transformer engine, and the
scripted-membership elastic arc at MLP scale."""

import os
import sys

import numpy as np
import pytest

import jax

import chainermn_tpu as ct
from chainermn_tpu import observability as obs
from chainermn_tpu.core.optimizer import MomentumSGD
from chainermn_tpu.dataset import (MultithreadIterator, SerialIterator,
                                   TupleDataset)
from chainermn_tpu.models import MLP, Classifier, TransformerLM
from chainermn_tpu.training import FusedUpdater, StandardUpdater, Trainer


@pytest.fixture
def events_mode():
    prev = obs.set_mode("events")
    obs.reset_tracer()
    obs.reset_registry()
    yield
    obs.set_mode(prev)
    obs.reset_tracer()
    obs.reset_registry()


def _span_names(events):
    return {e["name"] for e in events if e["ph"] in ("B", "i")}


def _data(n=32, d=12, k=3, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.normal(0, 1, (n, d)).astype(np.float32),
            rng.randint(0, k, n).astype(np.int32))


def _trainer(tmp_path, iterator, n_iter=3, with_checkpoint=True,
             updater_cls=StandardUpdater, **updater_kw):
    comm = ct.create_communicator("flat")
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.05), comm).setup(model)
    trainer = Trainer(updater_cls(iterator, opt, **updater_kw),
                      (n_iter, "iteration"), out=str(tmp_path))
    if with_checkpoint:
        cp = ct.create_multi_node_checkpointer(comm, name="obs",
                                               path=str(tmp_path))
        trainer.extend(cp, trigger=(2, "iteration"))
    return trainer


# -- acceptance: the 3-step trainer run --------------------------------------

def test_trainer_run_produces_schema_valid_trace(events_mode, tmp_path):
    x, t = _data()
    it = SerialIterator(TupleDataset(x, t), 8, shuffle=False)
    _trainer(tmp_path / "out", it).run()
    shard = tmp_path / "out" / "trace-rank0.jsonl"
    assert shard.exists()   # auto-exported by Trainer.run
    events = obs.read_jsonl(str(shard))
    obs.validate_events(events)
    names = _span_names(events)
    # the committed trainer-phase taxonomy (docs/observability.md)
    assert {"train/input_stall", "train/optimizer_update",
            "train/grad_exchange/bucket0",
            "train/checkpoint_serialize"} <= names, names
    # rank-tagged: every event carries the communicator's rank lane
    assert {e["pid"] for e in events} == {0}
    # and the registry carries the per-bucket exchange counter
    c = obs.registry().get(
        "chainermn_tpu_grad_exchange_payload_bytes_total")
    assert c is not None and c.value(bucket="0", exchange="flat") > 0


def test_trainer_run_off_emits_nothing(tmp_path):
    assert obs.mode() == "off"
    obs.reset_tracer()
    obs.reset_registry()
    x, t = _data()
    it = SerialIterator(TupleDataset(x, t), 8, shuffle=False)
    _trainer(tmp_path / "out", it).run()
    assert not (tmp_path / "out" / "trace-rank0.jsonl").exists()
    assert obs.tracer().events() == []
    assert obs.registry().metrics() == {}


# -- satellite: the universal input-stall counter ----------------------------

def test_input_stall_counter_every_iterator_kind_both_updaters(
        events_mode, tmp_path):
    """The contract the satellite pins: EVERY iterator kind, on BOTH
    updater paths, feeds chainermn_tpu_input_stall_ms_total — the
    accounting iterator (DevicePrefetchIterator) through its own
    stall meter, the rest through the next() wall clock."""
    from chainermn_tpu.dataset.iterators import DevicePrefetchIterator
    from chainermn_tpu.dataset.multiprocess_iterator import \
        MultiprocessIterator
    x, t = _data()

    def kinds():
        ds = TupleDataset(x, t)
        yield "SerialIterator", SerialIterator(ds, 8, shuffle=False)
        yield "MultithreadIterator", MultithreadIterator(
            ds, 8, shuffle=False, n_threads=2)
        yield "MultiprocessIterator", MultiprocessIterator(
            ds, 8, shuffle=False, n_processes=2)
        yield "DevicePrefetchIterator", DevicePrefetchIterator(
            SerialIterator(ds, 8, shuffle=False))

    for name, it in kinds():
        _trainer(tmp_path / f"std-{name}", it, n_iter=2,
                 with_checkpoint=False).run()
    # the fused path (update_scan) once — a second compile, so one kind
    it = SerialIterator(TupleDataset(x, t), 8, shuffle=False)
    _trainer(tmp_path / "fused", it, n_iter=2, with_checkpoint=False,
             updater_cls=FusedUpdater, n_fused=2).run()

    counter = obs.registry().get("chainermn_tpu_input_stall_ms_total")
    assert counter is not None
    labels = [dict(k) for k in counter.labels()]
    kinds_seen = {(l["iterator"], l["updater"]) for l in labels}
    assert {("SerialIterator", "StandardUpdater"),
            ("MultithreadIterator", "StandardUpdater"),
            ("MultiprocessIterator", "StandardUpdater"),
            ("DevicePrefetchIterator", "StandardUpdater"),
            ("SerialIterator", "FusedUpdater")} <= kinds_seen, kinds_seen
    for l in labels:
        assert counter.value(**l) >= 0


# -- acceptance: the serving request lifecycle -------------------------------

def _engine(prefix_cache=False, num_pages=16, **kw):
    from chainermn_tpu.serving import ServingEngine
    lm = TransformerLM(n_vocab=64, d_model=32, n_heads=2, n_layers=1,
                       max_len=64, seed=0)
    return ServingEngine(lm, num_pages=num_pages, page_size=8,
                         max_batch=2, max_context=32,
                         prefix_cache=prefix_cache, **kw)


def test_serving_request_lifecycle_trace(events_mode, tmp_path):
    from chainermn_tpu.serving import Request
    eng = _engine()
    rng = np.random.RandomState(0)
    req = Request(rng.randint(0, 64, 6), max_new_tokens=3,
                  arrival_time=0.0)
    eng.submit(req)
    step = 0
    while eng.running or eng.scheduler.pending():
        eng.step(now=float(step))
        step += 1
    assert len(req.tokens) == 3   # admit -> prefill -> 2 decode steps
    shard = tmp_path / "trace-rank0.jsonl"
    obs.tracer().export(str(shard))
    events = obs.read_jsonl(str(shard))
    obs.validate_events(events)
    names = _span_names(events)
    assert {"serve/queue_wait", "serve/prefill", "serve/decode_window",
            "serve/finish"} <= names, names
    # lifecycle spans ride the request's own lane; decode windows the
    # engine thread's
    req_lane = [e for e in events
                if e.get("tid") == 1 + req.request_id]
    assert {"serve/queue_wait", "serve/prefill", "serve/finish"} <= \
        _span_names(req_lane)
    # scheduler health metrics (satellite)
    reg = obs.registry()
    h = reg.get("chainermn_tpu_serving_queue_wait_ms")
    assert h is not None and h.value(tenant="default")[2] == 1
    g = reg.get("chainermn_tpu_serving_queue_depth")
    assert g is not None and g.value(tenant="default") == 0
    assert req.admit_time is not None


def test_serving_non_int_request_id_safe():
    """Request ids are caller-supplied and only ever dict keys — a
    string id must not crash the engine (the code-review finding:
    `_req_tid` used int()), trace off or on."""
    from chainermn_tpu.serving import Request
    prev = obs.set_mode("off")
    obs.reset_tracer()
    try:
        for mode in ("off", "events"):
            obs.set_mode(mode)
            eng = _engine()
            req = Request(np.arange(1, 7, dtype=np.int32),
                          max_new_tokens=2,
                          request_id=f"req-{mode}", arrival_time=0.0)
            eng.submit(req)
            step = 0
            while eng.running or eng.scheduler.pending():
                eng.step(now=float(step))
                step += 1
            assert len(req.tokens) == 2
        # deterministic synthetic lane for the string id
        assert eng._req_tid(req) == eng._req_tid(req) > 0
    finally:
        obs.set_mode(prev)
        obs.reset_tracer()
        obs.reset_registry()


def test_readmitted_request_queue_wait_measured_from_last_admission(
        events_mode):
    """Eviction + re-admit emits a SECOND queue_wait span measured from
    the previous admission (tagged readmit), never a re-span of the
    original arrival window overlapping the first (review finding)."""
    from chainermn_tpu.serving import Request
    eng = _engine(num_pages=4)   # 4 pages of 8: forces eviction at 2 seqs
    a = Request(np.arange(1, 9, dtype=np.int32), max_new_tokens=12,
                arrival_time=0.0)
    b = Request(np.arange(11, 19, dtype=np.int32), max_new_tokens=12,
                arrival_time=0.0)
    eng.submit(a)
    eng.submit(b)
    step = 0
    while (eng.running or eng.scheduler.pending()) and step < 80:
        eng.step(now=float(step))
        step += 1
    assert eng.evictions >= 1
    waits = [e for e in obs.tracer().events()
             if e["ph"] == "B" and e["name"] == "serve/queue_wait"]
    readmits = [e for e in waits if e["args"].get("readmit")]
    assert readmits, "re-admission emitted no tagged queue_wait span"
    # measured from the EVICTION's requeue stamp, not the original
    # arrival / prior admission: the step clock ticks 1s per step, so
    # a wait spanning the victim's whole running period would be many
    # seconds — the true re-queue dwell is a couple of steps
    for e in readmits:
        assert e["args"]["duration_ms"] <= 3000, e["args"]
    # the whole ring still exports schema-valid
    obs.validate_events(sorted(obs.tracer().events(),
                               key=lambda e: e["ts"]))


def test_serving_eviction_and_suffix_prefill_metrics(events_mode):
    """Eviction counters + the prefix-hit suffix-prefill span: two
    same-prefix requests on a pool sized to force an eviction."""
    from chainermn_tpu.serving import Request
    eng = _engine(prefix_cache=True, num_pages=6)
    rng = np.random.RandomState(0)
    prefix = rng.randint(0, 64, 8)
    a = Request(np.concatenate([prefix, rng.randint(0, 64, 4)]),
                max_new_tokens=4, arrival_time=0.0)
    b = Request(np.concatenate([prefix, rng.randint(0, 64, 4)]),
                max_new_tokens=4, arrival_time=0.0)
    eng.submit(a)
    eng.submit(b)
    step = 0
    while (eng.running or eng.scheduler.pending()) and step < 60:
        eng.step(now=float(step))
        step += 1
    names = _span_names(obs.tracer().events())
    assert eng.prefix_hits >= 1
    assert "serve/suffix_prefill" in names, names
    reg = obs.registry()
    if eng.evictions:
        assert "serve/evict" in names
        assert reg.get("chainermn_tpu_serving_evictions_total") \
            .value(tenant="default") == eng.evictions
    if eng.forks:
        assert reg.get("chainermn_tpu_serving_forks_total").value() \
            == eng.forks


# -- acceptance: the elastic shrink/regrow timeline --------------------------

def test_elastic_shrink_regrow_timeline(events_mode, tmp_path):
    """The scripted-membership supervisor arc (the ISSUE 10 harness)
    with tracing on: preempt detect -> resolve -> rebuild -> snapshot
    sync all appear, rank/epoch tags follow the resizes, and
    FailureRecovery.stats lands in the registry as gauges."""
    from tests.resilience_tests.test_elastic import (
        _elastic_trainer, _ScriptedMembership, _subset_factory)
    from chainermn_tpu.communicators import FaultSchedule

    split = {(0,): 2, (0, 1): 4}
    sched = FaultSchedule([dict(op="bcast_obj", nth=7)], seed=0)
    membership = _ScriptedMembership(views=[(0,), (0, 1)])
    trainer, model, opt, rec = _elastic_trainer(
        tmp_path / "el", sched, membership, _subset_factory(split))
    orig_resolve = membership.resolve

    def resolve(expect=None, timeout_ms=None):
        v = orig_resolve(expect, timeout_ms)
        if v.members == (0,):
            membership.joins = (1,)
        return v
    membership.resolve = resolve

    trainer.run()
    assert rec.stats["resizes"] == 2

    shard = tmp_path / "el" / "trace-rank0.jsonl"
    assert shard.exists()
    events = obs.read_jsonl(str(shard))
    obs.validate_events(events)
    names = _span_names(events)
    assert {"elastic/preempt_detect", "elastic/resolve",
            "elastic/rebuild", "elastic/snapshot_sync",
            "recover/consensus_load", "recover/quiesce",
            "train/optimizer_update"} <= names, names
    # epoch tags advance with the rebuilt incarnations
    epochs = {e["args"]["epoch"] for e in events
              if e.get("args", {}).get("epoch") is not None}
    assert {1, 2} <= epochs, epochs
    # FailureRecovery.stats folded into the registry (tentpole item c)
    reg = obs.registry()
    assert reg.get("chainermn_tpu_recovery_resizes").value() == 2
    assert reg.get("chainermn_tpu_recovery_ranks_lost").value() == 1
    assert reg.get("chainermn_tpu_recovery_ranks_joined").value() == 1
    assert reg.get("chainermn_tpu_recovery_recoveries").value() >= 1


# -- PROBE=obs + bench fingerprint fences ------------------------------------

def test_probe_obs_renders_merged_registry(events_mode, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "..", "..", "tools"))
    import probe_perf
    probe_perf.probe_obs()
    out = capsys.readouterr().out
    import json
    rows = [json.loads(l) for l in out.strip().split("\n")]
    head = [r for r in rows if r.get("probe") == "obs"]
    assert head and head[0]["schema_valid"]
    assert "serve/decode_window" in head[0]["span_counts"]
    assert "train/optimizer_update" in head[0]["span_counts"]
    prom = [r["line"] for r in rows if r.get("probe") == "obs_prometheus"]
    assert any(l.startswith("# TYPE chainermn_tpu_input_stall_ms_total")
               for l in prom)
    assert any("chainermn_tpu_serving_queue_wait_ms_count" in l
               for l in prom)


def test_bench_fingerprint_fences_traced_runs(monkeypatch):
    """CHAINERMN_TPU_TRACE=off (the default) leaves the flagship
    fingerprint unchanged; a traced run can never be flagship-cacheable
    (its numbers stamp the overhead delta, recovery-queue item 8)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "..", ".."))
    import bench
    monkeypatch.delenv("CHAINERMN_TPU_TRACE", raising=False)
    for model in ("resnet50", "transformer"):
        assert bench._config_fingerprint(model) \
            == bench._DEFAULT_FINGERPRINTS[model]
    monkeypatch.setenv("CHAINERMN_TPU_TRACE", "events")
    for model in ("resnet50", "transformer"):
        fp = bench._config_fingerprint(model)
        assert fp["trace"] == "events"
        assert fp != bench._DEFAULT_FINGERPRINTS[model]
        # legacy cached entries (no trace key) backfill to the default
        legacy = {k: v for k, v in
                  bench._DEFAULT_FINGERPRINTS[model].items()
                  if k != "trace"}
        assert bench._backfill_fp(model, legacy)["trace"] == "off"
