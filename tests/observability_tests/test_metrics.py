"""Metrics registry (ISSUE 14): counters/gauges/histograms, the
cross-rank merge semantics, and the Prometheus text rendering.

Host-only — no jit, no devices."""

import pytest

from chainermn_tpu.observability import (DEFAULT_TIME_BUCKETS_MS,
                                         MetricsRegistry)
from chainermn_tpu.observability import metrics as metrics_mod


def test_counter_inc_and_labels():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", help="requests")
    c.inc()
    c.inc(2, tenant="a")
    c.inc(3, tenant="a")
    assert c.value() == 1
    assert c.value(tenant="a") == 5
    with pytest.raises(ValueError, match="negative"):
        c.inc(-1)


def test_counter_get_or_create_idempotent_and_kind_clash():
    reg = MetricsRegistry()
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("x")


def test_gauge_set():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(4, tenant="a")
    g.set(2, tenant="a")
    assert g.value(tenant="a") == 2


def test_histogram_buckets_sum_count_percentile():
    reg = MetricsRegistry()
    h = reg.histogram("wait_ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 5.0, 50.0):
        h.observe(v)
    counts, total, n = h.value()
    assert counts == [1, 2, 1, 0] and total == 60.5 and n == 4
    assert h.percentile(50) == 10.0
    assert h.percentile(99) == 100.0
    h.observe(1e9)
    assert h.percentile(100) == float("inf")
    assert reg.histogram("empty").percentile(50) is None


def test_histogram_rejects_unsorted_bounds():
    with pytest.raises(ValueError, match="sorted"):
        MetricsRegistry().histogram("h", buckets=(10.0, 1.0))


def test_merge_counters_sum_histograms_add_gauges_rank_label():
    a, b = MetricsRegistry(), MetricsRegistry()
    for reg, v in ((a, 1), (b, 2)):
        reg.counter("c").inc(v)
        reg.gauge("g").set(v, tenant="t")
        reg.histogram("h", buckets=(1.0, 10.0)).observe(v)
    merged = MetricsRegistry()
    merged.merge_dict(a.to_dict(), rank=0)
    merged.merge_dict(b.to_dict(), rank=1)
    assert merged.get("c").value() == 3
    # gauges keep per-rank identity
    assert merged.get("g").value(tenant="t", rank="0") == 1
    assert merged.get("g").value(tenant="t", rank="1") == 2
    counts, total, n = merged.get("h").value()
    assert counts == [1, 1, 0] and total == 3 and n == 2


def test_merge_rejects_mismatched_histogram_bounds():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("h", buckets=(1.0,)).observe(0.5)
    b.histogram("h", buckets=(2.0,)).observe(0.5)
    merged = MetricsRegistry()
    merged.merge_dict(a.to_dict(), rank=0)
    with pytest.raises(ValueError, match="differ"):
        merged.merge_dict(b.to_dict(), rank=1)


def test_merge_across_rides_object_collectives():
    from chainermn_tpu.communicators import DummyCommunicator
    reg = MetricsRegistry()
    reg.counter("c").inc(7)
    merged = reg.merge_across(DummyCommunicator())
    assert merged.get("c").value() == 7


def test_label_key_roundtrip():
    key = (("a", "1"), ("b", "x y"))
    assert metrics_mod.unjson_key(metrics_mod.json_key(key)) == key
    assert metrics_mod.unjson_key(metrics_mod.json_key(())) == ()


def test_prometheus_text_format():
    reg = MetricsRegistry()
    reg.counter("c_total", help="the c").inc(2, tenant="a")
    reg.gauge("g").set(1.5)
    reg.histogram("h_ms", buckets=(1.0, 10.0)).observe(0.5)
    text = reg.to_prometheus()
    lines = text.strip().split("\n")
    assert "# HELP c_total the c" in lines
    assert "# TYPE c_total counter" in lines
    assert 'c_total{tenant="a"} 2' in lines
    assert "# TYPE g gauge" in lines
    assert "g 1.5" in lines
    assert "# TYPE h_ms histogram" in lines
    assert 'h_ms_bucket{le="1.0"} 1' in lines
    assert 'h_ms_bucket{le="+Inf"} 1' in lines
    assert "h_ms_sum 0.5" in lines
    assert "h_ms_count 1" in lines


def test_default_buckets_sorted():
    assert list(DEFAULT_TIME_BUCKETS_MS) == sorted(DEFAULT_TIME_BUCKETS_MS)


def test_prometheus_escapes_hostile_label_values():
    """Label values are caller-supplied (tenant names) — quotes,
    backslashes, and newlines must be escaped per the text exposition
    format, or one hostile tenant breaks/forges the whole scrape."""
    reg = MetricsRegistry()
    reg.counter("c").inc(1, tenant='a"b\\c\nd')
    (line,) = [l for l in reg.to_prometheus().splitlines()
               if not l.startswith("#")]
    assert line == 'c{tenant="a\\"b\\\\c\\nd"} 1'
    assert "\n" not in line
