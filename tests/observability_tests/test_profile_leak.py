"""Profile extension trace-leak regression (ISSUE 14 satellite).

The leak: a run that ends — or raises — inside the [start, start +
n_steps) capture window used to depend on every OTHER extension's
``finalize`` succeeding before Profile's ran; one failing finalizer
earlier in the fan-out left ``jax.profiler.start_trace`` open forever.
Pinned here: ``on_error`` stops the trace at the failure itself,
``Trainer.run`` exception-isolates the finalize fan-out, and ``_stop``
is idempotent and never masks the original exception."""

import pytest

import jax

from chainermn_tpu.training import Trainer
from chainermn_tpu.training.trainer import Extension
from chainermn_tpu.training.updaters import Updater
from chainermn_tpu.utils.profiling import Profile


class _FakeProfiler:
    def __init__(self):
        self.active = False
        self.starts = 0
        self.stops = 0

    def start_trace(self, log_dir):
        assert not self.active, "start_trace while already tracing"
        self.active = True
        self.starts += 1

    def stop_trace(self):
        assert self.active, "stop_trace with no active trace"
        self.active = False
        self.stops += 1


@pytest.fixture
def profiler(monkeypatch):
    fake = _FakeProfiler()
    monkeypatch.setattr(jax, "profiler", fake)
    return fake


class _StubUpdater(Updater):
    def __init__(self, fail_at=None):
        self.iteration = 0
        self.fail_at = fail_at

    def connect_trainer(self, trainer):
        pass

    def get_all_optimizers(self):
        return {}

    def update(self):
        self.iteration += 1
        if self.fail_at is not None and self.iteration == self.fail_at:
            raise RuntimeError("boom")

    def finalize(self):
        pass

    def serialize(self, serializer):
        pass


class _HostileFinalize(Extension):
    priority = 500  # finalizes BEFORE Profile (higher priority first)

    def __call__(self, trainer):
        pass

    def finalize(self):
        raise ValueError("hostile finalize")


def test_run_ends_inside_window_trace_stopped(profiler, tmp_path):
    trainer = Trainer(_StubUpdater(), (3, "iteration"),
                      out=str(tmp_path))
    trainer.extend(Profile(start=1, n_steps=10))
    trainer.run(show_loop_exception_msg=False)
    assert profiler.starts == 1
    assert not profiler.active, "trace leaked past a pre-window-end run"


def test_raise_inside_window_trace_stopped(profiler, tmp_path):
    trainer = Trainer(_StubUpdater(fail_at=2), None, out=str(tmp_path))
    trainer.extend(Profile(start=1, n_steps=10))
    with pytest.raises(RuntimeError, match="boom"):
        trainer.run(show_loop_exception_msg=False)
    assert profiler.starts == 1
    assert not profiler.active, "trace leaked past the raise"


def test_hostile_finalize_cannot_starve_profile_stop(profiler, tmp_path,
                                                     capsys):
    """THE regression: another extension's failing finalize used to
    abort the fan-out before Profile's finalize ran.  The trainer now
    isolates each finalizer; the first finalize failure is still
    re-raised (a clean run must not swallow it) AFTER everyone's
    cleanup ran."""
    trainer = Trainer(_StubUpdater(), (3, "iteration"),
                      out=str(tmp_path))
    trainer.extend(_HostileFinalize())
    trainer.extend(Profile(start=1, n_steps=10))
    with pytest.raises(ValueError, match="hostile finalize"):
        trainer.run(show_loop_exception_msg=False)
    assert not profiler.active, "hostile finalize starved Profile._stop"
    assert "hostile finalize" in capsys.readouterr().err


def test_updater_finalize_isolated_too(profiler, tmp_path):
    """Review finding: a failing updater.finalize must neither swallow
    a captured extension-finalize exception nor skip later cleanup."""
    class _HostileUpdater(_StubUpdater):
        def finalize(self):
            raise OSError("updater cleanup failed")

    trainer = Trainer(_HostileUpdater(), (3, "iteration"),
                      out=str(tmp_path))
    trainer.extend(_HostileFinalize())
    trainer.extend(Profile(start=1, n_steps=10))
    # the FIRST finalize failure (the extension's) is the one re-raised
    with pytest.raises(ValueError, match="hostile finalize"):
        trainer.run(show_loop_exception_msg=False)
    assert not profiler.active


def test_loop_exception_wins_over_finalize_exception(profiler, tmp_path):
    """When the loop is already unwinding with the real failure, a
    finalize failure must not REPLACE it."""
    trainer = Trainer(_StubUpdater(fail_at=1), None, out=str(tmp_path))
    trainer.extend(_HostileFinalize())
    trainer.extend(Profile(start=1, n_steps=10))
    with pytest.raises(RuntimeError, match="boom"):
        trainer.run(show_loop_exception_msg=False)
    assert not profiler.active


def test_stop_is_idempotent_and_never_masks(profiler, tmp_path):
    p = Profile(start=0, n_steps=5)
    profiler.start_trace("x")
    p._active = True
    p._stop()
    p._stop()   # second stop: no error, no double stop_trace
    assert profiler.stops == 1

    class _Wedged:
        def stop_trace(self):
            raise RuntimeError("profiler wedged")

    p2 = Profile()
    p2._active = True
    monkey = jax.profiler
    try:
        jax.profiler = _Wedged()
        with pytest.warns(UserWarning, match="wedged"):
            p2._stop()   # swallowed into a warning, _active cleared
        assert not p2._active
    finally:
        jax.profiler = monkey
