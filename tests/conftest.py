"""Test harness configuration.

Multi-chip behavior is tested on a simulated 8-device CPU mesh
(SURVEY.md §4: the TPU analog of the reference's ``mpiexec -n N`` on one
host).

Environment subtlety: the axon sitecustomize imports jax at interpreter
startup with ``JAX_PLATFORMS=axon`` (one real TPU chip via a tunnel), so
env vars set here are too late — ``jax.config.update`` is the reliable
lever, and ``XLA_FLAGS`` still applies because the CPU backend reads it
at first initialization (which happens after this file runs).
"""

import os

import jax

os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 "
    # tier-1 is a correctness tier on (often single-vCPU) CI: the CPU
    # backend's O2/LLVM pipeline buys nothing we assert on and costs
    # ~40% of suite wall time in compiles.  Parity tests compare runs
    # compiled under the SAME flags, so self-consistency is untouched;
    # explicitly-set XLA_FLAGS still win (later flags override).
    "--xla_backend_optimization_level=0 "
    + os.environ.get("XLA_FLAGS", ""))
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Tests target current jax (`jax.shard_map`, check_vma=); older installs
# ship it under jax.experimental with the pre-rename check_rep= kwarg.
# Route through the same compat shim the framework uses.
if not hasattr(jax, "shard_map"):
    from chainermn_tpu.utils.compat import shard_map
    jax.shard_map = shard_map
