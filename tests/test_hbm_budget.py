"""Byte-budget regression gate (ISSUE 3: "accounting that can't rot").

The committed budgets in tools/hbm_budgets.json are XLA HloCostAnalysis
``bytes accessed`` over the LOWERED (backend-neutral) flagship train
step — a property of the program the framework emits, identical on every
backend.  A future PR that inflates the step's byte bill past the
~2% headroom fails here and must either fix the regression or
consciously re-commit the budget.  Fast: lowering only, no backend
codegen, no execution.
"""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import probe_perf  # noqa: E402


def _measure(bs, size):
    return probe_perf.measure_hbm_bytes(bs, size, "NHWC", donate=True,
                                        do_compile=False)


def test_small_proxy_within_budget():
    budgets = probe_perf.load_hbm_budgets()
    key = probe_perf.hbm_budget_key(4, 64, "NHWC")
    assert key in budgets, "commit a budget row for the proxy config"
    row = _measure(4, 64)
    assert row["bytes_accessed"] > 0
    assert row["bytes_accessed"] <= budgets[key]["budget_bytes_accessed"], (
        f"byte budget regression: {row['bytes_accessed']} > "
        f"{budgets[key]['budget_bytes_accessed']} — the step program now "
        "moves more bytes than the committed budget; fix the regression "
        "or re-commit tools/hbm_budgets.json with justification "
        f"(category table: {row['bytes_by_category']})")


def test_flagship_within_budget_and_reduced_vs_pre_pr():
    budgets = probe_perf.load_hbm_budgets()
    key = probe_perf.hbm_budget_key(64, 224, "NHWC")
    entry = budgets.get(key)
    assert entry, "commit a budget row for the flagship config"
    row = _measure(64, 224)
    assert row["bytes_accessed"] <= entry["budget_bytes_accessed"], (
        f"flagship byte budget regression: {row['bytes_accessed']} > "
        f"{entry['budget_bytes_accessed']} "
        f"(category table: {row['bytes_by_category']})")
    # the acceptance bar this PR committed to: ≥10% below the pre-PR bill
    pre = entry["pre_pr_bytes_accessed"]
    assert row["bytes_accessed"] <= 0.9 * pre, (
        f"flagship bytes {row['bytes_accessed']} no longer ≥10% below the "
        f"pre-PR bill {pre}")
    # the select-and-scatter maxpool backward must stay gone
    assert row["bytes_by_category"].get("pooling_bwd", 0) == 0


def test_category_parser_on_known_program():
    import jax.numpy as jnp
    from jax import lax

    def f(x, w):
        y = lax.conv_general_dilated(x, w, (1, 1), "SAME",
                                     dimension_numbers=("NCHW", "OIHW",
                                                        "NCHW"))
        y = jnp.maximum(y, 0)
        return lax.reduce_window(y, -jnp.inf, lax.max, (1, 1, 2, 2),
                                 (1, 1, 2, 2), [(0, 0)] * 4).sum()

    x = jnp.ones((1, 2, 8, 8), jnp.float32)
    w = jnp.ones((2, 2, 3, 3), jnp.float32)
    text = jax.jit(f).lower(x, w).as_text()
    cats = probe_perf.stablehlo_bytes_by_category(text)
    # conv: x + w + y accesses
    conv_expected = (1 * 2 * 8 * 8 + 2 * 2 * 3 * 3 + 1 * 2 * 8 * 8) * 4
    assert cats["conv"] == conv_expected
    # reduce_window (multi-line region op): y + init + pooled accesses
    pool_expected = (2 * 8 * 8 + 1 + 2 * 4 * 4) * 4
    assert cats["pooling"] == pool_expected
    assert cats["elementwise"] > 0


def test_grad_program_categorizes_select_and_scatter(monkeypatch):
    import chainermn_tpu.nn.functions as F
    import jax.numpy as jnp

    monkeypatch.setattr(F, "_MAXPOOL_VJP", "xla")
    grad = jax.grad(lambda a: jnp.sum(F.max_pooling_2d(a, 2, 2, 0)))
    text = jax.jit(grad).lower(jnp.ones((1, 1, 8, 8), jnp.float32)).as_text()
    cats = probe_perf.stablehlo_bytes_by_category(text)
    assert cats.get("pooling_bwd", 0) > 0, \
        "select_and_scatter should be attributed to pooling_bwd"
