"""Artifact-integrity tests for the bench harness's last-good cache.

Round-3 postmortem (VERDICT r3 Missing #1): a 32×32/bs-2 CPU smoke run
persisted by a harness test was re-emitted under the headline
``resnet50_imagenet_train_throughput`` metric when the TPU relay wedged.
The cache is now gated by a config fingerprint on BOTH ends: persistence
(``_emit``) and stale re-emission (``_emit_stale_or_error``).

Pure host-side logic — no jax import, no device touch.
"""

import json
import os

import pytest

import bench


TPU_RESULT = {
    "metric": "resnet50_imagenet_train_throughput",
    "value": 1390.0, "unit": "images/sec/chip", "vs_baseline": 6.18,
    "platform": "axon", "device_kind": "TPU v5 lite", "n_devices": 1,
    "per_chip_batch": 64, "image_size": 224, "layout": "NHWC",
    "compile_s": 109.0,
}

CPU_SMOKE = {
    "metric": "resnet50_imagenet_train_throughput",
    "value": 3.33, "unit": "images/sec/chip", "vs_baseline": 0.015,
    "platform": "cpu", "device_kind": "cpu", "n_devices": 1,
    "per_chip_batch": 2, "image_size": 32, "layout": "NHWC",
    "compile_s": 5.9,
}


@pytest.fixture
def cache_path(tmp_path, monkeypatch):
    path = str(tmp_path / "last_bench.json")
    monkeypatch.setattr(bench, "_CACHE_PATH", path)
    # the repo-committed fallback slot must not leak real flagship data
    # into tests (or test payloads into the committed file)
    monkeypatch.setattr(bench, "_REPO_CACHE_PATH",
                        str(tmp_path / "repo_last_bench.json"))
    # _emit marks the XLA cache warm on successful accelerator results;
    # a test's fake axon payload must not plant the real sentinel (it
    # would shrink the driver's genuine first-contact deadline)
    monkeypatch.setattr(bench, "_PREWARM_SENTINEL_BASE",
                        str(tmp_path / "prewarmed"))
    # isolate the bench-start stamp: a real bench starting during the
    # test session must not mark test emissions contended
    monkeypatch.setattr(bench, "_START_STAMP",
                        str(tmp_path / "started"))
    return path


def _last_line(capsys):
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


def _warm(model="resnet50"):
    """Stamp the (tmp-redirected) prewarm sentinel: the stale-serving
    scenarios model a WARM environment — an earlier run succeeded and
    cached its datum.  Without the sentinel the run is first contact,
    where the stale re-serve is refused by design (ISSUE 5 satellite:
    three straight rounds of first-contact stale re-serves)."""
    with open(bench._prewarm_sentinel(model), "w") as f:
        f.write("warm 0\n")


def test_cacheable_accepts_only_default_config_accelerator_runs():
    assert bench._cacheable(TPU_RESULT)
    assert not bench._cacheable(CPU_SMOKE)
    assert not bench._cacheable({**TPU_RESULT, "platform": "cpu"})
    assert not bench._cacheable({**TPU_RESULT, "platform": "cpu_fallback"})
    assert not bench._cacheable({**TPU_RESULT, "image_size": 32})
    assert not bench._cacheable({**TPU_RESULT, "per_chip_batch": 2})
    assert not bench._cacheable({**TPU_RESULT, "per_chip_batch": 256})
    assert not bench._cacheable({**TPU_RESULT, "value": None})
    assert not bench._cacheable({**TPU_RESULT, "stale": True})
    assert not bench._cacheable({**TPU_RESULT, "error": "boom"})
    # payload sanity: non-flagship layout / fused-dispatch numbers are a
    # different measurement regime (planted/legacy-cache defense)
    assert not bench._cacheable({**TPU_RESULT, "layout": "NCHW"})
    assert not bench._cacheable({**TPU_RESULT,
                                 "fused_steps_per_dispatch": 8})


def test_cacheable_rejects_nondefault_requested_config(monkeypatch):
    """The recovery queue's variant runs (BENCH_BS=256, BENCH_SCAN=8,
    BENCH_LAYOUT=NCHW, BENCH_SEQ=8192 ...) must never persist under the
    flagship metric, even when the payload looks plausible — the env
    fingerprint covers every knob, including ones the payload omits."""
    for knob, value in [("BENCH_BS", "256"), ("BENCH_SCAN", "8"),
                        ("BENCH_LAYOUT", "NCHW"), ("BENCH_REMAT", "1"),
                        ("BENCH_SIZE", "32")]:
        monkeypatch.setenv(knob, value)
        assert not bench._cacheable(TPU_RESULT), knob
        monkeypatch.delenv(knob)
    assert bench._cacheable(TPU_RESULT)


def test_cacheable_transformer_needs_real_seq_len():
    base = {"metric": "transformer_lm_train_throughput", "value": 1e5,
            "platform": "axon", "seq_len": 1024, "per_chip_batch": 8}
    assert bench._cacheable(base)
    assert not bench._cacheable({**base, "seq_len": 64})
    assert not bench._cacheable({**base, "platform": "cpu"})


def test_cacheable_transformer_rejects_model_shape_variants(monkeypatch):
    """Vocab/heads/depth/width variants change FLOPs-per-token (a small
    vocab drops the output projection, ~15-20% of fwd FLOPs) — they must
    not masquerade as the flagship GPT-2-small datum, via either the env
    fingerprint (fresh runs) or the payload checks (legacy entries)."""
    base = {"metric": "transformer_lm_train_throughput", "value": 1e5,
            "platform": "axon", "seq_len": 1024, "per_chip_batch": 8}
    monkeypatch.setenv("BENCH_MODEL", "transformer")
    for knob, value in [("BENCH_VOCAB", "512"), ("BENCH_HEADS", "4"),
                        ("BENCH_D_MODEL", "256"), ("BENCH_LAYERS", "4")]:
        monkeypatch.setenv(knob, value)
        assert not bench._cacheable(base), knob
        monkeypatch.delenv(knob)
    assert bench._cacheable(base)
    # payload-side defense for legacy (fingerprint-less) entries
    assert not bench._cacheable({**base, "d_model": 256})
    assert not bench._cacheable({**base, "n_layers": 4})
    assert not bench._cacheable({**base, "n_vocab": 512})
    assert not bench._cacheable({**base, "remat": True})


def test_cacheable_transformer_rejects_longcontext_variant(monkeypatch):
    base = {"metric": "transformer_lm_train_throughput", "value": 1e5,
            "platform": "axon", "seq_len": 8192, "per_chip_batch": 2}
    monkeypatch.setenv("BENCH_BS", "2")
    monkeypatch.setenv("BENCH_SEQ", "8192")
    monkeypatch.setenv("BENCH_REMAT", "1")
    assert not bench._cacheable(base)


def test_emit_persists_only_cacheable(cache_path, capsys):
    bench._emit(CPU_SMOKE)
    with pytest.raises(FileNotFoundError):
        open(cache_path)
    bench._emit(TPU_RESULT)
    with open(cache_path) as f:
        saved = json.load(f)
    entry = saved["entries"][TPU_RESULT["metric"]]
    assert entry["result"]["value"] == TPU_RESULT["value"]
    assert entry["fingerprint"] == \
        bench._DEFAULT_FINGERPRINTS["resnet50"]
    capsys.readouterr()


def test_cache_keeps_one_slot_per_metric(cache_path, capsys):
    """The recovery queue interleaves resnet and transformer runs; a
    transformer persist must not destroy the last-good resnet datum."""
    tf_result = {"metric": "transformer_lm_train_throughput",
                 "value": 1e5, "unit": "tokens/sec/chip",
                 "platform": "axon", "seq_len": 1024, "per_chip_batch": 8}
    bench._emit(TPU_RESULT)
    bench._emit(tf_result)
    with open(cache_path) as f:
        entries = json.load(f)["entries"]
    assert entries["resnet50_imagenet_train_throughput"]["result"][
        "value"] == TPU_RESULT["value"]
    assert entries["transformer_lm_train_throughput"]["result"][
        "value"] == tf_result["value"]
    capsys.readouterr()


def test_repo_slot_survives_tmp_wipe(cache_path, capsys, monkeypatch):
    """Round-5 incident: the machine restart that healed the relay also
    wiped /tmp, destroying the freshly recorded flagship datum.  A
    successful emit now mirrors the entry into the repo-committed slot;
    after the /tmp slot vanishes, the stale re-serve path must find the
    repo copy (same gates) and a fresh emit must not drop the OTHER
    metric's repo entry when rebuilding the /tmp file."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    tf_result = {"metric": "transformer_lm_train_throughput",
                 "value": 1e5, "unit": "tokens/sec/chip",
                 "platform": "axon", "seq_len": 1024, "per_chip_batch": 8}
    bench._emit(TPU_RESULT)
    bench._emit(tf_result)
    os.remove(cache_path)  # the restart
    # a post-restart bench is a new process with its own run id
    monkeypatch.setenv("BENCH_RUN_ID", "post-restart-run")
    run_id, cached, fp = bench._load_cache(TPU_RESULT["metric"])
    assert cached["value"] == TPU_RESULT["value"]
    assert fp == bench._DEFAULT_FINGERPRINTS["resnet50"]
    bench._emit_stale_or_error("relay wedged after restart")
    out = _last_line(capsys)
    assert out["value"] == TPU_RESULT["value"]
    assert out["stale"] is True
    # a post-restart successful resnet run must merge, not clobber, the
    # transformer entry still present only in the repo slot
    bench._emit(dict(TPU_RESULT, value=1500.0))
    with open(cache_path) as f:
        entries = json.load(f)["entries"]
    assert entries["transformer_lm_train_throughput"]["result"][
        "value"] == tf_result["value"]
    assert entries[TPU_RESULT["metric"]]["result"]["value"] == 1500.0
    capsys.readouterr()


def test_malformed_cache_shapes_never_raise(cache_path, capsys,
                                            monkeypatch):
    """Hand-edited/truncated cache files in every malformed-but-valid-
    JSON shape must fall through to the error emit, not raise through
    _emit_stale_or_error (documented 'never raises')."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    shapes = [
        {"entries": []},                      # entries not a dict
        {"entries": {TPU_RESULT["metric"]: "junk"}},  # entry not a dict
        {"entries": {TPU_RESULT["metric"]: {          # fp not a dict
            "result": dict(TPU_RESULT), "fingerprint": "junk"}}},
        {"result": "junk"},                   # legacy slot not a dict
    ]
    for shape in shapes:
        with open(cache_path, "w") as f:
            json.dump(shape, f)
        bench._emit_stale_or_error("wedged")
        out = _last_line(capsys)
        assert out["value"] is None, shape
        assert out["error"] == "wedged"


def test_poisoned_tmp_slot_does_not_mask_repo_datum(cache_path, capsys,
                                                    monkeypatch):
    """A planted non-flagship payload in /tmp (the round-3 vector) must
    not make the fallback stop short of the valid repo-committed datum
    one slot further down."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setenv("BENCH_RUN_ID", "current-run")
    _warm()
    with open(cache_path, "w") as f:
        json.dump({"run_id": "plant", "saved_at": 0.0,
                   "result": CPU_SMOKE}, f)
    with open(bench._REPO_CACHE_PATH, "w") as f:
        json.dump({"entries": {TPU_RESULT["metric"]: {
            "run_id": "queue-run", "saved_at": 1.0,
            "fingerprint": bench._DEFAULT_FINGERPRINTS["resnet50"],
            "result": dict(TPU_RESULT)}}}, f)
    bench._emit_stale_or_error("relay wedged")
    out = _last_line(capsys)
    assert out["value"] == TPU_RESULT["value"]
    assert out["stale"] is True


def test_emit_does_not_promote_tmp_poison_into_repo_slot(
        cache_path, capsys, monkeypatch):
    """A legitimate flagship emit merges the other metric's entry across
    slots — but a /tmp entry that fails the shape/fingerprint/payload
    screen must not be written into the committed repo file, where it
    would outlive the restarts that used to flush it."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    poison = {"run_id": "plant", "saved_at": 0.0,
              "result": {"metric": "transformer_lm_train_throughput",
                         "value": 1.0, "platform": "cpu"}}
    # fingerprint-LESS accelerator-looking poison too: a non-flagship
    # payload (bs 256 ≫ flagship 8) must be stopped by the payload
    # gates, not only by the platform check
    fpless = {"run_id": "plant2", "saved_at": 9e9,
              "result": {"metric": "transformer_lm_train_throughput",
                         "value": 1e6, "unit": "tokens/sec/chip",
                         "platform": "axon", "seq_len": 1024,
                         "per_chip_batch": 256}}
    good_tf = {"run_id": "queue-run", "saved_at": 5.0,
               "fingerprint": bench._DEFAULT_FINGERPRINTS["transformer"],
               "result": {"metric": "transformer_lm_train_throughput",
                          "value": 1e5, "unit": "tokens/sec/chip",
                          "platform": "axon", "seq_len": 1024,
                          "per_chip_batch": 8}}
    for plant in (poison, fpless):
        with open(cache_path, "w") as f:
            json.dump({"entries": {
                "transformer_lm_train_throughput": plant}}, f)
        with open(bench._REPO_CACHE_PATH, "w") as f:
            json.dump({"entries": {
                "transformer_lm_train_throughput": good_tf}}, f)
        bench._emit(TPU_RESULT)
        with open(bench._REPO_CACHE_PATH) as f:
            repo_entries = json.load(f)["entries"]
        # the plant is screened out; the valid repo datum survives
        assert repo_entries["transformer_lm_train_throughput"][
            "run_id"] == "queue-run", plant
        assert repo_entries[TPU_RESULT["metric"]]["result"][
            "value"] == TPU_RESULT["value"]
    capsys.readouterr()


def test_merge_keeps_newest_entry_per_metric(cache_path, capsys,
                                             monkeypatch):
    """A week-old /tmp entry must not overwrite a newer repo-committed
    datum on the next emit of the OTHER metric — saved_at arbitrates."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    old_tf = {"run_id": "old-local", "saved_at": 100.0,
              "fingerprint": bench._DEFAULT_FINGERPRINTS["transformer"],
              "result": {"metric": "transformer_lm_train_throughput",
                         "value": 5e4, "unit": "tokens/sec/chip",
                         "platform": "axon", "seq_len": 1024,
                         "per_chip_batch": 8}}
    new_tf = {"run_id": "committed-newer", "saved_at": 200.0,
              "fingerprint": bench._DEFAULT_FINGERPRINTS["transformer"],
              "result": dict(old_tf["result"], value=1e5)}
    with open(cache_path, "w") as f:
        json.dump({"entries": {
            "transformer_lm_train_throughput": old_tf}}, f)
    with open(bench._REPO_CACHE_PATH, "w") as f:
        json.dump({"entries": {
            "transformer_lm_train_throughput": new_tf}}, f)
    bench._emit(TPU_RESULT)
    for path in (cache_path, bench._REPO_CACHE_PATH):
        with open(path) as f:
            entries = json.load(f)["entries"]
        assert entries["transformer_lm_train_throughput"][
            "run_id"] == "committed-newer", path
    capsys.readouterr()


def test_load_cache_serves_newest_across_slots(cache_path, capsys,
                                               monkeypatch):
    """Read-side arbitration mirrors the write side: a valid-but-older
    /tmp entry must not shadow a newer committed repo datum (git pull
    brought a fresher bench_last_good.json; relay wedges before any
    emit merges the slots)."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setenv("BENCH_RUN_ID", "current-run")
    older = {"run_id": "old-local", "saved_at": 100.0,
             "fingerprint": bench._DEFAULT_FINGERPRINTS["resnet50"],
             "result": dict(TPU_RESULT, value=999.0)}
    newer = {"run_id": "committed-newer", "saved_at": 200.0,
             "fingerprint": bench._DEFAULT_FINGERPRINTS["resnet50"],
             "result": dict(TPU_RESULT)}
    with open(cache_path, "w") as f:
        json.dump({"entries": {TPU_RESULT["metric"]: older}}, f)
    with open(bench._REPO_CACHE_PATH, "w") as f:
        json.dump({"entries": {TPU_RESULT["metric"]: newer}}, f)
    run_id, cached, fp = bench._load_cache(TPU_RESULT["metric"])
    assert run_id == "committed-newer"
    assert cached["value"] == TPU_RESULT["value"]


def test_merge_preserves_foreign_metric_entries(cache_path, capsys,
                                                monkeypatch):
    """A committed repo entry for a metric THIS version cannot judge
    (written by a newer branch) must survive an emit verbatim — the
    screens protect known slots, they must not delete durable data."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    foreign = {"run_id": "future-branch", "saved_at": 1.0,
               "result": {"metric": "diffusion_train_throughput",
                          "value": 7.0, "platform": "axon"}}
    # known metric, but a fingerprint key only a newer schema defines:
    # backfill works only forward, so this version cannot judge it
    newer_schema = {"run_id": "future-fp", "saved_at": 1.0,
                    "fingerprint": dict(
                        bench._DEFAULT_FINGERPRINTS["transformer"],
                        dtype="bf16"),
                    "result": {"metric": "transformer_lm_train_throughput",
                               "value": 3.0, "platform": "axon",
                               "seq_len": 1024, "per_chip_batch": 8}}
    with open(bench._REPO_CACHE_PATH, "w") as f:
        json.dump({"entries": {
            "diffusion_train_throughput": foreign,
            "transformer_lm_train_throughput": newer_schema}}, f)
    # an unjudgeable /tmp plant must NOT ride the merge into the
    # committed slot (transient state earns durability via the screens)
    with open(cache_path, "w") as f:
        json.dump({"entries": {
            "some_other_future_metric": {"run_id": "plant",
                                         "saved_at": 9e9}}}, f)
    bench._emit(TPU_RESULT)
    with open(bench._REPO_CACHE_PATH) as f:
        entries = json.load(f)["entries"]
    assert entries["diffusion_train_throughput"][
        "run_id"] == "future-branch"
    assert entries["transformer_lm_train_throughput"][
        "run_id"] == "future-fp"
    assert "some_other_future_metric" not in entries
    assert entries[TPU_RESULT["metric"]]["result"][
        "value"] == TPU_RESULT["value"]
    capsys.readouterr()


def test_load_cache_backfills_fingerprint_missing_model_key(
        cache_path, capsys, monkeypatch):
    """A stored fingerprint written before a schema bump added the
    'model' key must backfill from the METRIC's model and still serve
    (the docstring's fingerprint-schema-bump tolerance)."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setenv("BENCH_RUN_ID", "current-run")
    _warm()
    fp = {k: v for k, v in
          bench._DEFAULT_FINGERPRINTS["resnet50"].items()
          if k != "model"}
    with open(cache_path, "w") as f:
        json.dump({"entries": {TPU_RESULT["metric"]: {
            "run_id": "earlier-run", "saved_at": 1.0,
            "fingerprint": fp, "result": dict(TPU_RESULT)}}}, f)
    bench._emit_stale_or_error("wedged")
    out = _last_line(capsys)
    assert out["value"] == TPU_RESULT["value"]
    assert out["stale"] is True


def test_stale_reemit_refuses_poisoned_cache(cache_path, capsys,
                                             monkeypatch):
    """A cpu-smoke payload planted in the cache file (the round-3
    failure) must NOT be re-served — value:null + the error instead."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    with open(cache_path, "w") as f:
        json.dump({"run_id": "old", "saved_at": 0.0,
                   "result": CPU_SMOKE}, f)
    bench._emit_stale_or_error("deadline exceeded before first result")
    out = _last_line(capsys)
    assert out["value"] is None
    assert "deadline" in out["error"]
    assert out["metric"] == "resnet50_imagenet_train_throughput"


def test_stale_reemit_serves_real_tpu_datum(cache_path, capsys,
                                            monkeypatch):
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setenv("BENCH_RUN_ID", "current-run")
    _warm()
    with open(cache_path, "w") as f:
        json.dump({"run_id": "earlier-run", "saved_at": 0.0,
                   "result": TPU_RESULT}, f)
    bench._emit_stale_or_error("relay wedged")
    out = _last_line(capsys)
    assert out["value"] == TPU_RESULT["value"]
    assert out["stale"] is True
    assert out["platform"] == "axon"
    assert out["error"] == "relay wedged"


def test_stale_reemit_refuses_fingerprint_mismatch(cache_path, capsys,
                                                   monkeypatch):
    """A new-format entry recorded under a variant config (here scan=8)
    must not be re-served by a default-config run, even if its payload
    were doctored to look default."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    fp = dict(bench._DEFAULT_FINGERPRINTS["resnet50"], scan=8)
    with open(cache_path, "w") as f:
        json.dump({"entries": {TPU_RESULT["metric"]: {
            "run_id": "old", "saved_at": 0.0, "fingerprint": fp,
            "result": TPU_RESULT}}}, f)
    bench._emit_stale_or_error("relay wedged")
    out = _last_line(capsys)
    assert out["value"] is None
    assert "wedged" in out["error"]


def test_stale_reemit_serves_new_format_default_entry(cache_path, capsys,
                                                      monkeypatch):
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setenv("BENCH_RUN_ID", "current-run")
    _warm()
    with open(cache_path, "w") as f:
        json.dump({"entries": {TPU_RESULT["metric"]: {
            "run_id": "earlier-run", "saved_at": 0.0,
            "fingerprint": bench._DEFAULT_FINGERPRINTS["resnet50"],
            "result": TPU_RESULT}}}, f)
    bench._emit_stale_or_error("relay wedged")
    out = _last_line(capsys)
    assert out["value"] == TPU_RESULT["value"]
    assert out["stale"] is True
    assert out["config"] == bench._DEFAULT_FINGERPRINTS["resnet50"]


def test_stale_fp_override_restores_fallback_reserve(cache_path, capsys,
                                                     monkeypatch):
    """The CPU-fallback re-exec shrinks BENCH_BS for its own cpu
    measurement; BENCH_STALE_FP carries the ORIGINAL requested config so
    the child can still re-serve the cached default-config flagship
    datum when its cpu attempt also fails."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setenv("BENCH_RUN_ID", "current-run")
    monkeypatch.setenv("BENCH_BS", "8")  # the fallback child's cpu knob
    _warm()
    with open(cache_path, "w") as f:
        json.dump({"entries": {TPU_RESULT["metric"]: {
            "run_id": "earlier-run", "saved_at": 0.0,
            "fingerprint": bench._DEFAULT_FINGERPRINTS["resnet50"],
            "result": TPU_RESULT}}}, f)
    # without the override the shrunken bs refuses the cached datum ...
    bench._emit_stale_or_error("tpu down, cpu fallback also failed")
    assert _last_line(capsys)["value"] is None
    # ... with it (what _child_main sets on the re-exec) it re-serves
    monkeypatch.setenv("BENCH_STALE_FP", json.dumps(
        bench._DEFAULT_FINGERPRINTS["resnet50"]))
    bench._emit_stale_or_error("tpu down, cpu fallback also failed")
    out = _last_line(capsys)
    assert out["value"] == TPU_RESULT["value"]
    assert out["stale"] is True


def test_config_fingerprint_never_raises_on_bad_env(monkeypatch):
    """`_emit_stale_or_error` is documented 'never raises' — a typo'd
    int knob must not turn the always-emit fallback into a traceback."""
    monkeypatch.setenv("BENCH_SCAN", "8x")
    monkeypatch.setenv("BENCH_BS", "")
    fp = bench._config_fingerprint("resnet50")
    assert fp["scan"] == 0 and fp["bs"] == bench.DEFAULT_BS


def test_stale_reemit_never_repersists(cache_path, capsys, monkeypatch):
    """Re-emission must not refresh the cache file (stale results would
    otherwise look newer on every failure)."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setenv("BENCH_RUN_ID", "current-run")
    with open(cache_path, "w") as f:
        json.dump({"run_id": "earlier-run", "saved_at": 123.0,
                   "result": TPU_RESULT}, f)
    bench._emit_stale_or_error("still wedged")
    with open(cache_path) as f:
        assert json.load(f)["saved_at"] == 123.0
    capsys.readouterr()


def test_cacheable_rejects_input_pipeline_variant(cache_path, monkeypatch):
    """BENCH_INPUT_PIPELINE=1 measures the host feed, a different regime
    than the pre-staged flagship row — both the env fingerprint and the
    payload flag must keep it out of the last-good cache."""
    monkeypatch.setenv("BENCH_INPUT_PIPELINE", "1")
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_INPUT_PIPELINE")
    assert bench._cacheable(TPU_RESULT)
    assert not bench._cacheable({**TPU_RESULT, "input_pipeline": True})


def test_cacheable_rejects_prewarm_step_count(cache_path, monkeypatch):
    """ADVICE r4: the recovery queue's BENCH_STEPS=4 prewarm has
    different amortization than the 40-step flagship trial — it must not
    seed (env side) or be re-served from (payload side) the last-good
    cache."""
    monkeypatch.setenv("BENCH_STEPS", "4")
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_STEPS")
    assert bench._cacheable(TPU_RESULT)
    # payload-side defense: an entry recorded WITH the knob in its payload
    assert not bench._cacheable({**TPU_RESULT, "n_steps": 4})
    assert bench._cacheable({**TPU_RESULT,
                             "n_steps": bench.DEFAULT_STEPS})
    # transformer flavor
    tf = {"metric": "transformer_lm_train_throughput", "value": 1e5,
          "platform": "axon", "seq_len": 1024, "per_chip_batch": 8}
    assert not bench._cacheable({**tf, "n_steps": 4})
    monkeypatch.setenv("BENCH_MODEL", "transformer")
    monkeypatch.setenv("BENCH_STEPS", "4")
    assert not bench._cacheable(tf)


def test_emit_writes_prewarm_sentinel_on_accelerator_success(
        cache_path, capsys, monkeypatch):
    """Any successful on-chip trial (flagship or variant) marks its
    MODEL's XLA cache warm; cpu/stale/error results must not, and a
    transformer run must not mark the resnet flagship program warm."""
    sentinel = bench._prewarm_sentinel("resnet50")  # base is at tmp_path
    monkeypatch.setenv("BENCH_RUN_ID", "rid-1")
    bench._emit(CPU_SMOKE)
    assert not os.path.exists(sentinel)
    bench._emit({**TPU_RESULT, "stale": True}, persist=False)
    assert not os.path.exists(sentinel)
    # a transformer success warms only the transformer program's slot
    bench._emit({"metric": "transformer_lm_train_throughput", "value": 1e5,
                 "platform": "axon", "seq_len": 1024, "per_chip_batch": 8})
    assert not os.path.exists(sentinel)
    assert os.path.exists(bench._prewarm_sentinel("transformer"))
    # a VARIANT on-chip resnet run (not cacheable) still warms the cache
    bench._emit({**TPU_RESULT, "layout": "NCHW"})
    assert os.path.exists(sentinel)
    capsys.readouterr()


def test_default_deadline_extends_when_cache_cold(tmp_path):
    """VERDICT r4 Weak #4: a first-contact driver run (no prewarm
    sentinel) gets 480 s for cold compile through the relay; once the
    sentinel exists the default drops back to 270 s.  BENCH_DEADLINE_S
    always wins.  _DEADLINE_S is computed at import, so probe via a
    child interpreter."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = tmp_path / "prewarmed"

    def deadline(env_extra):
        env = dict(os.environ, BENCH_PREWARM_SENTINEL=str(base))
        env.pop("BENCH_DEADLINE_S", None)
        env.pop("BENCH_MODEL", None)
        env.update(env_extra)
        out = subprocess.run(
            [sys.executable, "-c", "import bench; print(bench._DEADLINE_S)"],
            env=env, capture_output=True, text=True, cwd=root, timeout=60)
        assert out.returncode == 0, out.stderr
        return float(out.stdout.strip())

    assert deadline({}) == 480.0
    (tmp_path / "prewarmed.resnet50").write_text("rid 0\n")
    assert deadline({}) == 270.0
    # the warm resnet sentinel does not cover the transformer program
    assert deadline({"BENCH_MODEL": "transformer"}) == 480.0
    assert deadline({"BENCH_DEADLINE_S": "123"}) == 123.0


def _run_supervised_wedge(tmp_path, wedge_mode, extra_env=None):
    """Launch bench.py (supervisor mode) with a fault-injected child in
    its own session; return (last JSON line, elapsed, detached child pid
    or None).  Always killpg-reaps the lingering FAKE child (it never
    touched a device, so killing it is safe — unlike the real thing)."""
    import signal as _signal
    import subprocess
    import sys
    import time as _time

    registry = tmp_path / "detached.pids"
    env = dict(os.environ, BENCH_TEST_WEDGE=wedge_mode,
               BENCH_DEADLINE_S="8",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"),
               BENCH_REPO_CACHE_PATH=str(tmp_path / "repo_cache.json"),
               BENCH_DETACH_REGISTRY=str(registry),
               BENCH_START_STAMP=str(tmp_path / "started"),
               **(extra_env or {}))
    env.pop("BENCH_MODEL", None)  # a leaked transformer mode would flip
    # the expected metric (the queue script sets it for its own runs)
    start = _time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench.py")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=60)
        elapsed = _time.monotonic() - start
        # liveness must be checked BEFORE the finally's killpg, or the
        # "detached child still alive" contract races its own cleanup
        detached_alive = False
        if registry.exists():
            entries = [ln.split() for ln in
                       registry.read_text().splitlines() if ln.split()]
            detached_alive = bool(entries) and \
                os.path.exists(f"/proc/{entries[-1][0]}")
        lines = [ln for ln in out.strip().splitlines()
                 if ln.startswith("{")]
        assert lines, out
        return json.loads(lines[-1]), elapsed, detached_alive
    finally:
        # reap the fake wedged grandchild left alive by design.  The
        # child runs in its OWN session (start_new_session — the point
        # of the group-signal hardening), so killing the supervisor's
        # group no longer reaches it: collect its pid from the registry
        # (detach path) and kill its session too.
        if registry.exists():
            for ln in registry.read_text().splitlines():
                parts = ln.split()
                if parts:
                    try:
                        os.killpg(int(parts[0]), _signal.SIGKILL)
                    except Exception:
                        pass
        try:
            os.killpg(proc.pid, _signal.SIGKILL)
        except Exception:
            pass


@pytest.mark.slow
def test_supervisor_emits_error_line_when_child_wedges(tmp_path):
    """The core driver contract (VERDICT r2 Missing #1): a child wedged
    before ANY output AND ignoring SIGTERM (a thread stuck in a C call
    never runs handlers) — the known relay failure mode — must still
    yield exactly one authoritative JSON line from the no-jax
    supervisor within the deadline, refusing stale re-emission when no
    valid cache exists.  And the child must be left ALIVE (detached):
    killing a process with an in-flight relay RPC is what wedges the
    relay (r5 postmortems)."""
    out, elapsed, detached_alive = _run_supervised_wedge(tmp_path, "1")
    assert out["value"] is None
    assert "deadline" in out["error"] or "terminated" in out["error"]
    assert out["metric"] == "resnet50_imagenet_train_throughput"
    assert elapsed < 45, f"supervisor took {elapsed:.0f}s for an 8s deadline"
    assert detached_alive, \
        "wedged child should be registered and still alive (detached)"


@pytest.mark.slow
def test_supervisor_serves_early_emit_from_wedged_child(tmp_path):
    """A child that printed an early-emit line before wedging: the
    supervisor's incremental read must serve that line as the run's
    authoritative result (the old communicate() lost partial output
    when it had to kill the child)."""
    out, elapsed, detached_alive = _run_supervised_wedge(
        tmp_path, "emit-then-wedge")
    assert out["value"] == 123.0
    assert out.get("early") is True
    assert elapsed < 45
    assert detached_alive


@pytest.mark.slow
def test_supervisor_kill_fallback_when_detach_cap_reached(tmp_path):
    """With _DETACH_CAP lingering children already registered, the
    supervisor falls back to terminate→kill (bounding host memory) and
    still emits the error line."""
    registry = tmp_path / "detached.pids"
    # two "alive" entries: our own pid+starttime, twice
    me = f"{os.getpid()} {bench._proc_starttime(os.getpid())}"
    registry.write_text(f"{me}\n{me}\n")
    out, elapsed, _ = _run_supervised_wedge(tmp_path, "1")
    assert out["value"] is None
    assert "deadline" in out["error"] or "terminated" in out["error"]
    # cap-reached also means the supervisor first waits deadline/3 for
    # the "sibling" (us) to drain before starting the child
    assert elapsed < 60
    # registry unchanged: the wedged child was killed, not registered
    assert registry.read_text().split("\n")[:2] == [me, me]


def test_register_detached_cap(tmp_path, monkeypatch):
    reg = str(tmp_path / "detached.pids")
    monkeypatch.setattr(bench, "_DETACH_REGISTRY", reg)
    assert bench._register_detached(os.getpid()) is True
    assert bench._register_detached(os.getpid()) is True
    # two alive entries -> cap reached, caller must fall back to kill
    assert bench._register_detached(os.getpid()) is False
    # dead/malformed entries are pruned on the way
    with open(reg, "w") as f:
        f.write("999999998 123\n999999999 456\nbare-pid-old-format\n")
    assert bench._register_detached(os.getpid()) is True
    lines = open(reg).read().splitlines()
    assert [int(ln.split()[0]) for ln in lines] == [os.getpid()]


def test_register_detached_is_pid_reuse_proof(tmp_path, monkeypatch):
    """An entry whose pid exists but with a DIFFERENT starttime (the pid
    was recycled by an unrelated process) must be pruned, not counted
    toward the cap — a tripped cap forces the kill fallback, the exact
    wedge cause the detach path exists to prevent."""
    reg = str(tmp_path / "detached.pids")
    monkeypatch.setattr(bench, "_DETACH_REGISTRY", reg)
    with open(reg, "w") as f:
        # our own live pid, but a wrong starttime: "recycled"
        f.write(f"{os.getpid()} not-the-real-starttime\n" * 2)
    assert bench._read_detached_alive() == []
    assert bench._register_detached(os.getpid()) is True


def test_contended_results_flagged_and_uncacheable(cache_path, capsys,
                                                   monkeypatch):
    """When a detached child from an earlier run is still draining on
    the chip, the supervisor marks the run contended: the emitted line
    must carry the flag and the payload gates must refuse to cache or
    re-serve it."""
    monkeypatch.setenv("BENCH_CONTENDED", "1")
    bench._emit(TPU_RESULT)
    out = _last_line(capsys)
    assert out["contended"] is True
    with pytest.raises(FileNotFoundError):  # not persisted
        open(cache_path)
    assert not bench._payload_flagship_ok(
        "resnet50", {**TPU_RESULT, "contended": True})


def test_detached_overrunner_marks_itself_contended(cache_path, capsys,
                                                    monkeypatch):
    """The OTHER direction of contention: a detached child that is still
    measuring when a NEWER bench stamps its start must mark its own
    (time-shared) result contended at persist time — otherwise its
    degraded throughput would overwrite the last-good cache as a clean
    flagship datum."""
    monkeypatch.delenv("BENCH_CONTENDED", raising=False)
    stamp = bench._START_STAMP
    # no stamp, or a stamp older than this process: clean persist
    bench._emit(TPU_RESULT)
    out = _last_line(capsys)
    assert "contended" not in out
    with open(cache_path):
        pass
    os.remove(cache_path)
    # a stamp NEWER than this process's start: the overrun scenario
    with open(stamp, "w") as f:
        f.write("newer-run\n")
    os.utime(stamp, (bench._WALL_START + 5, bench._WALL_START + 5))
    bench._emit(TPU_RESULT)
    out = _last_line(capsys)
    assert out["contended"] is True
    with pytest.raises(FileNotFoundError):  # refused by the gates
        open(cache_path)


def test_emit_persists_despite_dead_stdout(cache_path, monkeypatch):
    """A detached child's stdout is gone (supervisor exited); _emit must
    still persist the result — that persistence is what seeds the NEXT
    run's stale serve."""
    import sys

    class DeadPipe:
        def write(self, *_):
            raise BrokenPipeError
        def flush(self):
            raise BrokenPipeError
    monkeypatch.setattr(sys, "stdout", DeadPipe())
    bench._emit(TPU_RESULT)
    with open(cache_path) as f:
        entry = json.load(f)["entries"][TPU_RESULT["metric"]]
    assert entry["result"]["value"] == TPU_RESULT["value"]


def _run_gloo_harness(extra_args, timeout):
    """Shared launcher for the bench_scaling gloo tests: own session so
    a timeout reaps the gloo worker GRANDCHILDREN too (not just the
    bench_scaling parent), stdout parsed into JSON rows."""
    import signal
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(root, "bench_scaling.py"),
         *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        proc.communicate()
        raise
    assert proc.returncode == 0, stderr[-2000:]
    return [json.loads(ln) for ln in stdout.splitlines()
            if ln.startswith("{")]


@pytest.mark.slow
def test_gloo_scaling_harness_two_process(tmp_path):
    """bench_scaling --gloo-procs mechanics: the real cross-process
    compiled-DP measurement (VERDICT r3 Missing #4's instrument) keeps
    working — rows parse, per-hop summary present."""
    rows = _run_gloo_harness(
        ["--gloo-procs", "1,2", "--per-chip-bs", "8", "--steps", "5",
         "--gloo-hidden", "32"], timeout=420)
    by_procs = {r["processes"]: r for r in rows if "step_ms" in r}
    assert set(by_procs) == {1, 2}
    assert all(r["step_ms"] > 0 for r in by_procs.values())
    summary = [r for r in rows if "per_hop_overhead_raw_ms" in r]
    assert summary and summary[0]["processes"] == 2
    assert "overhead_vs_serialized_compute_ms" in summary[0]
    assert all(r["zero_sharding"] is False for r in by_procs.values())


@pytest.mark.slow
def test_gloo_scaling_harness_zero_mode(tmp_path):
    """--gloo-zero mechanics: the ZeRO-1 cross-process curve (psum_scatter
    + all_gather data plane) keeps producing parseable rows."""
    rows = _run_gloo_harness(
        ["--gloo-procs", "1", "--per-chip-bs", "8", "--steps", "5",
         "--gloo-hidden", "32", "--gloo-zero"], timeout=300)
    assert rows and rows[0]["zero_sharding"] is True
    assert rows[0]["step_ms"] > 0


# -- detach hardening: session isolation, signal forwarding, registry lock --

def test_registry_flock_serializes_read_modify_write(tmp_path, monkeypatch):
    """Two concurrent supervisors must not interleave the registry's
    read-append-replace (ADVICE r5: one os.replace could drop the
    other's just-written entry).  Deterministic probe: while this
    process holds the flock, a second (exec'd — a forked child would
    inherit our lock fd and keep the flock alive past our close) writer
    stays blocked; on release it completes and its entry lands."""
    import subprocess
    import sys
    import time as _time

    reg = str(tmp_path / "detached.pids")
    marker = str(tmp_path / "writer-started")
    monkeypatch.setattr(bench, "_DETACH_REGISTRY", reg)

    lock = bench._registry_locked()
    assert lock is not None
    env = dict(os.environ, BENCH_DETACH_REGISTRY=reg)
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import os, sys; sys.path.insert(0, sys.argv[1]); import bench;"
         "open(sys.argv[2], 'w').close();"
         "bench._register_detached(os.getpid())",
         os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
         marker],
        env=env)
    try:
        deadline = _time.monotonic() + 20
        while not os.path.exists(marker):  # writer up, about to lock
            assert _time.monotonic() < deadline, "writer never started"
            _time.sleep(0.05)
        _time.sleep(0.5)
        assert not os.path.exists(reg), \
            "writer got past the held registry lock"
        lock.close()  # releases the flock
        assert proc.wait(timeout=15) == 0
        pids = [int(ln.split()[0])
                for ln in open(reg).read().splitlines()]
        assert pids == [proc.pid]
    finally:
        if proc.poll() is None:
            proc.kill()


def test_register_detached_write_failure_emits_diagnostic(
        tmp_path, monkeypatch, capsys):
    """A failed registry write still detaches (never force a kill) but
    must say so on stderr — an unrecorded child is invisible to the
    next run's contention wait (ADVICE r5 low)."""
    reg = str(tmp_path / "no-such-dir" / "detached.pids")
    monkeypatch.setattr(bench, "_DETACH_REGISTRY", reg)
    assert bench._register_detached(os.getpid()) is True
    assert "could NOT be recorded" in capsys.readouterr().err


@pytest.mark.slow
def test_supervised_child_runs_in_own_session(tmp_path):
    """start_new_session: the supervised child leads its OWN session, so
    a group-directed signal at the supervisor (GNU timeout, Ctrl-C, CI
    group-kill) cannot reach it — a detach stays a real detach."""
    import signal as _signal
    import subprocess
    import sys

    registry = tmp_path / "detached.pids"
    env = dict(os.environ, BENCH_TEST_WEDGE="emit-then-wedge",
               BENCH_DEADLINE_S="8",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"),
               BENCH_REPO_CACHE_PATH=str(tmp_path / "repo_cache.json"),
               BENCH_DETACH_REGISTRY=str(registry),
               BENCH_START_STAMP=str(tmp_path / "started"))
    env.pop("BENCH_MODEL", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench.py")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, start_new_session=True)
    child_pid = None
    try:
        proc.communicate(timeout=60)
        entries = [ln.split() for ln in
                   registry.read_text().splitlines() if ln.split()]
        assert entries, "detached child was not registered"
        child_pid = int(entries[-1][0])
        assert os.getsid(child_pid) == child_pid, \
            "detached child is not a session leader"
    finally:
        for pid in filter(None, [child_pid, proc.pid]):
            try:
                os.killpg(pid, _signal.SIGKILL)
            except Exception:
                pass


@pytest.mark.slow
def test_supervisor_forwards_term_to_supervised_child(tmp_path):
    """Interactive kill semantics survive the session split: TERM at the
    still-supervising parent is forwarded to the child as SIGTERM, whose
    handler emits the terminated line before dying — and the supervisor
    serves it as the authoritative result, long before the deadline."""
    import signal as _signal
    import subprocess
    import sys
    import time as _time

    env = dict(os.environ, BENCH_TEST_WEDGE="sleep-obedient",
               BENCH_DEADLINE_S="120",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"),
               BENCH_REPO_CACHE_PATH=str(tmp_path / "repo_cache.json"),
               BENCH_DETACH_REGISTRY=str(tmp_path / "detached.pids"),
               BENCH_START_STAMP=str(tmp_path / "started"))
    env.pop("BENCH_MODEL", None)
    start = _time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench.py")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, start_new_session=True)
    try:
        _time.sleep(3)  # let the supervisor spawn its child
        os.kill(proc.pid, _signal.SIGTERM)
        out, _ = proc.communicate(timeout=30)
        elapsed = _time.monotonic() - start
        lines = [ln for ln in out.strip().splitlines()
                 if ln.startswith("{")]
        assert lines, out
        last = json.loads(lines[-1])
        assert last["value"] is None
        assert "terminated by supervisor" in last["error"]
        assert elapsed < 60, \
            f"TERM should end the run promptly, took {elapsed:.0f}s"
    finally:
        try:
            os.killpg(proc.pid, _signal.SIGKILL)
        except Exception:
            pass


@pytest.mark.slow
def test_supervisor_interruptible_during_contention_wait(tmp_path):
    """TERM/INT arriving while no supervised child exists (the
    pre-spawn contention wait for an earlier run's detached child) must
    not be swallowed: the handler re-delivers with the default
    disposition, so `timeout`/Ctrl-C still end the supervisor."""
    import signal as _signal
    import subprocess
    import sys
    import time as _time

    registry = tmp_path / "detached.pids"
    me = f"{os.getpid()} {bench._proc_starttime(os.getpid())}"
    registry.write_text(f"{me}\n")  # "alive sibling" -> contention wait
    env = dict(os.environ, BENCH_TEST_WEDGE="sleep-obedient",
               BENCH_DEADLINE_S="120",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"),
               BENCH_REPO_CACHE_PATH=str(tmp_path / "repo_cache.json"),
               BENCH_DETACH_REGISTRY=str(registry),
               BENCH_START_STAMP=str(tmp_path / "started"))
    env.pop("BENCH_MODEL", None)
    start = _time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench.py")],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)
    try:
        _time.sleep(2)  # inside the up-to-40s contention wait, no child
        os.kill(proc.pid, _signal.SIGTERM)
        rc = proc.wait(timeout=20)
        elapsed = _time.monotonic() - start
        assert rc != 0  # died by signal/default disposition
        assert elapsed < 20, \
            f"supervisor ignored TERM during contention wait ({elapsed:.0f}s)"
    finally:
        try:
            os.killpg(proc.pid, _signal.SIGKILL)
        except Exception:
            pass


# -- ISSUE 3: donation A/B knob + compile-phase deadline exclusion -----------


def test_donate_knob_excluded_from_flagship_cache(cache_path, capsys,
                                                  monkeypatch):
    """BENCH_DONATE=0 (the buffer-donation A/B leg) is a measurement,
    not flagship data: both the env fingerprint and the payload gate
    must refuse it."""
    monkeypatch.setenv("BENCH_DONATE", "0")
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_DONATE", raising=False)
    assert not bench._payload_flagship_ok(
        "resnet50", {**TPU_RESULT, "donated": False})
    # donated (or legacy rows lacking the key) stay flagship-eligible
    assert bench._payload_flagship_ok(
        "resnet50", {**TPU_RESULT, "donated": True})
    assert bench._payload_flagship_ok("resnet50", TPU_RESULT)


def test_resize_invalidates_flagship_cache(monkeypatch):
    """ISSUE 10 satellite: a mid-run elastic resize is a different
    measurement regime — the fingerprint knob (BENCH_PREEMPT_RANK) and
    the payload gate (rows carrying resizes > 0) must both refuse it,
    exactly like BENCH_INTER_SIZE fences the hierarchical legs."""
    # env half: the elastic A/B knob defeats the flagship fingerprint
    monkeypatch.setenv("BENCH_PREEMPT_RANK", "1")
    assert bench._config_fingerprint("resnet50")["preempt_rank"] == 1
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_PREEMPT_RANK", raising=False)
    assert bench._cacheable(TPU_RESULT)
    # payload half: a planted row that resized mid-run is refused even
    # with a clean environment (fingerprint-less planted-entry defense)
    assert not bench._payload_flagship_ok(
        "resnet50", {**TPU_RESULT, "resizes": 2})
    assert not bench._payload_flagship_ok(
        "resnet50", {**TPU_RESULT, "world_size": 2, "resizes": 1})
    # fixed-size rows (resizes 0 or legacy rows lacking the key) stay
    # flagship-eligible
    assert bench._payload_flagship_ok(
        "resnet50", {**TPU_RESULT, "world_size": 1, "resizes": 0})
    assert bench._payload_flagship_ok("resnet50", TPU_RESULT)


def test_fleet_knobs_invalidate_flagship_cache(monkeypatch):
    """ISSUE 15 satellite: the serving-fleet knobs (BENCH_SERVE_REPLICAS
    / BENCH_FLEET_KILL_AT) are fingerprint knobs on BOTH flagship
    models — a fleet measurement regime can never be cached or
    re-served as flagship data, and legacy entries backfill the
    fleet-less defaults (backfill-safe schema bump)."""
    monkeypatch.setenv("BENCH_SERVE_REPLICAS", "2")
    assert bench._config_fingerprint("resnet50")["serve_replicas"] == 2
    assert bench._config_fingerprint("transformer")["serve_replicas"] \
        == 2
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_SERVE_REPLICAS", raising=False)
    monkeypatch.setenv("BENCH_FLEET_KILL_AT", "40")
    assert bench._config_fingerprint("resnet50")["fleet_kill_at"] == 40
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_FLEET_KILL_AT", raising=False)
    assert bench._cacheable(TPU_RESULT)
    # backfill: a stored pre-round-16 fingerprint gains the defaults
    for model in ("resnet50", "transformer"):
        fp = dict(bench._DEFAULT_FINGERPRINTS[model])
        fp.pop("serve_replicas")
        fp.pop("fleet_kill_at")
        assert bench._backfill_fp(model, fp) \
            == bench._DEFAULT_FINGERPRINTS[model]


def test_diurnal_knobs_invalidate_flagship_cache(monkeypatch):
    """ISSUE 16 satellite: the diurnal capacity-transfer knobs
    (BENCH_DIURNAL / BENCH_DIURNAL_PERIOD) are fingerprint knobs on
    BOTH flagship models, a row whose world changed ROLE mid-window
    (non-zero conversions/role_transfers) is payload-fenced even with
    a clean environment, and legacy entries backfill the broker-less
    defaults (backfill-safe schema bump)."""
    # env half: the diurnal knobs defeat the flagship fingerprint
    monkeypatch.setenv("BENCH_DIURNAL", "1")
    assert bench._config_fingerprint("resnet50")["diurnal"] is True
    assert bench._config_fingerprint("transformer")["diurnal"] is True
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_DIURNAL", raising=False)
    monkeypatch.setenv("BENCH_DIURNAL_PERIOD", "30")
    assert bench._config_fingerprint("resnet50")["diurnal_period"] == 30
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_DIURNAL_PERIOD", raising=False)
    assert bench._cacheable(TPU_RESULT)
    # payload half: planted rows that executed capacity transfers are
    # refused (legacy rows lacking the keys had no broker — eligible)
    assert not bench._payload_flagship_ok(
        "resnet50", {**TPU_RESULT, "conversions": 1})
    assert not bench._payload_flagship_ok(
        "resnet50", {**TPU_RESULT, "conversions": 0, "role_transfers": 2})
    assert bench._payload_flagship_ok(
        "resnet50", {**TPU_RESULT, "conversions": 0, "role_transfers": 0})
    assert bench._payload_flagship_ok("resnet50", TPU_RESULT)
    # backfill: a stored pre-round-17 fingerprint gains the defaults
    for model in ("resnet50", "transformer"):
        fp = dict(bench._DEFAULT_FINGERPRINTS[model])
        fp.pop("diurnal")
        fp.pop("diurnal_period")
        assert bench._backfill_fp(model, fp) \
            == bench._DEFAULT_FINGERPRINTS[model]


def test_autotune_knob_invalidates_flagship_cache(monkeypatch):
    """ISSUE 19 satellite: BENCH_AUTOTUNE is a fingerprint knob on BOTH
    flagship models — an autotuned row executes whatever plan the
    micro-bench derived, a measurement of that plan, never flagship
    data; legacy entries backfill the hand-knobbed default
    (backfill-safe schema bump)."""
    monkeypatch.setenv("BENCH_AUTOTUNE", "1")
    assert bench._config_fingerprint("resnet50")["autotune"] is True
    assert bench._config_fingerprint("transformer")["autotune"] is True
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_AUTOTUNE", raising=False)
    assert bench._cacheable(TPU_RESULT)
    for model in ("resnet50", "transformer"):
        fp = dict(bench._DEFAULT_FINGERPRINTS[model])
        fp.pop("autotune")
        assert bench._backfill_fp(model, fp) \
            == bench._DEFAULT_FINGERPRINTS[model]


def test_spec_and_chunk_knobs_invalidate_flagship_cache(monkeypatch):
    """ISSUE 20 satellite: the speculative-decode / chunked-prefill
    knobs (BENCH_SERVE_SPEC_K / BENCH_SERVE_CHUNK) are fingerprint
    knobs on BOTH flagship models — a serving regime with a different
    dispatch shape can never be cached or re-served as flagship data,
    and legacy entries backfill the off defaults (backfill-safe schema
    bump)."""
    monkeypatch.setenv("BENCH_SERVE_SPEC_K", "4")
    assert bench._config_fingerprint("resnet50")["serve_spec_k"] == 4
    assert bench._config_fingerprint("transformer")["serve_spec_k"] == 4
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_SERVE_SPEC_K", raising=False)
    monkeypatch.setenv("BENCH_SERVE_CHUNK", "64")
    assert bench._config_fingerprint("resnet50")["serve_chunk"] == 64
    assert bench._config_fingerprint("transformer")["serve_chunk"] == 64
    assert not bench._cacheable(TPU_RESULT)
    monkeypatch.delenv("BENCH_SERVE_CHUNK", raising=False)
    assert bench._cacheable(TPU_RESULT)
    # backfill: a stored pre-round-20 fingerprint gains the defaults
    for model in ("resnet50", "transformer"):
        fp = dict(bench._DEFAULT_FINGERPRINTS[model])
        fp.pop("serve_spec_k")
        fp.pop("serve_chunk")
        assert bench._backfill_fp(model, fp) \
            == bench._DEFAULT_FINGERPRINTS[model]


def test_compile_credit_math(tmp_path):
    """The supervisor's deadline extension: recorded compile seconds,
    plus the in-flight phase's elapsed time, capped at grace, zero for
    a foreign run_id or a missing/garbled stamp."""
    stamp = str(tmp_path / "compile.stamp")
    assert bench._compile_credit_from_stamp(stamp, "rid", 100.0, 900) == 0.0

    with open(stamp, "w") as f:
        json.dump({"run_id": "rid", "phase": "done", "t": 50.0,
                   "credit_s": 37.0}, f)
    assert bench._compile_credit_from_stamp(stamp, "rid", 100.0, 900) == 37.0
    assert bench._compile_credit_from_stamp(stamp, "other", 100.0, 900) == 0.0
    assert bench._compile_credit_from_stamp(stamp, "rid", 100.0, 20) == 20.0

    with open(stamp, "w") as f:
        json.dump({"run_id": "rid", "phase": "compile", "t": 60.0,
                   "credit_s": 10.0}, f)
    # in flight since t=60, now=100 -> 40s elapsed + 10s recorded
    assert bench._compile_credit_from_stamp(stamp, "rid", 100.0, 900) == 50.0

    with open(stamp, "w") as f:
        f.write("not json")
    assert bench._compile_credit_from_stamp(stamp, "rid", 100.0, 900) == 0.0


def test_stamp_compile_roundtrip(tmp_path, monkeypatch):
    stamp = str(tmp_path / "compile.stamp")
    monkeypatch.setattr(bench, "_COMPILE_STAMP", stamp)
    bench._stamp_compile("compile", 0.0)
    with open(stamp) as f:
        st = json.load(f)
    assert st["phase"] == "compile"
    assert st["run_id"] == os.environ["BENCH_RUN_ID"]
    bench._stamp_compile("done", 12.5)
    with open(stamp) as f:
        assert json.load(f)["credit_s"] == 12.5


@pytest.mark.slow
def test_supervisor_excludes_compile_time_from_deadline(tmp_path):
    """VERDICT r5 Weak #1 (the satellite's acceptance shape): a compile
    phase LONGER than the whole deadline must not stale-out the run —
    the heartbeat pauses the supervisor's clock and the FRESH result is
    served."""
    import subprocess
    import sys
    import time as _time

    env = dict(os.environ, BENCH_TEST_WEDGE="slow-compile",
               BENCH_DEADLINE_S="6", BENCH_TEST_COMPILE_S="10",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"),
               BENCH_REPO_CACHE_PATH=str(tmp_path / "repo_cache.json"),
               BENCH_DETACH_REGISTRY=str(tmp_path / "detached.pids"),
               BENCH_START_STAMP=str(tmp_path / "started"),
               BENCH_COMPILE_STAMP=str(tmp_path / "compile.stamp"))
    env.pop("BENCH_MODEL", None)
    start = _time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench.py")],
        env=env, capture_output=True, text=True, timeout=60)
    elapsed = _time.monotonic() - start
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, proc.stdout
    out = json.loads(lines[-1])
    assert out.get("fresh_after_compile") is True, out
    assert out["value"] == 77.0
    assert "stale" not in out and "error" not in out
    # it genuinely outlived the 6s deadline thanks to the credit
    assert elapsed > 9, f"finished in {elapsed:.1f}s — compile not waited?"


# -- BENCH_MODEL=longcontext (ISSUE 4) ---------------------------------------

def test_err_metric_longcontext(monkeypatch):
    monkeypatch.setenv("BENCH_MODEL", "longcontext")
    assert bench._err_metric() == ("longcontext_flash_feasibility",
                                   "tokens_context")


def test_longcontext_rows_never_cacheable(monkeypatch):
    """The feasibility artifact is a measurement, not flagship data: its
    metric is outside _METRIC_TO_MODEL so neither the summary line nor
    the per-T rows can ever be persisted or re-served stale."""
    monkeypatch.setenv("BENCH_MODEL", "longcontext")
    for metric in ("longcontext_flash_feasibility",
                   "longcontext_flash_row", "longcontext_xla_contrast"):
        assert not bench._cacheable({
            "metric": metric, "value": 16384, "unit": "tokens_context",
            "platform": "axon", "device_kind": "TPU v5 lite"})


def test_longcontext_cpu_smoke_end_to_end(tmp_path):
    """Full child run of the longcontext mode on CPU (interpret mode,
    clamped T): per-T flash rows + xla contrast row + summary line with
    the largest completed T as the value."""
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_NO_SUPERVISE="1",
               BENCH_MODEL="longcontext", BENCH_LC_SEQS="64",
               BENCH_LC_XLA_T="64", BENCH_NO_FALLBACK="1",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"),
               BENCH_REPO_CACHE_PATH="",
               BENCH_PREWARM_SENTINEL=str(tmp_path / "prewarmed"))
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench.py")],
        env=env, capture_output=True, text=True, timeout=300)
    lines = [json.loads(l) for l in out.stdout.strip().splitlines()
             if l.startswith("{")]
    assert lines, out.stderr[-2000:]
    by_metric = {l["metric"]: l for l in lines}
    assert by_metric["longcontext_flash_row"]["T"] == 64
    assert by_metric["longcontext_flash_row"]["interpreted"] is True
    assert by_metric["longcontext_flash_row"]["bwd_mode"] == "fused"
    assert "longcontext_xla_contrast" in by_metric
    summary = lines[-1]
    assert summary["metric"] == "longcontext_flash_feasibility"
    assert summary["value"] == 64
    assert summary["rows"] and summary["xla_contrast"]["T"] == 64
    # the smoke must not have persisted anything as flagship data
    assert not os.path.exists(str(tmp_path / "cache.json"))


# -- ISSUE 5: first-contact staleness + exchange variants --------------------


def test_first_contact_refuses_stale_reserve(cache_path, capsys,
                                             monkeypatch):
    """VERDICT r5 Weak #1 (third straight stale round): with NO warm-
    cache sentinel — a first-contact invocation — the stale path must
    NOT re-serve the cached flagship, however valid.  Honest value:null
    with the first-contact label instead."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setenv("BENCH_RUN_ID", "current-run")
    with open(cache_path, "w") as f:
        json.dump({"entries": {TPU_RESULT["metric"]: {
            "run_id": "earlier-run", "saved_at": 0.0,
            "fingerprint": bench._DEFAULT_FINGERPRINTS["resnet50"],
            "result": TPU_RESULT}}}, f)
    bench._emit_stale_or_error("relay wedged")
    out = _last_line(capsys)
    assert out["value"] is None
    assert "stale" not in out
    assert out["first_contact"] is True
    assert out["error"] == "relay wedged"
    # the same cache WITH the sentinel still serves (warm-path contract)
    _warm()
    bench._emit_stale_or_error("relay wedged")
    out = _last_line(capsys)
    assert out["stale"] is True and out["value"] == TPU_RESULT["value"]


def test_effective_steps_first_contact_short_steps(cache_path,
                                                   monkeypatch):
    """First contact + a deadline tighter than the first-contact default
    clamps to the short-steps count (a FRESH row instead of measuring
    into the deadline); a warm sentinel or an explicit BENCH_STEPS
    restores full steps."""
    monkeypatch.delenv("BENCH_STEPS", raising=False)
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setattr(bench, "_DEADLINE_S", 270.0)
    assert bench._effective_steps(40) == (4, True)
    monkeypatch.setenv("BENCH_SHORT_STEPS", "6")
    assert bench._effective_steps(40) == (6, True)
    monkeypatch.delenv("BENCH_SHORT_STEPS", raising=False)
    # explicit BENCH_STEPS always wins
    monkeypatch.setenv("BENCH_STEPS", "17")
    assert bench._effective_steps(40) == (17, False)
    monkeypatch.delenv("BENCH_STEPS", raising=False)
    # a deadline at/above the first-contact default is not "tight"
    monkeypatch.setattr(bench, "_DEADLINE_S", 480.0)
    assert bench._effective_steps(40) == (40, False)
    # warm sentinel: full steps even under the tight window
    monkeypatch.setattr(bench, "_DEADLINE_S", 270.0)
    _warm()
    assert bench._effective_steps(40) == (40, False)


def test_short_steps_row_never_flagship_cacheable(cache_path,
                                                  monkeypatch):
    """The short-steps fallback row measures a different amortization
    regime: the payload gates must refuse it for the last-good cache
    exactly like the recovery queue's BENCH_STEPS=4 prewarm."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    for name in ("BENCH_BS", "BENCH_STEPS", "BENCH_SCAN", "BENCH_EXCHANGE",
                 "BENCH_BUCKET_MB"):
        monkeypatch.delenv(name, raising=False)
    short_row = dict(TPU_RESULT, n_steps=4, short_steps=True)
    assert not bench._cacheable(short_row)
    assert bench._cacheable(dict(TPU_RESULT, n_steps=40))


@pytest.mark.slow
def test_first_contact_wedge_never_returns_stale_rc0(tmp_path):
    """The fault-injection pin: a first-contact invocation (no
    sentinel) whose child wedges before any output, with a VALID cached
    flagship available, exits rc=0 with an honest value:null line —
    never '"stale": true'."""
    cache = tmp_path / "cache.json"
    with open(cache, "w") as f:
        json.dump({"entries": {TPU_RESULT["metric"]: {
            "run_id": "earlier-run", "saved_at": 0.0,
            "fingerprint": bench._DEFAULT_FINGERPRINTS["resnet50"],
            "result": TPU_RESULT}}}, f)
    out, _elapsed, _ = _run_supervised_wedge(
        tmp_path, "1",
        extra_env={"BENCH_PREWARM_SENTINEL": str(tmp_path / "prewarmed")})
    assert out["value"] is None
    assert "stale" not in out
    assert out["first_contact"] is True


@pytest.mark.slow
def test_warm_wedge_still_serves_stale(tmp_path):
    """Regression guard for the warm path: the SAME wedge with the
    sentinel present must keep serving the cached flagship stale (the
    outage resilience the cache exists for)."""
    cache = tmp_path / "cache.json"
    with open(cache, "w") as f:
        json.dump({"entries": {TPU_RESULT["metric"]: {
            "run_id": "earlier-run", "saved_at": 0.0,
            "fingerprint": bench._DEFAULT_FINGERPRINTS["resnet50"],
            "result": TPU_RESULT}}}, f)
    (tmp_path / "prewarmed.resnet50").write_text("warm 0\n")
    out, _elapsed, _ = _run_supervised_wedge(
        tmp_path, "1",
        extra_env={"BENCH_PREWARM_SENTINEL": str(tmp_path / "prewarmed")})
    assert out["value"] == TPU_RESULT["value"]
    assert out["stale"] is True


def test_cacheable_rejects_exchange_variants(cache_path, monkeypatch):
    """BENCH_EXCHANGE variants (the bucket sweep / reduce-scatter A/B
    legs) compile different collective structures — never flagship
    data, on either the fingerprint or the payload gate."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    for name in ("BENCH_BS", "BENCH_STEPS", "BENCH_SCAN"):
        monkeypatch.delenv(name, raising=False)
    flagship = dict(TPU_RESULT, n_steps=40)
    # env fingerprint gate
    monkeypatch.setenv("BENCH_EXCHANGE", "bucketed")
    monkeypatch.setenv("BENCH_BUCKET_MB", "8")
    assert not bench._cacheable(dict(flagship, exchange="bucketed",
                                     bucket_mb=8.0))
    monkeypatch.delenv("BENCH_EXCHANGE", raising=False)
    monkeypatch.delenv("BENCH_BUCKET_MB", raising=False)
    # payload gate (a planted row claiming a variant exchange)
    assert not bench._cacheable(dict(flagship, exchange="reduce_scatter"))
    assert bench._cacheable(dict(flagship, exchange="flat"))
    # legacy rows without the key were flat by construction
    assert bench._cacheable(flagship)
    # ISSUE 11: the striped ratio-sweep legs — same fences, both gates
    monkeypatch.setenv("BENCH_EXCHANGE", "striped")
    monkeypatch.setenv("BENCH_STRIPE_RATIO", "0.5")
    assert not bench._cacheable(dict(flagship, exchange="striped",
                                     stripe_ratio=0.5))
    monkeypatch.delenv("BENCH_EXCHANGE", raising=False)
    # a stray ratio knob ALONE (exchange unset → flat, which ignores
    # it) still flips the fingerprint: the row is a measurement
    assert not bench._cacheable(dict(flagship, exchange="flat"))
    monkeypatch.delenv("BENCH_STRIPE_RATIO", raising=False)
    # payload gate on a planted striped row
    assert not bench._cacheable(dict(flagship, exchange="striped"))


# -- MoE rows are fenced out of the flagship cache (ISSUE 12) ----------------

MOE_ROW = {
    "metric": "moe_lm_train_throughput",
    "value": 21000.0, "unit": "tokens/sec/chip", "vs_baseline": None,
    "platform": "axon", "device_kind": "TPU v5 lite", "n_devices": 8,
    "per_chip_batch": 8, "seq_len": 512, "d_model": 512, "n_layers": 6,
    "exchange": "hierarchical", "two_stage": True, "moe_experts": 8,
    "moe_topk": 1, "dispatch_bytes_dcn": 100, "n_steps": 20,
}


def test_moe_rows_are_never_flagship_cacheable(cache_path, capsys):
    """Even a pristine on-chip MoE row must not enter either cache
    slot: its metric is outside the flagship map (the serving/
    longcontext discipline), so `_cacheable` and the cross-slot
    screens refuse it on every path."""
    assert bench._cacheable(MOE_ROW) is False
    bench._emit(MOE_ROW)                  # persist path
    capsys.readouterr()
    assert not os.path.exists(cache_path)
    assert not os.path.exists(bench._REPO_CACHE_PATH)


def test_planted_moe_entry_is_not_promoted(cache_path, capsys,
                                           monkeypatch):
    """A planted /tmp MoE entry must not be promoted into the committed
    repo slot by a later flagship persist, and the stale re-serve path
    finds nothing to serve under the MoE metric."""
    with open(cache_path, "w") as f:
        json.dump({"entries": {"moe_lm_train_throughput": {
            "run_id": "planted", "saved_at": 9e9,
            "result": MOE_ROW}}}, f)
    for k in ("BENCH_BS", "BENCH_SIZE", "BENCH_STEPS", "BENCH_MODEL",
              "BENCH_EXCHANGE", "BENCH_DONATE"):
        monkeypatch.delenv(k, raising=False)
    bench._emit(dict(TPU_RESULT, per_chip_batch=64, n_steps=40))
    capsys.readouterr()
    with open(bench._REPO_CACHE_PATH) as f:
        entries = json.load(f)["entries"]
    assert "moe_lm_train_throughput" not in entries
    monkeypatch.setenv("BENCH_MODEL", "moe")
    run_id, cached, fp = bench._load_cache("moe_lm_train_throughput")
    assert cached is None


def test_moe_err_metric_and_first_contact_refusal(cache_path, capsys,
                                                  monkeypatch):
    """BENCH_MODEL=moe wires the error path to the MoE metric, and
    first contact (no moe sentinel) refuses any stale re-serve — an
    honest null, the longcontext discipline."""
    monkeypatch.setenv("BENCH_MODEL", "moe")
    assert bench._err_metric() == ("moe_lm_train_throughput",
                                   "tokens/sec/chip")
    assert bench._first_contact("moe")
    bench._emit_stale_or_error("relay wedged")
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["metric"] == "moe_lm_train_throughput"
    assert row["value"] is None
    assert row["first_contact"] is True
    assert "stale" not in row
