"""Artifact-integrity tests for the bench harness's last-good cache.

Round-3 postmortem (VERDICT r3 Missing #1): a 32×32/bs-2 CPU smoke run
persisted by a harness test was re-emitted under the headline
``resnet50_imagenet_train_throughput`` metric when the TPU relay wedged.
The cache is now gated by a config fingerprint on BOTH ends: persistence
(``_emit``) and stale re-emission (``_emit_stale_or_error``).

Pure host-side logic — no jax import, no device touch.
"""

import json
import os

import pytest

import bench


TPU_RESULT = {
    "metric": "resnet50_imagenet_train_throughput",
    "value": 2022.0, "unit": "images/sec/chip", "vs_baseline": 8.99,
    "platform": "axon", "device_kind": "TPU v5 lite", "n_devices": 1,
    "per_chip_batch": 256, "image_size": 224, "layout": "NHWC",
    "compile_s": 109.0,
}

CPU_SMOKE = {
    "metric": "resnet50_imagenet_train_throughput",
    "value": 3.33, "unit": "images/sec/chip", "vs_baseline": 0.015,
    "platform": "cpu", "device_kind": "cpu", "n_devices": 1,
    "per_chip_batch": 2, "image_size": 32, "layout": "NHWC",
    "compile_s": 5.9,
}


@pytest.fixture
def cache_path(tmp_path, monkeypatch):
    path = str(tmp_path / "last_bench.json")
    monkeypatch.setattr(bench, "_CACHE_PATH", path)
    return path


def _last_line(capsys):
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


def test_cacheable_accepts_only_default_config_accelerator_runs():
    assert bench._cacheable(TPU_RESULT)
    assert not bench._cacheable(CPU_SMOKE)
    assert not bench._cacheable({**TPU_RESULT, "platform": "cpu"})
    assert not bench._cacheable({**TPU_RESULT, "platform": "cpu_fallback"})
    assert not bench._cacheable({**TPU_RESULT, "image_size": 32})
    assert not bench._cacheable({**TPU_RESULT, "per_chip_batch": 2})
    assert not bench._cacheable({**TPU_RESULT, "value": None})
    assert not bench._cacheable({**TPU_RESULT, "stale": True})
    assert not bench._cacheable({**TPU_RESULT, "error": "boom"})


def test_cacheable_transformer_needs_real_seq_len():
    base = {"metric": "transformer_lm_train_throughput", "value": 1e5,
            "platform": "axon", "seq_len": 1024}
    assert bench._cacheable(base)
    assert not bench._cacheable({**base, "seq_len": 64})
    assert not bench._cacheable({**base, "platform": "cpu"})


def test_emit_persists_only_cacheable(cache_path, capsys):
    bench._emit(CPU_SMOKE)
    with pytest.raises(FileNotFoundError):
        open(cache_path)
    bench._emit(TPU_RESULT)
    with open(cache_path) as f:
        saved = json.load(f)
    assert saved["result"]["value"] == TPU_RESULT["value"]
    capsys.readouterr()


def test_stale_reemit_refuses_poisoned_cache(cache_path, capsys,
                                             monkeypatch):
    """A cpu-smoke payload planted in the cache file (the round-3
    failure) must NOT be re-served — value:null + the error instead."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    with open(cache_path, "w") as f:
        json.dump({"run_id": "old", "saved_at": 0.0,
                   "result": CPU_SMOKE}, f)
    bench._emit_stale_or_error("deadline exceeded before first result")
    out = _last_line(capsys)
    assert out["value"] is None
    assert "deadline" in out["error"]
    assert out["metric"] == "resnet50_imagenet_train_throughput"


def test_stale_reemit_serves_real_tpu_datum(cache_path, capsys,
                                            monkeypatch):
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setenv("BENCH_RUN_ID", "current-run")
    with open(cache_path, "w") as f:
        json.dump({"run_id": "earlier-run", "saved_at": 0.0,
                   "result": TPU_RESULT}, f)
    bench._emit_stale_or_error("relay wedged")
    out = _last_line(capsys)
    assert out["value"] == TPU_RESULT["value"]
    assert out["stale"] is True
    assert out["platform"] == "axon"
    assert out["error"] == "relay wedged"


def test_stale_reemit_never_repersists(cache_path, capsys, monkeypatch):
    """Re-emission must not refresh the cache file (stale results would
    otherwise look newer on every failure)."""
    monkeypatch.delenv("BENCH_MODEL", raising=False)
    monkeypatch.setenv("BENCH_RUN_ID", "current-run")
    with open(cache_path, "w") as f:
        json.dump({"run_id": "earlier-run", "saved_at": 123.0,
                   "result": TPU_RESULT}, f)
    bench._emit_stale_or_error("still wedged")
    with open(cache_path) as f:
        assert json.load(f)["saved_at"] == 123.0
    capsys.readouterr()


@pytest.mark.slow
def test_supervisor_emits_error_line_when_child_wedges(tmp_path):
    """The core driver contract (VERDICT r2 Missing #1): a child wedged
    before ANY output AND ignoring SIGTERM (a thread stuck in a C call
    never runs handlers) — the known relay failure mode — must still
    yield exactly one authoritative JSON line from the no-jax
    supervisor's terminate→kill escalation, within the deadline,
    refusing stale re-emission when no valid cache exists."""
    import subprocess
    import sys
    import time as _time

    # point the cache at an empty tmp location: no stale datum to serve
    env = dict(os.environ, BENCH_TEST_WEDGE="1", BENCH_DEADLINE_S="8",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"))
    env.pop("BENCH_MODEL", None)  # a leaked transformer mode would flip
    # the expected metric (the queue script sets it for its own runs)
    start = _time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench.py")],
        env=env, capture_output=True, text=True, timeout=60)
    elapsed = _time.monotonic() - start
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, proc.stdout
    out = json.loads(lines[-1])
    assert out["value"] is None
    assert "deadline" in out["error"] or "terminated" in out["error"]
    assert out["metric"] == "resnet50_imagenet_train_throughput"
    assert elapsed < 45, f"supervisor took {elapsed:.0f}s for an 8s deadline"


@pytest.mark.slow
def test_gloo_scaling_harness_two_process(tmp_path):
    """bench_scaling --gloo-procs mechanics: the real cross-process
    compiled-DP measurement (VERDICT r3 Missing #4's instrument) keeps
    working — rows parse, per-hop summary present."""
    import subprocess
    import sys

    import signal

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    # own session: a timeout must reap the gloo worker grandchildren
    # too, not just the bench_scaling parent
    proc = subprocess.Popen(
        [sys.executable, os.path.join(root, "bench_scaling.py"),
         "--gloo-procs", "1,2", "--per-chip-bs", "8", "--steps", "5",
         "--gloo-hidden", "32"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=420)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        proc.communicate()
        raise
    assert proc.returncode == 0, stderr[-2000:]
    rows = [json.loads(ln) for ln in stdout.splitlines()
            if ln.startswith("{")]
    by_procs = {r["processes"]: r for r in rows if "step_ms" in r}
    assert set(by_procs) == {1, 2}
    assert all(r["step_ms"] > 0 for r in by_procs.values())
    summary = [r for r in rows if "per_hop_overhead_raw_ms" in r]
    assert summary and summary[0]["processes"] == 2
    assert "overhead_vs_serialized_compute_ms" in summary[0]
