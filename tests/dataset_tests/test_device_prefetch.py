"""DevicePrefetchIterator: device placement, stream equivalence, resume.

The device-feed stage must be a transparent wrapper: same batch stream
and epoch bookkeeping as the base iterator, batches already resident on
device (optionally sharded), and bit-exact snapshot/resume at the
CONSUMER position regardless of prefetch depth.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.dataset import (DevicePrefetchIterator, SerialIterator,
                                   concat_examples)
from chainermn_tpu.serializers.npz import (DictionarySerializer,
                                           NpzDeserializer)


def _dataset(n=20):
    rng = np.random.RandomState(0)
    return [(rng.normal(0, 1, (4,)).astype(np.float32), i) for i in range(n)]


def test_stream_and_epochs_match_base():
    data = _dataset()
    ref = SerialIterator(data, 4, shuffle=True, seed=7)
    pref = DevicePrefetchIterator(
        SerialIterator(data, 4, shuffle=True, seed=7), size=3,
        converter=concat_examples)
    for _ in range(12):
        rb = concat_examples(ref.next())
        pb = pref.next()
        np.testing.assert_array_equal(np.asarray(pb[0]), rb[0])
        np.testing.assert_array_equal(np.asarray(pb[1]), rb[1])
        assert isinstance(pb[0], jax.Array)  # actually placed on device
        assert pref.epoch == ref.epoch
        assert pref.is_new_epoch == ref.is_new_epoch
        np.testing.assert_allclose(pref.epoch_detail, ref.epoch_detail)


def test_sharded_placement():
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    data = _dataset(32)
    pref = DevicePrefetchIterator(
        SerialIterator(data, 8, shuffle=False), size=2,
        sharding=sharding, converter=concat_examples)
    x, t = pref.next()
    assert x.sharding == sharding
    assert len(x.addressable_shards) == len(jax.devices())


def test_resume_is_bit_exact_despite_prefetch_depth():
    data = _dataset(24)

    def build():
        return DevicePrefetchIterator(
            SerialIterator(data, 4, shuffle=True, seed=3), size=3,
            converter=concat_examples)

    it = build()
    seen = [np.asarray(it.next()[1]) for _ in range(5)]
    # snapshot mid-stream: the prefetch buffer holds batches the
    # consumer has NOT seen — they must be replayed after resume
    s = DictionarySerializer()
    it.serialize(s)
    cont = [np.asarray(it.next()[1]) for _ in range(6)]

    it2 = build()
    it2.serialize(NpzDeserializer(s.target))
    resumed = [np.asarray(it2.next()[1]) for _ in range(6)]
    for a, b in zip(cont, resumed):
        np.testing.assert_array_equal(a, b)


def test_multithread_base_stream_and_resume():
    """DevicePrefetchIterator stacked over a MultithreadIterator base
    (prefetch-thread + device-feed, the full input pipeline): stream
    matches the serial order and mid-stream resume stays exact."""
    from chainermn_tpu.dataset import MultithreadIterator
    data = _dataset(24)

    def build():
        return DevicePrefetchIterator(
            MultithreadIterator(data, 4, shuffle=True, seed=3), size=2,
            converter=concat_examples)

    it = build()
    ref = SerialIterator(data, 4, shuffle=True, seed=3)
    for _ in range(5):
        np.testing.assert_array_equal(
            np.asarray(it.next()[1]),
            np.asarray(concat_examples(ref.next())[1]))
    s = DictionarySerializer()
    it.serialize(s)
    cont = [np.asarray(it.next()[1]) for _ in range(4)]
    it.finalize()

    it2 = build()
    it2.serialize(NpzDeserializer(s.target))
    resumed = [np.asarray(it2.next()[1]) for _ in range(4)]
    it2.finalize()
    for a, b in zip(cont, resumed):
        np.testing.assert_array_equal(a, b)


def test_native_base_stream_and_resume():
    """DevicePrefetchIterator stacked over a NativeBatchIterator base
    (C++ gather + device feed): now that the native iterator serializes
    at consumer granularity, the full composed pipeline must resume
    bit-exactly too."""
    import pytest

    from chainermn_tpu.utils.native import load_library
    if load_library() is None:
        pytest.skip("native loader unavailable")
    from chainermn_tpu.dataset import TupleDataset
    from chainermn_tpu.dataset.native_iterator import NativeBatchIterator
    xs = np.random.RandomState(0).normal(
        0, 1, (24, 4)).astype(np.float32)
    ys = np.arange(24, dtype=np.int32)

    def build():
        return DevicePrefetchIterator(
            NativeBatchIterator(TupleDataset(xs, ys), 4, shuffle=True,
                                seed=3, n_prefetch=2), size=2)

    it = build()
    for _ in range(5):
        it.next()
    s = DictionarySerializer()
    it.serialize(s)
    cont = [np.asarray(it.next()[1]) for _ in range(6)]
    it.finalize()

    it2 = build()
    it2.serialize(NpzDeserializer(s.target))
    resumed = [np.asarray(it2.next()[1]) for _ in range(6)]
    it2.finalize()
    for a, b in zip(cont, resumed):
        np.testing.assert_array_equal(a, b)


def test_multiprocess_base_stream_and_resume():
    """DevicePrefetchIterator stacked over the process-pool iterator
    (worker processes + shared-memory slots + overlapped device feed —
    the full reference pipeline): stream matches serial order and
    mid-stream resume stays exact."""
    from chainermn_tpu.dataset import MultiprocessIterator
    data = _dataset(24)

    def build():
        return DevicePrefetchIterator(
            MultiprocessIterator(data, 4, shuffle=True, seed=3,
                                 n_processes=2), size=2,
            converter=concat_examples)

    it = build()
    ref = SerialIterator(data, 4, shuffle=True, seed=3)
    for _ in range(5):
        np.testing.assert_array_equal(
            np.asarray(it.next()[1]),
            np.asarray(concat_examples(ref.next())[1]))
    s = DictionarySerializer()
    it.serialize(s)
    cont = [np.asarray(it.next()[1]) for _ in range(4)]
    it.finalize()

    it2 = build()
    it2.serialize(NpzDeserializer(s.target))
    resumed = [np.asarray(it2.next()[1]) for _ in range(4)]
    it2.finalize()
    for a, b in zip(cont, resumed):
        np.testing.assert_array_equal(a, b)


def test_overlap_off_matches_overlap_on():
    """The synchronous fill (overlap=False) and the feeder thread
    (overlap=True) are the same stream — only the scheduling differs."""
    data = _dataset(20)
    a = DevicePrefetchIterator(
        SerialIterator(data, 4, shuffle=True, seed=11), size=3,
        converter=concat_examples, overlap=False)
    b = DevicePrefetchIterator(
        SerialIterator(data, 4, shuffle=True, seed=11), size=3,
        converter=concat_examples, overlap=True)
    for _ in range(8):
        ba, bb = a.next(), b.next()
        np.testing.assert_array_equal(np.asarray(ba[1]),
                                      np.asarray(bb[1]))
        assert a.epoch == b.epoch
        np.testing.assert_allclose(a.epoch_detail, b.epoch_detail)
    a.finalize()
    b.finalize()


def test_input_stall_accounting():
    """input_stall_ms counts only time next() blocked on the feed —
    a slow consumer over a fast feed accumulates ~none."""
    import time as _time
    data = _dataset(16)
    it = DevicePrefetchIterator(
        SerialIterator(data, 4, shuffle=False), size=2,
        converter=concat_examples)
    it.next()
    first_stall = it.input_stall_ms  # pipeline cold: some stall expected
    for _ in range(4):
        _time.sleep(0.02)  # feeder refills while the "step" runs
        it.next()
    assert it.input_stall_ms >= first_stall  # monotone counter
    assert it.input_stall_ms - first_stall < 60.0  # feed kept up
    it.finalize()


def test_feeder_error_is_sticky_not_a_hang():
    """A converter/base error crossing from the feeder thread must be
    sticky: the feeder is dead, so a retrying caller's next next() has
    to re-raise instead of blocking forever on the empty queue."""
    import pytest
    data = _dataset(16)
    calls = [0]

    def bad_converter(batch):
        calls[0] += 1
        if calls[0] == 2:
            raise ValueError("converter blew up")
        return concat_examples(batch)

    it = DevicePrefetchIterator(
        SerialIterator(data, 4, shuffle=False), size=2,
        converter=bad_converter)
    with pytest.raises(ValueError, match="converter blew up"):
        for _ in range(4):
            it.next()
    with pytest.raises(ValueError, match="converter blew up"):
        it.next()  # sticky — must not block on the dead feeder's queue
    it.finalize()


def test_finalize_is_idempotent_and_stops_feeder():
    data = _dataset(16)
    it = DevicePrefetchIterator(
        SerialIterator(data, 4, shuffle=False), size=2,
        converter=concat_examples)
    it.next()
    it.finalize()
    it.finalize()
    t = getattr(it, "_thread", None)
    assert t is None or not t.is_alive()


def test_non_repeating_drains():
    data = _dataset(8)
    pref = DevicePrefetchIterator(
        SerialIterator(data, 4, repeat=False, shuffle=False), size=4,
        converter=concat_examples)
    batches = []
    try:
        while True:
            batches.append(pref.next())
    except StopIteration:
        pass
    assert len(batches) == 2
    got = np.concatenate([np.asarray(b[1]) for b in batches])
    np.testing.assert_array_equal(np.sort(got), np.arange(8))


def test_trainer_integration():
    """End-to-end: DevicePrefetchIterator feeding a Trainer with the
    identity converter trains normally and resumes its position."""
    import chainermn_tpu as ct
    from chainermn_tpu import F, L
    from chainermn_tpu.core.optimizer import SGD
    from chainermn_tpu.dataset import identity_converter
    from chainermn_tpu.training import StandardUpdater, Trainer

    class M(ct.Chain):
        def __init__(self):
            super().__init__()
            with self.init_scope():
                self.l1 = L.Linear(4, 3, seed=0)

        def forward(self, x, t):
            return F.softmax_cross_entropy(self.l1(x), t)

    rng = np.random.RandomState(1)
    data = [(rng.normal(0, 1, (4,)).astype(np.float32),
             rng.randint(0, 3)) for _ in range(32)]
    model = M()
    opt = SGD(lr=0.1).setup(model)
    it = DevicePrefetchIterator(
        SerialIterator(data, 8, shuffle=True, seed=0), size=2,
        converter=concat_examples)
    upd = StandardUpdater(it, opt, converter=identity_converter)
    trainer = Trainer(upd, (8, "iteration"), out="/tmp/dpref_out")
    trainer.run()
    assert upd.iteration == 8
    assert it.epoch == 2  # 32/8 = 4 iterations per epoch
