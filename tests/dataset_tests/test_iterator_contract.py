"""One contract, four iterator classes.

Serial / Multithread / Multiprocess / NativeBatch all promise the same
consumer-visible behavior (SURVEY §2.8 iterators row): identical batch
stream for identical (shuffle, seed), `SerialIterator`-parity epoch
bookkeeping, consumer-granularity ``serialize`` (mid-epoch resume
replays exactly what the uninterrupted run would have delivered,
regardless of prefetch depth), and idempotent ``finalize``.  The
process iterator additionally promises typed worker-failure propagation
and an unordered mode that still respects epoch boundaries.

Everything here is fast and deterministic — tier-1, no ``slow`` marker.
"""

import os

import numpy as np
import pytest

from chainermn_tpu.dataset import (MultiprocessIterator,
                                   MultithreadIterator, SerialIterator,
                                   TupleDataset)
from chainermn_tpu.dataset.multiprocess_iterator import (
    IteratorWorkerCrashed, IteratorWorkerError)
from chainermn_tpu.serializers.npz import (DictionarySerializer,
                                           NpzDeserializer)

KINDS = ["serial", "thread", "process", "native"]

N = 24
BS = 4


def _data(n=N):
    rng = np.random.RandomState(0)
    return [(rng.normal(0, 1, (4,)).astype(np.float32), np.int64(i))
            for i in range(n)]


def _make(kind, n=N, batch_size=BS, **kw):
    data = _data(n)
    if kind == "serial":
        return SerialIterator(data, batch_size, **kw)
    if kind == "thread":
        return MultithreadIterator(data, batch_size, **kw)
    if kind == "process":
        return MultiprocessIterator(data, batch_size, n_processes=2,
                                    **kw)
    if kind == "native":
        from chainermn_tpu.utils.native import load_library
        if load_library() is None:
            pytest.skip("native loader unavailable (no g++ toolchain)")
        from chainermn_tpu.dataset.native_iterator import \
            NativeBatchIterator
        xs = np.stack([x for x, _ in data])
        ys = np.asarray([int(y) for _, y in data], np.int64)
        return NativeBatchIterator(TupleDataset(xs, ys), batch_size, **kw)
    raise AssertionError(kind)


def _labels(batch):
    """Per-example integer labels, whatever the batch convention:
    list-of-example-tuples (serial/thread/process) or pre-stacked
    array tuple (native)."""
    if isinstance(batch, tuple):
        return [int(v) for v in batch[1]]
    return [int(l) for _, l in batch]


@pytest.fixture(params=KINDS)
def kind(request):
    return request.param


def test_stream_and_epoch_parity_with_serial(kind):
    """Same (shuffle, seed) ⇒ same batch stream as SerialIterator, and
    epoch / is_new_epoch / epoch_detail / previous_epoch_detail move in
    lock-step with the consumer."""
    ref = SerialIterator(_data(), BS, shuffle=True, seed=5)
    it = _make(kind, shuffle=True, seed=5)
    try:
        for _ in range(2 * (N // BS) + 3):  # crosses two epoch bounds
            assert _labels(it.next()) == _labels(ref.next())
            assert it.epoch == ref.epoch
            assert it.is_new_epoch == ref.is_new_epoch
            assert it.epoch_detail == pytest.approx(ref.epoch_detail)
            assert it.previous_epoch_detail == pytest.approx(
                ref.previous_epoch_detail)
    finally:
        it.finalize()


def test_resume_mid_epoch(kind):
    """Snapshot mid-epoch (prefetch pipelines running ahead), resume in
    a fresh instance: the continuation replays exactly the batches the
    uninterrupted run delivered."""
    it = _make(kind, shuffle=True, seed=3)
    for _ in range(3):  # mid-epoch: 3 of 6 batches consumed
        it.next()
    s = DictionarySerializer()
    it.serialize(s)
    cont = [_labels(it.next()) for _ in range(8)]  # crosses the bound
    it.finalize()

    it2 = _make(kind, shuffle=True, seed=3)
    it2.serialize(NpzDeserializer(s.target))
    resumed = [_labels(it2.next()) for _ in range(8)]
    it2.finalize()
    assert cont == resumed


def test_snapshot_keys_interchangeable_with_serial(kind):
    """All four classes serialize the consumer position under the same
    keys, so a snapshot from any of them resumes a SerialIterator (and
    vice versa) at the same stream position."""
    it = _make(kind, shuffle=True, seed=9)
    for _ in range(4):
        it.next()
    s = DictionarySerializer()
    it.serialize(s)
    cont = _labels(it.next())
    it.finalize()

    ref = SerialIterator(_data(), BS, shuffle=True, seed=9)
    ref.serialize(NpzDeserializer(s.target))
    assert _labels(ref.next()) == cont


def test_non_repeat_drains_exactly(kind):
    it = _make(kind, repeat=False, shuffle=False)
    seen = []
    try:
        while True:
            seen.extend(_labels(it.next()))
    except StopIteration:
        pass
    try:
        assert sorted(seen) == list(range(N))
        with pytest.raises(StopIteration):
            it.next()  # exhausted stays exhausted
    finally:
        it.finalize()


def test_double_finalize_is_idempotent(kind):
    it = _make(kind)
    it.next()
    it.finalize()
    it.finalize()  # second teardown must be a no-op, not an error


def test_finalize_without_consuming(kind):
    """Teardown with the pipeline full (nothing consumed) must not hang
    or leak: the prefetch depth of batches is simply dropped."""
    it = _make(kind)
    it.finalize()
    it.finalize()


# -- process-pool specifics -------------------------------------------------

def test_process_ordered_matches_serial_unordered_keeps_epochs():
    ref = SerialIterator(_data(), BS, shuffle=True, seed=1)
    ordered = MultiprocessIterator(_data(), BS, shuffle=True, seed=1,
                                   n_processes=2, ordered=True)
    unordered = MultiprocessIterator(_data(), BS, shuffle=True, seed=1,
                                     n_processes=2, ordered=False)
    try:
        per_epoch = N // BS
        for _ in range(per_epoch):
            assert _labels(ordered.next()) == _labels(ref.next())
        for epoch in range(2):
            got = sorted(l for _ in range(per_epoch)
                         for l in _labels(unordered.next()))
            # completion order may differ, but every epoch still
            # delivers the full example multiset before the next starts
            assert got == list(range(N)), epoch
    finally:
        ordered.finalize()
        unordered.finalize()


def test_process_transform_error_is_typed():
    class Boom:
        def __len__(self):
            return 12

        def __getitem__(self, i):
            if i == 9:
                raise ValueError("bad example 9")
            return (np.zeros(3, np.float32), np.int64(i))

    it = MultiprocessIterator(Boom(), 4, shuffle=False, n_processes=2)
    try:
        with pytest.raises(IteratorWorkerError) as ei:
            for _ in range(3):
                it.next()
        assert "bad example 9" in str(ei.value)
        assert "ValueError" in str(ei.value)  # worker traceback attached
        with pytest.raises(IteratorWorkerError):
            it.next()  # pipeline error is sticky, not silently resumed
    finally:
        it.finalize()


def test_process_worker_crash_is_typed():
    class Crash:
        def __len__(self):
            return 12

        def __getitem__(self, i):
            if i == 9:
                os._exit(7)  # simulate segfault/OOM-kill: no traceback
            return (np.zeros(3, np.float32), np.int64(i))

    it = MultiprocessIterator(Crash(), 4, shuffle=False, n_processes=2)
    try:
        with pytest.raises(IteratorWorkerCrashed) as ei:
            for _ in range(3):
                it.next()
        assert ei.value.exitcode == 7
    finally:
        it.finalize()


def test_thread_transform_error_propagates_and_is_sticky():
    class Boom:
        def __len__(self):
            return 12

        def __getitem__(self, i):
            if i == 9:
                raise ValueError("bad example 9")
            return (np.zeros(3, np.float32), np.int64(i))

    it = MultithreadIterator(Boom(), 4, shuffle=False)
    try:
        with pytest.raises(ValueError, match="bad example 9"):
            for _ in range(3):
                it.next()
        # sticky: the worker thread is dead — a retrying caller must get
        # the error again, not block forever on the empty queue
        with pytest.raises(ValueError, match="bad example 9"):
            it.next()
    finally:
        it.finalize()


def test_process_unordered_refuses_midstream_snapshot():
    """ordered=False delivery diverges from the schedule-order shadow:
    a mid-stream snapshot would resume with duplicated/dropped examples,
    so the writer must refuse loudly instead of corrupting the epoch."""
    it = MultiprocessIterator(_data(), BS, shuffle=True, seed=4,
                              n_processes=2, ordered=False)
    try:
        s = DictionarySerializer()
        it.serialize(s)  # nothing consumed yet: shadow == stream, fine
        it.next()
        with pytest.raises(RuntimeError, match="ordered=True"):
            it.serialize(DictionarySerializer())
    finally:
        it.finalize()


def test_process_slow_batch_tolerated_while_others_progress():
    """The no-progress deadline resets on every completed batch: ONE
    legitimately slow batch must not break a pipeline whose other
    workers keep delivering (the timeout is for dead-but-alive pools,
    not skewed transform cost)."""
    import time as _time

    class Skewed:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            if i == 0:
                _time.sleep(4.0)  # far beyond worker_timeout
            elif i % 2:
                _time.sleep(0.6)  # steady sibling progress
            return (np.zeros(2, np.float32), np.int64(i))

    # n_prefetch keeps the sibling worker supplied with tasks for the
    # whole duration of the slow batch, so results keep arriving
    it = MultiprocessIterator(Skewed(), 2, shuffle=False, n_processes=2,
                              n_prefetch=8, worker_timeout=2.0)
    try:
        labels = [l for _ in range(4) for _, l in it.next()]
        assert labels == list(range(8))
    finally:
        it.finalize()


def test_process_reset_restarts_stream():
    it = MultiprocessIterator(_data(), BS, repeat=False, shuffle=True,
                              seed=2, n_processes=2)
    try:
        first = [_labels(it.next()) for _ in range(3)]
        it.reset()
        again = [_labels(it.next()) for _ in range(3)]
        assert first == again
    finally:
        it.finalize()


def test_process_pickle_fallback_for_ragged_examples():
    """Examples whose shapes disagree with the probe can't use the
    shared-memory slots — the batch must still arrive (pickled),
    correct and in order."""

    class Ragged:
        def __len__(self):
            return 12

        def __getitem__(self, i):
            return (np.full(2 + (i % 3), i, np.float32), np.int64(i))

    it = MultiprocessIterator(Ragged(), 4, shuffle=False, n_processes=2)
    try:
        batch = it.next()
        assert [int(l) for _, l in batch] == [0, 1, 2, 3]
        assert batch[2][0].shape == (4,)  # ragged payload intact
    finally:
        it.finalize()


def test_process_scalar_and_multifield_layout():
    """Slot layout handles >2 fields and scalar fields."""
    class Three:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return (np.full((2, 2), i, np.float32), np.int64(i),
                    np.float32(i) / 2)

    it = MultiprocessIterator(Three(), 4, shuffle=False, n_processes=2)
    try:
        b = it.next()
        assert len(b) == 4 and len(b[1]) == 3
        np.testing.assert_array_equal(b[3][0], np.full((2, 2), 3))
        assert float(b[3][2]) == pytest.approx(1.5)
    finally:
        it.finalize()


def test_process_as_arrays_matches_native_convention():
    it = MultiprocessIterator(_data(), BS, shuffle=False, n_processes=2,
                              as_arrays=True)
    try:
        x, y = it.next()
        assert x.shape == (BS, 4) and y.shape == (BS,)
        np.testing.assert_array_equal(y, np.arange(BS))
    finally:
        it.finalize()
