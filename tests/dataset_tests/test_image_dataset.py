"""File-based image datasets (npy + PIL paths)."""

import os

import numpy as np
import pytest

from chainermn_tpu.dataset import ImageDataset, LabeledImageDataset


@pytest.fixture
def image_dir(tmp_path):
    rng = np.random.RandomState(0)
    paths = []
    for i in range(4):
        arr = rng.randint(0, 255, (5, 6, 3)).astype(np.uint8)  # HWC
        p = tmp_path / f"im{i}.npy"
        np.save(str(p), arr)
        paths.append(f"im{i}.npy")
    try:
        from PIL import Image
        png = rng.randint(0, 255, (5, 6, 3)).astype(np.uint8)
        Image.fromarray(png).save(str(tmp_path / "im_png.png"))
        paths.append("im_png.png")
    except ImportError:
        pass
    return str(tmp_path), paths


def test_image_dataset(image_dir):
    root, paths = image_dir
    ds = ImageDataset(paths, root=root)
    assert len(ds) == len(paths)
    img = ds[0]
    assert img.shape == (3, 5, 6)       # CHW
    assert img.dtype == np.float32
    if len(paths) == 5:                  # the PNG
        assert ds[4].shape == (3, 5, 6)


def test_labeled_image_dataset_and_listfile(image_dir, tmp_path):
    root, paths = image_dir
    pairs = [(p, i % 3) for i, p in enumerate(paths[:4])]
    ds = LabeledImageDataset(pairs, root=root)
    img, label = ds[1]
    assert img.shape == (3, 5, 6) and int(label) == 1

    listfile = tmp_path / "list.txt"
    listfile.write_text("".join(f"{p} {l}\n" for p, l in pairs))
    ds2 = LabeledImageDataset(str(listfile), root=root)
    assert len(ds2) == 4
    img2, label2 = ds2[2]
    np.testing.assert_array_equal(img2, ds[2][0])
