"""DLPack zero-copy host bridge (VERDICT r1 missing #5 / SURVEY §2.8).

The CPU backend can alias numpy buffers; the tests pin the no-copy
property by observing shared memory, and the NativeBatchIterator
hand-off exercises the bridge end-to-end.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chainermn_tpu.utils.dlpack import from_numpy, to_numpy


def _is_cpu():
    return jax.default_backend() == "cpu"


def test_from_numpy_import_contract():
    """Import direction: standard DLPack semantics — the result either
    aliases the source (zero-copy, observed on the simulated-mesh CPU
    backend) or holds an isolated copy; both are valid, and callers must
    not mutate the source after importing (documented contract)."""
    a = np.arange(16, dtype=np.float32)
    j = from_numpy(a)
    assert isinstance(j, jax.Array)
    np.testing.assert_array_equal(np.asarray(j), np.arange(16))
    a[0] = 99.0
    assert float(j[0]) in (0.0, 99.0)  # copied | aliased (zero-copy)
    np.testing.assert_array_equal(np.asarray(j)[1:], a[1:])


def test_to_numpy_zero_copy_on_cpu():
    if not _is_cpu():
        pytest.skip("aliasing property is CPU-backend-specific")
    j = jnp.arange(32, dtype=jnp.float32)
    n = to_numpy(j)
    n2 = to_numpy(j)
    assert n.__array_interface__["data"][0] == \
        n2.__array_interface__["data"][0]  # stable view, not fresh copies


def test_bridge_total_on_any_input():
    # non-contiguous, scalars, lists: must still convert (copying is fine)
    a = np.arange(24, dtype=np.float32).reshape(4, 6)[:, ::2]
    assert not a.flags.c_contiguous
    j = from_numpy(a)
    np.testing.assert_array_equal(np.asarray(j), a)
    assert from_numpy([1.0, 2.0]).shape == (2,)
    assert float(to_numpy(jnp.float32(3.5))) == 3.5


def test_to_device_routes_numpy_through_bridge():
    from chainermn_tpu.dataset import to_device
    x = {"a": np.arange(8, dtype=np.float32), "b": [np.ones(3, np.float32)]}
    placed = jax.tree.leaves(to_device(x))
    assert all(isinstance(leaf, jax.Array) for leaf in placed)
    np.testing.assert_array_equal(np.asarray(placed[0]), x["a"])


def test_native_iterator_zero_copy_handoff():
    from chainermn_tpu.utils.native import load_library
    if load_library() is None:
        pytest.skip("native loader unavailable")
    from chainermn_tpu.dataset.native_iterator import NativeBatchIterator
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    labels = np.arange(10, dtype=np.int32)
    it = NativeBatchIterator((data, labels), 5, shuffle=False,
                             zero_copy=True, n_prefetch=1)
    seen = []
    for _ in range(4):  # two epochs: ring slots recycle correctly
        x, t = it.next()
        assert isinstance(x, jax.Array) and isinstance(t, jax.Array)
        seen.append(np.asarray(x).copy())
        # batch contents are correct at consumption time
        np.testing.assert_array_equal(np.asarray(x), data[np.asarray(t)])
    it.finalize()
    full = np.concatenate(seen[:2])
    np.testing.assert_array_equal(full, data)


def test_zero_copy_view_outlives_loader():
    """Ring memory is python-owned (numpy), lent to the C++ engine: a
    view held past finalize() may go STALE in content but must never
    dangle.  Regression for a shutdown segfault: zero_copy batches still
    referenced when the loader closed dereferenced freed C++ heap."""
    from chainermn_tpu.utils.native import NativeLoader, load_library
    if load_library() is None:
        pytest.skip("native loader unavailable")
    data = np.arange(80, dtype=np.float32).reshape(20, 4)
    loader = NativeLoader(data, 5, n_buffers=2)
    loader.submit(np.arange(5, dtype=np.int64))
    view, buf_id = loader.next_view()
    # the view must alias the PYTHON-owned ring — the load-bearing
    # assertion: a regression back to C++-owned buffers (raw-pointer
    # frombuffer) would pass the post-close read below, because freed
    # heap pages are usually still mapped outside ASAN
    assert np.shares_memory(view, loader._ring), \
        "zero-copy view does not alias the python-owned ring"
    expect = view.copy()
    loader.release(buf_id)
    loader.close()  # destroys the C++ engine while `view` is still held
    # reading the held view after close must be safe: memory stays valid
    # via numpy ownership (content is whatever the last fill left — no
    # new fill happened after our batch, so it is still our batch)
    np.testing.assert_array_equal(view, expect)

    # and the full zero_copy iterator flow stays alive through the same
    # sequence (jax may import the DLPack capsule by copy or by alias;
    # either way nothing may crash)
    from chainermn_tpu.dataset.native_iterator import NativeBatchIterator
    labels = np.arange(20, dtype=np.int32)
    it = NativeBatchIterator((data, labels), 5, shuffle=False,
                             zero_copy=True, n_prefetch=1)
    x, t = it.next()
    it.finalize()
    assert np.asarray(x).shape == (5, 4)
    assert np.isfinite(np.asarray(x)).all()


def test_serializer_uses_bridge(tmp_path):
    from chainermn_tpu.serializers.npz import DictionarySerializer
    s = DictionarySerializer()
    s("w", jnp.arange(4, dtype=jnp.float32))
    np.testing.assert_array_equal(s.target["w"], [0, 1, 2, 3])
