"""Native C++ batch-assembly engine + iterator."""

import numpy as np
import pytest

from chainermn_tpu.dataset.datasets import TupleDataset

native = pytest.importorskip("chainermn_tpu.utils.native")


@pytest.fixture(scope="module")
def lib():
    lib = native.load_library()
    if lib is None:
        pytest.skip("g++ unavailable")
    return lib


def test_native_loader_gathers_rows(lib):
    data = np.arange(100 * 16, dtype=np.float32).reshape(100, 16)
    loader = native.NativeLoader(data, max_batch=8)
    idx = np.asarray([3, 97, 0, 42], dtype=np.int64)
    loader.submit(idx)
    batch = loader.next()
    np.testing.assert_array_equal(batch, data[idx])
    loader.close()


def test_native_loader_backpressure_many_batches(lib):
    data = np.random.RandomState(0).normal(
        0, 1, (256, 32)).astype(np.float32)
    loader = native.NativeLoader(data, max_batch=16, n_buffers=2)
    rng = np.random.RandomState(1)
    batches = []
    submitted = []
    for _ in range(20):
        idx = rng.randint(0, 256, 16).astype(np.int64)
        submitted.append(idx)
        loader.submit(idx)
        batches.append(loader.next())
    for idx, b in zip(submitted, batches):
        np.testing.assert_array_equal(b, data[idx])
    loader.close()


def test_native_loader_rejects_bad_indices(lib):
    data = np.zeros((10, 4), np.float32)
    loader = native.NativeLoader(data, max_batch=4)
    with pytest.raises(ValueError):
        loader.submit(np.asarray([0, 99], dtype=np.int64))
    loader.close()


def test_native_batch_iterator_epoch_coverage(lib):
    from chainermn_tpu.dataset.native_iterator import NativeBatchIterator
    x = np.arange(64, dtype=np.float32).reshape(64, 1)
    y = np.arange(64, dtype=np.int32)
    it = NativeBatchIterator(TupleDataset(x, y), 16, shuffle=True, seed=0)
    seen = []
    for _ in range(4):
        bx, by = it.next()
        assert bx.shape == (16, 1)
        np.testing.assert_array_equal(bx[:, 0].astype(np.int32), by)
        seen.extend(by.tolist())
    assert sorted(seen) == list(range(64))
    assert it.epoch == 1
    it.finalize()


def test_native_batch_iterator_no_repeat_stops(lib):
    from chainermn_tpu.dataset.native_iterator import NativeBatchIterator
    x = np.zeros((32, 4), np.float32)
    it = NativeBatchIterator(x, 16, repeat=False, shuffle=False)
    assert it.next().shape == (16, 4)
    assert it.next().shape == (16, 4)
    with pytest.raises(StopIteration):
        it.next()
    it.finalize()


def test_native_iterator_trains_with_updater(lib):
    from chainermn_tpu.dataset.native_iterator import NativeBatchIterator
    from chainermn_tpu.dataset.convert import identity_converter
    from chainermn_tpu.core.optimizer import Adam
    from chainermn_tpu.models import Classifier, MLP
    from chainermn_tpu.training import StandardUpdater, Trainer

    rng = np.random.RandomState(0)
    x = rng.normal(0, 1, (128, 8)).astype(np.float32)
    t = rng.randint(0, 3, 128).astype(np.int32)
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    opt = Adam().setup(model)
    it = NativeBatchIterator(TupleDataset(x, t), 32, seed=1)
    updater = StandardUpdater(it, opt, converter=identity_converter)
    trainer = Trainer(updater, (8, "iteration"), out="/tmp/native_it_out")
    trainer.run()
    assert opt.t == 8
    it.finalize()


def test_native_iterator_serialize_resume_exact(lib):
    """Consumer-granularity snapshot (reference MultiprocessIterator
    contract): save after K consumed batches, resume in a FRESH
    iterator, and the continued stream must be batch-for-batch
    identical to the uninterrupted one — across epoch boundaries and
    regardless of the n_prefetch submissions in flight at save time."""
    from chainermn_tpu.dataset.native_iterator import NativeBatchIterator
    from chainermn_tpu.serializers.npz import (DictionarySerializer,
                                               NpzDeserializer)
    x = np.arange(40, dtype=np.float32).reshape(40, 1)
    y = np.arange(40, dtype=np.int32)

    def fresh():
        return NativeBatchIterator(TupleDataset(x, y), 8, shuffle=True,
                                   seed=7, n_prefetch=3)

    for k in (2, 4, 7):  # mid-epoch, boundary-adjacent, into epoch 2
        it = fresh()
        for _ in range(k):
            it.next()
        s = DictionarySerializer()
        it.serialize(s)
        golden = [(it.next()[1].tolist(), it.epoch, it.is_new_epoch,
                   it.epoch_detail) for _ in range(6)]
        it.finalize()

        it2 = fresh()
        it2.serialize(NpzDeserializer(s.target))
        resumed = [(it2.next()[1].tolist(), it2.epoch, it2.is_new_epoch,
                    it2.epoch_detail) for _ in range(6)]
        it2.finalize()
        assert golden == resumed, f"diverged after k={k}"


def test_native_iterator_resumes_serial_iterator_snapshot(lib):
    """Drop-in contract: a snapshot written by SerialIterator (shared
    key names, no native-only keys) must restore cleanly under the
    STRICT reader and continue the same index stream."""
    from chainermn_tpu.dataset.iterators import SerialIterator
    from chainermn_tpu.dataset.native_iterator import NativeBatchIterator
    from chainermn_tpu.serializers.npz import (DictionarySerializer,
                                               NpzDeserializer)
    x = np.arange(32, dtype=np.float32).reshape(32, 1)
    y = np.arange(32, dtype=np.int32)
    serial = SerialIterator(TupleDataset(x, y), 8, shuffle=True, seed=3)
    for _ in range(2):
        serial.next()
    s = DictionarySerializer()
    serial.serialize(s)
    expect = [sorted(t for _, t in serial.next()) for _ in range(3)]

    it = NativeBatchIterator(TupleDataset(x, y), 8, shuffle=True, seed=99)
    it.serialize(NpzDeserializer(s.target))
    got = [sorted(it.next()[1].tolist()) for _ in range(3)]
    it.finalize()
    assert got == expect


def test_native_iterator_legacy_snapshot_tolerated(lib):
    """Snapshots written before the iterator gained serialize() (no
    keys) must load as a no-op under the strict reader."""
    from chainermn_tpu.dataset.native_iterator import NativeBatchIterator
    from chainermn_tpu.serializers.npz import NpzDeserializer
    it = NativeBatchIterator(TupleDataset(
        np.zeros((16, 1), np.float32), np.arange(16, dtype=np.int32)),
        4, shuffle=False)
    it.serialize(NpzDeserializer({}))  # empty snapshot: keep fresh state
    assert it.epoch == 0
    bx, by = it.next()
    np.testing.assert_array_equal(by, np.arange(4))
    it.finalize()


def test_reset_drains_inflight_submissions():
    """reset() must discard batches already queued in the C++ FIFO —
    otherwise the post-reset stream serves the old schedule's batches
    and leaks ring slots on every reset (Evaluator reuse pattern)."""
    from chainermn_tpu.utils.native import load_library
    if load_library() is None:
        import pytest
        pytest.skip("native loader unavailable")
    from chainermn_tpu.dataset.native_iterator import NativeBatchIterator
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    it = NativeBatchIterator(data, 4, shuffle=True, seed=0, n_prefetch=2)
    it.next()  # consume one batch from the first schedule
    for _ in range(5):  # Evaluator-style repeated resets must not leak
        it.reset()
    first_epoch = [it.next() for _ in range(3)]
    got = np.concatenate([np.asarray(b) for b in first_epoch])
    np.testing.assert_array_equal(np.sort(got[:, 0]),
                                  data[np.argsort(data[:, 0]), 0])
    it.finalize()


def test_loader_churn_and_midflight_destroy_stress(lib):
    """Regression for a shutdown/steady-state race: helpers read
    ``current`` lock-free inside gather_rows while the leader could
    move-assign it for the next job (use-after-move on the indices
    vector — observed as a flaky suite segfault in loader_destroy's
    join window).  Back-to-back submissions with several threads hammer
    the reassign path; closing with jobs still in flight hammers the
    shutdown path."""
    rng = np.random.RandomState(0)
    data = rng.normal(0, 1, (512, 16)).astype(np.float32)

    # steady-state churn: many consecutive jobs through few buffers
    loader = native.NativeLoader(data, max_batch=32, n_buffers=2,
                                 n_threads=4)
    for step in range(100):
        idx = rng.randint(0, len(data), 32)
        loader.submit(idx)
        batch = loader.next()
        np.testing.assert_array_equal(batch, data[idx])
    loader.close()

    # mid-flight destroy: close while queued jobs are being gathered
    for trial in range(20):
        loader = native.NativeLoader(data, max_batch=64, n_buffers=3,
                                     n_threads=4)
        for _ in range(3):
            loader.submit(rng.randint(0, len(data), 64))
        if trial % 2:
            loader.next()  # consume one, leave the rest in flight
        loader.close()
