"""End-to-end data-parallel MNIST (BASELINE config #1; SURVEY.md §7 step 3).

Golden rule (SURVEY §4): the distributed result must equal a single-device
run on the merged batch.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import chainermn_tpu as ct
from chainermn_tpu import F, L
from chainermn_tpu.core.optimizer import SGD, Adam, MomentumSGD
from chainermn_tpu.dataset import SerialIterator, get_mnist
from chainermn_tpu.training import StandardUpdater, Trainer, extensions


class MLP(ct.Chain):
    def __init__(self, n_units=32, n_out=10, seed=100):
        super().__init__()
        with self.init_scope():
            self.l1 = L.Linear(784, n_units, seed=seed)
            self.l2 = L.Linear(n_units, n_out, seed=seed + 1)

    def forward(self, x):
        return self.l2(F.relu(self.l1(x)))


class Classifier(ct.Chain):
    def __init__(self, predictor):
        super().__init__()
        with self.init_scope():
            self.predictor = predictor

    def forward(self, x, t):
        y = self.predictor(x)
        loss = F.softmax_cross_entropy(y, t)
        ct.report({"loss": loss, "accuracy": F.accuracy(y, t)}, self)
        return loss


def _batch(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.normal(0, 1, (n, 784)).astype(np.float32)
    t = rng.randint(0, 10, n).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(t)


def test_dp_step_equals_single_device_step():
    """One multi-node update == one single-device update on the full batch."""
    x, t = _batch(64)

    model_dp = Classifier(MLP())
    comm = ct.create_communicator("jax_ici")
    comm.bcast_data(model_dp)
    opt_dp = ct.create_multi_node_optimizer(SGD(lr=0.1), comm).setup(model_dp)

    model_ref = Classifier(MLP())  # same seeds → same init
    opt_ref = SGD(lr=0.1).setup(model_ref)

    loss_dp = opt_dp.update(model_dp, x, t)
    loss_ref = opt_ref.update(model_ref, x, t)

    np.testing.assert_allclose(float(loss_dp), float(loss_ref), rtol=1e-5)
    for (n1, p1), (n2, p2) in zip(model_dp.namedparams(),
                                  model_ref.namedparams()):
        np.testing.assert_allclose(np.asarray(p1.array), np.asarray(p2.array),
                                   rtol=1e-5, atol=1e-6)


def test_dp_step_grad_dtype_still_converges():
    x, t = _batch(64)
    model = Classifier(MLP())
    comm = ct.create_communicator("pure_nccl", allreduce_grad_dtype="bfloat16")
    opt = ct.create_multi_node_optimizer(SGD(lr=0.1), comm).setup(model)
    l0 = float(opt.update(model, x, t))
    for _ in range(20):
        l = float(opt.update(model, x, t))
    assert l < l0


def test_dp_batch_not_divisible_raises():
    x, t = _batch(30)  # 30 % 8 != 0
    model = Classifier(MLP())
    comm = ct.create_communicator("jax_ici")
    opt = ct.create_multi_node_optimizer(SGD(lr=0.1), comm).setup(model)
    with pytest.raises(ValueError, match="divisible"):
        opt.update(model, x, t)


def test_double_buffering_one_step_stale():
    """First DB update applies zero grads; second applies step-1's grads."""
    x, t = _batch(64)
    model_db = Classifier(MLP())
    comm = ct.create_communicator("pure_nccl")
    opt_db = ct.create_multi_node_optimizer(SGD(lr=0.1), comm,
                                            double_buffering=True).setup(model_db)
    w0 = np.asarray(model_db.predictor.l1.W.array).copy()
    opt_db.update(model_db, x, t)
    w1 = np.asarray(model_db.predictor.l1.W.array)
    np.testing.assert_allclose(w1, w0)  # zero stale grads → no movement

    # reference model: one plain update from the same start
    model_ref = Classifier(MLP())
    opt_ref = ct.create_multi_node_optimizer(SGD(lr=0.1), comm).setup(model_ref)
    opt_ref.update(model_ref, x, t)
    opt_db.update(model_db, x, t)  # applies grads computed at step 1
    np.testing.assert_allclose(np.asarray(model_db.predictor.l1.W.array),
                               np.asarray(model_ref.predictor.l1.W.array),
                               rtol=1e-5, atol=1e-6)


def test_double_buffering_add_hook_resets_stale_grads():
    """add_hook resets the wrapped optimizer's state mid-run; the
    double-buffer slot must reset with it — otherwise the next update
    applies the PRE-hook stale gradient against fresh optimizer state
    instead of the documented fresh-start (zero-grads-first) semantics."""
    x, t = _batch(64)
    model = Classifier(MLP())
    comm = ct.create_communicator("pure_nccl")
    opt = ct.create_multi_node_optimizer(SGD(lr=0.1), comm,
                                         double_buffering=True).setup(model)
    for _ in range(3):
        opt.update(model, x, t)
    assert opt._stale_grads is not None
    # GradientClipping is a no-op on the zero fresh-start grads (unlike
    # WeightDecay, which correctly moves params even at zero gradient)
    opt.add_hook(ct.core.GradientClipping(1.0))
    assert opt._stale_grads is None
    w_before = np.asarray(model.predictor.l1.W.array).copy()
    opt.update(model, x, t)  # fresh start: applies zero grads
    np.testing.assert_allclose(
        np.asarray(model.predictor.l1.W.array), w_before)


def test_double_buffering_converges():
    x, t = _batch(128)
    model = Classifier(MLP())
    comm = ct.create_communicator("pure_nccl",
                                  allreduce_grad_dtype="bfloat16")
    opt = ct.create_multi_node_optimizer(Adam(), comm,
                                         double_buffering=True).setup(model)
    losses = [float(opt.update(model, x, t)) for _ in range(30)]
    assert losses[-1] < losses[0]


def test_double_buffering_resume_bit_exact(tmp_path):
    """The one-step-stale gradient buffer is part of the observable
    state: save mid-training, resume in a fresh process, continue — the
    resumed trajectory bit-matches the uninterrupted one (without
    serializing _stale_grads the first post-resume update would apply
    zeros, i.e. silently restart the staleness pipeline)."""
    from chainermn_tpu.serializers import save_npz, load_npz
    x, t = _batch(64)

    def fresh():
        model = Classifier(MLP())
        comm = ct.create_communicator("pure_nccl")
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            SGD(lr=0.1), comm, double_buffering=True).setup(model)
        return model, opt

    model_a, opt_a = fresh()
    for _ in range(3):
        opt_a.update(model_a, x, t)
    path = str(tmp_path / "db.npz")
    save_npz(path, opt_a)
    for _ in range(2):
        opt_a.update(model_a, x, t)

    model_b, opt_b = fresh()
    load_npz(path, opt_b)
    for _ in range(2):
        opt_b.update(model_b, x, t)

    for (na, pa), (nb, pb) in zip(model_a.namedparams(),
                                  model_b.namedparams()):
        np.testing.assert_array_equal(np.asarray(pa.array),
                                      np.asarray(pb.array),
                                      err_msg=f"{na} diverged after "
                                              f"double-buffered resume")


def test_mnist_dp_end_to_end(tmp_path):
    """Full trainer pipeline: scatter → bcast → DP optimizer → evaluator."""
    comm = ct.create_communicator("jax_ici")
    model = Classifier(MLP())
    comm.bcast_data(model)
    optimizer = ct.create_multi_node_optimizer(Adam(), comm).setup(model)

    train, test = get_mnist(n_train=512, n_test=128)
    train = ct.scatter_dataset(train, comm, shuffle=True, seed=0)
    test = ct.scatter_dataset(test, comm, shuffle=False)
    assert len(train) % comm.size == 0  # equal-shard invariant

    train_iter = SerialIterator(train, 8 * comm.size)
    test_iter = SerialIterator(test, 8 * comm.size, repeat=False,
                               shuffle=False)
    updater = StandardUpdater(train_iter, optimizer)
    trainer = Trainer(updater, (3, "epoch"), out=str(tmp_path / "r"))
    evaluator = ct.create_multi_node_evaluator(
        extensions.Evaluator(test_iter, model), comm)
    trainer.extend(evaluator)
    trainer.extend(extensions.LogReport(trigger=(1, "epoch")))
    trainer.run()

    log = trainer.get_extension("LogReport").log
    assert log[-1]["validation/main/accuracy"] > 0.5
    assert log[-1]["main/loss"] < log[0]["main/loss"]


def test_scatter_dataset_equal_shards():
    comm = ct.create_communicator("jax_ici")
    ds = np.arange(100)
    shard = ct.scatter_dataset(ds, comm, shuffle=True, seed=1)
    # padded by wrap-around to a multiple of size
    assert len(shard) == -(-100 // comm.size) * comm.size
    values = [int(shard[i]) for i in range(len(shard))]
    assert set(values) == set(range(100))  # covers everything


def test_create_empty_dataset():
    ds = ct.create_empty_dataset(np.arange(10))
    assert len(ds) == 10
    assert ds[3] is None
    assert ds[2:5] == [None, None, None]


def test_dp_scalar_extra_arg_is_replicated():
    """Scalar (0-d) loss args get P() specs instead of crashing shard_map."""
    x, t = _batch(64)
    w = jnp.asarray(2.0)

    class WeightedClassifier(Classifier):
        def forward(self, x, t, w):
            y = self.predictor(x)
            return w * F.softmax_cross_entropy(y, t)

    model = WeightedClassifier(MLP())
    comm = ct.create_communicator("jax_ici")
    opt = ct.create_multi_node_optimizer(SGD(lr=0.1), comm).setup(model)
    loss = opt.update(model, x, t, w)
    assert np.isfinite(float(loss))


def test_step_cache_is_bounded():
    x, t = _batch(8)
    model = Classifier(MLP())
    opt = SGD(lr=0.1).setup(model)
    for _ in range(20):
        # fresh closure per step: worst-case pattern; cache must not grow
        opt.update(lambda a, b: model(a, b), x, t)
    assert len(opt._step_cache) <= opt._step_cache.maxsize


def test_standalone_update_without_trainer_does_not_crash():
    """No Trainer/reporter registered: in-forward report(…, self) must not
    raise (a fallback reporter with the target registered as ``main`` backs
    the capture); registered-observer KeyError semantics are preserved for
    genuinely unknown observers (reference contract)."""
    from chainermn_tpu.core import reporter as reporter_module
    x, t = _batch(16)
    model = Classifier(MLP())
    opt = SGD(lr=0.1).setup(model)
    loss = opt.update(model, x, t)
    assert np.isfinite(float(loss))
    rep = reporter_module.Reporter()
    with pytest.raises(KeyError):
        rep.report({"x": 1.0}, observer=model)


def test_multi_node_evaluator_sharded_eval_matches_plain():
    """The sharded compiled eval path produces the same metrics as the
    single-device evaluator."""
    from chainermn_tpu.training.extensions import Evaluator
    comm = ct.create_communicator("jax_ici")
    model = Classifier(MLP())
    test, _ = get_mnist(n_train=128, n_test=8)
    it1 = SerialIterator(test, 8 * comm.size, repeat=False, shuffle=False)
    it2 = SerialIterator(test, 8 * comm.size, repeat=False, shuffle=False)
    plain = Evaluator(it1, model)
    sharded = ct.create_multi_node_evaluator(Evaluator(it2, model), comm)
    r_plain = plain()
    r_sharded = sharded()
    for k, v in r_plain.items():
        np.testing.assert_allclose(r_sharded[k], float(np.asarray(v)),
                                   rtol=1e-4)


def test_update_scan_equals_sequential_updates():
    """K fused steps (one dispatch) == K sequential update() calls on the
    same per-step batches (deterministic model)."""
    K = 3
    batches = [_batch(64, seed=i) for i in range(K)]

    model_seq = Classifier(MLP())
    comm = ct.create_communicator("jax_ici")
    comm.bcast_data(model_seq)
    opt_seq = ct.create_multi_node_optimizer(SGD(lr=0.1), comm).setup(model_seq)
    seq_losses = [float(opt_seq.update(model_seq, x, t)) for x, t in batches]

    model_scan = Classifier(MLP())
    comm.bcast_data(model_scan)
    opt_scan = ct.create_multi_node_optimizer(SGD(lr=0.1), comm).setup(model_scan)
    xs = jnp.stack([x for x, _ in batches])
    ts = jnp.stack([t for _, t in batches])
    scan_losses = opt_scan.update_scan(model_scan, xs, ts)

    assert scan_losses.shape == (K,)
    np.testing.assert_allclose(np.asarray(scan_losses), seq_losses, rtol=1e-5)
    assert opt_scan.t == opt_seq.t == K
    for (_, p1), (_, p2) in zip(model_scan.namedparams(),
                                model_seq.namedparams()):
        np.testing.assert_allclose(np.asarray(p1.array), np.asarray(p2.array),
                                   rtol=1e-5, atol=1e-6)


def test_update_scan_snapshot_resume_bit_exact(tmp_path):
    """Mid-training save between fused K-step dispatches, resume in a
    FRESH optimizer, continue with update_scan: the resumed trajectory
    (params AND step count) must bit-match the uninterrupted one —
    pins that the scan path keeps `t` and the optax state serializable
    exactly like per-step update()."""
    from chainermn_tpu.serializers import save_npz, load_npz
    K = 3

    def fresh():
        model = Classifier(MLP())
        comm = ct.create_communicator("jax_ici")
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            MomentumSGD(lr=0.1, momentum=0.9), comm).setup(model)
        return model, opt

    def block(seed0):
        xs = jnp.stack([_batch(64, seed=seed0 + i)[0] for i in range(K)])
        ts = jnp.stack([_batch(64, seed=seed0 + i)[1] for i in range(K)])
        return xs, ts

    model_a, opt_a = fresh()
    opt_a.update_scan(model_a, *block(0))
    path = str(tmp_path / "scan_mid.npz")
    save_npz(path, opt_a)
    opt_a.update_scan(model_a, *block(10))  # uninterrupted continuation

    model_b, opt_b = fresh()
    load_npz(path, opt_b)
    assert opt_b.t == K
    opt_b.update_scan(model_b, *block(10))

    assert opt_a.t == opt_b.t == 2 * K
    for (na, pa), (nb, pb) in zip(model_a.namedparams(),
                                  model_b.namedparams()):
        assert na == nb
        np.testing.assert_array_equal(np.asarray(pa.array),
                                      np.asarray(pb.array),
                                      err_msg=f"param {na} diverged after "
                                              f"scan resume")


def test_update_scan_rejects_double_buffering():
    model = Classifier(MLP())
    comm = ct.create_communicator("jax_ici")
    opt = ct.create_multi_node_optimizer(SGD(lr=0.1), comm,
                                         double_buffering=True).setup(model)
    x, t = _batch(64)
    with pytest.raises(RuntimeError, match="double"):
        opt.update_scan(model, jnp.stack([x]), jnp.stack([t]))


def test_update_scan_bad_batch_axis_raises():
    model = Classifier(MLP())
    comm = ct.create_communicator("jax_ici")
    opt = ct.create_multi_node_optimizer(SGD(lr=0.1), comm).setup(model)
    x, t = _batch(30)  # 30 % 8 != 0
    with pytest.raises(ValueError, match="divisible"):
        opt.update_scan(model, jnp.stack([x]), jnp.stack([t]))
