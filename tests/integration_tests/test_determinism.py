"""End-to-end determinism + checkpoint fidelity.

The strongest statements a framework can make about its checkpoint story:
(1) identical seeds → identical trajectories; (2) snapshot/resume at the
midpoint reproduces the uninterrupted run exactly.
"""

import os

import numpy as np

import chainermn_tpu as ct
from chainermn_tpu.core.optimizer import MomentumSGD
from chainermn_tpu.dataset import SerialIterator, get_mnist
from chainermn_tpu.models import Classifier, MLP
from chainermn_tpu.serializers import load_npz
from chainermn_tpu.training import StandardUpdater, Trainer, extensions


def _build(out, epochs, comm):
    model = Classifier(MLP(n_units=16, n_out=10, seed=3))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.05), comm).setup(model)
    opt.seed = 42  # per-step rng seed (dropout-free model, but pinned)
    train, _ = get_mnist(n_train=256, n_test=8)
    train = ct.scatter_dataset(train, comm, shuffle=True, seed=5)
    it = SerialIterator(train, 8 * comm.size, seed=11)
    updater = StandardUpdater(it, opt)
    return model, Trainer(updater, (epochs, "epoch"), out=out)


def _weights(model):
    return {k: np.asarray(p.array) for k, p in model.namedparams()}


def test_same_seeds_identical_trajectory(tmp_path):
    comm = ct.create_communicator("jax_ici")
    m1, t1 = _build(str(tmp_path / "a"), 3, comm)
    t1.run()
    m2, t2 = _build(str(tmp_path / "b"), 3, comm)
    t2.run()
    for k, w in _weights(m1).items():
        np.testing.assert_array_equal(w, _weights(m2)[k], err_msg=k)


def test_resume_equals_uninterrupted(tmp_path):
    comm = ct.create_communicator("jax_ici")
    # uninterrupted 4 epochs
    m_full, t_full = _build(str(tmp_path / "full"), 4, comm)
    t_full.run()

    # first half + snapshot
    m_half, t_half = _build(str(tmp_path / "half"), 2, comm)
    t_half.extend(extensions.snapshot(filename="snap"), trigger=(2, "epoch"))
    t_half.run()
    snap = os.path.join(str(tmp_path / "half"), "snap")
    assert os.path.exists(snap)

    # second half from the snapshot
    m_res, t_res = _build(str(tmp_path / "res"), 4, comm)
    load_npz(snap, t_res)
    assert t_res.updater.iteration == t_half.updater.iteration
    t_res.run()

    for k, w in _weights(m_full).items():
        np.testing.assert_allclose(w, _weights(m_res)[k], rtol=1e-6,
                                   atol=1e-7, err_msg=k)


def test_resume_with_dropout_exact(tmp_path):
    """Stochastic models resume on the exact key sequence."""
    from chainermn_tpu import F, L
    from chainermn_tpu.core.optimizer import SGD
    from chainermn_tpu.serializers import save_npz
    import jax.numpy as jnp

    class DropNet(ct.Chain):
        def __init__(self):
            super().__init__()
            with self.init_scope():
                self.l = L.Linear(8, 4, seed=0)

        def forward(self, x, t):
            return F.softmax_cross_entropy(F.dropout(self.l(x), 0.5), t)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(0, 1, (16, 8)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 4, 16).astype(np.int32))

    def fresh():
        net = DropNet()
        opt = SGD(lr=0.1).setup(net)
        opt.seed = 77
        return net, opt

    net_a, opt_a = fresh()
    for _ in range(6):
        opt_a.update(net_a, x, t)

    net_b, opt_b = fresh()
    for _ in range(3):
        opt_b.update(net_b, x, t)
    snap = str(tmp_path / "opt.npz")
    save_npz(snap, opt_b)
    net_c, opt_c = fresh()
    load_npz(snap, opt_c)
    for _ in range(3):
        opt_c.update(net_c, x, t)
    for k, p in net_a.namedparams():
        np.testing.assert_array_equal(
            np.asarray(p.array),
            np.asarray(dict(net_c.namedparams())[k].array), err_msg=k)
