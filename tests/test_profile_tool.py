"""The xplane.pb walker in tools/profile_tpu_step.py must be known-good
BEFORE chip time depends on it (VERDICT r3 Weak #2): capture a real
2-step CPU trace in-suite and assert the summary yields nonempty op
rows.  Exercises jax.profiler.trace output end-to-end through the
hand-rolled protobuf varint walker — parser bitrot fails here, not on
the one chance at the chip.
"""

import os
import re
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import profile_tpu_step  # noqa: E402


def test_summarize_parses_real_trace(tmp_path, capsys):
    @jax.jit
    def step(x):
        return jnp.tanh(x @ x).sum()

    x = jnp.ones((256, 256), jnp.float32)
    float(step(x))  # compile outside the trace window
    out_dir = str(tmp_path / "trace")
    with jax.profiler.trace(out_dir):
        for _ in range(2):
            loss = step(x)
        float(loss)

    profile_tpu_step.summarize(out_dir)
    out = capsys.readouterr().out
    assert "plane:" in out, f"no plane found in summary output:\n{out}"
    # at least one per-op row:  "<ms> ms  <pct>%  <op name>"
    rows = re.findall(r"^\s+[\d.]+ ms\s+[\d.]+%\s+\S+", out, re.M)
    assert rows, f"no op rows parsed from trace:\n{out}"


def test_summarize_empty_dir_reports_cleanly(tmp_path, capsys):
    profile_tpu_step.summarize(str(tmp_path))
    out = capsys.readouterr().out
    assert "no xplane.pb" in out


def test_compare_diffs_two_real_traces(tmp_path, capsys):
    """--compare is the queue's NCHW-vs-NHWC instrument: capture two
    traces of different programs and assert per-op delta rows print
    (ops matched by name, missing side = 0)."""

    @jax.jit
    def step_a(x):
        return jnp.tanh(x @ x).sum()

    @jax.jit
    def step_b(x):
        return jnp.exp(jnp.sin(x @ x)).sum()  # different op mix

    x = jnp.ones((256, 256), jnp.float32)
    float(step_a(x)), float(step_b(x))  # compile outside the windows
    dirs = []
    for name, step in [("a", step_a), ("b", step_b)]:
        d = str(tmp_path / name)
        with jax.profiler.trace(d):
            for _ in range(2):
                loss = step(x)
            float(loss)
        dirs.append(d)

    profile_tpu_step.compare(*dirs)
    out = capsys.readouterr().out
    assert "total delta (B-A):" in out
    rows = re.findall(r"^\s*[\d.]+\s+[\d.]+\s+[+-][\d.]+\s+\S+", out, re.M)
    assert rows, f"no delta rows:\n{out}"


def test_compare_missing_trace_reports_cleanly(tmp_path, capsys):
    profile_tpu_step.compare(str(tmp_path / "nope"), str(tmp_path / "x"))
    out = capsys.readouterr().out
    assert "EMPTY" in out
