"""Flash-backward budget gate (ISSUE 4: the kernel win can't rot).

Mirrors tests/test_hbm_budget.py: tools/flash_budgets.json commits the
flash-attention backward's contract and this gate holds every future PR
to it.  Two layers:

* STRUCTURE (backend-neutral, checked here on CPU): the fused backward
  lowers to exactly one Pallas kernel with exactly one exp — the
  recompute-once property the fusion exists for — and the split escape
  hatch to the legacy two kernels.  Verified against the traced
  program, not against documentation.
* NUMBERS (measured on chip by `make sweep-flash`): when the committed
  sweep section says ``measured``, the T=8192 fused fwd+bwd TFLOP/s
  must meet the committed target (≥2× the r5 split-backward baseline);
  while it says ``pending_on_chip`` the numeric half is dormant but the
  schema/target relation is still enforced.
"""

import importlib
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import flash_sweep  # noqa: E402

fa = importlib.import_module("chainermn_tpu.ops.flash_attention")


def _budgets():
    with open(flash_sweep.BUDGETS_PATH) as f:
        return json.load(f)


def test_budget_schema_and_target_relation():
    b = _budgets()
    assert b["baseline"]["fwd_bwd_tflops_T8192"] == 31.8  # the r5 datum
    # the acceptance bar this PR committed to: >= 2x the split baseline
    assert b["target_fwd_bwd_tflops_T8192"] >= \
        2.0 * b["baseline"]["fwd_bwd_tflops_T8192"]
    assert b["structure"]["bwd_mode_default"] == "fused"
    assert set(b["bwd_block_table"]) == {"1024", "2048", "8192", "16384"}
    for blocks in b["bwd_block_table"].values():
        assert len(blocks) == 2
        assert all(x > 0 and x % 8 == 0 for x in blocks)
    assert b["sweep"]["status"] in ("pending_on_chip", "measured")


def test_bwd_block_table_matches_kernel_literal():
    """The kernel reads the literal table in ops/flash_attention.py;
    the budgets file records it — they must not desync (the sweep tool
    prints a reminder to paste winners into the literal)."""
    b = _budgets()
    assert {int(t): tuple(v) for t, v in b["bwd_block_table"].items()} \
        == fa._BWD_BLOCK_TABLE


def test_fused_structure_gate():
    """Recompute-once, machine-checked: the fused backward is ONE
    pallas kernel with ONE exp.  A PR that splits the pass again or
    adds a second exp(s - lse) recompute fails here and must either fix
    it or consciously re-commit the structure section."""
    b = _budgets()
    census = flash_sweep.bwd_kernel_census(fa, "fused")
    assert census == b["structure"]["fused_bwd_kernels"], (
        f"fused backward structure drifted: traced {census}, committed "
        f"{b['structure']['fused_bwd_kernels']}")


def test_split_structure_gate():
    b = _budgets()
    census = flash_sweep.bwd_kernel_census(fa, "split")
    assert census == b["structure"]["split_bwd_kernels"], (
        f"split escape hatch no longer the legacy two-kernel lowering: "
        f"traced {census}")


def test_measured_numbers_meet_target_when_present():
    b = _budgets()
    if b["sweep"]["status"] != "measured":
        return  # pending_on_chip: the numeric half is dormant
    results = b["sweep"]["results"]
    assert "8192" in results, "sweep measured but no T=8192 row"
    got = results["8192"]["fwd_bwd_tflops"]
    assert got >= b["target_fwd_bwd_tflops_T8192"], (
        f"committed T=8192 fused fwd+bwd {got} TFLOP/s below the "
        f"{b['target_fwd_bwd_tflops_T8192']} target — record the "
        "refutation in BENCH_NOTES (r5 ResNet precedent) before "
        "re-committing a lower target")


def test_sweep_tool_cpu_smoke(tmp_path):
    """The one-command reproducibility claim: the sweep tool runs its
    interpret-mode smoke end to end and refuses --write-budgets off
    chip (budgets are measured artifacts)."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "flash_sweep.py"),
         "--T", "64", "--blocks", "32:32", "--reps", "1"],
        env=env, capture_output=True, text=True, timeout=600, cwd=root)
    assert out.returncode == 0, out.stderr[-2000:]
    rows = [json.loads(l) for l in out.stdout.strip().splitlines()]
    timed = [r for r in rows if "fwd_bwd_ms" in r]
    assert {r["bwd_mode"] for r in timed} == {"fused", "split"}
    assert all(r["interpreted"] for r in timed)
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "flash_sweep.py"),
         "--T", "64", "--blocks", "32:32", "--reps", "1",
         "--write-budgets"],
        env=env, capture_output=True, text=True, timeout=600, cwd=root)
    assert out.returncode == 2
    assert "refused" in out.stdout
