"""Multi-node BN vs single-process BN on the concatenated batch.

Mirrors reference ``links_tests/test_batch_normalization.py``
(SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu import L
from chainermn_tpu.core.link import apply_state, extract_state
from chainermn_tpu.links import (MultiNodeBatchNormalization,
                                 create_mnbn_model)

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici")


def test_mnbn_matches_global_batch_bn():
    size = COMM.size
    bn_global = L.BatchNormalization(3)
    mnbn = MultiNodeBatchNormalization(3, COMM)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(2, 3, (size * 4, 3)).astype(np.float32))

    y_global, _ = apply_state(bn_global, extract_state(bn_global), x)

    state = extract_state(mnbn)

    def body(params, pstate, x):
        out, new = apply_state(mnbn, {"params": params, "state": pstate}, x)
        return out, new["state"]

    from chainermn_tpu.utils.compat import shard_map
    mapped = shard_map(body, mesh=COMM.mesh,
                       in_specs=(P(), P(), P(COMM.axis_name)),
                       out_specs=(P(COMM.axis_name), P()),
                       check_vma=False)
    y_mn, new_state = jax.jit(mapped)(state["params"], state["state"], x)
    np.testing.assert_allclose(np.asarray(y_mn), np.asarray(y_global),
                               rtol=1e-4, atol=1e-5)
    # running stats updated toward the global moments
    np.testing.assert_allclose(np.asarray(new_state["/avg_mean"]),
                               0.1 * np.asarray(x).mean(axis=0), rtol=1e-3)


def test_mnbn_gradients_match_global_bn():
    size = COMM.size
    bn_global = L.BatchNormalization(3)
    mnbn = MultiNodeBatchNormalization(3, COMM)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.normal(1, 2, (size * 2, 3)).astype(np.float32))

    sg = extract_state(bn_global)

    def loss_global(p):
        out, _ = apply_state(bn_global, {"params": p, "state": sg["state"]}, x)
        return jnp.sum(out ** 3)

    g_ref = jax.grad(loss_global)(sg["params"])

    sm = extract_state(mnbn)

    def body(params, pstate, x):
        # per-rank local loss; total gradient = psum of per-rank grads
        # (the multi-node optimizer's treatment) — cross-rank dependencies
        # through the pmean'd moments are handled by AD transposition
        def loss(p):
            out, _ = apply_state(mnbn, {"params": p, "state": pstate}, x)
            return jnp.sum(out ** 3)
        grads = jax.grad(loss)(params)
        return jax.tree.map(lambda g: jax.lax.psum(g, COMM.axis_name), grads)

    from chainermn_tpu.utils.compat import shard_map
    mapped = shard_map(body, mesh=COMM.mesh,
                       in_specs=(P(), P(), P(COMM.axis_name)),
                       out_specs=P(),
                       check_vma=False)
    g_mn = jax.jit(mapped)(sm["params"], sm["state"], x)
    for k in g_ref:
        np.testing.assert_allclose(np.asarray(g_mn[k]), np.asarray(g_ref[k]),
                                   rtol=1e-3, atol=1e-4)


def test_create_mnbn_model_rewrites_recursively():
    class Net(ct.Chain):
        def __init__(self):
            super().__init__()
            with self.init_scope():
                self.conv = L.Convolution2D(3, 8, 3, seed=0)
                self.bn = L.BatchNormalization(8)
                self.inner = ct.Sequential(L.Linear(8, 4, seed=1),
                                           L.BatchNormalization(4))

    net = Net()
    net.bn.gamma.array = jnp.full((8,), 2.0)
    mn = create_mnbn_model(net, COMM)
    assert isinstance(mn.bn, MultiNodeBatchNormalization)
    assert isinstance(mn.inner[1], MultiNodeBatchNormalization)
    assert not isinstance(mn.conv, MultiNodeBatchNormalization)
    np.testing.assert_allclose(np.asarray(mn.bn.gamma.array), 2.0)
    # original untouched
    assert not isinstance(net.bn, MultiNodeBatchNormalization)
    # params enumerate under the same paths
    assert [n for n, _ in mn.namedparams()] == [n for n, _ in net.namedparams()]


def test_bn_running_var_unbiased():
    """Running variance accumulates the unbiased batch variance
    (× m/(m-1)), matching the reference's adjustment (ADVICE r1)."""
    bn = L.BatchNormalization(2, decay=0.5)
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.normal(0, 2, (6, 2)).astype(np.float32))
    bn(x)
    m = x.shape[0]
    expected = 0.5 * 1.0 + 0.5 * np.asarray(x).var(axis=0) * m / (m - 1)
    np.testing.assert_allclose(np.asarray(bn.avg_var), expected, rtol=1e-5)
