"""Topology zoo for MultiNodeChainList.

Mirrors reference ``links_tests/test_multi_node_chain_list.py``
(SURVEY.md §4): straight pipeline, branching, merging — asserting
end-to-end outputs and gradients match a single-process reference model.
"""

import jax
import jax.numpy as jnp
import numpy as np

import chainermn_tpu as ct
from chainermn_tpu import F, L
from chainermn_tpu.core.link import extract_state, apply_state
from chainermn_tpu.core.optimizer import SGD
from chainermn_tpu.links import MultiNodeChainList

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici", axis_name="stage")


class _Block(ct.Chain):
    def __init__(self, n_in, n_out, seed):
        super().__init__()
        with self.init_scope():
            self.l = L.Linear(n_in, n_out, seed=seed)

    def forward(self, x):
        return F.relu(self.l(x))


class _Merge(ct.Chain):
    def __init__(self, n_in, n_out, seed):
        super().__init__()
        with self.init_scope():
            self.l = L.Linear(n_in, n_out, seed=seed)

    def forward(self, a, b):
        return self.l(jnp.concatenate([a, b], axis=1))


def _pipeline_model():
    m = MultiNodeChainList(COMM)
    m.add_link(_Block(4, 8, seed=1), rank_in=None, rank_out=1, rank=0)
    m.add_link(_Block(8, 6, seed=2), rank_in=0, rank_out=2, rank=1)
    m.add_link(_Block(6, 2, seed=3), rank_in=1, rank_out=None, rank=2)
    return m


def _reference_stack():
    return ct.Sequential(_Block(4, 8, seed=1), _Block(8, 6, seed=2),
                         _Block(6, 2, seed=3))


def test_straight_pipeline_forward_matches_reference():
    m = _pipeline_model()
    ref = _reference_stack()
    x = jnp.asarray(np.random.RandomState(0).normal(0, 1, (5, 4))
                    .astype(np.float32))
    y = m(x)
    y_ref = ref(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-6)


def test_straight_pipeline_gradients_match_reference():
    m = _pipeline_model()
    ref = _reference_stack()
    x = jnp.asarray(np.random.RandomState(1).normal(0, 1, (5, 4))
                    .astype(np.float32))

    def loss_of(model, params, pstate):
        def f(p):
            out, _ = apply_state(model, {"params": p, "state": pstate}, x)
            return jnp.sum(out ** 2)
        return f

    sm, sr = extract_state(m), extract_state(ref)
    gm = jax.grad(loss_of(m, sm["params"], sm["state"]))(sm["params"])
    gr = jax.grad(loss_of(ref, sr["params"], sr["state"]))(sr["params"])
    # parameter paths differ (mn_component_i/l vs i/l) — compare by order
    gm_leaves = [gm[k] for k in sorted(gm)]
    gr_leaves = [gr[k] for k in sorted(gr)]
    for a, b in zip(gm_leaves, gr_leaves):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_branching_and_merging_topology():
    """rank0 fans out to ranks 1 and 2; rank 3 merges both."""
    m = MultiNodeChainList(COMM)
    m.add_link(_Block(4, 6, seed=10), rank_in=None, rank_out=[1, 2], rank=0)
    m.add_link(_Block(6, 5, seed=11), rank_in=0, rank_out=3, rank=1)
    m.add_link(_Block(6, 5, seed=12), rank_in=0, rank_out=3, rank=2)
    m.add_link(_Merge(10, 2, seed=13), rank_in=[1, 2], rank_out=None, rank=3)

    b0, b1, b2 = _Block(4, 6, seed=10), _Block(6, 5, seed=11), _Block(6, 5, seed=12)
    mg = _Merge(10, 2, seed=13)
    x = jnp.asarray(np.random.RandomState(2).normal(0, 1, (3, 4))
                    .astype(np.float32))
    y = m(x)
    h = b0(x)
    y_ref = mg(b1(h), b2(h))
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_trains_with_multi_node_optimizer():
    """MultiNodeChainList under the DP optimizer wrapper: loss decreases."""

    class PipelineClassifier(ct.Chain):
        def __init__(self):
            super().__init__()
            with self.init_scope():
                self.pipe = _pipeline_model()

        def forward(self, x, t):
            y = self.pipe(x)
            return F.mean_squared_error(y, t)

    model = PipelineClassifier()
    # model-parallel stages live on the same mesh axis; the optimizer
    # treats the whole batch as replicated work on each stage rank
    opt = SGD(lr=0.05).setup(model)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.normal(0, 1, (8, 4)).astype(np.float32))
    t = jnp.asarray(rng.normal(0, 1, (8, 2)).astype(np.float32))
    losses = [float(opt.update(model, x, t)) for _ in range(20)]
    assert losses[-1] < losses[0]


def test_pipeline_bn_stats_come_from_owner_rank():
    """BN running stats inside a non-rank-0 stage must reflect the owner's
    real activations, not another rank's zero-input garbage."""
    m = MultiNodeChainList(COMM)
    m.add_link(_Block(4, 6, seed=20), rank_in=None, rank_out=1, rank=0)

    class _BNStage(ct.Chain):
        def __init__(self):
            super().__init__()
            with self.init_scope():
                self.bn = L.BatchNormalization(6)
                self.l = L.Linear(6, 2, seed=21)

        def forward(self, x):
            return self.l(self.bn(x))

    stage1 = _BNStage()
    m.add_link(stage1, rank_in=0, rank_out=None, rank=1)

    x = jnp.asarray(np.random.RandomState(5).normal(3, 1, (16, 4))
                    .astype(np.float32))
    m(x)
    # reference: single-process stack with the same seeds
    ref_b0, ref_stage = _Block(4, 6, seed=20), _BNStage()
    ref_stage(ref_b0(x))
    np.testing.assert_allclose(np.asarray(stage1.bn.avg_mean),
                               np.asarray(ref_stage.bn.avg_mean),
                               rtol=1e-4, atol=1e-5)
    # owner's activations have nonzero mean — garbage (zeros) would not
    assert float(np.abs(np.asarray(stage1.bn.avg_mean)).sum()) > 1e-3


def test_chain_list_topology_errors():
    import pytest
    m = MultiNodeChainList(COMM)
    m.add_link(_Block(4, 4, seed=1), rank_in=None, rank_out=1, rank=0)
    with pytest.raises(ValueError, match="no terminal"):
        m(jnp.ones((2, 4)))
    m2 = MultiNodeChainList(COMM)
    m2.add_link(_Block(4, 4, seed=1), rank_in=None, rank_out=None, rank=0)
    m2.add_link(_Block(4, 4, seed=2), rank_in=None, rank_out=None, rank=1)
    with pytest.raises(ValueError, match="multiple terminal"):
        m2(jnp.ones((2, 4)))


class _Merge3(ct.Chain):
    """Consumes three inputs (two from the same peer rank + one local)."""

    def __init__(self, seed):
        super().__init__()
        with self.init_scope():
            self.l = L.Linear(12, 3, seed=seed)

    def forward(self, a, b, c):
        return self.l(jnp.concatenate([a, b, c], axis=1))


def test_interleaved_multi_edge_same_rank_pair():
    """Two independent edges between the SAME (src, dst) rank pair, with
    an unrelated edge interleaved between them: per-edge tags must keep
    the channels separate (VERDICT r1 Weak #9 — tag-0 FIFO fragility).

    Topology: rank0 runs A (4→4) and B (4→4) from the input; rank2 runs
    D (4→4); rank1's merge consumes [A-out, B-out, D-out].  A and B are
    both rank0→rank1 edges; D's rank2→rank1 edge interleaves between
    their registrations.
    """
    m = MultiNodeChainList(COMM)
    m.add_link(_Block(4, 4, seed=11), rank_in=None, rank_out=1, rank=0)
    m.add_link(_Block(4, 4, seed=13), rank_in=None, rank_out=1, rank=2)
    m.add_link(_Block(4, 4, seed=12), rank_in=None, rank_out=1, rank=0)
    m.add_link(_Merge3(seed=14), rank_in=[0, 2, 0], rank_out=None, rank=1)

    a, d, b, merge = (_Block(4, 4, seed=11), _Block(4, 4, seed=13),
                      _Block(4, 4, seed=12), _Merge3(seed=14))
    x = jnp.asarray(np.random.RandomState(7).normal(0, 1, (5, 4))
                    .astype(np.float32))
    y = m(x)
    # reference consumes edges in the same (src, dst) FIFO order the
    # distributed walk produces them: rank0's first send is A, second is
    # B; rank2's only send is D; rank1's rank_in [0, 2, 0] therefore
    # binds (A-out, D-out, B-out)
    y_ref = merge(a(x), d(x), b(x))
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-6)


def test_two_parallel_pipelines_same_rank_pair():
    """Two full pipelines 0→1 registered back-to-back (the pure
    multi-edge case with no interleaving): outputs must not cross."""
    m = MultiNodeChainList(COMM)
    m.add_link(_Block(4, 4, seed=21), rank_in=None, rank_out=1, rank=0)
    m.add_link(_Block(4, 4, seed=22), rank_in=None, rank_out=1, rank=0)
    m.add_link(_Merge(8, 2, seed=23), rank_in=[0, 0], rank_out=None,
               rank=1)
    p1, p2 = _Block(4, 4, seed=21), _Block(4, 4, seed=22)
    mg = _Merge(8, 2, seed=23)
    x = jnp.asarray(np.random.RandomState(8).normal(0, 1, (3, 4))
                    .astype(np.float32))
    np.testing.assert_allclose(np.asarray(m(x)),
                               np.asarray(mg(p1(x), p2(x))),
                               rtol=1e-5, atol=1e-6)
