"""Channel-parallel convolution vs single-process conv (value + grad)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu import F
from chainermn_tpu.links.parallel_convolution import ParallelConvolution2D

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici", axis_name="tp")


def test_parallel_conv_forward_matches_dense():
    conv = ParallelConvolution2D(COMM, 3, 16, 3, pad=1, seed=0)
    x = jnp.asarray(np.random.RandomState(0).normal(0, 1, (2, 3, 8, 8))
                    .astype(np.float32))
    y_eager = conv(x)  # host mode: dense path
    W, b = conv.W.array, conv.b.array

    def body(x):
        return conv(x)

    y_tp = COMM.run_spmd(body, x, in_specs=(P(),), out_specs=P())
    y_ref = F.convolution_2d(x, W, b, 1, 1)
    np.testing.assert_allclose(np.asarray(y_eager), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y_tp), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_parallel_conv_gradients_match_dense():
    conv = ParallelConvolution2D(COMM, 3, 16, 3, pad=1, seed=1)
    x = jnp.asarray(np.random.RandomState(1).normal(0, 1, (2, 3, 8, 8))
                    .astype(np.float32))
    W0, b0 = conv.W.array, conv.b.array

    def body(W, b, x):
        def loss(args):
            W, b = args
            conv.W.array, conv.b.array = W, b
            return jnp.sum(conv(x) ** 2)
        g = jax.grad(loss)((W, b))
        conv.W.array, conv.b.array = W0, b0
        return g

    gW, gb = COMM.run_spmd(body, W0, b0, x,
                           in_specs=(P(), P(), P()), out_specs=(P(), P()))

    def ref_loss(args):
        W, b = args
        return jnp.sum(F.convolution_2d(x, W, b, 1, 1) ** 2)

    rW, rb = jax.grad(ref_loss)((W0, b0))
    np.testing.assert_allclose(np.asarray(gW), np.asarray(rW),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb),
                               rtol=1e-4, atol=1e-4)


def test_parallel_conv_trains_under_optimizer():
    from chainermn_tpu.core.optimizer import SGD

    class Net(ct.Chain):
        def __init__(self):
            super().__init__()
            with self.init_scope():
                self.conv = ParallelConvolution2D(COMM, 3, 8, 3, pad=1,
                                                  seed=2)

        def forward(self, x, t):
            y = self.conv(x).mean(axis=(2, 3))
            return F.softmax_cross_entropy(y, t)

    net = Net()
    opt = SGD(lr=0.1).setup(net)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.normal(0, 1, (8, 3, 8, 8)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 8, 8).astype(np.int32))
    losses = [float(opt.update(net, x, t)) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_parallel_conv_divisibility_check():
    import pytest
    with pytest.raises(ValueError, match="divisible"):
        ParallelConvolution2D(COMM, 3, 10, 3)
