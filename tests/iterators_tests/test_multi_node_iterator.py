"""Multi-node & synchronized iterator behavior (single-host contracts).

Mirrors reference ``iterators_tests`` (SURVEY.md §4).
"""

import numpy as np

import chainermn_tpu as ct
from chainermn_tpu.dataset import SerialIterator


def test_multi_node_iterator_passthrough_single_host():
    comm = ct.create_communicator("jax_ici")
    it = ct.create_multi_node_iterator(
        SerialIterator(np.arange(12), 4, shuffle=False), comm)
    b1 = it.next()
    assert len(b1) == 4
    for _ in range(2):
        it.next()
    assert it.epoch == 1
    assert it.is_new_epoch


def test_multi_node_iterator_serialize_delegates():
    from chainermn_tpu.serializers.npz import DictionarySerializer
    comm = ct.create_communicator("jax_ici")
    base = SerialIterator(np.arange(10), 5, shuffle=False)
    it = ct.create_multi_node_iterator(base, comm)
    it.next()
    s = DictionarySerializer()
    it.serialize(s)
    assert "current_position" in s.target


def test_synchronized_iterator_same_order():
    comm = ct.create_communicator("jax_ici")
    a = ct.create_synchronized_iterator(
        SerialIterator(np.arange(32), 8, shuffle=True, seed=None), comm)
    # single host: the returned iterator is the actual one with a
    # broadcast-agreed seed; order exists and is a permutation
    order = a._order
    assert sorted(order.tolist()) == list(range(32))


def test_global_except_hook_installable():
    import sys
    from chainermn_tpu import global_except_hook
    old = sys.excepthook
    try:
        global_except_hook.add_hook()
        assert sys.excepthook is not old
    finally:
        sys.excepthook = old
        global_except_hook._hook_installed = False


def test_observation_aggregator():
    comm = ct.create_communicator("jax_ici")
    agg = ct.extensions.ObservationAggregator(comm, "mykey", "mykey_agg")

    class _T:
        observation = {"mykey": 4.0}
    agg(_T())
    assert _T.observation["mykey_agg"] == 4.0


def test_synchronized_iterator_preserves_user_seed():
    """The master's existing RNG stream continues (VERDICT r1 Weak #7:
    a pre-seeded iterator must not lose its seed to a fresh broadcast
    seed)."""
    import chainermn_tpu as ct
    from chainermn_tpu.dataset.iterators import SerialIterator
    comm = ct.create_communicator("jax_ici")
    it = SerialIterator(np.arange(16), 4, shuffle=True, seed=42)
    sync = ct.create_synchronized_iterator(it, comm)
    rs = np.random.RandomState(42)
    rs.permutation(16)  # construction drew the first permutation
    np.testing.assert_array_equal(np.asarray(sync._order),
                                  rs.permutation(16))
