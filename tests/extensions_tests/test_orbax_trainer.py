"""Orbax trainer-extension checkpointer: trigger-driven snapshots,
generation GC, consensus resume (VERDICT r5 Missing #3 — the npz
checkpointer's contract at SURVEY §5 "orbax-style" scale)."""

import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint")

import chainermn_tpu as ct
from chainermn_tpu import F, L
from chainermn_tpu.core.optimizer import SGD
from chainermn_tpu.dataset import SerialIterator, get_mnist
from chainermn_tpu.extensions import create_multi_node_orbax_checkpointer
from chainermn_tpu.training import StandardUpdater, Trainer


class MLP(ct.Chain):
    def __init__(self):
        super().__init__()
        with self.init_scope():
            self.l1 = L.Linear(784, 16, seed=7)
            self.l2 = L.Linear(16, 10, seed=8)

    def forward(self, x, t):
        h = self.l2(F.relu(self.l1(x)))
        return F.softmax_cross_entropy(h, t)


def _make_trainer(out, epochs):
    model = MLP()
    comm = ct.create_communicator("jax_ici")
    opt = ct.create_multi_node_optimizer(SGD(lr=0.05), comm).setup(model)
    opt.seed = 11  # deterministic per-step rng stream for exact resume
    train, _ = get_mnist(n_train=256, n_test=8)
    train = ct.scatter_dataset(train, comm, shuffle=True, seed=0)
    it = SerialIterator(train, 8 * comm.size, shuffle=False)
    updater = StandardUpdater(it, opt)
    return model, comm, Trainer(updater, (epochs, "epoch"), out=out)


def test_orbax_save_and_consensus_resume_continues_exactly(tmp_path):
    ckpt_dir = str(tmp_path / "orbax")
    # golden: 4 uninterrupted epochs
    golden, _, trainer_g = _make_trainer(str(tmp_path / "g"), 4)
    trainer_g.run()
    w_golden = np.asarray(golden.l1.W.array)

    # crashed run: 2 epochs, snapshotting every epoch
    model1, comm1, trainer1 = _make_trainer(str(tmp_path / "r1"), 2)
    cp1 = create_multi_node_orbax_checkpointer(comm1, ckpt_dir)
    trainer1.extend(cp1, trigger=(1, "epoch"))
    trainer1.run()
    assert cp1.stats["snapshots"] == 2
    saved_iteration = trainer1.updater.iteration

    # relaunch: consensus resume restores the newest common generation,
    # then training continues to the SAME state as the uninterrupted run
    model2, comm2, trainer2 = _make_trainer(str(tmp_path / "r2"), 4)
    cp2 = create_multi_node_orbax_checkpointer(comm2, ckpt_dir)
    resumed = cp2.maybe_load(trainer2)
    assert resumed == saved_iteration
    assert trainer2.updater.iteration == saved_iteration
    np.testing.assert_array_equal(np.asarray(model2.l1.W.array),
                                  np.asarray(model1.l1.W.array))
    trainer2.extend(cp2, trigger=(1, "epoch"))
    trainer2.run()
    np.testing.assert_allclose(np.asarray(model2.l1.W.array), w_golden,
                               rtol=1e-6, atol=1e-7)


def test_orbax_maybe_load_empty_dir_returns_none(tmp_path):
    model, comm, trainer = _make_trainer(str(tmp_path / "r"), 1)
    cp = create_multi_node_orbax_checkpointer(comm, str(tmp_path / "none"))
    assert cp.maybe_load(trainer) is None


def test_orbax_gc_keeps_cp_interval_and_pins_protected(tmp_path):
    ckpt_dir = str(tmp_path / "orbax")
    model, comm, trainer = _make_trainer(str(tmp_path / "r"), 1)
    cp = create_multi_node_orbax_checkpointer(comm, ckpt_dir, cp_interval=2)
    for it in (1, 2, 3, 4, 5):
        cp.save(trainer, it)
    assert sorted(cp._ckpt.all_steps()) == [4, 5]
    assert cp.stats["gc"] == 3

    # a consensus resume pins its generation against later sweeps
    model2, comm2, trainer2 = _make_trainer(str(tmp_path / "r2"), 1)
    cp2 = create_multi_node_orbax_checkpointer(comm2, ckpt_dir,
                                               cp_interval=2)
    assert cp2.maybe_load(trainer2) == 5
    for it in (6, 7, 8):
        cp2.save(trainer2, it)
    steps = sorted(cp2._ckpt.all_steps())
    assert 5 in steps, "the resumed generation must never be swept"
    assert steps[-2:] == [7, 8]
