"""Distributed checkpointer: save/GC/consensus-resume round trip.

Mirrors reference ``extensions_tests/test_checkpoint.py`` (SURVEY.md §4).
"""

import os

import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu import F, L
from chainermn_tpu.core.optimizer import SGD
from chainermn_tpu.dataset import SerialIterator, get_mnist
from chainermn_tpu.training import StandardUpdater, Trainer


class MLP(ct.Chain):
    def __init__(self):
        super().__init__()
        with self.init_scope():
            self.l1 = L.Linear(784, 16, seed=7)
            self.l2 = L.Linear(16, 10, seed=8)

    def forward(self, x, t):
        h = self.l2(F.relu(self.l1(x)))
        return F.softmax_cross_entropy(h, t)


def _make_trainer(out, epochs=4):
    model = MLP()
    comm = ct.create_communicator("jax_ici")
    opt = ct.create_multi_node_optimizer(SGD(lr=0.05), comm).setup(model)
    train, _ = get_mnist(n_train=256, n_test=8)
    train = ct.scatter_dataset(train, comm, shuffle=True, seed=0)
    it = SerialIterator(train, 8 * comm.size, shuffle=False)
    updater = StandardUpdater(it, opt)
    return model, comm, Trainer(updater, (epochs, "epoch"), out=out)


def test_checkpoint_save_and_consensus_resume(tmp_path):
    out = str(tmp_path / "run")
    model, comm, trainer = _make_trainer(out)
    cp = ct.create_multi_node_checkpointer(comm, name="ckpt")
    trainer.extend(cp, trigger=(1, "epoch"))
    trainer.run()
    files = [f for f in os.listdir(out) if f.startswith("ckpt.")]
    assert files, "snapshots written"

    model2, comm2, trainer2 = _make_trainer(out)
    cp2 = ct.create_multi_node_checkpointer(comm2, name="ckpt")
    resumed = cp2.maybe_load(trainer2)
    assert resumed == max(int(f.split(".")[1]) for f in files)
    assert trainer2.updater.iteration == resumed
    w1 = np.asarray(model.l1.W.array)
    w2 = np.asarray(model2.l1.W.array)
    np.testing.assert_allclose(w1, w2, rtol=1e-6)


def test_checkpoint_gc_keeps_cp_interval(tmp_path):
    out = str(tmp_path / "run")
    model, comm, trainer = _make_trainer(out, epochs=8)
    cp = ct.create_multi_node_checkpointer(comm, name="g", cp_interval=3)
    trainer.extend(cp, trigger=(1, "epoch"))
    trainer.run()
    files = [f for f in os.listdir(out) if f.startswith("g.")]
    assert len(files) <= 3 + 1  # kept generations (+1 transient tolerance)
    assert cp.stats["snapshots"] == 8
    assert cp.stats["gc"] >= 4


def test_maybe_load_empty_dir_returns_none(tmp_path):
    out = str(tmp_path / "none")
    model, comm, trainer = _make_trainer(out)
    cp = ct.create_multi_node_checkpointer(comm, name="x")
    assert cp.maybe_load(trainer) is None
    assert trainer.updater.iteration == 0


def test_orbax_checkpointer_roundtrip(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from chainermn_tpu.extensions.orbax_checkpoint import OrbaxCheckpointer
    from chainermn_tpu import L
    import jax.numpy as jnp

    link = L.BatchNormalization(4)
    link.gamma.array = jnp.full((4,), 3.0)
    link.avg_mean = jnp.full((4,), 0.5)
    cp = OrbaxCheckpointer(str(tmp_path / "orbax"), max_to_keep=2)
    cp.save_link(1, link)
    cp.save_link(2, link)
    assert cp.latest_step() == 2

    link2 = L.BatchNormalization(4)
    assert cp.restore_link(link2)
    np.testing.assert_allclose(np.asarray(link2.gamma.array), 3.0)
    np.testing.assert_allclose(np.asarray(link2.avg_mean), 0.5)
    cp.close()
