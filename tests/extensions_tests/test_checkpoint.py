"""Distributed checkpointer: save/GC/consensus-resume round trip.

Mirrors reference ``extensions_tests/test_checkpoint.py`` (SURVEY.md §4).
"""

import os

import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu import F, L
from chainermn_tpu.core.optimizer import SGD
from chainermn_tpu.dataset import SerialIterator, get_mnist
from chainermn_tpu.training import StandardUpdater, Trainer


class MLP(ct.Chain):
    def __init__(self):
        super().__init__()
        with self.init_scope():
            self.l1 = L.Linear(784, 16, seed=7)
            self.l2 = L.Linear(16, 10, seed=8)

    def forward(self, x, t):
        h = self.l2(F.relu(self.l1(x)))
        return F.softmax_cross_entropy(h, t)


def _make_trainer(out, epochs=4):
    model = MLP()
    comm = ct.create_communicator("jax_ici")
    opt = ct.create_multi_node_optimizer(SGD(lr=0.05), comm).setup(model)
    train, _ = get_mnist(n_train=256, n_test=8)
    train = ct.scatter_dataset(train, comm, shuffle=True, seed=0)
    it = SerialIterator(train, 8 * comm.size, shuffle=False)
    updater = StandardUpdater(it, opt)
    return model, comm, Trainer(updater, (epochs, "epoch"), out=out)


def test_checkpoint_save_and_consensus_resume(tmp_path):
    out = str(tmp_path / "run")
    model, comm, trainer = _make_trainer(out)
    cp = ct.create_multi_node_checkpointer(comm, name="ckpt")
    trainer.extend(cp, trigger=(1, "epoch"))
    trainer.run()
    files = [f for f in os.listdir(out) if f.startswith("ckpt.")]
    assert files, "snapshots written"

    model2, comm2, trainer2 = _make_trainer(out)
    cp2 = ct.create_multi_node_checkpointer(comm2, name="ckpt")
    resumed = cp2.maybe_load(trainer2)
    assert resumed == max(int(f.split(".")[1]) for f in files)
    assert trainer2.updater.iteration == resumed
    w1 = np.asarray(model.l1.W.array)
    w2 = np.asarray(model2.l1.W.array)
    np.testing.assert_allclose(w1, w2, rtol=1e-6)


def test_checkpoint_gc_keeps_cp_interval(tmp_path):
    out = str(tmp_path / "run")
    model, comm, trainer = _make_trainer(out, epochs=8)
    cp = ct.create_multi_node_checkpointer(comm, name="g", cp_interval=3)
    trainer.extend(cp, trigger=(1, "epoch"))
    trainer.run()
    files = [f for f in os.listdir(out)
             if f.startswith("g.") and not f.endswith(".sum")]
    assert len(files) <= 3 + 1  # kept generations (+1 transient tolerance)
    # every surviving snapshot keeps its checksum sidecar (and GC removed
    # the stale generations' sidecars along with their data)
    sums = [f for f in os.listdir(out) if f.endswith(".sum")]
    assert {f + ".sum" for f in files} == set(sums)
    assert cp.stats["snapshots"] == 8
    assert cp.stats["gc"] >= 4


def test_maybe_load_empty_dir_returns_none(tmp_path):
    out = str(tmp_path / "none")
    model, comm, trainer = _make_trainer(out)
    cp = ct.create_multi_node_checkpointer(comm, name="x")
    assert cp.maybe_load(trainer) is None
    assert trainer.updater.iteration == 0


def test_orbax_zero_sharded_state_roundtrip(tmp_path):
    """ZeRO's flat optimizer state through the orbax path: each leaf is
    saved SHARDED (P(axis) over the mesh), restored onto a sharded
    template, and training continues bit-exactly — the pod-scale
    checkpoint mechanics for exactly the state ZeRO shards (the npz path
    gathers to host; orbax must not)."""
    pytest.importorskip("orbax.checkpoint")
    import jax
    from chainermn_tpu.extensions.orbax_checkpoint import OrbaxCheckpointer
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.models import Classifier, MLP

    def fresh():
        comm = ct.create_communicator("jax_ici")
        model = Classifier(MLP(n_units=16, n_out=3, seed=0))
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            MomentumSGD(lr=0.1, momentum=0.9), comm,
            zero_sharding=True).setup(model)
        return model, opt

    rng = np.random.RandomState(3)
    x = np.asarray(rng.normal(0, 1, (16, 12)).astype(np.float32))
    t = np.asarray(rng.randint(0, 3, 16).astype(np.int32))

    model_a, opt_a = fresh()
    for _ in range(3):
        opt_a.update(model_a, x, t)
    from chainermn_tpu.core.link import extract_state
    cp = OrbaxCheckpointer(str(tmp_path / "orbax_zero"))
    n_devices = len(jax.devices())

    def assert_flat_leaves_sharded(opt_state):
        flat = [l for l in jax.tree.leaves(opt_state)
                if getattr(l, "ndim", 0) == 1 and l.shape[0] > 1]
        assert flat
        for leaf in flat:
            assert len(leaf.addressable_shards) == n_devices
            assert leaf.addressable_shards[0].data.shape[0] \
                == leaf.shape[0] // n_devices

    # save-side pin: what we hand orbax IS the sharded state (no gather
    # upstream of save); OrbaxCheckpointer.save passes it through verbatim
    assert_flat_leaves_sharded(opt_a.actual_optimizer._opt_state)
    cp.save(3, {"model": extract_state(model_a),
                "opt": opt_a.actual_optimizer._opt_state})
    for _ in range(2):
        opt_a.update(model_a, x, t)

    # fresh process: run ONE update to materialize the sharded template,
    # then restore the step-3 state onto it
    model_b, opt_b = fresh()
    opt_b.update(model_b, x, t)
    template = {"model": extract_state(model_b),
                "opt": opt_b.actual_optimizer._opt_state}
    restored = cp.restore(3, template=template)
    cp.close()
    from chainermn_tpu.core.link import load_param_tree
    load_param_tree(model_b, restored["model"]["params"])
    opt_b.actual_optimizer._opt_state = restored["opt"]

    # restore-side pin: the restored flat leaves keep their P(axis)
    # sharding (placed per the sharded template, not replicated)
    assert_flat_leaves_sharded(restored["opt"])

    for _ in range(2):
        opt_b.update(model_b, x, t)
    for (na, pa), (nb, pb) in zip(model_a.namedparams(),
                                  model_b.namedparams()):
        np.testing.assert_array_equal(np.asarray(pa.array),
                                      np.asarray(pb.array),
                                      err_msg=f"{na} diverged after orbax "
                                              f"ZeRO resume")


def test_orbax_checkpointer_roundtrip(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from chainermn_tpu.extensions.orbax_checkpoint import OrbaxCheckpointer
    from chainermn_tpu import L
    import jax.numpy as jnp

    link = L.BatchNormalization(4)
    link.gamma.array = jnp.full((4,), 3.0)
    link.avg_mean = jnp.full((4,), 0.5)
    cp = OrbaxCheckpointer(str(tmp_path / "orbax"), max_to_keep=2)
    cp.save_link(1, link)
    cp.save_link(2, link)
    assert cp.latest_step() == 2

    link2 = L.BatchNormalization(4)
    assert cp.restore_link(link2)
    np.testing.assert_allclose(np.asarray(link2.gamma.array), 3.0)
    np.testing.assert_allclose(np.asarray(link2.avg_mean), 0.5)
    cp.close()
