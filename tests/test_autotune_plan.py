"""Self-tuning plan artifact gate (ISSUE 19: the derivation can't rot).

Mirrors tests/test_comm_budget.py's sweep pattern:
tools/autotune_plan.json commits HOW exchange plans are derived and —
once the recovery queue's FIRST-CHIP-CONTACT item 11 stamps it — WHAT
plan the first real fabric measurements implied.  Two layers:

* DERIVATION (backend-neutral, always on): the artifact's recorded
  formula / bucket rule / fallback constants must match the planner's
  own (``communicators._autotune`` + ``_memory_utility``), so the
  committed record tracks the code.  While ``status`` is
  ``pending_on_chip`` every numeric field is REFUSED off-chip and must
  stay null — a CPU-sim micro-bench number here would masquerade as
  fabric data.
* NUMBERS (armed when status flips to ``measured``): the committed
  plan must re-derive BIT-IDENTICALLY (same fingerprint) from the
  stamped measurements — the artifact can never disagree with what the
  planner says those measurements imply.
"""

import json
import os

ARTIFACT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools", "autotune_plan.json")


def _artifact():
    with open(ARTIFACT) as f:
        return json.load(f)


def test_artifact_schema():
    art = _artifact()
    assert art["status"] in ("pending_on_chip", "measured")
    for key in ("derivation", "plan", "measurements",
                "steps_per_sec_delta_vs_hand", "regression_tolerance_pct",
                "plan_version"):
        assert key in art, f"missing committed key {key!r}"
    assert art["regression_tolerance_pct"] > 0


def test_derivation_constants_track_the_planner():
    """The committed derivation record IS the planner's constants —
    a PR that changes the formula, the bucket rule, the overhead
    budget, or a fallback must re-commit the artifact and own the
    diff."""
    from chainermn_tpu.communicators import _autotune
    from chainermn_tpu.communicators._memory_utility import (
        DEFAULT_BUCKET_MB, DEFAULT_STRIPE_RATIO)
    art = _artifact()
    d = art["derivation"]
    assert art["plan_version"] == _autotune.PLAN_VERSION
    assert d["overhead_frac"] == _autotune.OVERHEAD_FRAC
    assert d["fallbacks"]["stripe_ratio"] == DEFAULT_STRIPE_RATIO
    assert d["fallbacks"]["bucket_mb"] == DEFAULT_BUCKET_MB
    # the recorded rule strings are exactly what derive_exchange_plan
    # writes into every plan's derivation block
    probe = _autotune.derive_exchange_plan(
        {"source": "startup", "hops": {"world": {"size": 2, "gbps": 1.0,
                                                 "lat_us": 100.0}}},
        {"axis": "probe", "kind": "flat", "size": 2,
         "exchange": "allreduce"})
    assert d["formula"] == probe["derivation"]["formula"]
    assert d["bucket_rule"] == probe["derivation"]["bucket_rule"]


def test_pending_refuses_numbers_off_chip():
    art = _artifact()
    if art["status"] != "pending_on_chip":
        return
    for key in ("plan", "measurements", "steps_per_sec_delta_vs_hand"):
        assert art[key] is None, (
            f"{key} is stamped while status is pending_on_chip — "
            f"numeric fields are refused off-chip; only the recovery "
            f"queue's FIRST-CHIP-CONTACT item 11 may stamp them "
            f"(and must flip status -> measured)")


def test_measured_plan_rederives_bit_identically():
    """Armed by item 11: the committed plan must be EXACTLY what the
    planner derives from the committed measurements — same fingerprint,
    byte for byte."""
    from chainermn_tpu.communicators._autotune import (derive_exchange_plan,
                                                       plan_fingerprint)
    art = _artifact()
    if art["status"] != "measured":
        return
    plan, measurements = art["plan"], art["measurements"]
    assert plan is not None and measurements is not None, \
        "status is measured but plan/measurements are unstamped"
    assert art["steps_per_sec_delta_vs_hand"] is not None
    rederived = derive_exchange_plan(measurements, plan["topology"])
    assert rederived["fingerprint"] == plan["fingerprint"], (
        "committed plan no longer re-derives from its own measurements "
        "(planner rules changed?): bump PLAN_VERSION and re-stamp via "
        "the recovery queue before re-committing")
    assert plan_fingerprint(plan) == plan["fingerprint"], \
        "committed plan body was edited without updating its fingerprint"
