"""XLA persistent-cache replay-segfault guard (ISSUE 3 satellite;
BENCH_NOTES r5 tail): on jax 0.4.37's CPU backend, a persisted
scan-over-train-steps executable compiles and runs clean on a COLD
cache, then SEGFAULTS when the next process replays the cached entry.
The guard (utils.compat.configure_persistent_cache) skips persistence
for exactly that (backend, program-kind) pair; elsewhere scan programs
get a ``.scan``-keyed sibling cache directory."""

import os
import subprocess
import sys

from chainermn_tpu.utils import compat
from chainermn_tpu.utils.compat import (configure_persistent_cache,
                                        persistent_cache_safe)


def test_safe_matrix(monkeypatch):
    # the CONFIRMED-broken pairs: cpu backend + scan program, and cpu
    # backend + params-donated step program
    assert not persistent_cache_safe("cpu", scan_program=True)
    assert not persistent_cache_safe("cpu", donated_program=True)
    assert not persistent_cache_safe("cpu", scan_program=True,
                                     donated_program=True)
    # undonated per-step programs replay fine on cpu
    assert persistent_cache_safe("cpu")
    assert persistent_cache_safe("tpu", scan_program=True)
    assert persistent_cache_safe("tpu", donated_program=True)
    assert persistent_cache_safe("tpu")
    # unset platform resolves through the host guess: the axon bench box
    # defaults to its TPU relay (cache stays on — it is relay
    # protection), any other host defaults to CPU, where the replay
    # crash is live
    monkeypatch.setattr(compat, "_platform_guess", lambda: "axon")
    assert persistent_cache_safe(None, scan_program=True)
    assert persistent_cache_safe("", donated_program=True)
    monkeypatch.setattr(compat, "_platform_guess", lambda: "cpu")
    assert not persistent_cache_safe(None, scan_program=True)
    assert not persistent_cache_safe(None, donated_program=True)
    assert persistent_cache_safe(None)


class _FakeJax:
    def __init__(self):
        self.updates = {}
        self.config = self

    def update(self, key, value):
        self.updates[key] = value


def test_configure_skips_cpu_scan_and_keys_scan_dir(tmp_path, monkeypatch):
    fake = _FakeJax()
    assert configure_persistent_cache(fake, cache_dir=str(tmp_path / "c"),
                                      platform="cpu",
                                      scan_program=True) is False
    assert fake.updates == {}
    # per-step cpu: enabled, plain dir
    assert configure_persistent_cache(fake, cache_dir=str(tmp_path / "c"),
                                      platform="cpu", scan_program=False)
    assert fake.updates["jax_compilation_cache_dir"] == str(tmp_path / "c")
    # scan on the TPU box (unset platform resolves to axon there):
    # enabled under the .scan-keyed sibling dir
    monkeypatch.setattr(compat, "_platform_guess", lambda: "axon")
    fake2 = _FakeJax()
    assert configure_persistent_cache(fake2, cache_dir=str(tmp_path / "c"),
                                      platform=None, scan_program=True)
    assert fake2.updates["jax_compilation_cache_dir"] \
        == str(tmp_path / "c") + ".scan"


_PROGRAM_TEMPLATE = r"""
import sys
import jax
from chainermn_tpu.utils.compat import configure_persistent_cache
enabled = configure_persistent_cache(jax, platform="cpu",
                                     scan_program={scan},
                                     donated_program={donated})
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import chainermn_tpu as ct
from chainermn_tpu.core.optimizer import SGD


class Quad(ct.Chain):
    def __init__(self):
        super().__init__()
        with self.init_scope():
            self.w = ct.Parameter(np.full(3, 5.0, np.float32))

    def forward(self, x):
        return jnp.sum((self.w.array - 3.0) ** 2) + 0.0 * jnp.sum(x)


m = Quad()
comm = ct.create_communicator("jax_ici")
inner = SGD(lr=0.1)
inner.donate_params = {donated}
opt = ct.create_multi_node_optimizer(inner, comm).setup(m)
if {scan}:
    xs = jnp.zeros((2, comm.size, 1))
    losses = opt.update_scan(m, xs)
    assert losses.shape == (2,)
else:
    opt.update(m, jnp.zeros((comm.size, 1)))
print("PROGRAM_OK", enabled)
"""


def _double_run(tmp_path, scan, donated):
    """Run the same program in two processes against one cache dir;
    assert both exit clean and report whether persistence was enabled."""
    cache_dir = str(tmp_path / "xla_cache")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ,
               CHAINERMN_TPU_XLA_CACHE_DIR=cache_dir,
               PYTHONPATH=root + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    env.pop("JAX_PLATFORMS", None)
    program = _PROGRAM_TEMPLATE.format(scan=scan, donated=donated)
    enabled = None
    for attempt in (1, 2):
        proc = subprocess.run([sys.executable, "-c", program],
                              env=env, capture_output=True, text=True,
                              timeout=300)
        assert proc.returncode == 0, (
            f"run {attempt} (scan={scan} donated={donated}) "
            f"rc={proc.returncode} (139/134 = the warm-cache replay "
            f"crash the guard exists for)\n{proc.stderr[-2000:]}")
        enabled = "PROGRAM_OK True" in proc.stdout
    return cache_dir, enabled


def test_scan_program_runs_twice_against_warm_cache(tmp_path):
    """The r5 repro shape: the SAME scan program, two processes, one
    cache directory (pre-guard: run1 RC=0, run2 RC=139)."""
    cache_dir, enabled = _double_run(tmp_path, scan=True, donated=False)
    assert enabled is False
    # the guard refused persistence: nothing was cached to replay
    assert not os.path.exists(cache_dir) or not os.listdir(cache_dir)


def test_donated_program_runs_twice_against_warm_cache(tmp_path):
    """The round-6 repro shape: a params-DONATED per-step program's
    persisted executable crashes on CPU replay exactly like the scan
    one (reproduced at the pre-PR base commit too) — and donation is
    now the default, so this pair is what every cpu bench run hits."""
    cache_dir, enabled = _double_run(tmp_path, scan=False, donated=True)
    assert enabled is False
    assert not os.path.exists(cache_dir) or not os.listdir(cache_dir)


def test_undonated_per_step_program_may_persist(tmp_path):
    """The SAFE pair (cpu, per-step, no params donation) keeps its
    persistent cache enabled and both runs stay clean.  (The tiny test
    program compiles under the 1 s persistence threshold, so the dir
    may legitimately stay empty — the contract under test is the
    guard's decision plus a clean double run, not the write.)"""
    _, enabled = _double_run(tmp_path, scan=False, donated=False)
    assert enabled is True
