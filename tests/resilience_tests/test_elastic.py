"""Elastic shrink/grow machinery, single process, tier-1 (ISSUE 10).

Three layers, all deterministic:

* the MEMBERSHIP PROTOCOL against an in-memory KV store — leave-
  excluded shrink consensus, join admission, epoch monotonicity,
  zombie-presence screening, typed timeout when no decision lands, and
  adoption of a view that excludes the caller (the split-brain escape);
* the RESIZE MACHINERY — ``change_communicator`` re-planning (zero
  layout recomputed, compiled-step cache dropped, stale/EF buffers
  re-seeding zeros, sharded flat state re-committed), the
  ``global_batch_plan`` policy table, and ``rescatter_dataset``'s
  no-sample-dropped/no-double-count partition property;
* the FULL SUPERVISOR ARC on the simulated 8-device CPU host — a
  scripted membership shrinks a 4-device world to 2 and grows it back
  mid-training through the real ``Trainer.run`` supervisor +
  fault-injected preemption, asserting convergence parity against the
  uninterrupted golden and the stats/giving-up satellite surface.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import chainermn_tpu as ct
from chainermn_tpu.communicators import (ElasticMembership,
                                         ElasticMeshCommunicator,
                                         FaultInjectionCommunicator,
                                         FaultSchedule, MembershipView,
                                         RankPreempted)
from chainermn_tpu.communicators._host_channel import ChannelTimeoutError
from chainermn_tpu.core.optimizer import MomentumSGD
from chainermn_tpu.extensions import (ElasticConfigError, ElasticRecovery,
                                      RecoveryGivingUp, global_batch_plan)
from chainermn_tpu.models import MLP, Classifier

pytestmark = pytest.mark.chaos


class KV:
    """Thread-safe in-memory stand-in for the coordination KV store
    (the real client's narrow surface: try_get raises on missing)."""

    def __init__(self):
        self.store = {}
        self.lock = threading.Lock()

    def key_value_set(self, k, v):
        with self.lock:
            self.store[k] = str(v)

    def key_value_try_get(self, k):
        with self.lock:
            if k not in self.store:
                raise KeyError(k)
            return self.store[k]

    def key_value_delete(self, k):
        with self.lock:
            self.store.pop(k, None)


def _member(kv, rank, world=2, **kw):
    kw.setdefault("settle_s", 0.05)
    kw.setdefault("poll_s", 0.002)
    kw.setdefault("timeout_ms", 4000)
    return ElasticMembership(kv, rank=rank, world=world, **kw)


# -- membership protocol -----------------------------------------------------

def test_bootstrap_view_and_epoch():
    m = _member(KV(), 0)
    assert m.current_epoch() == 0
    v = m.current_view()
    assert v.epoch == 0 and v.members == (0, 1)
    assert v.slot(1) == 1 and v.slot(5) is None
    assert 0 in v and 7 not in v


def test_leave_excluded_shrink_consensus():
    kv = KV()
    m0, m1 = _member(kv, 0), _member(kv, 1)
    m1.announce_leave(note="preempted")
    v = m0.resolve(expect={0})
    assert v == MembershipView(1, (0,))
    # the decision is durable: the departed rank adopts it too
    assert m1.current_view() == v
    assert m0.stats["led"] == 1


def test_grow_consensus_and_join_scrub():
    kv = KV()
    m0, m1 = _member(kv, 0), _member(kv, 1)
    m1.announce_leave()
    m0.resolve(expect={0})
    m1.announce_join()
    assert m0.pending_joins() == (1,)
    out = {}
    t = threading.Thread(
        target=lambda: out.setdefault(1, m1.resolve(expect={0, 1})))
    t.start()
    out[0] = m0.resolve(expect={0, 1})
    t.join()
    assert out[0] == out[1] == MembershipView(2, (0, 1))
    # consumed intents are scrubbed: no standing join re-admits
    assert m0.pending_joins() == ()


def test_epochs_monotonic_across_resolves():
    kv = KV()
    m0 = _member(kv, 0)
    _member(kv, 1).announce_leave()
    epochs = [m0.resolve(expect={0}).epoch for _ in range(3)]
    assert epochs == [1, 2, 3]


def test_announce_join_retracts_leave():
    kv = KV()
    m1 = _member(kv, 1)
    m1.announce_leave()
    m1.announce_join()
    v = _member(kv, 0).resolve(expect={0, 1}, timeout_ms=500) \
        if False else None
    # rank 1 has no live resolve loop here; just check the intent keys
    assert "cmn/elastic/leave/1" not in kv.store
    assert "cmn/elastic/join/1" in kv.store


def test_zombie_presence_screened_at_settle():
    """A presence key stranded by a dead rank's earlier attempt (its
    token never changes) must not be decided into the view."""
    kv = KV()
    kv.key_value_set("cmn/elastic/e1/present/1", "99")  # frozen token
    v = _member(kv, 0).resolve()  # settle path: no expect
    assert v.members == (0,)


def test_resolve_typed_timeout_when_leader_never_decides():
    """A live lower-ranked candidate that never publishes (it keeps
    beating but is stuck) leaves the higher rank with a TYPED timeout,
    not a hang."""
    kv = KV()
    beat = [0]

    def sleep(s):
        # rank 0 'exists': its token keeps changing, so rank 1 neither
        # leads (not the minimum) nor screens it out as a zombie
        beat[0] += 1
        kv.key_value_set("cmn/elastic/e1/present/0", str(beat[0]))
        time.sleep(0)

    m1 = _member(kv, 1, sleep=sleep, timeout_ms=300)
    with pytest.raises(ChannelTimeoutError) as e:
        m1.resolve()
    assert e.value.op == "membership.resolve"


def test_adopts_in_flight_decision_that_excludes_caller():
    """A late rank whose epoch was decided without it ADOPTS the
    published view (the caller handles its exclusion — the supervisor's
    rejoin path), never publishing a second one."""
    kv = KV()
    kv.key_value_set("cmn/elastic/e1/view", "0")  # decided without 1
    adopted = _member(kv, 1).resolve()
    assert adopted == MembershipView(1, (0,))
    assert 1 not in adopted


def test_require_blocks_lone_joiner_from_disjoint_world():
    """The split-brain guard: a joiner resolving with require=
    (the survivors) can NEVER settle a world by itself — unsatisfiable
    require ends in the typed timeout, not a disjoint view."""
    kv = KV()
    m0, m1 = _member(kv, 0), _member(kv, 1)
    m1.announce_leave()
    m0.resolve(expect={0})
    m1.announce_join()
    with pytest.raises(ChannelTimeoutError):
        m1.resolve(expect={0, 1}, require={0}, timeout_ms=300)
    # and WITH the survivor participating, the same resolve admits
    out = {}
    t = threading.Thread(target=lambda: out.setdefault(
        1, m1.resolve(expect={0, 1}, require={0})))
    t.start()
    out[0] = m0.resolve(expect={0, 1})
    t.join()
    assert out[0] == out[1]
    assert out[0].members == (0, 1)


# -- batch policy + rescatter ------------------------------------------------

def test_global_batch_plan_table():
    assert global_batch_plan(64, 8) == {
        "policy": "rescale", "global_bs": 64, "world_size": 8,
        "dispatch_bs": 64, "per_rank_bs": 8, "accum_steps": 1}
    # shrink 8 -> 2 at fixed global batch: per-rank grows 4x
    assert global_batch_plan(64, 2)["per_rank_bs"] == 32
    # bounded per-rank memory falls through to accumulation
    plan = global_batch_plan(64, 2, max_per_rank=8)
    assert plan == {"policy": "accumulate", "global_bs": 64,
                    "world_size": 2, "dispatch_bs": 16,
                    "per_rank_bs": 8, "accum_steps": 4}
    # explicit accumulate policy prefers the fewest dispatches
    assert global_batch_plan(64, 2, policy="accumulate")[
        "accum_steps"] == 1
    with pytest.raises(ElasticConfigError):
        global_batch_plan(12, 8)
    with pytest.raises(ValueError):
        global_batch_plan(8, 2, policy="bogus")


class _FakeTopology:
    def __init__(self, size, inter_size, inter_rank):
        self.size = size
        self.inter_size = inter_size
        self.inter_rank = inter_rank

    def allgather_obj(self, obj):
        return [obj] * self.inter_size


def test_rescatter_dataset_no_loss_no_double_count():
    """Re-slicing a scattered shard for a resized world is a pure
    function of (order, topology): the union over the new hosts equals
    the union over the old ones and every sample appears exactly once
    (beyond the documented equal-length wrap padding)."""
    data = list(range(21))
    comm2 = _FakeTopology(size=2, inter_size=2, inter_rank=0)
    shard0 = ct.scatter_dataset(data, comm2, shuffle=True, seed=5)
    # shrink to one host: re-slice from the SAME agreed order
    comm1 = _FakeTopology(size=1, inter_size=1, inter_rank=0)
    new = ct.rescatter_dataset(shard0, comm1)
    assert sorted(set(new[i] for i in range(len(new)))) == data
    assert len(new) == 21  # exact multiple of 1: padding gone
    # grow to four hosts: the four shards partition the order with
    # only the wrap-padding duplicated, and every member computes its
    # slice independently
    shards = [ct.rescatter_dataset(
        shard0, _FakeTopology(size=4, inter_size=4, inter_rank=r))
        for r in range(4)]
    seen = [s[i] for s in shards for i in range(len(s))]
    assert set(seen) == set(data)
    assert len(seen) == 24  # 21 padded to the next multiple of 4
    assert len(seen) - len(set(seen)) == 3  # exactly the wrap padding
    with pytest.raises(TypeError):
        ct.rescatter_dataset(data, comm1)


# -- elastic communicator + optimizer re-plan --------------------------------

def _data(n=16, d=12, k=3, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.normal(0, 1, (n, d)).astype(np.float32)),
            jnp.asarray(rng.randint(0, k, n).astype(np.int32)))


def _world(n_devices, epoch=0, **kw):
    return ElasticMeshCommunicator(members=[0], epoch=epoch,
                                   devices=jax.devices()[:n_devices],
                                   **kw)


def test_elastic_communicator_surface():
    comm = _world(4, epoch=3)
    assert comm.size == 4
    assert comm.members == (0,)
    assert comm.inter_size == 1 and comm.inter_rank == 0
    assert comm.stable_rank == 0
    assert comm.axis_name == "elastic_e3"
    assert comm._local_device_counts() == [4]
    # loopback object plane: never the all-boot-processes fallback
    assert comm._process_allgather_pickled({"a": 1}) == [{"a": 1}]
    with pytest.raises(ValueError):
        ElasticMeshCommunicator(members=[])


def test_change_communicator_reseeds_and_replans():
    """The documented resize contract: compiled steps re-derive, the
    ZeRO layout follows the new size, and the stale-grad/EF buffers
    re-seed zeros."""
    from chainermn_tpu.extensions.elastic import _rehome_model
    x, t = _data()
    comm = _world(4)
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1, momentum=0.9), comm,
        double_buffering=True).setup(model)
    for _ in range(2):
        opt.update(model, x, t)
    assert opt._stale_grads is not None
    comm2 = _world(2, epoch=1)
    opt.change_communicator(comm2)
    assert opt.communicator is comm2
    assert opt._stale_grads is None  # re-seed zeros
    assert opt._residual is None
    assert len(opt._mn_step_cache) == 0
    _rehome_model(model, comm2)
    # first post-resize update applies zeros (fresh double-buffer
    # semantics) and runs on the 2-device mesh
    assert np.isfinite(float(opt.update(model, x, t)))


def test_change_communicator_recommits_sharded_state():
    """Fully-addressable flat opt-state survives a resize by value:
    sliced to the true length and re-padded to the new world's
    multiple (the PR 5 size-changed-resume brick, in memory)."""
    from chainermn_tpu.extensions.elastic import _rehome_model
    x, t = _data()
    golden_m = Classifier(MLP(n_units=16, n_out=3, seed=0))
    gopt = MomentumSGD(lr=0.1, momentum=0.9).setup(golden_m)
    glosses = [float(gopt.update(golden_m, x, t)) for _ in range(4)]

    comm = _world(4)
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1, momentum=0.9), comm,
        exchange="reduce_scatter").setup(model)
    losses = [float(opt.update(model, x, t)) for _ in range(2)]
    comm2 = _world(2, epoch=1)
    opt.change_communicator(comm2)
    _rehome_model(model, comm2)
    assert opt._zero_layout is not None
    _, n, n_pad = opt._zero_layout
    assert n_pad % comm2.size == 0
    losses += [float(opt.update(model, x, t)) for _ in range(2)]
    np.testing.assert_allclose(losses, glosses, rtol=1e-5, atol=1e-7)


def test_change_communicator_same_comm_is_noop():
    comm = _world(2)
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1), comm).setup(model)
    assert opt.change_communicator(comm) is opt


# -- the full supervisor arc (simulated single-controller world) -------------

class _ScriptedMembership:
    """Duck-typed ElasticMembership whose decisions are scripted — the
    single-controller way to drive the supervisor through a shrink and
    a grow without real processes."""

    def __init__(self, views):
        self.rank = 0
        self.world = 2
        self.timeout_ms = 1000
        self.poll_s = 0.0
        self._epoch = 0
        self._views = list(views)  # member tuples, popped per resolve
        self.joins = ()
        self.left = []
        self.joined = []

    def current_epoch(self):
        return self._epoch

    def current_view(self):
        return MembershipView(self._epoch, (0, 1) if self._epoch == 0
                              else self._last)

    def bootstrap_view(self):
        return MembershipView(0, (0, 1))

    def announce_leave(self, note=""):
        self.left.append(note)

    def announce_join(self, note=""):
        self.joined.append(note)

    def pending_joins(self, view=None):
        joins, self.joins = self.joins, ()
        return joins

    def resolve(self, expect=None, require=None, timeout_ms=None):
        members = self._views.pop(0)
        self._epoch += 1
        self._last = tuple(members)
        return MembershipView(self._epoch, members)


def _elastic_trainer(tmp_path, schedule, membership, factory, iters=12):
    from chainermn_tpu.dataset import SerialIterator, TupleDataset
    from chainermn_tpu.training import StandardUpdater, Trainer
    from chainermn_tpu.training.trainer import Extension

    x, t = _data(n=32)

    class _Beacon(Extension):
        trigger = (1, "iteration")
        priority = 400

        def __init__(self, recovery):
            self.recovery = recovery

        def __call__(self, trainer):
            self.recovery.comm.bcast_obj(
                {"it": trainer.updater.iteration}, root=0)

    comm = _world(4)
    if schedule is not None:
        comm = FaultInjectionCommunicator(comm, schedule)
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.05, momentum=0.9), comm).setup(model)
    it = SerialIterator(TupleDataset(np.asarray(x), np.asarray(t)), 8,
                        shuffle=False)
    trainer = Trainer(StandardUpdater(it, opt), (iters, "iteration"),
                      out=str(tmp_path))
    cp = ct.create_multi_node_checkpointer(comm, name="els",
                                           path=str(tmp_path))
    recovery = ElasticRecovery(checkpointer=cp, comm=comm,
                               membership=membership,
                               comm_factory=factory, verbose=False)
    trainer.extend(_Beacon(recovery))
    trainer.extend(cp, trigger=(3, "iteration"))
    trainer.extend(recovery)
    return trainer, model, opt, recovery


def _subset_factory(split):
    """view -> device-subset world: the simulated-host map (member set
    -> how many of the 8 local devices the world covers)."""
    def factory(view):
        return ElasticMeshCommunicator(
            members=[0], epoch=view.epoch,
            devices=jax.devices()[:split[view.members]],
            axis_name=f"sim_e{view.epoch}")
    return factory


def test_supervisor_shrinks_and_regrows_with_parity(tmp_path):
    """The full arc through the REAL Trainer.run supervisor on the
    simulated host: injected fault at iteration 4 → scripted shrink to
    a 2-device world → training continues → scripted join at the next
    poll → grow back to 4 devices → the run finishes at the full
    iteration count with the final params inside parity of the
    uninterrupted golden run."""
    split = {(0,): 2, (0, 1): 4}
    sched = FaultSchedule([dict(op="bcast_obj", nth=7)], seed=0)
    membership = _ScriptedMembership(views=[(0,), (0, 1)])
    trainer, model, opt, rec = _elastic_trainer(
        tmp_path / "el", sched, membership, _subset_factory(split))

    # plant the join: after the shrink has happened, the next poll
    # admits member 1 back
    orig_resolve = membership.resolve

    def resolve(expect=None, timeout_ms=None):
        v = orig_resolve(expect, timeout_ms)
        if v.members == (0,):
            membership.joins = (1,)
        return v
    membership.resolve = resolve

    trainer.run()
    assert trainer.updater.iteration == 12
    assert rec.stats["resizes"] == 2, rec.stats
    assert rec.stats["ranks_lost"] == 1
    assert rec.stats["ranks_joined"] == 1
    assert rec.view.members == (0, 1)
    assert rec.comm.size == 4

    # golden: uninterrupted 12 iterations on the 4-device world
    g_trainer, g_model, _, g_rec = _elastic_trainer(
        tmp_path / "g", None, _ScriptedMembership([]), None)
    g_trainer.run()
    assert g_rec.stats["resizes"] == 0
    for a, b in zip(model.params(), g_model.params()):
        np.testing.assert_allclose(np.asarray(a.array),
                                   np.asarray(b.array),
                                   rtol=5e-2, atol=1e-4)


def test_preempted_rank_fail_stops_without_rejoin(tmp_path):
    """Production default (rejoin_after_s=None): RankPreempted
    announces the leave, then re-raises — the scheduler owns the
    restart, the process exits hard."""
    sched = FaultSchedule([dict(op="bcast_obj", nth=3,
                                action="preempt", rank=0)], seed=0)
    membership = _ScriptedMembership(views=[])
    trainer, _, _, rec = _elastic_trainer(
        tmp_path, sched, membership, None)
    with pytest.raises(RankPreempted):
        trainer.run()
    assert membership.left, "leave was not announced"


def test_min_world_floor_gives_up_with_view(tmp_path):
    """Shrinking below min_world raises RecoveryGivingUp carrying the
    membership view in its message (the satellite's who-was-there
    requirement)."""
    sched = FaultSchedule([dict(op="bcast_obj", nth=3)], seed=0)
    membership = _ScriptedMembership(views=[(0,)])
    trainer, _, _, rec = _elastic_trainer(
        tmp_path, sched, membership, None)
    rec.min_world = 2
    with pytest.raises(RecoveryGivingUp) as e:
        trainer.run()
    assert "members [0]" in str(e.value)
    assert e.value.membership.members == (0,)


def test_resize_rescatters_host_shard_even_to_one_controller():
    """The resize batch hook re-slices a scattered shard at EVERY new
    world size: a shrink to ONE controller must widen the survivor's
    partial shard to the full order — keeping the old half-shard would
    silently train on a fraction of each epoch."""
    from types import SimpleNamespace

    from chainermn_tpu.dataset import SerialIterator

    data = list(range(16))
    shard = ct.scatter_dataset(
        data, _FakeTopology(size=2, inter_size=2, inter_rank=0),
        shuffle=True, seed=3)
    assert len(shard) == 8  # the survivor's old half-shard
    it = SerialIterator(shard, 8, shuffle=False)
    trainer = SimpleNamespace(updater=SimpleNamespace(
        get_iterator=lambda name: it))
    rec = ElasticRecovery(membership=_ScriptedMembership([]),
                          comm=_world(1), verbose=False)
    rec._check_batch(trainer, _world(1, epoch=1))
    assert sorted(set(it.dataset[i] for i in range(len(it.dataset)))) \
        == data
    assert len(it.dataset) == 16


def test_swap_communicator_repoints_comm_holding_iterators():
    """Comm-holding iterators (the multi-node batch broadcaster) must
    follow a resize: left on the boot comm, every batch fetch would
    ride the dead world's channel (review fix)."""
    from types import SimpleNamespace

    from chainermn_tpu.dataset import SerialIterator

    boot = _world(4)
    base = SerialIterator(list(range(8)), 4, shuffle=False)
    mni = ct.create_multi_node_iterator(base, boot)
    assert mni.comm is boot
    trainer = SimpleNamespace(updater=SimpleNamespace(
        _iterators={"main": mni},
        get_all_optimizers=lambda: {}))
    rec = ElasticRecovery(membership=_ScriptedMembership([]),
                          comm=boot, verbose=False)
    new = _world(2, epoch=1)
    rec._swap_communicator(trainer, new)
    assert mni.comm is new
    assert rec.comm is new


def test_check_batch_unwraps_multi_node_iterator():
    """The batch-plan validation reaches through comm-wrapping
    iterators to the base iterator's batch_size — an indivisible
    global batch must fail TYPED at resize time, not as a shard_map
    shape error inside the first resized step (review fix)."""
    from types import SimpleNamespace

    from chainermn_tpu.dataset import SerialIterator

    boot = _world(4)
    base = SerialIterator(list(range(12)), 12, shuffle=False)
    mni = ct.create_multi_node_iterator(base, boot)
    trainer = SimpleNamespace(updater=SimpleNamespace(
        get_iterator=lambda name: mni))
    rec = ElasticRecovery(membership=_ScriptedMembership([]),
                          comm=boot, verbose=False,
                          max_per_rank_bs=2)  # shrink blows the bound
    with pytest.raises(ElasticConfigError) as e:
        rec._check_batch(trainer, _world(2, epoch=1))
    assert e.value.plan["accum_steps"] > 1


def test_epoch_discovery_is_monotone_append_only():
    """Decided epochs are append-only keys: discovery can never regress
    through a pointer-overwrite gap (review fix — the real client's
    delete-then-set emulation has a missing-key window)."""
    kv = KV()
    m0 = _member(kv, 0)
    _member(kv, 1).announce_leave()
    m0.resolve(expect={0})
    m0.resolve(expect={0})
    assert m0.current_epoch() == 2
    # no single mutable pointer exists to race on
    assert "cmn/elastic/epoch" not in kv.store
    assert "cmn/elastic/epochs/1" in kv.store
    assert "cmn/elastic/epochs/2" in kv.store
    # a FRESH instance (cache cold) discovers the same epoch
    assert _member(kv, 0).current_epoch() == 2


def test_giving_up_message_carries_last_view():
    err = RecoveryGivingUp("budget exhausted (3/3)",
                           membership=MembershipView(4, (0, 2, 3)))
    assert "epoch 4" in str(err)
    assert "members [0, 2, 3]" in str(err)
    plain = RecoveryGivingUp("budget exhausted (3/3)")
    assert "membership" not in str(plain)
