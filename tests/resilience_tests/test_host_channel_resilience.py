"""HostChannel tolerance mechanics against a fake KV store + fake clock:
per-op deadlines, bounded retry with exponential backoff, key cleanup in
``finally``, heartbeat → PeerLostError, generation rotation, and injected
transport faults (lost chunk / stale key / transient raise).

All deterministic: the fake clock advances only when the channel sleeps
or a blocking get times out, so backoff timing is asserted exactly."""

import pickle

import pytest

from chainermn_tpu.communicators import bind_host_channel
from chainermn_tpu.communicators._host_channel import (
    ChannelTimeoutError, HostChannel, PeerLostError)
from chainermn_tpu.communicators.fault_schedule import (FaultSchedule,
                                                        InjectedFault)

pytestmark = pytest.mark.chaos


class FakeClock:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


class FakeKV:
    """In-memory stand-in for the coordination-service KV client.

    ``blocking_key_value_get`` on a missing key advances the fake clock
    by the full timeout then raises (what the real client does, minus
    the waiting).  Barriers complete instantly when ``barrier_parties``
    is 1 and time out otherwise — single-threaded tests cannot have a
    peer arrive."""

    def __init__(self, clock, barrier_parties=1):
        self.store = {}
        self.clock = clock
        self.barrier_parties = barrier_parties
        self.barrier_waits = []

    def key_value_set(self, k, v):
        self.store[k] = v if isinstance(v, str) else str(v)

    def key_value_set_bytes(self, k, v):
        self.store[k] = bytes(v)

    def key_value_try_get(self, k):
        if k not in self.store:
            raise KeyError(k)
        return self.store[k]

    def key_value_delete(self, k):
        self.store.pop(k, None)

    def blocking_key_value_get(self, k, timeout_ms):
        if k in self.store:
            return self.store[k]
        self.clock.t += timeout_ms / 1000.0
        raise RuntimeError(f"Deadline Exceeded: {k}")

    def blocking_key_value_get_bytes(self, k, timeout_ms):
        v = self.blocking_key_value_get(k, timeout_ms)
        return v if isinstance(v, bytes) else v.encode()

    def wait_at_barrier(self, barrier_id, timeout_ms):
        self.barrier_waits.append(barrier_id)
        if self.barrier_parties > 1:
            self.clock.t += timeout_ms / 1000.0
            raise RuntimeError(f"Barrier timed out: {barrier_id}")


def make_channel(clock=None, kv=None, pid=0, nprocs=2, **kwargs):
    clock = clock or FakeClock()
    kv = kv if kv is not None else FakeKV(clock)
    kwargs.setdefault("timeout_ms", 1000)
    ch = HostChannel(namespace="t", client=kv, clock=clock,
                     sleep=clock.sleep, process_id=pid,
                     num_processes=nprocs, **kwargs)
    return ch, kv, clock


# -- retry / backoff / deadlines --------------------------------------------

def test_recv_missing_message_times_out_typed():
    ch, kv, clock = make_channel(timeout_ms=1000, max_retries=2,
                                 backoff_base_s=0.05)
    with pytest.raises(ChannelTimeoutError) as ei:
        ch.recv_obj(1)
    err = ei.value
    assert err.op == "p2p" and "p2p/1-0" in err.key
    assert err.timeout_ms == 1000
    # at least one attempt ran; the failure is typed, not a bare RuntimeError
    assert err.attempts >= 1


def test_backoff_sequence_doubles_and_caps():
    ch, kv, clock = make_channel(timeout_ms=3_600_000, max_retries=4,
                                 backoff_base_s=0.05, backoff_max_s=0.3)
    sched = FaultSchedule([dict(op="hc.get", prob=1.0, count=None)])
    bind_host_channel(ch, sched, sleep=clock.sleep)
    with pytest.raises(ChannelTimeoutError):
        ch.recv_obj(1)
    # every attempt raised at the hook before touching the store; the
    # pauses BETWEEN the 5 attempts (1 + 4 retries) double then cap —
    # and no dead pause after the final, already-decided failure
    assert clock.sleeps == [0.05, 0.1, 0.2, 0.3]


def test_transient_fault_absorbed_by_retry():
    ch, kv, clock = make_channel()
    ch2, _, _ = make_channel(kv=kv, clock=clock, pid=1)
    ch2.send_obj({"v": 41}, 0)
    sched = FaultSchedule([dict(op="hc.get", nth=1)])  # first attempt only
    bind_host_channel(ch, sched, sleep=clock.sleep)
    assert ch.recv_obj(1) == {"v": 41}
    assert ch.stats["retries"] == 1


def test_per_op_timeout_overrides_default():
    ch, kv, clock = make_channel(timeout_ms=50_000,
                                 op_timeouts={"p2p": 500}, max_retries=0)
    t0 = clock.t
    with pytest.raises(ChannelTimeoutError) as ei:
        ch.recv_obj(1)
    assert ei.value.timeout_ms == 500
    assert clock.t - t0 <= 1.5  # bounded by the p2p deadline, not 50 s


def test_peer_lost_not_retried():
    """PeerLostError must cut straight through the retry loop.

    Staleness is observer-local: the blocked get first *sees* the peer's
    frozen token, then accuses it once the token stays unchanged past
    stall_s of local waiting — no cross-host clock comparison."""
    clock = FakeClock()
    kv = FakeKV(clock)
    ch, _, _ = make_channel(clock=clock, kv=kv, max_retries=5,
                            timeout_ms=60_000)
    ch.enable_heartbeat(interval_s=1.0, stall_s=3.0, wall=clock,
                        thread=False)
    # peer 1 beat once, then went silent (token never changes again)
    kv.key_value_set(f"{ch._prefix()}/hb/1", "1:somewhen")
    clock.t += 10.0
    with pytest.raises(PeerLostError) as ei:
        ch.recv_obj(1)
    assert ei.value.rank == 1
    assert ei.value.stale_s >= 3.0
    assert clock.sleeps == []  # zero backoff pauses: not treated transient


def test_heartbeat_clock_skew_cannot_fabricate_lost_peer():
    """A peer whose wall clock is far behind ours but whose token keeps
    changing is alive — skew must never be mistaken for a stall."""
    clock = FakeClock()
    ch, kv, _ = make_channel(clock=clock)
    mon = ch.enable_heartbeat(interval_s=1.0, stall_s=2.0, wall=clock,
                              thread=False)
    for step in range(10):  # tokens change; embedded timestamps are bogus
        kv.key_value_set(f"{ch._prefix()}/hb/1", f"{step}:-99999.0")
        clock.t += 5.0  # each gap exceeds stall_s, but the token moved
        mon.check()


def test_heartbeat_never_accuses_silent_never_beaten_peer():
    clock = FakeClock()
    ch, kv, _ = make_channel(clock=clock)
    mon = ch.enable_heartbeat(interval_s=1.0, stall_s=2.0, wall=clock,
                              thread=False)
    clock.t += 100.0
    mon.check()  # peer 1 never posted a beat: absence is not evidence


def test_heartbeat_beat_rate_limited():
    clock = FakeClock()
    ch, kv, _ = make_channel(clock=clock)
    mon = ch.enable_heartbeat(interval_s=5.0, wall=clock, thread=False)
    key = f"{ch._prefix()}/hb/0"
    first = kv.store[key]
    clock.t += 1.0
    mon.beat()
    assert kv.store[key] == first  # within interval: no re-post
    clock.t += 5.0
    mon.beat()
    assert kv.store[key] != first


# -- abort fail-stop ---------------------------------------------------------

def test_posted_abort_unblocks_receiver():
    ch, kv, clock = make_channel()
    ch.post_abort("host 1: deliberate")
    with pytest.raises(RuntimeError, match="aborted by a peer"):
        ch.recv_obj(1)
    ch.clear_abort()
    ch2, _, _ = make_channel(kv=kv, clock=clock, pid=1)
    ch2.send_obj("after-clear", 0)
    assert ch.recv_obj(1) == "after-clear"


# -- key hygiene -------------------------------------------------------------

def _payload_keys(kv):
    return {k for k in kv.store if "/hb/" not in k and not k.endswith("abort")}


def test_p2p_roundtrip_leaves_no_keys():
    ch, kv, clock = make_channel()
    ch2, _, _ = make_channel(kv=kv, clock=clock, pid=1)
    ch2.send_obj(b"x" * 3_000_000, 0)  # multi-chunk (1 MiB chunks)
    assert ch.recv_obj(1) == b"x" * 3_000_000
    assert _payload_keys(kv) == set()


def test_send_failure_cleans_chunks_and_rolls_back_seq():
    ch, kv, clock = make_channel()
    sched = FaultSchedule([dict(op="hc.chunk", nth=2)])  # fail 2nd chunk
    bind_host_channel(ch, sched, sleep=clock.sleep)
    with pytest.raises(InjectedFault):
        ch.send_obj(b"y" * 3_000_000, 1)
    assert _payload_keys(kv) == set()  # no half-written message stranded
    # the sequence slot was rolled back: a retried send matches seq 0
    ch.send_obj("retry", 1)
    ch1, _, _ = make_channel(kv=kv, clock=clock, pid=1)
    assert ch1.recv_obj(0) == "retry"


def test_send_fault_after_publish_keeps_message_and_sequence():
    """The hc.put hook fires after meta — the publish point.  A fault
    there must NOT roll back: the receiver may already be consuming the
    message, so the sender keeps its advanced sequence and the retried
    send occupies the next slot."""
    ch, kv, clock = make_channel()
    sched = FaultSchedule([dict(op="hc.put", nth=1)])
    bind_host_channel(ch, sched, sleep=clock.sleep)
    with pytest.raises(InjectedFault):
        ch.send_obj("published-despite-fault", 1)
    ch.send_obj("second", 1)
    ch1, _, _ = make_channel(kv=kv, clock=clock, pid=1)
    assert ch1.recv_obj(0) == "published-despite-fault"
    assert ch1.recv_obj(0) == "second"


def test_allgather_failure_cleans_own_keys_in_finally():
    ch, kv, clock = make_channel(timeout_ms=500, max_retries=0, nprocs=2)
    # peer never contributes: the read of rank 1's slot times out
    with pytest.raises(ChannelTimeoutError):
        ch.allgather({"mine": 1})
    assert _payload_keys(kv) == set(), \
        "failed allgather stranded keys that would poison the next epoch"


def test_allgather_torn_multichunk_put_cleans_written_chunks():
    """A put that dies mid-chunk never wrote the meta key — cleanup must
    still reach the chunks already in the store (chunk count from the
    payload, not probed from the absent meta)."""
    ch, kv, clock = make_channel(nprocs=1)
    sched = FaultSchedule([dict(op="hc.chunk", nth=2)])
    bind_host_channel(ch, sched, sleep=clock.sleep)
    with pytest.raises(InjectedFault):
        ch.allgather(b"z" * 3_000_000)  # 3 chunks; dies on the 2nd
    assert _payload_keys(kv) == set(), \
        "torn allgather contribution stranded chunk keys"


def test_bcast_root_failure_cleans_value_key():
    clock = FakeClock()
    kv = FakeKV(clock, barrier_parties=2)  # done-barrier cannot complete
    ch, _, _ = make_channel(clock=clock, kv=kv, timeout_ms=500,
                            max_retries=0)
    with pytest.raises(ChannelTimeoutError):
        ch.bcast({"payload": 9}, root=0)
    assert _payload_keys(kv) == set()


def test_single_party_allgather_and_bcast_round_trip():
    ch, kv, clock = make_channel(nprocs=1)
    assert ch.allgather({"me": 0}) == [{"me": 0}]
    assert ch.bcast("b") == "b"
    ch.barrier()
    assert _payload_keys(kv) == set()


# -- generation rotation -----------------------------------------------------

def test_bump_generation_isolates_stranded_keys():
    ch, kv, clock = make_channel()
    ch1, _, _ = make_channel(kv=kv, clock=clock, pid=1)
    # strand a message in generation 0 (sent, never received)
    ch1.send_obj("stale-from-g0", 0)
    assert _payload_keys(kv) != set()
    g = ch.bump_generation()
    assert g == 1 and ch.generation == 1
    ch1.bump_generation()  # lock-step
    # new-generation traffic cannot match the stranded g0 key
    ch1.send_obj("fresh-g1", 0)
    assert ch.recv_obj(1) == "fresh-g1"
    # sequence counters re-armed: send/recv restarted at s0 in g1
    assert any("/g1/" in k or k.startswith("t/g1") for k in kv.store) \
        or True  # consumed already; the assert above is the behavior pin


def test_lost_chunk_fault_times_out_then_recovers_next_generation():
    ch, kv, clock = make_channel(timeout_ms=400, max_retries=1)
    ch1, _, _ = make_channel(kv=kv, clock=clock, pid=1)
    sched = FaultSchedule([dict(op="hc.put", nth=1, action="lost_chunk")])
    bind_host_channel(ch1, sched, sleep=clock.sleep)
    ch1.send_obj("doomed", 0)  # chunk c0 deleted after the put
    with pytest.raises(ChannelTimeoutError):
        ch.recv_obj(1)
    # recovery: both sides rotate generation; traffic flows again
    ch.bump_generation()
    ch1.bump_generation()
    ch1.send_obj("healthy", 0)
    assert ch.recv_obj(1) == "healthy"


def test_stale_key_fault_surfaces_as_timeout_not_hang():
    ch, kv, clock = make_channel(timeout_ms=400, max_retries=1)
    ch1, _, _ = make_channel(kv=kv, clock=clock, pid=1)
    sched = FaultSchedule([dict(op="hc.put", nth=1, action="stale_key")])
    bind_host_channel(ch1, sched, sleep=clock.sleep)
    ch1.send_obj("corrupted-meta", 0)
    with pytest.raises(ChannelTimeoutError):
        ch.recv_obj(1)  # meta says "stale:0" → malformed read, retried, typed


def test_stats_counters():
    ch, kv, clock = make_channel(timeout_ms=300, max_retries=1)
    with pytest.raises(ChannelTimeoutError):
        ch.recv_obj(1)
    assert ch.stats["timeouts"] == 1
    ch2, _, _ = make_channel(kv=kv, clock=clock, pid=1)
    ch2.send_obj(1, 0)
    ch.recv_obj(1)
    assert ch.stats["cleaned_keys"] >= 1
