"""Checkpoint integrity: atomic+checksummed writes, corrupt-snapshot
exclusion from the consensus vote, and GC protection of the generation a
consensus resume restored from."""

import os

import pytest

import chainermn_tpu as ct
from chainermn_tpu import F, L
from chainermn_tpu.core.optimizer import SGD
from chainermn_tpu.dataset import SerialIterator, get_mnist
from chainermn_tpu.training import StandardUpdater, Trainer

pytestmark = pytest.mark.chaos


class _MLP(ct.Chain):
    def __init__(self):
        super().__init__()
        with self.init_scope():
            self.l1 = L.Linear(784, 8, seed=3)
            self.l2 = L.Linear(8, 10, seed=4)

    def forward(self, x, t):
        return F.softmax_cross_entropy(self.l2(F.relu(self.l1(x))), t)


def _make_trainer(out, iters=12):
    model = _MLP()
    comm = ct.create_communicator("jax_ici")
    opt = ct.create_multi_node_optimizer(SGD(lr=0.05), comm).setup(model)
    train, _ = get_mnist(n_train=64, n_test=8)
    it = SerialIterator(train, 8 * comm.size, shuffle=False)
    return model, comm, Trainer(StandardUpdater(it, opt),
                                (iters, "iteration"), out=out)


def _run_with_checkpoints(out, iters=12, trigger=(3, "iteration"), **kw):
    model, comm, trainer = _make_trainer(out, iters)
    cp = ct.create_multi_node_checkpointer(comm, name="c", **kw)
    trainer.extend(cp, trigger=trigger)
    trainer.run()
    return model, comm, cp


def test_snapshots_carry_verifying_sidecars(tmp_path):
    out = str(tmp_path / "run")
    _, _, cp = _run_with_checkpoints(out)
    files = [f for f in os.listdir(out) if f.startswith("c.")
             and not f.endswith(".sum")]
    assert files
    for f in files:
        assert os.path.exists(os.path.join(out, f + ".sum"))
        assert cp._verify(os.path.join(out, f))
    assert cp.stats["verify_failures"] == 0


def test_corrupt_snapshot_excluded_from_consensus(tmp_path):
    out = str(tmp_path / "run")
    _, _, _ = _run_with_checkpoints(out)  # snapshots at 3/6/9/12
    # corrupt the NEWEST snapshot (flip bytes, keep length and sidecar)
    newest = os.path.join(out, "c.12.0")
    with open(newest, "r+b") as f:
        f.seek(10)
        f.write(b"\xde\xad\xbe\xef")
    model2, comm2, trainer2 = _make_trainer(out)
    cp2 = ct.create_multi_node_checkpointer(comm2, name="c")
    resumed = cp2.maybe_load(trainer2)
    # the torn snapshot lost the vote: consensus fell back to 9
    assert resumed == 9
    assert trainer2.updater.iteration == 9
    assert cp2.stats["verify_failures"] == 1


def test_all_generations_corrupt_returns_none(tmp_path):
    out = str(tmp_path / "run")
    _run_with_checkpoints(out)
    for f in os.listdir(out):
        if f.startswith("c.") and not f.endswith(".sum"):
            with open(os.path.join(out, f), "r+b") as fh:
                fh.seek(4)
                fh.write(b"\x00\x00\x00\x00")
    model2, comm2, trainer2 = _make_trainer(out)
    cp2 = ct.create_multi_node_checkpointer(comm2, name="c")
    assert cp2.maybe_load(trainer2) is None
    assert trainer2.updater.iteration == 0


def test_sidecarless_legacy_snapshot_still_admitted(tmp_path):
    out = str(tmp_path / "run")
    _run_with_checkpoints(out)
    for f in os.listdir(out):
        if f.endswith(".sum"):
            os.remove(os.path.join(out, f))
    model2, comm2, trainer2 = _make_trainer(out)
    cp2 = ct.create_multi_node_checkpointer(comm2, name="c")
    assert cp2.maybe_load(trainer2) == 12  # pre-integrity-pass files load


def test_gc_protects_consensus_resumed_generation(tmp_path):
    out = str(tmp_path / "run")
    # small retention so GC is aggressive: keep 2, collect every 2
    _run_with_checkpoints(out, iters=6, trigger=(3, "iteration"),
                          cp_interval=2, gc_interval=2)
    model2, comm2, trainer2 = _make_trainer(out, iters=18)
    cp2 = ct.create_multi_node_checkpointer(comm2, name="c",
                                            cp_interval=2, gc_interval=2)
    resumed = cp2.maybe_load(trainer2)
    assert resumed == 6
    assert cp2._protected_iteration == 6
    trainer2.extend(cp2, trigger=(3, "iteration"))
    trainer2.run()  # saves 9/12/15/18 → GC pressure well past the budget
    files = [f for f in os.listdir(out) if f.startswith("c.")
             and not f.endswith(".sum")]
    # newest cp_interval generations kept AND the consensus generation
    # survived every sweep
    assert "c.6.0" in files, \
        "GC must never delete the generation consensus resumed from"
    assert "c.18.0" in files and "c.15.0" in files
    # everything else was collected
    assert len(files) == 3


def test_resave_after_rollback_keeps_one_entry_per_generation(tmp_path):
    """Re-crossing a saved iteration after a consensus rollback must not
    duplicate the retention entry (a duplicate would make _gc's
    keep/stale split delete a file the keep list still holds)."""
    out = str(tmp_path / "run")
    model, comm, trainer = _make_trainer(out, iters=3)
    cp = ct.create_multi_node_checkpointer(comm, name="c", cp_interval=2,
                                           gc_interval=2)
    cp.save(trainer, 3)
    cp.save(trainer, 3)  # same generation re-saved (post-rollback path)
    assert cp._files.count("c.3.0") == 1
    cp.save(trainer, 6)
    cp.save(trainer, 9)
    cp.save(trainer, 12)  # triggers GC (4 entries ≥ cp+gc)
    assert os.path.exists(os.path.join(out, "c.9.0"))
    assert os.path.exists(os.path.join(out, "c.12.0"))


def test_write_fault_leaves_no_visible_snapshot(tmp_path):
    out = str(tmp_path / "run")
    model, comm, trainer = _make_trainer(out, iters=3)
    cp = ct.create_multi_node_checkpointer(comm, name="c")

    def boom(tmp, fname):
        raise OSError("disk gone mid-write")

    cp._write_fault_hook = boom
    with pytest.raises(OSError):
        cp.save(trainer, 3)
    leftovers = [f for f in os.listdir(out)] if os.path.isdir(out) else []
    assert [f for f in leftovers if f.startswith("c.3")] == [], \
        f"torn write left visible artifacts: {leftovers}"
