"""FaultInjectionCommunicator: schedule-driven drop/delay/raise at the
CommunicatorBase surface, transparent delegation otherwise."""

import os

import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu.communicators import (FaultInjectionCommunicator,
                                         FaultSchedule, InjectedFault)

pytestmark = pytest.mark.chaos

# `make chaos` rotates this (echoed in its output); tier-1 uses the fixed
# default — the assertions below hold for ANY seed
CHAOS_SEED = int(os.environ.get("CHAINERMN_TPU_CHAOS_SEED", "1234"))


def _wrap(specs, seed=0, base=None, sleep=None):
    sched = FaultSchedule(specs, seed=seed)
    kwargs = {} if sleep is None else {"sleep": sleep}
    return FaultInjectionCommunicator(base or ct.DummyCommunicator(),
                                      sched, **kwargs), sched


def test_raise_on_nth_collective():
    comm, sched = _wrap([dict(op="allreduce", nth=2)])
    np.testing.assert_array_equal(np.asarray(comm.allreduce(np.ones(3))),
                                  np.ones(3))
    with pytest.raises(InjectedFault):
        comm.allreduce(np.ones(3))
    # one-shot: the third call goes through
    np.testing.assert_array_equal(np.asarray(comm.allreduce(np.ones(3))),
                                  np.ones(3))
    assert comm.injected == 1
    assert sched.fired == [("allreduce", 2, "raise")]


def test_drop_on_send_obj_loses_message():
    comm, _ = _wrap([dict(op="send_obj", nth=1, action="drop")])
    comm.send_obj({"lost": True}, dest=0)
    comm.send_obj({"kept": True}, dest=0)
    # only the second send ever reached the base communicator's mailbox
    assert comm.recv_obj(source=0) == {"kept": True}


def test_drop_on_collective_returns_input_unchanged():
    comm, _ = _wrap([dict(op="allreduce", nth=1, action="drop")])
    x = np.arange(4.0)
    out = comm.allreduce(x)
    assert out is x  # silently-no-op collective


def test_drop_on_kwargs_invoked_collective_returns_input():
    comm, _ = _wrap([dict(op="bcast_obj", nth=1, action="drop")])
    payload = {"iteration": 7}
    assert comm.bcast_obj(obj=payload) is payload  # keyword call


def test_drop_without_silent_result_degrades_to_raise():
    comm, _ = _wrap([dict(op="allgather_obj", nth=1, action="drop"),
                     dict(op="scatter", nth=1, action="drop")])
    with pytest.raises(InjectedFault):
        comm.allgather_obj("x")
    with pytest.raises(InjectedFault):
        comm.scatter([1, 2, 3])


def test_preempt_action_raises_rank_preempted():
    """The elastic chaos action (ISSUE 10): a ``preempt`` spec surfaces
    as RankPreempted at the API surface — and wrapping BINDS the base
    communicator's rank, so a shared rank-targeted schedule fires only
    on its target."""
    from chainermn_tpu.communicators import RankPreempted
    # DummyCommunicator.rank == 0: a rank-0-targeted spec fires here...
    comm, sched = _wrap([dict(op="allreduce", nth=1, action="preempt",
                              rank=0)])
    assert sched.rank == 0  # bound at wrap time
    with pytest.raises(RankPreempted) as e:
        comm.allreduce(np.ones(2))
    assert e.value.rank == 0
    # ...and a rank-1-targeted one never does
    comm1, _ = _wrap([dict(op="allreduce", nth=1, action="preempt",
                           rank=1)])
    np.testing.assert_array_equal(
        np.asarray(comm1.allreduce(np.ones(2))), np.ones(2))


def test_preempt_not_absorbed_by_host_channel_retry():
    """An injected hc-level preempt is NON-transient: the channel's
    bounded-retry loop re-raises it immediately instead of burning the
    backoff budget on a host that is gone."""
    from chainermn_tpu.communicators import RankPreempted
    from chainermn_tpu.communicators._host_channel import HostChannel

    class _Clock:
        t = 0.0

        def __call__(self):
            return self.t

        def sleep(self, s):
            self.t += s

    clock = _Clock()
    ch = HostChannel(namespace="t", client=object(), clock=clock,
                     sleep=clock.sleep, process_id=0, num_processes=2,
                     timeout_ms=1000)
    calls = []

    def fn(remaining_ms):
        calls.append(remaining_ms)
        raise RankPreempted("hc.get", 1, rank=0)

    with pytest.raises(RankPreempted):
        ch._retrying("p2p", "k", fn)
    assert len(calls) == 1  # no retry, no backoff


def test_delay_uses_injected_sleep_then_executes():
    slept = []
    comm, _ = _wrap([dict(op="bcast_obj", nth=2, action="delay",
                          delay_s=7.5)], sleep=slept.append)
    assert comm.bcast_obj("a") == "a"
    assert comm.bcast_obj("b") == "b"  # delayed but not dropped
    assert slept == [7.5]


def test_topology_and_delegation_transparent():
    base = ct.DummyCommunicator()
    comm, _ = _wrap([], base=base)
    assert (comm.rank, comm.size) == (base.rank, base.size)
    assert (comm.intra_rank, comm.intra_size) == (0, 1)
    assert (comm.inter_rank, comm.inter_size) == (0, 1)
    assert comm.split(0, 0) is base  # Dummy.split returns self
    # non-intercepted attribute resolves through __getattr__
    assert comm.name == "dummy"
    assert comm.grad_transform()({"g": 1.0}) == {"g": 1.0}


def test_shared_schedule_same_sites_across_ranks():
    """The lock-step contract: two ranks driving identical op sequences
    against schedules built from the same spec+seed inject at identical
    call sites — the property that lets all ranks fail (and recover)
    together."""
    specs = [dict(op="allgather_obj", prob=0.25, count=None)]
    ops = ["allgather_obj"] * 50 + ["bcast_obj"] * 10

    def run(seed):
        comm, sched = _wrap(specs, seed=seed)
        for op in ops:
            try:
                getattr(comm, op)("payload")
            except InjectedFault:
                pass
        return list(sched.fired)

    assert run(CHAOS_SEED) == run(CHAOS_SEED)
    assert run(CHAOS_SEED) != run(CHAOS_SEED + 1)


def test_mesh_base_eager_collectives_still_work():
    base = ct.create_communicator("jax_ici")
    comm, _ = _wrap([dict(op="allreduce", nth=3)], base=base)
    stacked = np.tile(np.arange(4.0), (base.size, 1))
    out = np.asarray(comm.allreduce(stacked, op="mean"))
    np.testing.assert_allclose(out, np.arange(4.0))
    gathered = comm.allgather_obj("x")
    assert gathered == ["x"] * base.size


def test_finalize_unbinds_only_own_host_channel_hook():
    from chainermn_tpu.communicators import bind_host_channel

    class StubChannel:
        _fault_hook = None

        def set_fault_hook(self, hook):
            self._fault_hook = hook

    class StubBase(ct.DummyCommunicator):
        def __init__(self, ch):
            super().__init__()
            self._ch = ch

        def _host_channel(self):
            return self._ch

    ch = StubChannel()
    sched = FaultSchedule([], seed=0)
    bind_host_channel(ch, sched)
    comm = FaultInjectionCommunicator(StubBase(ch), sched)
    assert ch._fault_hook is not None
    comm.finalize()
    assert ch._fault_hook is None, \
        "faults must not outlive the fault communicator"
    # another owner's hook is left alone
    def other_hook(event, ctx):
        pass
    ch.set_fault_hook(other_hook)
    comm.finalize()
    assert ch._fault_hook is other_hook


def test_factory_fault_name(monkeypatch):
    import json
    monkeypatch.setenv(
        "CHAINERMN_TPU_FAULT_SCHEDULE",
        json.dumps({"seed": 3, "faults": [{"op": "allreduce", "nth": 1}]}))
    comm = ct.create_communicator("fault")
    assert isinstance(comm, FaultInjectionCommunicator)
    with pytest.raises(InjectedFault):
        comm.allreduce(np.ones((comm.size, 2)))
    monkeypatch.delenv("CHAINERMN_TPU_FAULT_SCHEDULE")
    with pytest.raises(ValueError):
        ct.create_communicator("fault")
