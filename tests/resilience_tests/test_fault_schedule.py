"""Fault-schedule determinism: same specs + seed + call sequence →
identical injected call sites (the replay property the whole chaos
harness rests on).  Tier-1, no communicator required."""

import os

import pytest

from chainermn_tpu.communicators.fault_schedule import (
    FaultSchedule, FaultSpec, InjectedFault, schedule_from_env)

# `make chaos` rotates this seed (echoed in its output for repro); the
# deterministic tier-1 subset uses the fixed default
CHAOS_SEED = int(os.environ.get("CHAINERMN_TPU_CHAOS_SEED", "1234"))


def _drive(schedule, ops):
    """Run an op-call sequence, recording what fired."""
    for op in ops:
        schedule.on_call(op)
    return list(schedule.fired)


pytestmark = pytest.mark.chaos


def test_nth_spec_fires_on_exact_call():
    s = FaultSchedule([dict(op="allreduce", nth=3)])
    assert s.on_call("allreduce") is None
    assert s.on_call("allreduce") is None
    fault = s.on_call("allreduce")
    assert fault is not None and fault.action == "raise"
    with pytest.raises(InjectedFault) as ei:
        raise fault.make_exception()
    assert ei.value.op == "allreduce" and ei.value.call_index == 3
    # count=1 default: armed once, never again
    assert s.on_call("allreduce") is None


def test_ops_counted_independently():
    s = FaultSchedule([dict(op="bcast_obj", nth=2)])
    assert s.on_call("allreduce") is None
    assert s.on_call("bcast_obj") is None
    assert s.on_call("allreduce") is None
    assert s.on_call("bcast_obj").action == "raise"
    assert s.calls("allreduce") == 2 and s.calls("bcast_obj") == 2


def test_wildcard_and_count_budget():
    s = FaultSchedule([dict(op="*", nth=None, prob=1.0, count=2)])
    fired = _drive(s, ["a", "b", "c", "d"])
    assert [(op, i) for op, i, _ in fired] == [("a", 1), ("b", 1)]


def test_unbounded_count():
    s = FaultSchedule([dict(op="x", prob=1.0, count=None)])
    assert len(_drive(s, ["x"] * 5)) == 5


def test_deterministic_replay_fixed_seed():
    ops = (["allreduce", "bcast_obj", "barrier"] * 40)
    specs = [dict(op="allreduce", prob=0.2, count=None),
             dict(op="barrier", prob=0.1, count=None, action="delay",
                  delay_s=0.5)]
    a = _drive(FaultSchedule(specs, seed=CHAOS_SEED), ops)
    b = _drive(FaultSchedule(specs, seed=CHAOS_SEED), ops)
    assert a == b, "same schedule+seed+call sequence must replay exactly"
    assert a, "prob=0.2 over 40 calls should fire at least once"


def test_reset_rearms_exactly():
    ops = ["op"] * 30
    s = FaultSchedule([dict(op="op", prob=0.3, count=3)], seed=CHAOS_SEED)
    first = _drive(s, ops)
    s.reset()
    assert _drive(s, ops) == first


def test_different_seeds_diverge():
    ops = ["op"] * 200
    a = _drive(FaultSchedule([dict(op="op", prob=0.5, count=None)], seed=1),
               ops)
    b = _drive(FaultSchedule([dict(op="op", prob=0.5, count=None)], seed=2),
               ops)
    assert a != b


def test_exhausted_prob_spec_still_consumes_draws():
    """Spec exhaustion must not shift later specs' injection sites: a
    schedule where spec A burns out early fires spec B at the same call
    sites as a schedule that never had spec A's budget limit reached."""
    ops = ["op"] * 100
    both = FaultSchedule([dict(op="op", prob=0.99, count=2),
                          dict(op="op", prob=0.05, count=None)],
                         seed=CHAOS_SEED)
    fired = _drive(both, ops)
    # replay identically — the draw accounting is part of the replay law
    again = FaultSchedule(both.to_dict()["faults"], seed=CHAOS_SEED)
    assert _drive(again, ops) == fired


def test_json_env_round_trip(monkeypatch, tmp_path):
    s = FaultSchedule([FaultSpec(op="allreduce", nth=5, action="delay",
                                 delay_s=1.5, count=2, note="straggler")],
                      seed=77)
    import json
    text = json.dumps(s.to_dict())
    monkeypatch.setenv("CHAINERMN_TPU_FAULT_SCHEDULE", text)
    env_s = schedule_from_env()
    assert env_s.seed == 77
    assert env_s.specs[0].to_dict() == s.specs[0].to_dict()
    # @file form
    p = tmp_path / "sched.json"
    p.write_text(text)
    monkeypatch.setenv("CHAINERMN_TPU_FAULT_SCHEDULE", f"@{p}")
    assert schedule_from_env().to_dict() == s.to_dict()
    monkeypatch.delenv("CHAINERMN_TPU_FAULT_SCHEDULE")
    assert schedule_from_env() is None


def test_spec_validation():
    with pytest.raises(ValueError):
        FaultSpec(op="x", action="explode", nth=1)
    with pytest.raises(ValueError):
        FaultSpec(op="x")  # neither nth nor prob
    with pytest.raises(ValueError):
        FaultSpec(op="x", nth=2, prob=0.5)  # both
    with pytest.raises(ValueError):
        FaultSpec(op="x", nth=0)  # 1-based


def test_custom_exception_type():
    class MyFault(ConnectionError):
        pass

    s = FaultSchedule([dict(op="op", nth=1, exc=MyFault)])
    fault = s.on_call("op")
    assert isinstance(fault.make_exception(), MyFault)


# -- preempt action + rank targeting (ISSUE 10 satellite) --------------------

def test_preempt_spec_round_trips_new_fields():
    """FaultSpec.to_dict carries the elastic fields — action='preempt'
    and the rank target — through the dict/JSON round trip."""
    spec = FaultSpec(op="bcast_obj", action="preempt", nth=5, rank=1,
                     note="spot reclaim")
    d = spec.to_dict()
    assert d == {"op": "bcast_obj", "action": "preempt", "nth": 5,
                 "rank": 1, "note": "spot reclaim"}
    assert FaultSpec(**d).to_dict() == d
    import json
    s = FaultSchedule([spec], seed=3)
    assert FaultSchedule.from_json(
        json.dumps(s.to_dict())).to_dict() == s.to_dict()


def test_preempt_fires_as_rank_preempted():
    from chainermn_tpu.communicators.fault_schedule import RankPreempted
    s = FaultSchedule([dict(op="allreduce", action="preempt", nth=2,
                            rank=3)], seed=0, rank=3)
    assert s.on_call("allreduce") is None
    fault = s.on_call("allreduce")
    exc = fault.make_exception()
    assert isinstance(exc, RankPreempted)
    assert (exc.op, exc.call_index, exc.rank) == ("allreduce", 2, 3)
    # preempt owns its type: InjectedFault-recoverable supervisors must
    # NOT see it as an in-place-retryable fault
    from chainermn_tpu.communicators.fault_schedule import InjectedFault
    assert not isinstance(exc, InjectedFault)


def test_rank_targeted_spec_fires_only_on_bound_rank():
    spec = dict(op="op", action="preempt", nth=1, rank=1)
    assert FaultSchedule([spec], seed=0).bind_rank(1).on_call("op") \
        is not None
    assert FaultSchedule([spec], seed=0).bind_rank(0).on_call("op") is None
    # unbound schedules never fire rank-restricted specs
    assert FaultSchedule([spec], seed=0).on_call("op") is None


def test_rank_filter_preserves_rng_stream_alignment():
    """A rank-restricted PROBABILISTIC spec consumes its draw on every
    rank (filtering happens after the draw), so a shared schedule's
    other specs fire at identical call sites regardless of binding."""
    specs = [dict(op="op", action="preempt", prob=0.5, rank=1,
                  count=None),
             dict(op="op", prob=0.3, count=None)]
    ops = ["op"] * 40

    def fired_sites(rank):
        s = FaultSchedule(specs, seed=11).bind_rank(rank)
        out = []
        for i, op in enumerate(ops):
            f = s.on_call(op)
            if f is not None:
                out.append((i, f.action))
        return out

    sites0 = fired_sites(0)
    sites1 = fired_sites(1)
    # only rank 1 sees the preempts
    assert not any(a == "preempt" for _, a in sites0)
    preempt1 = {i for i, a in sites1 if a == "preempt"}
    assert preempt1
    # outside the sites where rank 1's preempt won (first match wins),
    # the shared 'raise' spec fires at IDENTICAL indices on both ranks
    # — the draw stream stayed aligned through the rank filtering
    assert [i for i, a in sites0 if a == "raise" and i not in preempt1] \
        == [i for i, a in sites1 if a == "raise"]


def test_rank_validation():
    with pytest.raises(ValueError):
        FaultSpec(op="x", nth=1, rank=-2)


# -- conversion-step targeting (ISSUE 16 satellite) ---------------------------

def test_step_spec_round_trips():
    """FaultSpec.to_dict carries the capacity-protocol step target
    through the dict/JSON round trip."""
    spec = FaultSpec(op="capacity.convert", action="preempt", nth=1,
                     rank=1, step="CONVERTING")
    d = spec.to_dict()
    assert d == {"op": "capacity.convert", "action": "preempt",
                 "nth": 1, "rank": 1, "step": "CONVERTING"}
    assert FaultSpec(**d).to_dict() == d
    import json
    s = FaultSchedule([spec], seed=5)
    assert FaultSchedule.from_json(
        json.dumps(s.to_dict())).to_dict() == s.to_dict()


def test_step_targeted_spec_fires_only_at_named_step():
    spec = dict(op="capacity.convert", action="preempt", prob=1.0,
                step="RETIRING")
    s = FaultSchedule([spec], seed=0)
    assert s.on_call("capacity.convert", step="LEAVE_ANNOUNCED") is None
    assert s.on_call("capacity.convert", step="CONVERTING") is None
    fault = s.on_call("capacity.convert", step="RETIRING")
    assert fault is not None and fault.action == "preempt"
    # count=1 default was only consumed at the MATCHING step
    assert s.on_call("capacity.convert", step="RETIRING") is None
    # a step-free spec still fires at step-passing call sites
    free = FaultSchedule([dict(op="capacity.convert", nth=1)])
    assert free.on_call("capacity.convert", step="SERVING") is not None
    # and a step-restricted spec never fires at a step-less call site
    assert FaultSchedule([spec], seed=0).on_call("capacity.convert") \
        is None


def test_step_filter_preserves_rng_stream_alignment():
    """Step filtering mirrors rank filtering: the draw is consumed on
    every call regardless of the step match, so two ranks executing
    DIFFERENT protocol steps consume identical RNG stream positions —
    the shared schedule's other specs stay call-site-aligned."""
    specs = [dict(op="op", action="preempt", prob=0.5,
                  step="CONVERTING", count=None),
             dict(op="op", prob=0.3, count=None)]

    def fired_sites(step_sequence):
        s = FaultSchedule(specs, seed=11)
        out = []
        for i, step in enumerate(step_sequence):
            f = s.on_call("op", step=step)
            if f is not None:
                out.append((i, f.action))
        return out

    at_step = fired_sites(["CONVERTING"] * 40)
    off_step = fired_sites(["RETIRING"] * 40)
    assert not any(a == "preempt" for _, a in off_step)
    preempts = {i for i, a in at_step if a == "preempt"}
    assert preempts
    # outside the sites the step-targeted preempt won, the shared
    # 'raise' spec fires at IDENTICAL indices on both sequences
    assert [i for i, a in off_step if a == "raise" and i not in preempts] \
        == [i for i, a in at_step if a == "raise"]


def test_step_validation():
    with pytest.raises(ValueError):
        FaultSpec(op="x", nth=1, step="")
    with pytest.raises(ValueError):
        FaultSpec(op="x", nth=1, step=7)
