"""Inject → detect → recover → converge, single process, tier-1.

A trainer with a per-iteration control-plane beacon (``bcast_obj`` — the
same host-channel surface the multi-node iterator uses every batch) is
driven into injected faults; :class:`FailureRecovery` must fire
``on_error``, run the checkpointer's consensus ``maybe_load``, and resume
to the same final state as the fault-free run."""

import os

import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu import F, L
from chainermn_tpu.communicators import (FaultInjectionCommunicator,
                                         FaultSchedule, InjectedFault)
from chainermn_tpu.core.optimizer import SGD
from chainermn_tpu.dataset import SerialIterator, get_mnist
from chainermn_tpu.extensions import FailureRecovery, RecoveryGivingUp
from chainermn_tpu.training import StandardUpdater, Trainer
from chainermn_tpu.training.trainer import Extension

pytestmark = pytest.mark.chaos


class _MLP(ct.Chain):
    def __init__(self):
        super().__init__()
        with self.init_scope():
            self.l1 = L.Linear(784, 16, seed=7)
            self.l2 = L.Linear(16, 10, seed=8)

    def forward(self, x, t):
        return F.softmax_cross_entropy(self.l2(F.relu(self.l1(x))), t)


class _Beacon(Extension):
    """Per-iteration host control-plane op (what the multi-node iterator
    does for every batch): the fault-injection site for these tests."""

    trigger = (1, "iteration")
    priority = 400  # before everything, like batch broadcasting would be

    def __init__(self, comm):
        self.comm = comm
        self.errors = []

    def __call__(self, trainer):
        self.comm.bcast_obj({"iteration": trainer.updater.iteration})

    def on_error(self, trainer, exc, tb):
        self.errors.append(type(exc).__name__)


def _make_trainer(out, schedule=None, iters=12, cp_trigger=(3, "iteration"),
                  max_recoveries=3):
    model = _MLP()
    comm = ct.create_communicator("jax_ici")
    if schedule is not None:
        comm = FaultInjectionCommunicator(comm, schedule)
    opt = ct.create_multi_node_optimizer(SGD(lr=0.05), comm).setup(model)
    train, _ = get_mnist(n_train=64, n_test=8)
    it = SerialIterator(train, 8 * comm.size, shuffle=False)
    trainer = Trainer(StandardUpdater(it, opt), (iters, "iteration"),
                      out=out)
    beacon = _Beacon(comm)
    trainer.extend(beacon)
    cp = ct.create_multi_node_checkpointer(comm, name="rec")
    trainer.extend(cp, trigger=cp_trigger)
    recovery = FailureRecovery(checkpointer=cp, max_recoveries=max_recoveries,
                               verbose=False)
    trainer.extend(recovery)
    return model, trainer, beacon, cp, recovery


def _params(model):
    return [np.asarray(p.array).copy() for p in model.params()]


def test_recovers_from_injected_collective_fault(tmp_path):
    # fault-free golden
    gold_model, gold_trainer, _, _, _ = _make_trainer(
        str(tmp_path / "gold"))
    gold_trainer.run()
    assert gold_trainer.updater.iteration == 12

    # beacon's bcast_obj #8 raises on the faulted run
    sched = FaultSchedule([dict(op="bcast_obj", nth=8)], seed=5)
    model, trainer, beacon, cp, recovery = _make_trainer(
        str(tmp_path / "run"), schedule=sched)
    trainer.run()

    assert recovery.stats["recoveries"] == 1
    assert beacon.errors == ["InjectedFault"], \
        "on_error must fire on extensions before recovery"
    # consensus resume rolled back to the newest snapshot: beacon call #8
    # faults right after update 8, when snapshots 3 and 6 exist
    assert recovery.stats["resumed_iterations"] == [6]
    assert trainer.updater.iteration == 12

    # converged to the fault-free trajectory (deterministic data order +
    # snapshot-exact resume ⇒ identical final params)
    for a, b in zip(_params(gold_model), _params(model)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_recovers_from_fault_during_checkpoint_write(tmp_path):
    """A fault mid-checkpoint-write: the torn snapshot never becomes
    visible (atomic tmp+rename), recovery resumes from the previous
    generation, and training still completes."""
    model, trainer, beacon, cp, recovery = _make_trainer(
        str(tmp_path / "run"))
    fired = []

    def write_fault(tmp, fname):
        if fname.startswith("rec.6.") and not fired:
            fired.append(fname)
            raise InjectedFault("checkpoint.save", 1, "torn write")

    cp._write_fault_hook = write_fault
    trainer.run()
    assert fired, "the write fault must have fired"
    assert recovery.stats["recoveries"] == 1
    # resumed from generation 3 — generation 6's write was the fault
    assert recovery.stats["resumed_iterations"] == [3]
    assert trainer.updater.iteration == 12
    out = str(tmp_path / "run")
    # no torn iteration-6 file from the faulted attempt is visible...
    # (the retried save after recovery writes a fresh, verified one)
    files = [f for f in os.listdir(out) if f.startswith("rec.")]
    assert "rec.6.0" in files  # re-written post-recovery
    assert cp._verify(os.path.join(out, "rec.6.0"))


def test_unrecoverable_exception_still_fail_stops(tmp_path):
    sched = FaultSchedule([dict(op="bcast_obj", nth=4, exc=ValueError)],
                          seed=0)
    model, trainer, beacon, cp, recovery = _make_trainer(
        str(tmp_path / "run"), schedule=sched)
    with pytest.raises(ValueError):
        trainer.run(show_loop_exception_msg=False)
    assert recovery.stats["recoveries"] == 0
    assert beacon.errors == ["ValueError"]  # on_error fired on both paths


def test_recovery_budget_exhaustion(tmp_path):
    # beacon calls #5/#6/#7 all fault: budget of 2 recoveries burns out
    # and the third fault fail-stops through RecoveryGivingUp, chaining
    # the original fault (so 'gave up after N' is distinguishable from
    # 'never recoverable' in the crash output)
    sched = FaultSchedule([dict(op="bcast_obj", nth=5),
                           dict(op="bcast_obj", nth=6),
                           dict(op="bcast_obj", nth=7)], seed=0)
    model, trainer, beacon, cp, recovery = _make_trainer(
        str(tmp_path / "run"), schedule=sched, max_recoveries=2)
    with pytest.raises(RecoveryGivingUp) as ei:
        trainer.run(show_loop_exception_msg=False)
    assert isinstance(ei.value.__cause__, InjectedFault)
    assert recovery.stats["recoveries"] == 2


def test_peer_lost_fail_stops_by_default(tmp_path):
    """A dead peer can never answer the consensus allgather: in-place
    recovery must NOT be attempted for PeerLostError unless the
    deployment opts in (unrecoverable=())."""
    from chainermn_tpu.communicators import PeerLostError
    model, trainer, beacon, cp, recovery = _make_trainer(
        str(tmp_path / "run"))
    assert not recovery.can_recover(PeerLostError(1, 12.0))
    assert recovery.can_recover(InjectedFault("bcast_obj", 1))
    opt_in = FailureRecovery(checkpointer=cp, unrecoverable=())
    assert opt_in.can_recover(PeerLostError(1, 12.0))


def test_fault_schedule_rejected_for_non_fault_communicator():
    for name in ("jax_ici", "dummy"):  # incl. the early-return branch
        with pytest.raises(ValueError, match="only honored by the 'fault'"):
            ct.create_communicator(
                name, fault_schedule=FaultSchedule([], seed=0))


def test_recovery_without_checkpointer_restarts_live(tmp_path):
    """No checkpointer: recovery resumes from live in-memory state (no
    rollback), still reaching the stop trigger."""
    sched = FaultSchedule([dict(op="bcast_obj", nth=5)], seed=1)
    model = _MLP()
    comm = FaultInjectionCommunicator(ct.create_communicator("jax_ici"),
                                      sched)
    opt = ct.create_multi_node_optimizer(SGD(lr=0.05), comm).setup(model)
    train, _ = get_mnist(n_train=64, n_test=8)
    it = SerialIterator(train, 8 * comm.size, shuffle=False)
    trainer = Trainer(StandardUpdater(it, opt), (8, "iteration"),
                      out=str(tmp_path / "run"))
    trainer.extend(_Beacon(comm))
    recovery = FailureRecovery(comm=comm, verbose=False)
    trainer.extend(recovery)
    trainer.run()
    assert recovery.stats["recoveries"] == 1
    assert recovery.stats["resumed_iterations"] == [None]
    assert trainer.updater.iteration == 8
