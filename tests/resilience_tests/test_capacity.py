"""Capacity-transfer protocol (ISSUE 16): the CapacityBroker's
conversion state machine, both role floors, the hysteresis/cooldown
rails, and — the headline — the conversion-journal crash-recovery
matrix: a seeded kill at EVERY state-machine step leaves an orphaned
journal key that survivors detect, type, and roll forward or abort
with no zombie presence in either role group.  Tier-1."""

import threading

import numpy as np
import pytest

from chainermn_tpu import observability
from chainermn_tpu.communicators._membership import ElasticMembership
from chainermn_tpu.communicators.fault_schedule import (FaultSchedule,
                                                        RankPreempted)
from chainermn_tpu.elastic import (CONVERSION_STEPS, CapacityBroker,
                                   CapacityFloorError,
                                   CapacityProtocolError, LocalTrainGroup)
from chainermn_tpu.serving.fleet import ReplicaFleet

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_registry():
    observability.reset_registry()
    yield
    observability.reset_registry()


# -- fakes --------------------------------------------------------------------

class KV:
    """Thread-safe in-memory stand-in for the coordination KV store
    (the real client's narrow surface: try_get raises on missing)."""

    def __init__(self):
        self.store = {}
        self.lock = threading.Lock()

    def key_value_set(self, k, v):
        with self.lock:
            self.store[k] = str(v)

    def key_value_try_get(self, k):
        with self.lock:
            if k not in self.store:
                raise KeyError(k)
            return self.store[k]

    def key_value_delete(self, k):
        with self.lock:
            self.store.pop(k, None)


def _member(kv, rank, role="elastic", world=2, **kw):
    kw.setdefault("settle_s", 0.05)
    kw.setdefault("poll_s", 0.002)
    kw.setdefault("timeout_ms", 4000)
    return ElasticMembership(kv, rank=rank, world=world, role=role, **kw)


class _Scheduler:
    def __init__(self):
        self.q = []

    def pending(self, tenant=None):
        return len(self.q)

    def tenant_depths(self):
        out = {}
        for r in self.q:
            out[r.tenant] = out.get(r.tenant, 0) + 1
        return out

    def requeue_front(self, request, preempted=True):
        self.q.insert(0, request)

    def next_admission(self, arrived_by=None):
        return self.q.pop(0) if self.q else None


class _Allocator:
    num_pages = 8

    def pages_for(self, total):
        return 1

    def free(self, request_id):
        pass


class FakeEngine:
    """The LocalReplica surface without a jit in sight — state is a
    tiny pytree so the fleet's serialize/adopt weight path (and its
    bit-identity) still runs for real."""

    def __init__(self, seed=0):
        rng = np.random.RandomState(seed)
        self.state = {"w": rng.rand(4).astype(np.float32)}
        self.decode_steps = 0
        self.running = []
        self.completed = []
        self.max_context = 64
        self.scheduler = _Scheduler()
        self.allocator = _Allocator()

    def submit(self, request):
        self.scheduler.q.append(request)

    def step(self, now=None):
        self.decode_steps += 1
        return {"admitted": 0, "decoded": 0, "running": 0, "evicted": 0,
                "occupancy": 0.0, "capacity_x": 1.0}


def _weights(fleet, rid):
    return np.asarray(fleet.replicas[rid].engine.state["w"])


def _world(world=3, schedule=None, min_world=1, **kw):
    """One broker over a 3-rank training group and a 1-replica fleet,
    on a synthetic clock (`t[0]`, advanced by hand)."""
    t = [0.0]
    train = LocalTrainGroup(world=world)
    fleet = ReplicaFleet(engine_factory=lambda rid: FakeEngine(seed=0),
                         replicas=1, clock=lambda: t[0])
    broker = CapacityBroker(
        train, fleet, engine_factory=lambda r: FakeEngine(seed=100 + r),
        min_world=min_world, stale_s=0.5, schedule=schedule,
        clock=lambda: t[0], **kw)
    return train, fleet, broker, t


# -- journal over the real membership protocol --------------------------------

def test_journal_round_trip_and_role_shared_visibility():
    """The conversion journal lives OUTSIDE both role groups' key
    prefixes: a training-role member and a fleet-role member sharing
    one KV store read the same entries."""
    kv = KV()
    train = _member(kv, 0, role="elastic")
    fleet = _member(kv, 0, role="fleet")
    assert train.read_conversion(1) is None
    train.journal_conversion("LEAVE_ANNOUNCED", note="queue pressure",
                             rank=1)
    assert train.read_conversion(1) == ("LEAVE_ANNOUNCED", 1,
                                        "queue pressure")
    # the fleet-role member sees the SAME journal
    assert fleet.read_conversion(1) == ("LEAVE_ANNOUNCED", 1,
                                        "queue pressure")
    # the beat advances on every write (the liveness signal)
    train.journal_conversion("CONVERTING", rank=1)
    assert fleet.read_conversion(1) == ("CONVERTING", 2, "")
    assert fleet.scan_conversions() == {1: ("CONVERTING", 2, "")}
    # but role-group keys stay disjoint: no view/intent bleed
    train.announce_leave(note="x")
    assert fleet.scan_conversions() == {1: ("CONVERTING", 2, "")}
    fleet.clear_conversion(1)
    assert train.read_conversion(1) is None
    assert train.scan_conversions() == {}


def test_retract_join_scrubs_intent_without_leave():
    kv = KV()
    m0, m1 = _member(kv, 0), _member(kv, 1)
    m1.announce_join(note="wants in")
    assert m0.pending_joins() == ()   # already in the bootstrap view
    m1.announce_leave(note="gone")
    v = m0.resolve(expect={0})
    assert v.members == (0,)
    m1.announce_join(note="back")
    assert m0.pending_joins(v) == (1,)
    # a survivor scrubs the DEAD rank's intent: no admission ever
    m0.retract_join(rank=1)
    assert m0.pending_joins(v) == ()


# -- the round trip -----------------------------------------------------------

def test_convert_retire_round_trip():
    """training → fleet → training: the donor leaves training, serves
    with the fleet root's weights BIT-IDENTICALLY (the multicast-tree
    sync), retires, and rejoins; the journal is scrubbed and the
    per-role gauges track both world sizes throughout."""
    train, fleet, broker, t = _world()
    reg = observability.registry()
    gauge = reg.gauge("chainermn_tpu_role_world_size")
    assert gauge.value(role="elastic") == 3
    assert gauge.value(role="fleet") == 1

    rank = broker.convert_to_serving(now=0.0)
    assert rank == 2                      # default donor: highest rank
    assert rank not in train.current_view()           # left training
    rid = broker.converted[rank]
    assert rid in {r.rid for r in fleet.live_replicas()}
    # adopted weights are byte-equal to the root's (tree sync)
    np.testing.assert_array_equal(_weights(fleet, rid),
                                  _weights(fleet, 0))
    # the journal parks at SERVING for the whole stint
    assert train.read_conversion(rank)[0] == "SERVING"
    assert gauge.value(role="elastic") == 2
    assert gauge.value(role="fleet") == 2

    back = broker.retire_to_training(now=1.0)
    assert back == rank
    assert rank in train.current_view()               # rejoined
    assert rid not in {r.rid for r in fleet.live_replicas()}
    assert train.read_conversion(rank) is None        # journal scrubbed
    assert broker.converted == {}
    assert gauge.value(role="elastic") == 3
    assert gauge.value(role="fleet") == 1
    assert broker.stats["conversions"] == 1
    assert broker.stats["retires"] == 1
    assert broker.stats["role_transfers"] == 2


def test_floors_refuse_typed_with_both_views():
    """Training never below min_world, the fleet never below one live
    replica — violations refuse with CapacityFloorError carrying BOTH
    role views."""
    train, fleet, broker, t = _world(world=2, min_world=2)
    with pytest.raises(CapacityFloorError) as ei:
        broker.convert_to_serving()
    assert ei.value.training_view is not None
    assert ei.value.training_view.members == (0, 1)
    assert ei.value.fleet_view is not None
    assert ei.value.fleet_view.role == "fleet"
    assert broker.stats["floor_refusals"] == 1

    # fleet floor: retire the only live replica → refused
    train2, fleet2, broker2, _ = _world(world=3, min_world=1)
    rank = broker2.convert_to_serving(now=0.0)
    fleet2.preempt(0)       # the original replica dies: converted rank
    #                         is now the fleet's LAST live replica
    with pytest.raises(CapacityFloorError) as ei:
        broker2.retire_to_training(rank, now=1.0)
    assert ei.value.fleet_view is not None
    # the refusal moved nothing: the rank is still serving, the
    # journal still parked at SERVING
    assert rank not in train2.current_view()
    assert train2.read_conversion(rank)[0] == "SERVING"
    assert broker2.converted[rank] in {r.rid
                                       for r in fleet2.live_replicas()}


def test_state_machine_rejects_illegal_transitions():
    train, fleet, broker, t = _world()
    with pytest.raises(CapacityProtocolError):
        broker._journal(2, "CONVERTING")       # skips LEAVE_ANNOUNCED
    broker._journal(2, "LEAVE_ANNOUNCED")
    with pytest.raises(CapacityProtocolError):
        broker._journal(2, "SERVING")          # skips CONVERTING
    with pytest.raises(CapacityProtocolError):
        broker._journal(2, "LEAVE_ANNOUNCED")  # rewind
    broker._journal(2, "CONVERTING")
    broker._journal(2, "SERVING")
    broker._journal(2, "RETIRING")
    broker._journal(2, "REJOINING")
    train.clear_conversion(2)


# -- auto-apply + hysteresis --------------------------------------------------

def test_apply_executes_decisions_with_cooldowns():
    train, fleet, broker, t = _world(convert_cooldown_s=5.0,
                                     retire_cooldown_s=5.0)
    assert broker.apply(0, now=0.0) is None
    assert broker.apply(1, now=0.0) == ("convert", 2)
    # cooldown: a second +1 inside the window moves nothing
    assert broker.apply(1, now=2.0) is None
    assert broker.apply(1, now=6.0) == ("convert", 1)
    # training floor (min_world=1): a third +1 refuses quietly
    assert broker.apply(1, now=20.0) is None
    assert broker.stats["floor_refusals"] == 1
    # drain: retires come back LIFO, with their own cooldown
    assert broker.apply(-1, now=20.0) == ("retire", 1)
    assert broker.apply(-1, now=21.0) is None
    assert broker.apply(-1, now=30.0) == ("retire", 2)
    # nothing of ours left: -1 with no converted rank moves nothing
    assert broker.apply(-1, now=40.0) is None
    assert train.current_view().members == (0, 1, 2)


def test_apply_false_preserves_surfaced_only_behavior():
    """PR 15's contract under auto_apply=False: decisions are counted,
    nothing moves."""
    train, fleet, broker, t = _world(auto_apply=False)
    assert broker.apply(1, now=0.0) is None
    assert broker.apply(-1, now=1.0) is None
    assert broker.stats["surfaced"] == 2
    assert broker.stats["role_transfers"] == 0
    assert train.current_view().members == (0, 1, 2)
    assert len(fleet.live_replicas()) == 1


# -- the crash-recovery matrix ------------------------------------------------

# step -> (leg, expected orphan action)
_MATRIX = [("LEAVE_ANNOUNCED", "convert", "abort"),
           ("CONVERTING", "convert", "abort"),
           ("SERVING", "convert", "roll-forward"),
           ("RETIRING", "retire", "roll-forward"),
           ("REJOINING", "retire", "abort")]


def _assert_no_zombie(train, fleet, rank, broker):
    """The matrix's invariant: after recovery the dead rank is present
    in NEITHER role group and its journal key is gone."""
    assert rank not in train.current_view().members
    assert rank not in {r.rid for r in fleet.live_replicas()}
    rid = broker.converted.get(rank, rank)
    assert rid not in {r.rid for r in fleet.live_replicas()}
    assert train.read_conversion(rank) is None
    assert rank not in broker.converted


@pytest.mark.parametrize("step,leg,expect", _MATRIX,
                         ids=[s for s, _, _ in _MATRIX])
def test_seeded_kill_at_every_step_recovers(step, leg, expect):
    """A seeded preempt lands exactly at ``step`` (FaultSchedule step
    targeting); the orphaned journal key is detected after stale_s,
    typed, and rolled forward or aborted — no zombie presence in
    either role group, no capacity conjured or leaked."""
    schedule = FaultSchedule([dict(op="capacity.convert",
                                   action="preempt", prob=1.0,
                                   step=step, rank=2)],
                             seed=7).bind_rank(2)
    train, fleet, broker, t = _world(schedule=schedule)

    if leg == "convert":
        with pytest.raises(RankPreempted):
            broker.convert_to_serving(now=0.0)
        killed_rank = 2
    else:
        broker.schedule = None           # the convert leg runs clean
        killed_rank = broker.convert_to_serving(now=0.0)
        broker.schedule = schedule
        with pytest.raises(RankPreempted):
            broker.retire_to_training(killed_rank, now=0.0)

    # the journal records exactly the step the kill landed at
    entry = train.read_conversion(killed_rank)
    assert entry is not None and entry[0] == step

    # a kill at SERVING means the replica itself died too — the
    # fleet's own typed detection sheds it (here: simulated preempt),
    # and the journal roll-forward must not resurrect it
    if step == "SERVING":
        rid = broker.converted.get(killed_rank, killed_rank)
        fleet.preempt(rid, now=0.0)

    # survivor sweep: first sight arms the staleness clock, nothing
    # happens before stale_s
    assert broker.recover_orphans(now=1.0) == ()
    assert train.read_conversion(killed_rank) is not None
    # past stale_s with a frozen beat: the orphan is typed and resolved
    actions = broker.recover_orphans(now=2.0)
    assert actions == ((killed_rank, step, expect),)
    _assert_no_zombie(train, fleet, killed_rank, broker)
    key = "aborted" if expect == "abort" else "rolled_forward"
    assert broker.stats[key] == 1
    # the fleet's original replica survived every scenario (no
    # capacity leaked past the floor)
    assert 0 in {r.rid for r in fleet.live_replicas()}


def test_orphan_sweep_skips_live_conversions():
    """A beat that ADVANCES between sweeps is a live conversion; a
    healthy SERVING stint (rank live in the fleet) is never treated as
    orphaned no matter how stale its parked journal entry is."""
    train, fleet, broker, t = _world()
    rank = broker.convert_to_serving(now=0.0)
    # parked at SERVING, live in the fleet: sweeps never touch it
    assert broker.recover_orphans(now=0.0) == ()
    assert broker.recover_orphans(now=100.0) == ()
    assert train.read_conversion(rank)[0] == "SERVING"
    # an advancing beat re-arms the staleness clock
    train.journal_conversion("RETIRING", rank=rank)   # retire starts…
    assert broker.recover_orphans(now=100.0) == ()    # first sight
    train.journal_conversion("RETIRING", rank=rank,
                             note="still moving")     # beat advances
    assert broker.recover_orphans(now=200.0) == ()    # re-armed
    # only a FROZEN beat past stale_s is an orphan
    assert broker.recover_orphans(now=200.2) == ()
    actions = broker.recover_orphans(now=300.0)
    assert actions == ((rank, "RETIRING", "roll-forward"),)
    _assert_no_zombie(train, fleet, rank, broker)


def test_half_admitted_carcass_is_discarded():
    """A kill between the fleet resolve and the weight sync leaves a
    live=False carcass in the replica map; the CONVERTING abort evicts
    it through the fleet's typed discard (a LIVE replica refuses)."""
    train, fleet, broker, t = _world()
    # simulate the half-join by hand: journal to CONVERTING, then
    # plant a never-went-live replica like a mid-join crash would
    broker._journal(2, "LEAVE_ANNOUNCED")
    train.announce_leave(rank=2)
    broker._journal(2, "CONVERTING")
    from chainermn_tpu.serving.fleet import LocalReplica
    carcass = LocalReplica(2, FakeEngine(seed=9))
    carcass.live = False
    fleet.replicas[2] = carcass
    with pytest.raises(ValueError):
        fleet.discard(0)                 # live replicas refuse discard
    assert broker.recover_orphans(now=0.0) == ()
    actions = broker.recover_orphans(now=1.0)
    assert actions == ((2, "CONVERTING", "abort"),)
    assert 2 not in fleet.replicas
    _assert_no_zombie(train, fleet, 2, broker)


def test_converting_orphan_with_landed_join_rolls_forward():
    """The completes-or-aborts dichotomy's completing half: a kill
    AFTER the join landed but before the SERVING journal write rolls
    the record forward — the replica keeps serving."""
    train, fleet, broker, t = _world()
    rank = broker.convert_to_serving(now=0.0)
    rid = broker.converted[rank]
    # rewind the journal to CONVERTING, as if the SERVING write was
    # the casualty
    train._journal[rank] = ("CONVERTING", 2, "")
    broker.converted.pop(rank)
    assert broker.recover_orphans(now=10.0) == ()
    actions = broker.recover_orphans(now=11.0)
    assert actions == ((rank, "CONVERTING", "roll-forward"),)
    # rolled FORWARD: the journal now says SERVING and the replica is
    # still live — no capacity was thrown away
    assert train.read_conversion(rank)[0] == "SERVING"
    assert rid in {r.rid for r in fleet.live_replicas()}
    assert broker.converted[rank] == rid


def test_conversion_steps_constant_is_ordered():
    assert CONVERSION_STEPS == ("LEAVE_ANNOUNCED", "CONVERTING",
                                "SERVING", "RETIRING", "REJOINING")
