"""global_except_hook hardening: chaining to a previously-installed
excepthook and flushing before the abort exit."""

import sys

import pytest

from chainermn_tpu import global_except_hook

pytestmark = pytest.mark.chaos


@pytest.fixture
def fresh_hook_state(monkeypatch):
    """Run each test with the hook uninstalled and restore sys.excepthook."""
    monkeypatch.setattr(global_except_hook, "_hook_installed", False)
    original = sys.excepthook
    yield
    sys.excepthook = original


def test_chains_previously_installed_hook(fresh_hook_state, monkeypatch):
    seen = []
    exits = []

    def previous_hook(exc_type, exc_value, exc_tb):
        seen.append((exc_type, str(exc_value)))

    monkeypatch.setattr(sys, "excepthook", previous_hook)
    import os
    monkeypatch.setattr(os, "_exit", exits.append)
    global_except_hook.add_hook()
    assert sys.excepthook is not previous_hook
    err = RuntimeError("boom")
    sys.excepthook(RuntimeError, err, None)
    assert seen == [(RuntimeError, "boom")], \
        "previously-installed excepthook must still run"
    assert exits == [1], "abort path must still hard-exit non-zero"


def test_keyboard_interrupt_does_not_hard_exit(fresh_hook_state,
                                              monkeypatch):
    exits = []
    import os
    monkeypatch.setattr(os, "_exit", exits.append)
    global_except_hook.add_hook()
    sys.excepthook(KeyboardInterrupt, KeyboardInterrupt(), None)
    assert exits == []


def test_add_hook_idempotent(fresh_hook_state):
    global_except_hook.add_hook()
    installed = sys.excepthook
    global_except_hook.add_hook()
    assert sys.excepthook is installed
