"""Bitrot guard for tools/tpu_relay_watch.sh's fire-once logic.

The watcher runs unattended and consumes itself on the first accepted
sentinel — a false fire wastes the one recovery shot, a missed fire
loses the chip session.  A PATH-shimmed `python` stands in for the
probe; a stub queue records invocations.  No jax, no device touch.
"""

import os
import stat
import subprocess
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WATCH = os.path.join(ROOT, "tools", "tpu_relay_watch.sh")

TPU_LINE = '{"platform": "axon", "device_kind": "TPU v5 lite", "n": 1}'
CPU_LINE = '{"platform": "cpu", "device_kind": "cpu", "n": 1}'


def _setup(tmp_path, probe_stub):
    shim = tmp_path / "bin"
    shim.mkdir()
    py = shim / "python"
    py.write_text(probe_stub)
    py.chmod(py.stat().st_mode | stat.S_IEXEC)
    queue = tmp_path / "queue.sh"
    queue.write_text("#!/bin/bash\necho fired >> %s\n"
                     % (tmp_path / "queue_calls"))
    queue.chmod(queue.stat().st_mode | stat.S_IEXEC)
    env = dict(os.environ,
               PATH=f"{shim}{os.pathsep}{os.environ['PATH']}",
               WATCH_PROBE=str(tmp_path / "probe.py"),
               WATCH_SENTINEL=str(tmp_path / "sentinel.json"),
               WATCH_ERRFILE=str(tmp_path / "probe.err"),
               WATCH_INTERVAL="1", WATCH_QUEUE=str(queue))
    return env, tmp_path / "queue_calls"


@pytest.mark.slow
def test_fires_queue_once_on_tpu_sentinel(tmp_path):
    env, calls = _setup(tmp_path, f"#!/bin/bash\necho '{TPU_LINE}'\n")
    proc = subprocess.run(["bash", WATCH], env=env, capture_output=True,
                          text=True, timeout=30)
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert "TPU BACK" in proc.stdout
    assert calls.read_text() == "fired\n"  # exactly once


@pytest.mark.slow
def test_cpu_fallback_sentinel_does_not_consume_watcher(tmp_path):
    """A cpu-fallback probe result must NOT fire the one-shot recovery;
    the watcher clears it and keeps probing (here: the second probe
    reports the TPU and fires)."""
    stub = f"""#!/bin/bash
marker={tmp_path}/first_done
if [ ! -e "$marker" ]; then
  touch "$marker"
  echo '{CPU_LINE}'
else
  echo '{TPU_LINE}'
fi
"""
    env, calls = _setup(tmp_path, stub)
    proc = subprocess.run(["bash", WATCH], env=env, capture_output=True,
                          text=True, timeout=30)
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert "cpu-fallback probe" in proc.stdout
    assert "TPU BACK" in proc.stdout
    assert calls.read_text() == "fired\n"


@pytest.mark.slow
def test_failed_queue_propagates_nonzero_exit(tmp_path):
    """A missing/failing recovery script must not let the one-shot
    watcher exit 0 as if the measurement battery had run."""
    env, calls = _setup(tmp_path, f"#!/bin/bash\necho '{TPU_LINE}'\n")
    env["WATCH_QUEUE"] = str(tmp_path / "does_not_exist.sh")
    proc = subprocess.run(["bash", WATCH], env=env, capture_output=True,
                          text=True, timeout=30)
    assert proc.returncode != 0
    assert "RECOVERY QUEUE FAILED" in proc.stdout


@pytest.mark.slow
def test_stale_pre_start_sentinel_is_ignored(tmp_path):
    """A complete TPU sentinel left by a PREVIOUS session must not fire
    the recovery (its mtime predates this watcher's start); the watcher
    keeps probing instead."""
    env, calls = _setup(tmp_path, "#!/bin/bash\n")  # probe writes nothing
    sentinel = tmp_path / "sentinel.json"
    sentinel.write_text(TPU_LINE + "\n")
    old = time.time() - 7200
    os.utime(sentinel, (old, old))
    with pytest.raises(subprocess.TimeoutExpired):
        subprocess.run(["bash", WATCH], env=env, capture_output=True,
                       text=True, timeout=5)
    assert not calls.exists()
