"""Round-14 serving scale-out gates (ISSUE 13).

The three tentpole legs, parity-pinned:

* **copy-on-write prefix sharing** — a prefix-shared request's decode
  trajectory is bit-identical to its unshared solo run, INCLUDING
  across a fork-on-write, and the provider's trajectory is untouched
  by the borrower's fork (the COW correctness fact).  The suffix
  prefill's logits match the one-shot forward at fp32 atol 1e-5.
* **disaggregated prefill/decode** — the disagg-on engine's trajectory
  equals the single-mesh hatch (``CHAINERMN_TPU_SERVE_DISAGG=off``)
  exactly, with ``transferred_page_bytes`` metering the ship.
* **tensor-parallel decode** — tp=2 logits match the single-chip
  decode at fp32 atol 1e-5 (trajectory pinned equal end to end).

Plus the satellites: the never-retrace pin over the new per-slice
bucket grids (joins/leaves/forks/transfers, disagg on AND off) and the
eviction-livelock guard (typed ``EvictionStalledError`` when no victim
would free a page).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chainermn_tpu.core.link import extract_state
from chainermn_tpu.models import TransformerLM
from chainermn_tpu.serving import (BlockAllocator, EvictionStalledError,
                                   PagedKVCache, Request, RequestScheduler,
                                   ServingEngine, copy_page, decode_program,
                                   prefill_program, prefix_prefill_program)

VOCAB = 101


def _model(**kw):
    return TransformerLM(n_vocab=VOCAB, d_model=32, n_heads=2,
                         n_layers=2, max_len=128, seed=0, **kw)


def _oneshot(model, seq):
    return np.asarray(model.logits(jnp.asarray(
        np.asarray(seq, np.int32)[None])))[0]


def _chat_prompts(rng, shared_len=20, tails=(0, 9, 3)):
    """A provider + borrowers sharing a NON-page-aligned system prompt
    (default 20 tokens at S=8: 2 full pages + a 4-slot partial tail).
    The provider's prompt is exactly the system prompt (tail 0), so its
    registered partial tail page sits AT the borrowers' divergence
    point — the borrower path exercises the fork."""
    base = rng.randint(0, VOCAB, shared_len).astype(np.int32)
    return [np.concatenate([base, rng.randint(0, VOCAB, n)
                            .astype(np.int32)]) for n in tails]


def _run_engine(model, prompts, max_new=6, stagger=False, **kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_context", 64)
    kw.setdefault("page_dtype", jnp.float32)
    eng = ServingEngine(model, **kw)
    if stagger:
        # provider first, decoding alone for two steps, THEN the
        # borrowers join — the provider has already written generated
        # tokens into its (shared) partial tail page when the borrower
        # forks it: the hardest COW interleaving
        eng.submit(Request(prompts[0], max_new_tokens=max_new))
        eng.step(now=0.0)
        eng.step(now=0.0)
        for p in prompts[1:]:
            eng.submit(Request(p, max_new_tokens=max_new))
    else:
        for p in prompts:
            eng.submit(Request(p, max_new_tokens=max_new))
    eng.drain(now=0.0)
    toks = {r.request_id: r.tokens for r in eng.completed}
    return eng, [toks[k] for k in sorted(toks)]


def test_shared_trajectory_bit_identical_across_fork():
    """THE acceptance pin: prefix-shared trajectories (provider AND
    borrowers) equal the unshared run token-for-token, across a
    fork-on-write into a page the provider was actively writing."""
    model = _model()
    prompts = _chat_prompts(np.random.RandomState(1))
    e_off, t_off = _run_engine(model, prompts, stagger=True,
                               prefix_cache=False)
    e_on, t_on = _run_engine(model, prompts, stagger=True,
                             prefix_cache=True)
    assert e_off.prefix_hits == 0
    assert e_on.prefix_hits == 2          # both borrowers hit
    assert e_on.forks >= 1                # the partial tail forked
    assert e_on.prefix_tokens_matched > 0
    assert t_on == t_off                  # bit-identical trajectories
    assert e_on.allocator.check()
    assert len(e_on.completed) == 3


def test_page_aligned_share_no_fork_and_capacity_multiplier():
    """A page-aligned system prompt shares without forking (full pages
    are immutable), and the effective-capacity multiplier reflects the
    sharing while the borrowers are live."""
    model = _model()
    rng = np.random.RandomState(2)
    prompts = _chat_prompts(rng, shared_len=16, tails=(6, 7, 8))
    e_off, t_off = _run_engine(model, prompts, stagger=True,
                               prefix_cache=False, max_new=8)

    eng = ServingEngine(model, num_pages=64, page_size=8, max_batch=4,
                        max_context=64, page_dtype=jnp.float32,
                        prefix_cache=True)
    eng.submit(Request(prompts[0], max_new_tokens=8))
    eng.step(now=0.0)
    eng.step(now=0.0)
    for p in prompts[1:]:
        eng.submit(Request(p, max_new_tokens=8))
    eng.step(now=0.0)                     # borrowers admitted, live
    assert eng.prefix_hits == 2 and eng.forks == 0
    assert eng.capacity_multiplier() > 1.0
    assert eng.allocator.check()
    eng.drain(now=0.0)
    toks = {r.request_id: r.tokens for r in eng.completed}
    assert [toks[k] for k in sorted(toks)] == t_off


def test_suffix_prefill_logits_match_oneshot():
    """Program-level parity: share + fork + suffix prefill produce the
    same first-token logits as the one-shot forward (fp32 atol 1e-5),
    and the following decode steps stay on parity too."""
    model = _model()
    state = extract_state(model)
    rng = np.random.RandomState(3)
    base = rng.randint(0, VOCAB, 20).astype(np.int32)
    pa = base                            # provider: partial tail at 20
    pb = np.concatenate([base, rng.randint(0, VOCAB, 9).astype(np.int32)])
    blk = model.blocks[0].attn
    kv = PagedKVCache(2, 64, 8, blk.n_heads, blk.d_head,
                      dtype=jnp.float32)
    alloc = BlockAllocator(64, 8)
    N = 64 // 8

    def bt(sid):
        row = np.zeros(N, dtype=np.int32)
        t = alloc.block_table(sid)
        row[:len(t)] = t
        return jnp.asarray(row)

    # provider: full prefill, then register
    La = len(pa)
    alloc.ensure("a", La + 1)
    toks = np.zeros((1, 32), np.int32)
    toks[0, :La] = pa
    kv.k_pool, kv.v_pool, _ = prefill_program(
        model, state, kv.k_pool, kv.v_pool, jnp.asarray(toks),
        jnp.int32(La), bt("a"))
    alloc.register_prefix("a", tuple(int(t) for t in pa))

    # borrower: match (20 = 2 full + 4 partial), share, fork, suffix
    Lb = len(pb)
    pages, matched, n_full, partial = alloc.match_prefix(
        tuple(int(t) for t in pb), Lb - 1)
    assert matched == 20 and n_full == 2 and partial == 4
    alloc.share("b", pages)
    old, new = alloc.fork("b", n_full)
    assert old != new
    kv.k_pool, kv.v_pool = copy_page(kv.k_pool, kv.v_pool,
                                     jnp.int32(old), jnp.int32(new))
    alloc.ensure("b", Lb + 1)
    Ts = Lb - matched
    stoks = np.zeros((1, 16), np.int32)
    stoks[0, :Ts] = pb[matched:]
    kv.k_pool, kv.v_pool, logits = prefix_prefill_program(
        model, state, kv.k_pool, kv.v_pool, jnp.asarray(stoks),
        jnp.int32(Ts), jnp.int32(matched), bt("b"))
    ref = _oneshot(model, pb)
    np.testing.assert_allclose(np.asarray(logits), ref[Lb - 1],
                               atol=1e-5)

    # decode continues on parity THROUGH the forked page
    full = np.concatenate([pb, rng.randint(0, VOCAB, 4)
                           .astype(np.int32)])
    ref = _oneshot(model, full)
    for n in range(4):
        pos = Lb + n
        alloc.ensure("b", pos + 1)
        kv.k_pool, kv.v_pool, lg, _ = decode_program(
            model, state, kv.k_pool, kv.v_pool,
            jnp.asarray([full[pos]], jnp.int32) * 0 + int(full[pos]),
            jnp.asarray([pos], jnp.int32), bt("b")[None], mode="paged")
        np.testing.assert_allclose(np.asarray(lg)[0], ref[pos],
                                   atol=1e-5, err_msg=f"step {n}")
    assert alloc.check()


def test_warmup_covers_sharing_grid_no_retraces():
    """Satellite 2 (single-mesh half): after warmup, a chat-shaped load
    with hits AND forks triggers zero additional traces of any program
    — prefill, suffix prefill, fork copy, decode."""
    model = _model()
    eng = ServingEngine(model, num_pages=64, page_size=8, max_batch=4,
                        max_context=64, page_dtype=jnp.float32,
                        prefix_cache=True)
    eng.warmup()
    counts = (eng.prefill_traces, eng.prefix_prefill_traces,
              eng.decode_traces, eng.fork_traces)
    assert counts == (len(eng.prefill_buckets),
                      len(eng.prefill_buckets),
                      len(eng.batch_buckets), 1)
    rng = np.random.RandomState(4)
    prompts = _chat_prompts(rng) + _chat_prompts(rng, shared_len=16)
    for i, p in enumerate(prompts):
        eng.submit(Request(p, max_new_tokens=3 + i % 3,
                           arrival_time=float(i)))
    t = 0.0
    while eng.running or eng.scheduler.pending():
        eng.step(now=t)
        t += 1.0
    assert eng.prefix_hits > 0 and eng.forks > 0
    assert (eng.prefill_traces, eng.prefix_prefill_traces,
            eng.decode_traces, eng.fork_traces) == counts


def test_eviction_livelock_guard():
    """Satellite 1: the victim policy accounts only uniquely-owned
    pages (escalating past all-shared youngsters) and raises the typed
    error when NO victim would free anything."""
    sched = RequestScheduler()
    alloc = BlockAllocator(8, 4)
    t = alloc.ensure(0, 8)               # two pages, both shared below
    alloc.share(1, t)

    class R:
        def __init__(self, rid):
            self.request_id = rid
    r0, r1 = R(0), R(1)

    # legacy signature (no allocator): plain youngest
    assert sched.pick_victim([r0, r1]) is r1
    # all-shared: typed livelock error instead of a futile eviction
    with pytest.raises(EvictionStalledError) as ei:
        sched.pick_victim([r0, r1], alloc)
    assert ei.value.n_running == 2
    # escalation: youngest is all-shared, next-youngest owns a unique
    # page -> it is the victim
    alloc.ensure(0, 9)                   # r0 grows a unique page
    assert sched.pick_victim([r0, r1], alloc) is r0
    assert sched.pick_victim([r1, r0], alloc) is r0


def test_eviction_of_provider_keeps_borrower_correct():
    """End-to-end churn: a tiny pool forces eviction while pages are
    shared; trajectories still equal the uncontended (big-pool,
    no-sharing) run — recompute-on-readmit composes with refcounts."""
    model = _model()
    rng = np.random.RandomState(5)
    prompts = _chat_prompts(rng, shared_len=16, tails=(6, 5, 7))
    _, t_ref = _run_engine(model, prompts, max_new=6,
                           prefix_cache=False, num_pages=64)
    e_small, t_small = _run_engine(model, prompts, max_new=6,
                                   prefix_cache=True, num_pages=10)
    assert t_small == t_ref
    assert e_small.allocator.check()


# -- disaggregated prefill/decode -------------------------------------------


def test_disagg_trajectory_equals_single_mesh_hatch(monkeypatch):
    """Tentpole (b): the disagg-on engine's trajectory is identical to
    the single-mesh hatch, the ship is metered, and the env hatch
    CHAINERMN_TPU_SERVE_DISAGG=off forces single-mesh even when the
    constructor asks for the split."""
    model = _model()
    prompts = _chat_prompts(np.random.RandomState(6))
    e_off, t_off = _run_engine(model, prompts, stagger=True, disagg=False)
    e_on, t_on = _run_engine(model, prompts, stagger=True, disagg=True)
    assert e_on.disagg and not e_off.disagg
    assert t_on == t_off
    # only the prefix MISS prefill ships pages; hits run on the decode
    # pool (they must read the shared pages in place)
    assert e_on.transfers >= 1
    assert e_on.transferred_page_bytes > 0
    assert e_off.transferred_page_bytes == 0
    # the env hatch wins over the constructor
    monkeypatch.setenv("CHAINERMN_TPU_SERVE_DISAGG", "off")
    e_hatch, t_hatch = _run_engine(model, prompts, stagger=True,
                                   disagg=True)
    assert not e_hatch.disagg and e_hatch.transferred_page_bytes == 0
    assert t_hatch == t_off
    monkeypatch.setenv("CHAINERMN_TPU_SERVE_DISAGG", "on")
    assert ServingEngine(model, num_pages=16, page_size=8, max_batch=2,
                         max_context=32).disagg


def test_disagg_warmup_covers_transfer_grid_no_retraces():
    """Satellite 2 (disagg half): warmup pre-compiles the per-slice
    bucket grids — prefill on the prefill slice, extract+insert per
    transfer page bucket, suffix prefill + decode on the decode slice —
    and the full load then retraces NOTHING."""
    model = _model()
    eng = ServingEngine(model, num_pages=64, page_size=8, max_batch=4,
                        max_context=64, page_dtype=jnp.float32,
                        prefix_cache=True, disagg=True)
    eng.warmup()
    counts = (eng.prefill_traces, eng.prefix_prefill_traces,
              eng.decode_traces, eng.fork_traces, eng.transfer_traces)
    assert counts == (len(eng.prefill_buckets),
                      len(eng.prefill_buckets),
                      len(eng.batch_buckets), 1,
                      2 * len(eng.transfer_buckets))
    rng = np.random.RandomState(7)
    prompts = _chat_prompts(rng)
    for i, p in enumerate(prompts):
        eng.submit(Request(p, max_new_tokens=4, arrival_time=float(i)))
    t = 0.0
    while eng.running or eng.scheduler.pending():
        eng.step(now=t)
        t += 1.0
    assert eng.transfers >= 1 and eng.prefix_hits > 0
    assert (eng.prefill_traces, eng.prefix_prefill_traces,
            eng.decode_traces, eng.fork_traces,
            eng.transfer_traces) == counts


# -- tensor-parallel decode --------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
def test_tp_decode_matches_single_chip():
    """Tentpole (c): tp=2 head-sharded pools — the engine trajectory
    equals tp=1 end to end, and the decode logits match at fp32 atol
    1e-5 (program-level, sharded vs unsharded pools)."""
    model = _model()
    prompts = _chat_prompts(np.random.RandomState(8))
    e1, t1 = _run_engine(model, prompts, tp=1)
    e2, t2 = _run_engine(model, prompts, tp=2)
    assert e2.tp == 2 and t2 == t1

    # program-level logits parity through the sharded pools
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
    from chainermn_tpu.ops.paged_attention import head_sharding
    state = extract_state(model)
    blk = model.blocks[0].attn
    rng = np.random.RandomState(9)
    kv = PagedKVCache(2, 16, 8, blk.n_heads, blk.d_head,
                      dtype=jnp.float32)
    prompt = rng.randint(0, VOCAB, 11).astype(np.int32)
    toks = np.zeros((1, 16), np.int32)
    toks[0, :11] = prompt
    bt = jnp.asarray(np.arange(16 // 8 * 4, dtype=np.int32)[:8])
    k, v, _ = prefill_program(model, state, kv.k_pool, kv.v_pool,
                              jnp.asarray(toks), jnp.int32(11), bt)
    args = (jnp.asarray([int(prompt[-1])], jnp.int32),
            jnp.asarray([11], jnp.int32), bt[None])
    _, _, lg_ref, _ = decode_program(model, state, k, v, *args,
                                     mode="paged")
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    sh = head_sharding(mesh, 5, 3)
    repl = NamedSharding(mesh, PartitionSpec())
    k_sh, v_sh = jax.device_put(k, sh), jax.device_put(v, sh)
    state_sh = jax.device_put(state, repl)
    _, _, lg_tp, _ = jax.jit(
        lambda s, kk, vv, t, p, b: decode_program(
            model, s, kk, vv, t, p, b, mode="paged", tp_mesh=mesh))(
        state_sh, k_sh, v_sh, *args)
    np.testing.assert_allclose(np.asarray(lg_tp), np.asarray(lg_ref),
                               atol=1e-5)


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
def test_tp_validates_head_divisibility():
    model = _model()   # 2 heads
    with pytest.raises(ValueError):
        ServingEngine(model, num_pages=16, page_size=8, max_batch=2,
                      max_context=32, tp=3)
