"""Decode parity: prefill + N paged decode steps == one-shot forward.

The whole serving engine is only correct if the paged path is
indistinguishable from running the full sequence through the training
forward: prefill writes the prompt's K/V into pages, each decode step
appends one token's K/V and attends through the block table, and the
logits after N steps must equal ``model.logits(prompt + tokens)`` at
position ``prompt+N-1`` — fp32 atol 1e-5 (bf16 pages: the documented
band in docs/serving.md).  Covered here: ragged prompt lengths, a
batched ragged decode, a mid-stream join (continuous batching's
defining event), the ``CHAINERMN_TPU_PAGED_ATTN=dense`` escape hatch
(parity AND trajectory equality), and the engine-level never-retrace
contract.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from chainermn_tpu.core.link import extract_state
from chainermn_tpu.models import TransformerLM
from chainermn_tpu.serving import (BlockAllocator, PagedKVCache, Request,
                                   ServingEngine, decode_program,
                                   prefill_program)

VOCAB = 101


def _model(**kw):
    return TransformerLM(n_vocab=VOCAB, d_model=32, n_heads=2,
                         n_layers=2, max_len=128, seed=0, **kw)


class Harness:
    """Drives the pure prefill/decode programs with hand-held block
    tables — the engine's device semantics without its scheduling, so
    logits are observable at every step."""

    def __init__(self, model, page_size=8, num_pages=64, max_context=64,
                 mode="paged", dtype=jnp.float32):
        self.model = model
        self.state = extract_state(model)
        blk = model.blocks[0].attn
        self.kv = PagedKVCache(len(list(model.blocks)), num_pages,
                               page_size, blk.n_heads, blk.d_head,
                               dtype=dtype)
        self.alloc = BlockAllocator(num_pages, page_size)
        self.N = max_context // page_size
        self.mode = mode

    def _bt(self, sid):
        row = np.zeros(self.N, dtype=np.int32)
        t = self.alloc.block_table(sid)
        row[:len(t)] = t
        return jnp.asarray(row)

    def prefill(self, sid, prompt, bucket=None):
        L0 = len(prompt)
        self.alloc.ensure(sid, L0 + 1)
        Tb = bucket or max(8, 1 << (L0 - 1).bit_length())
        tokens = np.zeros((1, Tb), dtype=np.int32)
        tokens[0, :L0] = prompt
        k, v, logits = prefill_program(
            self.model, self.state, self.kv.k_pool, self.kv.v_pool,
            jnp.asarray(tokens), jnp.int32(L0), self._bt(sid))
        self.kv.k_pool, self.kv.v_pool = k, v
        return np.asarray(logits)

    def decode(self, sids, toks, poss):
        for sid, p in zip(sids, poss):
            self.alloc.ensure(sid, p + 1)
        bts = jnp.stack([self._bt(s) for s in sids])
        k, v, logits, nxt = decode_program(
            self.model, self.state, self.kv.k_pool, self.kv.v_pool,
            jnp.asarray(np.asarray(toks, np.int32)),
            jnp.asarray(np.asarray(poss, np.int32)), bts,
            mode=self.mode)
        self.kv.k_pool, self.kv.v_pool = k, v
        return np.asarray(logits)


def _oneshot(model, seq):
    return np.asarray(model.logits(jnp.asarray(
        np.asarray(seq, np.int32)[None])))[0]


@pytest.mark.parametrize("prompt_len", [5, 8, 13])
def test_prefill_then_n_decode_steps_match_oneshot(prompt_len):
    """fp32 pages: logits after prefill and after every decode step
    equal the one-shot forward at T = prompt + N, atol 1e-5 — across
    ragged (non-bucket-aligned) prompt lengths."""
    model = _model()
    rng = np.random.RandomState(prompt_len)
    full = rng.randint(0, VOCAB, prompt_len + 6).astype(np.int32)
    ref = _oneshot(model, full)
    h = Harness(model)
    logits = h.prefill(0, full[:prompt_len])
    np.testing.assert_allclose(logits, ref[prompt_len - 1], atol=1e-5)
    for n in range(6):
        pos = prompt_len + n
        logits = h.decode([0], [full[pos]], [pos])
        np.testing.assert_allclose(logits[0], ref[pos], atol=1e-5,
                                   err_msg=f"decode step {n}")


def test_batched_ragged_decode_matches_each_oneshot():
    """Two sequences of different lengths share one pool and one decode
    batch; each lane's logits match its own one-shot forward."""
    model = _model()
    rng = np.random.RandomState(0)
    full_a = rng.randint(0, VOCAB, 7 + 4).astype(np.int32)
    full_b = rng.randint(0, VOCAB, 12 + 4).astype(np.int32)
    ref_a, ref_b = _oneshot(model, full_a), _oneshot(model, full_b)
    h = Harness(model)
    la = h.prefill(0, full_a[:7])
    lb = h.prefill(1, full_b[:12])
    np.testing.assert_allclose(la, ref_a[6], atol=1e-5)
    np.testing.assert_allclose(lb, ref_b[11], atol=1e-5)
    for n in range(4):
        pa, pb = 7 + n, 12 + n
        logits = h.decode([0, 1], [full_a[pa], full_b[pb]], [pa, pb])
        np.testing.assert_allclose(logits[0], ref_a[pa], atol=1e-5)
        np.testing.assert_allclose(logits[1], ref_b[pb], atol=1e-5)


def test_mid_stream_join_preserves_running_sequence():
    """Continuous batching's defining event: B joins while A is mid-
    decode.  A's logits must be bit-identical to an A-alone run (the
    join touches disjoint pages), and B matches its one-shot."""
    model = _model()
    rng = np.random.RandomState(1)
    full_a = rng.randint(0, VOCAB, 6 + 6).astype(np.int32)
    full_b = rng.randint(0, VOCAB, 9 + 3).astype(np.int32)
    ref_b = _oneshot(model, full_b)

    # A alone, all six steps — the control trajectory
    h_solo = Harness(model)
    h_solo.prefill(0, full_a[:6])
    solo = [h_solo.decode([0], [full_a[6 + n]], [6 + n])[0]
            for n in range(6)]

    # A three steps, then B joins, then three more batched steps
    h = Harness(model)
    h.prefill(0, full_a[:6])
    joined = [h.decode([0], [full_a[6 + n]], [6 + n])[0]
              for n in range(3)]
    lb = h.prefill(1, full_b[:9])          # the join
    np.testing.assert_allclose(lb, ref_b[8], atol=1e-5)
    for n in range(3):
        pa, pb = 9 + n, 9 + n
        logits = h.decode([0, 1], [full_a[pa], full_b[pb]], [pa, pb])
        joined.append(logits[0])
        np.testing.assert_allclose(logits[1], ref_b[pb], atol=1e-5)
    for n, (s, j) in enumerate(zip(solo, joined)):
        if n < 3:
            # same compiled program (A alone) on both sides: bitwise
            np.testing.assert_array_equal(
                s, j, err_msg=f"A's step {n} disturbed by B's join")
        else:
            # after the join A rides the 2-lane bucket: a DIFFERENT
            # compiled program, whose codegen XLA does not promise is
            # bitwise-equal to the 1-lane program's (the tier-1 O0
            # backend makes the ulp-level divergence visible).  The
            # product contract is per-lane isolation — fp32-rounding
            # logits and the identical greedy token.
            np.testing.assert_allclose(
                s, j, atol=1e-5,
                err_msg=f"A's step {n} disturbed by B's join")
            assert np.argmax(s) == np.argmax(j), (
                f"A's step {n} token bent by B's join")


def test_dense_hatch_parity_and_trajectory():
    """CHAINERMN_TPU_PAGED_ATTN=dense: logits within fp32 rounding of
    the paged path (same gather, different softmax shape), and the
    engine-level greedy TRAJECTORY is equal — the acceptance pin."""
    model = _model()
    rng = np.random.RandomState(2)
    full = rng.randint(0, VOCAB, 10 + 5).astype(np.int32)
    hp = Harness(model, mode="paged")
    hd = Harness(model, mode="dense")
    lp = hp.prefill(0, full[:10])
    ld = hd.prefill(0, full[:10])
    np.testing.assert_allclose(lp, ld, atol=1e-5)
    for n in range(5):
        pos = 10 + n
        a = hp.decode([0], [full[pos]], [pos])
        b = hd.decode([0], [full[pos]], [pos])
        np.testing.assert_allclose(a, b, atol=1e-5)

    prompts = [rng.randint(0, VOCAB, rng.randint(4, 20)) for _ in range(4)]

    def run(env_mode, monkey=None):
        eng = ServingEngine(model, num_pages=64, page_size=8,
                            max_batch=4, max_context=64, mode=env_mode)
        for p in prompts:
            eng.submit(Request(p, max_new_tokens=8))
        eng.drain(now=0.0)
        return [r.tokens for r in eng.completed]

    assert run("paged") == run("dense")


def test_env_hatch_resolves_at_construction(monkeypatch):
    model = _model()
    monkeypatch.setenv("CHAINERMN_TPU_PAGED_ATTN", "dense")
    eng = ServingEngine(model, num_pages=16, page_size=8, max_batch=2,
                        max_context=32)
    assert eng.mode == "dense"
    monkeypatch.setenv("CHAINERMN_TPU_PAGED_ATTN", "bogus")
    with pytest.raises(ValueError):
        ServingEngine(model, num_pages=16, page_size=8, max_batch=2,
                      max_context=32)


def test_bf16_pages_within_documented_band():
    """bf16 pages (the serving default under bf16 compute): logits
    track the bf16 one-shot forward within the documented band — the
    page round-trip adds one bf16 quantization on K/V, nothing more.
    (docs/serving.md 'numerics'; the tight 1e-5 contract is fp32.)"""
    model = _model(compute_dtype=jnp.bfloat16)
    rng = np.random.RandomState(3)
    full = rng.randint(0, VOCAB, 8 + 4).astype(np.int32)
    ref = _oneshot(model, full)
    h = Harness(model, dtype=jnp.bfloat16)
    logits = h.prefill(0, full[:8])
    assert np.max(np.abs(logits - ref[7])) < 0.25
    for n in range(4):
        pos = 8 + n
        logits = h.decode([0], [full[pos]], [pos])
        assert np.max(np.abs(logits[0] - ref[pos])) < 0.25


def test_engine_greedy_matches_oneshot_trajectory():
    """End-to-end: the engine's greedy continuation equals argmax over
    the one-shot forward, request by request."""
    model = _model()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, VOCAB, n).astype(np.int32)
               for n in (5, 11, 16)]
    eng = ServingEngine(model, num_pages=64, page_size=8, max_batch=4,
                        max_context=64)
    for p in prompts:
        eng.submit(Request(p, max_new_tokens=6))
    eng.drain(now=0.0)
    assert len(eng.completed) == 3
    for req in eng.completed:
        seq = list(req.prompt)
        for n, tok in enumerate(req.tokens):
            ref = _oneshot(model, seq)
            assert tok == int(np.argmax(ref[-1])), f"token {n}"
            seq.append(tok)


def test_joins_and_leaves_never_retrace():
    """The bucketed-shapes contract: after warmup() has compiled every
    (prompt bucket × 1) prefill and (batch bucket) decode program, a
    full staggered load — joins, leaves, ragged prompts — triggers
    ZERO additional traces."""
    model = _model()
    eng = ServingEngine(model, num_pages=64, page_size=8, max_batch=4,
                        max_context=64)
    eng.warmup()
    p_traces, d_traces = eng.prefill_traces, eng.decode_traces
    assert p_traces == len(eng.prefill_buckets)
    assert d_traces == len(eng.batch_buckets)
    rng = np.random.RandomState(5)
    # staggered arrivals: the running batch sweeps sizes 1..4 and back
    for i in range(6):
        eng.submit(Request(rng.randint(0, VOCAB, rng.randint(3, 30)),
                           max_new_tokens=4 + i,
                           arrival_time=float(i)))
    t = 0.0
    while eng.running or eng.scheduler.pending():
        eng.step(now=t)
        t += 1.0
    assert len(eng.completed) == 6
    assert (eng.prefill_traces, eng.decode_traces) == (p_traces, d_traces)
