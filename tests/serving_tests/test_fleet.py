"""Elastic serving fleet, single process, tier-1 (ISSUE 15).

Four layers, all deterministic and tiny (the tier-1 compile budget):

* the ROLE-NAMESPACED MEMBERSHIP protocol — a ``fleet`` group and a
  training ``elastic`` group sharing one KV store are fully
  key-disjoint (presence/intent/epoch keys never cross), views carry
  their group role, and the leader publishes the multicast tree plan
  next to every decided view;
* the MULTICAST TREE PLAN — a pure function of the member set:
  deterministic, every non-root member exactly once, every source
  already a holder, depth ``== ceil(log2 N)``;
* the ROUTER — per-tenant fair spread with decorrelated rotations,
  typed sideways shedding on saturation, typed give-up when no live
  replica remains;
* the FLEET ARC on real (tiny) engines — kill one of two replicas
  under load → ZERO dropped requests, every request finishing with its
  solo-run trajectory; a third cold replica joins → bit-identical
  weights via the tree sync and the router spreads new admissions to
  it; losing the last replica raises ``RecoveryGivingUp`` naming the
  FLEET group (the ISSUE 15 small-fix pin).
"""

import math
import os
import threading

import numpy as np
import pytest

from chainermn_tpu import observability
from chainermn_tpu.communicators import (ElasticMembership, MembershipView,
                                         RankPreempted,
                                         multicast_tree_plan)
from chainermn_tpu.extensions import RecoveryGivingUp
from chainermn_tpu.serving import (FleetRouter, NoLiveReplicaError,
                                   QueueDepthScalePolicy,
                                   QueueSaturatedError, ReplicaFleet,
                                   Request, ServingEngine, fleet_mode)


@pytest.fixture(autouse=True)
def _fresh_registry():
    observability.reset_registry()
    yield
    observability.reset_registry()


# -- multicast tree plan (pure) ----------------------------------------------

@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 9, 16, 17])
def test_tree_plan_properties(n):
    members = tuple(range(100, 100 + 3 * n, 3))   # arbitrary ids
    root = members[n // 2]
    plan = multicast_tree_plan(members, root=root)
    # deterministic pure function
    assert plan == multicast_tree_plan(members, root=root)
    # depth == ceil(log2 N)
    assert len(plan) == (math.ceil(math.log2(n)) if n > 1 else 0)
    # every non-root member exactly once as a destination
    dsts = [d for rnd in plan for _, d in rnd]
    assert sorted(dsts) == sorted(m for m in members if m != root)
    # every source already holds the payload when it sends
    have = {root}
    for rnd in plan:
        for src, dst in rnd:
            assert src in have and dst not in have
        have |= {d for _, d in rnd}
    assert have == set(members)


def test_tree_plan_default_root_and_errors():
    assert multicast_tree_plan([5, 3, 9]) \
        == multicast_tree_plan([3, 5, 9], root=3)
    with pytest.raises(ValueError):
        multicast_tree_plan([])
    with pytest.raises(ValueError):
        multicast_tree_plan([1, 1, 2])
    with pytest.raises(ValueError):
        multicast_tree_plan([1, 2], root=7)


# -- role-namespaced membership ----------------------------------------------

class KV:
    """Thread-safe in-memory stand-in for the coordination KV store
    (the real client's narrow surface: try_get raises on missing)."""

    def __init__(self):
        self.store = {}
        self.lock = threading.Lock()

    def key_value_set(self, k, v):
        with self.lock:
            self.store[k] = str(v)

    def key_value_try_get(self, k):
        with self.lock:
            if k not in self.store:
                raise KeyError(k)
            return self.store[k]

    def key_value_delete(self, k):
        with self.lock:
            self.store.pop(k, None)


def _member(kv, rank, role="elastic", world=2, **kw):
    kw.setdefault("settle_s", 0.05)
    kw.setdefault("poll_s", 0.002)
    kw.setdefault("timeout_ms", 4000)
    return ElasticMembership(kv, rank=rank, world=world, role=role, **kw)


def test_role_groups_are_key_disjoint():
    """A fleet group and a training elastic group in the same store
    never see each other's keys: intents, presence, epochs, views."""
    kv = KV()
    fleet0 = _member(kv, 0, role="fleet")
    el0 = _member(kv, 0)
    # a fleet join intent is invisible to the elastic group (and vice
    # versa)
    _member(kv, 1, role="fleet").announce_join()
    _member(kv, 1).announce_leave()
    assert el0.pending_joins() == ()
    assert "cmn/fleet/join/1" in kv.store
    assert "cmn/elastic/leave/1" in kv.store
    # the elastic rank-1 LEAVE must NOT exclude fleet rank 1: both
    # fleet members resolve and the fleet view keeps rank 1
    out = {}
    fleet1 = _member(kv, 1, role="fleet")
    t = threading.Thread(target=lambda: out.setdefault(
        1, fleet1.resolve(expect={0, 1})))
    t.start()
    out[0] = fleet0.resolve(expect={0, 1})
    t.join()
    assert out[0] == out[1]
    assert out[0].members == (0, 1)
    assert out[0].role == "fleet"
    # meanwhile the elastic group's resolve honors ITS leave
    v = el0.resolve(expect={0})
    assert v.members == (0,) and v.role == "elastic"
    # epochs advanced independently, and every key sits under its role
    assert fleet0.current_epoch() == 1 and el0.current_epoch() == 1
    assert all(k.startswith(("cmn/fleet/", "cmn/elastic/"))
               for k in kv.store)


def test_views_of_different_roles_never_compare_equal():
    assert MembershipView(1, (0, 1), role="fleet") \
        != MembershipView(1, (0, 1))
    assert MembershipView(1, (0, 1)) == MembershipView(1, (0, 1))


def test_leader_publishes_tree_plan_next_to_view():
    kv = KV()
    m0 = _member(kv, 0, role="fleet", world=3)
    out = {}
    others = [_member(kv, r, role="fleet", world=3) for r in (1, 2)]
    ts = [threading.Thread(target=lambda m=m, r=r: out.setdefault(
        r, m.resolve(expect={0, 1, 2})))
        for r, m in zip((1, 2), others)]
    for t in ts:
        t.start()
    out[0] = m0.resolve(expect={0, 1, 2})
    for t in ts:
        t.join()
    assert out[0].members == (0, 1, 2)
    assert "cmn/fleet/e1/tree" in kv.store
    # the published plan IS the pure plan, from any member's reader
    assert others[0].read_tree_plan(1) \
        == multicast_tree_plan((0, 1, 2))
    # and a reader without the key falls back to computing it
    kv.key_value_delete("cmn/fleet/e1/tree")
    assert m0.read_tree_plan(1) == multicast_tree_plan((0, 1, 2))


def test_giving_up_names_the_fleet_group():
    """ISSUE 15 small fix: a RecoveryGivingUp raised inside a
    serving-role group names the FLEET namespace in its carried view —
    not the training elastic one the same process may also hold."""
    err = RecoveryGivingUp(
        "fleet shrank below min_replicas=1",
        membership=MembershipView(4, (0, 2), role="fleet"))
    assert "group 'fleet'" in str(err)
    assert "epoch 4" in str(err) and "members [0, 2]" in str(err)
    # the training group keeps naming elastic (back-compat format)
    err = RecoveryGivingUp(
        "budget exhausted", membership=MembershipView(2, (0,)))
    assert "group 'elastic'" in str(err)
    plain = RecoveryGivingUp("budget exhausted")
    assert "membership" not in str(plain)


def test_membership_role_validation():
    with pytest.raises(ValueError):
        ElasticMembership(KV(), 0, 2, role="a/b")
    with pytest.raises(ValueError):
        ElasticMembership(KV(), 0, 2, role="")


# -- router policy (fake replicas, no engines) -------------------------------

class _FakeReplica:
    remote = False

    def __init__(self, rid, capacity=100):
        self.rid = rid
        self.live = True
        self.capacity = capacity
        self.q = []

    def submit(self, req):
        if len(self.q) >= self.capacity:
            raise QueueSaturatedError(req.tenant, len(self.q),
                                      self.capacity)
        self.q.append(req)

    def queue_depth(self, tenant=None):
        if tenant is None:
            return len(self.q)
        return sum(1 for r in self.q if r.tenant == tenant)

    def tenant_depths(self):
        out = {}
        for r in self.q:
            out[r.tenant] = out.get(r.tenant, 0) + 1
        return out

    def busy(self):
        return bool(self.q)


class _FakeFleet:
    def __init__(self, replicas):
        self.replicas = {r.rid: r for r in replicas}

    def live_replicas(self):
        return [self.replicas[rid] for rid in sorted(self.replicas)
                if self.replicas[rid].live]


def _req(tenant, rid=None):
    return Request(np.arange(1, 5, dtype=np.int32), 2, tenant=tenant,
                   arrival_time=0.0, request_id=rid)


def test_router_per_tenant_fair_spread_is_decorrelated():
    fleet = _FakeFleet([_FakeReplica(0), _FakeReplica(1),
                        _FakeReplica(2)])
    router = FleetRouter(fleet)
    a = [router.route(_req("a")) for _ in range(6)]
    b = [router.route(_req("b")) for _ in range(3)]
    # each tenant rotates over ALL live replicas (fair spread)...
    assert a == [0, 1, 2, 0, 1, 2]
    # ...with its own persistent cursor: tenant b starts at 0 again,
    # not wherever tenant a's flood left the rotation
    assert b == [0, 1, 2]
    assert router.by_replica == {0: 3, 1: 3, 2: 3}


def test_router_sheds_sideways_and_reraises_typed():
    full = _FakeReplica(0, capacity=0)
    fleet = _FakeFleet([full, _FakeReplica(1)])
    router = FleetRouter(fleet)
    # replica 0 saturated: the request sheds to replica 1, typed error
    # swallowed, spill counted
    assert router.route(_req("t")) == 1
    assert router.spills == 1
    # every replica saturated: the LAST typed error surfaces unchanged
    fleet.replicas[1].capacity = 1
    with pytest.raises(QueueSaturatedError) as e:
        router.route(_req("t"))
    assert e.value.tenant == "t"
    # no live replica at all: the typed no-capacity error
    for r in fleet.replicas.values():
        r.live = False
    with pytest.raises(NoLiveReplicaError):
        router.route(_req("t"))


def test_router_sheds_channel_dead_replica_at_ingress():
    """A dead remote worker discovered at SUBMIT time (typed channel
    error) must not surface to the caller while a live replica exists:
    the router skips it for this placement and sheds it afterwards
    (review fix — it used to stay live, charging every admission the
    full channel deadline)."""
    from chainermn_tpu.communicators._host_channel import (
        ChannelTimeoutError)

    class _DeadReplica(_FakeReplica):
        remote = True

        def submit(self, req):
            raise ChannelTimeoutError("p2p", "key", 6000, 1)

    class _FleetWithPreempt(_FakeFleet):
        def __init__(self, replicas):
            super().__init__(replicas)
            self.preempted = []

        def preempt(self, rid, exc=None, now=None):
            self.replicas[rid].live = False
            self.preempted.append(rid)

    fleet = _FleetWithPreempt([_DeadReplica(0), _FakeReplica(1)])
    router = FleetRouter(fleet)
    fleet.replicas[0].router = router
    assert router.route(_req("t")) == 1
    assert fleet.preempted == [0]
    assert fleet.replicas[0].live is False
    # subsequent admissions never touch the dead handle again
    assert router.route(_req("t")) == 1


def test_reroute_forces_past_saturated_survivor_zero_drop():
    """Review fix: a survivor's saturated queue must not DROP rerouted
    in-flight work mid-replay — refused requests force front-of-line
    (bound-exempt, the eviction-requeue discipline) and every request
    still completes."""
    def factory(rid):
        eng = _make_engine(seed=0)
        eng.scheduler.max_queue = 1    # saturate trivially
        return eng

    fleet = ReplicaFleet(engine_factory=factory, replicas=2)
    rng = np.random.RandomState(11)
    reqs = [Request(rng.randint(1, 97, 5).astype(np.int32), 3,
                    tenant="t0", arrival_time=0.0, request_id=i)
            for i in range(2)]
    placements = [fleet.submit(r) for r in reqs]
    assert placements == [0, 1]          # one queued per replica
    # replica 1 dies holding its queued request; replica 0's queue is
    # at its bound — the replay must force past it, not raise/drop
    fleet.preempt(1, now=0.0)
    assert fleet.reroutes == 1
    fleet.drain(now=1.0)
    assert sorted(r.request_id for r in fleet.completed) == [0, 1]


def test_drain_for_reroute_requeue_stamp_clock_domains():
    """Review fix: RUNNING requests get a requeue stamp in the
    caller's engine-clock domain (synthetic ``now`` when given, the
    monotonic default otherwise) so re-admission books the re-queue
    dwell — never the prior decode time — as queue wait; queued-only
    requests keep arrival-based accounting (no stamp)."""
    from chainermn_tpu.serving.fleet import LocalReplica
    engine = _make_engine(seed=0)
    running = Request(np.arange(1, 6, dtype=np.int32), 3, tenant="t",
                      arrival_time=0.0, request_id="run")
    queued = Request(np.arange(1, 6, dtype=np.int32), 3, tenant="t",
                     arrival_time=0.0, request_id="q")
    engine.submit(running)
    engine.step(now=0.5)                  # 'running' admitted
    engine.submit(queued)
    replica = LocalReplica(0, engine)
    reqs = {r.request_id: r for r in
            replica.drain_for_reroute(now=5.0)}
    assert reqs["run"].requeue_time == 5.0
    assert reqs["q"].requeue_time is None
    # and with no caller clock, the stamp falls back to the engines'
    # monotonic default instead of None (None would re-book the whole
    # prior life as queue wait at re-admission)
    engine2 = _make_engine(seed=0)
    r2 = Request(np.arange(1, 6, dtype=np.int32), 3, tenant="t",
                 arrival_time=0.0)
    engine2.submit(r2)
    engine2.step()
    out = LocalReplica(1, engine2).drain_for_reroute()
    assert out[0].requeue_time is not None


def test_router_exclude_and_ledger():
    fleet = _FakeFleet([_FakeReplica(0), _FakeReplica(1)])
    router = FleetRouter(fleet)
    req = _req("t", rid="r-1")
    assert router.route(req, exclude=(0,)) == 1
    assert router.ledger["r-1"] == 1
    assert router.placements(1) == ("r-1",)
    assert router.rerouted == 0
    router.route(_req("t", rid="r-2"), exclude=(1,), reroute=True)
    assert router.rerouted == 1


# -- scale policy off the registry gauges ------------------------------------

def test_queue_depth_scale_policy_reads_registry_gauges():
    reg = observability.registry()
    policy = QueueDepthScalePolicy(scale_up_depth=8, scale_down_depth=0,
                                   min_replicas=1, max_replicas=4)
    # no gauge yet: hold
    assert policy.decide(reg, 2) == 0
    g = reg.gauge(QueueDepthScalePolicy.GAUGE)
    g.set(3, tenant="a")
    g.set(9, tenant="b")          # one tenant's backlog over the bound
    assert policy.decide(reg, 2) == 1
    assert policy.decide(reg, 4) == 0     # at max_replicas: hold
    g.set(0, tenant="a")
    g.set(0, tenant="b")
    assert policy.decide(reg, 2) == -1    # everyone idle: shrink
    assert policy.decide(reg, 1) == 0     # at min_replicas: hold


def test_queue_depth_scale_policy_hysteresis_one_spike_one_decision():
    """ISSUE 16 satellite: a sustained excursion past a water mark
    collapses to ONE decision — the direction re-arms only once the
    gauge crosses back past its own mark (the PR 15 stateless read
    re-emitted +1 on every step of one spike)."""
    reg = observability.registry()
    policy = QueueDepthScalePolicy(scale_up_depth=8, scale_down_depth=2,
                                   min_replicas=1, max_replicas=4)
    g = reg.gauge(QueueDepthScalePolicy.GAUGE)
    g.set(9, tenant="a")
    assert policy.decide(reg, 2) == 1
    # the spike persists: NOT re-emitted
    assert policy.decide(reg, 2) == 0
    assert policy.decide(reg, 3) == 0
    # dips below the HIGH mark but stays above the LOW mark: re-arms
    # the up direction, emits nothing (inside the band)
    g.set(5, tenant="a")
    assert policy.decide(reg, 3) == 0
    # a fresh spike is a fresh decision
    g.set(12, tenant="a")
    assert policy.decide(reg, 3) == 1
    assert policy.decide(reg, 3) == 0
    # drain past the low mark: one shrink, then silence while parked
    g.set(1, tenant="a")
    assert policy.decide(reg, 4) == -1
    assert policy.decide(reg, 3) == 0
    assert policy.decide(reg, 2) == 0
    # back inside the band re-arms the down direction
    g.set(5, tenant="a")
    assert policy.decide(reg, 2) == 0
    g.set(0, tenant="a")
    assert policy.decide(reg, 2) == -1


def test_queue_depth_scale_policy_cooldown_windows():
    """Per-direction cooldowns (enforced only when the caller threads
    ``now``): a re-armed direction still holds until its window
    elapses; the legacy now-less call sites keep the re-arm rule
    alone."""
    reg = observability.registry()
    policy = QueueDepthScalePolicy(scale_up_depth=8, scale_down_depth=0,
                                   max_replicas=8, up_cooldown_s=10.0,
                                   down_cooldown_s=20.0)
    g = reg.gauge(QueueDepthScalePolicy.GAUGE)
    g.set(9, tenant="a")
    assert policy.decide(reg, 2, now=0.0) == 1
    g.set(3, tenant="a")                   # re-arm up
    assert policy.decide(reg, 2, now=1.0) == 0
    g.set(9, tenant="a")
    assert policy.decide(reg, 2, now=5.0) == 0    # re-armed, cooling
    assert policy.decide(reg, 2, now=12.0) == 1   # window elapsed
    # the down direction's window is independent
    g.set(0, tenant="a")
    assert policy.decide(reg, 3, now=13.0) == -1
    g.set(9, tenant="a")                   # re-arm down on the way up
    policy.decide(reg, 3, now=14.0)
    g.set(0, tenant="a")
    assert policy.decide(reg, 3, now=20.0) == 0   # still cooling
    assert policy.decide(reg, 3, now=34.0) == -1
    with pytest.raises(ValueError):
        QueueDepthScalePolicy(scale_up_depth=2, scale_down_depth=5)


# -- the fleet arc on real engines (tiny: the tier-1 compile budget) ---------

def _make_engine(seed=0):
    import jax.numpy as jnp  # noqa: F401 (cpu backend pinned by conftest)
    from chainermn_tpu.models import TransformerLM
    model = TransformerLM(n_vocab=97, d_model=32, n_heads=1, n_layers=1,
                          max_len=32, seed=seed)
    return ServingEngine(model, num_pages=32, page_size=16, max_batch=2,
                         max_context=32, prefix_cache=False)


def _state_leaves(engine):
    import jax
    return [np.asarray(x) for x in jax.tree.leaves(engine.state)]


def test_fleet_arc_kill_join_parity():
    """The scripted-membership tier-1 acceptance arc: kill one of two
    replicas under seeded open-loop load → zero dropped requests and
    every request completes with its solo-run trajectory (rerouted
    sequences replay from their prompts); join a third (cold, different
    seed) replica → bit-identical weights via the tree plan and the
    router spreads new admissions to it; losing the last replica gives
    up TYPED naming the fleet group."""
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 97, rng.randint(4, 9)).astype(np.int32)
               for _ in range(6)]
    fleet = ReplicaFleet(engine_factory=lambda rid: _make_engine(seed=0),
                         replicas=2)
    assert fleet.view.role == "fleet"
    reqs = [Request(p, 4, tenant=f"t{i % 2}", arrival_time=0.0,
                    request_id=i) for i, p in enumerate(prompts)]
    placements = [fleet.submit(r) for r in reqs]
    assert set(placements) == {0, 1}          # load spread over both
    epoch0 = fleet.view.epoch
    fleet.replicas[1].kill_at = 1             # seeded kill under load
    fleet.drain(now=1.0)

    # zero drops: every submitted request completed, exactly once
    assert sorted(r.request_id for r in fleet.completed) \
        == list(range(6))
    assert fleet.sheds == 1 and fleet.reroutes >= 1
    assert fleet.view.epoch > epoch0
    rerouted = [r for r in fleet.completed if r.preemptions > 0]
    assert rerouted, "the kill must have caught in-flight sequences"

    # solo-run trajectory parity: each request's generated sequence
    # (fold-surviving prompt suffix + final tokens) equals the solo run
    golden = _make_engine(seed=0)
    for i, req in enumerate(sorted(fleet.completed,
                                   key=lambda r: r.request_id)):
        generated = list(req.prompt[len(prompts[req.request_id]):]) \
            + list(req.tokens)
        g = Request(prompts[req.request_id], 4, tenant="g",
                    arrival_time=0.0)
        golden.submit(g)
        golden.drain(now=1.0)
        solo = golden.completed[-1].tokens
        assert generated == solo, (req.request_id, generated, solo)

    # join a COLD replica built with different seed weights: the tree
    # sync must land it bit-identical to the root survivor
    joiner = _make_engine(seed=123)
    root_leaves = _state_leaves(fleet.replicas[0].engine)
    assert any((a != b).any() for a, b in
               zip(_state_leaves(joiner), root_leaves))
    new_ids = fleet.join(engines={2: joiner})
    assert new_ids == [2]
    assert fleet.weight_syncs == 1
    assert fleet.weight_sync_rounds == 1     # 1 joiner: ceil(log2 2)
    assert fleet.weight_sync_bytes > 0
    assert fleet.weight_sync_s >= 0.0
    assert all((a == b).all() for a, b in
               zip(_state_leaves(joiner), root_leaves))

    # the router spreads NEW admissions onto the joiner
    more = [Request(rng.randint(1, 97, 4).astype(np.int32), 2,
                    tenant="t0", arrival_time=0.0,
                    request_id=100 + i) for i in range(4)]
    new_placements = [fleet.submit(r) for r in more]
    assert 2 in new_placements
    fleet.drain(now=2.0)
    assert sorted(r.request_id for r in fleet.completed
                  if r.request_id >= 100) == [100, 101, 102, 103]

    # registry gauges published for the scale policy (trace-off)
    reg = observability.registry()
    assert reg.gauge("chainermn_tpu_fleet_replicas").value() == 2
    assert reg.counter("chainermn_tpu_fleet_reroutes_total").value() \
        == fleet.reroutes

    # shrink to nothing: typed give-up carrying the FLEET-role view
    fleet.preempt(0)
    with pytest.raises(RecoveryGivingUp) as e:
        fleet.preempt(2)
    assert "group 'fleet'" in str(e.value)
    assert e.value.membership.role == "fleet"


def test_fleet_off_hatch_is_single_engine(monkeypatch):
    """CHAINERMN_TPU_FLEET=off: the fleet clamps to ONE replica (the
    factory is called once), every admission routes to it, and join()
    refuses typed — single-engine serving, exactly the PR 13 shape."""
    monkeypatch.setenv("CHAINERMN_TPU_FLEET", "off")
    assert fleet_mode() is False
    assert fleet_mode(True) is False          # the hatch wins
    calls = []

    def factory(rid):
        calls.append(rid)
        return _make_engine(seed=0)

    fleet = ReplicaFleet(engine_factory=factory, replicas=3)
    assert calls == [0]
    reqs = [Request(np.arange(1, 6, dtype=np.int32), 2, tenant="t",
                    arrival_time=0.0) for _ in range(3)]
    assert [fleet.submit(r) for r in reqs] == [0, 0, 0]
    with pytest.raises(RecoveryGivingUp) as e:
        fleet.join(engines={1: _make_engine(seed=1)})
    assert "CHAINERMN_TPU_FLEET=off" in str(e.value)
    fleet.drain(now=1.0)
    assert len(fleet.completed) == 3
    monkeypatch.delenv("CHAINERMN_TPU_FLEET")
    assert fleet_mode() is True
    assert fleet_mode(False) is False


def test_fleet_step_surfaces_scale_decision():
    """The policy's decision rides step() stats (the fleet never grows
    itself — capacity is granted through join/retire)."""
    fleet = ReplicaFleet(engine_factory=lambda rid: _make_engine(seed=0),
                         replicas=1,
                         scale_policy=QueueDepthScalePolicy(
                             scale_up_depth=2, max_replicas=4))
    # back the queue up past the bound: submit more than one step admits
    for i in range(8):
        fleet.submit(Request(np.arange(1, 5, dtype=np.int32), 2,
                             tenant="t", arrival_time=10.0 + i))
    st = fleet.step(now=0.0)   # nothing eligible yet: queues deep
    assert st["scale_decision"] == 1
    fleet.drain(now=20.0)
    st = fleet.step(now=30.0)
    assert st["scale_decision"] in (-1, 0)
