"""Property suite for the paged-KV block allocator (ISSUE 9 satellite).

The allocator is the serving engine's only host-side source of truth
about page ownership; a single double-grant corrupts two sequences'
caches silently.  These tests churn it with a seeded random trace and
assert the invariants after EVERY step via ``BlockAllocator.check``:

* every block owned by exactly one sequence (no aliasing);
* free-list conservation across alloc/free/evict interleaving;
* deterministic tables from a seeded request trace (bit-identical
  across two independent replays — the cross-host determinism the
  engine's recompute-on-readmit relies on);
* pool exhaustion raises the TYPED error with the allocator state
  untouched (OOM is a scheduling event, never corruption).
"""

import numpy as np
import pytest

from chainermn_tpu.serving import BlockAllocator, PagePoolExhaustedError


def test_basic_alloc_free_roundtrip():
    a = BlockAllocator(8, 4)
    t = a.ensure("s0", 9)          # 3 pages
    assert len(t) == 3 and a.free_pages == 5
    assert a.capacity("s0") == 12
    assert a.check()
    # idempotent: same coverage, no growth
    assert a.ensure("s0", 9) == t
    # growth appends, never reshuffles
    t2 = a.ensure("s0", 13)
    assert t2[:3] == t and len(t2) == 4
    assert a.free("s0") == 4
    assert a.free_pages == 8 and a.check()


def test_pages_for_boundaries():
    a = BlockAllocator(4, 8)
    assert a.pages_for(0) == 0
    assert a.pages_for(1) == 1
    assert a.pages_for(8) == 1
    assert a.pages_for(9) == 2


def test_exclusive_ownership_and_conservation_under_churn():
    rng = np.random.RandomState(0)
    a = BlockAllocator(32, 4)
    live = {}
    for step in range(600):
        op = rng.randint(3)
        if op == 0 and len(live) < 12:        # admit
            sid = f"s{step}"
            want = int(rng.randint(1, 40))
            try:
                a.ensure(sid, want)
                live[sid] = want
            except PagePoolExhaustedError:
                pass
        elif op == 1 and live:                # grow (decode append)
            sid = rng.choice(sorted(live))
            live[sid] += int(rng.randint(1, 9))
            try:
                a.ensure(sid, live[sid])
            except PagePoolExhaustedError:
                a.free(sid)                   # evict on OOM
                del live[sid]
        elif op == 2 and live:                # retire
            sid = rng.choice(sorted(live))
            a.free(sid)
            del live[sid]
        assert a.check()                      # invariants after EVERY op
    # the shadow model and the allocator agree on who is live and how
    # much they hold
    assert set(live) == set(a.sequences())
    for sid, want in live.items():
        assert a.capacity(sid) >= want
    assert a.used_pages == sum(a.pages_for(n) for n in live.values())


def test_seeded_trace_is_deterministic():
    """Two independent replays of the same seeded trace produce
    bit-identical block tables at every step — the pure-function
    property recompute-on-readmit (and any cross-host replica of the
    scheduler) depends on."""
    def replay(seed):
        rng = np.random.RandomState(seed)
        a = BlockAllocator(24, 4)
        live = set()
        tables = []
        for step in range(300):
            op = rng.randint(3)
            if op == 0 and len(live) < 8:
                sid = step
                try:
                    a.ensure(sid, int(rng.randint(1, 30)))
                    live.add(sid)
                except PagePoolExhaustedError:
                    pass
            elif op == 1 and live:
                sid = sorted(live)[int(rng.randint(len(live)))]
                try:
                    a.ensure(sid, a.capacity(sid) + 1)
                except PagePoolExhaustedError:
                    a.free(sid)
                    live.discard(sid)
            elif op == 2 and live:
                sid = sorted(live)[int(rng.randint(len(live)))]
                a.free(sid)
                live.discard(sid)
            tables.append({s: tuple(a.block_table(s)) for s in live})
        return tables

    assert replay(7) == replay(7)
    assert replay(7) != replay(8)  # the trace, not the code, is fixed


def test_exhaustion_is_typed_and_atomic():
    a = BlockAllocator(4, 4)
    a.ensure("big", 12)            # 3 of 4 pages
    snapshot = (a.free_pages, a.block_table("big"))
    with pytest.raises(PagePoolExhaustedError) as ei:
        a.ensure("huge", 9)        # needs 3, only 1 free
    assert ei.value.requested == 3
    assert ei.value.free == 1
    assert ei.value.total == 4
    # atomicity: nothing was granted, nothing was registered
    assert (a.free_pages, a.block_table("big")) == snapshot
    assert "huge" not in a.sequences()
    assert a.check()
    # and a partially-covering retry after a free succeeds cleanly
    a.free("big")
    assert len(a.ensure("huge", 9)) == 3
    assert a.check()


def test_freed_pages_recycle_fifo():
    """Free-list order is part of the determinism contract: pages
    return in table order and recycle FIFO, so a replayed trace sees
    identical ids (not merely identical counts)."""
    a = BlockAllocator(6, 2)
    t0 = a.ensure(0, 8)            # pages 0..3
    assert t0 == [0, 1, 2, 3]
    a.free(0)
    t1 = a.ensure(1, 4)            # FIFO: the remaining 4,5 first
    assert t1 == [4, 5]
    t2 = a.ensure(2, 6)
    assert t2 == [0, 1, 2]
    assert a.check()


def test_admission_order_exposed_for_eviction_policy():
    a = BlockAllocator(8, 2)
    for sid in ("a", "b", "c"):
        a.ensure(sid, 2)
    assert a.sequences() == ["a", "b", "c"]   # oldest first
    a.free("b")
    a.ensure("d", 2)
    assert a.sequences() == ["a", "c", "d"]
