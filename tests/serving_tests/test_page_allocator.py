"""Property suite for the paged-KV block allocator (ISSUE 9 satellite).

The allocator is the serving engine's only host-side source of truth
about page ownership; a single double-grant corrupts two sequences'
caches silently.  These tests churn it with a seeded random trace and
assert the invariants after EVERY step via ``BlockAllocator.check``:

* every block owned by exactly one sequence (no aliasing);
* free-list conservation across alloc/free/evict interleaving;
* deterministic tables from a seeded request trace (bit-identical
  across two independent replays — the cross-host determinism the
  engine's recompute-on-readmit relies on);
* pool exhaustion raises the TYPED error with the allocator state
  untouched (OOM is a scheduling event, never corruption).
"""

import numpy as np
import pytest

from chainermn_tpu.serving import BlockAllocator, PagePoolExhaustedError


def test_basic_alloc_free_roundtrip():
    a = BlockAllocator(8, 4)
    t = a.ensure("s0", 9)          # 3 pages
    assert len(t) == 3 and a.free_pages == 5
    assert a.capacity("s0") == 12
    assert a.check()
    # idempotent: same coverage, no growth
    assert a.ensure("s0", 9) == t
    # growth appends, never reshuffles
    t2 = a.ensure("s0", 13)
    assert t2[:3] == t and len(t2) == 4
    assert a.free("s0") == 4
    assert a.free_pages == 8 and a.check()


def test_pages_for_boundaries():
    a = BlockAllocator(4, 8)
    assert a.pages_for(0) == 0
    assert a.pages_for(1) == 1
    assert a.pages_for(8) == 1
    assert a.pages_for(9) == 2


def test_exclusive_ownership_and_conservation_under_churn():
    rng = np.random.RandomState(0)
    a = BlockAllocator(32, 4)
    live = {}
    for step in range(600):
        op = rng.randint(3)
        if op == 0 and len(live) < 12:        # admit
            sid = f"s{step}"
            want = int(rng.randint(1, 40))
            try:
                a.ensure(sid, want)
                live[sid] = want
            except PagePoolExhaustedError:
                pass
        elif op == 1 and live:                # grow (decode append)
            sid = rng.choice(sorted(live))
            live[sid] += int(rng.randint(1, 9))
            try:
                a.ensure(sid, live[sid])
            except PagePoolExhaustedError:
                a.free(sid)                   # evict on OOM
                del live[sid]
        elif op == 2 and live:                # retire
            sid = rng.choice(sorted(live))
            a.free(sid)
            del live[sid]
        assert a.check()                      # invariants after EVERY op
    # the shadow model and the allocator agree on who is live and how
    # much they hold
    assert set(live) == set(a.sequences())
    for sid, want in live.items():
        assert a.capacity(sid) >= want
    assert a.used_pages == sum(a.pages_for(n) for n in live.values())


def test_seeded_trace_is_deterministic():
    """Two independent replays of the same seeded trace produce
    bit-identical block tables at every step — the pure-function
    property recompute-on-readmit (and any cross-host replica of the
    scheduler) depends on."""
    def replay(seed):
        rng = np.random.RandomState(seed)
        a = BlockAllocator(24, 4)
        live = set()
        tables = []
        for step in range(300):
            op = rng.randint(3)
            if op == 0 and len(live) < 8:
                sid = step
                try:
                    a.ensure(sid, int(rng.randint(1, 30)))
                    live.add(sid)
                except PagePoolExhaustedError:
                    pass
            elif op == 1 and live:
                sid = sorted(live)[int(rng.randint(len(live)))]
                try:
                    a.ensure(sid, a.capacity(sid) + 1)
                except PagePoolExhaustedError:
                    a.free(sid)
                    live.discard(sid)
            elif op == 2 and live:
                sid = sorted(live)[int(rng.randint(len(live)))]
                a.free(sid)
                live.discard(sid)
            tables.append({s: tuple(a.block_table(s)) for s in live})
        return tables

    assert replay(7) == replay(7)
    assert replay(7) != replay(8)  # the trace, not the code, is fixed


def test_exhaustion_is_typed_and_atomic():
    a = BlockAllocator(4, 4)
    a.ensure("big", 12)            # 3 of 4 pages
    snapshot = (a.free_pages, a.block_table("big"))
    with pytest.raises(PagePoolExhaustedError) as ei:
        a.ensure("huge", 9)        # needs 3, only 1 free
    assert ei.value.requested == 3
    assert ei.value.free == 1
    assert ei.value.total == 4
    # atomicity: nothing was granted, nothing was registered
    assert (a.free_pages, a.block_table("big")) == snapshot
    assert "huge" not in a.sequences()
    assert a.check()
    # and a partially-covering retry after a free succeeds cleanly
    a.free("big")
    assert len(a.ensure("huge", 9)) == 3
    assert a.check()


def test_freed_pages_recycle_fifo():
    """Free-list order is part of the determinism contract: pages
    return in table order and recycle FIFO, so a replayed trace sees
    identical ids (not merely identical counts)."""
    a = BlockAllocator(6, 2)
    t0 = a.ensure(0, 8)            # pages 0..3
    assert t0 == [0, 1, 2, 3]
    a.free(0)
    t1 = a.ensure(1, 4)            # FIFO: the remaining 4,5 first
    assert t1 == [4, 5]
    t2 = a.ensure(2, 6)
    assert t2 == [0, 1, 2]
    assert a.check()


def test_admission_order_exposed_for_eviction_policy():
    a = BlockAllocator(8, 2)
    for sid in ("a", "b", "c"):
        a.ensure(sid, 2)
    assert a.sequences() == ["a", "b", "c"]   # oldest first
    a.free("b")
    a.ensure("d", 2)
    assert a.sequences() == ["a", "c", "d"]


# -- round 14: refcounted sharing + the prefix-hash trie ---------------------


def test_share_refcounts_and_conservation():
    a = BlockAllocator(8, 4)
    t = a.ensure("prov", 9)              # 3 pages
    a.share("bor", t[:2])                # 2 shared pages
    assert a.refcount(t[0]) == 2 and a.refcount(t[2]) == 1
    # conservation counts DISTINCT owned pages; logical counts holders
    assert a.free_pages == 5 and a.used_pages == 3
    assert a.logical_pages() == 5
    assert a.unique_pages("prov") == 1 and a.unique_pages("bor") == 0
    assert a.check()
    # provider frees: shared pages stay alive through the borrower
    assert a.free("prov") == 1           # only its unique page returns
    assert a.refcount(t[0]) == 1
    assert a.used_pages == 2 and a.check()
    # borrower frees: now they come back
    assert a.free("bor") == 2
    assert a.free_pages == 8 and a.check()


def test_shared_pages_recycle_fifo_at_refcount_zero():
    """FIFO free-order is preserved AT THE MOMENT a page's refcount
    hits zero — not at the first free of a holder (the page is still
    live then)."""
    a = BlockAllocator(6, 2)
    t = a.ensure(0, 8)                   # pages 0..3
    a.share(1, t[:2])                    # 0,1 shared
    a.free(0)                            # frees 2,3 only (0,1 shared)
    assert a.ensure(2, 4) == [4, 5]      # FIFO: the untouched tail first
    assert a.ensure(3, 4) == [2, 3]      # then 0's returned unique pages
    a.free(1)                            # NOW 0,1 return, in table order
    assert a.ensure(4, 4) == [0, 1]
    assert a.check()


def test_fork_moves_refcount_and_is_atomic():
    a = BlockAllocator(4, 4)
    t = a.ensure("prov", 6)              # pages 0,1
    a.share("bor", t)
    old, new = a.fork("bor", 1)
    assert (old, new) == (1, 2)
    assert a.refcount(1) == 1 and a.refcount(2) == 1
    assert a.block_table("bor") == [0, 2]
    assert a.block_table("prov") == [0, 1]   # provider untouched
    assert a.check()
    # unshared page: fork degenerates to a no-op (old == new)
    assert a.fork("bor", 1) == (2, 2)
    # pool dry: typed + atomic
    a.ensure("filler", 4)                # takes the last free page
    a.share("b2", a.block_table("prov"))
    snapshot = (a.free_pages, a.block_table("b2"))
    with pytest.raises(PagePoolExhaustedError):
        a.fork("b2", 0)
    assert (a.free_pages, a.block_table("b2")) == snapshot
    assert a.check()


def test_trie_match_full_pages_and_partial_tail():
    a = BlockAllocator(16, 4)
    prompt = tuple(range(10))            # 2 full chunks + 2-token tail
    a.ensure("prov", 11)
    a.register_prefix("prov", prompt)
    # identical prompt, capped at L-1=9: 2 full pages + 1 partial token
    pages, matched, n_full, partial = a.match_prefix(prompt, 9)
    assert (matched, n_full, partial) == (9, 2, 1)
    assert pages == a.block_table("prov")[:3]
    # page-aligned divergence: only the matching full chunk shares
    other = tuple(range(4)) + (99,) * 6
    pages, matched, n_full, partial = a.match_prefix(other, 9)
    assert (matched, n_full, partial) == (4, 1, 0)
    # no registration -> no match
    assert a.match_prefix((7, 7, 7, 7), 3) == ([], 0, 0, 0)
    # freeing the provider unregisters: nothing matches afterwards
    a.free("prov")
    assert a.match_prefix(prompt, 9) == ([], 0, 0, 0)
    assert a.check()


def test_trie_partial_cap_and_first_registration_wins():
    a = BlockAllocator(16, 4)
    a.ensure("p1", 7)
    a.register_prefix("p1", (1, 2, 3, 4, 5, 6))      # tail (5, 6)
    a.ensure("p2", 7)
    a.register_prefix("p2", (1, 2, 3, 4, 5, 7))      # tail (5, 7)
    # both partials match (5,...) with c=1: the FIRST registration wins
    pages, matched, n_full, partial = a.match_prefix(
        (1, 2, 3, 4, 5, 8, 9), 6)
    assert (matched, n_full, partial) == (5, 1, 1)
    assert pages[-1] == a.block_table("p1")[1]
    # the longer common prefix wins over registration order
    pages2, matched2, _, partial2 = a.match_prefix(
        (1, 2, 3, 4, 5, 7, 9), 6)
    assert (matched2, partial2) == (6, 2)
    assert pages2[-1] == a.block_table("p2")[1]
    # cap clips a would-be partial match entirely
    assert a.match_prefix((1, 2, 3, 4, 5, 6), 4)[1] == 4


def test_seeded_trace_with_sharing_is_deterministic():
    """The PR 9 determinism contract survives sharing: a seeded
    admit/share/fork/free churn replays to bit-identical tables."""
    def replay(seed):
        rng = np.random.RandomState(seed)
        a = BlockAllocator(24, 4)
        live = {}
        tables = []
        for step in range(300):
            op = rng.randint(4)
            if op == 0 and len(live) < 8:          # admit w/ match
                sid = step
                toks = tuple(int(x) for x in rng.randint(0, 3, 11))
                pages, m, n_full, c = a.match_prefix(toks, len(toks) - 1)
                try:
                    if m:
                        a.share(sid, pages)
                        if c:
                            a.fork(sid, n_full)
                        a.ensure(sid, len(toks) + 1)
                    else:
                        a.ensure(sid, len(toks) + 1)
                    a.register_prefix(sid, toks)
                    live[sid] = toks
                except PagePoolExhaustedError:
                    if sid in a.sequences():
                        a.free(sid)
            elif op == 1 and live:                 # grow
                sid = sorted(live)[int(rng.randint(len(live)))]
                try:
                    a.ensure(sid, a.capacity(sid) + 1)
                except PagePoolExhaustedError:
                    a.free(sid)
                    del live[sid]
            elif op == 2 and live:                 # retire
                sid = sorted(live)[int(rng.randint(len(live)))]
                a.free(sid)
                del live[sid]
            assert a.check()
            tables.append({s: tuple(a.block_table(s)) for s in live})
        return tables

    assert replay(11) == replay(11)
    assert replay(11) != replay(12)


# -- round 20: chunk-stride churn --------------------------------------------


def test_chunk_stride_grow_and_mid_chunk_eviction_conserve_pool():
    """Round-20 churn shape: chunked admissions grow in page-multiple
    chunk strides (``ensure`` at chunk boundaries, exactly the engine's
    admission pattern) and may be evicted MID-chunk — freed in full,
    re-admitted later from position zero.  Seeded grow/evict/restart
    cycles must conserve the pool and keep every ownership invariant at
    each step; a leaked chunk page here is the silent-corruption bug
    the mid-chunk eviction satellite exists to prevent."""
    rng = np.random.RandomState(20)
    a = BlockAllocator(32, 4)
    chunk = 8                                  # 2 pages per stride
    live = {}                                  # sid -> covered positions
    for step in range(400):
        op = rng.randint(4)
        if op == 0 and len(live) < 6:          # admit: first chunk
            sid = f"c{step}"
            try:
                a.ensure(sid, chunk)
                live[sid] = chunk
            except PagePoolExhaustedError:
                pass
        elif op == 1 and live:                 # advance one chunk
            sid = rng.choice(sorted(live))
            try:
                a.ensure(sid, live[sid] + chunk)
                live[sid] += chunk
            except PagePoolExhaustedError:     # pool dry mid-advance:
                a.free(sid)                    # the mid-chunk eviction
                del live[sid]
        elif op == 2 and live:                 # forced mid-chunk evict
            sid = rng.choice(sorted(live))
            a.free(sid)
            del live[sid]                      # cursor resets host-side
        elif op == 3 and live:                 # re-admit a fresh cycle
            sid = rng.choice(sorted(live))
            a.free(sid)
            del live[sid]
            try:
                a.ensure(sid, chunk)           # restart from chunk 0
                live[sid] = chunk
            except PagePoolExhaustedError:
                pass
        assert a.check()                       # invariants EVERY op
        assert a.used_pages == sum(a.pages_for(n) for n in live.values())
    for sid in list(live):
        a.free(sid)
    assert a.free_pages == 32 and a.check()


def test_eviction_accounting_unique_pages():
    """The livelock guard's accounting surface: a sequence whose pages
    are ALL shared would free nothing; unique_pages says so."""
    a = BlockAllocator(8, 4)
    t = a.ensure("prov", 8)              # 2 pages
    a.share("bor", t)                    # borrower holds ONLY shared
    assert a.unique_pages("bor") == 0
    assert a.unique_pages("prov") == 0   # both sides fully shared now
    a.ensure("bor", 9)                   # growth page is unique
    assert a.unique_pages("bor") == 1
    assert a.check()
