"""Serving bench-mode harness tests (ISSUE 9 satellites).

Two contracts: (a) serving rows are FENCED OUT of the flagship
last-good cache — same discipline as the longcontext/exchange rows: the
metric is not flagship-cacheable, so neither a /tmp plant nor a real
serving run can ever be re-served as training throughput; (b) the CPU
smoke is CLAMPED and LABELED (``cpu_smoke: true``, seconds-scale) so a
first-contact serving run can neither stale-out on size nor read as a
perf datum, and its measured window never retraces.
"""

import json
import os
import subprocess
import sys

import pytest

import bench

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SERVING_ROW = {
    "metric": "serving_engine_throughput",
    "value": 5120.0, "unit": "tokens/sec", "vs_baseline": None,
    "platform": "axon", "device_kind": "TPU v5 lite", "n_devices": 1,
    "p50_token_latency_ms": 3.1, "p99_token_latency_ms": 18.0,
    "qps": 16.0, "tenants": 4,
}


@pytest.fixture
def cache_paths(tmp_path, monkeypatch):
    primary = str(tmp_path / "last_bench.json")
    repo = str(tmp_path / "repo_last_bench.json")
    monkeypatch.setattr(bench, "_CACHE_PATH", primary)
    monkeypatch.setattr(bench, "_REPO_CACHE_PATH", repo)
    monkeypatch.setattr(bench, "_PREWARM_SENTINEL_BASE",
                        str(tmp_path / "prewarmed"))
    monkeypatch.setattr(bench, "_START_STAMP", str(tmp_path / "started"))
    return primary, repo


def test_serving_rows_are_never_flagship_cacheable(cache_paths, capsys):
    """Even a pristine on-chip serving row must not enter either cache
    slot: its metric is outside the flagship map, so `_cacheable` and
    the cross-slot screens refuse it on every path."""
    primary, repo = cache_paths
    assert bench._cacheable(SERVING_ROW) is False
    bench._emit(SERVING_ROW)              # persist path
    capsys.readouterr()
    assert not os.path.exists(primary)
    assert not os.path.exists(repo)


def test_planted_serving_entry_is_not_promoted(cache_paths, capsys,
                                              monkeypatch):
    """A serving entry planted in /tmp must not be promoted into the
    committed repo slot by a later flagship persist, and must never be
    re-served under any metric."""
    primary, repo = cache_paths
    with open(primary, "w") as f:
        json.dump({"entries": {"serving_engine_throughput": {
            "run_id": "planted", "saved_at": 9e9,
            "result": SERVING_ROW}}}, f)
    # a legit flagship result persists; the serving plant must not ride
    for k in ("BENCH_BS", "BENCH_SIZE", "BENCH_STEPS", "BENCH_MODEL",
              "BENCH_EXCHANGE", "BENCH_DONATE"):
        monkeypatch.delenv(k, raising=False)
    from tests.test_bench_harness import TPU_RESULT
    bench._emit(dict(TPU_RESULT, per_chip_batch=64, n_steps=40))
    capsys.readouterr()
    with open(repo) as f:
        entries = json.load(f)["entries"]
    assert "serving_engine_throughput" not in entries
    # stale re-serve path: serving metric finds nothing to serve
    monkeypatch.setenv("BENCH_MODEL", "serving")
    run_id, cached, fp = bench._load_cache("serving_engine_throughput")
    assert cached is None


def test_err_metric_and_first_contact_refusal(cache_paths, capsys,
                                              monkeypatch):
    """BENCH_MODEL=serving wires the error path to the serving metric,
    and first contact (no serving sentinel) refuses any stale re-serve
    — an honest null, the longcontext discipline."""
    monkeypatch.setenv("BENCH_MODEL", "serving")
    assert bench._err_metric() == ("serving_engine_throughput",
                                   "tokens/sec")
    assert bench._first_contact("serving")
    bench._emit_stale_or_error("relay wedged")
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["metric"] == "serving_engine_throughput"
    assert row["value"] is None
    assert row["first_contact"] is True
    assert "stale" not in row


def test_cpu_smoke_is_clamped_labeled_and_retrace_free(tmp_path):
    """End-to-end subprocess: the serving bench on the CPU backend
    emits one final row that is (a) labeled cpu_smoke, (b) clamped to
    the smoke load even when the env asks for more, (c) retrace-free in
    its measured window, and (d) carries the full metric surface
    (tokens/sec + p50/p99 + occupancy)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_NO_SUPERVISE="1",
               BENCH_MODEL="serving",
               BENCH_SERVE_REQUESTS="64",      # clamps to 12
               BENCH_SERVE_QPS="200",          # fast arrivals: no idle
               BENCH_SERVE_TENANTS="3",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"),
               BENCH_REPO_CACHE_PATH=str(tmp_path / "repo.json"),
               BENCH_PREWARM_SENTINEL=str(tmp_path / "prewarm"),
               BENCH_START_STAMP=str(tmp_path / "started"),
               BENCH_DEADLINE_S="480")
    out = subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                         env=env, capture_output=True, text=True,
                         timeout=420, cwd=ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["metric"] == "serving_engine_throughput"
    assert row["cpu_smoke"] is True
    assert row["requests"] == 12               # the clamp
    assert row["tenants"] == 3                 # knobs respected
    assert row["qps"] == 200.0
    assert row["value"] and row["value"] > 0
    assert row["window_retraces"] == 0
    assert row["completed"] == 12
    for key in ("p50_token_latency_ms", "p99_token_latency_ms",
                "page_occupancy_mean", "page_occupancy_max",
                "attn_mode", "page_dtype", "prefix_hit_rate",
                "prefix_matched_tokens", "effective_capacity_x",
                "forks", "disagg", "transferred_page_bytes", "tp"):
        assert key in row, key
    # round-16 fleet columns are present on EVERY serving row with the
    # single-engine defaults backfilled (ISSUE 15 satellite: row
    # consumers never key-miss on fleet-less rows)
    assert row["replicas"] == 1
    assert row["reroutes"] == 0
    assert row["weight_sync_s"] == 0.0
    # the chat-shaped load (per-tenant shared system prompts, the
    # default) must actually HIT: measured sharing economics, not
    # zero-filled columns (the ISSUE 13 acceptance pin)
    assert row["prefix_hit_rate"] > 0
    assert row["effective_capacity_x"] > 1.0
    assert row["disagg"] is False and row["tp"] == 1
    # the smoke never touches the caches (metric fencing end-to-end)
    assert not os.path.exists(tmp_path / "cache.json")
    assert not os.path.exists(tmp_path / "repo.json")
    # and a CPU run never stamps the serving prewarm sentinel
    assert not os.path.exists(str(tmp_path / "prewarm") + ".serving")


def test_fleet_rows_are_fenced_and_knobs_defeat_flagship(monkeypatch):
    """ISSUE 15 satellite: (env half) the fleet knobs defeat BOTH
    flagship fingerprints — a multi-replica or kill-under-load run can
    never be cached as training throughput; (payload half) a fleet
    serving row is metric-fenced like every serving row."""
    from tests.test_bench_harness import TPU_RESULT
    for knob, value in (("BENCH_SERVE_REPLICAS", "2"),
                        ("BENCH_FLEET_KILL_AT", "6")):
        monkeypatch.setenv(knob, value)
        assert not bench._cacheable(TPU_RESULT), knob
        monkeypatch.delenv(knob)
    assert bench._cacheable(TPU_RESULT)
    # legacy fingerprints backfill the fleet-less defaults (a stored
    # pre-round-16 flagship entry stays servable)
    assert bench._backfill_fp("resnet50", {})["serve_replicas"] == 1
    assert bench._backfill_fp("transformer", {})["fleet_kill_at"] == -1
    # a fleet row (serving metric) is refused on every cache path
    fleet_row = dict(SERVING_ROW, replicas=2, reroutes=5,
                     weight_sync_s=0.8)
    assert bench._cacheable(fleet_row) is False


def test_spec_chunk_rows_are_fenced(monkeypatch):
    """ISSUE 20 satellite (env half, serving side): the spec/chunk
    knobs defeat the flagship cache exactly like the fleet knobs — a
    speculative or chunked serving run can never be re-served as
    training throughput — and a spec-shaped serving row is
    metric-fenced on every cache path."""
    from tests.test_bench_harness import TPU_RESULT
    for knob, value in (("BENCH_SERVE_SPEC_K", "4"),
                        ("BENCH_SERVE_CHUNK", "64")):
        monkeypatch.setenv(knob, value)
        assert not bench._cacheable(TPU_RESULT), knob
        monkeypatch.delenv(knob)
    assert bench._cacheable(TPU_RESULT)
    spec_row = dict(SERVING_ROW, spec_k=4, spec_steps=78,
                    accepted_tokens_per_dispatch=2.4)
    assert bench._cacheable(spec_row) is False


@pytest.mark.slow
def test_cpu_smoke_spec_and_chunk_leg(tmp_path):
    """End-to-end subprocess (slow tier — the tier-1 fence tests above
    keep the knob fingerprinting gated), ISSUE 20 leg: BENCH_SERVE_SPEC_K=4 +
    BENCH_SERVE_CHUNK=64 on the CPU smoke — the chunk threshold clamps
    to 16 so the smoke's long prompts actually chunk, speculation and
    chunking are BOTH exercised (non-zero spec_steps /
    chunked_admissions), the row carries the full round-20 metric
    surface, the measured window stays retrace-free with the verify and
    chunk grids in the warmup set, and the caches stay untouched."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_NO_SUPERVISE="1",
               BENCH_MODEL="serving",
               BENCH_SERVE_REQUESTS="64",      # clamps to 12
               BENCH_SERVE_QPS="200",
               BENCH_SERVE_TENANTS="3",
               BENCH_SERVE_SPEC_K="4",
               BENCH_SERVE_CHUNK="64",         # clamps to 16
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"),
               BENCH_REPO_CACHE_PATH=str(tmp_path / "repo.json"),
               BENCH_PREWARM_SENTINEL=str(tmp_path / "prewarm"),
               BENCH_START_STAMP=str(tmp_path / "started"),
               BENCH_DEADLINE_S="480")
    out = subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                         env=env, capture_output=True, text=True,
                         timeout=420, cwd=ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["metric"] == "serving_engine_throughput"
    assert row["cpu_smoke"] is True
    assert row["spec_k"] == 4
    assert row["chunk_tokens"] == 16           # the smoke clamp (64 -> 16)
    # speculation ran: dispatches counted, and every dispatch emitted
    # at least its pending token (== 1.0 exactly at zero accepts)
    assert row["spec_steps"] > 0
    assert row["accepted_tokens_per_dispatch"] >= 1.0
    assert 0.0 <= row["spec_acceptance_rate"] <= 1.0
    assert row["draft_overhead"] == 0.0        # n-gram draft: no dispatches
    # chunking ran: the smoke's long prompts admitted in chunks
    assert row["chunked_admissions"] > 0
    assert row["chunk_prefills"] > row["chunked_admissions"]
    assert row["completed"] == 12
    assert row["value"] and row["value"] > 0
    assert row["window_retraces"] == 0         # verify+chunk grids warmed
    assert not os.path.exists(tmp_path / "cache.json")
    assert not os.path.exists(tmp_path / "repo.json")


def test_cpu_smoke_fleet_kill_reroutes_with_zero_drops(tmp_path):
    """End-to-end subprocess, fleet leg (ISSUE 15): 2 replicas behind
    the router, the highest killed at decode step 3 — the row carries
    replicas/reroutes/weight_sync_s with the kill actually fired (zero
    dropped requests: completed == requests), stays labeled cpu_smoke,
    and never touches the caches."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_NO_SUPERVISE="1",
               BENCH_MODEL="serving",
               BENCH_SERVE_REQUESTS="64",      # clamps to 12
               BENCH_SERVE_QPS="200",
               BENCH_SERVE_TENANTS="3",
               BENCH_SERVE_REPLICAS="2",
               BENCH_FLEET_KILL_AT="3",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"),
               BENCH_REPO_CACHE_PATH=str(tmp_path / "repo.json"),
               BENCH_PREWARM_SENTINEL=str(tmp_path / "prewarm"),
               BENCH_START_STAMP=str(tmp_path / "started"),
               BENCH_DEADLINE_S="480")
    out = subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                         env=env, capture_output=True, text=True,
                         timeout=420, cwd=ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["metric"] == "serving_engine_throughput"
    assert row["cpu_smoke"] is True
    assert row["replicas"] == 2
    assert row["fleet_kill_at"] == 3
    # the kill fired under load: in-flight sequences rerouted, none
    # dropped, and a cold replica joined via the tree sync
    assert row["reroutes"] > 0
    assert row["weight_sync_s"] > 0.0
    assert row["completed"] == row["requests"] == 12
    assert row["value"] and row["value"] > 0
    # the initial replicas' measured window stays retrace-free (the
    # joiner's cold compiles are the join's cost, not the window's)
    assert row["window_retraces"] == 0
    assert not os.path.exists(tmp_path / "cache.json")
    assert not os.path.exists(tmp_path / "repo.json")
