"""Speculative decoding + chunked prefill (ISSUE 20 tentpole).

The correctness contract that makes both features safe to ship: greedy
speculative decoding is BIT-IDENTICAL to vanilla greedy decode on
every lane — the verify argmax row is exactly what one-token decode
would have produced, so rejection truncates but never alters the
trajectory — and chunked prefill is indistinguishable from a one-shot
prefill (same pages, logits equal atol 1e-5 at ragged chunk
boundaries).  Covered: solo / batched-ragged / mid-stream-join parity,
parity across a forced same-point eviction, the self-draft
dispatch-count reduction (the perf claim pinned STRUCTURALLY:
ceil(budget / (K+1)) verify dispatches at 100% acceptance), a separate
draft model, chunk-vs-one-shot trajectory and logit parity, prompts
longer than the largest prefill bucket (the ``_bucket`` ValueError
satellite), mid-chunk eviction accounting, and the never-retrace
contract with the spec/chunk programs in the warmup set.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from chainermn_tpu.core.link import extract_state
from chainermn_tpu.models import TransformerLM
from chainermn_tpu.serving import (BlockAllocator, PagedKVCache, Request,
                                   ServingEngine, ngram_propose,
                                   prefill_program, prefix_prefill_program)

VOCAB = 101


def _model(seed=0, **kw):
    # single layer keeps tier-1 compile time down; the combined
    # spec+chunk parity test below re-runs at n_layers=2 so per-layer
    # pool indexing stays covered
    kw.setdefault("n_layers", 1)
    return TransformerLM(n_vocab=VOCAB, d_model=32, n_heads=2,
                         max_len=128, seed=seed, **kw)


def _engine(model, **kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 8)
    # two lanes keeps the per-engine compile count down (batch buckets
    # (1, 2)); the ragged-batch and mid-stream-join tests pass
    # max_batch=4 explicitly for four-lane coverage
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_context", 64)
    return ServingEngine(model, **kw)


def _serve(model, prompts, max_new=8, arrivals=None, **kw):
    eng = _engine(model, **kw)
    for i, p in enumerate(prompts):
        eng.submit(Request(p, max_new_tokens=max_new,
                           arrival_time=0.0 if arrivals is None
                           else arrivals[i]))
    t = 0.0
    while eng.running or eng.prefilling or eng.scheduler.pending():
        eng.step(now=t)
        t += 1.0
    return eng


def _seqs(eng):
    """Final full sequences (original prompt + every generated token),
    keyed by the first prompt token — stable across eviction folding,
    which appends to the prompt but never touches its head."""
    return {int(r.prompt[0]): list(int(x) for x in r.prompt) + r.tokens
            for r in eng.completed}


def _prompts(rng, lengths):
    out = []
    for i, L in enumerate(lengths):
        p = rng.randint(0, VOCAB, L).astype(np.int32)
        p[0] = i   # distinct keys for _seqs
        out.append(p)
    return out


# -- speculative decoding: bit-identity on every lane ------------------------


@pytest.mark.parametrize("spec_k", [1, 4])
def test_spec_solo_bit_identical(spec_k):
    """One lane, every K: the speculative trajectory equals vanilla
    greedy token for token — acceptance only shortens the step count,
    never bends the sequence."""
    model = _model()
    p = _prompts(np.random.RandomState(spec_k), [9])[0]
    van = _serve(model, [p], max_new=10)
    spec = _serve(model, [p], max_new=10, spec_k=spec_k)
    assert _seqs(spec) == _seqs(van)
    assert spec.spec_steps > 0
    assert spec.spec_emitted == 9   # prefill emits token 1 of 10


def test_spec_batched_ragged_bit_identical():
    """Four ragged lanes share the verify batch; per-lane ``n_valid``
    clips each near-budget lane's span and every lane still lands on
    its vanilla trajectory."""
    model = _model()
    prompts = _prompts(np.random.RandomState(0), (4, 9, 14, 19))
    van = _serve(model, prompts, max_new=8, max_batch=4)
    spec = _serve(model, prompts, max_new=8, max_batch=4, spec_k=4)
    assert _seqs(spec) == _seqs(van)
    assert spec.spec_lane_steps >= spec.spec_steps > 0


def test_spec_mid_stream_join_bit_identical():
    """Continuous batching's defining event under speculation: lanes
    join while others are mid-verify (idle lanes ride the bucket with
    start = -1, their span writes dropping); trajectories match the
    vanilla run with the same staggered arrivals."""
    model = _model()
    prompts = _prompts(np.random.RandomState(1), (5, 8, 12, 6))
    arrivals = [0.0, 0.0, 3.0, 5.0]
    van = _serve(model, prompts, max_new=8, arrivals=arrivals,
                 max_batch=4)
    spec = _serve(model, prompts, max_new=8, arrivals=arrivals,
                  max_batch=4, spec_k=3)
    assert _seqs(spec) == _seqs(van)


def test_spec_parity_across_forced_same_point_eviction():
    """Pressure-driven eviction timing is load-dependent (a spec run
    reaches pressure at different steps than a vanilla run), so the pin
    forces the SAME eviction point in both: after three steps the
    youngest running lane is evicted by hand, folds its tokens, and
    recomputes on re-admit — final sequences still match."""
    model = _model()
    prompts = _prompts(np.random.RandomState(2), (6, 10, 15))

    def run(**kw):
        eng = _engine(model, **kw)
        for p in prompts:
            eng.submit(Request(p, max_new_tokens=10))
        t = 0.0
        for _ in range(3):
            eng.step(now=t)
            t += 1.0
        eng._evict(eng.running[-1], t)
        while eng.running or eng.prefilling or eng.scheduler.pending():
            eng.step(now=t)
            t += 1.0
        assert eng.evictions == 1
        assert any(r.preemptions > 0 for r in eng.completed)
        return _seqs(eng)

    assert run(spec_k=4) == run()


def test_self_draft_accepts_everything_and_cuts_dispatches():
    """The dispatch-per-token reduction, pinned structurally: with the
    target as its own draft every proposal verifies, so each dispatch
    emits its full K+1 window and an 8-token decode tail costs exactly
    ceil(8 / 3) = 3 verify dispatches where vanilla pays 8 decode
    steps — same tokens, one third the dispatches."""
    model = _model()
    p = _prompts(np.random.RandomState(3), [8])[0]
    van = _serve(model, [p], max_new=9)
    spec = _serve(model, [p], max_new=9, spec_k=2, draft_model=model)
    assert _seqs(spec) == _seqs(van)
    # prefill emits token 1; the remaining 8 arrive in 3,3,2 windows
    assert spec.spec_steps == 3
    assert spec.spec_proposed == spec.spec_accepted > 0
    assert spec.spec_emitted == 8


def test_separate_draft_model_parity():
    """A draft with DIFFERENT weights proposes junk relative to the
    target; acceptance drops but the emitted trajectory is still the
    target's vanilla greedy — the verify argmax, not the draft, decides
    every token."""
    model = _model()
    draft = _model(seed=1)
    prompts = _prompts(np.random.RandomState(4), (6, 11))
    van = _serve(model, prompts, max_new=8)
    spec = _serve(model, prompts, max_new=8, spec_k=3, draft_model=draft)
    assert _seqs(spec) == _seqs(van)
    assert spec.draft_dispatches > 0
    assert spec.spec_accepted <= spec.spec_proposed


def test_spec_counters_measure_dispatch_economics():
    """The bench columns' sources: every verify dispatch emits at least
    one token (the pending token's argmax is always recorded), so
    accepted_tokens_per_dispatch = emitted / lane_steps >= 1.0 exactly
    when speculation pays for itself and == 1.0 at zero accepts."""
    model = _model()
    prompts = _prompts(np.random.RandomState(5), (5, 9))
    spec = _serve(model, prompts, max_new=8, spec_k=4)
    assert spec.spec_lane_steps >= spec.spec_steps > 0
    assert 0 <= spec.spec_accepted <= spec.spec_proposed
    atpd = spec.spec_emitted / spec.spec_lane_steps
    assert atpd >= 1.0


def test_ngram_self_draft_is_pure_host_lookup():
    """The default draft never dispatches: it is an n-gram suffix match
    over the lane's own history, padded with the last token when the
    history is short or matchless."""
    hist = [1, 2, 3, 1, 2, 3, 1, 2]
    assert list(ngram_propose(hist, 3)) == [3, 1, 2]   # continues the match
    assert list(ngram_propose([7], 2)) == [7, 7]       # degenerate history
    eng = _serve(_model(), _prompts(np.random.RandomState(6), [7]),
                 max_new=6, spec_k=3)
    assert eng.draft_dispatches == 0


# -- chunked prefill ---------------------------------------------------------


def test_chunked_prefill_matches_unchunked_trajectory():
    """Mixed short/long load: prompts above the chunk threshold admit
    in page-multiple chunks interleaved with decode; every request
    lands on the one-shot-prefill trajectory."""
    model = _model()
    prompts = _prompts(np.random.RandomState(7), (5, 20, 50))
    van = _serve(model, prompts, max_new=6)
    chunked = _serve(model, prompts, max_new=6, chunk_tokens=16)
    assert chunked.chunked_admissions >= 2    # the 20- and 50-token prompts
    assert chunked.chunk_prefills > chunked.chunked_admissions
    assert _seqs(chunked) == _seqs(van)


def test_chunk_boundary_logits_match_oneshot():
    """Driving the offset writer directly: a 37-token prompt prefilled
    in 16+16+5 chunks produces, at EVERY chunk boundary, the same
    logits row the one-shot forward puts at that position — atol 1e-5,
    including the ragged 5-token tail."""
    model = _model()
    state = extract_state(model)
    blk = model.blocks[0].attn
    kv = PagedKVCache(len(list(model.blocks)), 64, 8, blk.n_heads,
                      blk.d_head, dtype=jnp.float32)
    alloc = BlockAllocator(64, 8)
    L, chunk = 37, 16
    full = np.random.RandomState(8).randint(0, VOCAB, L).astype(np.int32)
    ref = np.asarray(model.logits(jnp.asarray(full[None])))[0]
    alloc.ensure(0, L + 1)
    row = np.zeros(8, np.int32)               # max_context 64 / page 8
    t = alloc.block_table(0)
    row[:len(t)] = t
    bt = jnp.asarray(row)
    start = 0
    while start < L:
        n = min(chunk, L - start)
        toks = np.zeros((1, chunk), np.int32)
        toks[0, :n] = full[start:start + n]
        if start == 0:
            k, v, logits = prefill_program(
                model, state, kv.k_pool, kv.v_pool, jnp.asarray(toks),
                jnp.int32(n), bt)
        else:
            k, v, logits = prefix_prefill_program(
                model, state, kv.k_pool, kv.v_pool, jnp.asarray(toks),
                jnp.int32(n), jnp.int32(start), bt)
        kv.k_pool, kv.v_pool = k, v
        np.testing.assert_allclose(
            np.asarray(logits), ref[start + n - 1], atol=1e-5,
            err_msg=f"chunk boundary at {start + n}")
        start += n


def test_prompt_longer_than_largest_bucket_serves():
    """The satellite pin: with chunking on, the prefill bucket set
    collapses to (chunk_tokens,) and ``_bucket``'s ValueError is
    unreachable for chunk-admitted prompts — a 50-token prompt (>> the
    16-token bucket) serves to completion on the vanilla trajectory."""
    model = _model()
    prompts = _prompts(np.random.RandomState(9), [50])
    eng = _serve(model, prompts, max_new=6, chunk_tokens=16)
    assert tuple(eng.prefill_buckets) == (16,)
    assert eng.chunked_admissions == 1
    assert _seqs(eng) == _seqs(_serve(model, prompts, max_new=6))


def test_mid_chunk_eviction_frees_pages_and_resets_cursor():
    """A mid-chunk victim holds chunk pages but has produced nothing:
    eviction frees every page (the allocator conserves), the requeue
    resets the chunk cursor to zero, and re-admission replays the whole
    prompt to the vanilla trajectory."""
    model = _model()
    prompts = _prompts(np.random.RandomState(10), [50])
    van = _serve(model, prompts, max_new=6)
    eng = _engine(model, chunk_tokens=16)
    eng.submit(Request(prompts[0], max_new_tokens=6))
    t = 0.0
    for _ in range(3):   # 50 tokens / 16-chunks: prefilling for >= 2 steps
        if eng.prefilling and eng.prefilling[0]._chunk_pos > 0:
            break
        eng.step(now=t)
        t += 1.0
    req = eng.prefilling[0]
    assert 0 < req._chunk_pos < 50     # genuinely MID-chunk
    assert eng.allocator.used_pages > 0
    eng._evict(req, t)
    assert req._chunk_pos == 0
    assert req.preemptions == 1
    assert eng.allocator.used_pages == 0 and eng.allocator.check()
    while eng.running or eng.prefilling or eng.scheduler.pending():
        eng.step(now=t)
        t += 1.0
    assert _seqs(eng) == _seqs(van)


def test_spec_plus_chunk_combined_parity():
    """Both features on at once — chunks interleave with verify steps
    and a long prompt joins lanes already speculating — still the
    vanilla trajectory on every lane."""
    model = _model(n_layers=2)   # multi-layer pool indexing coverage
    prompts = _prompts(np.random.RandomState(11), (5, 40, 9))
    arrivals = [0.0, 1.0, 2.0]
    van = _serve(model, prompts, max_new=8, arrivals=arrivals)
    both = _serve(model, prompts, max_new=8, arrivals=arrivals,
                  spec_k=3, chunk_tokens=16)
    assert both.spec_steps > 0 and both.chunked_admissions == 1
    assert _seqs(both) == _seqs(van)


# -- never-retrace -----------------------------------------------------------


def test_spec_and_chunk_never_retrace_after_warmup():
    """The bucketed-shapes contract extends to the round-20 programs:
    after warmup() has compiled the verify grid per batch bucket and
    the chunk grid per prefill bucket, a staggered load with joins,
    long chunked prompts and a forced evict/rejoin triggers ZERO
    additional traces of any program."""
    model = _model()
    eng = _engine(model, spec_k=3, chunk_tokens=16)
    eng.warmup()
    assert eng.spec_traces > 0 and eng.chunk_traces > 0
    frozen = (eng.prefill_traces, eng.decode_traces, eng.spec_traces,
              eng.chunk_traces)
    rng = np.random.RandomState(12)
    for i in range(6):
        eng.submit(Request(rng.randint(0, VOCAB, int(rng.randint(3, 50))),
                           max_new_tokens=4 + i, arrival_time=float(i)))
    t, evicted = 0.0, False
    while eng.running or eng.prefilling or eng.scheduler.pending():
        eng.step(now=t)
        t += 1.0
        if not evicted and len(eng.running) >= 2:
            eng._evict(eng.running[-1], t)   # an evict/rejoin cycle
            evicted = True
    assert len(eng.completed) == 6 and evicted
    assert (eng.prefill_traces, eng.decode_traces, eng.spec_traces,
            eng.chunk_traces) == frozen


def test_spec_k_env_hatch_and_validation():
    """CHAINERMN_TPU_SERVE_SPEC=off is the operational kill switch —
    construction-time, like the attention hatch — and negative K is a
    construction error."""
    model = _model()
    with pytest.raises(ValueError):
        _engine(model, spec_k=-1)
    import os
    os.environ["CHAINERMN_TPU_SERVE_SPEC"] = "off"
    try:
        eng = _engine(model, spec_k=4)
        assert eng.spec_k == 0
    finally:
        del os.environ["CHAINERMN_TPU_SERVE_SPEC"]
    with pytest.raises(ValueError):   # non-page-multiple chunk
        _engine(model, chunk_tokens=12)
    with pytest.raises(ValueError):   # chunk above max_context
        _engine(model, chunk_tokens=128)
