"""Scheduler policy tests: fairness, backpressure, preemption.

The scheduler is pure host bookkeeping — these tests pin its contract
(deterministic fair rotation, typed bounds, recompute-on-readmit) and
the engine-level consequences (eviction under pool pressure preserves
the greedy trajectory bit-for-bit).
"""

import numpy as np
import pytest

from chainermn_tpu.models import TransformerLM
from chainermn_tpu.serving import (PagePoolExhaustedError,
                                   QueueSaturatedError, Request,
                                   RequestScheduler, ServingEngine)

VOCAB = 97


def _req(tenant, arrival=0.0, n=4, new=4):
    return Request(np.arange(1, n + 1), max_new_tokens=new,
                   tenant=tenant, arrival_time=arrival)


def test_round_robin_is_fair_across_tenants():
    """A flooding tenant cannot starve the others: grants rotate one
    per tenant regardless of queue depths."""
    s = RequestScheduler()
    for _ in range(6):
        s.submit(_req("hog"))
    for _ in range(2):
        s.submit(_req("small"))
    order = []
    while s.pending():
        order.append(s.next_admission().tenant)
    assert order == ["hog", "small", "hog", "small",
                     "hog", "hog", "hog", "hog"]


def test_rotation_cursor_persists_across_passes():
    s = RequestScheduler()
    for t in ("a", "b", "c"):
        s.submit(_req(t))
        s.submit(_req(t))
    first_pass = [s.next_admission().tenant for _ in range(3)]
    second_pass = [s.next_admission().tenant for _ in range(3)]
    assert first_pass == ["a", "b", "c"]
    assert second_pass == ["a", "b", "c"]


def test_open_loop_arrival_gating():
    s = RequestScheduler()
    s.submit(_req("t", arrival=5.0))
    s.submit(_req("u", arrival=1.0))
    assert s.next_admission(arrived_by=0.5) is None
    got = s.next_admission(arrived_by=2.0)
    assert got.tenant == "u"
    assert s.next_admission(arrived_by=2.0) is None  # t not arrived yet
    assert s.next_admission(arrived_by=5.0).tenant == "t"


def test_queue_bound_is_typed_backpressure():
    s = RequestScheduler(max_queue=2)
    s.submit(_req("t"))
    s.submit(_req("t"))
    with pytest.raises(QueueSaturatedError) as ei:
        s.submit(_req("t"))
    assert (ei.value.tenant, ei.value.depth, ei.value.bound) == ("t", 2, 2)
    assert s.rejected == 1
    # other tenants are unaffected (per-tenant bound)
    s.submit(_req("u"))


def test_requeue_front_folds_generated_tokens():
    s = RequestScheduler()
    r = _req("t", n=3, new=6)
    r.tokens = [7, 8]
    r.token_times = [0.1, 0.2]
    s.requeue_front(r)
    assert list(r.prompt) == [1, 2, 3, 7, 8]
    assert r.max_new_tokens == 4
    assert r.tokens == []
    assert r.token_times == [0.1, 0.2]   # production times survive
    assert r.preemptions == 1
    # admission back-off path is not a preemption
    s2 = RequestScheduler()
    r2 = _req("t")
    s2.requeue_front(r2, preempted=False)
    assert r2.preemptions == 0
    # and it really is front-of-line within the tenant
    s.submit(_req("t"))
    assert s.next_admission() is r


def test_zero_token_budget_rejected():
    """max_new_tokens < 1 is a construction error: prefill always
    produces one token, and a 0 budget on an exact-pool-fit prompt
    would livelock admission (the engine sizes by prompt + max_new)."""
    with pytest.raises(ValueError):
        _req("t", new=0)
    with pytest.raises(ValueError):
        _req("t", new=-3)


def test_pick_victim_is_youngest():
    running = [_req("a"), _req("b"), _req("c")]
    assert RequestScheduler.pick_victim(running) is running[-1]
    assert RequestScheduler.pick_victim([]) is None


class _FakeAlloc:
    """unique_pages stub: the only allocator surface pick_victim uses."""

    def __init__(self, unique):
        self._u = unique

    def unique_pages(self, sid):
        return self._u.get(sid, 0)


def test_pick_victim_prefers_mid_chunk_prefilling_youngest_first():
    """Round 20: a mid-chunk prompt holds pages but has produced zero
    tokens — evicting it wastes the least completed work, so the
    prefilling pool is scanned youngest-first BEFORE any decoding
    sequence is considered."""
    running = [_req("a"), _req("b")]
    pre = [_req("p"), _req("q")]
    assert RequestScheduler.pick_victim(running, prefilling=pre) is pre[-1]
    assert RequestScheduler.pick_victim([], prefilling=pre) is pre[-1]
    # empty prefilling degrades to the classic youngest-running policy
    assert RequestScheduler.pick_victim(running, prefilling=[]) \
        is running[-1]


def test_pick_victim_allocator_aware_across_both_pools():
    """The round-14 zero-unique escalation composes with the round-20
    prefilling preference: fully-shared candidates are skipped through
    BOTH pools (prefilling first), and when nobody would free a page
    the typed stall counts every candidate."""
    from chainermn_tpu.serving import EvictionStalledError
    running = [_req("a"), _req("b")]
    pre = [_req("p"), _req("q")]
    unique = {running[0].request_id: 1, running[1].request_id: 1,
              pre[0].request_id: 2, pre[1].request_id: 0}
    # q holds only shared pages: p is next in the prefilling scan
    assert RequestScheduler.pick_victim(
        running, _FakeAlloc(unique), pre) is pre[0]
    unique[pre[0].request_id] = 0
    # both prefilling candidates sterile: fall through to running
    assert RequestScheduler.pick_victim(
        running, _FakeAlloc(unique), pre) is running[-1]
    with pytest.raises(EvictionStalledError) as ei:
        RequestScheduler.pick_victim(
            running, _FakeAlloc({}), pre)
    assert ei.value.n_running == 4   # counts BOTH pools


def test_requeue_front_resets_chunk_cursor():
    """Round 20: the chunk cursor is only meaningful while the engine
    holds the chunk pages — ANY path back to the queue (preemption or
    admission back-off) must reset it so re-admission restarts from
    chunk zero against freshly-allocated pages."""
    s = RequestScheduler()
    r = _req("t", n=3, new=6)
    r._chunk_pos = 24
    s.requeue_front(r)
    assert r._chunk_pos == 0
    r2 = _req("t")
    r2._chunk_pos = 8
    s.requeue_front(r2, preempted=False)
    assert r2._chunk_pos == 0


# -- engine-level consequences ------------------------------------------------


def _model():
    return TransformerLM(n_vocab=VOCAB, d_model=32, n_heads=2,
                         n_layers=2, max_len=128, seed=0)


def test_engine_rejects_impossible_requests_typed():
    eng = ServingEngine(_model(), num_pages=4, page_size=8, max_batch=2,
                        max_context=64)
    with pytest.raises(ValueError):   # exceeds max_context outright
        eng.submit(Request(np.arange(1, 60), max_new_tokens=10))
    with pytest.raises(PagePoolExhaustedError):  # bigger than the POOL
        eng.submit(Request(np.arange(1, 40), max_new_tokens=2))


def test_engine_rejects_requests_that_would_outgrow_the_pool():
    """The livelock guard: a request whose PROMPT fits but whose full
    context (prompt + max_new) exceeds the pool must be rejected typed
    at submit — admitted, it would grow to exhaustion, evict itself
    (eviction frees only other sequences' pages), fold, re-admit into
    the same wall forever."""
    eng = ServingEngine(_model(), num_pages=4, page_size=8, max_batch=2,
                        max_context=64)   # pool = 32 positions
    with pytest.raises(PagePoolExhaustedError) as ei:
        eng.submit(Request(np.arange(1, 31), max_new_tokens=30))
    assert ei.value.requested == 8        # pages_for(60)
    assert ei.value.total == 4
    # the boundary case still fits: 30 + 2 = 32 positions = the pool
    eng.submit(Request(np.arange(1, 31), max_new_tokens=2))
    eng.drain(now=0.0, max_steps=50)
    assert len(eng.completed) == 1
    assert len(eng.completed[0].tokens) == 2


def test_preemption_by_eviction_preserves_trajectory():
    """Pool pressure: the youngest running sequence is evicted (typed
    scheduling event, not an error), recomputed on re-admit, and every
    request's final token sequence is IDENTICAL to an uncontended
    big-pool run — preemption costs time, never correctness."""
    model = _model()
    prompts = [np.random.RandomState(i).randint(0, VOCAB, 16)
               .astype(np.int32) for i in range(3)]

    def run(num_pages):
        eng = ServingEngine(model, num_pages=num_pages, page_size=8,
                            max_batch=4, max_context=64)
        for p in prompts:
            eng.submit(Request(p, max_new_tokens=16))
        eng.drain(now=0.0, max_steps=500)
        assert len(eng.completed) == 3
        assert eng.allocator.used_pages == 0 and eng.allocator.check()
        out = {}
        for r in eng.completed:
            key = tuple(r.prompt[:16])
            out[key] = list(r.prompt[16:]) + r.tokens  # folded + tail
        return eng, out

    tight_eng, tight = run(num_pages=6)    # 48 slots for 3×32 positions
    big_eng, big = run(num_pages=64)
    assert tight_eng.evictions > 0         # pressure actually happened
    assert big_eng.evictions == 0
    assert tight == big
    assert any(r.preemptions > 0 for r in tight_eng.completed)


def test_fairness_survives_engine_loop():
    """Two tenants, one flooding: completion interleaving shows the
    round-robin — the flood tenant never gets two admissions while the
    other has one waiting."""
    model = _model()
    eng = ServingEngine(model, num_pages=32, page_size=8, max_batch=2,
                        max_context=32)
    rng = np.random.RandomState(0)
    for i in range(4):
        eng.submit(Request(rng.randint(0, VOCAB, 8), max_new_tokens=2,
                           tenant="hog", arrival_time=0.0))
    eng.submit(Request(rng.randint(0, VOCAB, 8), max_new_tokens=2,
                       tenant="small", arrival_time=0.0))
    admit_order = []
    orig = eng._admit

    def spy(req, clock):
        admit_order.append(req.tenant)
        return orig(req, clock)

    eng._admit = spy
    eng.drain(now=0.0)
    assert len(eng.completed) == 5
    # the small tenant's lone request is admitted in the first rotation
    assert "small" in admit_order[:2]
