"""Bucketed gradient exchange: the plan's cross-rank contract.

The per-bucket collectives only line up across ranks because every rank
traces the IDENTICAL partition from the identical (shapes, dtypes,
bound) inputs — these tests pin the properties that contract rests on
(ISSUE 5 satellite: every leaf in exactly one bucket, deterministic
order), plus the knob plumbing.  Numeric equivalence of the exchange
flavors lives in tests/core_tests/test_exchange_equivalence.py.
"""

import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu.communicators._memory_utility import (
    DEFAULT_BUCKET_MB, bucket_table, exchanged_bytes, plan_buckets)


def _random_cases(n_cases=30, seed=0):
    rng = np.random.RandomState(seed)
    dtypes = ["float32", "bfloat16", "float16", "int32"]
    for _ in range(n_cases):
        n = int(rng.randint(1, 40))
        shapes = []
        dts = []
        for _ in range(n):
            nd = int(rng.randint(0, 4))
            shapes.append(tuple(int(s) for s in rng.randint(1, 40, nd)))
            dts.append(dtypes[int(rng.randint(len(dtypes)))])
        bound = int(rng.choice([64, 512, 4096, 1 << 20]))
        yield shapes, dts, bound


def test_every_leaf_in_exactly_one_bucket():
    for shapes, dts, bound in _random_cases():
        buckets = plan_buckets(shapes, dts, bound)
        flat = [i for b in buckets for i in b]
        assert sorted(flat) == list(range(len(shapes))), \
            (shapes, dts, bound)


def test_reverse_registration_order():
    """Buckets are emitted last-registered-parameter first, and leaves
    within and across buckets stay in strict reverse leaf order — the
    property that lets early buckets close while earlier layers'
    gradients are still being computed."""
    for shapes, dts, bound in _random_cases(seed=1):
        buckets = plan_buckets(shapes, dts, bound)
        flat = [i for b in buckets for i in b]
        assert flat == list(reversed(range(len(shapes))))


def test_deterministic_across_calls():
    """Pure function of the inputs: two traces (two ranks) produce the
    identical plan."""
    for shapes, dts, bound in _random_cases(n_cases=10, seed=2):
        assert plan_buckets(shapes, dts, bound) == \
            plan_buckets(list(shapes), list(dts), bound)


def test_size_bound_and_dtype_purity():
    import jax.numpy as jnp
    for shapes, dts, bound in _random_cases(seed=3):
        for b in plan_buckets(shapes, dts, bound):
            leaf_bytes = [int(np.prod(shapes[i]))
                          * jnp.dtype(dts[i]).itemsize for i in b]
            # a bucket exceeds the bound only as a single oversize leaf
            assert sum(leaf_bytes) <= bound or len(b) == 1
            assert len({jnp.dtype(dts[i]) for i in b}) == 1


def test_bucket_table_accounts_every_byte():
    shapes = [(128, 4), (33,), (), (256,)]
    dts = ["float32"] * 4
    rows = bucket_table(shapes, dts, 1024)
    assert sum(r["elems"] for r in rows) == sum(
        int(np.prod(s)) for s in shapes)
    assert all(r["bytes"] == r["elems"] * 4 for r in rows)


def test_plan_rejects_nonpositive_bound():
    with pytest.raises(ValueError):
        plan_buckets([(4,)], ["float32"], 0)


def test_exchanged_bytes_formulas():
    # ring accounting: allreduce = 2·(n-1)/n, rs/ag = (n-1)/n, 1 rank = 0
    assert exchanged_bytes(800, 8, "psum") == 1400
    assert exchanged_bytes(800, 8, "reduce_scatter") == 700
    assert exchanged_bytes(800, 8, "all_gather") == 700
    assert exchanged_bytes(800, 1, "psum") == 0
    with pytest.raises(ValueError):
        exchanged_bytes(8, 8, "alltoall")


def test_communicator_bucket_knobs():
    comm = ct.create_communicator("jax_ici",
                                  batch_collectives="bucketed")
    assert comm.exchange == "bucketed"
    assert comm.bucket_mb == DEFAULT_BUCKET_MB
    comm = ct.create_communicator("jax_ici",
                                  batch_collectives="bucketed",
                                  bucket_mb=0.5)
    assert comm.bucket_mb == 0.5
    assert ct.create_communicator("jax_ici").exchange == "flat"
    assert ct.create_communicator("naive").exchange == "per_leaf"
    with pytest.raises(ValueError, match="batch_collectives"):
        ct.create_communicator("jax_ici", batch_collectives="chunky")
    with pytest.raises(ValueError, match="bucket_mb"):
        ct.create_communicator("jax_ici", bucket_mb=-1)


def test_bucket_mb_env_knob(monkeypatch):
    monkeypatch.setenv("CHAINERMN_TPU_BUCKET_MB", "2.5")
    comm = ct.create_communicator("jax_ici",
                                  batch_collectives="bucketed")
    assert comm.bucket_mb == 2.5
    # explicit argument wins over the env
    comm = ct.create_communicator("jax_ici",
                                  batch_collectives="bucketed",
                                  bucket_mb=1.0)
    assert comm.bucket_mb == 1.0


def test_split_propagates_bucket_config():
    comm = ct.create_communicator("jax_ici",
                                  batch_collectives="bucketed",
                                  bucket_mb=2.0)
    subs = comm.split_all(0, 0)
    assert all(s.batch_collectives == "bucketed" and s.bucket_mb == 2.0
               for s in subs)


def test_grad_buckets_matches_plan():
    """grad_buckets (what probes/tests census) is the SAME plan the hot
    path traces, for all three exchange flavors."""
    shapes = [(100,), (200,), (300,)]
    dts = ["float32"] * 3
    comm = ct.create_communicator("jax_ici", batch_collectives="bucketed",
                                  bucket_mb=1600 / 2 ** 20)
    assert comm.grad_buckets(shapes, dts) == \
        plan_buckets(shapes, dts, 1600)
    flat = ct.create_communicator("jax_ici")
    assert flat.grad_buckets(shapes, dts) == [[2, 1, 0]]
    naive = ct.create_communicator("naive")
    assert naive.grad_buckets(shapes, dts) == [[2], [1], [0]]
