"""pack/unpack utilities (N2 parity surface)."""

import jax.numpy as jnp
import numpy as np

from chainermn_tpu.communicators._memory_utility import (
    pack_params, tree_pack, tree_unpack, unpack_params)
from chainermn_tpu.core.link import Parameter


def test_tree_pack_roundtrip():
    tree = {"a": jnp.arange(6.0).reshape(2, 3),
            "b": jnp.ones((4,), jnp.float32)}
    flat, spec = tree_pack(tree)
    assert flat.shape == (10,)
    back = tree_unpack(flat, spec)
    np.testing.assert_array_equal(np.asarray(back["a"]),
                                  np.asarray(tree["a"]))
    np.testing.assert_array_equal(np.asarray(back["b"]),
                                  np.asarray(tree["b"]))


def test_tree_pack_dtype_cast():
    tree = [jnp.ones((3,), jnp.float32)]
    flat, spec = tree_pack(tree, dtype=jnp.bfloat16)
    assert flat.dtype == jnp.bfloat16
    back = tree_unpack(flat, spec)
    assert back[0].dtype == jnp.float32  # restored per-leaf dtype


def test_pack_unpack_params_grads():
    ps = [Parameter(jnp.zeros((2, 2))), Parameter(jnp.zeros((3,)))]
    ps[0].grad = jnp.full((2, 2), 2.0)
    ps[1].grad = jnp.full((3,), 3.0)
    flat, spec = pack_params(ps, "grad")
    assert flat.shape == (7,)
    unpack_params(ps, flat * 2, spec, "grad")
    np.testing.assert_allclose(np.asarray(ps[0].grad), 4.0)
    np.testing.assert_allclose(np.asarray(ps[1].grad), 6.0)


def test_tree_pack_roundtrip_randomized_structures():
    """Property sweep over the structures ZeRO flattens: random nesting,
    shapes (incl. 0-d and empty), mixed dtypes — pack→unpack is the
    identity on values, shapes, dtypes, and tree structure."""
    import jax
    import numpy as np
    from chainermn_tpu.communicators._memory_utility import (tree_pack,
                                                             tree_unpack)
    rng = np.random.RandomState(0)
    dtypes = [np.float32, np.float16, np.int32]
    for case in range(20):
        n_leaves = rng.randint(1, 7)
        leaves = {}
        for i in range(n_leaves):
            nd = rng.randint(0, 4)
            shape = tuple(int(s) for s in rng.randint(0, 5, nd))
            dt = dtypes[rng.randint(len(dtypes))]
            arr = (rng.randint(-100, 100, shape).astype(dt)
                   if dt == np.int32
                   else rng.normal(0, 1, shape).astype(dt))
            # random nesting: half the leaves go under a sub-dict
            if i % 2:
                leaves.setdefault("sub", {})[f"l{i}"] = jnp.asarray(arr)
            else:
                leaves[f"l{i}"] = jnp.asarray(arr)
        flat, spec = tree_pack(leaves)
        assert flat.ndim == 1
        assert flat.shape[0] == sum(
            int(np.prod(l.shape)) for l in jax.tree.leaves(leaves))
        out = tree_unpack(flat, spec)
        assert jax.tree.structure(out) == jax.tree.structure(leaves)
        for a, b in zip(jax.tree.leaves(leaves), jax.tree.leaves(out)):
            assert a.shape == b.shape and a.dtype == b.dtype, case
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_tree_pack_empty_tree():
    from chainermn_tpu.communicators._memory_utility import (tree_pack,
                                                             tree_unpack)
    flat, spec = tree_pack({})
    assert flat.shape == (0,)
    assert tree_unpack(flat, spec) == {}


def test_pad_to_multiple():
    from chainermn_tpu.communicators._memory_utility import pad_to_multiple
    v = jnp.arange(10.0)
    padded, n = pad_to_multiple(v, 4)
    assert padded.shape == (12,) and n == 10
    np.testing.assert_array_equal(np.asarray(padded[10:]), 0.0)
    same, n = pad_to_multiple(v, 5)
    assert same is v and n == 10  # already a multiple: no copy


def test_hierarchical_exchanged_bytes_per_hop():
    """ISSUE 6 satellite: the per-hop rs+ag byte accounting — DCN only
    ever carries the 1/intra chunk."""
    from chainermn_tpu.communicators._memory_utility import (
        hierarchical_exchanged_bytes)
    # 800 bytes over 4×2: ici rs+ag = 2·800·3/4 = 1200; dcn allreduce
    # on the 200-byte chunk = 2·200·1/2 = 200
    hops = hierarchical_exchanged_bytes(800, 4, 2, "psum")
    assert hops == {"ici": 1200, "dcn": 200}
    # reduce-scatter / all-gather: one crossing per hop
    assert hierarchical_exchanged_bytes(800, 4, 2, "reduce_scatter") == \
        {"ici": 600, "dcn": 100}
    assert hierarchical_exchanged_bytes(800, 4, 2, "all_gather") == \
        {"ici": 600, "dcn": 100}


def test_hierarchical_bytes_ring_identity():
    """The hierarchy relocates bytes onto the fast wires without adding
    any: hop totals equal the flat ring figure over intra·inter ranks."""
    from chainermn_tpu.communicators._memory_utility import (
        exchanged_bytes, hierarchical_exchanged_bytes)
    for n, intra, inter in ((1 << 20, 4, 2), (1 << 16, 8, 4),
                            (960, 4, 4)):
        hops = hierarchical_exchanged_bytes(n, intra, inter, "psum")
        assert hops["ici"] + hops["dcn"] == \
            exchanged_bytes(n, intra * inter, "psum"), (n, intra, inter)


def test_hierarchical_bytes_degenerate_and_dtype():
    from chainermn_tpu.communicators._memory_utility import (
        hierarchical_exchanged_bytes)
    # one host (inter=1): nothing crosses DCN
    assert hierarchical_exchanged_bytes(800, 4, 1, "psum")["dcn"] == 0
    # one device per host (intra=1): ICI moves nothing, DCN carries all
    hops = hierarchical_exchanged_bytes(800, 1, 8, "psum")
    assert hops["ici"] == 0 and hops["dcn"] == 1400
    # per-hop dtype override: a bf16 DCN chunk halves only the slow hop
    f32 = hierarchical_exchanged_bytes(800, 4, 2, "psum")
    bf16 = hierarchical_exchanged_bytes(800, 4, 2, "psum",
                                        dcn_n_bytes=100)
    assert bf16["ici"] == f32["ici"]
    assert bf16["dcn"] * 2 == f32["dcn"]
    # guardrails
    import pytest
    with pytest.raises(ValueError, match="divisible"):
        hierarchical_exchanged_bytes(801, 4, 2)
    with pytest.raises(ValueError, match="collective"):
        hierarchical_exchanged_bytes(800, 4, 2, "alltoall")
    with pytest.raises(ValueError, match=">= 1"):
        hierarchical_exchanged_bytes(800, 0, 2)


def test_hop_schedule_slow_hop_first():
    """The emission schedule the hierarchical grad_transform follows:
    per-bucket dataflow order, buckets in plan order, and EVERY dcn op
    before ANY fast-hop all_gather."""
    from chainermn_tpu.communicators._memory_utility import hop_schedule
    assert hop_schedule(0) == []
    for k in (1, 2, 5):
        sched = hop_schedule(k)
        assert len(sched) == 3 * k
        pos = {}
        for i, (op, b) in enumerate(sched):
            pos[(op, b)] = i
        for b in range(k):
            assert pos[("ici_reduce_scatter", b)] \
                < pos[("dcn_exchange", b)] \
                < pos[("ici_all_gather", b)]
            if b:
                assert pos[("dcn_exchange", b - 1)] \
                    < pos[("dcn_exchange", b)]
        last_dcn = max(pos[("dcn_exchange", b)] for b in range(k))
        first_ag = min(pos[("ici_all_gather", b)] for b in range(k))
        assert last_dcn < first_ag
    import pytest
    with pytest.raises(ValueError):
        hop_schedule(-1)


def test_orthogonal_initializer():
    from chainermn_tpu.nn.initializers import Orthogonal
    W = Orthogonal()((6, 6), np.float32, np.random.RandomState(0))
    np.testing.assert_allclose(W @ W.T, np.eye(6), atol=1e-5)
