"""pack/unpack utilities (N2 parity surface)."""

import jax.numpy as jnp
import numpy as np

from chainermn_tpu.communicators._memory_utility import (
    pack_params, tree_pack, tree_unpack, unpack_params)
from chainermn_tpu.core.link import Parameter


def test_tree_pack_roundtrip():
    tree = {"a": jnp.arange(6.0).reshape(2, 3),
            "b": jnp.ones((4,), jnp.float32)}
    flat, spec = tree_pack(tree)
    assert flat.shape == (10,)
    back = tree_unpack(flat, spec)
    np.testing.assert_array_equal(np.asarray(back["a"]),
                                  np.asarray(tree["a"]))
    np.testing.assert_array_equal(np.asarray(back["b"]),
                                  np.asarray(tree["b"]))


def test_tree_pack_dtype_cast():
    tree = [jnp.ones((3,), jnp.float32)]
    flat, spec = tree_pack(tree, dtype=jnp.bfloat16)
    assert flat.dtype == jnp.bfloat16
    back = tree_unpack(flat, spec)
    assert back[0].dtype == jnp.float32  # restored per-leaf dtype


def test_pack_unpack_params_grads():
    ps = [Parameter(jnp.zeros((2, 2))), Parameter(jnp.zeros((3,)))]
    ps[0].grad = jnp.full((2, 2), 2.0)
    ps[1].grad = jnp.full((3,), 3.0)
    flat, spec = pack_params(ps, "grad")
    assert flat.shape == (7,)
    unpack_params(ps, flat * 2, spec, "grad")
    np.testing.assert_allclose(np.asarray(ps[0].grad), 4.0)
    np.testing.assert_allclose(np.asarray(ps[1].grad), 6.0)


def test_tree_pack_roundtrip_randomized_structures():
    """Property sweep over the structures ZeRO flattens: random nesting,
    shapes (incl. 0-d and empty), mixed dtypes — pack→unpack is the
    identity on values, shapes, dtypes, and tree structure."""
    import jax
    import numpy as np
    from chainermn_tpu.communicators._memory_utility import (tree_pack,
                                                             tree_unpack)
    rng = np.random.RandomState(0)
    dtypes = [np.float32, np.float16, np.int32]
    for case in range(20):
        n_leaves = rng.randint(1, 7)
        leaves = {}
        for i in range(n_leaves):
            nd = rng.randint(0, 4)
            shape = tuple(int(s) for s in rng.randint(0, 5, nd))
            dt = dtypes[rng.randint(len(dtypes))]
            arr = (rng.randint(-100, 100, shape).astype(dt)
                   if dt == np.int32
                   else rng.normal(0, 1, shape).astype(dt))
            # random nesting: half the leaves go under a sub-dict
            if i % 2:
                leaves.setdefault("sub", {})[f"l{i}"] = jnp.asarray(arr)
            else:
                leaves[f"l{i}"] = jnp.asarray(arr)
        flat, spec = tree_pack(leaves)
        assert flat.ndim == 1
        assert flat.shape[0] == sum(
            int(np.prod(l.shape)) for l in jax.tree.leaves(leaves))
        out = tree_unpack(flat, spec)
        assert jax.tree.structure(out) == jax.tree.structure(leaves)
        for a, b in zip(jax.tree.leaves(leaves), jax.tree.leaves(out)):
            assert a.shape == b.shape and a.dtype == b.dtype, case
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_tree_pack_empty_tree():
    from chainermn_tpu.communicators._memory_utility import (tree_pack,
                                                             tree_unpack)
    flat, spec = tree_pack({})
    assert flat.shape == (0,)
    assert tree_unpack(flat, spec) == {}


def test_orthogonal_initializer():
    from chainermn_tpu.nn.initializers import Orthogonal
    W = Orthogonal()((6, 6), np.float32, np.random.RandomState(0))
    np.testing.assert_allclose(W @ W.T, np.eye(6), atol=1e-5)
