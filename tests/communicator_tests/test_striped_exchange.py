"""Striped multi-path exchange: the plan's cross-rank contract
(ISSUE 11).

The two slices' collectives only line up across ranks because every
rank traces the IDENTICAL split from the identical ``(n_elems, ratio)``
inputs — these tests pin the properties that contract rests on (every
element in exactly one slice, contiguity, the committed ratio honored,
degenerate collapse, cross-process determinism), the generalized
striped ``hop_schedule`` ordering, the per-path byte identities, and
the knob plumbing.  Numeric equivalence of the striped exchange lives
in tests/core_tests/test_exchange_equivalence.py; the traced per-path
structure is gated by tests/test_comm_budget.py.
"""

import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu.communicators import EXCHANGES, exchange_knobs
from chainermn_tpu.communicators._memory_utility import (
    DEFAULT_STRIPE_RATIO, exchanged_bytes, hop_schedule, stripe_plan,
    striped_exchanged_bytes)


def test_every_element_in_exactly_one_slice():
    rng = np.random.RandomState(0)
    for _ in range(50):
        n = int(rng.randint(0, 1 << 20))
        ratio = float(rng.uniform(0, 1))
        n_i, n_d = stripe_plan(n, ratio)
        assert n_i >= 0 and n_d >= 0
        assert n_i + n_d == n, (n, ratio)


def test_ratio_respected():
    """The DCN share is the committed ratio rounded to whole elements
    — never off by more than the rounding of one element."""
    rng = np.random.RandomState(1)
    for _ in range(50):
        n = int(rng.randint(1, 1 << 20))
        ratio = float(rng.uniform(0, 1))
        _, n_d = stripe_plan(n, ratio)
        assert n_d == int(round(ratio * n))
        assert abs(n_d - ratio * n) <= 0.5


def test_degenerate_ratios_collapse_to_single_path():
    """ratio 0 == the strict hierarchical plan (everything on the
    fast-hop-major path); ratio 1 routes the whole payload over the
    slow-hop-major path — the one-fabric flat shape with DCN as the
    bulk wire."""
    for n in (0, 1, 17, 4096):
        assert stripe_plan(n, 0.0) == (n, 0)
        assert stripe_plan(n, 1.0) == (0, n)


def test_cross_process_determinism():
    """Pure function of the inputs: two traces (two ranks) produce the
    identical split — including at awkward float ratios."""
    for n in (7, 1000, 999999):
        for ratio in (0.1, 0.25, 1 / 3, 0.5, 0.75):
            assert stripe_plan(n, ratio) == stripe_plan(n, ratio)


def test_stripe_plan_rejects_bad_inputs():
    with pytest.raises(ValueError, match="ratio"):
        stripe_plan(10, -0.1)
    with pytest.raises(ValueError, match="ratio"):
        stripe_plan(10, 1.1)
    with pytest.raises(ValueError, match="n_elems"):
        stripe_plan(-1, 0.5)


def test_striped_hop_schedule_ordering():
    """The striped schedule's contract: per path dataflow order holds,
    the slow path's op leads each phase, and EVERY scatter/exchange op
    of both paths precedes ANY bucket's gather epilogue (the
    concurrency window the census hop_ordered gate validates)."""
    assert hop_schedule(0, mode="striped") == []
    for k in (1, 2, 5):
        sched = hop_schedule(k, mode="striped")
        assert len(sched) == 6 * k
        pos = {pair: i for i, pair in enumerate(sched)}
        for b in range(k):
            # per-path dataflow
            assert pos[("dcn_path_scatter", b)] \
                < pos[("dcn_path_exchange", b)] \
                < pos[("dcn_path_gather", b)]
            assert pos[("ici_path_scatter", b)] \
                < pos[("ici_path_exchange", b)] \
                < pos[("ici_path_gather", b)]
            # slow path leads each phase of its bucket
            assert pos[("dcn_path_scatter", b)] \
                < pos[("ici_path_scatter", b)]
            assert pos[("dcn_path_gather", b)] \
                < pos[("ici_path_gather", b)]
        last_phase1 = max(pos[(op, b)] for b in range(k)
                          for op in ("dcn_path_scatter", "ici_path_scatter",
                                     "dcn_path_exchange",
                                     "ici_path_exchange"))
        first_gather = min(pos[(op, b)] for b in range(k)
                           for op in ("dcn_path_gather",
                                      "ici_path_gather"))
        assert last_phase1 < first_gather
    with pytest.raises(ValueError, match="mode"):
        hop_schedule(1, mode="diagonal")


def test_striped_bytes_conservation_and_share():
    """The per-path accounting's two identities, exact on cleanly
    dividing splits: path totals sum to the flat allreduce figure over
    intra×inter ranks, and the DCN path's share IS the ratio."""
    for n, intra, inter, ratio in ((3200, 4, 2, 0.25),
                                   (3200, 4, 2, 0.5),
                                   (1 << 20, 8, 4, 0.75)):
        paths = striped_exchanged_bytes(n, intra, inter, ratio)
        total = paths["ici_path"]["total"] + paths["dcn_path"]["total"]
        assert total == exchanged_bytes(n, intra * inter, "psum"), \
            (n, intra, inter, ratio)
        assert paths["dcn_path"]["total"] / total == ratio
        # fabric split inside each path: the ICI path's bulk rides ici,
        # the DCN path's bulk rides dcn
        assert paths["ici_path"]["ici"] > paths["ici_path"]["dcn"] \
            or ratio == 1.0
        assert paths["dcn_path"]["dcn"] > paths["dcn_path"]["ici"]


def test_striped_bytes_degenerate_ratios():
    flat = exchanged_bytes(3200, 8, "psum")
    r0 = striped_exchanged_bytes(3200, 4, 2, 0.0)
    assert r0["dcn_path"]["total"] == 0
    assert r0["ici_path"]["total"] == flat
    r1 = striped_exchanged_bytes(3200, 4, 2, 1.0)
    assert r1["ici_path"]["total"] == 0
    assert r1["dcn_path"]["total"] == flat


def test_striped_bytes_dcn_dtype_halves_only_dcn_fabric():
    f32 = striped_exchanged_bytes(3200, 4, 2, 0.5)
    bf16 = striped_exchanged_bytes(3200, 4, 2, 0.5, dcn_itemsize=2)
    # ICI-fabric crossings untouched on both paths
    assert bf16["ici_path"]["ici"] == f32["ici_path"]["ici"]
    assert bf16["dcn_path"]["ici"] == f32["dcn_path"]["ici"]
    # DCN-fabric crossings halve on both paths
    assert bf16["ici_path"]["dcn"] * 2 == f32["ici_path"]["dcn"]
    assert bf16["dcn_path"]["dcn"] * 2 == f32["dcn_path"]["dcn"]


# -- knob plumbing -----------------------------------------------------------

def test_communicator_stripe_knobs():
    comm = ct.create_communicator("hierarchical", inter_size=2,
                                  stripe_ratio=0.25)
    assert comm.striped and comm.stripe_ratio == 0.25
    assert comm.topology == "striped"
    # ratio 0 is the strict hierarchical schedule
    comm = ct.create_communicator("hierarchical", inter_size=2,
                                  stripe_ratio=0.0)
    assert not comm.striped and comm.topology == "hierarchical"
    with pytest.raises(ValueError, match="stripe_ratio"):
        ct.create_communicator("hierarchical", inter_size=2,
                               stripe_ratio=1.5)
    # a flat mesh has one fabric: nothing to stripe
    with pytest.raises(ValueError, match="stripe_ratio"):
        ct.create_communicator("jax_ici", stripe_ratio=0.5)


def test_stripe_ratio_env_knob(monkeypatch):
    monkeypatch.setenv("CHAINERMN_TPU_STRIPE_RATIO", "0.5")
    comm = ct.create_communicator("hierarchical", inter_size=2)
    assert comm.striped and comm.stripe_ratio == 0.5
    # explicit argument wins over the env
    comm = ct.create_communicator("hierarchical", inter_size=2,
                                  stripe_ratio=0.25)
    assert comm.stripe_ratio == 0.25
    # a flat communicator never reads the knob (nothing to stripe —
    # a stray env var must not break the flat flavors)
    flat = ct.create_communicator("jax_ici")
    assert not flat.striped and flat.stripe_ratio == 0.0


def test_hierarchy_flat_hatch_drops_striping(monkeypatch):
    """CHAINERMN_TPU_HIERARCHY=flat degrades a striped communicator to
    the flat single-path exchange — loudly, never silently."""
    monkeypatch.setenv("CHAINERMN_TPU_HIERARCHY", "flat")
    from chainermn_tpu import communicators as C
    monkeypatch.setattr(C, "_WARNED_FLAT_STRIPES", set())
    with pytest.warns(UserWarning, match="stripe_ratio"):
        comm = ct.create_communicator("hierarchical", inter_size=2,
                                      stripe_ratio=0.25)
    assert comm.hierarchy is None and not comm.striped
    assert comm.topology == "flat"


def test_exchange_vocabulary_and_knobs():
    assert "striped" in EXCHANGES and "striped_rs" in EXCHANGES
    assert exchange_knobs("striped") == ("hierarchical", True, "allreduce")
    assert exchange_knobs("striped_rs") == \
        ("hierarchical", True, "reduce_scatter")
    assert DEFAULT_STRIPE_RATIO == 0.25


def test_grad_dcn_stale_len_matches_plan():
    """The DCN-slice stale buffer's length is the sum of the buckets'
    DCN-path slices — the stripe_ratio fraction of the gradient, the
    footprint claim of the dcn-only double-buffering variant."""
    from chainermn_tpu.models import MLP
    comm = ct.create_communicator("hierarchical", inter_size=2,
                                  stripe_ratio=0.5)
    model = MLP(n_units=16, n_out=4, seed=0)
    # materialize params
    import jax.numpy as jnp
    model(jnp.zeros((2, 8), jnp.float32))
    shapes, dtypes = comm.grad_leaf_specs(model)
    from chainermn_tpu.communicators._memory_utility import stripe_plan
    expect = sum(
        stripe_plan(sum(int(np.prod(shapes[i])) for i in idx), 0.5)[1]
        for idx in comm.grad_buckets(shapes, dtypes))
    assert comm.grad_dcn_stale_len_for(model) == expect
    assert expect > 0
    flat = ct.create_communicator("jax_ici")
    assert flat.grad_dcn_stale_len_for(model) == 0
