"""Self-tuning communicator (ISSUE 19): measure → agree → plan → apply.

The contract these tests pin, layer by layer:

* the PURE derivation pieces (``derived_stripe_ratio`` /
  ``derived_bucket_bytes`` in ``_memory_utility`` — satellite 1's
  extraction) obey their documented properties: §10's ``r*`` recovers
  the committed 0.25 seed at the 1:3 ratio, is monotone in B_dcn, and
  is clamped to the open interval; the bucket rule amortizes
  bandwidth×latency with hard [1, 32] MB clamps;
* ``derive_exchange_plan`` is DETERMINISTIC — byte-identical
  fingerprints regardless of dict insertion order or a JSON round-trip
  (the property the cross-rank gate rests on);
* ``agree_exchange_plan`` over the real (simulated-mesh) comm records
  the plan artifact, and under injected rank skew the RANK-0 broadcast
  wins with a warning + divergence counter, never a silent
  split-brain;
* ``autotune=`` at the factory applies the agreed plan ONLY to knobs
  the caller left free (hand knobs always win), and the golden
  trajectory of an autotuned run is BITWISE equal to the equivalent
  hand-knobbed run;
* online mode reads bandwidth off the tracer's payload-tagged
  ``train/grad_exchange`` spans (the satellite-6 attribute, asserted
  here on a live eager trace);
* an elastic ``change_communicator`` re-tunes: one fresh plan artifact
  per mesh, new fingerprint.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import chainermn_tpu as ct
from chainermn_tpu import L
from chainermn_tpu import observability as obs
from chainermn_tpu.communicators import _autotune
from chainermn_tpu.communicators._autotune import (agree_exchange_plan,
                                                   derive_exchange_plan,
                                                   measurements_from_trace,
                                                   plan_fingerprint,
                                                   reduce_measurements,
                                                   topology_summary)
from chainermn_tpu.communicators._memory_utility import (
    DEFAULT_BUCKET_MB, DEFAULT_STRIPE_RATIO, derived_bucket_bytes,
    derived_stripe_ratio)
from chainermn_tpu.core.optimizer import MomentumSGD, SGD
from chainermn_tpu.models import MLP, Classifier

# the fixed reference measurements the derivation tests key off: ICI
# 3x the DCN bandwidth (the committed 1:3 seed), DCN the slow hop
FIXED_HIER = {"source": "startup", "probe_mb": 1.0, "iters": 4,
              "hops": {"ici": {"size": 4, "gbps": 3.0, "lat_us": 50.0},
                       "dcn": {"size": 2, "gbps": 1.0, "lat_us": 200.0}}}
FIXED_FLAT = {"source": "startup", "probe_mb": 1.0, "iters": 4,
              "hops": {"world": {"size": 8, "gbps": 2.0,
                                 "lat_us": 100.0}}}


@pytest.fixture
def events_mode():
    prev = obs.set_mode("events")
    obs.reset_tracer()
    obs.reset_registry()
    yield
    obs.set_mode(prev)
    obs.reset_tracer()
    obs.reset_registry()


def _fake_measure(monkeypatch, measurement=FIXED_HIER):
    monkeypatch.setattr(_autotune, "measure_fabric",
                        lambda comm, **kw: measurement)


# -- satellite 1: the extracted pure derivations -----------------------------

def test_derived_stripe_ratio_recovers_committed_seed():
    """The documented fallback is the 1:3 DCN:ICI point of the SAME
    formula — r*(3, 1) is exactly the committed 0.25 seed."""
    assert derived_stripe_ratio(3.0, 1.0) == DEFAULT_STRIPE_RATIO == 0.25


def test_derived_stripe_ratio_monotone_in_dcn_bandwidth():
    prev = 0.0
    for b_dcn in (0.01, 0.1, 0.5, 1.0, 3.0, 10.0, 1000.0):
        r = derived_stripe_ratio(3.0, b_dcn)
        assert r > prev, "a faster DCN must earn a larger DCN share"
        prev = r


def test_derived_stripe_ratio_clamped_to_open_interval():
    assert 0.0 < derived_stripe_ratio(1e12, 1e-12) < 1.0
    assert 0.0 < derived_stripe_ratio(1e-12, 1e12) < 1.0


@pytest.mark.parametrize("b_ici,b_dcn", [
    (0.0, 1.0), (1.0, 0.0), (-1.0, 1.0), (1.0, -1.0),
    (float("inf"), 1.0), (1.0, float("nan"))])
def test_derived_stripe_ratio_rejects_unmeasurable(b_ici, b_dcn):
    with pytest.raises(ValueError):
        derived_stripe_ratio(b_ici, b_dcn)


def test_derived_bucket_bytes_rule_and_clamps():
    # 1 GB/s x 200 us / 0.125 = 1.6e6 B = 1.526 MiB -> 1.5 MiB (2 sig)
    assert derived_bucket_bytes(1.0, 200.0) == int(1.5 * (1 << 20))
    # launch latency ~0: floor at 1 MiB (a sub-MB bucket would thrash)
    assert derived_bucket_bytes(0.001, 1.0) == 1 << 20
    # fat, laggy fabric: capped at 32 MiB (overlap still needs K>1)
    assert derived_bucket_bytes(1000.0, 10000.0) == 32 << 20
    for bad in (0.0, -1.0, float("nan")):
        with pytest.raises(ValueError):
            derived_bucket_bytes(bad, 100.0)
    with pytest.raises(ValueError):
        derived_bucket_bytes(1.0, -5.0)


# -- the pure planner --------------------------------------------------------

def test_derive_plan_from_fixed_measurements():
    plan = derive_exchange_plan(
        FIXED_HIER, {"axis": "dcnxici", "kind": "hierarchical",
                     "size": 8, "exchange": "allreduce",
                     "inter": 2, "intra": 4})
    assert plan["bucket_mb"] == 1.5          # slowest hop: dcn
    assert plan["stripe_ratio"] == 0.25      # r* = 1 / (3 + 1)
    assert plan["grad_dtype"] == {"ici": None, "dcn": "bfloat16"}
    assert plan["fingerprint"] == plan_fingerprint(plan)
    assert any("r* = B_dcn" in n or "finish-together" in n
               for n in plan["derivation"]["notes"])


def test_derive_plan_falls_back_with_notes_when_unmeasurable():
    """A size-1 (or online, latency-free) hop never silently guesses:
    the fallback is taken AND named in the derivation notes."""
    m = {"source": "startup",
         "hops": {"ici": {"size": 1, "gbps": None, "lat_us": None},
                  "dcn": {"size": 2, "gbps": 1.0, "lat_us": None}}}
    plan = derive_exchange_plan(
        m, {"axis": "dcnxici", "kind": "hierarchical", "size": 2,
            "exchange": "allreduce", "inter": 2, "intra": 1})
    assert plan["bucket_mb"] is None         # no latency sample
    assert plan["stripe_ratio"] == DEFAULT_STRIPE_RATIO
    notes = " ".join(plan["derivation"]["notes"])
    assert "falls back" in notes and str(DEFAULT_BUCKET_MB) in notes


def test_derive_plan_deterministic_across_key_order_and_roundtrip():
    topo = {"axis": "dcnxici", "kind": "hierarchical", "size": 8,
            "exchange": "allreduce", "inter": 2, "intra": 4}
    a = derive_exchange_plan(FIXED_HIER, topo)
    shuffled = {"hops": {"dcn": dict(reversed(
        list(FIXED_HIER["hops"]["dcn"].items()))),
        "ici": FIXED_HIER["hops"]["ici"]},
        "iters": 4, "probe_mb": 1.0, "source": "startup"}
    b = derive_exchange_plan(shuffled, dict(reversed(list(topo.items()))))
    assert a["fingerprint"] == b["fingerprint"]
    c = json.loads(json.dumps(a))
    assert plan_fingerprint(c) == a["fingerprint"]


def test_reduce_measurements_median_with_fixed_tiebreak():
    gathered = []
    for gbps in (5.0, 1.0, 3.0, 4.0):   # 4 ranks, even count
        g = {"source": "startup", "probe_mb": 1.0, "iters": 4,
             "hops": {"world": {"size": 8, "gbps": gbps,
                                "lat_us": 100.0 * gbps}}}
        gathered.append(g)
    agreed = reduce_measurements(gathered)
    # sorted [1,3,4,5], fixed tie-break element (n-1)//2 -> 3.0
    assert agreed["hops"]["world"]["gbps"] == 3.0
    assert agreed["hops"]["world"]["lat_us"] == 300.0
    assert agreed["ranks"] == 4
    # order-insensitive: every rank holds the same allgathered list
    assert reduce_measurements(list(reversed(gathered))) == agreed


def test_measurements_from_trace_payload_spans():
    """Online mode: Σbytes/Σduration per hop tag off payload-tagged
    B/E pairs; spans without a payload attribute are not samples."""
    mb = 1 << 20
    events = [
        {"name": "train/grad_exchange", "ph": "B", "ts": 0.0,
         "pid": 0, "tid": 0, "args": {"payload_bytes": 8 * mb,
                                      "hop": "dcn"}},
        {"name": "train/grad_exchange", "ph": "E", "ts": 4000.0,
         "pid": 0, "tid": 0},
        {"name": "train/grad_exchange", "ph": "B", "ts": 5000.0,
         "pid": 0, "tid": 0, "args": {"payload_bytes": 8 * mb,
                                      "hop": "dcn"}},
        {"name": "train/grad_exchange", "ph": "E", "ts": 7000.0,
         "pid": 0, "tid": 0},
        # no payload attribute: timing alone is not a bandwidth sample
        {"name": "train/grad_exchange", "ph": "B", "ts": 8000.0,
         "pid": 0, "tid": 0},
        {"name": "train/grad_exchange", "ph": "E", "ts": 9000.0,
         "pid": 0, "tid": 0},
        # unrelated span: ignored
        {"name": "train/optimizer_update", "ph": "B", "ts": 0.0,
         "pid": 0, "tid": 0, "args": {"payload_bytes": 1}},
        {"name": "train/optimizer_update", "ph": "E", "ts": 1.0,
         "pid": 0, "tid": 0},
    ]
    m = measurements_from_trace(events)
    assert m["source"] == "online"
    assert set(m["hops"]) == {"dcn"}
    hop = m["hops"]["dcn"]
    assert hop["samples"] == 2
    # 16 MiB over 6 ms
    np.testing.assert_allclose(hop["gbps"],
                               16 * mb / 6e-3 / 1e9, rtol=1e-6)
    assert hop["lat_us"] is None   # a full-exchange span bounds launch
    #                                overhead only loosely


# -- agreement over the real comm --------------------------------------------

def test_agree_over_real_comm_records_artifact(monkeypatch, tmp_path):
    obs.reset_registry()
    monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_DIR", str(tmp_path))
    comm = ct.create_communicator("flat")
    plan = agree_exchange_plan(comm, FIXED_FLAT)
    assert plan["fingerprint"] == plan_fingerprint(plan)
    assert plan["topology"] == topology_summary(comm)
    path = tmp_path / "autotune_plan_mn_world.json"
    assert path.exists()
    assert json.loads(path.read_text())["fingerprint"] \
        == plan["fingerprint"]
    g = obs.registry().get("chainermn_tpu_autotune_plan_fingerprint")
    assert g is not None \
        and g.value(axis="mn_world") == float(int(plan["fingerprint"][:12],
                                                  16))


def test_rank0_broadcast_wins_under_injected_skew(monkeypatch):
    """A rank whose local derivation diverges executes rank 0's plan
    anyway — warned and counted, never a silent split-brain
    exchange."""
    obs.reset_registry()
    comm = ct.create_communicator("flat")
    tampered = derive_exchange_plan(
        reduce_measurements([FIXED_FLAT]), topology_summary(comm))
    tampered["bucket_mb"] = 99.0   # rank 0 "derived" something else
    tampered["fingerprint"] = plan_fingerprint(tampered)
    monkeypatch.setattr(comm, "bcast_obj",
                        lambda obj, root=0: tampered)
    with pytest.warns(RuntimeWarning, match="diverged"):
        plan = agree_exchange_plan(comm, FIXED_FLAT)
    assert plan["fingerprint"] == tampered["fingerprint"]
    c = obs.registry().get(
        "chainermn_tpu_autotune_plan_divergence_total")
    assert c is not None and c.value(axis="mn_world") == 1


def test_real_microbench_measures_every_hop():
    """The startup micro-bench over the real simulated mesh: every
    hop of size > 1 gets finite bandwidth + latency samples."""
    comm = ct.create_communicator("hierarchical", inter_size=2)
    m = _autotune.measure_fabric(comm, probe_mb=0.125, iters=2)
    assert set(m["hops"]) == {"ici", "dcn"}
    for hop in m["hops"].values():
        assert hop["size"] > 1
        assert hop["gbps"] is not None and hop["gbps"] > 0
        assert hop["lat_us"] is not None and hop["lat_us"] > 0


# -- the factory knob and the golden-trajectory contract ---------------------

def _data(seed=0, n=32, d=8, k=4):
    rng = np.random.RandomState(seed)
    return (rng.normal(0, 1, (n, d)).astype(np.float32),
            rng.randint(0, k, n).astype(np.int32))


def _losses(comm, steps=3):
    model = Classifier(MLP(n_units=16, n_out=4, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1, momentum=0.9), comm).setup(model)
    x, t = _data()
    return [float(opt.update(model, x, t)) for _ in range(steps)]


def test_factory_autotune_fills_only_free_knobs(monkeypatch):
    _fake_measure(monkeypatch)
    comm = ct.create_communicator("hierarchical", inter_size=2,
                                  autotune=True)
    assert comm.autotune_plan is not None
    assert comm.stripe_ratio == 0.25          # plan-applied
    assert comm.dcn_grad_dtype == jnp.bfloat16
    assert comm.striped
    # hand knob wins: an explicit ratio is never overwritten, and its
    # provenance survives onto the retuned clone
    hand = ct.create_communicator("hierarchical", inter_size=2,
                                  stripe_ratio=0.6, autotune=True)
    assert hand.stripe_ratio == 0.6
    assert hand.autotune_plan is not None     # plan still agreed
    assert hand._hand_knobs["stripe_ratio"] is True
    assert hand.dcn_grad_dtype == jnp.bfloat16  # free knob still filled


def test_autotune_rejected_on_dummy_and_bad_mode():
    with pytest.raises(ValueError, match="autotune"):
        ct.create_communicator("dummy", autotune=True)
    with pytest.raises(ValueError, match="autotune"):
        ct.create_communicator("flat", autotune="sometimes")


def test_golden_trajectory_autotune_equals_hand_knobs(monkeypatch):
    """The gate the whole knob-provenance design serves: an autotuned
    run whose derived plan matches the hand knobs executes the
    IDENTICAL compiled program — losses bitwise equal, step for
    step."""
    _fake_measure(monkeypatch)
    auto = ct.create_communicator("hierarchical", inter_size=2,
                                  autotune=True)
    hand = ct.create_communicator(
        "hierarchical", inter_size=2, stripe_ratio=0.25,
        allreduce_grad_dtype={"ici": None, "dcn": "bfloat16"})
    assert _losses(auto) == _losses(hand)     # bitwise, not allclose


def test_optimizer_level_autotune_startup(monkeypatch):
    """``create_multi_node_optimizer(..., autotune=True)`` re-tunes the
    communicator before any validation sees it — same plan, same
    trajectory as the factory-level knob."""
    _fake_measure(monkeypatch)
    comm = ct.create_communicator("hierarchical", inter_size=2)
    model = Classifier(MLP(n_units=16, n_out=4, seed=0))
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1, momentum=0.9), comm, autotune=True)
    assert opt.communicator is not comm
    assert opt.communicator.autotune_plan is not None
    assert opt.communicator.stripe_ratio == 0.25
    opt.communicator.bcast_data(model)
    opt.setup(model)
    x, t = _data()
    ref = _losses(ct.create_communicator(
        "hierarchical", inter_size=2, stripe_ratio=0.25,
        allreduce_grad_dtype={"ici": None, "dcn": "bfloat16"}))
    assert [float(opt.update(model, x, t)) for _ in range(3)] == ref


# -- online mode + the payload-tagged eager span (satellite 6) ---------------

def _eager_opt(autotune=None):
    comm = ct.create_communicator("flat")
    model = L.Linear(4, 2, seed=0)
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(SGD(lr=0.1), comm,
                                         autotune=autotune).setup(model)
    return opt, model


def _set_grads(model):
    model.W.grad = jnp.ones_like(model.W.array)
    model.b.grad = jnp.ones_like(model.b.array)


def test_eager_span_carries_payload_bytes(events_mode):
    opt, model = _eager_opt()
    _set_grads(model)
    opt.update()
    spans = [e for e in obs.tracer().events()
             if e.get("name") == "train/grad_exchange"
             and e.get("ph") == "B"]
    assert spans, "eager update must emit the timed exchange span"
    args = spans[0].get("args") or {}
    # Linear(4, 2): W 8 + b 2 = 10 f32 elems on the wire
    assert args.get("payload_bytes") == 40
    assert args.get("buckets") == 1


def test_online_autotune_derives_after_n_steps(events_mode):
    opt, model = _eager_opt(autotune=2)
    assert opt._autotune_online_after == 2
    assert opt.communicator.autotune_plan is None
    for _ in range(2):
        _set_grads(model)
        opt.update()
    assert opt._autotune_online_after == 0    # one-shot: disarmed
    plan = opt.communicator.autotune_plan
    assert plan is not None
    assert plan["measurements"]["source"] == "online"


def test_online_autotune_without_tracing_falls_back_to_startup(
        monkeypatch):
    """autotune='online' with tracing off cannot read spans that don't
    exist: warned, and the startup micro-bench runs instead."""
    assert obs.mode() == "off"
    _fake_measure(monkeypatch, FIXED_FLAT)
    comm = ct.create_communicator("flat")
    with pytest.warns(UserWarning, match="tracing is off"):
        opt = ct.create_multi_node_optimizer(SGD(lr=0.1), comm,
                                             autotune="online")
    assert opt.communicator.autotune_plan is not None
    assert opt._autotune_online_after == 0


# -- elastic re-tune: one fresh plan per mesh --------------------------------

def test_change_communicator_retunes_fresh_plan_per_mesh(monkeypatch,
                                                         tmp_path):
    monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_DIR", str(tmp_path))
    _fake_measure(monkeypatch)
    comm = ct.create_communicator("hierarchical", inter_size=2,
                                  autotune=True)
    model = Classifier(MLP(n_units=16, n_out=4, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1, momentum=0.9), comm).setup(model)
    first = comm.autotune_plan
    # a "resize": a rebuilt 4-device world under a fresh axis, no plan
    # (the elastic factory passes the old knob VALUES as constructor
    # args — provenance must carry over, not read as hand-set)
    slow = {"source": "startup", "probe_mb": 1.0, "iters": 4,
            "hops": {"ici": {"size": 2, "gbps": 3.0, "lat_us": 50.0},
                     "dcn": {"size": 2, "gbps": 0.5, "lat_us": 400.0}}}
    _fake_measure(monkeypatch, slow)
    small = ct.create_communicator(
        "hierarchical", devices=jax.devices()[:4], inter_size=2,
        axis_name=("dcn_ep1", "ici_ep1"), stripe_ratio=comm.stripe_ratio)
    opt.change_communicator(small)
    second = opt.communicator.autotune_plan
    assert second is not None
    assert second["fingerprint"] != first["fingerprint"]
    assert opt.communicator.stripe_ratio \
        == pytest.approx(0.5 / 3.5, abs=1e-6)  # re-derived, not carried
    # one artifact per mesh axis: the resized world's trail is its own
    arts = sorted(p.name for p in tmp_path.glob("autotune_plan_*.json"))
    assert len(arts) == 2, arts
