"""Pin `_axis_in_scope`'s dispatch (VERDICT open item 7).

The check selects between eager collectives (outside any mapped trace)
and rank-local bodies (inside a shard_map binding the communicator's
axis).  It must be an EXPLICIT axis-environment query — these tests pin
the observable behavior so a jax upgrade that changes how an unbound
``lax.axis_index`` fails cannot silently flip the mode selection.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators import create_communicator
from chainermn_tpu.utils.compat import axis_env_contains, shard_map


def test_out_of_scope_is_false():
    comm = create_communicator("jax_ici")
    assert comm._axis_in_scope() is False
    assert axis_env_contains(comm.axis_name) is False


def test_in_scope_inside_shard_map():
    comm = create_communicator("jax_ici")
    seen = []

    def body(x):
        seen.append(comm._axis_in_scope())
        return jax.lax.psum(x, comm.axis_name)

    x = jnp.arange(comm.size, dtype=jnp.float32).reshape(comm.size, 1)
    mapped = shard_map(body, mesh=comm.mesh, in_specs=P(comm.axis_name),
                       out_specs=P(comm.axis_name), check_vma=False)
    out = jax.jit(mapped)(x)
    assert seen and all(seen)
    np.testing.assert_allclose(
        np.asarray(out).ravel(), [np.arange(comm.size).sum()] * comm.size)


def test_other_axis_name_stays_out_of_scope():
    """Binding some OTHER axis must not count as this communicator's."""
    comm = create_communicator("jax_ici")
    seen = []

    def body(x):
        seen.append((axis_env_contains("not_the_axis"),
                     axis_env_contains(comm.axis_name)))
        return x

    x = jnp.zeros((comm.size, 1), jnp.float32)
    mapped = shard_map(body, mesh=comm.mesh, in_specs=P(comm.axis_name),
                       out_specs=P(comm.axis_name), check_vma=False)
    jax.jit(mapped)(x)
    assert seen and all(other is False and own is True
                        for other, own in seen)


def test_scope_check_restored_after_trace():
    """The query reads the CURRENT trace's env: once the shard_map trace
    ends, the axis is unbound again (no sticky state)."""
    comm = create_communicator("jax_ici")

    def body(x):
        return jax.lax.psum(x, comm.axis_name)

    x = jnp.ones((comm.size, 1), jnp.float32)
    comm.run_spmd(body, x)
    assert comm._axis_in_scope() is False
