"""Communicator tests.

Mirrors the reference workhorse (SURVEY.md §4:
``communicator_tests/test_communicator.py``): parameterized over all
communicator names; point-to-point echo, ndarray + object collectives,
``bcast_data``, ``allreduce_grad`` asserting grads equal the analytic mean
across ranks, and ``split`` behavior.  Multi-rank is realized as an
8-device simulated CPU mesh (the TPU analog of ``mpiexec -n N``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu import L
from chainermn_tpu.communicators import (create_communicator,
                                         DummyCommunicator, MeshCommunicator)

ALL_NAMES = ["naive", "flat", "hierarchical", "two_dimensional",
             "single_node", "non_cuda_aware", "pure_nccl", "jax_ici"]


@pytest.fixture(scope="module", params=ALL_NAMES)
def comm(request):
    return create_communicator(request.param)


def _stacked(comm, shape=(3,), offset=0.0):
    return jnp.asarray(
        np.stack([np.full(shape, float(i) + offset, np.float32)
                  for i in range(comm.size)]))


def test_factory_names():
    for name in ALL_NAMES:
        c = create_communicator(name)
        assert c.size == len(jax.devices())
    assert isinstance(create_communicator("dummy"), DummyCommunicator)
    with pytest.raises(ValueError):
        create_communicator("mpi")


def test_factory_grad_dtype_validation():
    c = create_communicator("pure_nccl", allreduce_grad_dtype="bfloat16")
    assert c.allreduce_grad_dtype == jnp.bfloat16
    with pytest.raises(ValueError):
        create_communicator("naive", allreduce_grad_dtype="float16")


def test_topology_properties(comm):
    assert comm.rank == 0
    assert comm.size == 8
    assert comm.intra_rank == 0
    assert comm.inter_size == 1


# -- eager (host-mode) collectives -----------------------------------------

def test_eager_allreduce_sum_and_mean(comm):
    x = _stacked(comm)
    total = comm.allreduce(x, op="sum")
    np.testing.assert_allclose(np.asarray(total), sum(range(comm.size)))
    mean = comm.allreduce(x, op="mean")
    np.testing.assert_allclose(np.asarray(mean),
                               np.mean(range(comm.size)), rtol=1e-6)
    mn = comm.multi_node_mean(x)
    np.testing.assert_allclose(np.asarray(mn), np.asarray(mean))


def test_eager_allgather(comm):
    x = _stacked(comm)
    parts = comm.allgather(x)
    assert len(parts) == comm.size
    np.testing.assert_allclose(np.asarray(parts[3]), 3.0)


def test_eager_bcast_gather_scatter(comm):
    x = _stacked(comm)
    np.testing.assert_allclose(np.asarray(comm.bcast(x, root=2)), 2.0)
    parts = comm.gather(x, root=0)
    assert len(parts) == comm.size
    s = comm.scatter(x, root=0)
    np.testing.assert_allclose(np.asarray(s), np.asarray(x))


def test_eager_alltoall(comm):
    # input [src, dst, ...]: src i sends value 10*i + j to dst j
    x = jnp.asarray(np.array(
        [[10 * i + j for j in range(comm.size)] for i in range(comm.size)],
        np.float32))
    y = comm.alltoall(x)
    # rank j receives [10*0+j, 10*1+j, ...]
    np.testing.assert_allclose(np.asarray(y[1]),
                               [10 * i + 1 for i in range(comm.size)])


def test_eager_shape_guard(comm):
    with pytest.raises(ValueError):
        comm.allreduce(jnp.ones((3, 2)))  # leading axis != size


def test_send_recv_echo(comm):
    comm.send(jnp.asarray([1.0, 2.0]), dest=1, tag=7)
    out = comm.recv(source=0, tag=7)
    np.testing.assert_allclose(np.asarray(out), [1.0, 2.0])


def test_obj_collectives(comm):
    assert comm.bcast_obj({"a": 1}) == {"a": 1}
    gathered = comm.allgather_obj(5)
    assert gathered == [5] * comm.size
    assert comm.allreduce_obj(2) == 2 * comm.size
    comm.send_obj("x", dest=3, tag=1)
    assert comm.recv_obj(source=0, tag=1) == "x"


# -- in-step (traced) collectives -------------------------------------------

def test_spmd_allreduce(comm):
    x = _stacked(comm, shape=(4,))

    def f(x):
        return comm.allreduce(x, op="sum")

    from jax.sharding import PartitionSpec as P
    out = comm.run_spmd(f, x, out_specs=P(comm.axis_name))
    # every rank's shard holds the sum
    np.testing.assert_allclose(np.asarray(out).reshape(comm.size, -1)[0],
                               sum(range(comm.size)))


def test_spmd_allgather_bcast(comm):
    x = _stacked(comm, shape=(2,))

    def f(x):
        gathered = comm.allgather(x)          # [size, 1, 2] per rank
        root_val = comm.bcast(x, root=5)
        return gathered.sum(axis=0) + 0 * x, root_val

    from jax.sharding import PartitionSpec as P
    g, r = comm.run_spmd(f, x, out_specs=(P(comm.axis_name),
                                          P(comm.axis_name)))
    np.testing.assert_allclose(np.asarray(r).reshape(comm.size, -1),
                               5.0)


def test_spmd_alltoall(comm):
    x = jnp.asarray(np.arange(comm.size * comm.size, dtype=np.float32)
                    .reshape(comm.size, comm.size, 1))

    def f(x):
        # x: [1, size, 1] local → drop leading, alltoall over dst axis
        return comm.alltoall(x[0])[:, None]

    from jax.sharding import PartitionSpec as P
    out = comm.run_spmd(f, x, out_specs=P(comm.axis_name))
    out = np.asarray(out).reshape(comm.size, comm.size)
    np.testing.assert_allclose(out, out.T * 0 + np.asarray(
        np.arange(comm.size * comm.size).reshape(comm.size, comm.size)).T)


# -- model ops -----------------------------------------------------------------

def test_bcast_data_replicates(comm):
    model = L.Linear(4, 2, seed=0)
    comm.bcast_data(model)
    sh = model.W.array.sharding
    assert sh.is_fully_replicated


def test_allreduce_grad_means_stacked_grads(comm):
    model = L.Linear(2, 2, seed=0)
    per_rank = np.stack([np.full((2, 2), float(i), np.float32)
                         for i in range(comm.size)])
    model.W.grad = jnp.asarray(per_rank)
    model.b.grad = jnp.zeros((2,))  # already-global grad left alone
    comm.allreduce_grad(model)
    np.testing.assert_allclose(np.asarray(model.W.grad),
                               np.mean(range(comm.size)) * np.ones((2, 2)),
                               rtol=1e-6)
    assert model.b.grad.shape == (2,)


def test_allreduce_grad_zero_fill(comm):
    model = L.Linear(2, 2, seed=0)
    model.W.grad = jnp.asarray(np.stack(
        [np.ones((2, 2), np.float32) * i for i in range(comm.size)]))
    model.b.grad = None
    comm.multi_node_mean_grad(model, zero_fill=True)
    np.testing.assert_allclose(np.asarray(model.b.grad), 0.0)


def test_grad_dtype_compression_close_to_exact():
    comm = create_communicator("pure_nccl", allreduce_grad_dtype="bfloat16")
    model = L.Linear(2, 2, seed=0)
    vals = np.stack([np.full((2, 2), 1.0 + 0.001 * i, np.float32)
                     for i in range(comm.size)])
    model.W.grad = jnp.asarray(vals)
    comm.allreduce_grad(model)
    assert model.W.grad.dtype == jnp.float32  # cast back
    np.testing.assert_allclose(np.asarray(model.W.grad), vals.mean(axis=0),
                               rtol=1e-2)


# -- split ------------------------------------------------------------------------

def test_split_two_groups(comm):
    colors = [i % 2 for i in range(comm.size)]
    keys = list(range(comm.size))
    subs = comm.split_all(colors, keys) if isinstance(comm, MeshCommunicator) \
        else [comm.split(colors, keys)]
    assert len(subs) == 2
    assert subs[0].size == comm.size // 2
    x = jnp.asarray(np.arange(subs[0].size, dtype=np.float32))
    np.testing.assert_allclose(
        np.asarray(subs[0].allreduce(x, op="sum")),
        sum(range(subs[0].size)))


def test_split_scalar_color(comm):
    sub = comm.split(0, 0)
    assert sub.size == comm.size


def test_split_mixed_colors_raises_single_controller():
    """Under one controller all devices are local, so a mixed-color
    split has no single 'caller's group' — split() must say so instead
    of silently returning the first color (VERDICT r2 Weak #5); the
    caller's-group behavior under real processes is asserted in the
    two-process suite (_worker.run_dp_step)."""
    world = create_communicator("jax_ici")
    if world.size < 2:
        pytest.skip("needs >= 2 devices")
    colors = [i % 2 for i in range(world.size)]
    with pytest.raises(ValueError, match="straddle"):
        world.split(colors, 0)


def test_bcast_obj_out_of_range_root_raises():
    """A mis-addressed object-channel root raises instead of silently
    re-rooting to 0 (VERDICT r2 Weak #6)."""
    world = create_communicator("jax_ici")
    with pytest.raises(ValueError, match="root"):
        world.bcast_obj({"x": 1}, root=world.size + 5)
    with pytest.raises(ValueError, match="root"):
        world.bcast_obj({"x": 1}, root=-1)
    assert world._owning_process(0) == 0


# -- dummy ---------------------------------------------------------------------------

def test_dummy_communicator_noops():
    d = DummyCommunicator()
    assert d.size == 1 and d.rank == 0
    x = jnp.ones(3)
    np.testing.assert_allclose(np.asarray(d.allreduce(x)), 1.0)
    assert d.allgather_obj("a") == ["a"]
    model = L.Linear(2, 2, seed=0)
    d.bcast_data(model)
    d.multi_node_mean_grad(model)


def test_debug_communicator_signature_checking():
    from chainermn_tpu.communicators.debug_communicator import (
        DebugCommunicator, SignatureMismatchError)
    comm = create_communicator("debug")
    assert isinstance(comm, DebugCommunicator)
    x = jnp.ones((comm.size, 3))
    out = comm.run_spmd(lambda x: x * 2, x)
    assert comm.signature_checks == 1
    comm.run_spmd(lambda x: x * 3, x)  # same signature → cached
    assert comm.signature_checks == 1
    comm.run_spmd(lambda x: x, jnp.ones((comm.size, 5)))  # new shape
    assert comm.signature_checks == 2

    # simulate a host disagreeing
    orig = comm.allgather_obj
    comm.allgather_obj = lambda obj: [obj, (1, "deadbeef", "(9, 9):bad")]
    with pytest.raises(SignatureMismatchError, match="disagree"):
        comm.verify_step_signature(jnp.ones((2, 2)))
    comm.allgather_obj = orig


def test_debug_communicator_under_optimizer():
    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import SGD
    from chainermn_tpu.models import Classifier, MLP
    comm = create_communicator("debug")
    model = Classifier(MLP(n_units=8, n_out=4, seed=0))
    opt = ct.create_multi_node_optimizer(SGD(lr=0.1), comm).setup(model)
    x = jnp.ones((comm.size * 2, 6))
    t = jnp.zeros((comm.size * 2,), jnp.int32)
    opt.update(model, x, t)
    assert comm.signature_checks >= 1


def test_eager_recv_source_matching():
    """Two pending senders with declared sources must not cross wires
    (VERDICT r1 Weak #4: MPI source-matching semantics)."""
    c = create_communicator("jax_ici")
    c.send(jnp.asarray([1.0]), dest=0, tag=3, source=5)
    c.send(jnp.asarray([2.0]), dest=0, tag=3, source=6)
    np.testing.assert_allclose(np.asarray(c.recv(source=6, tag=3)), [2.0])
    np.testing.assert_allclose(np.asarray(c.recv(source=5, tag=3)), [1.0])
    # undeclared sends keep the legacy wildcard behavior
    c.send(jnp.asarray([7.0]), dest=0, tag=4)
    np.testing.assert_allclose(np.asarray(c.recv(source=2, tag=4)), [7.0])
    with pytest.raises(RuntimeError, match="no matching message"):
        c.recv(source=0, tag=99)


def test_split_subcomm_collectives_are_independent():
    """split()-derived sub-communicators run collectives confined to
    their group (VERDICT r1 item 10): group means must not mix."""
    world = create_communicator("jax_ici")
    if world.size < 4:
        pytest.skip("needs >= 4 devices")
    half = world.size // 2
    colors = [0] * half + [1] * half
    subs = world.split_all(colors, list(range(world.size)))
    assert len(subs) == 2 and all(c.size == half for c in subs)
    for g, sub in enumerate(subs):
        # stacked eager allreduce within the group only
        vals = jnp.asarray(np.stack(
            [np.full((2,), 10.0 * g + i, np.float32) for i in range(half)]))
        out = sub.allreduce(vals, op="mean")
        expect = 10.0 * g + (half - 1) / 2.0
        np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


def test_split_subcomm_spmd_inside_own_mesh():
    """A split() sub-communicator's run_spmd launches over its OWN
    sub-mesh: per-group psum totals differ per group."""
    world = create_communicator("jax_ici")
    if world.size < 4:
        pytest.skip("needs >= 4 devices")
    half = world.size // 2
    subs = world.split_all([0] * half + [1] * half, 0)
    totals = []
    for g, sub in enumerate(subs):
        x = jnp.arange(half, dtype=jnp.float32) + 100.0 * g

        def body(x):
            return jax.lax.psum(x, sub.axis_name)

        out = sub.run_spmd(body, x)
        totals.append(float(np.asarray(out)[0]))
    base = sum(range(half))
    np.testing.assert_allclose(totals[0], base)
    np.testing.assert_allclose(totals[1], base + 100.0 * half)


def test_hierarchical_communicator_is_two_level():
    """ISSUE 6: 'hierarchical'/'two_dimensional' are REAL two-level
    communicators (not aliases of the flat path): a (dcn, ici) mesh,
    tuple axis binding, and the per-hop grad exchange."""
    for name in ("hierarchical", "two_dimensional"):
        comm = create_communicator(name, inter_size=2)
        assert comm.hierarchy == ("dcn", "ici")
        assert comm.topology == "hierarchical"
        assert comm.axis_name == ("dcn", "ici")
        assert comm.dcn_size == 2 and comm.ici_size == 4
        assert tuple(comm.mesh.axis_names) == ("dcn", "ici")
    # the default split on one controller: a degenerate size-1 dcn axis
    # (structure kept; a real multihost run infers one group per host)
    comm = create_communicator("hierarchical")
    assert comm.dcn_size == 1 and comm.ici_size == comm.size
    # invalid splits fail at construction, not inside the first trace
    with pytest.raises(ValueError, match="divide"):
        create_communicator("hierarchical", inter_size=3)
    with pytest.raises(ValueError, match="device count"):
        create_communicator("hierarchical", inter_size=2, intra_size=2)


def test_hierarchy_escape_hatch(monkeypatch):
    """CHAINERMN_TPU_HIERARCHY=flat collapses the hierarchical names
    back to the flat one-axis alias (sizes ignored) — the no-code-change
    rollback documented in docs/performance.md §8."""
    monkeypatch.setenv("CHAINERMN_TPU_HIERARCHY", "flat")
    comm = create_communicator("hierarchical", inter_size=2)
    assert comm.hierarchy is None
    assert comm.topology == "flat"
    assert isinstance(comm.axis_name, str)
    # a (dcn, ici) axis_name tuple must not re-trigger the split
    # through the hatch (it would silently ignore the rollback)
    comm = create_communicator("hierarchical", inter_size=2,
                               axis_name=("dcn", "ici"))
    assert comm.hierarchy is None and isinstance(comm.axis_name, str)
    # per-hop dict intent degrades onto the single hop: the dcn entry
    # wins, else the ici entry — never a silent drop to lossless
    comm = create_communicator(
        "hierarchical", allreduce_grad_dtype={"dcn": "bfloat16"})
    assert comm.allreduce_grad_dtype == jnp.bfloat16
    comm = create_communicator(
        "hierarchical", allreduce_grad_dtype={"ici": "bfloat16"})
    assert comm.allreduce_grad_dtype == jnp.bfloat16


def test_hierarchy_escape_hatch_warns_on_dict_degradation(monkeypatch):
    """ISSUE 8 satellite: degrading a per-hop dict onto the flat alias's
    single hop is intent-changing (the FULL gradient now rides the dcn
    compression) — it must warn ONCE per distinct dict, naming the
    dropped keys, and still apply the documented dcn-wins rule."""
    import warnings as _warnings
    from chainermn_tpu import communicators as comm_mod
    monkeypatch.setenv("CHAINERMN_TPU_HIERARCHY", "flat")
    monkeypatch.setattr(comm_mod, "_WARNED_FLAT_DICTS", set())
    spec = {"ici": "bfloat16", "dcn": "int8"}
    with pytest.warns(UserWarning, match="degrades per-hop") as rec:
        comm = create_communicator("hierarchical",
                                   allreduce_grad_dtype=dict(spec))
    assert comm.allreduce_grad_dtype == jnp.int8  # dcn entry won
    assert comm.hierarchy is None
    msg = str(rec[0].message)
    assert "ici" in msg and "'dcn'" in msg  # dropped + kept keys named
    # one-time: the SAME dict intent does not warn again ...
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        create_communicator("hierarchical",
                            allreduce_grad_dtype=dict(spec))
    # ... but a DIFFERENT dict does
    with pytest.warns(UserWarning, match="degrades per-hop"):
        create_communicator("hierarchical",
                            allreduce_grad_dtype={"dcn": "bfloat16"})


def test_quantized_dtype_knobs():
    """ISSUE 8 construction surface: quantized wire dtypes resolve per
    hop (scalar quantized → DCN only on hierarchical communicators),
    the ici hop refuses quantization, and error_feedback rides the
    factory."""
    comm = create_communicator("hierarchical", inter_size=2,
                               allreduce_grad_dtype={"dcn": "int8"})
    assert comm.allreduce_grad_dtype is None  # ici lossless
    assert comm.dcn_grad_dtype == jnp.int8
    assert comm.quantized and comm.error_feedback
    assert str(comm.quantized_wire_dtype) == "int8"
    # scalar quantized on hierarchical: DCN only (unlike bf16, which
    # compresses both hops — int8 cannot ride a psum_scatter)
    comm = create_communicator("hierarchical", inter_size=2,
                               allreduce_grad_dtype="int8")
    assert comm.allreduce_grad_dtype is None
    assert comm.dcn_grad_dtype == jnp.int8
    # fp8 alias spelling resolves to jax's e4m3fn
    comm = create_communicator("hierarchical", inter_size=2,
                               allreduce_grad_dtype={"dcn": "float8_e4m3"},
                               error_feedback=False)
    assert comm.dcn_grad_dtype == jnp.dtype(jnp.float8_e4m3fn)
    assert not comm.error_feedback
    with pytest.raises(ValueError, match="lossless by design"):
        create_communicator("hierarchical", inter_size=2,
                            allreduce_grad_dtype={"ici": "int8"})
    # flat communicator: scalar quantized compresses the one hop
    comm = create_communicator("jax_ici", allreduce_grad_dtype="int8")
    assert comm.quantized and str(comm.quantized_wire_dtype) == "int8"


def test_compress_env_escape_hatch(monkeypatch):
    """CHAINERMN_TPU_COMPRESS=off strips QUANTIZED wires back to
    lossless at construction; plain bf16 cast compression is untouched
    (it predates the quantized path and has its own knobs)."""
    monkeypatch.setenv("CHAINERMN_TPU_COMPRESS", "off")
    comm = create_communicator("hierarchical", inter_size=2,
                               allreduce_grad_dtype={"ici": "bfloat16",
                                                     "dcn": "int8"})
    assert comm.dcn_grad_dtype is None  # int8 stripped
    assert comm.allreduce_grad_dtype == jnp.bfloat16  # bf16 kept
    assert not comm.quantized
    comm = create_communicator("jax_ici", allreduce_grad_dtype="int8")
    assert comm.allreduce_grad_dtype is None
    assert not comm.quantized


def test_per_hop_dtype_validation():
    comm = create_communicator(
        "hierarchical", inter_size=2,
        allreduce_grad_dtype={"dcn": "bfloat16"})
    assert comm.allreduce_grad_dtype is None  # ici lossless
    assert comm.dcn_grad_dtype == jnp.bfloat16
    # scalar dtype compresses BOTH hops (flat-path parity)
    comm = create_communicator("hierarchical", inter_size=2,
                               allreduce_grad_dtype="bfloat16")
    assert comm.allreduce_grad_dtype == jnp.bfloat16
    assert comm.dcn_grad_dtype == jnp.bfloat16
    with pytest.raises(ValueError, match="hierarchical"):
        create_communicator("jax_ici",
                            allreduce_grad_dtype={"dcn": "bfloat16"})
    with pytest.raises(ValueError, match="hops"):
        create_communicator("hierarchical", inter_size=2,
                            allreduce_grad_dtype={"ici": None,
                                                  "wan": "bfloat16"})


def test_hierarchical_split_flattens():
    """split() of a hierarchical communicator returns FLAT sub-groups
    (documented: an arbitrary color partition has no canonical
    two-level structure) — and their collectives stay correct."""
    comm = create_communicator("hierarchical", inter_size=2)
    subs = comm.split_all([i % 2 for i in range(comm.size)],
                          list(range(comm.size)))
    assert len(subs) == 2
    for sub in subs:
        assert sub.hierarchy is None and sub.size == comm.size // 2
    # per-hop compression intent survives the flatten: the subgroup's
    # single hop gets the parent's DCN entry, never silently lossless
    hcomm = create_communicator("hierarchical", inter_size=2,
                                allreduce_grad_dtype={"dcn": "bfloat16"})
    for sub in hcomm.split_all(0, 0):
        assert sub.allreduce_grad_dtype == jnp.bfloat16
    # an explicit split on any fused name may carry the per-hop dict too
    comm2 = create_communicator("jax_ici", inter_size=2,
                                allreduce_grad_dtype={"dcn": "bfloat16"})
    assert comm2.hierarchy == ("dcn", "ici")
    assert comm2.dcn_grad_dtype == jnp.bfloat16
    x = jnp.asarray(np.arange(subs[0].size, dtype=np.float32))
    np.testing.assert_allclose(
        np.asarray(subs[0].allreduce(x, op="sum")),
        sum(range(subs[0].size)))


def test_exchange_knobs_vocabulary():
    """The one exchange-name mapping bench.py and bench_scaling share:
    (communicator name, batch_collectives, optimizer exchange)."""
    from chainermn_tpu.communicators import EXCHANGES, exchange_knobs
    assert exchange_knobs("flat") == ("jax_ici", True, "allreduce")
    assert exchange_knobs("bucketed") == \
        ("jax_ici", "bucketed", "allreduce")
    assert exchange_knobs("reduce_scatter") == \
        ("jax_ici", True, "reduce_scatter")
    assert exchange_knobs("hierarchical") == \
        ("hierarchical", True, "allreduce")
    assert exchange_knobs("hierarchical_rs") == \
        ("hierarchical", True, "reduce_scatter")
    assert set(EXCHANGES) == {"per_leaf", "flat", "bucketed",
                              "reduce_scatter", "hierarchical",
                              "hierarchical_rs", "striped",
                              "striped_rs"}
    with pytest.raises(ValueError, match="unknown exchange"):
        exchange_knobs("chunky")


def test_hierarchical_two_level_reduction_matches_global():
    """Reference 'hierarchical' structure as an explicit two-level
    reduction over split() groups: intra-group mean → leader-level mean
    == one global mean (the XLA torus does this internally; the
    composition over sub-communicators must agree)."""
    world = create_communicator("jax_ici")
    if world.size < 4:
        pytest.skip("needs >= 4 devices")
    half = world.size // 2
    subs = world.split_all([0] * half + [1] * half, 0)
    rng = np.random.RandomState(3)
    per_rank = rng.normal(0, 1, (world.size, 5)).astype(np.float32)
    # level 1: mean within each group (stacked eager form)
    g0 = subs[0].allreduce(jnp.asarray(per_rank[:half]), op="mean")
    g1 = subs[1].allreduce(jnp.asarray(per_rank[half:]), op="mean")
    # level 2: mean across the two group leaders
    leaders = create_communicator("jax_ici").split_all(
        [0 if i in (0, half) else 1 for i in range(world.size)], 0)[0]
    assert leaders.size == 2
    two_level = leaders.allreduce(jnp.stack([g0, g1]), op="mean")
    np.testing.assert_allclose(np.asarray(two_level),
                               per_rank.mean(axis=0), rtol=1e-5,
                               atol=1e-6)


def test_from_mesh_axis_split_interaction():
    """split() of a from_mesh_axis communicator: sub-groups of one axis
    of an enclosing 2-D mesh keep correct device subsets."""
    import jax as _jax
    from jax.sharding import Mesh
    devs = np.asarray(_jax.devices())
    if devs.size < 8:
        pytest.skip("needs 8 devices")
    mesh = Mesh(devs.reshape(2, 4), ("dp", "mp"))
    mp_comm = MeshCommunicator.from_mesh_axis(mesh, "mp")
    assert mp_comm.size == 4
    subs = mp_comm.split_all([0, 0, 1, 1], 0)
    assert [c.size for c in subs] == [2, 2]
    got = {d.id for c in subs for d in c._devices}
    assert got == {d.id for d in mp_comm._devices}
