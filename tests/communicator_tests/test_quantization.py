"""Property suite for the quantized gradient wire (ISSUE 8).

The quantize/dequantize pair and the error-feedback residual are the
numerical core of the compressed exchange — convergence parity rests on
four properties pinned here:

* round-trip error is BOUNDED (scale/2 per element for int8; relative
  2^-mantissa for fp8) — quantization is lossy but never unbounded;
* the scale is a DETERMINISTIC pure function of the buffer — every rank
  quantizing the same chunk derives the same codebook, which is what
  lets the dequantize-sum reconstruct a cross-rank mean at all;
* zero / inf / NaN gradients have DEFINED behavior (zeros stay zeros
  with scale 1; inf saturates without poisoning the scale; NaN encodes
  as 0 and contributes 0 residual) — one overflowed step must not
  destroy the buffer or the carried error;
* the residual TELESCOPES: over K steps of error feedback the sum of
  applied (dequantized) updates equals the sum of true gradients up to
  exactly the last residual — the carried error never accumulates.

The convergence-side counterpart lives in
tests/core_tests/test_quantized_parity.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chainermn_tpu.communicators._memory_utility import (
    QUANTIZED_DTYPES, dequantize_symmetric, is_quantized_dtype,
    quantization_residual, quantize_symmetric, quantized_hop_bytes,
    resolve_grad_dtype)

WIRES = ("int8", "float8_e4m3", "float8_e5m2")

#: per-wire relative round-trip bound: int8 is a uniform 127-level
#: codebook (half a step of the largest magnitude); fp8 is relative
#: floating-point rounding (2^-mantissa_bits of the element, but bounded
#: here against absmax for simplicity of the uniform statement)
REL_BOUND = {"int8": 0.5 / 127.0, "float8_e4m3": 2.0 ** -3,
             "float8_e5m2": 2.0 ** -2}


def _vec(seed=0, n=257, scale=3.0):
    rng = np.random.RandomState(seed)
    return jnp.asarray((rng.normal(0, scale, n)).astype(np.float32))


@pytest.mark.parametrize("wire", WIRES)
def test_round_trip_error_bound(wire):
    v = _vec()
    q, s = quantize_symmetric(v, wire)
    err = np.abs(np.asarray(dequantize_symmetric(q, s)) - np.asarray(v))
    absmax = float(np.max(np.abs(np.asarray(v))))
    assert float(np.max(err)) <= absmax * REL_BOUND[wire] * (1 + 1e-6), wire


@pytest.mark.parametrize("wire", WIRES)
def test_wire_dtype_and_itemsize(wire):
    q, _ = quantize_symmetric(_vec(), wire)
    assert q.dtype == resolve_grad_dtype(wire)
    assert q.dtype.itemsize == 1  # the whole point: 1/4 of f32 bytes
    assert is_quantized_dtype(wire)
    assert is_quantized_dtype(str(resolve_grad_dtype(wire)))


def test_fp8_alias_resolution():
    """The ISSUE spells fp8 without jax's ``fn`` suffix; both resolve
    to the OCP finite-only e4m3 dtype."""
    assert resolve_grad_dtype("float8_e4m3") == jnp.dtype(jnp.float8_e4m3fn)
    assert resolve_grad_dtype("float8_e4m3fn") == \
        jnp.dtype(jnp.float8_e4m3fn)
    assert not is_quantized_dtype("bfloat16")
    assert not is_quantized_dtype(None)
    assert resolve_grad_dtype(None) is None


@pytest.mark.parametrize("wire", WIRES)
def test_scale_deterministic_across_ranks(wire):
    """Two independent quantizations of the same buffer (the cross-rank
    contract: same chunk → same codebook), eager AND under jit, agree
    bitwise."""
    v = _vec(seed=3)
    q1, s1 = quantize_symmetric(v, wire)
    q2, s2 = quantize_symmetric(jnp.asarray(np.asarray(v)), wire)
    assert float(s1) == float(s2)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
    qj, sj = jax.jit(lambda x: quantize_symmetric(x, wire))(v)
    assert float(sj) == float(s1)
    np.testing.assert_array_equal(np.asarray(qj), np.asarray(q1))


@pytest.mark.parametrize("wire", WIRES)
def test_zero_buffer(wire):
    v = jnp.zeros((64,), jnp.float32)
    q, s = quantize_symmetric(v, wire)
    assert float(s) == 1.0  # never a 0/0
    np.testing.assert_array_equal(np.asarray(dequantize_symmetric(q, s)),
                                  np.zeros(64, np.float32))
    r = quantization_residual(v, q, s)
    np.testing.assert_array_equal(np.asarray(r), np.zeros(64, np.float32))


@pytest.mark.parametrize("wire", WIRES)
def test_inf_nan_handling(wire):
    """inf saturates to ±qmax·scale with the scale computed over the
    FINITE values only; NaN encodes as 0; the residual is 0 at every
    non-finite position (error feedback must not carry poison)."""
    v = jnp.asarray(np.asarray(
        [1.0, -2.0, np.inf, -np.inf, np.nan, 0.5], np.float32))
    q, s = quantize_symmetric(v, wire)
    qmax = QUANTIZED_DTYPES[wire]
    # scale derived from the finite absmax (2.0), not poisoned by inf
    assert float(s) == pytest.approx(2.0 / qmax)
    deq = np.asarray(dequantize_symmetric(q, s))
    assert np.isfinite(deq).all()
    assert deq[2] == pytest.approx(2.0, rel=0.26)   # +inf → +absmax
    assert deq[3] == pytest.approx(-2.0, rel=0.26)  # -inf → -absmax
    assert deq[4] == 0.0                            # NaN → 0
    r = np.asarray(quantization_residual(v, q, s))
    assert np.isfinite(r).all()
    assert r[2] == r[3] == r[4] == 0.0


@pytest.mark.parametrize("wire", WIRES)
def test_residual_telescopes(wire):
    """K steps of error feedback: sum of applied (dequantized) updates
    == sum of true gradients − the LAST residual, so the total applied
    error is bounded by ONE step's quantization error forever."""
    rng = np.random.RandomState(7)
    e = jnp.zeros((128,), jnp.float32)
    applied = np.zeros(128, np.float64)
    true_sum = np.zeros(128, np.float64)
    last_scale = 1.0
    for k in range(20):
        g = jnp.asarray(rng.normal(0, 1 + k % 3, 128).astype(np.float32))
        true_sum += np.asarray(g, np.float64)
        v = g + e
        q, s = quantize_symmetric(v, wire)
        applied += np.asarray(dequantize_symmetric(q, s), np.float64)
        e = quantization_residual(v, q, s)
        last_scale = float(s)
    gap = np.abs(true_sum - applied - np.asarray(e, np.float64))
    # the identity is exact up to f32 accumulation noise
    assert float(np.max(gap)) <= 1e-3 * max(1.0, last_scale * 127), wire
    # and the residual itself is one-step-sized, not K-step-sized
    qmax = QUANTIZED_DTYPES[wire]
    assert float(np.max(np.abs(np.asarray(e)))) \
        <= float(np.max(np.abs(true_sum))) * 0.5  # never accumulates


def test_residual_len_matches_transform(comm_factory=None):
    """comm.grad_residual_len agrees with the residual the transform
    actually emits, flat AND hierarchical (the zero-seed, the serialize
    template, and the hot path must agree)."""
    import chainermn_tpu as ct
    shapes = [(7,), (33,), (5, 5)]
    dtypes = [jnp.float32] * 3
    flat = ct.create_communicator("jax_ici", allreduce_grad_dtype="int8")
    assert flat.grad_residual_len(shapes, dtypes) == 7 + 33 + 25
    hier = ct.create_communicator("hierarchical", inter_size=2,
                                  allreduce_grad_dtype={"dcn": "int8"})
    # one flat bucket of 65 elems, padded to 68 (ici=4) → 17 per device
    assert hier.grad_residual_len(shapes, dtypes) == 17
    lossless = ct.create_communicator("hierarchical", inter_size=2)
    assert lossless.grad_residual_len(shapes, dtypes) == 0


def test_quantized_hop_bytes_pinned():
    """The wire-byte pricing of the quantized slow hop, unit-pinned:
    all_gather (allreduce hop) = chunk·(size−1) at 1 byte; all_to_all
    (sharded-update hop) = chunk·(size−1)/size — exactly the quantized
    fraction of the f32 reduce-scatter crossing."""
    from chainermn_tpu.communicators._memory_utility import exchanged_bytes
    chunk = 1024
    assert quantized_hop_bytes(chunk, 2, "psum", "int8") == chunk
    # f32 psum on the same chunk at inter=2: 2·4·chunk·(1/2) = 4·chunk
    assert exchanged_bytes(chunk * 4, 2, "psum") == 4 * chunk
    assert quantized_hop_bytes(chunk, 2, "psum", "int8") * 4 == \
        exchanged_bytes(chunk * 4, 2, "psum")
    # the all_to_all reduce-scatter: quantized fraction at ANY size
    for size in (2, 4, 8):
        assert quantized_hop_bytes(chunk, size, "reduce_scatter",
                                   "int8") * 4 == \
            exchanged_bytes(chunk * 4, size, "reduce_scatter")
    assert quantized_hop_bytes(chunk, 1, "psum", "int8") == 0
    with pytest.raises(ValueError):
        quantized_hop_bytes(chunk, 2, "all_gather", "int8")


def _trace_one_arg_transform(comm):
    """Trace comm.grad_transform's legacy 1-arg form inside a bound
    mesh axis (the warning fires at trace time, before any execution)."""
    from chainermn_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    def body(g):
        return comm.grad_transform()({"w": g})["w"]

    jax.make_jaxpr(shard_map(
        body, mesh=comm.mesh, in_specs=(P("mn_world"),),
        out_specs=P("mn_world"), check_vma=False))(
        jnp.ones((comm.size * 8,)))


def test_legacy_one_arg_transform_warns_when_ef_inert():
    """A legacy 1-arg grad_transform call (e.g. the DCGAN updater's
    direct use) on an EF-enabled quantized communicator silently runs
    the EF-off ablation — it must warn once per process so the inert
    error_feedback=True is visible."""
    import warnings as _w
    import chainermn_tpu as ct
    from chainermn_tpu.communicators import mesh_communicator as mc
    comm = ct.create_communicator("jax_ici", allreduce_grad_dtype="int8")
    old = mc._warned_inert_ef
    try:
        mc._warned_inert_ef = False
        with pytest.warns(UserWarning, match="error feedback is inert"):
            _trace_one_arg_transform(comm)
        # once per process: second call stays quiet
        with _w.catch_warnings():
            _w.simplefilter("error")
            _trace_one_arg_transform(comm)
        # an explicit error_feedback=False ablation does not warn
        mc._warned_inert_ef = False
        quiet = ct.create_communicator("jax_ici",
                                       allreduce_grad_dtype="int8",
                                       error_feedback=False)
        with _w.catch_warnings():
            _w.simplefilter("error")
            _trace_one_arg_transform(quiet)
    finally:
        mc._warned_inert_ef = old


def test_quantized_exchange_matches_hand_mean():
    """The gather-based quantized exchange reconstructs the cross-rank
    mean of per-rank DEQUANTIZED buffers exactly (each rank's own scale
    travels with its codewords) — checked against a hand-computed
    reference on the 8-device mesh."""
    import chainermn_tpu as ct
    from chainermn_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    comm = ct.create_communicator("jax_ici", allreduce_grad_dtype="int8")
    rng = np.random.RandomState(11)
    per_rank = rng.normal(0, 2, (comm.size, 40)).astype(np.float32)
    transform = comm.grad_transform()

    def body(g):
        return transform({"w": g})["w"]

    out = jax.jit(shard_map(
        body, mesh=comm.mesh, in_specs=(P("mn_world"),),
        out_specs=P("mn_world"), check_vma=False))(
        jnp.asarray(per_rank).reshape(comm.size * 40))
    got = np.asarray(out).reshape(comm.size, 40)[0]
    expect = np.zeros(40, np.float64)
    for r in range(comm.size):
        q, s = quantize_symmetric(jnp.asarray(per_rank[r]), "int8")
        expect += np.asarray(dequantize_symmetric(q, s), np.float64)
    expect /= comm.size
    np.testing.assert_allclose(got, expect.astype(np.float32),
                               rtol=1e-6, atol=1e-6)
