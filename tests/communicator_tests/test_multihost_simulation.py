"""Multi-host code paths under a simulated two-host topology.

Real multi-process DCN can't run in one test process; these tests stand
up pairs of communicators whose host-level views (``inter_rank``/
``inter_size``/object channel) are cross-wired in memory — the same
trick the reference's CPU-only CI used for its MPI paths (SURVEY §4:
multi-node simulated by local processes).
"""

import os

import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu.communicators.mesh_communicator import MeshCommunicator


class _FakeHostComm(MeshCommunicator):
    """Communicator presenting a 2-host topology; the object channel is
    an in-memory exchange between the two instances."""

    def __init__(self, host, peer_box, **kwargs):
        super().__init__(**kwargs)
        self._host = host
        self._peer_box = peer_box  # dict: host -> last submitted obj

    @property
    def inter_rank(self):
        return self._host

    @property
    def inter_size(self):
        return 2

    def allgather_obj(self, obj):
        # this fake is driven sequentially from one thread, so when the
        # peer has not reached this collective yet, assume it contributes
        # the same object (SPMD same-code assumption).  Real lock-step
        # transport is exercised by tests/multiprocess_tests/.
        self._peer_box[self._host] = obj
        assert len(self._peer_box) <= 3  # 2 hosts + bcast slot
        other = 1 - self._host
        per_host = [self._peer_box.get(0, obj), self._peer_box.get(1, obj)]
        del other
        out = []
        for h, o in enumerate(per_host):
            out.extend([o] * (self.size // 2))
        return out

    def bcast_obj(self, obj, root=0):
        if self._host == root:
            self._peer_box[f"bcast"] = obj
            return obj
        return self._peer_box["bcast"]


def _host_pair():
    box = {}
    a = _FakeHostComm(0, box)
    b = _FakeHostComm(1, box)
    return a, b


def test_scatter_dataset_splits_across_hosts():
    a, b = _host_pair()
    data = np.arange(64)
    shard_a = ct.scatter_dataset(data, a, shuffle=True, seed=4)
    shard_b = ct.scatter_dataset(data, b, shuffle=True, seed=4)
    assert len(shard_a) == len(shard_b) == 32
    union = {int(shard_a[i]) for i in range(32)} | \
        {int(shard_b[i]) for i in range(32)}
    assert union == set(range(64))
    inter = {int(shard_a[i]) for i in range(32)} & \
        {int(shard_b[i]) for i in range(32)}
    assert not inter  # disjoint host shards


def test_checkpointer_consensus_across_hosts(tmp_path):
    from chainermn_tpu.extensions.checkpoint import _MultiNodeCheckpointer
    out = str(tmp_path)
    a, b = _host_pair()
    cp_a = _MultiNodeCheckpointer(a, "ck", 5, 5, out)
    cp_b = _MultiNodeCheckpointer(b, "ck", 5, 5, out)
    # host 0 has snapshots {10, 20, 30}; host 1 only {10, 20}
    for it in (10, 20, 30):
        open(os.path.join(out, f"ck.{it}.0"), "wb").close()
    for it in (10, 20):
        open(os.path.join(out, f"ck.{it}.1"), "wb").close()

    # drive the consensus allgather on both sides (lock-step contract);
    # intercept the load to observe the chosen iteration
    chosen = {}

    class _T:
        pass

    import chainermn_tpu.extensions.checkpoint as ckpt_mod
    orig_load = ckpt_mod.load_npz
    ckpt_mod.load_npz = lambda path, trainer, strict=True: chosen.setdefault(
        "path", path)
    try:
        a_local = sorted(cp_a._scan(out))
        b_local = sorted(cp_b._scan(out))
        assert a_local == [10, 20, 30] and b_local == [10, 20]
        # simulate both hosts entering maybe_load: seed the box with the
        # peer's set first (lock-step)
        a._peer_box[1] = b_local
        got = cp_a.maybe_load(_T(), path=out)
        assert got == 20  # newest iteration present on BOTH hosts
        assert chosen["path"].endswith("ck.20.0")
    finally:
        ckpt_mod.load_npz = orig_load


def test_evaluator_averages_across_hosts():
    a, b = _host_pair()
    from chainermn_tpu.training.extensions import Evaluator

    class _Ev:
        def __init__(self, value):
            self.value = value

        def evaluate(self):
            return {"validation/main/loss": self.value}

    ev_a, ev_b = _Ev(1.0), _Ev(3.0)
    wrapped_a = ct.create_multi_node_evaluator(ev_a, a)
    # host 1 contributes its (value, count) metrics to the box first
    # (lock-step); no counts exposed -> weight 1 per host
    a._peer_box[1] = {"validation/main/loss": (3.0, 1.0)}
    result = wrapped_a.evaluate()
    assert result["validation/main/loss"] == pytest.approx(2.0)


def test_multi_node_iterator_replica_follows_master():
    from chainermn_tpu.dataset import SerialIterator
    a, b = _host_pair()
    master = ct.create_multi_node_iterator(
        SerialIterator(np.arange(8), 4, shuffle=False), a, rank_master=0)
    replica = ct.create_multi_node_iterator(
        SerialIterator(np.arange(8), 4, shuffle=False), b, rank_master=0)
    batch_m = master.next()       # master broadcasts into the box
    batch_r = replica.next()      # replica receives the same batch
    np.testing.assert_array_equal(batch_m, batch_r)
    assert replica.epoch_detail == master.epoch_detail

class _FakeHostHierComm(_FakeHostComm):
    """Two-host harness view of a HIERARCHICAL communicator: the device
    mesh carries the (dcn, ici) split while the host-level overrides
    present the matching 2-controller topology — the configuration a
    real 2-host pod reports."""

    def __init__(self, host, peer_box):
        super().__init__(host, peer_box, name="hierarchical",
                         inter_size=2)


def test_from_mesh_axes_two_level_topology():
    """MeshCommunicator.from_mesh_axis on a 2-axis mesh (ISSUE 6
    satellite): a (dcn, ici) tuple builds a hierarchical communicator
    whose intra/inter views match the mesh split, independent of the
    mesh's own axis order."""
    import jax as _jax
    from jax.sharding import Mesh
    devs = np.asarray(_jax.devices())
    if devs.size < 8:
        pytest.skip("needs 8 devices")
    mesh = Mesh(devs.reshape(2, 4), ("dcn", "ici"))
    comm = MeshCommunicator.from_mesh_axis(mesh, ("dcn", "ici"))
    assert comm.hierarchy == ("dcn", "ici")
    assert comm.topology == "hierarchical"
    assert comm.size == 8
    assert comm.dcn_size == 2 and comm.ici_size == 4
    assert comm.intra_size == 4  # mesh view of "ranks per node"
    assert comm.chunk_axes() == ("ici", "dcn")
    # the collectives address the ENCLOSING mesh (from_mesh_axis
    # contract) — its axes must carry the hierarchy's names
    assert comm.mesh is mesh

    # mesh declared in the REVERSED axis order: the communicator's
    # (dcn, ici) request must still resolve each axis by NAME
    mesh_r = Mesh(devs.reshape(4, 2), ("ici", "dcn"))
    comm_r = MeshCommunicator.from_mesh_axis(mesh_r, ("dcn", "ici"))
    assert comm_r.dcn_size == 2 and comm_r.ici_size == 4
    assert comm_r.hierarchy == ("dcn", "ici")

    # device grid ordering: group g of the dcn axis holds the devices
    # of mesh column/row g — the (dcn-major, ici-minor) flatten
    grid = np.asarray(comm._devices).reshape(2, 4)
    for d in range(2):
        assert {dev.id for dev in grid[d]} == \
            {dev.id for dev in mesh.devices[d]}


def test_from_mesh_axes_on_wider_mesh_picks_representatives():
    """On a 3-axis mesh the 2-tuple path spans (dcn, ici) and takes one
    representative device per remaining-axis position — same contract
    as the 1-axis from_mesh_axis."""
    import jax as _jax
    from jax.sharding import Mesh
    devs = np.asarray(_jax.devices())
    if devs.size < 8:
        pytest.skip("needs 8 devices")
    mesh = Mesh(devs.reshape(2, 2, 2), ("dcn", "ici", "mp"))
    comm = MeshCommunicator.from_mesh_axis(mesh, ("dcn", "ici"))
    assert comm.size == 4
    assert comm.dcn_size == 2 and comm.ici_size == 2
    got = {d.id for d in comm._devices}
    assert got == {int(mesh.devices[i, j, 0].id)
                   for i in range(2) for j in range(2)}


def test_hierarchical_ranks_under_two_host_harness():
    """intra_rank/inter_rank/intra_size/inter_size of a hierarchical
    communicator under the simulated 2-host topology: the host-level
    view (inter_*) matches the dcn split, the device-level view
    (intra_*) matches the ici split, and the reference slot arithmetic
    holds on both hosts."""
    box = {}
    a = _FakeHostHierComm(0, box)
    b = _FakeHostHierComm(1, box)
    for host, comm in enumerate((a, b)):
        assert comm.inter_rank == host
        assert comm.inter_size == 2 == comm.dcn_size
        assert comm.intra_size == 4 == comm.ici_size
        assert comm.intra_rank == 0  # first slot this controller drives
        assert 0 <= comm.intra_rank < comm.intra_size
        assert comm.inter_rank * comm.intra_size + comm.intra_rank \
            < comm.size
    # host-level object ops still shard by CONTROLLER rank: the
    # hierarchy must not break scatter_dataset's per-host split
    data = np.arange(64)
    shard_a = ct.scatter_dataset(data, a, shuffle=True, seed=7)
    shard_b = ct.scatter_dataset(data, b, shuffle=True, seed=7)
    assert len(shard_a) == len(shard_b) == 32
    assert not ({int(x) for x in shard_a} & {int(x) for x in shard_b})


def test_hierarchical_simulated_split_keeps_host_semantics():
    """A SIMULATED split (inter_size=2 on one controller) changes only
    the device-mesh view: the host/object-channel view stays
    single-controller, so scatter_dataset still feeds the full dataset
    (the compiled step expects the global batch) — the trap the
    dcn_size/inter_size separation exists to avoid."""
    comm = ct.create_communicator("hierarchical", inter_size=2)
    assert comm.dcn_size == 2 and comm.ici_size == comm.size // 2
    assert comm.inter_size == 1  # one controller process
    data = np.arange(48)
    shard = ct.scatter_dataset(data, comm)
    assert len(shard) == 48


def test_evaluator_weighted_by_sample_counts():
    """Cross-host metric reduction weights by per-key observation counts
    (VERDICT r1 Weak #6: ragged shards skewed the unweighted mean)."""
    import chainermn_tpu as ct

    a, b = _host_pair()

    class _Eval:
        def __init__(self, loss, n):
            self._loss, self._n = loss, n

        def evaluate(self):
            self._mn_counts = {"main/loss": self._n}
            return {"main/loss": self._loss}

    # host 0 evaluated 3 batches at loss 1.0; host 1 only 1 batch at 5.0
    ev_a = ct.create_multi_node_evaluator(_Eval(1.0, 3), a)
    ev_b = ct.create_multi_node_evaluator(_Eval(5.0, 1), b)
    a._peer_box.clear()
    b.allgather_obj({"main/loss": (5.0, 1.0)})  # host 1 contributes first
    out = ev_a.evaluate()
    # weighted: (1.0*3 + 5.0*1) / 4 = 2.0 — NOT the unweighted 3.0
    assert abs(out["main/loss"] - 2.0) < 1e-9, out
