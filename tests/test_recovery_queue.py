"""Bitrot guard for tools/tpu_recovery_queue.sh.

The queue runs unattended exactly ONCE when the TPU relay recovers —
its mechanics (per-step no-pipe capture, authoritative-line extraction,
BENCH_NOTES auto-record isolated from older log content) must be known
good beforehand.  A PATH-shimmed `python` stub stands in for every
bench/probe invocation; no jax, no device touch.
"""

import os
import stat
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
QUEUE = os.path.join(ROOT, "tools", "tpu_recovery_queue.sh")

# The stub prints a preliminary JSON line then the authoritative final
# line (bench.py's emit contract: the LAST line wins).  The final line
# encodes the env knobs so the test can verify every queue step ran
# with its intended config.
STUB = """#!/bin/bash
case "$*" in
  *bench.py*)
    echo '{"prelim": true}'
    echo '{"final": "'"${BENCH_MODEL:-resnet50}-bs${BENCH_BS:-d}-${BENCH_LAYOUT:-d}-scan${BENCH_SCAN:-d}-seq${BENCH_SEQ:-d}-ip${BENCH_INPUT_PIPELINE:-0}-rp${BENCH_REMAT_POLICY:-n}-dn${BENCH_DONATE:-1}-ex${BENCH_EXCHANGE:-d}-bk${BENCH_BUCKET_MB:-d}-is${BENCH_INTER_SIZE:-d}-sr${BENCH_STRIPE_RATIO:-d}-gd${BENCH_GRAD_DTYPE:-d}-ef${BENCH_ERROR_FEEDBACK:-1}-sq${BENCH_SERVE_QPS:-d}-st${BENCH_SERVE_TENANTS:-d}-sp${BENCH_SERVE_PREFIX:-d}-sd${BENCH_SERVE_DISAGG:-d}-stp${BENCH_SERVE_TP:-d}-pr${BENCH_PREEMPT_RANK:-d}-me${BENCH_MOE_EXPERTS:-d}-mk${BENCH_MOE_TOPK:-d}-fr${BENCH_SERVE_REPLICAS:-d}-fk${BENCH_FLEET_KILL_AT:-d}-di${BENCH_DIURNAL:-d}-dp${BENCH_DIURNAL_PERIOD:-d}-at${BENCH_AUTOTUNE:-d}-sk${BENCH_SERVE_SPEC_K:-d}-ch${BENCH_SERVE_CHUNK:-d}"'"}'
    ;;
  *bench_scaling.py*)
    echo "gloo curve header text"
    echo '{"gloo": "'"${@: -1}"'"}'
    ;;
  *probe_perf.py*)
    echo "flashcmp header text"
    echo '{"flash_vs_xla": "T2048"}'
    echo '{"flash_vs_xla": "T8192"}'
    ;;
  *flash_sweep.py*)
    echo "flash sweep header text"
    echo '{"probe": "flash_sweep", "T": 8192}'
    echo '{"probe": "flash_sweep", "wrote": "flash_budgets.json"}'
    ;;
  *profile_tpu_step.py*)
    echo "profile stub ran: $*"
    ;;
  *)
    echo "unexpected stub invocation: $*" >&2
    exit 1
    ;;
esac
"""


@pytest.mark.slow
def test_queue_records_only_this_runs_authoritative_lines(tmp_path):
    shim = tmp_path / "bin"
    shim.mkdir()
    py = shim / "python"
    py.write_text(STUB)
    py.chmod(py.stat().st_mode | stat.S_IEXEC)

    repo = tmp_path / "repo"
    (repo / "tools").mkdir(parents=True)
    notes = repo / "NOTES.md"
    notes.write_text("# notes\n")
    log = repo / "queue.log"
    # pre-contaminate the cumulative log with an aborted earlier run's
    # rows: they must NOT leak into the new auto-record section
    log.write_text('=== old run ===\n{"final": "STALE-OLD-ROW"}\n')

    env = dict(os.environ,
               PATH=f"{shim}{os.pathsep}{os.environ['PATH']}",
               QUEUE_REPO=str(repo), QUEUE_LOG=str(log),
               QUEUE_NOTES=str(notes))
    proc = subprocess.run(["bash", QUEUE], env=env, capture_output=True,
                          text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr[-2000:]

    notes_text = notes.read_text()
    assert "On-chip results" in notes_text
    # all 35 bench steps recorded, each once, in queue order.  Every
    # row's fingerprint tail carries the ISSUE 15 fleet knobs (-fr/-fk),
    # the ISSUE 16 diurnal knobs (-di/-dp), the ISSUE 19 autotune knob
    # (-at) and the ISSUE 20 speculative/chunked serving knobs
    # (-sk/-ch), default 'd'; the fleet, diurnal, autotune, spec and
    # chunk A/B rows pin theirs explicitly below
    expected = [
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",  # prewarm
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",  # flagship
        "resnet50-bs256-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "resnet50-bs256-NCHW-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "resnet50-bs256-d-scan8-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn0-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",  # donation
        "resnet50-bs512-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",  # headroom
        "resnet50-bsd-d-scand-seqd-ip1-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",  # input
        # ISSUE 5: bucket-MB sweep + reduce-scatter A/B legs
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exbucketed-bk1-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exbucketed-bk4-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exbucketed-bk16-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exreduce_scatter-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        # ISSUE 6: hierarchical two-level exchange, forced 2x4 split
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exhierarchical-bkd-is2-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        # ISSUE 8: DCN wire-dtype A/B + error-feedback ablation
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exhierarchical-bkd-is2-srd-gdnone-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exhierarchical-bkd-is2-srd-gdint8-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exhierarchical-bkd-is2-srd-gdint8-ef0-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exhierarchical_rs-bkd-is2-srd-gdint8-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        # ISSUE 11: striped multi-path exchange, 2x4 split at r=0.25
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exstriped-bkd-is2-sr0.25-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        # ISSUE 19: the autotuned striped leg (checklist item 11) — the
        # BENCH_AUTOTUNE fingerprint knob pinned explicitly, the stripe
        # ratio left free for the derived plan (srd)
        "resnet50-bsd-d-scand-seqd-ip0-rpn-dn1-exstriped-bkd-is2-srd"
        "-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd-frd-fkd-did-dpd-at1",
        "transformer-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "transformer-bs2-d-scand-seq8192-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "transformer-bs2-d-scand-seq8192-ip0-rpdots-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "longcontext-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",  # flash
        # ISSUE 9: serving engine rows (flagship qps16x4 + saturation)
        "serving-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "serving-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sq64-st8-spd-sdd-stpd-prd-med-mkd",
        # ISSUE 13: serving scale-out A/Bs (prefix-off, disagg, tp=2)
        "serving-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-sp0-sdd-stpd-prd-med-mkd",
        "serving-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sq64-std-spd-sd1-stpd-prd-med-mkd",
        "serving-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stp2-prd-med-mkd",
        # ISSUE 15: the serving-fleet kill-under-load A/B row (the
        # BENCH_SERVE_REPLICAS/BENCH_FLEET_KILL_AT fingerprint knobs
        # pinned explicitly)
        "serving-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd-fr2-fk40",
        # ISSUE 16: the diurnal capacity-transfer A/B row (the
        # BENCH_DIURNAL/BENCH_DIURNAL_PERIOD fingerprint knobs pinned
        # explicitly; fleet knobs default)
        "serving-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1"
        "-sqd-std-spd-sdd-stpd-prd-med-mkd-frd-fkd-di1-dp30",
        # ISSUE 20: speculative-decode and chunked-prefill A/B rows (the
        # BENCH_SERVE_SPEC_K / BENCH_SERVE_CHUNK fingerprint knobs
        # pinned explicitly, one per row)
        "serving-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1"
        "-sqd-std-spd-sdd-stpd-prd-med-mkd-frd-fkd-did-dpd-atd-sk4-chd",
        "serving-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1"
        "-sqd-std-spd-sdd-stpd-prd-med-mkd-frd-fkd-did-dpd-atd-skd-ch64",
        # ISSUE 12: MoE dispatch A/B rows (flat vs two-stage vs
        # two-stage+int8; BENCH_MOE_* fingerprint knobs pinned — the
        # int8 row sets BENCH_MOE_TOPK explicitly)
        "moe-bsd-d-scand-seqd-ip0-rpn-dn1-exd-bkd-isd-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "moe-bsd-d-scand-seqd-ip0-rpn-dn1-exhierarchical-bkd-is2-srd-gdd-ef1-sqd-std-spd-sdd-stpd-prd-med-mkd",
        "moe-bsd-d-scand-seqd-ip0-rpn-dn1-exhierarchical-bkd-is2-srd-gdint8-ef1-sqd-std-spd-sdd-stpd-prd-med-mk1",
    ]
    expected = [e if e.endswith(("-fk40", "-dp30", "-at1",
                                 "-chd", "-ch64"))
                else e + "-frd-fkd" for e in expected]
    expected = [e if e.endswith(("-dp30", "-at1", "-chd", "-ch64"))
                else e + "-did-dpd" for e in expected]
    expected = [e if e.endswith(("-at1", "-chd", "-ch64")) else e + "-atd"
                for e in expected]
    expected = [e if e.endswith(("-chd", "-ch64")) else e + "-skd-chd"
                for e in expected]
    finals = [ln for ln in notes_text.splitlines() if '"final"' in ln]
    assert [f'{{"final": "{e}"}}' for e in expected] == finals
    # exposed-comm A/B (ISSUE 5 + 6 + 10 + 11 + 19): four gloo exchange
    # curves, the ONE self-gating autotune invocation that replaced the
    # three-point striped ratio sweep (its last CLI arg is the
    # --autotune flag itself), and the elastic preempt-and-rejoin A/B
    # (its last CLI arg is the preempted rank — the
    # BENCH_PREEMPT_RANK-class knob pinned above), folded in their own
    # section after the main fold
    # (ISSUE 15 adds the fleet kill-under-load curve — its last CLI arg
    # is the kill decode step; ISSUE 16 adds the capacity-transfer A/B —
    # its last CLI arg is the --capacity flag itself)
    assert [ln for ln in notes_text.splitlines() if '"gloo"' in ln] == [
        '{"gloo": "flat"}', '{"gloo": "bucketed"}',
        '{"gloo": "reduce_scatter"}', '{"gloo": "hierarchical"}',
        '{"gloo": "--autotune"}',
        '{"gloo": "1"}', '{"gloo": "2"}', '{"gloo": "--capacity"}']
    assert notes_text.index("On-chip results") \
        < notes_text.index("Exposed-comm A/B rows")
    # flashcmp rows recorded in their own section AFTER the main fold
    # (the fold must precede the unsupervised wedge-capable steps)
    assert notes_text.count('"flash_vs_xla"') == 2
    assert "Flash-vs-XLA attention rows" in notes_text
    assert notes_text.index("On-chip results") \
        < notes_text.index("Flash-vs-XLA attention rows")
    # flash backward tile-sweep rows folded too (ISSUE 4), after the
    # supervised benches' fold like every unsupervised step's section
    assert notes_text.count('"flash_sweep"') == 2
    assert notes_text.index("On-chip results") \
        < notes_text.index("Flash backward tile-sweep rows")
    # isolation: preliminary lines and the old run's rows are excluded
    assert '"prelim"' not in notes_text
    assert "STALE-OLD-ROW" not in notes_text
    # the cumulative log keeps everything, including the old content
    log_text = log.read_text()
    assert "STALE-OLD-ROW" in log_text
    assert "=== TPU recovery queue done" in log_text
    # all three profile invocations (NHWC + NCHW captures, then the
    # offline layout compare) ran after the auto-record
    assert log_text.count("profile stub ran") == 3
    assert "--compare" in log_text


FLASHCMP_NO_JSON_STUB = STUB.replace(
    """  *probe_perf.py*)
    echo "flashcmp header text"
    echo '{"flash_vs_xla": "T2048"}'
    echo '{"flash_vs_xla": "T8192"}'
    ;;""",
    """  *probe_perf.py*)
    echo "flashcmp crashed before any JSON"
    exit 1
    ;;""")


@pytest.mark.slow
def test_queue_flashcmp_failure_appends_no_empty_section(tmp_path):
    """When the flash-vs-xla probe wedges/crashes before printing JSON,
    the queue must still complete (|| true), the thirty-five bench
    rows must already be folded, and NO empty 'Flash-vs-XLA' section
    may be appended."""
    shim = tmp_path / "bin"
    shim.mkdir()
    py = shim / "python"
    py.write_text(FLASHCMP_NO_JSON_STUB)
    py.chmod(py.stat().st_mode | stat.S_IEXEC)

    repo = tmp_path / "repo"
    (repo / "tools").mkdir(parents=True)
    notes = repo / "NOTES.md"
    notes.write_text("# notes\n")

    env = dict(os.environ,
               PATH=f"{shim}{os.pathsep}{os.environ['PATH']}",
               QUEUE_REPO=str(repo), QUEUE_LOG=str(repo / "queue.log"),
               QUEUE_NOTES=str(notes))
    proc = subprocess.run(["bash", QUEUE], env=env, capture_output=True,
                          text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr[-2000:]
    notes_text = notes.read_text()
    assert "On-chip results" in notes_text
    assert len([ln for ln in notes_text.splitlines()
                if '"final"' in ln]) == 35
    assert "Flash-vs-XLA" not in notes_text
