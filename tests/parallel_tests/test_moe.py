"""Expert-parallel Switch MoE: routing correctness + training, plus the
two-stage (ici × dcn) dispatch property suite (ISSUE 12): every token
crosses the two hops exactly once (two-stage == flat bit-for-bit, round
trip == identity), on-host tokens never touch the slow fabric (they stay
bit-exact under a quantized DCN crossing), routing is deterministic
across ranks, capacity overflow is reported honestly (``dropped_frac``),
and the quantized dispatch gates on convergence parity (the 5%
final-loss band) on the MoE transformer vertical while the lossless
two-stage path is bit-parity with the flat reference."""

import warnings

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu.parallel import switch_moe
from chainermn_tpu.parallel import moe as moe_mod

COMM = None
COMM_H = None


def setup_module(module):
    global COMM, COMM_H
    COMM = ct.create_communicator("jax_ici", axis_name="ep")
    COMM_H = ct.create_communicator("hierarchical", inter_size=2)


def _weights(D=8, H=16, seed=0):
    rng = np.random.RandomState(seed)
    E = COMM.size
    router = rng.normal(0, 0.5, (D, E)).astype(np.float32)
    w_in = rng.normal(0, 0.3, (E, D, H)).astype(np.float32)
    b_in = np.zeros((E, H), np.float32)
    w_out = rng.normal(0, 0.3, (E, H, D)).astype(np.float32)
    b_out = np.zeros((E, D), np.float32)
    return map(jnp.asarray, (router, w_in, b_in, w_out, b_out))


def test_moe_forward_matches_dense_routing():
    """With generous capacity, MoE output == per-token expert MLP."""
    D, H = 8, 16
    router, w_in, b_in, w_out, b_out = _weights(D, H)
    E = COMM.size
    T_local = 4
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.normal(0, 1, (E * T_local, D)).astype(np.float32))

    def body(x, router, w_in, b_in, w_out, b_out):
        out, aux = switch_moe(COMM, x, router, w_in[0], b_in[0],
                              w_out[0], b_out[0], capacity_factor=float(E))
        return out, aux["aux_loss"].reshape(1)

    out, aux = COMM.run_spmd(
        body, x, router, w_in, b_in, w_out, b_out,
        in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
        out_specs=(P("ep"), P("ep")))

    # dense reference: every token through its argmax expert
    xn = np.asarray(x)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(xn) @ router, axis=-1))
    idx = probs.argmax(-1)
    expect = np.zeros_like(xn)
    for t in range(xn.shape[0]):
        e = idx[t]
        h = np.asarray(jax.nn.gelu(jnp.asarray(
            xn[t] @ np.asarray(w_in)[e] + np.asarray(b_in)[e])))
        expect[t] = (h @ np.asarray(w_out)[e] + np.asarray(b_out)[e]) \
            * probs[t, e]
    np.testing.assert_allclose(np.asarray(out), expect, rtol=2e-4,
                               atol=2e-5)


def test_moe_trains():
    D, H = 8, 16
    router, w_in, b_in, w_out, b_out = _weights(D, H, seed=2)
    E = COMM.size
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.normal(0, 1, (E * 8, D)).astype(np.float32))
    target = jnp.asarray(rng.normal(0, 1, (E * 8, D)).astype(np.float32))

    def body(params, x, target):
        router, w_in, b_in, w_out, b_out = params

        def loss(params):
            router, w_in, b_in, w_out, b_out = params
            out, aux = switch_moe(COMM, x, router, w_in[0], b_in[0],
                                  w_out[0], b_out[0], capacity_factor=2.0)
            return jnp.mean((out - target) ** 2) + 0.01 * aux["aux_loss"]

        l, g = jax.value_and_grad(loss)(params)
        return l.reshape(1), g

    spec = (P(), P("ep"), P("ep"), P("ep"), P("ep"))
    params = (router, w_in, b_in, w_out, b_out)
    for _ in range(12):
        l, g = COMM.run_spmd(
            body, params, x, target,
            in_specs=(spec, P("ep"), P("ep")),
            out_specs=(P("ep"), spec))
        params = jax.tree.map(lambda p, gg: p - 0.5 * gg, params, g)
        if '_l0' not in dir():
            _l0 = float(np.asarray(l)[0])
    assert float(np.asarray(l)[0]) < _l0


def test_topk_moe_matches_dense_topk():
    """k=2 routing at generous capacity == dense top-2 mixture."""
    from chainermn_tpu.parallel import moe_dispatch_combine_topk
    D, H = 8, 16
    router, w_in, b_in, w_out, b_out = _weights(D, H, seed=4)
    E = COMM.size
    T_local = 4
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.normal(0, 1, (E * T_local, D)).astype(np.float32))

    def body(x, router, w_in, b_in, w_out, b_out):
        def expert(h):
            return jax.nn.gelu(h @ w_in[0] + b_in[0]) @ w_out[0] + b_out[0]
        out, aux = moe_dispatch_combine_topk(
            COMM, x, x @ router, expert, k=2, capacity_factor=float(E))
        return out

    out = COMM.run_spmd(
        body, x, router, w_in, b_in, w_out, b_out,
        in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
        out_specs=P("ep"))

    xn = np.asarray(x)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(xn) @ router, axis=-1))
    topk = np.argsort(-probs, axis=1)[:, :2]
    expect = np.zeros_like(xn)
    for t in range(xn.shape[0]):
        g = probs[t, topk[t]]
        g = g / g.sum()
        for j, e in enumerate(topk[t]):
            h = np.asarray(jax.nn.gelu(jnp.asarray(
                xn[t] @ np.asarray(w_in)[e] + np.asarray(b_in)[e])))
            expect[t] += g[j] * (h @ np.asarray(w_out)[e]
                                 + np.asarray(b_out)[e])
    np.testing.assert_allclose(np.asarray(out), expect, rtol=3e-4,
                               atol=3e-5)


# -- two-stage dispatch over the ici × dcn hierarchy (ISSUE 12) --------------

def _stacked_exchange(comm, base, ops):
    """Run a list of ``(two_stage, combine)`` exchange legs over the
    stacked ``[size*E, C, D]`` sentinel, chaining each leg on the
    PREVIOUS leg's output when ``chain`` is set."""
    axes = comm.axis_name

    def body(buf):
        outs = []
        cur = buf
        for two_stage, combine, chain in ops:
            src = cur if chain else buf
            cur = moe_mod._exchange(comm, src, two_stage, combine=combine)
            outs.append(cur)
        return tuple(outs)

    return comm.run_spmd(body, jnp.asarray(base), in_specs=(P(axes),),
                         out_specs=tuple(P(axes) for _ in ops))


def test_two_stage_exchange_every_token_exactly_once():
    """The routing-plan conservation property: the two-stage exchange is
    the SAME permutation as the flat single-axis all_to_all (every
    unique sentinel value lands exactly once, at the flat reference's
    position — nothing duplicated, dropped, or misrouted across the two
    hops), and the combine exchange is its exact inverse (round trip ==
    identity)."""
    E, C, D = COMM_H.size, 4, 2
    base = np.arange(E * E * C * D, dtype=np.float32) \
        .reshape(E * E, C, D)
    flat, two, back = _stacked_exchange(
        COMM_H, base, [(False, False, False), (True, False, False),
                       (True, True, True)])
    np.testing.assert_array_equal(np.asarray(two), np.asarray(flat))
    np.testing.assert_array_equal(np.asarray(back), base)


def test_two_stage_exchange_deterministic_across_ranks():
    """Determinism: the exchange is a pure function of the buffer — a
    freshly constructed communicator over the same devices reproduces
    it bitwise (the cross-rank contract: every rank traces the same
    plan from the same arguments)."""
    E, C, D = COMM_H.size, 3, 2
    rng = np.random.RandomState(7)
    base = rng.normal(0, 1, (E * E, C, D)).astype(np.float32)
    (a,) = _stacked_exchange(COMM_H, base, [(True, False, False)])
    comm2 = ct.create_communicator("hierarchical", inter_size=2)
    (b,) = _stacked_exchange(comm2, base, [(True, False, False)])
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_on_host_tokens_never_cross_dcn():
    """The behavioral pin of "on-host tokens never touch the slow
    fabric": under an int8 DCN crossing, blocks whose SOURCE host is
    the receiving host are bit-exact vs the lossless exchange (they
    never met the codebook), while off-host blocks demonstrably
    quantized."""
    comm_q = ct.create_communicator("hierarchical", inter_size=2,
                                    allreduce_grad_dtype={"dcn": "int8"})
    E, C, D = comm_q.size, 4, 3
    intra = comm_q.ici_size
    rng = np.random.RandomState(3)
    base = rng.normal(0, 1, (E * E, C, D)).astype(np.float32)
    (lossless,) = _stacked_exchange(COMM_H, base, [(True, False, False)])
    (quant,) = _stacked_exchange(comm_q, base, [(True, False, False)])
    lossless, quant = np.asarray(lossless), np.asarray(quant)
    changed_off_host = 0
    for r in range(E):
        block = slice(r * E, (r + 1) * E)  # rank r's [E, C, D] result
        lo, qo = lossless[block], quant[block]
        for src in range(E):
            if src // intra == r // intra:   # same-host source block
                np.testing.assert_array_equal(
                    qo[src], lo[src],
                    err_msg=f"on-host block {src}->{r} was quantized")
            elif (qo[src] != lo[src]).any():
                changed_off_host += 1
    assert changed_off_host > 0, \
        "no off-host block changed: the int8 crossing is not engaging"


def test_topk_two_stage_matches_flat_bitwise():
    """The GShard top-k path shares the exchange: two-stage lossless ==
    flat single-axis, bit for bit."""
    from chainermn_tpu.parallel import moe_dispatch_combine_topk
    E = COMM_H.size
    T, D = 8, 8
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.normal(0, 1, (E * T, D)).astype(np.float32))
    router = jnp.asarray(rng.normal(0, 0.5, (D, E)).astype(np.float32))
    axes = COMM_H.axis_name

    def body(x, router):
        def run(two_stage):
            out, _ = moe_dispatch_combine_topk(
                COMM_H, x, x @ router, lambda h: h * 2.0 + 1.0, k=2,
                capacity_factor=2.0, two_stage=two_stage)
            return out
        return run(False), run(True)

    flat, two = COMM_H.run_spmd(body, x, router,
                                in_specs=(P(axes), P()),
                                out_specs=(P(axes), P(axes)))
    np.testing.assert_array_equal(np.asarray(two), np.asarray(flat))


def test_dropped_frac_reports_capacity_overflow():
    """The capacity-honesty satellite: ``dropped_frac`` equals the
    dense-reference count of tokens beyond each expert's queue, and the
    load-balancing statistics (``frac``/``mean_prob``) are reported
    next to it with ``aux_loss`` their exact contraction."""
    from chainermn_tpu.parallel import moe_dispatch_combine
    E = COMM.size
    T, D = 16, 8
    capacity_factor = 0.5
    capacity = max(1, int(capacity_factor * T / E))
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.normal(0, 1, (E * T, D)).astype(np.float32))
    router = jnp.asarray(rng.normal(0, 0.5, (D, E)).astype(np.float32))

    def body(x, router):
        out, aux = moe_dispatch_combine(
            COMM, x, x @ router, lambda h: h, 
            capacity_factor=capacity_factor)
        return (out, aux["dropped_frac"].reshape(1),
                aux["frac"], aux["mean_prob"], aux["aux_loss"].reshape(1))

    out, dropped, frac, mean_prob, aux_loss = COMM.run_spmd(
        body, x, router, in_specs=(P("ep"), P()),
        out_specs=(P("ep"), P("ep"), P("ep"), P("ep"), P("ep")))
    dropped = np.asarray(dropped)
    frac = np.asarray(frac).reshape(E, E)
    mean_prob = np.asarray(mean_prob).reshape(E, E)
    aux_loss = np.asarray(aux_loss)

    probs = np.asarray(jax.nn.softmax(x @ router, axis=-1))
    idx = probs.argmax(-1).reshape(E, T)  # [rank, local token]
    for r in range(E):
        counts = np.zeros(E, dtype=int)
        kept = 0
        for e in idx[r]:
            if counts[e] < capacity:
                kept += 1
            counts[e] += 1
        assert dropped[r] == pytest.approx(1.0 - kept / T, abs=1e-6), r
        np.testing.assert_allclose(
            aux_loss[r], E * np.sum(frac[r] * mean_prob[r]), rtol=1e-6)
    assert (dropped > 0).any(), \
        "capacity_factor=0.5 dropped nothing: the test is vacuous"


def test_two_stage_on_flat_comm_is_loud():
    """Guard rail: requesting the two-stage exchange on a one-fabric
    communicator is a construction-site error, never a silent flat
    run."""
    from chainermn_tpu.parallel import moe_dispatch_combine
    x = jnp.zeros((8, 4))
    with pytest.raises(ValueError, match="two_stage"):
        COMM.run_spmd(
            lambda x: moe_dispatch_combine(
                COMM, x, jnp.zeros((x.shape[0], COMM.size)),
                lambda h: h, two_stage=True)[0],
            x, in_specs=(P("ep"),), out_specs=P("ep"))


def test_hierarchy_flat_hatch_drops_two_stage_with_warning(monkeypatch):
    """The CHAINERMN_TPU_HIERARCHY=flat hatch drops two-stage routing
    with the one-time warning pattern PR 11 established for striping —
    precisely: only a communicator the hatch actually DEGRADED (a
    requested hierarchy collapsed to one axis) warns; a comm that was
    never hierarchical keeps the loud two_stage=True error and never
    warns, whatever the environment says.  The dropped run IS the flat
    dispatch, bit for bit."""
    from chainermn_tpu.parallel import moe_dispatch_combine
    monkeypatch.setenv("CHAINERMN_TPU_HIERARCHY", "flat")
    monkeypatch.setattr(ct.communicators, "_WARNED_FLAT_TWO_STAGE",
                        set())
    # a requested hierarchy, collapsed by the hatch to one flat axis
    hatch_comm = ct.create_communicator("hierarchical", inter_size=2,
                                        axis_name="moe_hatch")
    assert hatch_comm.hierarchy is None
    E = hatch_comm.size
    rng = np.random.RandomState(13)
    x = jnp.asarray(rng.normal(0, 1, (E * 4, 8)).astype(np.float32))
    router = jnp.asarray(rng.normal(0, 0.5, (8, E)).astype(np.float32))

    def run(comm, two_stage):
        axes = comm.axis_name

        def body(x, router):
            out, _ = moe_dispatch_combine(
                comm, x, x @ router, lambda h: h * 2.0,
                capacity_factor=2.0, two_stage=two_stage)
            return out
        return comm.run_spmd(body, x, router, in_specs=(P(axes), P()),
                             out_specs=P(axes))

    with pytest.warns(UserWarning, match="two-stage MoE routing"):
        dropped = run(hatch_comm, True)
    # one-time: a second resolution does not warn again
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        again = run(hatch_comm, True)
    # a NEVER-hierarchical comm stays loud even with the hatch set
    with pytest.raises(ValueError, match="two_stage"):
        run(COMM, True)
    flat = run(hatch_comm, False)
    np.testing.assert_array_equal(np.asarray(dropped), np.asarray(flat))
    np.testing.assert_array_equal(np.asarray(again), np.asarray(flat))


def _train_moe_vertical(dispatch_dtype=None, two_stage=None, steps=25):
    """Train the MoE transformer vertical (the BENCH_MODEL=moe family,
    scaled tier-1 small) through the multi-node optimizer on the
    simulated 2-host split.  ``dispatch_dtype`` compresses ONLY the
    token dispatch's DCN crossing (a separate ep communicator binding
    the same (dcn, ici) axes) while the gradient exchange stays
    lossless — the gradient wire is PR 7's already-gated story, and
    folding it in would attribute its noise to the dispatch."""
    from chainermn_tpu.core.optimizer import Adam
    from chainermn_tpu.models import MoETransformerLM
    comm = ct.create_communicator("hierarchical", inter_size=2)
    ep = comm if dispatch_dtype is None else ct.create_communicator(
        "hierarchical", inter_size=2,
        allreduce_grad_dtype=dispatch_dtype)
    model = MoETransformerLM(n_vocab=64, ep_comm=ep, d_model=32,
                             n_heads=2, n_layers=2, max_len=16, seed=0,
                             two_stage=two_stage)
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        Adam(alpha=3e-3), comm).setup(model)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, 64, (8, 16)).astype(np.int32))
    t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))
    return [float(opt.update(model, x, t)) for _ in range(steps)]


def test_moe_vertical_convergence_parity():
    """The acceptance gates on the BENCH_MODEL=moe vertical: the
    lossless two-stage dispatch trains the SAME trajectory as the
    explicit flat single-axis dispatch on the same communicator (the
    exchange itself is bit-equal — pinned by the dispatch-level tests
    above and the golden-equality gate in test_exchange_equivalence —
    so the only admissible trajectory difference is XLA reassociating
    f32 math around the differing collective structure: the same
    reduction-order tolerance the hierarchical gradient exchange
    gets), and the int8 DCN crossing sits inside the committed 5%
    final-loss band of the lossless run (the EF-style
    convergence-parity discipline — the codebook rounds, so
    bit-exactness is not the claim)."""
    lossless = _train_moe_vertical(two_stage=True)
    flat = _train_moe_vertical(two_stage=False)
    np.testing.assert_allclose(
        lossless, flat, rtol=1e-5, atol=1e-7,
        err_msg="two-stage lossless dispatch drifted from the flat "
                "reference beyond reduction-order noise")
    assert lossless[-1] < lossless[0], "the vertical does not learn"
    for wire in ({"dcn": "int8"}, {"dcn": "bfloat16"}):
        quant = _train_moe_vertical(dispatch_dtype=wire)
        assert np.isfinite(quant).all()
        assert abs(quant[-1] - lossless[-1]) <= 0.05 * lossless[-1], (
            f"{wire} dispatch final loss {quant[-1]} outside the 5% "
            f"band of lossless {lossless[-1]}")
