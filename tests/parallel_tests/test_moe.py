"""Expert-parallel Switch MoE: routing correctness + training."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu.parallel import switch_moe

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici", axis_name="ep")


def _weights(D=8, H=16, seed=0):
    rng = np.random.RandomState(seed)
    E = COMM.size
    router = rng.normal(0, 0.5, (D, E)).astype(np.float32)
    w_in = rng.normal(0, 0.3, (E, D, H)).astype(np.float32)
    b_in = np.zeros((E, H), np.float32)
    w_out = rng.normal(0, 0.3, (E, H, D)).astype(np.float32)
    b_out = np.zeros((E, D), np.float32)
    return map(jnp.asarray, (router, w_in, b_in, w_out, b_out))


def test_moe_forward_matches_dense_routing():
    """With generous capacity, MoE output == per-token expert MLP."""
    D, H = 8, 16
    router, w_in, b_in, w_out, b_out = _weights(D, H)
    E = COMM.size
    T_local = 4
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.normal(0, 1, (E * T_local, D)).astype(np.float32))

    def body(x, router, w_in, b_in, w_out, b_out):
        out, aux = switch_moe(COMM, x, router, w_in[0], b_in[0],
                              w_out[0], b_out[0], capacity_factor=float(E))
        return out, aux["aux_loss"].reshape(1)

    out, aux = COMM.run_spmd(
        body, x, router, w_in, b_in, w_out, b_out,
        in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
        out_specs=(P("ep"), P("ep")))

    # dense reference: every token through its argmax expert
    xn = np.asarray(x)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(xn) @ router, axis=-1))
    idx = probs.argmax(-1)
    expect = np.zeros_like(xn)
    for t in range(xn.shape[0]):
        e = idx[t]
        h = np.asarray(jax.nn.gelu(jnp.asarray(
            xn[t] @ np.asarray(w_in)[e] + np.asarray(b_in)[e])))
        expect[t] = (h @ np.asarray(w_out)[e] + np.asarray(b_out)[e]) \
            * probs[t, e]
    np.testing.assert_allclose(np.asarray(out), expect, rtol=2e-4,
                               atol=2e-5)


def test_moe_trains():
    D, H = 8, 16
    router, w_in, b_in, w_out, b_out = _weights(D, H, seed=2)
    E = COMM.size
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.normal(0, 1, (E * 8, D)).astype(np.float32))
    target = jnp.asarray(rng.normal(0, 1, (E * 8, D)).astype(np.float32))

    def body(params, x, target):
        router, w_in, b_in, w_out, b_out = params

        def loss(params):
            router, w_in, b_in, w_out, b_out = params
            out, aux = switch_moe(COMM, x, router, w_in[0], b_in[0],
                                  w_out[0], b_out[0], capacity_factor=2.0)
            return jnp.mean((out - target) ** 2) + 0.01 * aux["aux_loss"]

        l, g = jax.value_and_grad(loss)(params)
        return l.reshape(1), g

    spec = (P(), P("ep"), P("ep"), P("ep"), P("ep"))
    params = (router, w_in, b_in, w_out, b_out)
    for _ in range(12):
        l, g = COMM.run_spmd(
            body, params, x, target,
            in_specs=(spec, P("ep"), P("ep")),
            out_specs=(P("ep"), spec))
        params = jax.tree.map(lambda p, gg: p - 0.5 * gg, params, g)
        if '_l0' not in dir():
            _l0 = float(np.asarray(l)[0])
    assert float(np.asarray(l)[0]) < _l0


def test_topk_moe_matches_dense_topk():
    """k=2 routing at generous capacity == dense top-2 mixture."""
    from chainermn_tpu.parallel import moe_dispatch_combine_topk
    D, H = 8, 16
    router, w_in, b_in, w_out, b_out = _weights(D, H, seed=4)
    E = COMM.size
    T_local = 4
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.normal(0, 1, (E * T_local, D)).astype(np.float32))

    def body(x, router, w_in, b_in, w_out, b_out):
        def expert(h):
            return jax.nn.gelu(h @ w_in[0] + b_in[0]) @ w_out[0] + b_out[0]
        out, aux = moe_dispatch_combine_topk(
            COMM, x, x @ router, expert, k=2, capacity_factor=float(E))
        return out

    out = COMM.run_spmd(
        body, x, router, w_in, b_in, w_out, b_out,
        in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
        out_specs=P("ep"))

    xn = np.asarray(x)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(xn) @ router, axis=-1))
    topk = np.argsort(-probs, axis=1)[:, :2]
    expect = np.zeros_like(xn)
    for t in range(xn.shape[0]):
        g = probs[t, topk[t]]
        g = g / g.sum()
        for j, e in enumerate(topk[t]):
            h = np.asarray(jax.nn.gelu(jnp.asarray(
                xn[t] @ np.asarray(w_in)[e] + np.asarray(b_in)[e])))
            expect[t] += g[j] * (h @ np.asarray(w_out)[e]
                                 + np.asarray(b_out)[e])
    np.testing.assert_allclose(np.asarray(out), expect, rtol=3e-4,
                               atol=3e-5)
