"""1F1B schedule: gradients and loss equal the sequential stack."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu.parallel import one_f_one_b, split_microbatches

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici", axis_name="fb")


def _stage_fn(params, h):
    W, b = params
    return jnp.tanh(h @ W + b)


def _loss_fn(out, y):
    return jnp.mean((out - y) ** 2)


def _params(seed):
    rng = np.random.RandomState(seed)
    S = COMM.size
    W = rng.normal(0, 0.5, (S, 8, 8)).astype(np.float32)
    b = rng.normal(0, 0.1, (S, 8)).astype(np.float32)
    return jnp.asarray(W), jnp.asarray(b)


def test_1f1b_matches_sequential_gradients():
    W, b = _params(0)
    rng = np.random.RandomState(1)
    M = 6
    x = jnp.asarray(rng.normal(0, 1, (M * 4, 8)).astype(np.float32))
    y = jnp.asarray(rng.normal(0, 1, (M * 4, 8)).astype(np.float32))
    xm = split_microbatches(x, M)
    ym = split_microbatches(y, M)

    def body(Wl, bl, xm, ym):
        loss, (gW, gb) = one_f_one_b(COMM, _stage_fn, _loss_fn,
                                     (Wl[0], bl[0]), xm, ym)
        return loss.reshape(1), gW[None], gb[None]

    loss, gW, gb = jax.jit(jax.shard_map(
        body, mesh=COMM.mesh,
        in_specs=(P("fb"), P("fb"), P(), P()),
        out_specs=(P("fb"), P("fb"), P("fb")),
        check_vma=False))(W, b, xm, ym)

    # sequential reference: mean over microbatches of per-microbatch loss
    def ref_loss(params):
        W, b = params
        total = 0.0
        for i in range(M):
            h = xm[i]
            for s in range(COMM.size):
                h = _stage_fn((W[s], b[s]), h)
            total = total + _loss_fn(h, ym[i])
        return total / M

    l_ref, (gW_ref, gb_ref) = jax.value_and_grad(ref_loss)((W, b))
    np.testing.assert_allclose(float(np.asarray(loss)[0]), float(l_ref),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gW), np.asarray(gW_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb_ref),
                               rtol=1e-4, atol=1e-5)


def test_1f1b_single_microbatch():
    W, b = _params(2)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.normal(0, 1, (1, 4, 8)).astype(np.float32))
    y = jnp.asarray(rng.normal(0, 1, (1, 4, 8)).astype(np.float32))

    def body(Wl, bl, xm, ym):
        loss, _ = one_f_one_b(COMM, _stage_fn, _loss_fn,
                              (Wl[0], bl[0]), xm, ym)
        return loss.reshape(1)

    loss = jax.jit(jax.shard_map(
        body, mesh=COMM.mesh,
        in_specs=(P("fb"), P("fb"), P(), P()),
        out_specs=P("fb"), check_vma=False))(W, b, x, y)
    h = x[0]
    for s in range(COMM.size):
        h = _stage_fn((W[s], b[s]), h)
    np.testing.assert_allclose(float(np.asarray(loss)[0]),
                               float(_loss_fn(h, y[0])), rtol=1e-5)


def test_pipeline_train_step_converges():
    import optax
    from chainermn_tpu.parallel import make_pipeline_train_step
    W, b = _params(5)
    params = (W, b)
    tx = optax.sgd(0.2)
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.normal(0, 1, (16, 8)).astype(np.float32))
    y = jnp.asarray(rng.normal(0, 0.3, (16, 8)).astype(np.float32))
    step = make_pipeline_train_step(COMM, _stage_fn, _loss_fn, tx,
                                    n_microbatches=4)
    per_stage = jax.tree.map(lambda p: p[0], params)
    opt_state = tx.init(per_stage)
    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(np.asarray(loss)))
    assert losses[-1] < losses[0] * 0.7


def test_1f1b_heterogeneous_stages_match_sequential():
    """Different per-stage computation (relu/gelu/tanh/identity mix via
    heterogeneous_stage_fn's lax.switch) still reproduces the sequential
    stack's loss and gradients exactly."""
    from chainermn_tpu.parallel import heterogeneous_stage_fn

    acts = [jax.nn.relu, jax.nn.gelu, jnp.tanh, lambda h: h]

    def make_stage(act):
        return lambda params, h: act(h @ params[0] + params[1])

    S = COMM.size
    stage_fns = [make_stage(acts[s % len(acts)]) for s in range(S)]
    het_fn = heterogeneous_stage_fn(stage_fns, "fb")

    W, b = _params(7)
    rng = np.random.RandomState(8)
    M = 4
    x = jnp.asarray(rng.normal(0, 1, (M * 4, 8)).astype(np.float32))
    y = jnp.asarray(rng.normal(0, 1, (M * 4, 8)).astype(np.float32))
    xm = split_microbatches(x, M)
    ym = split_microbatches(y, M)

    def body(Wl, bl, xm, ym):
        loss, (gW, gb) = one_f_one_b(COMM, het_fn, _loss_fn,
                                     (Wl[0], bl[0]), xm, ym)
        return loss.reshape(1), gW[None], gb[None]

    loss, gW, gb = jax.jit(jax.shard_map(
        body, mesh=COMM.mesh,
        in_specs=(P("fb"), P("fb"), P(), P()),
        out_specs=(P("fb"), P("fb"), P("fb")),
        check_vma=False))(W, b, xm, ym)

    def ref_loss(params):
        W, b = params
        total = 0.0
        for i in range(M):
            h = xm[i]
            for s in range(S):
                h = stage_fns[s]((W[s], b[s]), h)
            total = total + _loss_fn(h, ym[i])
        return total / M

    l_ref, (gW_ref, gb_ref) = jax.value_and_grad(ref_loss)((W, b))
    np.testing.assert_allclose(float(np.asarray(loss)[0]), float(l_ref),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gW), np.asarray(gW_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb_ref),
                               rtol=1e-4, atol=1e-5)


def _singular_stage_fn(params, h):
    """VJP singular at h == 0: d|h|/dh = h/|h| is NaN at 0.  A stage
    like this poisons every gradient if warmup/drain ticks feed zeros
    through the schedule (VERDICT r2 Weak #8 stress case)."""
    W, b = params
    return jnp.tanh(jnp.sqrt(h * h) @ W + b)


def test_1f1b_zero_singular_stage_grads_finite_and_match():
    """Warmup/drain ticks must not route zeros into a stage whose VJP is
    singular at zero: gradients stay finite AND equal the sequential
    stack (real data has no exact zeros, so the golden is well-defined)."""
    W, b = _params(4)
    rng = np.random.RandomState(5)
    M = 5
    x = jnp.asarray(rng.normal(0, 1, (M * 4, 8)).astype(np.float32))
    y = jnp.asarray(rng.normal(0, 1, (M * 4, 8)).astype(np.float32))
    xm = split_microbatches(x, M)
    ym = split_microbatches(y, M)

    def body(Wl, bl, xm, ym):
        loss, (gW, gb) = one_f_one_b(COMM, _singular_stage_fn, _loss_fn,
                                     (Wl[0], bl[0]), xm, ym)
        return loss.reshape(1), gW[None], gb[None]

    loss, gW, gb = jax.jit(jax.shard_map(
        body, mesh=COMM.mesh,
        in_specs=(P("fb"), P("fb"), P(), P()),
        out_specs=(P("fb"), P("fb"), P("fb")),
        check_vma=False))(W, b, xm, ym)

    assert np.isfinite(np.asarray(loss)).all()
    assert np.isfinite(np.asarray(gW)).all()
    assert np.isfinite(np.asarray(gb)).all()

    def ref_loss(params):
        W, b = params
        total = 0.0
        for i in range(M):
            h = xm[i]
            for s in range(COMM.size):
                h = _singular_stage_fn((W[s], b[s]), h)
            total = total + _loss_fn(h, ym[i])
        return total / M

    l_ref, (gW_ref, gb_ref) = jax.value_and_grad(ref_loss)((W, b))
    np.testing.assert_allclose(float(np.asarray(loss)[0]), float(l_ref),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gW), np.asarray(gW_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb_ref),
                               rtol=1e-4, atol=1e-5)


def test_gpipe_zero_singular_stage_grads_finite():
    """Same stress for the GPipe schedule: forward through gpipe_apply
    with a zero-singular stage differentiates to finite gradients equal
    to the sequential stack."""
    from chainermn_tpu.parallel import gpipe_apply
    W, b = _params(6)
    rng = np.random.RandomState(7)
    M = 4
    x = jnp.asarray(rng.normal(0, 1, (M * 4, 8)).astype(np.float32))
    y = jnp.asarray(rng.normal(0, 1, (M * 4, 8)).astype(np.float32))
    xm = split_microbatches(x, M)
    ym = split_microbatches(y, M)

    def body(Wl, bl, xm, ym):
        def loss(params):
            Wl0, bl0 = params
            out = gpipe_apply(COMM, _singular_stage_fn, (Wl0, bl0), xm)
            return jnp.mean((out - ym) ** 2)
        l, (gW, gb) = jax.value_and_grad(loss)((Wl[0], bl[0]))
        return l.reshape(1), gW[None], gb[None]

    loss, gW, gb = jax.jit(jax.shard_map(
        body, mesh=COMM.mesh,
        in_specs=(P("fb"), P("fb"), P(), P()),
        out_specs=(P("fb"), P("fb"), P("fb")),
        check_vma=False))(W, b, xm, ym)

    assert np.isfinite(np.asarray(loss)).all()
    assert np.isfinite(np.asarray(gW)).all()
    assert np.isfinite(np.asarray(gb)).all()

    def ref_loss(params):
        W, b = params
        total = 0.0
        for i in range(M):
            h = xm[i]
            for s in range(COMM.size):
                h = _singular_stage_fn((W[s], b[s]), h)
            total = total + jnp.mean((h - ym[i]) ** 2)
        return total / M

    l_ref, (gW_ref, gb_ref) = jax.value_and_grad(ref_loss)((W, b))
    np.testing.assert_allclose(float(np.asarray(loss)[0]), float(l_ref),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gW), np.asarray(gW_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb_ref),
                               rtol=1e-4, atol=1e-5)
