"""Sequence parallelism: ring attention and Ulysses vs full attention.

Golden rule (SURVEY.md §4): distributed result == single-device result on
the gathered sequence, forward AND backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu.parallel import (ring_self_attention, ulysses_attention)

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici", axis_name="seq")


def _full_reference(q, k, v, causal, scale=None):
    D = q.shape[-1]
    scale = scale or 1.0 / np.sqrt(D)
    s = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = s.shape[-1]
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask[None, None], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


def _data(B=2, H=4, T=None, D=16, seed=0):
    T = T or 8 * COMM.size
    rng = np.random.RandomState(seed)
    mk = lambda: rng.normal(0, 1, (B, H, T, D)).astype(np.float32)
    return mk(), mk(), mk()


def _spec():
    return P(None, None, "seq", None)


def _run(fn, q, k, v):
    spec = _spec()
    return COMM.run_spmd(fn, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         in_specs=(spec, spec, spec), out_specs=spec)


def test_ring_attention_matches_full():
    q, k, v = _data(seed=1)
    out = _run(lambda q, k, v: ring_self_attention(COMM, q, k, v), q, k, v)
    ref = _full_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_causal_matches_full():
    q, k, v = _data(seed=2)
    out = _run(lambda q, k, v: ring_self_attention(COMM, q, k, v,
                                                   causal=True), q, k, v)
    ref = _full_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_zigzag_matches_full():
    """Balanced causal schedule is EXACT: zigzag-shard, ring, unshard ==
    full causal attention on the contiguous sequence."""
    from chainermn_tpu.parallel import zigzag_shard, zigzag_unshard
    q, k, v = _data(seed=7)
    n = COMM.size
    qz, kz, vz = (zigzag_shard(jnp.asarray(a), n) for a in (q, k, v))
    out_z = _run(lambda q, k, v: ring_self_attention(
        COMM, q, k, v, causal=True, schedule="zigzag"), qz, kz, vz)
    out = zigzag_unshard(out_z, n)
    ref = _full_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_zigzag_gradients_match_full():
    from chainermn_tpu.parallel import zigzag_shard, zigzag_unshard
    q, k, v = _data(B=1, H=2, D=8, seed=8)
    n = COMM.size
    qz, kz, vz = (zigzag_shard(jnp.asarray(a), n) for a in (q, k, v))

    def dist_loss(q, k, v):
        out = ring_self_attention(COMM, q, k, v, causal=True,
                                  schedule="zigzag")
        return jnp.sum(out ** 2)

    spec = _spec()
    gq, gk, gv = COMM.run_spmd(
        lambda q, k, v: jax.grad(dist_loss, argnums=(0, 1, 2))(q, k, v),
        qz, kz, vz, in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec))

    def ref_loss(q, k, v):
        D = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
        return jnp.sum(out ** 2)

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(zigzag_unshard(g, n)),
                                   np.asarray(r), rtol=2e-3, atol=2e-4)


def test_zigzag_schedule_is_balanced():
    """Flop-balance assertion (VERDICT r2 Weak #3): enumerate the branch
    every (rank, step) takes via the implementation's own
    ``_causal_branch`` selector and weigh it in dense-half-block units.
    The zigzag schedule is perfectly uniform — every rank does the same
    work at every step — while the naive schedule's per-rank totals span
    a factor of ~n (rank 0: one diagonal; rank n−1: everything)."""
    from chainermn_tpu.parallel.ring_attention import _causal_branch
    n = COMM.size
    weights = {"naive": {0: 4.0, 1: 2.0, 2: 0.0},
               "zigzag": {0: 2.0, 1: 2.0, 2: 2.0}}
    totals = {}
    per_step = {}
    for sched in ("naive", "zigzag"):
        w = weights[sched]
        table = np.zeros((n, n))  # [rank, step] dense-half-block units
        for rank in range(n):
            for step in range(n):
                kv = (rank - step) % n
                table[rank, step] = w[int(_causal_branch(sched, kv, rank))]
        totals[sched] = table.sum(axis=1)
        per_step[sched] = table
    # zigzag: identical work per rank AND per step (no idle ticks)
    assert np.all(per_step["zigzag"] == 2.0)
    assert np.all(totals["zigzag"] == totals["zigzag"][0])
    # same total causal flops overall (both compute the lower triangle)
    np.testing.assert_allclose(totals["zigzag"].sum(),
                               totals["naive"].sum())
    # naive: worst rank does ~n× the best rank's work
    assert totals["naive"].max() / totals["naive"].min() >= n - 1


def test_zigzag_shard_roundtrip():
    from chainermn_tpu.parallel import zigzag_shard, zigzag_unshard
    x = jnp.arange(2 * 3 * (4 * COMM.size) * 5.0).reshape(
        2, 3, 4 * COMM.size, 5)
    y = zigzag_unshard(zigzag_shard(x, COMM.size), COMM.size)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_ring_attention_gradients_match_full():
    q, k, v = _data(B=1, H=2, D=8, seed=3)

    def dist_loss(q, k, v):
        out = ring_self_attention(COMM, q, k, v, causal=True)
        return jnp.sum(out ** 2)

    def body(q, k, v):
        g = jax.grad(dist_loss, argnums=(0, 1, 2))(q, k, v)
        return g

    spec = _spec()
    gq, gk, gv = COMM.run_spmd(body, jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v),
                               in_specs=(spec, spec, spec),
                               out_specs=(spec, spec, spec))

    qj, kj, vj = map(jnp.asarray, (q, k, v))

    def ref_loss(q, k, v):
        D = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        return jnp.sum(out ** 2)

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(qj, kj, vj)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv),
                               rtol=1e-3, atol=1e-4)


def test_ulysses_matches_full():
    q, k, v = _data(H=8, seed=4)  # H divisible by size
    out = _run(lambda q, k, v: ulysses_attention(COMM, q, k, v), q, k, v)
    ref = _full_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_ulysses_causal_matches_full():
    q, k, v = _data(H=8, seed=5)
    out = _run(lambda q, k, v: ulysses_attention(COMM, q, k, v, causal=True),
               q, k, v)
    ref = _full_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_ulysses_head_count_validation():
    import pytest
    q = jnp.zeros((1, 3, 8 * COMM.size, 4))  # 3 heads not divisible by 8

    def body(q):
        from chainermn_tpu.parallel import seq_to_head_shard
        return seq_to_head_shard(COMM, q)

    with pytest.raises(Exception):
        COMM.run_spmd(body, q, in_specs=(_spec(),), out_specs=_spec())


def _max_intermediate_dim_product(fn, *args):
    """Largest (second-to-last × last) dim product over every intermediate
    in the jaxpr — a [T, T] score matrix at large T dominates this."""
    jaxpr = jax.make_jaxpr(fn)(*args)

    def walk(jx):
        worst = 0
        for eqn in jx.eqns:
            for var in eqn.outvars:
                shape = getattr(var.aval, "shape", ())
                if len(shape) >= 2:
                    worst = max(worst, shape[-1] * shape[-2])
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    worst = max(worst, walk(sub.jaxpr))
                if isinstance(sub, (list, tuple)):
                    for s in sub:
                        if hasattr(s, "jaxpr"):
                            worst = max(worst, walk(s.jaxpr))
        return worst

    return walk(jaxpr.jaxpr)


def test_ulysses_never_materializes_TxT():
    """Long-context memory contract (VERDICT r1 missing #6): at T where
    [T, T] would dominate, no intermediate of that size may exist."""
    T = 512 * COMM.size  # global T = 4096
    q = jnp.zeros((1, 8, T, 16), jnp.float32)

    def run(q):
        spec = _spec()
        return COMM.run_spmd(
            lambda q, k, v: ulysses_attention(COMM, q, k, v, causal=True),
            q, q, q, in_specs=(spec, spec, spec), out_specs=spec)

    worst = _max_intermediate_dim_product(run, q)
    Tg = T  # full sequence length after head exchange
    assert worst < Tg * Tg, \
        f"found [~T,T]-sized intermediate: {worst} >= {Tg * Tg}"


def test_ring_never_materializes_TlxTl_blocks_beyond_block():
    """Ring path: intermediates stay O(T_local x block), not
    O(T_local x T_local) at large local length."""
    Tl = 2048  # per-rank; naive per-block einsum would be [2048, 2048]
    q = jnp.zeros((1, 2, Tl * COMM.size, 16), jnp.float32)

    def run(q):
        spec = _spec()
        return COMM.run_spmd(
            lambda q, k, v: ring_self_attention(COMM, q, k, v, causal=True),
            q, q, q, in_specs=(spec, spec, spec), out_specs=spec)

    worst = _max_intermediate_dim_product(run, q)
    assert worst < Tl * Tl, \
        f"found [T_local, T_local] intermediate: {worst} >= {Tl * Tl}"


def test_ring_cross_attention_unequal_lengths():
    """Cross-attention with Tq != Tkv per rank (VERDICT r1 Weak #5: the
    docstring promised it; now tested)."""
    B, H, D = 1, 2, 16
    Tq, Tk = 4 * COMM.size, 12 * COMM.size
    rng = np.random.RandomState(9)
    q = rng.normal(0, 1, (B, H, Tq, D)).astype(np.float32)
    k = rng.normal(0, 1, (B, H, Tk, D)).astype(np.float32)
    v = rng.normal(0, 1, (B, H, Tk, D)).astype(np.float32)
    from chainermn_tpu.parallel import ring_attention
    spec = _spec()
    out = COMM.run_spmd(
        lambda q, k, v: ring_attention(COMM, q, k, v), jnp.asarray(q),
        jnp.asarray(k), jnp.asarray(v),
        in_specs=(spec, spec, spec), out_specs=spec)
    ref = _full_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_ring_causal_unequal_lengths_rejected():
    import pytest
    q = jnp.zeros((1, 2, 4 * COMM.size, 16))
    k = jnp.zeros((1, 2, 8 * COMM.size, 16))
    spec = _spec()
    with pytest.raises(Exception, match="equal local q/KV"):
        COMM.run_spmd(
            lambda q, k, v: ring_self_attention(COMM, q, k, v, causal=True),
            q, k, k, in_specs=(spec, spec, spec), out_specs=spec)


def test_ring_attention_randomized_geometry_sweep():
    """Property sweep: random (B, H, T, D) × causal × schedule, distributed
    output == dense reference on the gathered sequence.  Catches
    geometry-dependent masking/merge bugs the fixed-shape tests miss."""
    from chainermn_tpu.parallel import zigzag_shard, zigzag_unshard
    rng = np.random.RandomState(7)
    n = COMM.size
    for case in range(6):
        B = int(rng.randint(1, 3))
        H = int(rng.randint(1, 4))
        D = int(2 ** rng.randint(2, 5))
        t_mult = int(rng.randint(1, 4))
        causal = bool(case % 2)
        T = 2 * n * t_mult  # divisible for both layouts
        q, k, v = (rng.normal(0, 1, (B, H, T, D)).astype(np.float32)
                   for _ in range(3))
        ref = _full_reference(q, k, v, causal)
        # zigzag applies to every causal case: 3 distinct zigzag
        # geometries per sweep, alongside naive for both causal modes
        schedules = ("naive", "zigzag") if causal else ("naive",)
        for schedule in schedules:
            if schedule == "zigzag":
                qs, ks, vs = (zigzag_shard(jnp.asarray(a), n)
                              for a in (q, k, v))
            else:
                qs, ks, vs = (jnp.asarray(a) for a in (q, k, v))
            out = _run(lambda a, b, c: ring_self_attention(
                COMM, a, b, c, causal=causal, schedule=schedule),
                qs, ks, vs)
            if schedule == "zigzag":
                out = zigzag_unshard(out, n)
            np.testing.assert_allclose(
                np.asarray(out), ref, rtol=2e-4, atol=2e-5,
                err_msg=f"case={case} B={B} H={H} T={T} D={D} "
                        f"causal={causal} schedule={schedule}")


# -- consumers differentiated through the Pallas FUSED backward --------------
#
# ISSUE 4: ring attention and Ulysses must keep their golden-rule
# exactness when the gradient flows through the real fused flash
# backward kernel instead of the blockwise-jnp fallback the CPU
# dispatch normally takes.  CHAINERMN_TPU_FLASH_INTERPRET=1 routes the
# attention_with_lse/attention dispatchers through the Pallas kernels
# in interpreter mode on any backend.

def test_ring_zigzag_grads_through_pallas_fused_bwd(monkeypatch):
    """Zigzag causal schedule through the fused backward: the LSE-merge
    (whose weights differentiate via the g_lse → delta folding) must
    stay exact through the new kernel."""
    from chainermn_tpu.parallel import zigzag_shard, zigzag_unshard
    import importlib
    fa = importlib.import_module("chainermn_tpu.ops.flash_attention")
    monkeypatch.setenv("CHAINERMN_TPU_FLASH_INTERPRET", "1")
    assert fa._flash_bwd_mode() == "fused"
    q, k, v = _data(B=1, H=2, D=8, seed=21)
    n = COMM.size
    qz, kz, vz = (zigzag_shard(jnp.asarray(a), n) for a in (q, k, v))

    def dist_loss(q, k, v):
        out = ring_self_attention(COMM, q, k, v, causal=True,
                                  schedule="zigzag")
        return jnp.sum(out ** 2)

    spec = _spec()
    gq, gk, gv = COMM.run_spmd(
        lambda q, k, v: jax.grad(dist_loss, argnums=(0, 1, 2))(q, k, v),
        qz, kz, vz, in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec))

    def ref_loss(q, k, v):
        D = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
        return jnp.sum(out ** 2)

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(zigzag_unshard(g, n)),
                                   np.asarray(r), rtol=2e-3, atol=2e-4)


def test_ring_naive_grads_through_pallas_fused_bwd(monkeypatch):
    import importlib
    fa = importlib.import_module("chainermn_tpu.ops.flash_attention")
    monkeypatch.setenv("CHAINERMN_TPU_FLASH_INTERPRET", "1")
    assert fa._flash_bwd_mode() == "fused"
    q, k, v = _data(B=1, H=2, D=8, seed=22)

    def dist_loss(q, k, v):
        out = ring_self_attention(COMM, q, k, v, causal=True)
        return jnp.sum(out ** 2)

    spec = _spec()
    gq, gk, gv = COMM.run_spmd(
        lambda q, k, v: jax.grad(dist_loss, argnums=(0, 1, 2))(q, k, v),
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        in_specs=(spec, spec, spec), out_specs=(spec, spec, spec))

    def ref_loss(q, k, v):
        D = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
        return jnp.sum(out ** 2)

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-3, atol=2e-4)


def test_ulysses_grads_through_pallas_fused_bwd(monkeypatch):
    import importlib
    fa = importlib.import_module("chainermn_tpu.ops.flash_attention")
    monkeypatch.setenv("CHAINERMN_TPU_FLASH_INTERPRET", "1")
    assert fa._flash_bwd_mode() == "fused"
    q, k, v = _data(B=1, H=8, D=8, seed=23)  # H divisible by size

    def dist_loss(q, k, v):
        out = ulysses_attention(COMM, q, k, v, causal=True)
        return jnp.sum(out ** 2)

    spec = _spec()
    gq, gk, gv = COMM.run_spmd(
        lambda q, k, v: jax.grad(dist_loss, argnums=(0, 1, 2))(q, k, v),
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        in_specs=(spec, spec, spec), out_specs=(spec, spec, spec))

    def ref_loss(q, k, v):
        D = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
        return jnp.sum(out ** 2)

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-3, atol=2e-4)


def test_interpret_force_actually_routes_through_pallas(monkeypatch):
    """The consumer tests above are only meaningful if the interpret
    hook really selects the Pallas custom-VJP path on CPU: pin it
    structurally (pallas_call present in the traced program; absent
    without the hook)."""
    from chainermn_tpu.ops.flash_attention import attention_with_lse
    q, k, v = (jnp.ones((1, 2, 16, 8), jnp.float32),) * 3
    monkeypatch.setenv("CHAINERMN_TPU_FLASH_INTERPRET", "1")
    text = str(jax.make_jaxpr(
        lambda q, k, v: attention_with_lse(q, k, v, causal=True))(q, k, v))
    assert "pallas_call" in text
    monkeypatch.delenv("CHAINERMN_TPU_FLASH_INTERPRET")
    text = str(jax.make_jaxpr(
        lambda q, k, v: attention_with_lse(q, k, v, causal=True))(q, k, v))
    assert "pallas_call" not in text
