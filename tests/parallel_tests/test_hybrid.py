"""Hybrid parallelism on one N-D mesh: DP×SP transformer training step.

The reference's hybrid story is split() + two communicators (SURVEY §2.6);
the mesh-native form is axes of one mesh. This test runs a full train
step with batch sharded over 'data' and sequence over 'seq'
simultaneously, asserting gradients match single-device execution.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu.core.link import bind_state, extract_state
from chainermn_tpu.models.transformer import TransformerLM
from chainermn_tpu.parallel import make_mesh, axis_communicators


def test_dp_sp_hybrid_transformer_step():
    mesh = make_mesh({"data": 2, "seq": 4})
    comms = axis_communicators(mesh)
    sp_comm = comms["seq"]

    B, T, V = 4, 16, 50  # B sharded over data(2), T over seq(4)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, V, (B, T)).astype(np.int32))
    t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))

    sp = TransformerLM(V, d_model=32, n_heads=2, n_layers=1, seed=21,
                      sp_comm=sp_comm, sp_mode="ring")
    single = TransformerLM(V, d_model=32, n_heads=2, n_layers=1, seed=21)
    state = extract_state(sp)

    def body(params, pstate, x, t):
        def loss(p):
            with bind_state(sp, {"params": p, "state": pstate}):
                return sp(x, t)
        l, g = jax.value_and_grad(loss)(params)
        # mean over both batch shards and sequence shards
        g = jax.tree.map(
            lambda a: jax.lax.pmean(jax.lax.pmean(a, "seq"), "data"), g)
        return jax.lax.pmean(jax.lax.pmean(l, "seq"), "data"), g

    loss_h, g_h = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P("data", "seq"), P("data", "seq")),
        out_specs=(P(), P()), check_vma=False))(
            state["params"], state["state"], x, t)

    s2 = extract_state(single)

    def ref_loss(p):
        with bind_state(single, {"params": p, "state": s2["state"]}):
            return single(x, t)

    l_ref, g_ref = jax.value_and_grad(ref_loss)(s2["params"])
    np.testing.assert_allclose(float(loss_h), float(l_ref), rtol=1e-4)
    for k in g_ref:
        np.testing.assert_allclose(np.asarray(g_h[k]), np.asarray(g_ref[k]),
                                   rtol=5e-3, atol=5e-4, err_msg=k)
