"""Hybrid parallelism on one N-D mesh: DP×SP transformer training step.

The reference's hybrid story is split() + two communicators (SURVEY §2.6);
the mesh-native form is axes of one mesh. This test runs a full train
step with batch sharded over 'data' and sequence over 'seq'
simultaneously, asserting gradients match single-device execution.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu.core.link import bind_state, extract_state
from chainermn_tpu.models.transformer import TransformerLM
from chainermn_tpu.parallel import make_mesh, axis_communicators


def test_dp_sp_hybrid_transformer_step():
    mesh = make_mesh({"data": 2, "seq": 4})
    comms = axis_communicators(mesh)
    sp_comm = comms["seq"]

    B, T, V = 4, 16, 50  # B sharded over data(2), T over seq(4)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, V, (B, T)).astype(np.int32))
    t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))

    sp = TransformerLM(V, d_model=32, n_heads=2, n_layers=1, seed=21,
                      sp_comm=sp_comm, sp_mode="ring")
    single = TransformerLM(V, d_model=32, n_heads=2, n_layers=1, seed=21)
    state = extract_state(sp)

    def body(params, pstate, x, t):
        def loss(p):
            with bind_state(sp, {"params": p, "state": pstate}):
                return sp(x, t)
        l, g = jax.value_and_grad(loss)(params)
        # mean over both batch shards and sequence shards
        g = jax.tree.map(
            lambda a: jax.lax.pmean(jax.lax.pmean(a, "seq"), "data"), g)
        return jax.lax.pmean(jax.lax.pmean(l, "seq"), "data"), g

    loss_h, g_h = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P("data", "seq"), P("data", "seq")),
        out_specs=(P(), P()), check_vma=False))(
            state["params"], state["state"], x, t)

    s2 = extract_state(single)

    def ref_loss(p):
        with bind_state(single, {"params": p, "state": s2["state"]}):
            return single(x, t)

    l_ref, g_ref = jax.value_and_grad(ref_loss)(s2["params"])
    np.testing.assert_allclose(float(loss_h), float(l_ref), rtol=1e-4)
    for k in g_ref:
        np.testing.assert_allclose(np.asarray(g_h[k]), np.asarray(g_ref[k]),
                                   rtol=5e-3, atol=5e-4, err_msg=k)


def test_dp_ep_hybrid_moe_step():
    """2-D data × expert mesh: batch sharded over 'data', experts over
    'ep'; gradients match dense single-device routing."""
    from chainermn_tpu.parallel import make_mesh, axis_communicators
    from chainermn_tpu.parallel.moe import moe_dispatch_combine

    mesh = make_mesh({"data": 2, "ep": 4})
    comms = axis_communicators(mesh)
    ep = comms["ep"]
    E = 4
    D, H = 8, 16
    rng = np.random.RandomState(0)
    router = jnp.asarray(rng.normal(0, 0.5, (D, E)).astype(np.float32))
    w_in = jnp.asarray(rng.normal(0, 0.3, (E, D, H)).astype(np.float32))
    w_out = jnp.asarray(rng.normal(0, 0.3, (E, H, D)).astype(np.float32))
    T = 16  # global tokens; split over data(2)
    x = jnp.asarray(rng.normal(0, 1, (T, D)).astype(np.float32))
    tgt = jnp.asarray(rng.normal(0, 1, (T, D)).astype(np.float32))

    def body(router, w_in, w_out, x, tgt):
        def loss(params):
            router, w_in, w_out = params
            import chainermn_tpu.functions as mnfn
            w_in_full = mnfn.psum_gradient(ep, w_in)
            w_out_full = mnfn.psum_gradient(ep, w_out)
            idx = jax.lax.axis_index("ep")
            wi = jax.lax.dynamic_index_in_dim(w_in_full, idx, 0, False)
            wo = jax.lax.dynamic_index_in_dim(w_out_full, idx, 0, False)
            gate_logits = x @ router
            out, aux = moe_dispatch_combine(
                ep, x, gate_logits,
                lambda h: jax.nn.gelu(h @ wi) @ wo,
                capacity_factor=float(E))
            return jnp.mean((out - tgt) ** 2)

        l, g = jax.value_and_grad(loss)((router, w_in, w_out))
        g = jax.tree.map(lambda a: jax.lax.pmean(a, "data"), g)
        return jax.lax.pmean(l, "data"), g

    loss_h, g_h = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))(router, w_in, w_out, x, tgt)

    # dense single-device reference
    def ref_loss(params):
        router, w_in, w_out = params
        probs = jax.nn.softmax(x @ router, axis=-1)
        eidx = jnp.argmax(probs, axis=-1)
        gate = jnp.take_along_axis(probs, eidx[:, None], 1)[:, 0]
        h = jnp.einsum("td,edh->teh", x, w_in)
        y = jnp.einsum("teh,ehd->ted", jax.nn.gelu(h), w_out)
        out = jnp.take_along_axis(
            y, eidx[:, None, None].repeat(D, axis=2), 1)[:, 0]
        out = out * gate[:, None]
        return jnp.mean((out - tgt) ** 2)

    l_ref, g_ref = jax.value_and_grad(ref_loss)((router, w_in, w_out))
    np.testing.assert_allclose(float(loss_h), float(l_ref), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(g_h), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=1e-5)
