"""GPipe microbatched pipeline: equivalence with sequential stage stack."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu.parallel import (gpipe_apply, merge_microbatches,
                                    split_microbatches, make_mesh,
                                    axis_communicators)

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici", axis_name="pipe")


def _stage_fn(params, h):
    W, b = params
    return jnp.tanh(h @ W + b)


def _params(seed):
    rng = np.random.RandomState(seed)
    S = COMM.size
    W = rng.normal(0, 0.5, (S, 8, 8)).astype(np.float32)
    b = rng.normal(0, 0.1, (S, 8)).astype(np.float32)
    return W, b


def test_gpipe_matches_sequential_stack():
    W, b = _params(0)
    x = np.random.RandomState(1).normal(0, 1, (16, 8)).astype(np.float32)
    M = 4
    xm = split_microbatches(jnp.asarray(x), M)

    def body(Wl, bl, xm):
        # shard_map gives [1, 8, 8] per rank — drop the stacked axis
        return gpipe_apply(COMM, _stage_fn, (Wl[0], bl[0]), xm)

    out = COMM.run_spmd(body, jnp.asarray(W), jnp.asarray(b), xm,
                        in_specs=(P("pipe"), P("pipe"), P()),
                        out_specs=P())
    got = merge_microbatches(out)

    h = jnp.asarray(x)
    for s in range(COMM.size):
        h = _stage_fn((jnp.asarray(W[s]), jnp.asarray(b[s])), h)
    np.testing.assert_allclose(np.asarray(got), np.asarray(h),
                               rtol=1e-5, atol=1e-6)


def test_gpipe_differentiable():
    W, b = _params(2)
    x = np.random.RandomState(3).normal(0, 1, (8, 8)).astype(np.float32)
    xm = split_microbatches(jnp.asarray(x), 2)

    def body(Wl, bl, xm):
        def loss(args):
            Wl, bl = args
            out = gpipe_apply(COMM, _stage_fn, (Wl[0], bl[0]), xm)
            return jnp.sum(out ** 2)
        gW, gb = jax.grad(loss)((Wl, bl))
        return gW, gb

    gW, gb = COMM.run_spmd(body, jnp.asarray(W), jnp.asarray(b), xm,
                           in_specs=(P("pipe"), P("pipe"), P()),
                           out_specs=(P("pipe"), P("pipe")))

    def ref_loss(args):
        W, b = args
        h = jnp.asarray(x)
        for s in range(COMM.size):
            h = _stage_fn((W[s], b[s]), h)
        return jnp.sum(h ** 2)

    rW, rb = jax.grad(ref_loss)((jnp.asarray(W), jnp.asarray(b)))
    np.testing.assert_allclose(np.asarray(gW), np.asarray(rW),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb),
                               rtol=1e-4, atol=1e-5)


def test_make_mesh_and_axis_communicators():
    mesh = make_mesh({"data": 4, "model": -1})
    assert mesh.devices.shape == (4, 2)
    comms = axis_communicators(mesh)
    assert comms["data"].size == 4
    assert comms["model"].size == 2


def test_split_merge_roundtrip():
    x = jnp.arange(24.0).reshape(12, 2)
    m = split_microbatches(x, 3)
    assert m.shape == (3, 4, 2)
    np.testing.assert_array_equal(np.asarray(merge_microbatches(m)),
                                  np.asarray(x))


def test_gpipe_remat_matches_no_remat():
    W, b = _params(5)
    x = np.random.RandomState(6).normal(0, 1, (8, 8)).astype(np.float32)
    xm = split_microbatches(jnp.asarray(x), 2)

    grads = {}
    for remat in (False, True):
        def body(Wl, bl, xm):
            def loss(args):
                Wl, bl = args
                out = gpipe_apply(COMM, _stage_fn, (Wl[0], bl[0]), xm,
                                  remat=remat)
                return jnp.sum(out ** 2)
            return jax.grad(loss)((Wl, bl))

        grads[remat] = COMM.run_spmd(
            body, jnp.asarray(W), jnp.asarray(b), xm,
            in_specs=(P("pipe"), P("pipe"), P()),
            out_specs=(P("pipe"), P("pipe")))
    for a, b2 in zip(jax.tree.leaves(grads[False]),
                     jax.tree.leaves(grads[True])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=1e-5, atol=1e-6)
