"""Single-pass BN statistics: output equivalence against the two-pass
mean/var formulation they replace, plus the bf16 traffic discipline of
the pooling ops (ISSUE 3 tentpole: one read for stats, one read + one
write for normalize, f32 confined to the per-channel vectors)."""

import jax
import jax.numpy as jnp
import numpy as np

import chainermn_tpu as ct
from chainermn_tpu import L
from chainermn_tpu.nn import functions as F


def _two_pass_reference(x, gamma, beta, eps, axis):
    x32 = np.asarray(x, np.float32)
    mean = x32.mean(axis=axis)
    var = x32.var(axis=axis)
    return np.asarray(
        F._apply_bn(jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta),
                    jnp.asarray(mean), jnp.asarray(var), eps, axis))


def test_batch_moments_single_pass_matches_two_pass():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(2, 3, (16, 8, 5, 5)).astype(np.float32))
    mean, var = F.batch_moments(x, (0, 2, 3))
    np.testing.assert_allclose(np.asarray(mean),
                               np.asarray(x).mean((0, 2, 3)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(var),
                               np.asarray(x).var((0, 2, 3)),
                               rtol=1e-4, atol=1e-5)
    assert mean.dtype == jnp.float32 and var.dtype == jnp.float32


def test_batch_moments_variance_never_negative():
    # fp32 cancellation territory: large mean, tiny variance
    x = jnp.full((64, 4), 1e4, jnp.float32)
    _, var = F.batch_moments(x, (0,))
    assert np.all(np.asarray(var) >= 0.0)


def test_batch_normalization_matches_two_pass_reference():
    rng = np.random.RandomState(1)
    for shape, axis in [((32, 6), (0,)), ((8, 6, 7, 7), (0, 2, 3))]:
        x = jnp.asarray(rng.normal(1, 2, shape).astype(np.float32))
        gamma = jnp.asarray(rng.uniform(0.5, 2, shape[1]).astype(np.float32))
        beta = jnp.asarray(rng.normal(0, 1, shape[1]).astype(np.float32))
        y = F.batch_normalization(x, gamma, beta, axis=axis)
        ref = _two_pass_reference(x, gamma, beta, 2e-5, axis)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-5)


def test_bn_link_forward_and_ema_match_two_pass():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.normal(0, 2, (16, 3, 4, 4)).astype(np.float32))
    bn = L.BatchNormalization(3, decay=0.8)
    y = bn(x)
    ref = _two_pass_reference(x, np.ones(3, np.float32),
                              np.zeros(3, np.float32), 2e-5, (0, 2, 3))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-5)
    m = 16 * 4 * 4
    expected_var = 0.8 * 1.0 + 0.2 * np.asarray(x).var((0, 2, 3)) * m / (m - 1)
    np.testing.assert_allclose(np.asarray(bn.avg_var), expected_var,
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(bn.avg_mean),
                               0.2 * np.asarray(x).mean((0, 2, 3)),
                               rtol=1e-4, atol=1e-6)


def test_bn_bf16_keeps_activation_dtype_and_f32_stats():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.normal(0, 1, (8, 4, 6, 6)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    bn = L.BatchNormalization(4)
    y = bn(x)
    assert y.dtype == jnp.bfloat16
    assert bn.avg_mean.dtype == jnp.float32
    assert bn.avg_var.dtype == jnp.float32
    ref = _two_pass_reference(np.asarray(x, np.float32),
                              np.ones(4, np.float32),
                              np.zeros(4, np.float32), 2e-5, (0, 2, 3))
    np.testing.assert_allclose(np.asarray(y, np.float32), ref,
                               rtol=2e-2, atol=2e-2)  # bf16 output rounding


def test_bn_gradients_match_two_pass_formulation():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.normal(1, 2, (12, 5)).astype(np.float32))
    gamma = jnp.asarray(rng.uniform(0.5, 2, 5).astype(np.float32))
    beta = jnp.zeros(5, jnp.float32)

    def loss_single(a):
        return jnp.sum(F.batch_normalization(a, gamma, beta, axis=(0,)) ** 3)

    def loss_two_pass(a):
        a32 = a.astype(jnp.float32)
        mean = a32.mean(axis=0)
        var = a32.var(axis=0)
        return jnp.sum(F._apply_bn(a, gamma, beta, mean, var, 2e-5,
                                   (0,)) ** 3)

    g1 = jax.grad(loss_single)(x)
    g2 = jax.grad(loss_two_pass)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-3, atol=1e-4)


def test_pooling_bf16_stays_bf16():
    x = jnp.ones((2, 3, 8, 8), jnp.bfloat16)
    assert F.average_pooling_2d(x, 2).dtype == jnp.bfloat16
    assert F.global_average_pooling_2d(x).dtype == jnp.bfloat16
    xh = jnp.ones((2, 8, 8, 3), jnp.bfloat16)
    assert F.global_average_pooling_2d(xh, layout="NHWC").dtype \
        == jnp.bfloat16
