"""Forward + backward checks of differentiable send/recv.

Mirrors reference ``functions_tests/test_point_to_point_communication.py``
(SURVEY.md §4): values cross the edge forward; gradients cross it
backward (here via ppermute's automatic transpose).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu import functions as mnfn

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici")


def _per_rank(shape=(3,)):
    size = COMM.size
    return jnp.asarray(
        np.arange(size * int(np.prod(shape)), dtype=np.float32)
        .reshape((size,) + shape))


def test_point_to_point_forward():
    x = _per_rank((2,))

    def body(x):
        return mnfn.point_to_point(x, COMM, src=2, dst=5)

    out = COMM.run_spmd(body, x, out_specs=P(COMM.axis_name))
    out = np.asarray(out).reshape(COMM.size, 2)
    np.testing.assert_allclose(out[5], np.asarray(x[2]))
    np.testing.assert_allclose(out[0], 0.0)


def test_point_to_point_gradient_reverses_edge():
    x = _per_rank((2,))

    def loss(x):
        y = mnfn.point_to_point(x, COMM, src=1, dst=4)
        # only rank 4's received value contributes (others got zeros)
        return jnp.sum(y * 3.0)

    grad = COMM.run_spmd(lambda x: jax.grad(loss)(x), x,
                         out_specs=P(COMM.axis_name))
    g = np.asarray(grad).reshape(COMM.size, 2)
    # rank 4's cotangent (3) flows back along the reversed edge 4 → 1
    np.testing.assert_allclose(g[1], 3.0)
    np.testing.assert_allclose(g[0], 0.0)


def test_send_recv_pair_and_delegate():
    x = _per_rank((2,))

    def loss(x):
        h = x * 2.0                         # stage-0 compute (owner: rank 0)
        delegate = mnfn.send(h, COMM, 3, self_rank=0)
        y = mnfn.recv(COMM, 0, delegate_variable=delegate, self_rank=3)
        return jnp.sum(y * y)               # stage-1 loss on rank 3

    val, grad = COMM.run_spmd(
        lambda x: (loss(x).reshape(1), jax.grad(loss)(x)), x,
        out_specs=(P(COMM.axis_name), P(COMM.axis_name)))
    vals = np.asarray(val).reshape(COMM.size)
    # rank 3 received 2*x_0; every rank's loss term: only rank 3's nonzero
    expect = float(((2 * np.asarray(x[0])) ** 2).sum())
    np.testing.assert_allclose(vals[3], expect, rtol=1e-6)
    g = np.asarray(grad).reshape(COMM.size, 2)
    # rank 3's cotangent 2y = 4 x_0 crosses back 3 → 0, then ×2 for h = 2x
    np.testing.assert_allclose(g[0], 8.0 * np.asarray(x[0]), rtol=1e-6)


def test_recv_without_send_raises():
    x = _per_rank((1,))

    def body(x):
        return mnfn.recv(COMM, 0, self_rank=1)

    import pytest
    with pytest.raises(Exception, match="no matching send"):
        COMM.run_spmd(body, x, out_specs=P(COMM.axis_name))


def test_pseudo_connect_keeps_edge_alive():
    x = _per_rank((2,))

    def loss(x):
        delegate = mnfn.send(x, COMM, 2, self_rank=1)
        y = mnfn.recv(COMM, 1, self_rank=2)
        local = jnp.sum(x)                  # some local head
        fused = mnfn.pseudo_connect(delegate, local)
        return fused + jnp.sum(y) * 0.0     # y unused: edge kept by delegate

    grad = COMM.run_spmd(lambda x: jax.grad(loss)(x), x,
                         out_specs=P(COMM.axis_name))
    g = np.asarray(grad).reshape(COMM.size, 2)
    np.testing.assert_allclose(g, 1.0)  # local head grad everywhere
