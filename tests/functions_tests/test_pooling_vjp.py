"""Traffic-lean max-pooling VJP: output equivalence against the XLA
``reduce_window``/``select-and-scatter`` lowering it replaces.

The argmax path stores each window's argmax in the forward (one uint8
plane) and scatters the cotangent through it in one fused pad-and-sum
pass; the XLA backward re-compares the whole input against the output
(``select-and-scatter`` — the 0.75 ms/step HBM-bound row in the r5
ResNet trace).  These tests pin the two lowerings equal — values AND
gradients — across layouts, geometries, cover_all, and dtypes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import chainermn_tpu.nn.functions as F

GEOMETRIES = [
    # (h, w, ksize, stride, pad, cover_all)
    (7, 7, 3, 2, 1, False),     # the ResNet stem shape family
    (8, 10, 3, 2, 1, True),     # cover_all extra padding, non-square
    (6, 6, 2, 2, 0, True),
    (9, 9, 3, 3, 1, True),
    (5, 5, 3, 1, 1, False),     # stride 1 (fully overlapping windows)
    (14, 14, 2, 2, 0, False),
    (6, 6, (3, 2), (2, 1), (1, 0), True),  # asymmetric window/stride/pad
]


def _xla_reference(x, k, s, p, ca, layout, monkeypatch):
    monkeypatch.setattr(F, "_MAXPOOL_VJP", "xla")
    try:
        y = F.max_pooling_2d(x, k, s, p, ca, layout)
        g = jax.grad(lambda a: jnp.sum(
            F.max_pooling_2d(a, k, s, p, ca, layout) ** 2))(x)
    finally:
        monkeypatch.setattr(F, "_MAXPOOL_VJP", "argmax")
    return y, g


@pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
@pytest.mark.parametrize("geom", GEOMETRIES)
def test_argmax_vjp_matches_xla_lowering(layout, geom, monkeypatch):
    h, w, k, s, p, ca = geom
    rng = np.random.RandomState(hash((layout, str(geom))) % (2 ** 31))
    shape = (2, 3, h, w) if layout == "NCHW" else (2, h, w, 3)
    x = jnp.asarray(rng.normal(0, 1, shape).astype(np.float32))
    y_ref, g_ref = _xla_reference(x, k, s, p, ca, layout, monkeypatch)
    assert F._MAXPOOL_VJP == "argmax"
    y = F.max_pooling_2d(x, k, s, p, ca, layout)
    g = jax.grad(lambda a: jnp.sum(
        F.max_pooling_2d(a, k, s, p, ca, layout) ** 2))(x)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))
    np.testing.assert_array_equal(np.asarray(g), np.asarray(g_ref))


def test_bf16_values_and_grads_match(monkeypatch):
    # TIE-FREE bf16 data: 512 distinct bf16 values (bf16's 8-bit
    # mantissa makes random draws collide within windows, and on exact
    # ties the two lowerings intentionally diverge — argmax routes to
    # the first max, XLA's packed select-and-gather picks by tangent
    # bit pattern; see _max_pool_argmax)
    rng = np.random.RandomState(3)
    vals = np.concatenate([np.linspace(lo, 2 * lo, 128, endpoint=False)
                           for lo in (1.0, 2.0, 4.0, 8.0)])
    rng.shuffle(vals)
    x = jnp.asarray(vals.astype(np.float32).reshape(2, 8, 8, 4)
                    ).astype(jnp.bfloat16)
    assert len(set(np.asarray(x, np.float32).ravel())) == 512

    def loss(a):
        return jnp.sum(F.max_pooling_2d(
            a, 3, 2, 1, False, "NHWC").astype(jnp.float32))

    monkeypatch.setattr(F, "_MAXPOOL_VJP", "xla")
    y_ref = F.max_pooling_2d(x, 3, 2, 1, False, "NHWC")
    g_ref = jax.grad(loss)(x)
    monkeypatch.setattr(F, "_MAXPOOL_VJP", "argmax")
    y = F.max_pooling_2d(x, 3, 2, 1, False, "NHWC")
    g = jax.grad(loss)(x)
    assert y.dtype == jnp.bfloat16 and g.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(y, np.float32),
                                  np.asarray(y_ref, np.float32))
    np.testing.assert_array_equal(np.asarray(g, np.float32),
                                  np.asarray(g_ref, np.float32))


def test_tie_routes_gradient_to_first_max_like_argmax():
    # constant window: both lowerings send the whole cotangent to the
    # FIRST element in window order
    x = jnp.ones((1, 1, 4, 4), jnp.float32)
    g = jax.grad(lambda a: jnp.sum(F.max_pooling_2d(a, 2, 2, 0)))(x)
    expected = np.zeros((1, 1, 4, 4), np.float32)
    expected[0, 0, ::2, ::2] = 1.0
    np.testing.assert_array_equal(np.asarray(g), expected)


def test_integer_inputs_keep_reduce_window_path():
    xi = jnp.arange(36, dtype=jnp.int32).reshape(1, 1, 6, 6)
    y = F.max_pooling_2d(xi, 2, 2, 0)
    assert y.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(y)[0, 0, 0],
                                  np.asarray([7, 9, 11]))


def test_no_select_and_scatter_in_argmax_backward():
    x = jnp.ones((2, 8, 8, 4), jnp.bfloat16)
    grad_fn = jax.grad(lambda a: jnp.sum(F.max_pooling_2d(
        a, 3, 2, 1, False, "NHWC").astype(jnp.float32)))
    text = jax.jit(grad_fn).lower(x).as_text()
    assert "select_and_scatter" not in text
    # and the stored residual is the uint8 argmax plane
    assert "ui8" in text


def test_jit_and_second_application_consistent():
    # under jit, and reused at a second shape (fresh trace) — the
    # custom_vjp's static-argument plumbing must not leak shapes
    f = jax.jit(lambda a: F.max_pooling_2d(a, 3, 2, 1, False, "NHWC"))
    a = jnp.asarray(np.random.RandomState(0).normal(
        0, 1, (1, 12, 12, 2)).astype(np.float32))
    b = jnp.asarray(np.random.RandomState(1).normal(
        0, 1, (2, 20, 20, 3)).astype(np.float32))
    ya, yb = f(a), f(b)
    assert ya.shape == (1, 6, 6, 2) and yb.shape == (2, 10, 10, 3)
