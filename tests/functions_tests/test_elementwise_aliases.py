"""The F.* elementwise/manipulation alias tail (reference parity
surface, ``nn/functions.py``): table-driven equivalence against the
numpy/jax counterparts on random inputs, plus differentiability spot
checks — turns the pass-through tail into verified surface.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chainermn_tpu import F


RNG = np.random.RandomState(0)
X = RNG.normal(0, 1, (3, 4)).astype(np.float32)
POS = np.abs(X) + 0.1        # strictly positive (log/rsqrt domains)
UNIT = np.tanh(X) * 0.99     # inside (-1, 1) for arcsin/arccos


UNARY_CASES = [
    ("sin", X, np.sin), ("cos", X, np.cos), ("tan", X, np.tan),
    ("arcsin", UNIT, np.arcsin), ("arccos", UNIT, np.arccos),
    ("arctan", X, np.arctan), ("sinh", X, np.sinh), ("cosh", X, np.cosh),
    ("floor", X, np.floor), ("ceil", X, np.ceil), ("sign", X, np.sign),
    ("square", X, np.square), ("log2", POS, np.log2),
    ("log10", POS, np.log10), ("log1p", POS, np.log1p),
    ("expm1", X, np.expm1), ("fix", X, np.fix),
    ("rsqrt", POS, lambda a: 1.0 / np.sqrt(a)),
    ("fliplr", X, np.fliplr), ("flipud", X, np.flipud),
]


@pytest.mark.parametrize("name,arg,ref", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary_alias_matches_numpy(name, arg, ref):
    out = getattr(F, name)(jnp.asarray(arg))
    np.testing.assert_allclose(np.asarray(out), ref(arg),
                               rtol=1e-5, atol=1e-6)


def test_special_and_binary_aliases():
    from scipy import special as sp  # available via jax's scipy mirror
    np.testing.assert_allclose(np.asarray(F.erf(jnp.asarray(X))),
                               sp.erf(X), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(F.erfc(jnp.asarray(X))),
                               sp.erfc(X), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(F.arctan2(jnp.asarray(X), jnp.asarray(POS))),
        np.arctan2(X, POS), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(F.fmod(jnp.asarray(X), 0.7)), np.fmod(X, 0.7),
        rtol=1e-4, atol=1e-5)


def test_reduction_and_scan_aliases():
    np.testing.assert_allclose(np.asarray(F.cumsum(jnp.asarray(X), 1)),
                               np.cumsum(X, 1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(F.cumprod(jnp.asarray(X), 1)),
                               np.cumprod(X, 1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(F.prod(jnp.asarray(POS), 1)),
                               np.prod(POS, 1), rtol=1e-5)
    from scipy.special import logsumexp
    np.testing.assert_allclose(np.asarray(F.logsumexp(jnp.asarray(X), 1)),
                               logsumexp(X, 1), rtol=1e-5)


def test_activation_aliases():
    x = jnp.asarray(X * 10)
    np.testing.assert_allclose(np.asarray(F.relu6(x)),
                               np.clip(X * 10, 0, 6), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(F.hard_sigmoid(x)),
                               np.clip(X * 10 * 0.2 + 0.5, 0, 1),
                               rtol=1e-5, atol=1e-6)
    sm = np.asarray(F.softmin(jnp.asarray(X), axis=1))
    np.testing.assert_allclose(sm.sum(1), 1.0, rtol=1e-5)
    assert np.all(np.argmin(X, 1) == np.argmax(sm, 1))
    cr = np.asarray(F.crelu(jnp.asarray(X), axis=1))
    assert cr.shape == (3, 8)
    np.testing.assert_allclose(cr[:, :4], np.maximum(X, 0), rtol=1e-6)
    np.testing.assert_allclose(cr[:, 4:], np.maximum(-X, 0), rtol=1e-6)


def test_shape_manipulation_aliases():
    x = jnp.asarray(RNG.normal(0, 1, (2, 3, 4)).astype(np.float32))
    np.testing.assert_array_equal(np.asarray(F.swapaxes(x, 0, 2)),
                                  np.swapaxes(np.asarray(x), 0, 2))
    np.testing.assert_array_equal(np.asarray(F.moveaxis(x, 0, 1)),
                                  np.moveaxis(np.asarray(x), 0, 1))
    np.testing.assert_array_equal(np.asarray(F.rollaxis(x, 2)),
                                  np.rollaxis(np.asarray(x), 2))
    np.testing.assert_array_equal(np.asarray(F.flip(x, 1)),
                                  np.flip(np.asarray(x), 1))
    np.testing.assert_array_equal(np.asarray(F.repeat(x, 2, 1)),
                                  np.repeat(np.asarray(x), 2, 1))
    m = jnp.asarray(X)
    np.testing.assert_array_equal(np.asarray(F.diagonal(m)),
                                  np.diagonal(X))


def test_scale_bias_broadcast_semantics():
    """Reference F.scale/F.bias: y broadcast from ``axis`` (chainer's
    axis=1 channel convention), not numpy trailing-dim broadcasting."""
    x = jnp.asarray(RNG.normal(0, 1, (2, 3, 4)).astype(np.float32))
    y = jnp.asarray(np.asarray([1.0, 2.0, 3.0], np.float32))
    out = np.asarray(F.scale(x, y, axis=1))
    np.testing.assert_allclose(out, np.asarray(x) * y[None, :, None],
                               rtol=1e-6)
    out = np.asarray(F.bias(x, y, axis=1))
    np.testing.assert_allclose(out, np.asarray(x) + y[None, :, None],
                               rtol=1e-6)


def test_linalg_and_misc_aliases():
    a = jnp.asarray(X)
    b = jnp.asarray(RNG.normal(0, 1, (4, 5)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(F.matmul_nn(a, b)),
                               np.asarray(a) @ np.asarray(b), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(F.einsum("ij,jk->ik", a, b)),
        np.asarray(a) @ np.asarray(b), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(F.tensordot(a, b, axes=1)),
        np.tensordot(X, np.asarray(b), axes=1), rtol=1e-5)
    assert F.cast(a, jnp.bfloat16).dtype == jnp.bfloat16
    assert F.identity(a) is a
    assert F.identity(a, b) == (a, b)


def test_alias_tail_differentiable_under_jit():
    """The aliases sit in compiled train steps: spot-check grad+jit on a
    composition spanning trig/special/clip families."""
    def f(x):
        return jnp.sum(F.sin(x) * F.erf(x) + F.log1p(F.square(x))
                       + F.hard_sigmoid(x))

    g = jax.jit(jax.grad(f))(jnp.asarray(X))
    assert np.isfinite(np.asarray(g)).all()
    # analytic check at a point: d/dx[log1p(x^2)] = 2x/(1+x^2) for the
    # isolated term
    x0 = jnp.asarray(np.float32(0.5))
    g2 = jax.grad(lambda v: F.log1p(F.square(v)))(x0)
    np.testing.assert_allclose(float(g2), 2 * 0.5 / 1.25, rtol=1e-5)
