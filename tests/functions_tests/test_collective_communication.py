"""Forward + backward checks of the differentiable collectives.

Mirrors reference ``functions_tests/test_collective_communication.py``
(SURVEY.md §4): every op's forward values and gradients are asserted
against single-device math on the merged data.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu import functions as mnfn

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici")


def _per_rank(shape=(3,), scale=1.0):
    size = COMM.size
    return jnp.asarray(
        np.arange(size * int(np.prod(shape)), dtype=np.float32)
        .reshape((size,) + shape) * scale)


def test_allgather_forward_backward():
    x = _per_rank((2,))

    def f(x):
        parts = mnfn.allgather(COMM, x)
        assert len(parts) == COMM.size
        # weight rank i's slice by (i+1): grad wrt own x = (rank+1)
        return sum((i + 1) * jnp.sum(p) for i, p in enumerate(parts))

    def launched(x):
        return COMM.run_spmd(lambda x: (f(x), jax.grad(f)(x)), x,
                             out_specs=(P(), P(COMM.axis_name)))

    val, grad = launched(x)
    # forward: sum_i (i+1) * sum(x_i)
    expect = sum((i + 1) * np.asarray(x[i]).sum() for i in range(COMM.size))
    np.testing.assert_allclose(float(np.asarray(val)), expect, rtol=1e-6)
    # backward: every rank computes the (replicated) loss, so the
    # all_gather transpose accumulates size cotangent copies on each
    # source: d/dx_i = size * (i+1)
    g = np.asarray(grad).reshape(COMM.size, -1)
    for i in range(COMM.size):
        np.testing.assert_allclose(g[i], COMM.size * (i + 1), rtol=1e-6)


def test_allreduce_forward_backward():
    x = _per_rank((2,))

    def f(x):
        return jnp.sum(mnfn.allreduce(COMM, x) * 2.0)

    val, grad = COMM.run_spmd(lambda x: (f(x), jax.grad(f)(x)), x,
                              out_specs=(P(), P(COMM.axis_name)))
    # every rank's loss = 2 * sum over all ranks; psum of per-rank losses
    # not taken — check gradient instead: d loss_i/dx_j = 2 for all j;
    # reverse psum accumulates over ranks → 2 * size
    g = np.asarray(grad).reshape(COMM.size, -1)
    np.testing.assert_allclose(g, 2.0 * COMM.size, rtol=1e-6)


def test_bcast_forward_backward():
    x = _per_rank((2,))
    root = 3

    def f(x):
        y = mnfn.bcast(COMM, x, root=root)
        return jnp.sum(y * y)

    val, grad = COMM.run_spmd(
        lambda x: (f(x).reshape(1), jax.grad(f)(x)), x,
        out_specs=(P(COMM.axis_name), P(COMM.axis_name)))
    vals = np.asarray(val).reshape(COMM.size)
    expect_val = float((np.asarray(x[root]) ** 2).sum())
    np.testing.assert_allclose(vals, expect_val, rtol=1e-6)
    # gradient accumulates to root: sum over ranks of 2*x_root
    g = np.asarray(grad).reshape(COMM.size, -1)
    np.testing.assert_allclose(g[root],
                               2 * COMM.size * np.asarray(x[root]),
                               rtol=1e-6)
    for i in range(COMM.size):
        if i != root:
            np.testing.assert_allclose(g[i], 0.0)


def test_alltoall_forward_backward():
    size = COMM.size
    # rank r's input slice for destination d carries value 100*r + d
    x = jnp.asarray(np.array(
        [[[100 * r + d] for d in range(size)] for r in range(size)],
        np.float32))

    def f(local):
        # local: [size, 1] — one slice per destination
        out = mnfn.alltoall(COMM, local)
        # received[s] came from source s: value 100*s + me
        return sum((s + 1) * jnp.sum(o) for s, o in enumerate(out))

    def body(local):
        local2 = local.reshape(size, 1)
        val = f(local2).reshape(1)
        grad = jax.grad(lambda l: f(l.reshape(size, 1)))(local)
        return val, grad

    val, grad = COMM.run_spmd(body, x.reshape(size, size),
                              out_specs=(P(COMM.axis_name),
                                         P(COMM.axis_name)))
    vals = np.asarray(val).reshape(size)
    for me in range(size):
        expect = sum((s + 1) * (100 * s + me) for s in range(size))
        np.testing.assert_allclose(vals[me], expect, rtol=1e-6)
    # gradient: d loss_me / d x_r[d] flows back via reverse alltoall;
    # x_r[d] is consumed by rank d with weight (r+1)
    g = np.asarray(grad).reshape(size, size)
    for r in range(size):
        for d in range(size):
            np.testing.assert_allclose(g[r, d], r + 1, rtol=1e-6)


def test_scatter_forward():
    size = COMM.size
    xs = jnp.asarray(np.arange(size, dtype=np.float32).reshape(size, 1))

    def body(local):
        # every rank holds the root's stacked list (replicated input)
        return mnfn.scatter(COMM, xs, root=0) + 0.0 * local

    out = COMM.run_spmd(body, jnp.zeros((size, 1)),
                        out_specs=P(COMM.axis_name))
    np.testing.assert_allclose(np.asarray(out).reshape(size),
                               np.arange(size))


def test_gather_matches_allgather():
    x = _per_rank((1,))

    def body(x):
        parts = mnfn.gather(COMM, x, root=0)
        return jnp.concatenate(parts)

    out = COMM.run_spmd(body, x, out_specs=P(COMM.axis_name))
    flat = np.asarray(out).reshape(COMM.size, COMM.size)
    np.testing.assert_allclose(flat[0], np.arange(COMM.size))
