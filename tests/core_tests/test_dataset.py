"""Dataset / iterator / converter tests."""

import numpy as np
import pytest

from chainermn_tpu.dataset import (TupleDataset, SubDataset, TransformDataset,
                                   split_dataset, SerialIterator,
                                   MultithreadIterator, concat_examples,
                                   get_mnist)


def test_tuple_dataset():
    x = np.arange(10, dtype=np.float32)
    y = np.arange(10, dtype=np.int32) * 2
    ds = TupleDataset(x, y)
    assert len(ds) == 10
    assert ds[3] == (3.0, 6)
    sliced = ds[2:5]
    assert len(sliced) == 3 and sliced[0] == (2.0, 4)


def test_sub_dataset_with_order():
    base = np.arange(10)
    order = np.array([9, 8, 7, 6, 5, 4, 3, 2, 1, 0])
    sub = SubDataset(base, 2, 5, order=order)
    assert len(sub) == 3
    assert [sub[i] for i in range(3)] == [7, 6, 5]


def test_split_dataset():
    base = np.arange(10)
    a, b = split_dataset(base, 4)
    assert len(a) == 4 and len(b) == 6
    assert a[0] == 0 and b[0] == 4


def test_transform_dataset():
    ds = TransformDataset(np.arange(5), lambda x: x * 10)
    assert ds[2] == 20


def test_serial_iterator_epochs():
    ds = np.arange(10)
    it = SerialIterator(ds, batch_size=4, shuffle=False)
    seen = []
    for _ in range(5):
        seen.append(it.next())
    assert it.epoch == 2
    assert len(seen[0]) == 4


def test_serial_iterator_no_repeat():
    it = SerialIterator(np.arange(6), 4, repeat=False, shuffle=False)
    b1 = it.next()
    b2 = it.next()
    assert len(b1) == 4 and len(b2) == 2
    with pytest.raises(StopIteration):
        it.next()


def test_serial_iterator_shuffle_covers_all():
    it = SerialIterator(np.arange(8), 4, shuffle=True, seed=0)
    batch = it.next() + it.next()
    assert sorted(batch) == list(range(8))


def test_serial_iterator_serialize(tmp_path):
    from chainermn_tpu.serializers.npz import (DictionarySerializer,
                                               NpzDeserializer)
    it = SerialIterator(np.arange(10), 3, shuffle=True, seed=1)
    it.next()
    s = DictionarySerializer()
    it.serialize(s)
    np.savez(str(tmp_path / "it.npz"), **s.target)
    it2 = SerialIterator(np.arange(10), 3, shuffle=True, seed=2)
    with np.load(str(tmp_path / "it.npz")) as npz:
        it2.serialize(NpzDeserializer(npz))
    np.testing.assert_array_equal(it._order, it2._order)
    assert it2.current_position == it.current_position


def test_multithread_iterator():
    it = MultithreadIterator(np.arange(20), 5, shuffle=False)
    batches = [it.next() for _ in range(4)]
    assert sum(len(b) for b in batches) == 20
    it.finalize()


def test_concat_examples_tuples():
    batch = [(np.ones(3), 1), (np.zeros(3), 2)]
    x, y = concat_examples(batch)
    assert x.shape == (2, 3)
    np.testing.assert_array_equal(y, [1, 2])


def test_concat_examples_padding():
    batch = [np.ones(2), np.ones(4)]
    x = concat_examples(batch, padding=0)
    assert x.shape == (2, 4)
    np.testing.assert_array_equal(x[0], [1, 1, 0, 0])


def test_get_mnist_learnable_shapes():
    train, test = get_mnist(n_train=100, n_test=20)
    assert len(train) == 100 and len(test) == 20
    x, y = train[0]
    assert x.shape == (784,) and 0 <= y < 10


def test_multithread_iterator_reset():
    it = MultithreadIterator(np.arange(8), 4, repeat=False, shuffle=False)
    batches = []
    try:
        while True:
            batches.append(it.next())
    except StopIteration:
        pass
    assert len(batches) == 2
    it.reset()
    again = it.next()
    assert len(again) == 4
    it.finalize()


def test_trainer_default_stop_trigger_is_callable():
    from chainermn_tpu.training.trainer import Trainer

    class _FakeUpdater:
        iteration = 0
        epoch = 0
        epoch_detail = 0.0

        def get_all_optimizers(self):
            return {}

        def connect_trainer(self, trainer):
            pass

    t = Trainer(_FakeUpdater())
    assert t.stop_trigger(t) is False


def test_multithread_iterator_serialize_resume(tmp_path):
    """Prefetching iterator snapshots the CONSUMER position: resume
    continues the stream exactly where training saw it (ADVICE r1: the
    inherited no-op serialize restarted the stream)."""
    from chainermn_tpu.serializers.npz import (DictionarySerializer,
                                               NpzDeserializer)
    it = MultithreadIterator(np.arange(12), 4, shuffle=True, seed=3)
    seen = [sorted(it.next()) for _ in range(2)]
    s = DictionarySerializer()
    it.serialize(s)
    np.savez(str(tmp_path / "mt.npz"), **s.target)
    continuation = [sorted(it.next()) for _ in range(3)]
    it.finalize()

    it2 = MultithreadIterator(np.arange(12), 4, shuffle=True, seed=99)
    with np.load(str(tmp_path / "mt.npz")) as npz:
        it2.serialize(NpzDeserializer(npz))
    resumed = [sorted(it2.next()) for _ in range(3)]
    it2.finalize()
    assert resumed == continuation
    assert it2.epoch == it.epoch  # epoch bookkeeping restored


def test_multithread_iterator_epoch_detail_tracks_consumer():
    it = MultithreadIterator(np.arange(8), 4, shuffle=False, n_prefetch=4)
    assert it.epoch_detail == 0.0
    it.next()
    assert it.epoch_detail == 0.5  # consumer view, not prefetcher's
    it.next()
    assert it.epoch == 1 and it.is_new_epoch
    it.finalize()
