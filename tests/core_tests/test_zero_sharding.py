"""ZeRO-1 sharded optimizer state (beyond-reference, TPU-idiomatic).

Golden rule: the zero_sharding DP step computes EXACTLY the same
parameter trajectory as the plain DP step (which itself equals the
single-device full-batch step) — reduce-scatter + shard update +
all-gather is an exact refactoring of allreduce + replicated update.
Plus: the optimizer state really is sharded (per-device memory 1/n).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import chainermn_tpu as ct
from chainermn_tpu.core.optimizer import Adam, MomentumSGD
from chainermn_tpu.models import Classifier, MLP


def _data(seed=0, n=16, d=12, k=3):
    rng = np.random.RandomState(seed)
    x = rng.normal(0, 1, (n, d)).astype(np.float32)
    t = rng.randint(0, k, n).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(t)


def _run(zero, opt_cls, steps=4, hooks=(), **opt_kw):
    comm = ct.create_communicator("jax_ici")
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        opt_cls(**opt_kw), comm, zero_sharding=zero).setup(model)
    for hook in hooks:
        opt.add_hook(hook)
    x, t = _data()
    losses = [float(opt.update(model, x, t)) for _ in range(steps)]
    params = [np.asarray(p.array) for p in model.params()]
    return losses, params, opt


@pytest.mark.parametrize("opt_cls,kw", [
    (MomentumSGD, dict(lr=0.1, momentum=0.9)),
    (Adam, dict(alpha=1e-2)),
])
def test_zero_matches_plain_dp(opt_cls, kw):
    losses_z, params_z, _ = _run(True, opt_cls, **kw)
    losses_p, params_p, _ = _run(False, opt_cls, **kw)
    np.testing.assert_allclose(losses_z, losses_p, rtol=1e-5, atol=1e-7)
    for a, b in zip(params_z, params_p):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_zero_matches_plain_dp_with_gradient_clipping():
    """GradientClipping under ZeRO must clip by the GLOBAL norm (psum of
    per-chunk squared norms), not this rank's 1/n chunk norm — a
    chunk-local clip is off by up to sqrt(n) and silently diverges the
    trajectory.  Threshold chosen low enough that the clip engages from
    step one (MLP grads at init here have norm ~O(1))."""
    from chainermn_tpu.core.optimizer import GradientClipping
    hooks = (GradientClipping(0.05),)
    losses_z, params_z, _ = _run(True, MomentumSGD, hooks=hooks, lr=0.1,
                                 momentum=0.9)
    losses_p, params_p, _ = _run(False, MomentumSGD, hooks=hooks, lr=0.1,
                                 momentum=0.9)
    np.testing.assert_allclose(losses_z, losses_p, rtol=1e-5, atol=1e-7)
    for a, b in zip(params_z, params_p):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_zero_state_is_sharded():
    _, _, opt = _run(True, MomentumSGD, lr=0.1, momentum=0.9)
    n_devices = len(jax.devices())
    leaves = [l for l in jax.tree.leaves(opt.actual_optimizer._opt_state)
              if getattr(l, "ndim", 0) == 1 and l.shape[0] > 1]
    assert leaves, "no flat momentum leaf found"
    for leaf in leaves:
        # the state array stays sharded across steps: each device holds
        # exactly its 1/n chunk
        assert len(leaf.addressable_shards) == n_devices
        shard = leaf.addressable_shards[0]
        assert shard.data.shape[0] == leaf.shape[0] // n_devices


def test_zero_with_bf16_grad_compression():
    comm = ct.create_communicator("jax_ici",
                                  allreduce_grad_dtype="bfloat16")
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1), comm, zero_sharding=True).setup(model)
    x, t = _data(seed=2)
    l0 = float(opt.update(model, x, t))
    for _ in range(5):
        l = float(opt.update(model, x, t))
    assert np.isfinite(l) and l < l0


def test_zero_rejects_double_buffering():
    comm = ct.create_communicator("jax_ici")
    with pytest.raises(ValueError, match="zero_sharding"):
        ct.create_multi_node_optimizer(MomentumSGD(lr=0.1), comm,
                                       double_buffering=True,
                                       zero_sharding=True)


def test_zero_update_scan_matches_plain_scan():
    """ZeRO × fused K-step dispatch: the zero scan computes the same
    trajectory as the plain-DP scan (deterministic model), and the
    carried opt state stays the sharded flat vector."""
    K = 3

    def run(zero):
        comm = ct.create_communicator("jax_ici")
        model = Classifier(MLP(n_units=16, n_out=3, seed=0))
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            MomentumSGD(lr=0.1, momentum=0.9), comm,
            zero_sharding=zero).setup(model)
        rng = np.random.RandomState(4)
        xs = jnp.asarray(rng.normal(0, 1, (K, 16, 12)).astype(np.float32))
        ts = jnp.asarray(rng.randint(0, 3, (K, 16)).astype(np.int32))
        losses = np.asarray(opt.update_scan(model, xs, ts))
        params = [np.asarray(p.array) for p in model.params()]
        return losses, params, opt

    losses_z, params_z, opt_z = run(True)
    losses_p, params_p, _ = run(False)
    np.testing.assert_allclose(losses_z, losses_p, rtol=1e-5, atol=1e-7)
    for a, b in zip(params_z, params_p):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    n_devices = len(jax.devices())
    flat = [l for l in jax.tree.leaves(opt_z.actual_optimizer._opt_state)
            if getattr(l, "ndim", 0) == 1 and l.shape[0] > 1]
    assert flat and all(len(l.addressable_shards) == n_devices
                        for l in flat)


@pytest.mark.parametrize("opt_cls,kw", [
    (MomentumSGD, dict(lr=0.1, momentum=0.9)),
    (Adam, dict(alpha=1e-2)),
])
def test_zero_serialize_resume_roundtrip(tmp_path, opt_cls, kw):
    """Save mid-training, resume in a FRESH optimizer/model, continue:
    the resumed run must bit-exactly track the uninterrupted one.  The
    saved opt_state is the flat sharded vector — the resume path must
    rebuild the flat template + _zero_layout before leaf placement (a
    per-param template would silently mis-restore via leaf mismatch)."""
    from chainermn_tpu.serializers import save_npz, load_npz

    def fresh():
        comm = ct.create_communicator("jax_ici")
        model = Classifier(MLP(n_units=16, n_out=3, seed=0))
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            opt_cls(**kw), comm, zero_sharding=True).setup(model)
        opt.seed = 7
        return model, opt

    x, t = _data(seed=5)
    model_a, opt_a = fresh()
    for _ in range(3):
        opt_a.update(model_a, x, t)
    path = str(tmp_path / "zero_opt.npz")
    save_npz(path, opt_a)

    # uninterrupted continuation
    for _ in range(2):
        opt_a.update(model_a, x, t)

    # fresh-process resume: no prior update() — _zero_layout is None and
    # params come from the snapshot
    model_b, opt_b = fresh()
    load_npz(path, opt_b)
    assert opt_b.t == 3
    for _ in range(2):
        opt_b.update(model_b, x, t)

    for (na, pa), (nb, pb) in zip(model_a.namedparams(),
                                  model_b.namedparams()):
        assert na == nb
        np.testing.assert_array_equal(np.asarray(pa.array),
                                      np.asarray(pb.array),
                                      err_msg=f"param {na} diverged after "
                                              f"ZeRO resume")


def test_zero_resume_under_changed_communicator_size(tmp_path):
    """The host-gathered snapshot is a FULL flat vector, so resuming
    under a different communicator size is well-defined: the commit path
    slices to the true length n and re-pads to the new mesh's n_pad
    (8-way save → 2-way resume here: n_pad 264 vs 260 for the 259-param
    MLP).  Trajectory must keep matching the original continuation."""
    from chainermn_tpu.serializers import load_npz, save_npz

    x, t = _data(seed=5)

    # save under the 8-device jax_ici communicator
    comm = ct.create_communicator("jax_ici")
    model_a = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model_a)
    opt_a = ct.create_multi_node_optimizer(
        Adam(alpha=1e-2), comm, zero_sharding=True).setup(model_a)
    for _ in range(3):
        opt_a.update(model_a, x, t)
    path = str(tmp_path / "zero8.npz")
    save_npz(path, opt_a)

    # golden continuation on the original 8-way run
    for _ in range(2):
        opt_a.update(model_a, x, t)

    # resume under a 2-device communicator (different n_pad)
    comm2 = ct.create_communicator("jax_ici", devices=jax.devices()[:2])
    model_b = Classifier(MLP(n_units=16, n_out=3, seed=0))
    opt_b = ct.create_multi_node_optimizer(
        Adam(alpha=1e-2), comm2, zero_sharding=True).setup(model_b)
    load_npz(path, opt_b)
    assert opt_b.t == 3
    for _ in range(2):
        opt_b.update(model_b, x, t)

    for (na, pa), (nb, pb) in zip(model_a.namedparams(),
                                  model_b.namedparams()):
        assert na == nb
        np.testing.assert_allclose(
            np.asarray(pa.array), np.asarray(pb.array),
            rtol=1e-5, atol=1e-6,
            err_msg=f"param {na} diverged after size-changed resume")


def test_zero_resetup_then_load_restores_correctly(tmp_path):
    """Re-running setup() on a WARM ZeRO optimizer (e.g. rebinding the
    model before a resume) resets the wrapped optimizer's _opt_state —
    the wrapper's _zero_layout must reset with it.  A stale layout made
    the deserialize guard skip the flat-template pre-seed: the base
    reader then built a per-param template and placed the saved flat
    chunks onto mismatched slots (corrupted state), and the next
    update() crashed unpacking the layout."""
    from chainermn_tpu.serializers import save_npz, load_npz

    def fresh():
        comm = ct.create_communicator("jax_ici")
        model = Classifier(MLP(n_units=16, n_out=3, seed=0))
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            MomentumSGD(lr=0.1, momentum=0.9), comm,
            zero_sharding=True).setup(model)
        return model, opt

    x, t = _data(seed=9)
    model_a, opt_a = fresh()
    for _ in range(3):
        opt_a.update(model_a, x, t)
    path = str(tmp_path / "zero_resetup.npz")
    save_npz(path, opt_a)
    for _ in range(2):
        opt_a.update(model_a, x, t)

    # warm optimizer, then setup() again before loading the snapshot
    model_b, opt_b = fresh()
    for _ in range(4):  # warm: _zero_layout/_opt_state populated
        opt_b.update(model_b, x, t)
    opt_b.setup(model_b)  # resets _opt_state — layout must reset too
    load_npz(path, opt_b)
    assert opt_b.t == 3
    for _ in range(2):
        opt_b.update(model_b, x, t)

    for (na, pa), (nb, pb) in zip(model_a.namedparams(),
                                  model_b.namedparams()):
        assert na == nb
        np.testing.assert_array_equal(np.asarray(pa.array),
                                      np.asarray(pb.array),
                                      err_msg=f"param {na} diverged after "
                                              f"re-setup ZeRO resume")


def test_zero_warm_load_without_saved_state_keeps_state(tmp_path):
    """Loading a snapshot that carries NO opt_state keys (saved before
    the first update) into a WARM ZeRO optimizer must preserve the
    trained flat state — matching the non-ZeRO reader's semantics — not
    reset it to fresh init."""
    from chainermn_tpu.serializers import save_npz, load_npz
    comm = ct.create_communicator("jax_ici")
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1, momentum=0.9), comm,
        zero_sharding=True).setup(model)
    path = str(tmp_path / "pre_update.npz")
    save_npz(path, opt)  # t=0: no opt_state_* keys in the file
    x, t = _data()
    for _ in range(3):
        opt.update(model, x, t)
    before = [np.asarray(l) for l in
              jax.tree.leaves(opt.actual_optimizer._opt_state)]
    load_npz(path, opt)
    after = [np.asarray(l) for l in
             jax.tree.leaves(opt.actual_optimizer._opt_state)]
    assert len(before) == len(after)
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a, b)


def test_zero_rejects_unmarked_global_hook():
    """A hook that neither declares chunk_local nor provides
    to_optax_sharded must be rejected under ZeRO — applying a
    global-statistic hook to a 1/n chunk silently changes semantics."""
    import optax
    from chainermn_tpu.core.optimizer import _Hook

    class CustomGlobalHook(_Hook):
        name = "CustomGlobalHook"

        def to_optax(self):
            return optax.identity()

    comm = ct.create_communicator("jax_ici")
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1), comm, zero_sharding=True).setup(model)
    opt.add_hook(CustomGlobalHook())
    x, t = _data()
    with pytest.raises(ValueError, match="chunk_local"):
        opt.update(model, x, t)


def test_zero_grad_not_populated_documented_contract():
    _, _, opt = _run(True, MomentumSGD, steps=1, lr=0.1)
    for p in opt.target.params():
        assert p.grad is None
