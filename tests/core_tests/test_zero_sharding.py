"""ZeRO-1 sharded optimizer state (beyond-reference, TPU-idiomatic).

Golden rule: the zero_sharding DP step computes EXACTLY the same
parameter trajectory as the plain DP step (which itself equals the
single-device full-batch step) — reduce-scatter + shard update +
all-gather is an exact refactoring of allreduce + replicated update.
Plus: the optimizer state really is sharded (per-device memory 1/n).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import chainermn_tpu as ct
from chainermn_tpu.core.optimizer import Adam, MomentumSGD
from chainermn_tpu.models import Classifier, MLP


def _data(seed=0, n=16, d=12, k=3):
    rng = np.random.RandomState(seed)
    x = rng.normal(0, 1, (n, d)).astype(np.float32)
    t = rng.randint(0, k, n).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(t)


def _run(zero, opt_cls, steps=4, **opt_kw):
    comm = ct.create_communicator("jax_ici")
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        opt_cls(**opt_kw), comm, zero_sharding=zero).setup(model)
    x, t = _data()
    losses = [float(opt.update(model, x, t)) for _ in range(steps)]
    params = [np.asarray(p.array) for p in model.params()]
    return losses, params, opt


@pytest.mark.parametrize("opt_cls,kw", [
    (MomentumSGD, dict(lr=0.1, momentum=0.9)),
    (Adam, dict(alpha=1e-2)),
])
def test_zero_matches_plain_dp(opt_cls, kw):
    losses_z, params_z, _ = _run(True, opt_cls, **kw)
    losses_p, params_p, _ = _run(False, opt_cls, **kw)
    np.testing.assert_allclose(losses_z, losses_p, rtol=1e-5, atol=1e-7)
    for a, b in zip(params_z, params_p):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_zero_state_is_sharded():
    _, _, opt = _run(True, MomentumSGD, lr=0.1, momentum=0.9)
    n_devices = len(jax.devices())
    leaves = [l for l in jax.tree.leaves(opt.actual_optimizer._opt_state)
              if getattr(l, "ndim", 0) == 1 and l.shape[0] > 1]
    assert leaves, "no flat momentum leaf found"
    for leaf in leaves:
        # the state array stays sharded across steps: each device holds
        # exactly its 1/n chunk
        assert len(leaf.addressable_shards) == n_devices
        shard = leaf.addressable_shards[0]
        assert shard.data.shape[0] == leaf.shape[0] // n_devices


def test_zero_with_bf16_grad_compression():
    comm = ct.create_communicator("jax_ici",
                                  allreduce_grad_dtype="bfloat16")
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1), comm, zero_sharding=True).setup(model)
    x, t = _data(seed=2)
    l0 = float(opt.update(model, x, t))
    for _ in range(5):
        l = float(opt.update(model, x, t))
    assert np.isfinite(l) and l < l0


def test_zero_rejects_double_buffering_and_scan():
    comm = ct.create_communicator("jax_ici")
    with pytest.raises(ValueError, match="zero_sharding"):
        ct.create_multi_node_optimizer(MomentumSGD(lr=0.1), comm,
                                       double_buffering=True,
                                       zero_sharding=True)
    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1), comm, zero_sharding=True).setup(model)
    x, t = _data()
    xs = jnp.broadcast_to(x, (2,) + x.shape)
    ts = jnp.broadcast_to(t, (2,) + t.shape)
    with pytest.raises(RuntimeError, match="zero_sharding"):
        opt.update_scan(model, xs, ts)


def test_zero_grad_not_populated_documented_contract():
    _, _, opt = _run(True, MomentumSGD, steps=1, lr=0.1)
    for p in opt.target.params():
        assert p.grad is None
