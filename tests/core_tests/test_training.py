"""Trainer loop end-to-end: single-device MNIST MLP trains + extensions fire."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

import chainermn_tpu as ct
from chainermn_tpu import F, L
from chainermn_tpu.core.optimizer import Adam, SGD
from chainermn_tpu.dataset import SerialIterator, get_mnist
from chainermn_tpu.serializers import save_npz, load_npz
from chainermn_tpu.training import StandardUpdater, Trainer, extensions


class MLP(ct.Chain):
    def __init__(self, n_units=32, n_out=10):
        super().__init__()
        with self.init_scope():
            self.l1 = L.Linear(None, n_units, seed=10)
            self.l2 = L.Linear(None, n_out, seed=11)

    def forward(self, x):
        return self.l2(F.relu(self.l1(x)))


class Classifier(ct.Chain):
    def __init__(self, predictor):
        super().__init__()
        with self.init_scope():
            self.predictor = predictor

    def forward(self, x, t):
        y = self.predictor(x)
        loss = F.softmax_cross_entropy(y, t)
        acc = F.accuracy(y, t)
        ct.report({"loss": loss, "accuracy": acc}, self)
        return loss


@pytest.fixture(scope="module")
def mnist_small():
    return get_mnist(n_train=512, n_test=128)


def test_trainer_end_to_end(tmp_path, mnist_small):
    train, test = mnist_small
    model = Classifier(MLP())
    optimizer = Adam().setup(model)
    train_iter = SerialIterator(train, 64, seed=0)
    test_iter = SerialIterator(test, 64, repeat=False, shuffle=False)
    updater = StandardUpdater(train_iter, optimizer)
    trainer = Trainer(updater, (3, "epoch"), out=str(tmp_path / "result"))
    trainer.extend(extensions.Evaluator(test_iter, model), trigger=(1, "epoch"))
    trainer.extend(extensions.LogReport(trigger=(1, "epoch")))
    trainer.run()

    log = trainer.get_extension("LogReport").log
    assert len(log) == 3
    assert "main/loss" in log[0]
    assert "validation/main/accuracy" in log[0]
    # synthetic task is learnable: accuracy well above chance by epoch 3
    assert log[-1]["validation/main/accuracy"] > 0.5
    assert log[-1]["main/loss"] < log[0]["main/loss"]
    assert os.path.exists(os.path.join(str(tmp_path / "result"), "log"))


def test_snapshot_and_resume(tmp_path, mnist_small):
    train, _ = mnist_small

    def build():
        model = Classifier(MLP())
        optimizer = SGD(lr=0.05).setup(model)
        it = SerialIterator(train, 64, seed=3)
        updater = StandardUpdater(it, optimizer)
        return model, Trainer(updater, (2, "epoch"),
                              out=str(tmp_path / "result"))

    model, trainer = build()
    trainer.extend(extensions.snapshot(filename="snap_{.updater.iteration}"),
                   trigger=(1, "epoch"))
    trainer.run()
    snaps = [f for f in os.listdir(trainer.out) if f.startswith("snap_")]
    assert snaps
    # resume into a fresh trainer
    model2, trainer2 = build()
    load_npz(os.path.join(trainer.out, sorted(
        snaps, key=lambda s: int(s.split("_")[1]))[-1]), trainer2)
    it = trainer2.updater.get_iterator("main")
    assert trainer2.updater.iteration > 0
    w1 = np.asarray(dict(model.namedparams())["/predictor/l1/W"].array)
    # last snapshot was at epoch boundary 2 == end; params match final state
    w2 = np.asarray(dict(model2.namedparams())["/predictor/l1/W"].array)
    np.testing.assert_allclose(w1, w2, rtol=1e-6)


def test_resume_pre_trigger_serialize_snapshot(tmp_path, mnist_small):
    """ADVICE r4: Max/Min/OnceTrigger gained serialize() in r4, so a
    STRICT load of a snapshot written before that (no stop_trigger/
    keys) must not KeyError — the stop trigger keeps fresh state."""
    train, _ = mnist_small

    def build():
        model = Classifier(MLP())
        optimizer = SGD(lr=0.05).setup(model)
        it = SerialIterator(train, 64, seed=3)
        updater = StandardUpdater(it, optimizer)
        return model, Trainer(updater, (2, "iteration"),
                              out=str(tmp_path / "pre"))

    from chainermn_tpu.serializers.npz import DictionarySerializer
    model, trainer = build()
    trainer.run()
    s = DictionarySerializer()
    trainer.serialize(s)
    legacy = {k: v for k, v in s.target.items()
              if not k.startswith("stop_trigger/")}
    path = str(tmp_path / "legacy_snap.npz")
    np.savez(path, **legacy)
    _, trainer2 = build()
    load_npz(path, trainer2)  # strict — must not raise
    assert trainer2.updater.iteration == 2


def test_exponential_shift(tmp_path, mnist_small):
    train, _ = mnist_small
    model = Classifier(MLP())
    optimizer = SGD(lr=1.0).setup(model)
    it = SerialIterator(train, 128, seed=1)
    updater = StandardUpdater(it, optimizer)
    trainer = Trainer(updater, (8, "iteration"), out=str(tmp_path / "r2"))
    trainer.extend(extensions.ExponentialShift("lr", 0.5),
                   trigger=(2, "iteration"))
    trainer.run()
    assert optimizer.lr == pytest.approx(1.0 * 0.5 ** 4)


def test_link_serialize_roundtrip(tmp_path):
    m1 = MLP()
    m1(np.ones((1, 784), np.float32))  # materialize lazy params
    path = str(tmp_path / "model.npz")
    save_npz(path, m1)
    m2 = MLP()
    m2(np.ones((1, 784), np.float32))
    load_npz(path, m2)
    for (n1, p1), (n2, p2) in zip(m1.namedparams(), m2.namedparams()):
        assert n1 == n2
        np.testing.assert_allclose(np.asarray(p1.array), np.asarray(p2.array))


def test_bn_link_serialize_includes_persistent(tmp_path):
    bn1 = L.BatchNormalization(4)
    x = np.random.RandomState(0).normal(1, 2, (32, 4)).astype(np.float32)
    from chainermn_tpu.core.link import extract_state, apply_state
    state = extract_state(bn1)
    _, new_state = apply_state(bn1, state, x)
    # write mutated stats back into the link, then snapshot
    bn1.avg_mean = new_state["state"]["/avg_mean"]
    bn1.avg_var = new_state["state"]["/avg_var"]
    path = str(tmp_path / "bn.npz")
    save_npz(path, bn1)
    bn2 = L.BatchNormalization(4)
    load_npz(path, bn2)
    np.testing.assert_allclose(np.asarray(bn2.avg_mean),
                               np.asarray(bn1.avg_mean))


def test_evaluator_falls_back_for_untraceable_forward(tmp_path, mnist_small):
    """Forwards with value-dependent Python control flow still evaluate
    (eager fallback instead of a trace crash)."""
    train, test = mnist_small

    class HostyClassifier(Classifier):
        def forward(self, x, t):
            y = self.predictor(x)
            loss = F.softmax_cross_entropy(y, t)
            # host-side branch: not jit-traceable
            if float(np.asarray(loss)) > -1.0:
                ct.report({"loss": loss}, self)
            return loss

    model = HostyClassifier(MLP())
    model(np.ones((1, 784), np.float32), np.zeros((1,), np.int32))
    from chainermn_tpu.training.extensions import Evaluator
    from chainermn_tpu.dataset import SerialIterator
    ev = Evaluator(SerialIterator(test, 64, repeat=False, shuffle=False),
                   model)
    result = ev()
    assert any(k.endswith("main/loss") for k in result)
    assert ev._eval_compile_failed


def test_stateful_lstm_no_tracer_leak_through_compiled_paths():
    """bind_state restores volatile LSTM state after traced calls."""
    import jax

    class LstmNet(ct.Chain):
        def __init__(self):
            super().__init__()
            with self.init_scope():
                self.lstm = L.LSTM(4, 6, seed=0)
                self.out = L.Linear(6, 2, seed=1)

        def forward(self, x, t):
            self.lstm.reset_state()
            h = self.lstm(x)
            return F.softmax_cross_entropy(self.out(h), t)

    net = LstmNet()
    opt = SGD(lr=0.1).setup(net)
    x = np.random.RandomState(0).normal(0, 1, (3, 4)).astype(np.float32)
    t = np.zeros(3, np.int32)
    opt.update(net, jnp.asarray(x), jnp.asarray(t))
    # volatile state restored — no tracer leaked into the link
    assert not isinstance(net.lstm.h, jax.core.Tracer)
    opt.update(net, jnp.asarray(x), jnp.asarray(t))  # second step fine


def test_profile_extension_captures_trace(tmp_path, mnist_small):
    train, _ = mnist_small
    from chainermn_tpu.utils.profiling import Profile
    model = Classifier(MLP())
    optimizer = SGD(lr=0.05).setup(model)
    it = SerialIterator(train, 128, seed=5)
    updater = StandardUpdater(it, optimizer)
    trainer = Trainer(updater, (6, "iteration"), out=str(tmp_path / "p"))
    trainer.extend(Profile(start=2, n_steps=2,
                           log_dir=str(tmp_path / "trace")))
    trainer.run()
    assert os.path.isdir(str(tmp_path / "trace"))


def test_parameter_statistics_extension():
    from chainermn_tpu.training.extensions import ParameterStatistics
    model = MLP()
    model(np.ones((1, 784), np.float32))
    for p in model.params():
        p.grad = jnp.ones_like(p.array) * 2.0
    ext = ParameterStatistics(model, prefix=None)
    obs = ext(None)
    keys = list(obs)
    assert any(k.endswith("/l1/W/data/mean") for k in keys)
    grad_means = [float(np.asarray(v)) for k, v in obs.items()
                  if k.endswith("/grad/mean")]
    np.testing.assert_allclose(grad_means, 2.0, rtol=1e-6)


def test_groupnorm_and_bn_finetune():
    from chainermn_tpu import L
    gn = L.GroupNormalization(2, 8)
    x = jnp.asarray(np.random.RandomState(0).normal(2, 3, (4, 8))
                    .astype(np.float32))
    y = gn(x)
    assert y.shape == x.shape
    # per-group normalization: near-zero mean per group
    groups = np.asarray(y).reshape(4, 2, 4)
    np.testing.assert_allclose(groups.mean(axis=2), 0.0, atol=1e-4)

    bn = L.BatchNormalization(8)
    bn(x, finetune=True)
    assert bn.N == 1
    bn(x, finetune=True)
    assert bn.N == 2


def test_logreport_log_survives_snapshot(tmp_path, mnist_small):
    train, _ = mnist_small

    def build():
        model = Classifier(MLP())
        optimizer = SGD(lr=0.05).setup(model)
        it = SerialIterator(train, 128, seed=9)
        updater = StandardUpdater(it, optimizer)
        trainer = Trainer(updater, (2, "epoch"), out=str(tmp_path / "lr"))
        trainer.extend(extensions.LogReport(trigger=(1, "epoch")))
        return trainer

    t1 = build()
    t1.extend(extensions.snapshot(filename="s"), trigger=(2, "epoch"))
    t1.run()
    assert len(t1.get_extension("LogReport").log) == 2
    t2 = build()
    load_npz(os.path.join(str(tmp_path / "lr"), "s"), t2)
    assert len(t2.get_extension("LogReport").log) == 2


def test_fused_updater_equals_standard(tmp_path, mnist_small):
    """FusedUpdater (K steps per dispatch) produces the same weights as
    StandardUpdater over the same batch stream (deterministic model)."""
    from chainermn_tpu.training import FusedUpdater
    train, _ = mnist_small
    comm = ct.create_communicator("jax_ici")

    def run(fused):
        model = Classifier(MLP())
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(SGD(lr=0.05), comm).setup(model)
        it = SerialIterator(train, 64, seed=0)
        if fused:
            upd = FusedUpdater(it, opt, n_fused=2)
            trainer = Trainer(upd, (4, "iteration"), out=str(tmp_path / "f"))
        else:
            upd = StandardUpdater(it, opt)
            trainer = Trainer(upd, (4, "iteration"), out=str(tmp_path / "s"))
        trainer.run()
        assert upd.iteration == 4
        return model

    m_std = run(False)
    m_fused = run(True)
    for (_, p1), (_, p2) in zip(m_fused.namedparams(), m_std.namedparams()):
        np.testing.assert_allclose(np.asarray(p1.array), np.asarray(p2.array),
                                   rtol=1e-5, atol=1e-6)


def test_fused_updater_with_zero_sharding(tmp_path, mnist_small):
    """ZeRO-1 under the FusedUpdater (update_scan path): same weights as
    the plain-DP FusedUpdater over the same batch stream."""
    from chainermn_tpu.training import FusedUpdater
    train, _ = mnist_small
    comm = ct.create_communicator("jax_ici")

    def run(zero):
        model = Classifier(MLP())
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            SGD(lr=0.05), comm, zero_sharding=zero).setup(model)
        it = SerialIterator(train, 64, seed=0)
        upd = FusedUpdater(it, opt, n_fused=2)
        trainer = Trainer(upd, (4, "iteration"),
                          out=str(tmp_path / ("z" if zero else "p")))
        trainer.run()
        assert upd.iteration == 4
        return model

    m_zero = run(True)
    m_plain = run(False)
    for (_, p1), (_, p2) in zip(m_zero.namedparams(),
                                m_plain.namedparams()):
        np.testing.assert_allclose(np.asarray(p1.array),
                                   np.asarray(p2.array),
                                   rtol=1e-5, atol=1e-6)


def test_fused_updater_logreport_matches_unfused(tmp_path, mnist_small):
    """Observation parity (VERDICT r2 Weak #7): update_scan reports the
    MEAN observation over its K fused steps, so a LogReport window
    covering the same iterations logs the same main/loss either way
    (deterministic model, identical batch stream)."""
    from chainermn_tpu.training import FusedUpdater
    train, _ = mnist_small
    comm = ct.create_communicator("jax_ici")

    def run(fused, out):
        model = Classifier(MLP())
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(SGD(lr=0.05), comm).setup(model)
        it = SerialIterator(train, 64, seed=0)
        upd = FusedUpdater(it, opt, n_fused=2) if fused \
            else StandardUpdater(it, opt)
        trainer = Trainer(upd, (4, "iteration"), out=out)
        trainer.extend(extensions.LogReport(trigger=(4, "iteration")))
        trainer.run()
        return trainer.get_extension("LogReport").log

    log_f = run(True, str(tmp_path / "f"))
    log_s = run(False, str(tmp_path / "s"))
    assert len(log_f) == len(log_s) == 1
    for key in ("main/loss", "main/accuracy"):
        np.testing.assert_allclose(log_f[0][key], log_s[0][key],
                                   rtol=1e-5, atol=1e-6)


def test_fused_updater_epoch_boundary_mid_block(mnist_small):
    """new_epoch() fires even when the epoch boundary lands on a
    non-final pull of the fused block."""
    from chainermn_tpu.training import FusedUpdater
    train, _ = mnist_small  # 512 samples
    comm = ct.create_communicator("jax_ici")
    model = Classifier(MLP())
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(SGD(lr=0.05), comm).setup(model)
    # 512/128 = 4 iterations per epoch; n_fused=3 puts the first epoch
    # boundary on pull 1 of the second dispatch (iteration 4)
    it = SerialIterator(train, 128, seed=0)
    upd = FusedUpdater(it, opt, n_fused=3)
    upd.update()          # iterations 1-3, no boundary
    assert opt.epoch == 0
    upd.update()          # iterations 4-6: boundary at 4 (mid-block)
    assert opt.epoch == 1
