"""Convergence-parity gate for the quantized gradient wire (ISSUE 8).

The quantized exchange is deliberately NOT bit-exact — int8/fp8
codebooks round.  Its golden gate (ROADMAP item 2) is therefore
CONVERGENCE PARITY on the transformer vertical: train the same model
through the quantized wire and through the lossless one, and hold the
quantized trajectory inside a tolerance band of the lossless one —
final loss within the band, parameter trajectory close — across the
full grid {int8, fp8} × {error feedback on/off} × {hierarchical,
hierarchical_rs} on the simulated 2-host mesh (dcn 2 × ici 4).

The ablation half is the point of error feedback: with the residual
carried, the accumulated quantization error telescopes (one step's
error, forever); with it off, the per-step rounding bias random-walks
into the trajectory.  Final LOSS barely notices on a converged toy —
parameter-space distance to the lossless trajectory is the sensitive
discriminator — so the assertion is on distances: error-feedback OFF
lands demonstrably farther from the lossless run than error-feedback
ON, for every wire × exchange (deterministic on the CPU mesh: fixed
seeds, fixed schedule).

Tier-1 runs a scaled instance of the SAME TransformerLM family as the
committed census vertical (tools/comm_census.py VERTICAL is ~5.8M
params — minutes of CPU compile × 9 configs would blow the tier-1
budget); the committed-size run is the ``slow``-marked variant below.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import chainermn_tpu as ct
from chainermn_tpu.core.optimizer import Adam
from chainermn_tpu.models.transformer import TransformerLM

#: the tier-1 parity vertical: same family/graph as the census vertical,
#: scaled so 9 compiled runs stay in seconds
V, B, T = 64, 8, 16
STEPS = 40
ALPHA = 3e-3
#: final-loss tolerance band vs the lossless trajectory (relative);
#: observed deviations are ≲1.3% (e5m2, the coarsest wire, excluded
#: from the tier-1 grid — it rides the slow variant)
LOSS_BAND = 0.05
#: EF-off must land at least this factor farther (param space) from the
#: lossless trajectory than EF-on; observed ratios are ~1.25–1.35
ABLATION_MARGIN = 1.1

GRID_WIRES = ("int8", "float8_e4m3")
GRID_EXCHANGES = ("allreduce", "reduce_scatter")


def _data(vocab=V):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, vocab, (B, T)).astype(np.int32))
    t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1).astype(np.int32))
    return x, t


def _run(grad_dtype=None, error_feedback=True, exchange="allreduce",
         steps=STEPS, vertical=None):
    v = vertical or dict(n_vocab=V, d_model=32, n_heads=2, n_layers=2)
    comm = ct.create_communicator(
        "hierarchical", inter_size=2,
        allreduce_grad_dtype=grad_dtype, error_feedback=error_feedback)
    model = TransformerLM(v["n_vocab"], d_model=v["d_model"],
                          n_heads=v["n_heads"], n_layers=v["n_layers"],
                          seed=0)
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        Adam(alpha=ALPHA), comm, exchange=exchange).setup(model)
    x, t = _data(v["n_vocab"])
    losses = [float(opt.update(model, x, t)) for _ in range(steps)]
    params = np.concatenate([np.asarray(p.array).ravel()
                             for p in model.params()])
    return losses, params, opt


@pytest.fixture(scope="module")
def lossless():
    losses, params, _ = _run()
    # the vertical actually converges — parity against a non-learning
    # run would be vacuous
    assert losses[-1] < 0.25 < losses[0]
    return losses, params


@pytest.mark.parametrize("exchange", GRID_EXCHANGES)
@pytest.mark.parametrize("wire", GRID_WIRES)
def test_quantized_parity_and_ef_ablation(wire, exchange, lossless):
    """The acceptance grid: EF-on stays in the band AND beats EF-off in
    trajectory distance, per wire × exchange."""
    glosses, gparams = lossless
    ef_losses, ef_params, ef_opt = _run(
        {"dcn": wire}, True, exchange)
    no_losses, no_params, no_opt = _run(
        {"dcn": wire}, False, exchange)
    # 1. convergence parity (the golden gate): final loss in the band
    assert abs(ef_losses[-1] - glosses[-1]) \
        <= LOSS_BAND * glosses[-1], (wire, exchange, ef_losses[-1])
    assert np.isfinite(ef_losses).all()
    # 2. the machinery engaged: EF run carries a live residual, the
    #    ablation run never allocated one
    assert ef_opt._residual is not None
    assert float(jnp.max(jnp.abs(ef_opt._residual))) > 0
    assert no_opt._residual is None
    # 3. the ablation (the reason error feedback exists): EF-off drifts
    #    demonstrably farther from the lossless trajectory
    d_ef = float(np.linalg.norm(ef_params - gparams))
    d_no = float(np.linalg.norm(no_params - gparams))
    assert d_no > d_ef * ABLATION_MARGIN, (
        f"{wire}×{exchange}: error-feedback-off distance {d_no:.4f} is "
        f"not demonstrably worse than error-feedback-on {d_ef:.4f} — "
        f"either the residual is not being applied or the wire is not "
        f"actually quantizing")


def test_compress_off_escape_hatch_restores_lossless(lossless,
                                                     monkeypatch):
    """CHAINERMN_TPU_COMPRESS=off: the factory-level escape hatch
    drops the quantized wire back to lossless — trajectory EQUALS the
    lossless run (not merely within the band)."""
    monkeypatch.setenv("CHAINERMN_TPU_COMPRESS", "off")
    losses, params, opt = _run({"dcn": "int8"}, True, "allreduce",
                               steps=3)
    assert not opt.communicator.quantized
    assert opt._residual is None
    np.testing.assert_allclose(losses, lossless[0][:3], rtol=1e-6,
                               atol=1e-7)


@pytest.mark.slow
def test_quantized_parity_committed_vertical():
    """The committed-size census vertical (tools/comm_census.VERTICAL)
    through the int8 wire — the full-fidelity version of the tier-1
    gate above (minutes of CPU compile; run via ``-m slow`` or on
    chip).  Same assertions, committed model size."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "tools"))
    import comm_census
    vert = {k: comm_census.VERTICAL[k]
            for k in ("n_vocab", "d_model", "n_heads", "n_layers")}
    steps = 15
    glosses, gparams, _ = _run(steps=steps, vertical=vert)
    ef_losses, ef_params, _ = _run({"dcn": "int8"}, True, "allreduce",
                                   steps=steps, vertical=vert)
    no_losses, no_params, _ = _run({"dcn": "int8"}, False, "allreduce",
                                   steps=steps, vertical=vert)
    assert abs(ef_losses[-1] - glosses[-1]) <= LOSS_BAND * glosses[-1]
    d_ef = float(np.linalg.norm(ef_params - gparams))
    d_no = float(np.linalg.norm(no_params - gparams))
    assert d_no > d_ef
