"""Link/Parameter container tests (reference test model: chainer link tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu import L, F
from chainermn_tpu.core.link import (extract_state, apply_state, bind_state,
                                     param_tree, load_param_tree)


class _MLP(ct.Chain):
    def __init__(self):
        super().__init__()
        with self.init_scope():
            self.l1 = L.Linear(4, 8, seed=0)
            self.l2 = L.Linear(8, 3, seed=1)

    def forward(self, x):
        return self.l2(F.relu(self.l1(x)))


def test_param_registration():
    m = _MLP()
    names = [n for n, _ in m.namedparams()]
    assert sorted(names) == ["/l1/W", "/l1/b", "/l2/W", "/l2/b"]
    assert m.count_params() == 4 * 8 + 8 + 8 * 3 + 3


def test_outside_init_scope_not_registered():
    m = _MLP()
    m.extra = ct.Parameter(jnp.zeros(3))
    assert "/extra" not in [n for n, _ in m.namedparams()]


def test_cleargrads():
    m = _MLP()
    for p in m.params():
        p.grad = jnp.zeros_like(p.array)
    m.cleargrads()
    assert all(p.grad is None for p in m.params())


def test_extract_and_apply_state():
    m = _MLP()
    state = extract_state(m)
    assert set(state["params"]) == {"/l1/W", "/l1/b", "/l2/W", "/l2/b"}
    x = jnp.ones((2, 4))
    y_direct = m(x)
    y_fn, _ = apply_state(m, state, x)
    np.testing.assert_allclose(np.asarray(y_direct), np.asarray(y_fn))


def test_apply_state_is_jittable_and_differentiable():
    m = _MLP()
    state = extract_state(m)
    x = jnp.ones((2, 4))

    @jax.jit
    def loss_fn(params, x):
        y, _ = apply_state(m, {"params": params, "state": {}}, x)
        return jnp.sum(y ** 2)

    g = jax.grad(loss_fn)(state["params"], x)
    assert set(g) == set(state["params"])
    assert all(np.isfinite(np.asarray(v)).all() for v in g.values())


def test_bn_persistent_state_threads_through_jit():
    bn = L.BatchNormalization(3)
    state = extract_state(bn)
    assert "/avg_mean" in state["state"] and "/avg_var" in state["state"]
    x = jnp.asarray(np.random.RandomState(0).normal(2.0, 3.0, (16, 3)).astype(np.float32))

    @jax.jit
    def step(state, x):
        y, new_state = apply_state(bn, state, x)
        return y, new_state

    y, new_state = step(state, x)
    # running stats moved toward batch moments
    assert not np.allclose(np.asarray(new_state["state"]["/avg_mean"]), 0.0)
    # normalized output: ~zero mean, ~unit var
    np.testing.assert_allclose(np.asarray(y.mean(axis=0)), 0.0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(y.var(axis=0)), 1.0, atol=1e-2)


def test_bn_test_mode_uses_running_stats():
    bn = L.BatchNormalization(3)
    x = jnp.asarray(np.random.RandomState(1).normal(0, 1, (8, 3)).astype(np.float32))
    with ct.using_config("train", False):
        y = bn(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-4)


def test_chainlist_and_sequential():
    cl = ct.ChainList(L.Linear(2, 3, seed=0), L.Linear(3, 4, seed=1))
    assert len(cl) == 2
    names = [n for n, _ in cl.namedparams()]
    assert "/0/W" in names and "/1/W" in names
    seq = ct.Sequential(L.Linear(2, 5, seed=0), F.relu, L.Linear(5, 2, seed=1))
    y = seq(jnp.ones((3, 2)))
    assert y.shape == (3, 2)


def test_copyparams():
    a, b = _MLP(), _MLP()
    b.l1.W.array = jnp.zeros_like(b.l1.W.array)
    b.copyparams(a)
    np.testing.assert_allclose(np.asarray(b.l1.W.array), np.asarray(a.l1.W.array))


def test_lazy_linear_initializes_on_first_call():
    layer = L.Linear(None, 7)
    assert layer.W.array is None
    y = layer(jnp.ones((2, 5)))
    assert layer.W.array.shape == (7, 5)
    assert y.shape == (2, 7)


def test_conv2d_two_arg_form():
    # Chainer-style Convolution2D(out_channels, ksize) with lazy in_channels
    conv = L.Convolution2D(16, 3)
    y = conv(jnp.ones((2, 5, 8, 8)))
    assert conv.W.array.shape == (16, 5, 3, 3)
    assert y.shape == (2, 16, 6, 6)


def test_unpooling_2d_stride_pad():
    x = jnp.arange(8.0).reshape(1, 1, 2, 4)
    y = F.unpooling_2d(x, 2, stride=2, pad=0, cover_all=False)
    assert y.shape == (1, 1, 4, 8)
    np.testing.assert_allclose(np.asarray(y[0, 0, :2, :2]),
                               [[0, 0], [0, 0]])
    # overlapping windows sum: ksize=3, stride=1
    x2 = jnp.ones((1, 1, 3, 3))
    y2 = F.unpooling_2d(x2, 3, stride=1, pad=0, cover_all=False)
    assert y2.shape == (1, 1, 5, 5)
    # center cell covered by all 9 windows
    assert float(y2[0, 0, 2, 2]) == 9.0


def test_gru_and_nstep_rnns():
    from chainermn_tpu.nn.rnn import GRU, NStepGRU, NStepLSTM
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(0, 1, (3, 5, 4)).astype(np.float32))

    hy, cy, ys = NStepLSTM(2, 4, 6, seed=0)(None, None, x)
    assert hy.shape == (2, 3, 6) and cy.shape == (2, 3, 6)
    assert ys.shape == (3, 5, 6)

    hy2, ys2 = NStepGRU(2, 4, 6, seed=1)(None, x)
    assert hy2.shape == (2, 3, 6) and ys2.shape == (3, 5, 6)

    # mask freezes state: fully-masked suffix leaves hy at the prefix value
    mask = jnp.asarray(np.array([[True] * 2 + [False] * 3] * 3))
    lstm = NStepLSTM(1, 4, 6, seed=2)
    hy_m, _, _ = lstm(None, None, x, mask=mask)
    hy_p, _, _ = lstm(None, None, x[:, :2])
    np.testing.assert_allclose(np.asarray(hy_m), np.asarray(hy_p),
                               rtol=1e-5)

    gru = GRU(4, 6, seed=3)
    h1 = gru(x[:, 0])
    h2 = gru(x[:, 1])
    assert h2.shape == (3, 6)
    gru.reset_state()
    np.testing.assert_allclose(np.asarray(gru(x[:, 0])), np.asarray(h1),
                               rtol=1e-6)


def test_additional_links_and_functions():
    from chainermn_tpu.nn.links import Highway, Maxout, Scale
    x = jnp.asarray(np.random.RandomState(0).normal(0, 1, (4, 6))
                    .astype(np.float32))
    assert Highway(6, seed=0)(x).shape == (4, 6)
    assert Maxout(6, 3, 2, seed=1)(x).shape == (4, 3)
    sc = Scale(axis=1, W_shape=(6,), bias_term=True)
    np.testing.assert_allclose(np.asarray(sc(x)), np.asarray(x), rtol=1e-6)

    # L.Classifier alias resolves to the models implementation
    clf = L.Classifier(L.Linear(6, 3, seed=2))
    loss = clf(x, jnp.zeros(4, jnp.int32))
    assert np.isfinite(float(loss))

    y = F.select_item(x, jnp.asarray([0, 1, 2, 3]))
    np.testing.assert_allclose(np.asarray(y),
                               [x[i, i] for i in range(4)], rtol=1e-6)
    assert F.swish(x).shape == x.shape
    n = F.normalize(x)
    np.testing.assert_allclose(np.asarray(jnp.sum(n * n, axis=1)), 1.0,
                               rtol=1e-3)
    img = jnp.ones((2, 8, 4, 4))
    assert F.local_response_normalization(img).shape == img.shape


def test_function_long_tail_aliases():
    x = jnp.asarray(np.random.RandomState(0).normal(0, 1, (4, 6))
                    .astype(np.float32))
    assert F.erf(x).shape == x.shape
    assert F.relu6(x).max() <= 6
    assert F.crelu(x).shape == (4, 12)
    np.testing.assert_allclose(np.asarray(F.square(x)),
                               np.asarray(x) ** 2, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(F.logsumexp(x, axis=1)),
        np.log(np.exp(np.asarray(x)).sum(axis=1)), rtol=1e-5)
    y = F.scale(jnp.ones((2, 3, 4)), jnp.asarray([1.0, 2.0, 3.0]), axis=1)
    np.testing.assert_allclose(np.asarray(y[:, 1]), 2.0)
    b = F.bias(jnp.zeros((2, 3)), jnp.asarray([1.0, 2.0, 3.0]), axis=1)
    np.testing.assert_allclose(np.asarray(b[0]), [1, 2, 3])
    assert F.einsum("ij,jk->ik", x, x.T).shape == (4, 4)


def test_softmax_cross_entropy_class_weight():
    x = jnp.asarray(np.random.RandomState(0).normal(0, 1, (4, 3))
                    .astype(np.float32))
    t = jnp.asarray([0, 1, 2, 1], dtype=jnp.int32)
    w = jnp.asarray([1.0, 2.0, 0.5])
    plain = F.softmax_cross_entropy(x, t, reduce="no")
    weighted = F.softmax_cross_entropy(x, t, reduce="no", class_weight=w)
    np.testing.assert_allclose(np.asarray(weighted),
                               np.asarray(plain) * np.asarray(w)[[0, 1, 2, 1]],
                               rtol=1e-6)
