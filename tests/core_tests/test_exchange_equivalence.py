"""Golden equality of every gradient-exchange variant (ISSUE 5 + 6).

The exchange structure — per-leaf psums, one flat bucket, K size-bounded
buckets, reduce-scatter + shard update + all-gather, or the two-level
hierarchical (ici × dcn) composition of either — changes the SCHEDULE
of the DP step, never its math.  Golden rule (SURVEY §4): each
variant's trajectory must EQUAL the single-device run on the merged
batch; the allreduce packings must be BITWISE equal to each other
(pmean is elementwise), and the reduce-scatter / hierarchical updates
must match to f32 reduction-order noise (chained per-hop sums reorder
the additions).  Composition axes from the ISSUE grids: {donation,
double buffering, compressed dtype} × the exchanges; the hierarchical
legs run on a SIMULATED 2-host split (``inter_size=2`` → dcn 2 × ici
4) of the 8-device CPU mesh.

Compile budget: every run here is a small MLP step (~1 s CPU compile);
the grid is kept to ~a dozen compiles so the suite stays tier-1-cheap.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import chainermn_tpu as ct
from chainermn_tpu.core.optimizer import SGD, MomentumSGD
from chainermn_tpu.models import Classifier, MLP

STEPS = 3
#: tiny bound so even the toy MLP splits into several buckets
TINY_BUCKET_MB = 2000 / 2 ** 20

_BC = {"per_leaf": False, "flat": True, "bucketed": "bucketed",
       "hierarchical_bucketed": "bucketed",
       "striped_bucketed": "bucketed"}
#: exchange names that run on the two-level communicator (simulated
#: 2-host split); *_rs routes through the sharded-update step; the
#: striped names (ISSUE 11) run the multi-path exchange at ratio 0.5 —
#: both fabrics carry half of every bucket, the most adversarial split
#: for the equality grid
_HIER = ("hierarchical", "hierarchical_bucketed", "hierarchical_rs",
         "striped", "striped_bucketed", "striped_rs")
_STRIPED = ("striped", "striped_bucketed", "striped_rs")
STRIPE_RATIO = 0.5
_RS = ("reduce_scatter", "hierarchical_rs", "striped_rs")


def _data(seed=0, n=32, d=8, k=4):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.normal(0, 1, (n, d)).astype(np.float32)),
            jnp.asarray(rng.randint(0, k, n).astype(np.int32)))


def _model():
    return Classifier(MLP(n_units=16, n_out=4, seed=0))


def _run(exchange, double_buffering=False, donate=True, grad_dtype=None,
         steps=STEPS, opt_cls=MomentumSGD, **opt_kw):
    """Trajectory (losses, params) of one exchange variant.

    ``exchange``: per_leaf | flat | bucketed (communicator flavors of
    the allreduce) | reduce_scatter (the optimizer-level step variant)
    | hierarchical / hierarchical_bucketed / hierarchical_rs (the same
    structures on the two-level communicator, simulated 2-host split).
    """
    opt_kw = opt_kw or dict(lr=0.1, momentum=0.9)
    comm = ct.create_communicator(
        "hierarchical" if exchange in _HIER else "jax_ici",
        inter_size=2 if exchange in _HIER else None,
        batch_collectives=_BC.get(exchange, True),
        bucket_mb=TINY_BUCKET_MB if "bucketed" in exchange else None,
        stripe_ratio=STRIPE_RATIO if exchange in _STRIPED else None,
        allreduce_grad_dtype=grad_dtype)
    model = _model()
    comm.bcast_data(model)
    inner = opt_cls(**opt_kw)
    inner.donate_params = donate
    opt = ct.create_multi_node_optimizer(
        inner, comm, double_buffering=double_buffering,
        exchange="reduce_scatter" if exchange in _RS
        else "allreduce").setup(model)
    x, t = _data()
    losses = [float(opt.update(model, x, t)) for _ in range(steps)]
    return losses, [np.asarray(p.array) for p in model.params()], opt


def _golden(steps=STEPS, opt_cls=MomentumSGD, **opt_kw):
    """Single-device trajectory on the merged batch (the golden rule's
    reference point — no communicator at all)."""
    opt_kw = opt_kw or dict(lr=0.1, momentum=0.9)
    model = _model()
    opt = opt_cls(**opt_kw).setup(model)
    x, t = _data()
    losses = [float(opt.update(model, x, t)) for _ in range(steps)]
    return losses, [np.asarray(p.array) for p in model.params()]


@pytest.fixture(scope="module")
def golden():
    return _golden()


@pytest.mark.parametrize("exchange",
                         ["per_leaf", "flat", "bucketed",
                          "reduce_scatter", "hierarchical",
                          "hierarchical_bucketed", "hierarchical_rs",
                          "striped", "striped_bucketed", "striped_rs"])
def test_exchange_matches_single_device_golden(exchange, golden):
    """Acceptance bar: all exchange variants — including the two-level
    hierarchical AND multi-path striped ones on the simulated 2-host
    mesh — golden-equal to the single-device trajectory on the CPU
    mesh."""
    glosses, gparams = golden
    losses, params, _ = _run(exchange)
    np.testing.assert_allclose(losses, glosses, rtol=1e-5, atol=1e-7,
                               err_msg=f"{exchange} losses diverged")
    for a, g in zip(params, gparams):
        np.testing.assert_allclose(a, g, rtol=1e-5, atol=1e-6,
                                   err_msg=f"{exchange} params diverged")


def test_allreduce_packings_bitwise_equal():
    """per-leaf == flat == bucketed BITWISE: packing changes the
    schedule, not the math (pmean is elementwise)."""
    ref = _run("per_leaf")
    for exchange in ("flat", "bucketed"):
        losses, params, _ = _run(exchange)
        assert losses == ref[0], f"{exchange} losses differ bitwise"
        for a, b in zip(params, ref[1]):
            np.testing.assert_array_equal(a, b)


def test_double_buffering_grid_equal():
    """Double buffering × {flat, bucketed, reduce_scatter,
    hierarchical, hierarchical_rs}: the one-step-stale semantics are
    exchange-independent (first update applies zeros, update t applies
    grads of t-1) — including the reduce-scatter variants, whose stale
    buffer is the sharded chunk (on the hierarchical mesh: the
    1/(ici·dcn) chunk in the fast-hop-major layout)."""
    ref = _run("flat", double_buffering=True, steps=4)
    # stale application is observable: step 2's loss equals step 1's
    assert ref[0][0] == ref[0][1]
    for exchange in ("bucketed", "reduce_scatter", "hierarchical",
                     "hierarchical_rs", "striped", "striped_rs"):
        losses, params, _ = _run(exchange, double_buffering=True, steps=4)
        np.testing.assert_allclose(losses, ref[0], rtol=1e-5, atol=1e-7,
                                   err_msg=f"db×{exchange} diverged")
        for a, b in zip(params, ref[1]):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("exchange", ["reduce_scatter", "hierarchical",
                                      "striped", "striped_rs"])
def test_donation_off_matches_donation_on(exchange):
    """The donation axis of the grid, on the sharded-update and
    two-level steps: buffer aliasing must not change the trajectory."""
    on = _run(exchange, donate=True)
    off = _run(exchange, donate=False)
    np.testing.assert_allclose(on[0], off[0], rtol=1e-6, atol=1e-8)
    for a, b in zip(on[1], off[1]):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_compressed_dtype_composes():
    """bf16 gradient compression per bucket: bucketed and flat compress
    identically (bitwise — same cast, same elementwise mean), and the
    compressed reduce-scatter step stays finite and learns.  bf16 is
    NOT golden-exact vs f32 by design, so no golden assert here."""
    flat = _run("flat", grad_dtype="bfloat16")
    bucketed = _run("bucketed", grad_dtype="bfloat16")
    assert flat[0] == bucketed[0]
    for a, b in zip(flat[1], bucketed[1]):
        np.testing.assert_array_equal(a, b)
    rs_losses, _, _ = _run("reduce_scatter", grad_dtype="bfloat16",
                           steps=5)
    assert np.isfinite(rs_losses).all() and rs_losses[-1] < rs_losses[0]
    # hierarchical × bf16 (BOTH hops compressed): chained per-hop sums
    # reorder bf16 roundings, so equality to the flat bf16 leg is
    # approximate at bf16 precision — and the run must learn
    h_losses, _, _ = _run("hierarchical", grad_dtype="bfloat16", steps=5)
    np.testing.assert_allclose(h_losses[:3], flat[0], rtol=5e-3,
                               err_msg="hier×bf16 far from flat×bf16")
    assert np.isfinite(h_losses).all() and h_losses[-1] < h_losses[0]


def test_per_hop_dtype_stays_close_to_lossless():
    """allreduce_grad_dtype={'dcn': 'bfloat16'} (lossless ICI +
    compressed DCN — the knob that halves only the slow hop's bytes):
    trajectory stays within bf16 rounding of the f32 hierarchical run
    and learns."""
    f32 = _run("hierarchical", steps=5)
    dcn = _run("hierarchical", grad_dtype={"dcn": "bfloat16"}, steps=5)
    np.testing.assert_allclose(dcn[0], f32[0], rtol=5e-3,
                               err_msg="dcn-bf16 far from lossless")
    assert dcn[0][-1] < dcn[0][0]


def test_striped_bf16_composes():
    """Compressed-dtype axes × striping: a scalar bf16 dtype (both
    hops, both paths) stays within bf16 rounding of the flat bf16
    trajectory and learns; the per-hop {'dcn': bf16} variant (only the
    DCN-fabric crossings of BOTH paths compressed) stays within bf16
    rounding of the lossless striped run."""
    flat = _run("flat", grad_dtype="bfloat16", steps=5)
    s_bf16 = _run("striped", grad_dtype="bfloat16", steps=5)
    np.testing.assert_allclose(s_bf16[0], flat[0], rtol=5e-3,
                               err_msg="striped×bf16 far from flat×bf16")
    assert np.isfinite(s_bf16[0]).all() and s_bf16[0][-1] < s_bf16[0][0]
    f32 = _run("striped", steps=5)
    dcn = _run("striped", grad_dtype={"dcn": "bfloat16"}, steps=5)
    np.testing.assert_allclose(dcn[0], f32[0], rtol=5e-3,
                               err_msg="striped dcn-bf16 far from lossless")
    assert dcn[0][-1] < dcn[0][0]


def test_striped_dcn_only_stale_degenerates():
    """The DCN-slice-only double-buffering variant (ISSUE 11,
    ``double_buffering="dcn"``): per-path staleness interpolates
    between the fresh and fully-stale trajectories, pinned at the
    degenerate ratios — ratio 1 (everything on the DCN path) equals
    FULL double buffering bitwise-close, and the mid-ratio run is a
    genuine third trajectory that still learns."""
    def run_ratio(ratio, db, steps=4):
        comm = ct.create_communicator("hierarchical", inter_size=2,
                                      batch_collectives=True,
                                      stripe_ratio=ratio)
        model = _model()
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            MomentumSGD(lr=0.1, momentum=0.9), comm,
            double_buffering=db).setup(model)
        x, t = _data()
        return [float(opt.update(model, x, t)) for _ in range(steps)], opt

    full, _ = run_ratio(1.0, True)
    dcn_only, _ = run_ratio(1.0, "dcn")
    np.testing.assert_allclose(dcn_only, full, rtol=1e-6, atol=1e-7,
                               err_msg="ratio-1 dcn-stale != full stale")
    mid, opt = run_ratio(0.5, "dcn")
    fresh, _ = run_ratio(0.5, False)
    assert np.isfinite(mid).all() and mid[-1] < mid[0]
    # genuinely between the two: not the fresh trajectory, not the full
    # one-step-stale one (the ICI half is fresh, the DCN half stale)
    assert mid != fresh
    assert mid != full
    # footprint claim: the stale buffer is the DCN slices only
    assert opt._stale_grads.shape[0] == \
        opt.communicator.grad_dcn_stale_len_for(opt.target)


def test_striped_dcn_only_stale_resume_bit_exact(tmp_path):
    """The DCN-slice stale buffer is OBSERVABLE state like every other
    stale buffer: same-size serialize → restore → continue is
    bit-exact."""
    from chainermn_tpu.serializers import load_npz, save_npz
    path = str(tmp_path / "snap.npz")
    x, t = _data()

    _, _, opt = _run("striped", double_buffering="dcn", steps=2)
    assert opt._stale_grads is not None
    save_npz(path, opt)
    cont_ref = [float(opt.update(opt.target, x, t)) for _ in range(2)]

    _, _, fresh = _run("striped", double_buffering="dcn", steps=1)
    load_npz(path, fresh)
    cont = [float(fresh.update(fresh.target, x, t)) for _ in range(2)]
    np.testing.assert_allclose(cont, cont_ref, rtol=0, atol=0)


def test_double_buffered_striped_rs_resume_bit_exact(tmp_path):
    """The striped sharded update's stale PAIR (fast- and slow-hop-
    major chunks) round-trips through the flat-vector serialization
    bit-exactly, like the single-layout chunk does."""
    from chainermn_tpu.serializers import load_npz, save_npz
    path = str(tmp_path / "snap.npz")
    x, t = _data()

    _, _, opt = _run("striped_rs", double_buffering=True, steps=2)
    save_npz(path, opt)
    cont_ref = [float(opt.update(opt.target, x, t)) for _ in range(2)]

    _, _, fresh = _run("striped_rs", double_buffering=True, steps=1)
    load_npz(path, fresh)
    cont = [float(fresh.update(fresh.target, x, t)) for _ in range(2)]
    np.testing.assert_allclose(cont, cont_ref, rtol=0, atol=0)


def test_striped_rs_quantized_wire_rejected():
    """The slow-hop-major chain has no quantized psum_scatter shape:
    int8 × striped × reduce_scatter is a LOUD construction error, not
    a silently lossless run."""
    comm = ct.create_communicator("hierarchical", inter_size=2,
                                  stripe_ratio=0.5,
                                  allreduce_grad_dtype={"dcn": "int8"})
    with pytest.raises(ValueError, match="striped"):
        ct.create_multi_node_optimizer(
            MomentumSGD(lr=0.1), comm, exchange="reduce_scatter")


def test_hierarchical_rs_grad_not_populated():
    """The sharded-update contract holds on the two-level step too:
    the full mean gradient never materializes."""
    _, _, opt = _run("hierarchical_rs")
    assert all(p.grad is None for p in opt.target.params())


def test_striped_rs_grad_not_populated():
    """Same contract on the striped pair-layout step."""
    _, _, opt = _run("striped_rs")
    assert all(p.grad is None for p in opt.target.params())


def test_hierarchical_update_scan_continues_trajectory():
    """hierarchical × fused K-step dispatch: the scan continues the
    SAME trajectory as the golden run's steps 4-5 (both the allreduce
    and the sharded-update hierarchical steps drive the scan maker)."""
    glosses, _ = _golden(steps=5)
    for exchange in ("hierarchical", "hierarchical_rs", "striped",
                     "striped_rs"):
        losses, _, opt = _run(exchange, steps=3)
        x, t = _data()
        scan_losses = np.asarray(opt.update_scan(
            opt.target, jnp.stack([x, x]), jnp.stack([t, t])))
        np.testing.assert_allclose(list(losses) + list(scan_losses),
                                   glosses, rtol=1e-5, atol=1e-7,
                                   err_msg=f"{exchange} scan diverged")


def test_double_buffered_hierarchical_rs_resume_bit_exact(tmp_path):
    """Serialize → restore → continue is bit-exact for the
    hierarchical reduce-scatter double-buffering pair: the stale chunk
    (fast-hop-major layout) round-trips through the flat-vector
    serialization exactly like the one-axis layout does."""
    from chainermn_tpu.serializers import load_npz, save_npz
    path = str(tmp_path / "snap.npz")
    x, t = _data()

    losses_a, _, opt = _run("hierarchical_rs", double_buffering=True,
                            steps=2)
    save_npz(path, opt)
    cont_ref = [float(opt.update(opt.target, x, t)) for _ in range(2)]

    _, _, fresh = _run("hierarchical_rs", double_buffering=True, steps=1)
    load_npz(path, fresh)
    cont = [float(fresh.update(fresh.target, x, t)) for _ in range(2)]
    np.testing.assert_allclose(cont, cont_ref, rtol=0, atol=0)


def test_moe_two_stage_dispatch_golden_equal_flat():
    """ISSUE 12: the two-stage (ici → dcn) MoE token dispatch on the
    simulated 2×4 split is GOLDEN-EQUAL — bit for bit — to the flat
    single-axis dispatch: the two stages compose to the exact same
    permutation as the joint-axis all_to_all, so routing, capacity
    drops, expert compute, and combine weights all coincide.  Checked
    at the full dispatch+combine level (a real expert MLP), against
    BOTH flat references: the explicit ``two_stage=False`` escape on
    the same hierarchical communicator AND a genuinely flat one-axis
    communicator over the same devices."""
    from jax.sharding import PartitionSpec as P
    from chainermn_tpu.parallel import switch_moe

    hier = ct.create_communicator("hierarchical", inter_size=2)
    flat = ct.create_communicator("jax_ici", axis_name="moe_flat_ref")
    E = hier.size
    D, H, T = 8, 16, 8
    rng = np.random.RandomState(17)
    x = jnp.asarray(rng.normal(0, 1, (E * T, D)).astype(np.float32))
    router = jnp.asarray(rng.normal(0, 0.5, (D, E)).astype(np.float32))
    w_in = jnp.asarray(rng.normal(0, 0.3, (D, H)).astype(np.float32))
    w_out = jnp.asarray(rng.normal(0, 0.3, (H, D)).astype(np.float32))
    b_in = jnp.zeros((H,), jnp.float32)
    b_out = jnp.zeros((D,), jnp.float32)

    def run(comm, two_stage):
        def body(x, router, w_in, b_in, w_out, b_out):
            out, aux = switch_moe(comm, x, router, w_in, b_in, w_out,
                                  b_out, capacity_factor=1.0,
                                  two_stage=two_stage)
            return out, aux["dropped_frac"].reshape(1)
        axes = comm.axis_name
        return comm.run_spmd(
            body, x, router, w_in, b_in, w_out, b_out,
            in_specs=(P(axes), P(), P(), P(), P(), P()),
            out_specs=(P(axes), P(axes)))

    out_two, drop_two = run(hier, True)
    out_hflat, drop_hflat = run(hier, False)
    out_flat, drop_flat = run(flat, None)
    np.testing.assert_array_equal(np.asarray(out_two),
                                  np.asarray(out_hflat))
    np.testing.assert_array_equal(np.asarray(out_two),
                                  np.asarray(out_flat))
    np.testing.assert_array_equal(np.asarray(drop_two),
                                  np.asarray(drop_flat))


def test_reduce_scatter_grad_not_populated():
    """The documented sharded-update contract holds for the plain-DP
    reduce-scatter step too: the full mean gradient never materializes,
    so Parameter.grad stays None."""
    _, _, opt = _run("reduce_scatter")
    assert all(p.grad is None for p in opt.target.params())


def test_reduce_scatter_update_scan_continues_trajectory(golden):
    """exchange="reduce_scatter" × fused K-step dispatch: the scan
    continues the SAME trajectory as the golden run's steps 4-5."""
    glosses, _ = _golden(steps=5)
    losses, _, opt = _run("reduce_scatter", steps=3)
    x, t = _data()
    scan_losses = np.asarray(opt.update_scan(
        opt.target, jnp.stack([x, x]), jnp.stack([t, t])))
    np.testing.assert_allclose(list(losses) + list(scan_losses), glosses,
                               rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("exchange,db", [("hierarchical", False),
                                         ("hierarchical_rs", False),
                                         ("hierarchical_rs", True),
                                         ("striped", False),
                                         ("striped", "dcn")])
def test_quantized_residual_resume_bit_exact(tmp_path, exchange, db):
    """The error-feedback residual is OBSERVABLE state (ISSUE 8): a
    same-size serialize → restore → continue is bit-exact — the
    telescoping sum (applied + residual == true) survives the
    checkpoint — on the allreduce, sharded-update, and
    double-buffered×rs quantized paths."""
    from chainermn_tpu.serializers import load_npz, save_npz
    path = str(tmp_path / "snap.npz")
    x, t = _data()

    _, _, opt = _run(exchange, double_buffering=db,
                     grad_dtype={"dcn": "int8"}, steps=2)
    assert opt._residual is not None
    save_npz(path, opt)
    cont_ref = [float(opt.update(opt.target, x, t)) for _ in range(2)]

    _, _, fresh = _run(exchange, double_buffering=db,
                       grad_dtype={"dcn": "int8"}, steps=1)
    load_npz(path, fresh)
    assert fresh._residual is not None
    cont = [float(fresh.update(fresh.target, x, t)) for _ in range(2)]
    np.testing.assert_allclose(cont, cont_ref, rtol=0, atol=0)


def test_quantized_residual_pre_feature_snapshot_zero_seeds(tmp_path):
    """A snapshot saved WITHOUT error feedback (no ef_residual section)
    loads onto an EF run with fresh zero-seed semantics — no crash, no
    stale residual invented."""
    from chainermn_tpu.serializers import load_npz, save_npz
    path = str(tmp_path / "snap.npz")
    x, t = _data()
    _, _, plain = _run("hierarchical", steps=2)  # lossless: no residual
    save_npz(path, plain)
    _, _, ef = _run("hierarchical", grad_dtype={"dcn": "int8"}, steps=2)
    assert ef._residual is not None
    load_npz(path, ef)
    assert ef._residual is None  # zero-seeds on the next update
    assert np.isfinite(float(ef.update(ef.target, x, t)))


def _run_sized(exchange, n_devices, double_buffering=False,
               grad_dtype=None, steps=2):
    """Like :func:`_run` but over an explicit device-count world — the
    changed-communicator-size resume grid (ISSUE 10 satellite).  The
    hierarchical legs keep the forced dcn=2 split, so 8 devices = 2×4
    and 4 devices = 2×2: a genuinely different chunk partition."""
    comm = ct.create_communicator(
        "hierarchical" if exchange in _HIER else "jax_ici",
        devices=jax.devices()[:n_devices],
        inter_size=2 if exchange in _HIER else None,
        batch_collectives=_BC.get(exchange, True),
        allreduce_grad_dtype=grad_dtype)
    model = _model()
    comm.bcast_data(model)
    inner = MomentumSGD(lr=0.1, momentum=0.9)
    opt = ct.create_multi_node_optimizer(
        inner, comm, double_buffering=double_buffering,
        exchange="reduce_scatter"
        if exchange in ("reduce_scatter", "hierarchical_rs")
        else "allreduce").setup(model)
    x, t = _data()
    losses = [float(opt.update(model, x, t)) for _ in range(steps)]
    return losses, opt


def test_size_changed_resume_reseeds_ef_residual(tmp_path):
    """ISSUE 10 satellite: the re-seed-zeros contract for the
    error-feedback ``_residual`` was documented but only SAME-size
    resume was pinned.  Changed size: a snapshot from the 2×4 world
    loads into a 2×2 world — params carry over, the residual (per-
    DEVICE quantization error, meaningless under a new partition)
    re-seeds zeros, and training continues finite."""
    from chainermn_tpu.serializers import load_npz, save_npz
    path = str(tmp_path / "snap.npz")
    x, t = _data()

    _, opt8 = _run_sized("hierarchical", 8, grad_dtype={"dcn": "int8"})
    assert opt8._residual is not None
    save_npz(path, opt8)
    saved_params = [np.asarray(p.array) for p in opt8.target.params()]

    _, opt4 = _run_sized("hierarchical", 4, grad_dtype={"dcn": "int8"})
    assert opt4._residual is not None
    load_npz(path, opt4)
    # params resumed from the snapshot bit-exact (size-independent)...
    for a, b in zip(opt4.target.params(), saved_params):
        np.testing.assert_array_equal(np.asarray(a.array), b)
    # ...the residual re-seeded (zero on the next update), explicitly
    # EXCLUDED from the bit-exact contract
    assert opt4._residual is None
    assert np.isfinite(float(opt4.update(opt4.target, x, t)))


def test_size_changed_resume_reseeds_sharded_ef_residual(tmp_path):
    """Same pin for the sharded-update (hierarchical_rs) residual: its
    length follows the flat chunk layout, so a changed world size can
    never reuse it — zero-seed, while the flat opt-state re-pads to
    the new multiple (the PR 5 brick)."""
    from chainermn_tpu.serializers import load_npz, save_npz
    path = str(tmp_path / "snap.npz")
    x, t = _data()

    _, opt8 = _run_sized("hierarchical_rs", 8,
                         grad_dtype={"dcn": "int8"})
    assert opt8._residual is not None
    save_npz(path, opt8)

    _, opt4 = _run_sized("hierarchical_rs", 4,
                         grad_dtype={"dcn": "int8"})
    load_npz(path, opt4)
    assert opt4._residual is None  # re-seeded
    _, n, n_pad = opt4._zero_layout
    assert n_pad % 4 == 0
    # the flat opt-state slices to the true length and re-pads to the
    # NEW world's multiple — the compiled step runs on it directly
    assert np.isfinite(float(opt4.update(opt4.target, x, t)))


def test_size_changed_resume_repads_stale_chunk(tmp_path):
    """The double-buffer stale CHUNK has the complementary contract: it
    is GLOBAL content (the flat one-step-stale mean gradient), so a
    size-changed resume slices/re-pads it instead of zero-seeding —
    the first resumed update still applies the saved step's gradient."""
    from chainermn_tpu.serializers import load_npz, save_npz
    path = str(tmp_path / "snap.npz")
    x, t = _data()

    _, opt8 = _run_sized("reduce_scatter", 8, double_buffering=True)
    assert opt8._stale_grads is not None
    saved = np.asarray(opt8._stale_grads)
    save_npz(path, opt8)

    _, opt4 = _run_sized("reduce_scatter", 4, double_buffering=True)
    load_npz(path, opt4)
    assert opt4._stale_grads is not None
    _, n, n_pad4 = opt4._zero_layout
    restored = np.asarray(opt4._stale_grads)
    assert restored.shape[0] == n_pad4
    np.testing.assert_array_equal(restored[:n], saved[:n])
    assert np.isfinite(float(opt4.update(opt4.target, x, t)))


def test_double_buffered_reduce_scatter_resume_bit_exact(tmp_path):
    """Serialize → restore → continue must be bit-exact for the
    reduce-scatter double-buffering pair: the stale CHUNK is observable
    state (without it a resumed run would apply zeros on its first
    update)."""
    from chainermn_tpu.serializers import load_npz, save_npz
    path = str(tmp_path / "snap.npz")
    x, t = _data()

    losses_a, _, opt = _run("reduce_scatter", double_buffering=True,
                            steps=2)
    save_npz(path, opt)
    cont_ref = [float(opt.update(opt.target, x, t)) for _ in range(2)]

    _, _, fresh = _run("reduce_scatter", double_buffering=True, steps=1)
    load_npz(path, fresh)
    cont = [float(fresh.update(fresh.target, x, t)) for _ in range(2)]
    np.testing.assert_allclose(cont, cont_ref, rtol=0, atol=0)
