"""End-to-end buffer donation: safe-by-default contract.

ISSUE 3 tentpole part 2 — ``donate_params`` is ON by default across all
four updater paths (plain, multi-node, ``update_scan``, ZeRO,
double-buffering incl. the stale-grad buffer).  This suite proves:

* donated and undonated runs produce BIT-EXACT trajectories,
* ``memory_analysis()`` shows params + opt-state aliased into outputs,
* the Link pytree bridge rebinds donated arrays, so code that goes
  through ``Parameter`` objects never touches a deleted buffer
  (``copyparams`` copies by value for the same reason),
* a failed donated step raises the containment error instead of leaving
  the Link silently holding dead arrays.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu import F, L
from chainermn_tpu.core.optimizer import (MomentumSGD, SGD,
                                          raise_if_donated_state_lost)

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici")


class Net(ct.Chain):
    """Small conv+BN+fc net: params, persistent BN state, and a maxpool
    so the donation suite rides the traffic-lean kernels too."""

    def __init__(self):
        super().__init__()
        with self.init_scope():
            self.conv = L.Convolution2D(3, 4, 3, pad=1, seed=5)
            self.bn = L.BatchNormalization(4)
            self.fc = L.Linear(4, 2, seed=6)

    def forward(self, x, t):
        h = F.relu(self.bn(self.conv(x)))
        h = F.max_pooling_2d(h, 2, 2, 0, cover_all=False)
        h = F.global_average_pooling_2d(h)
        return F.softmax_cross_entropy(self.fc(h), t)


def _batch(global_bs=None):
    rng = np.random.RandomState(0)
    bs = global_bs or 2 * COMM.size
    x = jnp.asarray(rng.normal(0, 1, (bs, 3, 8, 8)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, bs).astype(np.int32))
    return x, t


def _run(donate, make_opt, n_steps=3, scan=False):
    model = Net()
    inner = MomentumSGD(lr=0.1, momentum=0.9)
    inner.donate_params = donate
    inner.seed = 13  # identical per-step rng stream on both sides
    opt = make_opt(inner, model)
    x, t = _batch()
    if scan:
        xs = jnp.broadcast_to(x, (n_steps,) + x.shape)
        ts = jnp.broadcast_to(t, (n_steps,) + t.shape)
        opt.update_scan(model, xs, ts)
    else:
        for _ in range(n_steps):
            opt.update(model, x, t)
    return model, opt


def _assert_trees_bitexact(m1, m2):
    p1 = dict(m1.namedparams())
    p2 = dict(m2.namedparams())
    assert p1.keys() == p2.keys()
    for path in p1:
        np.testing.assert_array_equal(np.asarray(p1[path].array),
                                      np.asarray(p2[path].array),
                                      err_msg=path)
    np.testing.assert_array_equal(np.asarray(m1.bn.avg_mean),
                                  np.asarray(m2.bn.avg_mean))


def _param_opt_bytes(opt):
    params = sum(np.asarray(p.array).nbytes
                 for p in opt.target.params())
    opt_state = sum(np.asarray(l).nbytes
                    for l in jax.tree.leaves(opt._opt_state)
                    if hasattr(l, "dtype"))
    return params + opt_state


PATHS = {
    "plain": lambda inner, model: inner.setup(model),
    "multi_node": lambda inner, model:
        ct.create_multi_node_optimizer(inner, COMM).setup(model),
    "zero": lambda inner, model:
        ct.create_multi_node_optimizer(inner, COMM,
                                       zero_sharding=True).setup(model),
    "double_buffering": lambda inner, model:
        ct.create_multi_node_optimizer(inner, COMM,
                                       double_buffering=True).setup(model),
}


@pytest.mark.parametrize("path", sorted(PATHS))
def test_donated_trajectory_bitexact(path):
    m_d, _ = _run(True, PATHS[path])
    m_u, _ = _run(False, PATHS[path])
    _assert_trees_bitexact(m_d, m_u)


def test_update_scan_donated_trajectory_equivalent():
    """The K-step fused dispatch: donation must not change the math.

    Unlike the per-dispatch paths (bit-exact above), the donated scan
    program is NOT bit-identical on the CPU backend: input-output
    aliasing lets XLA schedule the loop-carry fusions differently, and
    the reassociated rounding shows up at ~4e-7 relative (measured,
    deterministic run-to-run).  Pinned here at a few-ulp tolerance so a
    real math divergence still fails loudly."""
    m_d, _ = _run(True, PATHS["multi_node"], scan=True)
    m_u, _ = _run(False, PATHS["multi_node"], scan=True)
    p_d = dict(m_d.namedparams())
    p_u = dict(m_u.namedparams())
    for path in p_d:
        np.testing.assert_allclose(np.asarray(p_d[path].array),
                                   np.asarray(p_u[path].array),
                                   rtol=5e-6, atol=1e-7, err_msg=path)


@pytest.mark.parametrize("path", sorted(PATHS))
def test_memory_analysis_confirms_aliasing(path):
    _, opt = _run(True, PATHS[path], n_steps=1)
    ma = opt.compiled_step_memory_analysis()
    if ma is None:
        pytest.skip("backend implements no memory_analysis")
    expected = _param_opt_bytes(opt) if path != "zero" else 0
    assert ma.alias_size_in_bytes >= max(expected, 1), \
        f"{path}: alias={ma.alias_size_in_bytes} expected>={expected}"
    _, opt_u = _run(False, PATHS[path], n_steps=1)
    ma_u = opt_u.compiled_step_memory_analysis()
    # undonated: only opt-state may alias — strictly less than donated
    assert ma_u.alias_size_in_bytes < ma.alias_size_in_bytes


def test_update_scan_memory_analysis_confirms_aliasing():
    _, opt = _run(True, PATHS["multi_node"], scan=True)
    ma = opt.compiled_step_memory_analysis()
    if ma is None:
        pytest.skip("backend implements no memory_analysis")
    assert ma.alias_size_in_bytes >= _param_opt_bytes(opt)


def test_double_buffering_donates_stale_grad_buffer():
    _, opt = _run(True, PATHS["double_buffering"], n_steps=2)
    ma = opt.compiled_step_memory_analysis()
    if ma is None:
        pytest.skip("backend implements no memory_analysis")
    params = sum(np.asarray(p.array).nbytes for p in opt.target.params())
    # params + opt-state + the params-sized stale-grad buffer
    assert ma.alias_size_in_bytes >= _param_opt_bytes(opt) + params


def test_rebind_safety_through_parameter_objects():
    model = Net()
    opt = MomentumSGD(lr=0.1).setup(model)  # donation on by default
    p = model.conv.W  # user code holds the PARAMETER (the bridge)
    raw = p.array     # ...and a raw array alias (the one unsafe thing)
    x, t = _batch(4)
    opt.update(model, x, t)
    # the bridge rebinds: Parameter access is alive and fresh
    assert np.all(np.isfinite(np.asarray(p.array)))
    assert p.array is not raw
    if raw.is_deleted():  # donation actually took (backend-dependent)
        with pytest.raises(RuntimeError):
            np.asarray(raw)
    # gradients were rebound through the bridge too
    assert p.grad is not None and np.all(np.isfinite(np.asarray(p.grad)))


def test_copyparams_copies_values_not_aliases():
    src = Net()
    dst = Net()
    dst.copyparams(src)
    np.testing.assert_array_equal(np.asarray(dst.conv.W.array),
                                  np.asarray(src.conv.W.array))
    assert dst.conv.W.array is not src.conv.W.array
    # a donated update on src must leave dst fully usable
    opt = MomentumSGD(lr=0.1).setup(src)
    x, t = _batch(4)
    opt.update(src, x, t)
    assert np.all(np.isfinite(np.asarray(dst.conv.W.array)))


def test_failed_donated_step_raises_containment_error():
    def deleted_array():
        arr = jnp.ones(2)
        jax.jit(lambda a: a * 2, donate_argnums=0)(arr)
        return arr  # consumed by donation → genuinely deleted

    class FakeParam:
        def __init__(self, array):
            self.array = array

    class FakeTarget:
        def __init__(self, params):
            self._params = params

        def params(self):
            return iter(self._params)

    class FakeOpt:
        def __init__(self, target, donate):
            self.target = target
            self.donate_params = donate

    lost = FakeOpt(FakeTarget([FakeParam(deleted_array())]), True)
    with pytest.raises(RuntimeError, match="rebuild or reload"):
        raise_if_donated_state_lost(ValueError("boom"), lost)
    # alive buffers, or donation off: no containment raise — the
    # ORIGINAL error propagates from the caller's bare `raise`
    raise_if_donated_state_lost(
        ValueError("boom"), FakeOpt(FakeTarget([FakeParam(jnp.ones(2))]),
                                    True))
    raise_if_donated_state_lost(
        ValueError("boom"),
        FakeOpt(FakeTarget([FakeParam(deleted_array())]), False))
