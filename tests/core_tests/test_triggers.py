"""Triggers (reference: ``chainer.training.triggers``): firing semantics
and — the part resumes depend on — serialization of trigger STATE
(IntervalTrigger position, OnceTrigger flag, best-value memory).
"""

import numpy as np

from chainermn_tpu.serializers.npz import (DictionarySerializer,
                                           NpzDeserializer)
from chainermn_tpu.training.triggers import (IntervalTrigger,
                                             MaxValueTrigger,
                                             MinValueTrigger, OnceTrigger)


class _FakeUpdater:
    def __init__(self):
        self.iteration = 0
        self.epoch_detail = 0.0


class _FakeTrainer:
    def __init__(self):
        self.updater = _FakeUpdater()
        self.observation = {}

    def step(self, obs=None):
        self.updater.iteration += 1
        self.updater.epoch_detail = self.updater.iteration / 4.0
        self.observation = obs or {}


def _roundtrip(trigger, build):
    s = DictionarySerializer()
    trigger.serialize(s)
    fresh = build()
    fresh.serialize(NpzDeserializer(s.target))
    return fresh


def test_interval_trigger_fires_on_period():
    tr = _FakeTrainer()
    trig = IntervalTrigger(3, "iteration")
    fires = []
    for _ in range(9):
        tr.step()
        fires.append(trig(tr))
    assert fires == [False, False, True] * 3


def test_once_trigger_fires_once_and_not_after_resume():
    tr = _FakeTrainer()
    trig = OnceTrigger()
    assert trig(tr) is True
    assert trig(tr) is False
    resumed = _roundtrip(trig, OnceTrigger)
    assert resumed(tr) is False  # already fired before the snapshot


def test_once_trigger_call_on_resume():
    trig = OnceTrigger(call_on_resume=True)
    tr = _FakeTrainer()
    assert trig(tr) is True
    assert trig(tr) is False
    resumed = _roundtrip(trig, lambda: OnceTrigger(call_on_resume=True))
    assert resumed(tr) is True  # explicit opt-in re-fires after resume


def test_max_value_trigger_fires_on_improvement():
    tr = _FakeTrainer()
    trig = MaxValueTrigger("acc", trigger=(1, "iteration"))
    fires = []
    for v in (0.1, 0.5, 0.3, 0.7):
        tr.step({"acc": v})
        fires.append(trig(tr))
    assert fires == [True, True, False, True]


def test_best_value_trigger_resume_keeps_best():
    """A resumed MaxValueTrigger must remember its best: forgetting it
    would re-fire on a WORSE value (e.g. overwrite a 'best' snapshot
    with a worse model)."""
    tr = _FakeTrainer()
    trig = MaxValueTrigger("acc", trigger=(1, "iteration"))
    tr.step({"acc": 0.9})
    assert trig(tr) is True  # best = 0.9

    resumed = _roundtrip(
        trig, lambda: MaxValueTrigger("acc", trigger=(1, "iteration")))
    tr.step({"acc": 0.5})
    assert resumed(tr) is False  # worse than the remembered best
    tr.step({"acc": 0.95})
    assert resumed(tr) is True


def test_min_value_trigger_resume_keeps_best():
    tr = _FakeTrainer()
    trig = MinValueTrigger("loss", trigger=(1, "iteration"))
    tr.step({"loss": 0.2})
    assert trig(tr) is True
    resumed = _roundtrip(
        trig, lambda: MinValueTrigger("loss", trigger=(1, "iteration")))
    tr.step({"loss": 0.4})
    assert resumed(tr) is False
    tr.step({"loss": 0.1})
    assert resumed(tr) is True


def test_best_value_trigger_resume_preserves_nan_latch():
    """A NaN best (diverged metric window) is a LATCHED state — NaN
    comparisons are always False, so the trigger never fires again.
    Resume must preserve that, not re-arm the trigger (which would
    overwrite a 'best' snapshot unconditionally)."""
    tr = _FakeTrainer()
    trig = MaxValueTrigger("acc", trigger=(1, "iteration"))
    tr.step({"acc": float("nan")})
    assert trig(tr) is True  # first window always fires; best = NaN
    tr.step({"acc": 0.9})
    assert trig(tr) is False  # latched: NaN comparisons are False

    resumed = _roundtrip(
        trig, lambda: MaxValueTrigger("acc", trigger=(1, "iteration")))
    tr.step({"acc": 0.9})
    assert resumed(tr) is False  # still latched after resume


def test_best_value_trigger_nonstrict_load_preserves_live_state():
    """A non-strict load from a snapshot LACKING the trigger keys (any
    pre-upgrade snapshot) must leave the live trigger untouched — not
    wipe its remembered best to 0.0 and clear the summary window."""
    tr = _FakeTrainer()
    trig = MaxValueTrigger("acc", trigger=(2, "iteration"))
    tr.step({"acc": 0.9})
    assert trig(tr) is False  # summary open: [0.9]
    tr.step({"acc": 0.9})
    assert trig(tr) is True   # best = 0.9
    tr.step({"acc": 0.7})
    assert trig(tr) is False  # summary open: [0.7]

    trig.serialize(NpzDeserializer({}, strict=False))
    assert trig._best == 0.9
    assert trig._summary == [0.7]
    tr.step({"acc": 0.8})
    assert trig(tr) is False  # mean(0.7, 0.8) = 0.75 < 0.9


def test_best_value_trigger_resume_keeps_summary_window():
    """Mid-window observations (accumulated but not yet compared) must
    survive a snapshot: the epoch-trigger mean after resume equals the
    uninterrupted one."""
    def build():
        return MaxValueTrigger("acc", trigger=(2, "iteration"))

    tr = _FakeTrainer()
    trig = build()
    tr.step({"acc": 1.0})
    assert trig(tr) is False  # window open: summary holds [1.0]
    resumed = _roundtrip(trig, build)
    tr.step({"acc": 0.0})
    # mean over the FULL window [1.0, 0.0] = 0.5; a dropped summary
    # would compare mean([0.0]) = 0.0
    assert resumed(tr) is True
    assert resumed._best == 0.5
