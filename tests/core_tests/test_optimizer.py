"""Optimizer tests: update rules vs analytic math, hooks, serialization."""

import jax.numpy as jnp
import numpy as np
import pytest

import chainermn_tpu as ct
from chainermn_tpu import F, L
from chainermn_tpu.core.optimizer import (SGD, MomentumSGD, Adam, RMSprop,
                                          AdaGrad, WeightDecay,
                                          GradientClipping)


class _Quad(ct.Chain):
    """loss = 0.5 * ||w - target||^2 — gradient is (w - target)."""

    def __init__(self, dim=4, target=3.0):
        super().__init__()
        self.target_value = target
        with self.init_scope():
            self.w = ct.Parameter(jnp.zeros(dim))

    def forward(self):
        return 0.5 * jnp.sum((self.w.array - self.target_value) ** 2)


def test_sgd_matches_analytic_step():
    m = _Quad()
    opt = SGD(lr=0.1).setup(m)
    opt.update(m)
    # w1 = w0 - lr * (w0 - 3) = 0 - 0.1*(-3) = 0.3
    np.testing.assert_allclose(np.asarray(m.w.array), 0.3, rtol=1e-6)
    opt.update(m)
    np.testing.assert_allclose(np.asarray(m.w.array), 0.3 + 0.1 * 2.7, rtol=1e-6)


def test_momentum_sgd_matches_analytic():
    m = _Quad(dim=1)
    opt = MomentumSGD(lr=0.1, momentum=0.9).setup(m)
    opt.update(m)
    np.testing.assert_allclose(np.asarray(m.w.array), 0.3, rtol=1e-6)
    opt.update(m)
    # v2 = 0.9*(-3) + (w1-3) = -2.7 - 2.7 = -5.4 ; w2 = w1 - 0.1*(-5.4)... wait
    # optax.trace: t2 = g2 + m*t1 = -2.7... chainer: v = m*v - lr*g; equivalent.
    # w2 = 0.3 + 0.1 * (2.7 + 0.9*3) = 0.3 + 0.54
    np.testing.assert_allclose(np.asarray(m.w.array), 0.84, rtol=1e-5)


def test_sgd_converges_on_quadratic():
    m = _Quad()
    opt = SGD(lr=0.5).setup(m)
    for _ in range(50):
        opt.update(m)
    np.testing.assert_allclose(np.asarray(m.w.array), 3.0, atol=1e-4)


@pytest.mark.parametrize("opt_cls,lr,steps", [
    (Adam, 0.1, 300), (RMSprop, 0.1, 300), (AdaGrad, 0.5, 500)])
def test_adaptive_optimizers_converge(opt_cls, lr, steps):
    m = _Quad()
    opt = opt_cls().setup(m)
    opt.lr = lr
    for _ in range(steps):
        opt.update(m)
    np.testing.assert_allclose(np.asarray(m.w.array), 3.0, atol=0.05)


def test_weight_decay_hook():
    m = _Quad(dim=1, target=0.0)
    m.w.array = jnp.ones(1)
    opt = SGD(lr=0.1).setup(m)
    opt.add_hook(WeightDecay(0.5))
    opt.update(m)
    # grad = (w - 0) + 0.5*w = 1.5 ; w1 = 1 - 0.15 = 0.85
    np.testing.assert_allclose(np.asarray(m.w.array), 0.85, rtol=1e-6)


def test_gradient_clipping_hook():
    m = _Quad(dim=1, target=101.0)
    opt = SGD(lr=1.0).setup(m)
    opt.add_hook(GradientClipping(1.0))
    opt.update(m)
    # raw grad = -101, clipped to norm 1 → step = +1
    np.testing.assert_allclose(np.asarray(m.w.array), 1.0, rtol=1e-5)


def test_lr_mutation_without_recompile():
    m = _Quad(dim=1)
    opt = SGD(lr=0.1).setup(m)
    opt.update(m)
    w1 = float(np.asarray(m.w.array)[0])
    opt.lr = 0.0
    opt.update(m)
    np.testing.assert_allclose(np.asarray(m.w.array), w1)
    assert len(opt._step_cache) == 1  # same compiled step reused


def test_update_from_stored_grads():
    m = _Quad(dim=2)
    opt = SGD(lr=0.1).setup(m)
    m.w.grad = jnp.asarray([1.0, -1.0])
    opt.update()
    np.testing.assert_allclose(np.asarray(m.w.array), [-0.1, 0.1], rtol=1e-6)


def test_optimizer_serialize_roundtrip(tmp_path):
    from chainermn_tpu.serializers import save_npz, load_npz
    m = _Quad()
    opt = MomentumSGD(lr=0.1).setup(m)
    for _ in range(3):
        opt.update(m)
    path = str(tmp_path / "opt.npz")
    save_npz(path, opt)
    m2 = _Quad()
    opt2 = MomentumSGD(lr=0.1).setup(m2)
    opt2._ensure_opt_state({p: a for p, a in
                            [(k, v) for k, v in
                             [(n, q.array) for n, q in m2.namedparams()]]})
    load_npz(path, opt2)
    assert opt2.t == 3
    # momentum buffer restored: next update matches.  copyparams (copy
    # by VALUE) rather than aliasing m's array object: updates donate
    # their param buffers by default, so a raw alias shared across
    # models would be consumed by m's next update (the donation
    # contract — see core/optimizer.py donate_params).
    m2.copyparams(m)
    opt.update(m)
    opt2.update(m2)
    np.testing.assert_allclose(np.asarray(m2.w.array), np.asarray(m.w.array),
                               rtol=1e-6)


def test_dropout_fresh_mask_every_compiled_step():
    """Per-step traced rng: dropout masks differ across steps with lr=0
    (params frozen → loss variation can only come from the mask)."""
    import chainermn_tpu as ct
    from chainermn_tpu import F, L

    class DropNet(ct.Chain):
        def __init__(self):
            super().__init__()
            with self.init_scope():
                self.l = L.Linear(16, 4, seed=0)

        def forward(self, x, t):
            h = F.dropout(x, 0.5)
            return F.softmax_cross_entropy(self.l(h), t)

    net = DropNet()
    opt = SGD(lr=0.0).setup(net)
    opt.seed = 123
    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    x = jnp.asarray(rng.normal(0, 1, (32, 16)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 4, 32).astype(np.int32))
    losses = [float(opt.update(net, x, t)) for _ in range(4)]
    assert len(set(losses)) > 1, "dropout mask frozen across steps"
    # reproducible with the same seed
    net2 = DropNet()
    opt2 = SGD(lr=0.0).setup(net2)
    opt2.seed = 123
    losses2 = [float(opt2.update(net2, x, t)) for _ in range(4)]
    np.testing.assert_allclose(losses, losses2, rtol=1e-6)


def test_bn_counter_does_not_double_compile():
    """Persistent python scalars must not create a second jit cache entry
    (python-int leaf on step 1 vs written-back Array on step 2)."""
    import chainermn_tpu as ct
    from chainermn_tpu import F, L

    class Net(ct.Chain):
        def __init__(self):
            super().__init__()
            with self.init_scope():
                self.bn = L.BatchNormalization(4)
                self.l = L.Linear(4, 2, seed=0)

        def forward(self, x, t):
            return F.softmax_cross_entropy(self.l(self.bn(x)), t)

    import jax.numpy as jnp
    net = Net()
    opt = SGD(lr=0.1).setup(net)
    x = jnp.ones((8, 4))
    t = jnp.zeros((8,), jnp.int32)
    for _ in range(3):
        opt.update(net, x, t)
    (step,) = list(opt._step_cache.values())
    assert step._cache_size() == 1, \
        f"step compiled {step._cache_size()} times"


def test_adam_weight_decay_not_scaled_by_alpha():
    """Reference Adam adds ``eta * weight_decay_rate * param`` to the update
    UN-scaled by alpha (`chainer/optimizers/adam.py · AdamRule.update_core`);
    regression for the decay landing inside the -lr scaling (~1/alpha weaker)."""
    m = _Quad(dim=1, target=0.0)
    m.w.array = jnp.ones(1)
    opt = Adam(alpha=0.001, weight_decay_rate=0.1).setup(m)
    opt.update(m)
    # grad = 1; first-step adam term ~= 1 (bias-corrected m/sqrt(v)), so
    # w1 ~= 1 - alpha*1 - wd*1 = 0.899.  The buggy path gave ~0.999.
    w1 = float(np.asarray(m.w.array)[0])
    np.testing.assert_allclose(w1, 1.0 - 0.001 - 0.1, atol=2e-3)


def test_optimizer_serialize_before_first_update(tmp_path):
    """Snapshot taken before any update() (no opt_state yet) must load
    cleanly under the strict deserializer (ADVICE r1: opt_state_len
    KeyError)."""
    from chainermn_tpu.serializers import save_npz, load_npz
    m = _Quad()
    opt = MomentumSGD(lr=0.1).setup(m)
    path = str(tmp_path / "opt.npz")
    save_npz(path, opt)
    m2 = _Quad()
    opt2 = MomentumSGD(lr=0.1).setup(m2)
    load_npz(path, opt2)  # must not raise KeyError
    assert opt2.t == 0


def test_deserialize_flat_tree_warns_on_leaf_count_mismatch():
    """ADVICE r4: resuming a flat-tree snapshot saved under a different
    optimizer/hook configuration must warn, not silently mix template
    and saved leaves."""
    import warnings

    from chainermn_tpu.core.optimizer import (deserialize_flat_tree,
                                              serialize_flat_tree)
    from chainermn_tpu.serializers.npz import (DictionarySerializer,
                                               NpzDeserializer)
    s = DictionarySerializer()
    serialize_flat_tree(s, [np.ones(2), np.zeros(3)], "n", "leaf")
    template = [np.full(2, 7.0), np.full(3, 7.0), np.full(4, 7.0)]
    with pytest.warns(UserWarning, match="leaves"):
        out = deserialize_flat_tree(NpzDeserializer(s.target), template,
                                    "n", "leaf")
    np.testing.assert_array_equal(np.asarray(out[0]), np.ones(2))
    np.testing.assert_array_equal(np.asarray(out[2]), np.full(4, 7.0))
    # the exact-match path stays silent
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = deserialize_flat_tree(
            NpzDeserializer(s.target), [np.zeros(2), np.ones(3)],
            "n", "leaf")
    np.testing.assert_array_equal(np.asarray(out[1]), np.zeros(3))


def test_deserialize_flat_tree_warns_on_missing_leaf():
    from chainermn_tpu.core.optimizer import (deserialize_flat_tree,
                                              serialize_flat_tree)
    from chainermn_tpu.serializers.npz import (DictionarySerializer,
                                               NpzDeserializer)
    s = DictionarySerializer()
    serialize_flat_tree(s, [np.ones(2), np.zeros(3)], "n", "leaf")
    del s.target["leaf1"]
    with pytest.warns(UserWarning, match="missing"):
        out = deserialize_flat_tree(
            NpzDeserializer(s.target), [np.zeros(2), np.full(3, 7.0)],
            "n", "leaf")
    np.testing.assert_array_equal(np.asarray(out[1]), np.full(3, 7.0))


def test_donate_params_same_results():
    """donate_params=True must not change the math (in-place is an XLA
    aliasing hint; CPU ignores it, TPU updates params in place)."""
    m1, m2 = _Quad(), _Quad()
    o1 = SGD(lr=0.1).setup(m1)
    o2 = SGD(lr=0.1).setup(m2)
    o2.donate_params = True
    for _ in range(3):
        o1.update(m1)
        o2.update(m2)
    np.testing.assert_allclose(np.asarray(m1.w.array),
                               np.asarray(m2.w.array), rtol=1e-7)


def test_donate_params_multi_node_same_results():
    comm = ct.create_communicator("jax_ici")
    m1, m2 = _Quad(), _Quad()
    o1 = ct.create_multi_node_optimizer(SGD(lr=0.1), comm).setup(m1)
    inner = SGD(lr=0.1)
    inner.donate_params = True
    o2 = ct.create_multi_node_optimizer(inner, comm).setup(m2)

    import jax.numpy as jnp

    def lossfun1(x):
        return 0.5 * jnp.sum((m1.w.array - 3.0) ** 2) + 0.0 * jnp.sum(x)

    def lossfun2(x):
        return 0.5 * jnp.sum((m2.w.array - 3.0) ** 2) + 0.0 * jnp.sum(x)

    x = jnp.zeros((comm.size * 2, 1))
    for _ in range(3):
        o1.update(lossfun1, x)
        o2.update(lossfun2, x)
    np.testing.assert_allclose(np.asarray(m1.w.array),
                               np.asarray(m2.w.array), rtol=1e-7)
