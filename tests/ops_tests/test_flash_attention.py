"""Flash-attention kernel vs XLA reference (interpreter mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from chainermn_tpu.ops import flash_attention, xla_attention


def _data(B=2, H=2, T=128, D=32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (B, H, T, D))
                             .astype(np.float32))
    return mk(), mk(), mk()


def test_flash_matches_xla():
    q, k, v = _data()
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_causal_matches_xla():
    q, k, v = _data(seed=1)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_irregular_shapes_fall_back():
    q, k, v = _data(T=100, seed=2)  # not divisible by blocks
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
