"""Flash-attention kernel vs XLA reference (interpreter mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from chainermn_tpu.ops import flash_attention, xla_attention


def _data(B=2, H=2, T=128, D=32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (B, H, T, D))
                             .astype(np.float32))
    return mk(), mk(), mk()


def test_flash_matches_xla():
    q, k, v = _data()
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_causal_matches_xla():
    q, k, v = _data(seed=1)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_irregular_shapes_fall_back():
    q, k, v = _data(T=100, seed=2)  # not divisible by blocks
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_custom_vjp_gradients_match_xla():
    import jax
    from chainermn_tpu.ops.flash_attention import _flash_diff
    q, k, v = _data(T=64, seed=3)

    # interpret-mode flash forward inside the custom-vjp wrapper
    # (the ops package re-exports the function under the module's name,
    # so resolve the module via importlib)
    import importlib
    fa = importlib.import_module("chainermn_tpu.ops.flash_attention")
    orig = fa.flash_attention
    fa.flash_attention = lambda *a, **kw: orig(*a, interpret=True, **kw)
    try:
        def loss_flash(q):
            return jnp.sum(_flash_diff(q, k, v, True, None) ** 2)

        def loss_ref(q):
            return jnp.sum(xla_attention(q, k, v, causal=True) ** 2)

        g_flash = jax.grad(loss_flash)(q)
        g_ref = jax.grad(loss_ref)(q)
        np.testing.assert_allclose(np.asarray(g_flash), np.asarray(g_ref),
                                   rtol=2e-4, atol=2e-5)
    finally:
        fa.flash_attention = orig
