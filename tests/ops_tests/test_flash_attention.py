"""Flash-attention kernel vs XLA reference (interpreter mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chainermn_tpu.ops import flash_attention, xla_attention


def _data(B=2, H=2, T=128, D=32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (B, H, T, D))
                             .astype(np.float32))
    return mk(), mk(), mk()


def test_flash_matches_xla():
    q, k, v = _data()
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_causal_matches_xla():
    q, k, v = _data(seed=1)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_irregular_shapes_fall_back():
    q, k, v = _data(T=100, seed=2)  # not divisible by blocks
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_backward_kernels_match_xla_grads():
    """Pallas flash backward (dq/dk/dv kernels) vs XLA autodiff, causal
    and non-causal, all three gradients."""
    import jax
    from chainermn_tpu.ops.flash_attention import _flash_diff
    for causal in (False, True):
        q, k, v = _data(T=128, D=32, seed=3 + causal)

        def loss_flash(q, k, v):
            return jnp.sum(_flash_diff(q, k, v, causal, None, True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(xla_attention(q, k, v, causal=causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
                err_msg=f"d{name} causal={causal}")


def test_flash_fwd_lse_matches_softmax_normalizer():
    from chainermn_tpu.ops.flash_attention import flash_attention_fwd
    q, k, v = _data(T=64, D=16, seed=5)
    out, lse = flash_attention_fwd(q, k, v, causal=False, interpret=True)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    s = np.einsum("bhqd,bhkd->bhqk", np.asarray(q), np.asarray(k)) \
        / np.sqrt(q.shape[-1])
    lse_ref = np.log(np.exp(s - s.max(-1, keepdims=True)).sum(-1)) \
        + s.max(-1)
    np.testing.assert_allclose(np.asarray(lse), lse_ref, rtol=1e-4,
                               atol=1e-4)


def test_flash_vjp_irregular_shape_fallback():
    import jax
    from chainermn_tpu.ops.flash_attention import _flash_diff
    q, k, v = _data(T=100, seed=6)  # not block-divisible → XLA both ways
    g = jax.grad(lambda q: jnp.sum(_flash_diff(q, k, v, True, None,
                                               True) ** 2))(q)
    gr = jax.grad(lambda q: jnp.sum(
        xla_attention(q, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=2e-4, atol=2e-5)


def test_attention_with_lse_matches_reference():
    """(out, lse) primitive: both dispatch paths agree with the XLA
    reference; lse is the true softmax normalizer."""
    from chainermn_tpu.ops.flash_attention import (
        attention_with_lse, _blockwise_attention_lse_jnp, _flash_lse_diff,
        xla_attention)
    q, k, v = _data(B=1, H=2, T=128, D=32, seed=11)
    for causal in (False, True):
        ref = xla_attention(q, k, v, causal=causal)
        out_j, lse_j = _blockwise_attention_lse_jnp(q, k, v, causal,
                                                    1.0 / np.sqrt(32),
                                                    block_k=32)
        np.testing.assert_allclose(np.asarray(out_j), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
        out_f, lse_f = _flash_lse_diff(q, k, v, causal, 1.0 / np.sqrt(32),
                                       True)  # interpret mode
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(lse_f), np.asarray(lse_j),
                                   rtol=1e-4, atol=1e-5)


def test_flash_lse_cotangent_grads_match_jnp():
    """The g_lse -> delta - g_lse folding in the backward kernels: grads
    of a function of BOTH outputs (out, lse) must match the blockwise jnp
    path (ring attention's merge weights depend on lse)."""
    from chainermn_tpu.ops.flash_attention import (
        _blockwise_attention_lse_jnp, _flash_lse_diff)
    q, k, v = _data(B=1, H=2, T=128, D=32, seed=12)
    scale = 1.0 / np.sqrt(32)

    def loss_flash(q, k, v):
        out, lse = _flash_lse_diff(q, k, v, True, scale, True)
        return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))

    def loss_jnp(q, k, v):
        out, lse = _blockwise_attention_lse_jnp(q, k, v, True, scale,
                                                block_k=32)
        return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gj = jax.grad(loss_jnp, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gj):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_blockwise_jnp_irregular_length_stays_blockwise():
    """Tk not divisible by the block: padding + masking, not a full-width
    block (the full-width fallback would materialize [Tq, Tk])."""
    from chainermn_tpu.ops.flash_attention import (
        _blockwise_attention_lse_jnp, xla_attention)
    q, k, v = _data(B=1, H=2, T=64, D=16, seed=13)
    k, v = k[:, :, :56], v[:, :, :56]  # Tk=56, block 32 -> pad to 64
    out, _ = _blockwise_attention_lse_jnp(q, k, v, False, 0.25, block_k=32)
    ref = xla_attention(q, k, v, scale=0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    # and the jaxpr contains no [Tq, Tk_pad]-wide intermediate beyond block
    jaxpr = jax.make_jaxpr(
        lambda q, k, v: _blockwise_attention_lse_jnp(q, k, v, False, 0.25,
                                                     block_k=32))(q, k, v)
    shapes = []
    def walk(jx):
        for eqn in jx.eqns:
            for var in eqn.outvars:
                shapes.append(getattr(var.aval, "shape", ()))
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)
    walk(jaxpr.jaxpr)
    assert not any(len(s) >= 2 and s[-1] > 32 and s[-2] == 64
                   for s in shapes), shapes


def test_flash_bf16_matches_fp32_reference():
    """bf16 storage dtype: kernel keeps bf16 into the MXU dots with fp32
    accumulators/softmax — output must track the fp32 reference within
    bf16 rounding, and gradients must flow."""
    q, k, v = _data(T=128, D=32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ref = xla_attention(q, k, v, causal=True)
    out = flash_attention(qb, kb, vb, causal=True, block_q=64, block_k=64,
                          interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.02)

    from chainermn_tpu.ops.flash_attention import _flash_diff

    def loss(q, k, v):
        return _flash_diff(q, k, v, True, None, True).astype(
            jnp.float32).sum()

    def loss_ref(q, k, v):
        return xla_attention(q, k, v, causal=True).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(qb, kb, vb)
    rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g, dtype=np.float32),
                                   np.asarray(r), rtol=0.1, atol=0.05)


def test_adaptive_block_defaults(monkeypatch):
    """Round-5 on-chip sweep: tile defaults are shape-adaptive (largest
    candidate dividing T), env still pins, explicit args still win."""
    from chainermn_tpu.ops.flash_attention import _adaptive_block, \
        _flash_blocks

    monkeypatch.delenv("CHAINERMN_TPU_FLASH_BLOCK_Q", raising=False)
    monkeypatch.delenv("CHAINERMN_TPU_FLASH_BLOCK_K", raising=False)
    assert _adaptive_block(8192) == 1024
    assert _adaptive_block(1024) == 1024
    assert _adaptive_block(1536) == 512   # 1536 % 1024 != 0
    assert _adaptive_block(384) == 128
    assert _adaptive_block(64) == 128     # legacy clamp path (min(b, T))
    assert _adaptive_block(None) == 128   # no shape info: legacy default
    assert _flash_blocks(tq=2048, tk=8192) == (1024, 1024)
    assert _flash_blocks(256, None, tq=2048, tk=1536) == (256, 512)
    monkeypatch.setenv("CHAINERMN_TPU_FLASH_BLOCK_Q", "64")
    assert _flash_blocks(tq=2048, tk=2048) == (64, 1024)

def test_adaptive_block_invalid_env(monkeypatch):
    monkeypatch.setenv("CHAINERMN_TPU_FLASH_BLOCK_K", "70")
    from chainermn_tpu.ops.flash_attention import _flash_blocks
    with pytest.raises(ValueError):
        _flash_blocks(tq=2048, tk=2048)
