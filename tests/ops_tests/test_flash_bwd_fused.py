"""Fused flash-attention backward (ISSUE 4 tentpole).

Interpret-mode (CPU tier-1) coverage:

* grad parity of the fused one-pass dq/dkv kernel vs the
  ``_blockwise_attention_lse_jnp`` reference over a (T, causal,
  tile-shape, dtype) grid — including ragged T where the bwd tile table
  does not divide and the kernel must fall back to the forward tiles;
* the ``CHAINERMN_TPU_FLASH_BWD=split`` escape hatch restores the
  legacy two-kernel lowering bit-for-bit;
* backward tile resolution (env knobs, sweep table, explicit args);
* fused↔split numerical agreement.

Ring/Ulysses consumer coverage lives in
tests/parallel_tests/test_long_context.py (the kernels there run under
shard_map via CHAINERMN_TPU_FLASH_INTERPRET=1).
"""

import functools
import importlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

fa = importlib.import_module("chainermn_tpu.ops.flash_attention")


def _data(B=1, H=2, T=128, D=16, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (B, H, T, D))
                             .astype(np.float32)).astype(dtype)
    return mk(), mk(), mk()


def _grads(loss, q, k, v):
    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


# (T, (block_q, block_k)) — 192/160 are the ragged rows: no default
# candidate (1024/512/256/128) divides them, and the bwd table misses,
# so the fused kernel exercises its forward-tile fallback branch; the
# 64/128 rows resolve bwd tiles through _adaptive_block.
_GRID = [
    (64, (32, 32)),
    (128, (64, 64)),
    (128, (64, 32)),
    (192, (64, 64)),
    (160, (32, 32)),
]


@pytest.mark.parametrize("T,blocks", _GRID)
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_bwd_grad_parity_vs_blockwise(monkeypatch, T, blocks,
                                            causal, dtype):
    """Full-grid grad parity: fused backward (interpret mode) vs the
    differentiable blockwise jnp reference, for a loss touching BOTH
    outputs (out and lse — the g_lse→delta folding included)."""
    bq, bk = blocks
    monkeypatch.setenv("CHAINERMN_TPU_FLASH_BLOCK_Q", str(bq))
    monkeypatch.setenv("CHAINERMN_TPU_FLASH_BLOCK_K", str(bk))
    monkeypatch.delenv("CHAINERMN_TPU_FLASH_BWD_BLOCK_Q", raising=False)
    monkeypatch.delenv("CHAINERMN_TPU_FLASH_BWD_BLOCK_K", raising=False)
    assert fa._flash_bwd_mode() == "fused"
    q, k, v = _data(T=T, seed=T + causal, dtype=dtype)
    scale = 1.0 / np.sqrt(q.shape[-1])

    def loss_flash(q, k, v):
        out, lse = fa._flash_lse_diff(q, k, v, causal, scale, True)
        return jnp.sum(out.astype(jnp.float32) ** 2) \
            + jnp.sum(jnp.sin(lse))

    def loss_ref(q, k, v):
        out, lse = fa._blockwise_attention_lse_jnp(q, k, v, causal,
                                                   scale, block_k=32)
        return jnp.sum(out.astype(jnp.float32) ** 2) \
            + jnp.sum(jnp.sin(lse))

    gf = _grads(loss_flash, q, k, v)
    gr = _grads(loss_ref, q, k, v)
    if dtype == jnp.float32:
        rtol, atol = 2e-4, 1e-5
    else:
        rtol, atol = 0.1, 0.05
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32),
            np.asarray(b, dtype=np.float32), rtol=rtol, atol=atol,
            err_msg=f"d{name} T={T} blocks={blocks} causal={causal} "
                    f"dtype={dtype.__name__}")


def _legacy_two_kernel_bwd(q, k, v, out, lse, g, causal, scale,
                           block_q, block_k):
    """The pre-fusion lowering, reconstructed verbatim from the split
    kernels and their original pallas_call specs — the bit-for-bit
    reference for the escape hatch."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    gr = g.reshape(B * H, Tq, D)
    lser = lse.reshape(B * H, Tq, 1)
    delta = jnp.sum(gr.astype(jnp.float32)
                    * out.reshape(B * H, Tq, D).astype(jnp.float32),
                    axis=-1, keepdims=True)
    dq = pl.pallas_call(
        functools.partial(fa._flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale),
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        interpret=True,
    )(qr, kr, vr, gr, lser, delta)
    dk, dv = pl.pallas_call(
        functools.partial(fa._flash_bwd_dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale),
        grid=(B * H, Tk // block_k),
        in_specs=[
            pl.BlockSpec((None, Tq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tq, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tq, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype),
        ],
        interpret=True,
    )(qr, kr, vr, gr, lser, delta)
    return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
            dv.reshape(B, H, Tk, D))


@pytest.mark.parametrize("causal", [False, True])
def test_split_escape_hatch_restores_legacy_bit_for_bit(monkeypatch,
                                                        causal):
    q, k, v = _data(T=128, seed=3, dtype=jnp.float32)
    g = _data(T=128, seed=4)[0]
    scale = 1.0 / np.sqrt(q.shape[-1])
    out, lse = fa.flash_attention_fwd(q, k, v, causal=causal, scale=scale,
                                      block_q=64, block_k=64,
                                      interpret=True)
    monkeypatch.setattr(fa, "_FLASH_BWD", "split")
    got = fa.flash_attention_bwd(q, k, v, out, lse, g, causal=causal,
                                 scale=scale, block_q=64, block_k=64,
                                 interpret=True)
    want = _legacy_two_kernel_bwd(q, k, v, out, lse, g, causal, scale,
                                  64, 64)
    for a, b, name in zip(got, want, ("dq", "dk", "dv")):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"{name}: split mode no longer the legacy lowering")


@pytest.mark.parametrize("causal", [False, True])
def test_fused_matches_split(monkeypatch, causal):
    """The two lowerings are the same math: fp32 agreement to float
    noise (the only difference is dq's cross-block summation order)."""
    q, k, v = _data(T=128, seed=5)
    g = _data(T=128, seed=6)[0]
    scale = 1.0 / np.sqrt(q.shape[-1])
    out, lse = fa.flash_attention_fwd(q, k, v, causal=causal, scale=scale,
                                      block_q=64, block_k=64,
                                      interpret=True)
    monkeypatch.setattr(fa, "_FLASH_BWD", "fused")
    fused = fa.flash_attention_bwd(q, k, v, out, lse, g, causal=causal,
                                   scale=scale, block_q=64, block_k=64,
                                   interpret=True, bwd_block_q=64,
                                   bwd_block_k=64)
    monkeypatch.setattr(fa, "_FLASH_BWD", "split")
    split = fa.flash_attention_bwd(q, k, v, out, lse, g, causal=causal,
                                   scale=scale, block_q=64, block_k=64,
                                   interpret=True)
    for a, b, name in zip(fused, split, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6, err_msg=name)


def test_bwd_mode_validation(monkeypatch):
    monkeypatch.setattr(fa, "_FLASH_BWD", "nonsense")
    with pytest.raises(ValueError, match="CHAINERMN_TPU_FLASH_BWD"):
        fa._flash_bwd_mode()


def test_bwd_block_resolution(monkeypatch):
    """Explicit args > env knobs > sweep table > fwd-adaptive default."""
    monkeypatch.delenv("CHAINERMN_TPU_FLASH_BWD_BLOCK_Q", raising=False)
    monkeypatch.delenv("CHAINERMN_TPU_FLASH_BWD_BLOCK_K", raising=False)
    # table rows exist for the swept lengths
    for t in (1024, 2048, 8192, 16384):
        assert fa._flash_bwd_blocks(tq=t, tk=t) == fa._BWD_BLOCK_TABLE[t]
    # off-table lengths: fwd-adaptive fallback
    assert fa._flash_bwd_blocks(tq=512, tk=512) == (512, 512)
    assert fa._flash_bwd_blocks(tq=192, tk=192) == (128, 128)
    # env knobs pin, explicit args win
    monkeypatch.setenv("CHAINERMN_TPU_FLASH_BWD_BLOCK_Q", "256")
    assert fa._flash_bwd_blocks(tq=8192, tk=8192) == (
        256, fa._BWD_BLOCK_TABLE[8192][1])
    assert fa._flash_bwd_blocks(64, None, tq=8192, tk=8192) == (
        64, fa._BWD_BLOCK_TABLE[8192][1])
    monkeypatch.setenv("CHAINERMN_TPU_FLASH_BWD_BLOCK_K", "70")
    with pytest.raises(ValueError, match="multiples of 8"):
        fa._flash_bwd_blocks(tq=8192, tk=8192)


def test_fused_bwd_kernel_count_and_single_exp():
    """Structural pin of the recompute-once property: the fused backward
    lowers to exactly ONE pallas_call whose kernel contains exactly ONE
    exp; split lowers to two kernels with one exp each.  Uses the same
    jaxpr census the tier-1 budget gate runs (tools/flash_sweep.py) —
    here pinned against absolute expectations, there against the
    committed tools/flash_budgets.json structure section."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "tools"))
    import flash_sweep

    # fused: ONE backward kernel with ONE exp
    assert flash_sweep.bwd_kernel_census(fa, "fused") == \
        {"_flash_bwd_fused_kernel": 1}
    # split: the legacy pair, each recomputing its own exp(s - lse) —
    # the duplicated recompute the fusion eliminates
    assert flash_sweep.bwd_kernel_census(fa, "split") == \
        {"_flash_bwd_dq_kernel": 1, "_flash_bwd_dkv_kernel": 1}
