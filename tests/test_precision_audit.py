"""Bitrot guard for the StableHLO precision-audit classifier
(tools/probe_perf.py · classify_contractions): the dtype regexes must
keep parsing the StableHLO text format, and the classification must
distinguish the correct MXU configuration (bf16 inputs, f32
accumulator) from genuine f32-input contractions."""

import importlib.util
import os

SNIPPET = """\
  %0 = stablehlo.dot_general %a, %b, contracting_dims = [1] x [0] : (tensor<8x16xbf16>, tensor<16x4xbf16>) -> tensor<8x4xbf16>
  %1 = stablehlo.dot_general %c, %d, contracting_dims = [1] x [0] : (tensor<8x16xbf16>, tensor<16x4xbf16>) -> tensor<8x4xf32>
  %2 = stablehlo.dot_general %e, %f, contracting_dims = [1] x [0] : (tensor<8x16xf32>, tensor<16x4xf32>) -> tensor<8x4xf32>
  %3 = stablehlo.add %0, %0 : tensor<8x4xbf16>
  %4 = stablehlo.convolution(%x, %w) {foo} : (tensor<1x8x8x3xbf16>, tensor<3x3x3x4xbf16>) -> tensor<1x8x8x4xbf16>
"""


def _load():
    spec = importlib.util.spec_from_file_location(
        "probe_perf_audit", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "probe_perf.py"))
    # import would trigger the module's jax config at top level — that is
    # fine (tests pin cpu), but keep it isolated under its own name
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_classify_contractions_by_input_and_result_dtype():
    mod = _load()
    dots = mod.classify_contractions(SNIPPET, "dot_general")
    assert dots == {"bf16->bf16": 1, "bf16->f32": 1, "f32->f32": 1}
    convs = mod.classify_contractions(SNIPPET, "convolution")
    assert convs == {"bf16->bf16": 1}
